"""Generic graph algorithms used by the search.

TPU-native rebuild of the reference's header-only graph toolkit
(include/flexflow/dominators.h:205-261, basic_graph.h, graph_structures.h,
include/flexflow/utils/disjoint_set.h), exercised there by tests/unit.
The algorithms are hardware-agnostic; they operate on a minimal adjacency
protocol so both the PCG and ad-hoc test graphs can use them.

Used by the Unity search for sequence splits: a *bottleneck* node — one that
every source-to-sink path passes through — is found via immediate
post-dominators (reference: Graph::find_bottleneck_node,
src/runtime/graph.cc:610-623) and lets the DP split the graph into
independently-searchable segments.
"""
from __future__ import annotations

from typing import Dict, Generic, Hashable, Iterable, List, Optional, Set, \
    Tuple, TypeVar

N = TypeVar("N", bound=Hashable)


class BasicGraph(Generic[N]):
    """Minimal directed-graph container (reference: basic_graph.h)."""

    def __init__(self, nodes: Iterable[N] = (),
                 edges: Iterable[Tuple[N, N]] = ()):
        self.nodes: Set[N] = set(nodes)
        self._out: Dict[N, Set[N]] = {}
        self._in: Dict[N, Set[N]] = {}
        for u, v in edges:
            self.add_edge(u, v)

    def add_node(self, n: N) -> None:
        self.nodes.add(n)

    def add_edge(self, u: N, v: N) -> None:
        self.nodes.add(u)
        self.nodes.add(v)
        self._out.setdefault(u, set()).add(v)
        self._in.setdefault(v, set()).add(u)

    def out_edges(self, n: N) -> Set[N]:
        return self._out.get(n, set())

    def in_edges(self, n: N) -> Set[N]:
        return self._in.get(n, set())

    def sources(self) -> List[N]:
        return [n for n in self.nodes if not self._in.get(n)]

    def sinks(self) -> List[N]:
        return [n for n in self.nodes if not self._out.get(n)]

    def reversed(self) -> "BasicGraph[N]":
        g: BasicGraph[N] = BasicGraph(self.nodes)
        for u, vs in self._out.items():
            for v in vs:
                g.add_edge(v, u)
        return g

    def topo_order(self) -> List[N]:
        indeg = {n: len(self._in.get(n, ())) for n in self.nodes}
        # deterministic order for reproducible search traces
        ready = sorted((n for n, d in indeg.items() if d == 0), key=repr)
        out: List[N] = []
        while ready:
            n = ready.pop(0)
            out.append(n)
            for v in sorted(self._out.get(n, ()), key=repr):
                indeg[v] -= 1
                if indeg[v] == 0:
                    ready.append(v)
        if len(out) != len(self.nodes):
            raise ValueError("graph has a cycle")
        return out


def dominators(g: BasicGraph[N]) -> Dict[N, Set[N]]:
    """node -> set of its dominators, incl. itself (dominators.h:205).

    d dominates n iff every path from any source to n passes through d.
    Iterative dataflow over the topological order; multi-source graphs get
    an implicit virtual root (matching the reference, which unions over
    sources)."""
    order = g.topo_order()
    dom: Dict[N, Set[N]] = {}
    for n in order:
        preds = g.in_edges(n)
        if not preds:
            dom[n] = {n}
            continue
        common: Optional[Set[N]] = None
        for p in preds:
            common = set(dom[p]) if common is None else (common & dom[p])
        dom[n] = (common or set()) | {n}
    return dom


def post_dominators(g: BasicGraph[N]) -> Dict[N, Set[N]]:
    """node -> set of its post-dominators (dominators on the reverse graph;
    reference: post_dominators, dominators.h:230)."""
    return dominators(g.reversed())


def _imm_from_sets(g: BasicGraph[N], doms: Dict[N, Set[N]],
                   order: List[N]) -> Dict[N, N]:
    """Immediate dominator = the strict dominator that appears latest in the
    topological order (reference: imm_dominators picks via topo position)."""
    pos = {n: i for i, n in enumerate(order)}
    imm: Dict[N, N] = {}
    for n in g.nodes:
        strict = [d for d in doms[n] if d != n]
        imm[n] = max(strict, key=lambda d: pos[d]) if strict else n
    return imm


def _imm_dominators_native(g: BasicGraph[N]) -> Optional[Dict[N, N]]:
    """Native CHK fast path (flexflow_tpu/native/ffnative.cpp::
    imm_dominators_native); None when the library is unavailable."""
    try:
        from ..native import imm_dominators_edges
    except ImportError:
        return None
    nodes = list(g.nodes)
    ids = {n: i for i, n in enumerate(nodes)}
    edges = [(ids[u], ids[v]) for u in nodes for v in g.out_edges(u)]
    out = imm_dominators_edges(len(nodes), edges)
    if out is None:
        return None
    return {n: (n if out[i] < 0 else nodes[out[i]])
            for i, n in enumerate(nodes)}


def imm_dominators(g: BasicGraph[N]) -> Dict[N, N]:
    """node -> its immediate dominator (itself for sources;
    dominators.h:246)."""
    if len(g.nodes) > 64:  # native pays off on large graphs
        native = _imm_dominators_native(g)
        if native is not None:
            return native
    return _imm_from_sets(g, dominators(g), g.topo_order())


def imm_post_dominators(g: BasicGraph[N]) -> Dict[N, N]:
    """node -> its immediate post-dominator (itself for sinks;
    dominators.h:253)."""
    rev = g.reversed()
    return _imm_from_sets(rev, dominators(rev), rev.topo_order())


def transitive_reduction(g: BasicGraph[N]) -> BasicGraph[N]:
    """Remove edges implied by longer paths (reference: Graph::reduced,
    include/flexflow/graph.h:352). DAG only."""
    order = g.topo_order()
    pos = {n: i for i, n in enumerate(order)}
    # reach[n] = nodes reachable from n (excl. n)
    reach: Dict[N, Set[N]] = {n: set() for n in g.nodes}
    for n in reversed(order):
        for v in g.out_edges(n):
            reach[n].add(v)
            reach[n] |= reach[v]
    out: BasicGraph[N] = BasicGraph(g.nodes)
    for u in g.nodes:
        succs = sorted(g.out_edges(u), key=lambda v: pos[v])
        for v in succs:
            # edge u->v is redundant if v reachable from another successor
            if any(v in reach[w] for w in succs if w != v):
                continue
            out.add_edge(u, v)
    return out


def find_bottlenecks(g: BasicGraph[N]) -> List[N]:
    """Nodes through which EVERY source-to-sink path passes, in topo order
    (reference: find_bottleneck_node via imm_post_dominators,
    src/runtime/graph.cc:610-623). Sources/sinks themselves are excluded
    unless they genuinely cut the graph.

    A node is a bottleneck iff it dominates every sink and post-dominates
    every source."""
    if not g.nodes:
        return []
    dom = dominators(g)
    pdom = post_dominators(g)
    sinks, srcs = g.sinks(), g.sources()
    order = g.topo_order()
    out = []
    for n in order:
        if all(n in dom[s] for s in sinks) and \
                all(n in pdom[s] for s in srcs):
            out.append(n)
    return out


class DisjointSet(Generic[N]):
    """Union-find with path compression + union by rank
    (reference: include/flexflow/utils/disjoint_set.h, tests/unit)."""

    def __init__(self):
        self._parent: Dict[N, N] = {}
        self._rank: Dict[N, int] = {}

    def find(self, x: N) -> N:
        if x not in self._parent:
            self._parent[x] = x
            self._rank[x] = 0
            return x
        root = x
        while self._parent[root] != root:
            root = self._parent[root]
        while self._parent[x] != root:  # path compression
            self._parent[x], x = root, self._parent[x]
        return root

    def union(self, a: N, b: N) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra == rb:
            return
        if self._rank[ra] < self._rank[rb]:
            ra, rb = rb, ra
        self._parent[rb] = ra
        if self._rank[ra] == self._rank[rb]:
            self._rank[ra] += 1

    def same(self, a: N, b: N) -> bool:
        return self.find(a) == self.find(b)

    def groups(self) -> List[Set[N]]:
        by_root: Dict[N, Set[N]] = {}
        for x in self._parent:
            by_root.setdefault(self.find(x), set()).add(x)
        return list(by_root.values())


def pcg_basic_graph(pcg, compute_only: bool = True) -> BasicGraph[int]:
    """Adapt a PCG into a BasicGraph of guids (reference:
    GraphStructure adapter, graph_structures.h)."""
    from ..ffconst import OperatorType

    g: BasicGraph[int] = BasicGraph()
    nodes = pcg.compute_nodes() if compute_only else pcg.topo_order()
    keep = {n.guid for n in nodes}
    for n in nodes:
        g.add_node(n.guid)
        for pg, _ in n.inputs:
            if pg in keep:
                g.add_edge(pg, n.guid)
    return g
