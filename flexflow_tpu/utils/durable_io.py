"""Shared atomic-write / checksum / stale-staging idioms (ISSUE 20).

PR 4 proved the durable-commit recipe for training checkpoints
(``execution/checkpoint.py``): stage, fsync the payloads AND the parent
directory, checksum with crc32, and sweep dead writers' ``.tmp``
leftovers only after a grace window. PR 20's crash-durable request
journal (``serving/journal.py``) needs the identical primitives, so they
live here once — one implementation, two consumers. Nothing in this
module imports jax/orbax: it is plain-POSIX host code usable from any
layer.
"""
from __future__ import annotations

import json
import os
import shutil
import zlib
from typing import List, Tuple

#: a foreign .tmp staging path is only swept once it has sat untouched
#: this long — a replacement process resuming during its predecessor's
#: SIGTERM grace window must not race a LIVE writer's staging out from
#: under it (the PR 4 rule, now shared with the request journal)
STALE_TMP_AGE_S = 15 * 60


def fsync_path(path: str) -> None:
    """fsync a file or directory; directory fsync persists the entry names
    (the rename-based commit is only durable once the parent dir is)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse dir fsync; commit still atomic
    finally:
        os.close(fd)


def write_json(path: str, obj, fsync: bool = True) -> None:
    with open(path, "w") as f:
        json.dump(obj, f)
        if fsync:
            f.flush()
            os.fsync(f.fileno())


def crc_file(path: str, chunk: int = 1 << 20) -> Tuple[int, int]:
    """(crc32, size) of a file, streamed in ``chunk``-byte reads."""
    crc, size = 0, 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                break
            crc = zlib.crc32(buf, crc)
            size += len(buf)
    return crc & 0xFFFFFFFF, size


def crc_bytes(data: bytes) -> int:
    """crc32 of an in-memory record — the journal's per-record frame
    checksum (the file-level sibling of :func:`crc_file`)."""
    return zlib.crc32(data) & 0xFFFFFFFF


def sweep_stale_tmp(directory: str, age_s: float = STALE_TMP_AGE_S
                    ) -> List[str]:
    """Sweep ``.tmp.<pid>`` staging entries from DEAD writers: other
    pids only, untouched for ``age_s``. A vanished entry mid-sweep means
    its writer is live — leave it alone. Returns removed paths."""
    import time

    removed: List[str] = []
    if not os.path.isdir(directory):
        return removed
    now = time.time()
    for d in os.listdir(directory):
        if ".tmp." in d and not d.endswith(f".tmp.{os.getpid()}"):
            p = os.path.join(directory, d)
            try:
                stale = now - os.path.getmtime(p) > age_s
            except OSError:
                continue  # vanished: its writer is live, leave it alone
            if not stale:
                continue
            if os.path.isdir(p):
                shutil.rmtree(p, ignore_errors=True)
                removed.append(p)
            else:
                try:
                    os.remove(p)
                    removed.append(p)
                except OSError:
                    pass
    return removed
