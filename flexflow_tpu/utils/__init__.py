from .recursive_logger import RecursiveLogger  # noqa: F401
