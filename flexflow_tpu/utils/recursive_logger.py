"""Indent-scoped search tracing.

Rebuild of the reference's RecursiveLogger (include/flexflow/utils/
recursive_logger.h, src/runtime/recursive_logger.cc) used throughout the
substitution search: nested scopes indent their messages so the search tree
is readable in the log.
"""
from __future__ import annotations

import contextlib
import logging


class RecursiveLogger:
    def __init__(self, name: str):
        self.logger = logging.getLogger(f"flexflow_tpu.{name}")
        self.depth = 0

    @contextlib.contextmanager
    def scope(self, msg: str = "", *args):
        if msg:
            self.info(msg, *args)
        self.depth += 1
        try:
            yield self
        finally:
            self.depth -= 1

    def _emit(self, level: int, msg: str, *args) -> None:
        self.logger.log(level, "%s" + msg, "  " * self.depth, *args)

    def info(self, msg: str, *args) -> None:
        self._emit(logging.INFO, msg, *args)

    def debug(self, msg: str, *args) -> None:
        self._emit(logging.DEBUG, msg, *args)

    def spew(self, msg: str, *args) -> None:
        self._emit(logging.DEBUG, msg, *args)
