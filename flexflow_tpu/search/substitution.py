"""Graph substitution engine (GraphXfer) + TASO-style JSON rule loader.

Rebuild of the reference's pattern engine (include/flexflow/substitution.h:
64-247 ``OpX/TensorX/GraphXfer``; src/runtime/substitution.cc:3802) and the
JSON rule collection loader (substitution_loader.h:131-179, rules file
substitutions/graph_subst_3_v2.json).

Role in the TPU build: the Unity DP search (unity.py) already covers the
parallelization xfers (partition/replicate linear+attention combine) natively
via sharding choices. The GraphXfer engine here covers the *algebraic* graph
rewrites those rules express (fusing linear+linear, reordering ops), applied
as a pre-pass over the PCG, and gives ``--substitution-json`` parity: rules
loaded from a JSON file are matched against the PCG and applied when the
simulator says they help.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..ffconst import OperatorType
from ..parallel.pcg import PCG, PCGNode

# name map (reference: substitution_loader.h operator-name table)
_NAME_TO_OP = {
    "OP_LINEAR": OperatorType.OP_LINEAR,
    "OP_CONV2D": OperatorType.OP_CONV2D,
    "OP_RELU": OperatorType.OP_RELU,
    "OP_SIGMOID": OperatorType.OP_SIGMOID,
    "OP_TANH": OperatorType.OP_TANH,
    "OP_EW_ADD": OperatorType.OP_EW_ADD,
    "OP_EW_MUL": OperatorType.OP_EW_MUL,
    "OP_MATMUL": OperatorType.OP_BATCHMATMUL,
    "OP_BATCHMATMUL": OperatorType.OP_BATCHMATMUL,
    "OP_CONCAT": OperatorType.OP_CONCAT,
    "OP_SPLIT": OperatorType.OP_SPLIT,
    "OP_RESHAPE": OperatorType.OP_RESHAPE,
    "OP_TRANSPOSE": OperatorType.OP_TRANSPOSE,
    "OP_SOFTMAX": OperatorType.OP_SOFTMAX,
    "OP_REPARTITION": OperatorType.OP_REPARTITION,
    "OP_COMBINE": OperatorType.OP_COMBINE,
    "OP_REPLICATE": OperatorType.OP_REPLICATE,
    "OP_REDUCTION": OperatorType.OP_REDUCTION,
    "OP_MULTIHEAD_ATTENTION": OperatorType.OP_MULTIHEAD_ATTENTION,
}


@dataclasses.dataclass
class OpX:
    """Pattern node (reference: substitution.h:64-110): an op type plus
    input slots referencing other pattern nodes (by index) or open inputs
    (negative)."""

    op_type: OperatorType
    inputs: List[int]  # >=0: OpX index in pattern; <0: open input slot
    attr_constraints: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class GraphXfer:
    """A source pattern -> destination pattern rewrite."""

    name: str
    src: List[OpX]
    dst: List[OpX]
    # map dst open-input slots to src open-input slots (identity by default)

    def find_matches(self, pcg: PCG) -> List[Dict[int, int]]:
        """Return list of {pattern_idx -> node_guid} matches. Pattern edges
        must map to PCG edges; matched interior nodes must have no external
        consumers (reference: GraphXfer::can_match)."""
        matches = []
        nodes = pcg.compute_nodes()
        by_type: Dict[OperatorType, List[PCGNode]] = {}
        for n in nodes:
            by_type.setdefault(n.op.op_type, []).append(n)

        def backtrack(i: int, mapping: Dict[int, int]):
            if i == len(self.src):
                matches.append(dict(mapping))
                return
            px = self.src[i]
            for cand in by_type.get(px.op_type, []):
                if cand.guid in mapping.values():
                    continue
                ok = True
                for slot, pin in enumerate(px.inputs):
                    if pin >= 0:
                        if slot >= len(cand.inputs) or \
                                cand.inputs[slot][0] != mapping.get(pin):
                            ok = False
                            break
                for k, v in px.attr_constraints.items():
                    if cand.op.attrs.get(k) != v:
                        ok = False
                        break
                if ok:
                    mapping[i] = cand.guid
                    backtrack(i + 1, mapping)
                    del mapping[i]

        backtrack(0, {})
        # interior nodes (consumed inside the pattern) must have no external
        # consumers
        out = []
        for m in matches:
            interior = set()
            for px in self.src:
                for pin in px.inputs:
                    if pin >= 0:
                        interior.add(m[pin])
            valid = all(
                all(c in m.values() for c in pcg.consumers(g))
                for g in interior)
            if valid:
                out.append(m)
        return out


def load_substitution_json(path: str) -> List[GraphXfer]:
    """Parse a TASO-style rule collection (reference:
    substitution_loader.cc `from_json`; format: {"rule": [{"name", "srcOp":
    [{"type", "input": [{"opId","tsId"}], "para": [...]}], "dstOp": [...]}]}).
    Unknown op types skip the rule (the reference does the same for ops it
    can't map)."""
    with open(path) as f:
        data = json.load(f)
    rules = data.get("rule", data.get("rules", []))
    xfers: List[GraphXfer] = []
    for rule in rules:
        try:
            src = _parse_ops(rule.get("srcOp", []))
            dst = _parse_ops(rule.get("dstOp", []))
        except KeyError:
            continue
        if src:
            xfers.append(GraphXfer(rule.get("name", f"rule{len(xfers)}"),
                                   src, dst))
    return xfers


def _parse_ops(ops_json) -> List[OpX]:
    out = []
    for op in ops_json:
        tname = op.get("type")
        if tname not in _NAME_TO_OP:
            raise KeyError(tname)
        inputs = []
        for inp in op.get("input", []):
            op_id = inp.get("opId", -1)
            inputs.append(op_id if op_id >= 0 else -1 - len(inputs))
        attrs = {}
        for p in op.get("para", []):
            if "key" in p and "value" in p:
                attrs[str(p["key"])] = p["value"]
        out.append(OpX(_NAME_TO_OP[tname], inputs, attrs))
    return out


# ------------------------------------------------------- built-in fusion rules
def fuse_consecutive_reshapes(pcg: PCG) -> int:
    """reshape(reshape(x)) -> reshape(x) (simplification pass analog of the
    reference's Graph::simplify). Returns number of rewrites."""
    count = 0
    for node in list(pcg.compute_nodes()):
        if node.op.op_type != OperatorType.OP_RESHAPE:
            continue
        (g, i) = node.inputs[0]
        prod = pcg.nodes.get(g)
        if prod is None or prod.op.op_type != OperatorType.OP_RESHAPE:
            continue
        if len(pcg.consumers(g)) != 1:
            continue
        node.inputs[0] = prod.inputs[0]
        del pcg.nodes[g]
        pcg._order.remove(g)
        count += 1
    return count


def builtin_xfers() -> List[GraphXfer]:
    """Hand-registered patterns mirroring the reference's manual xfers
    (substitution.cc:3041-3226). The parallelization variants are realized by
    the DP search; these document the pattern shapes for the JSON engine."""
    return [
        GraphXfer(
            "linear_relu_fuse",
            src=[OpX(OperatorType.OP_LINEAR, [-1]),
                 OpX(OperatorType.OP_RELU, [0])],
            dst=[OpX(OperatorType.OP_LINEAR, [-1],
                     {"activation": "relu"})]),
    ]


def apply_simplifications(pcg: PCG) -> int:
    """Run the always-beneficial simplification passes (reference:
    Graph::simplify called during optimization)."""
    return fuse_consecutive_reshapes(pcg)
