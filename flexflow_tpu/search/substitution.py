"""Graph substitution engine (GraphXfer) + TASO-style JSON rule loader.

Rebuild of the reference's pattern engine (include/flexflow/substitution.h:
64-247 ``OpX/TensorX/GraphXfer``; src/runtime/substitution.cc:3802) and the
JSON rule collection loader (substitution_loader.h:131-179, rules file
substitutions/graph_subst_3_v2.json).

Role in the TPU build: the Unity DP search (unity.py) already covers the
parallelization xfers (partition/replicate linear+attention combine) natively
via sharding choices. The GraphXfer engine here covers the *algebraic* graph
rewrites those rules express (fusing linear+linear, reordering ops), applied
as a pre-pass over the PCG, and gives ``--substitution-json`` parity: rules
loaded from a JSON file are matched against the PCG and applied when the
simulator says they help.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional

from ..ffconst import OperatorType
from ..parallel.pcg import PCG, PCGNode

# name map (reference: substitution_loader.h operator-name table)
_NAME_TO_OP = {
    "OP_LINEAR": OperatorType.OP_LINEAR,
    "OP_CONV2D": OperatorType.OP_CONV2D,
    "OP_RELU": OperatorType.OP_RELU,
    "OP_SIGMOID": OperatorType.OP_SIGMOID,
    "OP_TANH": OperatorType.OP_TANH,
    "OP_EW_ADD": OperatorType.OP_EW_ADD,
    "OP_EW_MUL": OperatorType.OP_EW_MUL,
    "OP_MATMUL": OperatorType.OP_BATCHMATMUL,
    "OP_BATCHMATMUL": OperatorType.OP_BATCHMATMUL,
    "OP_CONCAT": OperatorType.OP_CONCAT,
    "OP_SPLIT": OperatorType.OP_SPLIT,
    "OP_RESHAPE": OperatorType.OP_RESHAPE,
    "OP_TRANSPOSE": OperatorType.OP_TRANSPOSE,
    "OP_SOFTMAX": OperatorType.OP_SOFTMAX,
    "OP_REPARTITION": OperatorType.OP_REPARTITION,
    # the TASO collection's names for the parallel ops
    # (substitution_loader.h's table): OP_PARTITION == Repartition,
    # OP_REDUCE == Reduction
    "OP_PARTITION": OperatorType.OP_REPARTITION,
    "OP_COMBINE": OperatorType.OP_COMBINE,
    "OP_REPLICATE": OperatorType.OP_REPLICATE,
    "OP_REDUCTION": OperatorType.OP_REDUCTION,
    "OP_REDUCE": OperatorType.OP_REDUCTION,
    "OP_MULTIHEAD_ATTENTION": OperatorType.OP_MULTIHEAD_ATTENTION,
}


@dataclasses.dataclass
class OpX:
    """Pattern node (reference: substitution.h:64-110): an op type plus
    input slots referencing other pattern nodes (by index) or open inputs
    (negative).

    src side: ``attr_constraints`` filters matches — a value, a tuple of
    admissible values, or a callable predicate.
    dst side: ``attrs_from`` names the src OpX index whose matched node's
    attrs seed the new op (default: first src OpX of the same type), then
    ``attr_overrides`` are applied on top."""

    op_type: OperatorType
    inputs: List[int]  # >=0: OpX index in pattern; <0: open input slot
    attr_constraints: Dict[str, Any] = dataclasses.field(default_factory=dict)
    attrs_from: Optional[int] = None
    attr_overrides: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def constraint_ok(self, attrs: Dict[str, Any]) -> bool:
        for k, v in self.attr_constraints.items():
            got = attrs.get(k)
            if callable(v):
                if not v(got):
                    return False
            elif isinstance(v, tuple):
                if got not in v:
                    return False
            elif got != v:
                return False
        return True


@dataclasses.dataclass
class GraphXfer:
    """A source pattern -> destination pattern rewrite."""

    name: str
    src: List[OpX]
    dst: List[OpX]
    # map dst open-input slots to src open-input slots (identity by default)

    def find_matches(self, pcg: PCG) -> List[Dict[int, int]]:
        """Return list of {pattern_idx -> node_guid} matches. Pattern edges
        must map to PCG edges; matched interior nodes must have no external
        consumers (reference: GraphXfer::can_match)."""
        matches = []
        nodes = pcg.compute_nodes()
        by_type: Dict[OperatorType, List[PCGNode]] = {}
        for n in nodes:
            by_type.setdefault(n.op.op_type, []).append(n)

        def backtrack(i: int, mapping: Dict[int, int],
                      open_bind: Dict[int, tuple]):
            if i == len(self.src):
                matches.append(dict(mapping))
                return
            px = self.src[i]
            for cand in by_type.get(px.op_type, []):
                if cand.guid in mapping.values():
                    continue
                ok = True
                bound_here = []
                for slot, pin in enumerate(px.inputs):
                    if pin >= 0:
                        if slot >= len(cand.inputs) or \
                                cand.inputs[slot][0] != mapping.get(pin):
                            ok = False
                            break
                    elif slot < len(cand.inputs):
                        # open slots with the same id are the SAME external
                        # tensor (TASO rules share weights/inputs this way)
                        # — every occurrence must bind to one producer
                        prod = cand.inputs[slot]
                        if pin in open_bind:
                            if open_bind[pin] != prod:
                                ok = False
                                break
                        else:
                            open_bind[pin] = prod
                            bound_here.append(pin)
                if ok and not px.constraint_ok(cand.op.attrs):
                    ok = False
                if ok:
                    mapping[i] = cand.guid
                    backtrack(i + 1, mapping, open_bind)
                    del mapping[i]
                for pin in bound_here:
                    del open_bind[pin]
                bound_here.clear()

        backtrack(0, {}, {})
        # interior nodes (consumed inside the pattern) must have no external
        # consumers
        out = []
        for m in matches:
            interior = set()
            for px in self.src:
                for pin in px.inputs:
                    if pin >= 0:
                        interior.add(m[pin])
            valid = all(
                all(c in m.values() for c in pcg.consumers(g))
                for g in interior)
            if valid:
                out.append(m)
        return out

    def apply(self, pcg: PCG, match: Dict[int, int],
              return_touched: bool = False):
        """Apply the rewrite on a copy of ``pcg`` (reference:
        GraphXfer::run, substitution.cc — create_new_operator + rewire).

        Convention: the LAST src OpX is the pattern's output node; its
        external consumers are rewired to the LAST dst node's output 0. Open
        input slots bind to the matched nodes' actual producers. The new op's
        attrs come from ``attrs_from`` (see OpX) so shape-bearing parameters
        (out_dim, num_heads, ...) carry over. Shapes must be preserved by the
        rule — verified, ValueError otherwise.

        With ``return_touched`` the result is ``(graph, touched_guids)``
        where ``touched_guids`` are the newly created nodes — the seed of
        the delta-cost engine's dirty set (best_first_optimize re-costs
        only them plus their descendants; the matched nodes are deleted, and
        every rewired consumer is a descendant of a touched node)."""
        from ..ops.base import op_class_for

        g = pcg.copy()
        # open-input bindings: pattern slot id -> (producer_guid, out_idx)
        bindings: Dict[int, tuple] = {}
        for i, px in enumerate(self.src):
            node = g.nodes[match[i]]
            for slot, pin in enumerate(px.inputs):
                if pin < 0 and slot < len(node.inputs):
                    bindings[pin] = node.inputs[slot]

        out_src_guid = match[len(self.src) - 1]
        old_out = g.nodes[out_src_guid]

        new_nodes = []
        for j, dx in enumerate(self.dst):
            src_idx = dx.attrs_from
            if src_idx is None:
                for i, px in enumerate(self.src):
                    if px.op_type == dx.op_type:
                        src_idx = i
                        break
            attrs = dict(g.nodes[match[src_idx]].op.attrs) \
                if src_idx is not None else {}
            attrs.update(dx.attr_overrides)
            template = g.nodes[match[src_idx]] if src_idx is not None \
                else old_out
            inputs = []
            for pin in dx.inputs:
                if pin >= 0:
                    inputs.append((new_nodes[pin].guid, 0))
                else:
                    if pin not in bindings:
                        raise ValueError(
                            f"{self.name}: unbound open input {pin}")
                    inputs.append(bindings[pin])
            # the output node inherits its attrs-template's name: it carries
            # that node's weights (e.g. the fused Linear keeps the original
            # Linear's name), so name-keyed weight mapping — frontends'
            # copy_torch_weights, checkpoints — survives the rewrite
            if j == len(self.dst) - 1 and src_idx is not None:
                name = template.op.name
            else:
                name = f"{self.name}_{j}_g{old_out.guid}"
            op = op_class_for(dx.op_type)(
                name, attrs, template.op.data_type, num_inputs=len(inputs))
            node = g.add_node(op, inputs)
            new_nodes.append(node)

        new_out = new_nodes[-1]
        if new_out.out_shapes[0] != old_out.out_shapes[0]:
            raise ValueError(
                f"{self.name}: rewrite changes output shape "
                f"{old_out.out_shapes[0]} -> {new_out.out_shapes[0]}")
        # rewire external consumers of the pattern output
        for n in g.nodes.values():
            if n.guid == new_out.guid:
                continue
            n.inputs = [(new_out.guid, i) if pg == out_src_guid
                        else (pg, i) for pg, i in n.inputs]
        # drop all matched nodes
        for guid in match.values():
            del g.nodes[guid]
            g._order.remove(guid)
        g.retopo()
        if return_touched:
            return g, tuple(n.guid for n in new_nodes)
        return g


def load_substitution_json(path: str) -> List[GraphXfer]:
    """Parse a TASO-style rule collection (reference:
    substitution_loader.cc `from_json`; format: {"rule": [{"name", "srcOp":
    [{"type", "input": [{"opId","tsId"}], "para": [...]}], "dstOp": [...]}]}).
    Unknown op types or parameter values skip the rule (the reference does
    the same for ops it can't map)."""
    with open(path) as f:
        data = json.load(f)
    rules = data.get("rule", data.get("rules", []))
    xfers: List[GraphXfer] = []
    for rule in rules:
        try:
            src_json = rule.get("srcOp", [])
            src = _parse_ops(src_json)
            # first same-type src op's raw PM params — the template a dst op
            # inherits its attrs from (OpX.attrs_from default). Dropping a
            # dst-side PM_* key is only sound when it RESTATES the
            # template's value; _parse_ops rejects the rule otherwise.
            src_pm: Dict[OperatorType, Dict[str, Any]] = {}
            for op in src_json:
                t = _NAME_TO_OP.get(op.get("type"))
                if t is not None and t not in src_pm:
                    src_pm[t] = {str(p["key"]): p["value"]
                                 for p in op.get("para", [])
                                 if "key" in p and "value" in p}
            dst = _parse_ops(rule.get("dstOp", []), dst=True, src_pm=src_pm)
        except KeyError:
            continue
        if src:
            xfers.append(GraphXfer(rule.get("name", f"rule{len(xfers)}"),
                                   src, dst))
    return xfers


# TASO's ActiMode encoding in the rule collection (values observed in
# graph_subst_3_v2.json: 0 and 2) -> our ActiMode. An unmapped value makes
# the RULE unparseable — silently dropping the constraint would let an
# activation-fusing rule delete a relu without fusing it (r5 review).
_TASO_ACTI = {0: None, 1: "AC_MODE_SIGMOID", 2: "AC_MODE_RELU",
              3: "AC_MODE_TANH"}


# PM_* keys that are fully enforced by the pattern structure and apply()'s
# hard output-shape check: op type comes from the record's "type", arity
# from the pattern edges, dim counts from shape inference — dropping them
# loses nothing on either side
_PM_SHAPE_ENFORCED = {"PM_OP_TYPE", "PM_NUMDIM", "PM_NUM_INPUTS",
                      "PM_NUM_OUTPUTS"}


def _parse_ops(ops_json, dst: bool = False,
               src_pm: Optional[Dict[OperatorType, Dict[str, Any]]] = None
               ) -> List[OpX]:
    """``dst=False``: parameters become match CONSTRAINTS on the src
    pattern. ``dst=True``: they become attr OVERRIDES on the new ops —
    apply() reads only attr_overrides, so dst-side attributes fed into
    constraints would be silently ignored (r5 review). ``src_pm`` (dst side
    only) maps each src op type to its first src op's raw PM params: a dst
    op inherits its attrs from that matched node's template, so a dst-side
    PM_* key may be dropped only when it restates the template's value."""
    from ..ffconst import ActiMode

    out = []
    for op in ops_json:
        tname = op.get("type")
        if tname not in _NAME_TO_OP:
            raise KeyError(tname)
        inputs = []
        for inp in op.get("input", []):
            # negative opIds are the rule's GLOBAL open-input slots: the
            # same id appearing in several ops means the same external
            # tensor (e.g. a shared weight), so keep them verbatim —
            # renumbering per op (pre-round-5 bug) collided distinct
            # tensors AND broke src<->dst slot correspondence
            inputs.append(inp.get("opId", -1))
        attrs = {}
        for p in op.get("para", []):
            if "key" not in p or "value" not in p:
                continue
            key, val = str(p["key"]), p["value"]
            if key == "PM_ACTI":
                if val not in _TASO_ACTI:
                    raise KeyError(f"PM_ACTI={val}")
                name = _TASO_ACTI[val]
                mode = ActiMode.AC_MODE_NONE if name is None \
                    else getattr(ActiMode, name)
                # src constraint accepts both spellings of "no activation";
                # dst override must be one concrete value
                attrs["activation"] = mode if dst else (
                    (None, ActiMode.AC_MODE_NONE)
                    if name is None else mode)
            elif key.startswith("PM_"):
                if dst and key not in _PM_SHAPE_ENFORCED:
                    # semantics-bearing override (PM_AXIS, PM_PERM,
                    # PM_PARALLEL_*, ... — untranslated here: the reference
                    # stores them with reversed-dims indexing). Dropping it
                    # is sound ONLY when a same-type src template exists
                    # AND restates the same value — then the new op
                    # inherits the matched node's real attr. With no
                    # template the op would be built with DEFAULT attrs;
                    # with a DIFFERING value the rule deliberately changes
                    # the attr (e.g. a new transpose perm) and inheritance
                    # would apply the old one — either way a
                    # shape-preserving mismatch (square dims, equal-size
                    # axes) could slip a semantically wrong rewrite past
                    # the cost gate. Reject the rule like an unknown
                    # PM_ACTI (ADVICE r5) instead of silently dropping.
                    tpl = None if src_pm is None else \
                        src_pm.get(_NAME_TO_OP[tname])
                    if tpl is None or key not in tpl or tpl[key] != val:
                        raise KeyError(f"{key}={val}")
                # src-side constraints and template-restated dst keys:
                # shape-enforced keys (PM_NUMDIM, PM_NUM_INPUTS, ...) are
                # re-checked structurally; the dims-indexed ones use the
                # reference's reversed-dims indexing, so dropping them only
                # widens matching — soundness is kept by apply()'s hard
                # output-shape check plus the cost gate
                continue
            else:
                attrs[key] = val
        if dst:
            out.append(OpX(_NAME_TO_OP[tname], inputs,
                           attr_overrides=attrs))
        else:
            out.append(OpX(_NAME_TO_OP[tname], inputs, attrs))
    return out


# ------------------------------------------------------- built-in fusion rules
def fuse_consecutive_reshapes(pcg: PCG) -> int:
    """reshape(reshape(x)) -> reshape(x) (simplification pass analog of the
    reference's Graph::simplify). Returns number of rewrites."""
    count = 0
    for node in list(pcg.compute_nodes()):
        if node.op.op_type != OperatorType.OP_RESHAPE:
            continue
        (g, i) = node.inputs[0]
        prod = pcg.nodes.get(g)
        if prod is None or prod.op.op_type != OperatorType.OP_RESHAPE:
            continue
        if len(pcg.consumers(g)) != 1:
            continue
        node.inputs[0] = prod.inputs[0]
        del pcg.nodes[g]
        pcg._order.remove(g)
        count += 1
    return count


def builtin_xfers() -> List[GraphXfer]:
    """Hand-registered rewrite rules mirroring the reference's manual xfers
    (substitution.cc:3041-3226). The parallelization variants
    (partition/replicate + combine) are realized natively by the DP search's
    sharding states (unity.node_options); the algebraic rules here fuse a
    Linear with a following activation into the Linear's fused-activation
    form (the reference's cuBLAS GEMM + fused activation epilogue,
    src/ops/kernels/linear_kernels.cu) — applied by best_first_optimize when
    the simulator approves."""
    from ..ffconst import ActiMode

    none_act = (None, ActiMode.AC_MODE_NONE)
    xfers = []
    for act_op, mode, name in [
            (OperatorType.OP_RELU, ActiMode.AC_MODE_RELU, "relu"),
            (OperatorType.OP_SIGMOID, ActiMode.AC_MODE_SIGMOID, "sigmoid"),
            (OperatorType.OP_TANH, ActiMode.AC_MODE_TANH, "tanh"),
            (OperatorType.OP_GELU, ActiMode.AC_MODE_GELU, "gelu")]:
        xfers.append(GraphXfer(
            f"linear_{name}_fuse",
            src=[OpX(OperatorType.OP_LINEAR, [-1],
                     {"activation": none_act}),
                 OpX(act_op, [0])],
            dst=[OpX(OperatorType.OP_LINEAR, [-1], attrs_from=0,
                     attr_overrides={"activation": mode})]))
    return xfers


def apply_simplifications(pcg: PCG) -> int:
    """Run the always-beneficial simplification passes (reference:
    Graph::simplify called during optimization)."""
    return fuse_consecutive_reshapes(pcg)
