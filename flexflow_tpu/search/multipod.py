"""Two-level hierarchical DCN x ICI strategy search (docs/multipod.md).

The flat ``search_all`` sweep enumerates ``(dp, tp)`` factorizations x DCN
placements over the whole machine, which dies combinatorially at pod scale
(ROADMAP item 3; Alpa/OSDI'22 showed the fix shape: decompose into an
inter-mesh and an intra-mesh level). This module is that decomposition for
TPU multi-pod machines:

* **ICI level** — for one pod's chip budget, solve the full per-op
  sharding problem (dp/tp/spatial/remat via the existing ``{R,S,Q,H}``
  DP) with the simulator pinned to the single-pod topology
  ``set_axis_topology(1, 1)``. Each pod-local sub-solution is memoized by
  ``(pod subgraph signature, chip budget, pod count, lambda, remat,
  search-space, batch)`` in the Simulator's bounded table LRU, so it is
  reused across every DCN candidate of this search AND across searches on
  a warm simulator. The per-node cost entries underneath are guid-free
  (unity._node_cost_entries), so BERT's 24 twin blocks still share one
  entry — per-candidate costing is sublinear in model depth.

* **DCN level** — enumerate cross-pod structure over the memoized ICI
  sub-solutions: FSDP-style cross-pod data parallelism (the pod count
  rides the data axis as its outer, DCN-spanning factor) x a
  gradient-accumulation factor. Each candidate is priced by the
  **composition law**: the pod-local time plus the per-weight-group DCN
  delta (``hier_allreduce(w, n/p, p) - allreduce(w, n)`` — exactly the
  term the flat sweep's dcn-keyed pricing would add), with NO new
  ``op_cost`` calls. Cross-pod *pipeline* structure (pods as pipeline
  stages, schedule per cut) is enumerated by ``unity_search``'s pipeline
  block over the pod-aligned grids this module hands it
  (``pipeline_grids``).

The top composed candidates are then re-priced exactly (the simulator's
dcn-keyed entries at the candidate's real topology), so the winner is
always an exactly-priced plan; on meshes small enough to enumerate both
ways (``FLEXFLOW_TPU_SEARCH_SELFCHECK``), every candidate is re-priced
and the hierarchical winner is asserted identical to the flat
``search_all`` winner.

ShardLint (analysis.analyze_candidate) prunes statically ill-formed ICI
sub-solutions before any DCN candidate is built over them — the same
pre-simulation gate the flat sweep applies.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..parallel.pcg import PCG
from .machine_model import TPUMachineModel
from .simulator import Simulator

# exhaustive exact re-pricing below this device count (the selfcheck
# regime: candidate spaces small enough to enumerate both ways); above it
# only the REPRICE_TOP_K best composed candidates are re-priced exactly
SELFCHECK_MAX_DEV = 32
REPRICE_TOP_K = 4
# auto mode turns the hierarchical path on at this chip count (below it
# the flat sweep is cheap and covers strictly more DCN placements)
AUTO_MIN_DEV = 64

# simulated multi-pod regression topologies (cost model only, CPU):
# chips -> (pods, generation). 256 = 2 pods of 128, 1024 = 8 x 128,
# 4096 = 16 x 256 — the scaling ladder tier-1 pins without hardware.
SIMULATED_TOPOLOGIES: Dict[int, Tuple[int, str]] = {
    256: (2, "v5p"),
    1024: (8, "v5p"),
    4096: (16, "v5p"),
}


def simulated_multipod_machine(num_chips: int,
                               dcn_gbps: float = 0.0) -> TPUMachineModel:
    """One of the pinned regression topologies (SIMULATED_TOPOLOGIES)."""
    if num_chips not in SIMULATED_TOPOLOGIES:
        raise ValueError(
            f"no simulated multi-pod topology for {num_chips} chips; "
            f"pinned sizes: {sorted(SIMULATED_TOPOLOGIES)}")
    pods, gen = SIMULATED_TOPOLOGIES[num_chips]
    return TPUMachineModel.multipod(gen, pods, num_chips // pods,
                                    dcn_gbps=dcn_gbps)


def hierarchical_enabled(config, machine: TPUMachineModel,
                         n_dev: int) -> bool:
    """Whether unity_search routes the SPMD sweep through the two-level
    decomposition: ``--hierarchical-search on`` forces it (pods fall
    back to the host count), ``off`` disables it, ``auto`` (default)
    enables it only for machines EXPLICITLY declared multi-pod (--pods,
    a machine file's num_pods, or a simulated topology) at >=
    AUTO_MIN_DEV chips — a plain multi-host machine keeps the flat
    sweep, whose extra DCN placements (tp over DCN) it would otherwise
    silently stop enumerating."""
    mode = (getattr(config, "search_hierarchical", "auto") or "auto")
    if mode == "off":
        return False
    pods = machine.pods
    if pods <= 1 or n_dev % pods or n_dev // pods < 1:
        return False
    if mode == "on":
        return True
    return machine.num_pods >= 2 and n_dev >= AUTO_MIN_DEV


def pipeline_grids(n_dev: int, machine: TPUMachineModel,
                   hierarchical: bool) -> Tuple[int, ...]:
    """Pipeline-parallel degrees the search sweeps. Flat: the classic
    (2, 4, 8). Hierarchical: pod-aligned grids — every stage boundary
    coincides with (or tiles) a pod boundary, so the activation hop at a
    cut is the only DCN traffic and ``simulate_pipeline``'s host-span
    pricing charges exactly it. The schedule per cut (gpipe/1f1b/
    interleaved) stays a searched axis either way (ISSUE 10)."""
    if not hierarchical:
        return (2, 4, 8)
    pods = machine.pods
    out = sorted({pp for pp in (pods, 2 * pods, 4 * pods)
                  if 2 <= pp <= n_dev and n_dev % pp == 0})
    return tuple(out)


# --------------------------------------------------------------- ICI level
@dataclasses.dataclass
class PodSolution:
    """One memoized pod-local sub-solution: the full-graph DP solved at
    ``(dp_total, tp)`` with the simulator pinned to the single-pod
    topology. ``dp_total = pods * dp_ici`` so per-chip work is divided at
    the global scale while every collective is priced pod-local; the DCN
    delta is composed on top per candidate."""

    dp_ici: int
    tp: int
    dp_total: int
    t_ici: float          # simulate_best at topology (1, 1)
    mem: int              # per-chip peak (topology-independent)
    w_resident: int       # weights + opt state + grads part of ``mem``
    # per weight group: (synced grad bytes per chip, participants) — the
    # inputs to the DCN composition delta
    sync_groups: Tuple[Tuple[int, int], ...]
    pcg: PCG
    assignment: Dict
    states: Dict


class ICISubSolver:
    """Memoized pod-local solver. Solutions live in the Simulator's
    bounded table LRU (so a warm simulator serves them across searches)
    keyed by (pod subgraph signature, chip budget, pod count, lambda,
    remat, search-space, batch); hit/miss counters feed the bench leg and
    the memo-law test. Entries whose winning graph was rewritten by a
    GraphXfer are pinned to their concrete PCG object (guids are not
    portable across isomorphic graphs); un-rewritten entries — the common
    case, rewrites are greedy-fused before the sweep — are re-hydrated
    onto any structurally identical graph by topo position."""

    def __init__(self, sim: Simulator):
        self.sim = sim
        self.hits = 0
        self.misses = 0
        # distinct statically-pruned plans (the flat sweep's pruned_keys
        # contract: a pruned PLAN is counted/logged once, not once per
        # lambda iteration)
        self.pruned_static = 0
        self._pruned_keys: set = set()

    def solve(self, pcg: PCG, machine: TPUMachineModel, chips: int,
              pods: int, batch: int, lam: float, remat: str, space,
              xfers, budget: int, alpha: float,
              protected_guids: Sequence[int], split_threshold: int,
              slog, static_on: bool) -> List[PodSolution]:
        from .unity import _space_key

        # every hyperparameter best_first_optimize's answer depends on is
        # part of the key — a warm simulator shared across differently
        # configured searches (elastic replan, drift re-rank) must never
        # serve a solution the new configuration would not have produced
        key = ("ici_pod_solution", pcg.hash(), chips, pods,
               round(lam, 6), remat, _space_key(space), batch,
               tuple(sorted(x.name for x in xfers)), budget,
               round(alpha, 9), tuple(sorted(protected_guids)),
               split_threshold, bool(static_on))
        hit = self.sim.table_get(key)
        if hit is not None:
            sols = self._rehydrate(hit, pcg)
            if sols is not None:
                self.hits += 1
                return sols
        self.misses += 1
        sols = self._solve_uncached(
            pcg, machine, chips, pods, batch, lam, remat, space, xfers,
            budget, alpha, protected_guids, split_threshold, slog,
            static_on)
        self.sim.table_put(key, self._dehydrate(sols, pcg))
        return sols

    def _solve_uncached(self, pcg, machine, chips, pods, batch, lam,
                        remat, space, xfers, budget, alpha,
                        protected_guids, split_threshold, slog,
                        static_on) -> List[PodSolution]:
        from .unity import (assignment_to_strategy, best_first_optimize,
                            factorizations)

        if static_on:
            from ..analysis import analyze_candidate
        sim = self.sim
        sols: List[PodSolution] = []
        saved_topo = (sim.dp_dcn, sim.tp_dcn)
        try:
            sim.set_axis_topology(1, 1)  # pure pod-local pricing
            for dp_ici, tp in factorizations(chips):
                dp_total = dp_ici * pods
                if batch % dp_total:
                    continue
                g, a, s, t = best_first_optimize(
                    pcg, sim, dp_total, tp, batch, xfers,
                    budget=max(budget // 4, 4), alpha=alpha, space=space,
                    lam=lam, protected_guids=protected_guids,
                    split_threshold=split_threshold, search_log=slog,
                    remat=remat)
                if static_on:
                    strat = assignment_to_strategy(g, a, s, dp_total, tp,
                                                   machine=machine)
                    strat.remat = remat
                    rep = analyze_candidate(g, strat)
                    if rep.errors:
                        pk = (dp_total, tp, remat)
                        if pk not in self._pruned_keys:
                            self._pruned_keys.add(pk)
                            self.pruned_static += 1
                            slog.log(event="pruned_static", dp=dp_total,
                                     tp=tp, lam=round(lam, 4),
                                     remat=remat, level="ici",
                                     rules=rep.rules_fired(),
                                     first=rep.errors[0]
                                     .format_line()[:300])
                        continue
                _, mem = sim.simulate(g, a, s)
                w_res, groups = _sync_profile(sim, g, a)
                sols.append(PodSolution(
                    dp_ici=dp_ici, tp=tp, dp_total=dp_total, t_ici=t,
                    mem=mem, w_resident=w_res, sync_groups=groups,
                    pcg=g, assignment=a, states=s))
        finally:
            sim.set_axis_topology(*saved_topo)
        return sols

    # --- memo (de)hydration: guid-free by topo position ------------------
    def _dehydrate(self, sols: List[PodSolution], base: PCG):
        import weakref

        out = []
        for sol in sols:
            if sol.pcg is not base:
                # a rewrite won: the solution's guids are private to the
                # rewritten graph, so the entry is only valid for callers
                # passing the SAME base graph it was solved from (the
                # within-search λ/remat re-solve case) — pin via weakref
                # so a dead candidate graph never anchors the LRU
                out.append(("pinned", (weakref.ref(base), sol)))
                continue
            order = [n.guid for n in base.compute_nodes()]
            a_list = [sol.assignment.get(gg) for gg in order]
            s_list = [sol.states.get(gg, "R") for gg in order]
            out.append(("portable",
                        (sol.dp_ici, sol.tp, sol.dp_total, sol.t_ici,
                         sol.mem, sol.w_resident, sol.sync_groups,
                         a_list, s_list)))
        return tuple(out)

    def _rehydrate(self, stored, pcg: PCG) -> Optional[List[PodSolution]]:
        sols: List[PodSolution] = []
        order = [n.guid for n in pcg.compute_nodes()]
        for kind, payload in stored:
            if kind == "pinned":
                base_ref, sol = payload
                if base_ref() is not pcg:
                    # solved from a different base graph: the whole entry
                    # is for another graph generation — re-solve
                    return None
                sols.append(sol)
                continue
            (dp_ici, tp, dp_total, t_ici, mem, w_res, groups,
             a_list, s_list) = payload
            if len(a_list) != len(order):
                return None
            sols.append(PodSolution(
                dp_ici=dp_ici, tp=tp, dp_total=dp_total, t_ici=t_ici,
                mem=mem, w_resident=w_res, sync_groups=groups, pcg=pcg,
                assignment={gg: sh for gg, sh in zip(order, a_list)
                            if sh is not None},
                states={gg: st for gg, st in zip(order, s_list)}))
        return sols


def _sync_profile(sim: Simulator, g: PCG, assignment: Dict
                  ) -> Tuple[int, Tuple[Tuple[int, int], ...]]:
    """(weights-resident bytes, per-group (synced grad bytes, n)) from a
    solution's cached CostMetrics — all ``op_cost`` lookups hit when
    called at the topology the solution was priced under."""
    w_res = 0
    groups: List[Tuple[int, int]] = []
    for node in g.compute_nodes():
        sh = assignment.get(node.guid)
        if sh is None:
            continue
        in_shapes = [g.nodes[gg].out_shapes[i] for gg, i in node.inputs]
        cm = sim.op_cost(node, in_shapes, sh)
        w_res += cm.weights_memory * (1 + sim.opt_state_words) \
            + sim.scaled_bytes(cm.weights_memory, node)
        sync_n = sh.dp * (sh.tp if sh.kind in ("ring", "spatial")
                          else sh.act_tp)
        if cm.weights_memory and sync_n > 1:
            groups.append((cm.weights_memory, sync_n))
    return w_res, tuple(groups)


# --------------------------------------------------------------- DCN level
@dataclasses.dataclass
class DCNCandidate:
    """One cross-pod candidate: an ICI sub-solution lifted to the full
    machine with the pod count on the data axis plus a gradient-
    accumulation factor. ``est_*`` are the composition-law estimates;
    exact pricing is filled by the reprice pass for the top candidates."""

    sol: PodSolution
    remat: str
    ga: int
    est_t: float
    est_mem: int
    exact: bool = False
    t: float = 0.0
    mem: int = 0


def compose_dcn_sync(machine: TPUMachineModel, sim: Simulator,
                     sol: PodSolution, pods: int) -> float:
    """The composition law's DCN term: for every weight group the delta
    between the hierarchical allreduce the flat dcn-keyed pricing would
    charge (``hier_allreduce(w, n/p, p)``) and the pod-local allreduce
    already inside ``t_ici`` (``allreduce(w, n)``). Groups whose
    participant count the pod factor does not divide stay pod-local (the
    same clamp ``Simulator._op_cost_uncached`` applies)."""
    delta = 0.0
    for w_bytes, sync_n in sol.sync_groups:
        if sync_n % pods:
            continue
        sync_ici = sync_n // pods
        delta += (machine.hier_allreduce_time(
            w_bytes, sync_ici, pods,
            nic_sharers=sim._nic_sharers(sync_ici))
            - machine.allreduce_time(w_bytes, sync_n))
    return max(delta, 0.0)


def _accum_overhead(sol: PodSolution, ga: int, sim: Simulator) -> float:
    """Extra per-step time of ``ga`` gradient-accumulation microsteps:
    compute and sync totals are unchanged (same flops, one reduction),
    but each extra microstep re-dispatches the graph."""
    if ga <= 1:
        return 0.0
    n_nodes = len(sol.pcg.compute_nodes())
    return (ga - 1) * n_nodes * 2 * sim.op_overhead


def _ga_mem(sol: PodSolution, ga: int) -> int:
    """Gradient accumulation scales the activation+transient part of the
    peak by 1/ga (each microstep materializes 1/ga of the batch); weights,
    optimizer state and grads stay resident."""
    act = max(sol.mem - sol.w_resident, 0)
    return sol.w_resident + -(-act // ga)


def hierarchical_sweep(base_pcg: PCG, sim: Simulator,
                       machine: TPUMachineModel, n_dev: int, batch: int,
                       lam: float, mem_budget: Optional[int],
                       space, remat_levels: Sequence[str], xfers,
                       budget: int, alpha: float,
                       protected_guids: Sequence[int],
                       split_threshold: int, slog,
                       solver: ICISubSolver, static_on: bool,
                       pool_consider: Callable, stats: Dict):
    """One sweep of the two-level search at a fixed lambda — the
    hierarchical replacement for ``unity_search``'s flat ``search_all``
    closure. Returns the chosen SearchResult (or None), applying the same
    selection rule: best feasible candidate by exact time, falling back
    to minimum memory."""
    from .unity import SearchResult

    pods = machine.pods
    chips = n_dev // pods

    # ---- ICI level: memoized pod-local sub-solutions per remat level
    sols_by_remat: Dict[str, List[PodSolution]] = {}
    for remat in remat_levels:
        sols_by_remat[remat] = solver.solve(
            base_pcg, machine, chips, pods, batch, lam, remat, space,
            xfers, budget, alpha, protected_guids, split_threshold, slog,
            static_on)

    # ---- DCN level: compose candidates over the memoized solutions.
    # Zero op_cost work happens in this loop — the miss counter delta is
    # the memo law's ground truth (stats["dcn_enum_op_cost_misses"]).
    misses0 = sim.cost_cache_misses
    cands: List[DCNCandidate] = []
    ga_levels = (1, 2, 4) if mem_budget is not None else (1,)
    for remat, sols in sols_by_remat.items():
        for sol in sols:
            dcn_delta = compose_dcn_sync(machine, sim, sol, pods)
            for ga in ga_levels:
                if batch % (sol.dp_total * ga):
                    continue
                est_t = sol.t_ici + dcn_delta + _accum_overhead(sol, ga,
                                                                sim)
                est_mem = _ga_mem(sol, ga)
                cands.append(DCNCandidate(sol=sol, remat=remat, ga=ga,
                                          est_t=est_t, est_mem=est_mem))
                slog.log(event="dcn_candidate", dp=sol.dp_total,
                         tp=sol.tp, pods=pods, ga=ga, lam=round(lam, 4),
                         remat=remat, cost_ms=round(est_t * 1e3, 4),
                         mem_mib=round(est_mem / 2 ** 20, 1),
                         feasible=bool(mem_budget is None
                                       or est_mem <= mem_budget))
    stats["dcn_candidates"] = stats.get("dcn_candidates", 0) + len(cands)
    stats["dcn_enum_op_cost_misses"] = stats.get(
        "dcn_enum_op_cost_misses", 0) + (sim.cost_cache_misses - misses0)
    if not cands:
        return None

    # ---- exact re-pricing of the top composed candidates at their real
    # topology (exhaustive on small meshes — the selfcheck regime)
    def _order(c: DCNCandidate):
        feas = mem_budget is None or c.est_mem <= mem_budget
        return (not feas, c.est_t)

    cands.sort(key=_order)
    k = len(cands) if n_dev <= SELFCHECK_MAX_DEV else REPRICE_TOP_K
    repriced: List[Tuple[DCNCandidate, SearchResult]] = []
    # `accepted` mirrors THIS sweep's actual selection rule (feasibility
    # included) and best_ms is monotone — the same search-log invariant
    # the flat sweep keeps, so replaying the log reconstructs the sweep
    sweep_best = float("inf")
    for cand in cands[:k]:
        res = _reprice_exact(base_pcg, sim, machine, pods, batch, lam,
                             cand, space, xfers, budget, alpha,
                             protected_guids, split_threshold, slog,
                             static_on, solver)
        if res is None:
            continue  # ShardLint pruned the repriced assignment
        repriced.append((cand, res))
        pool_consider(res)
        feasible = mem_budget is None or cand.mem <= mem_budget
        accepted = feasible and cand.t < sweep_best
        if accepted:
            sweep_best = cand.t
        slog.log(event="candidate", dp=cand.sol.dp_total, tp=cand.sol.tp,
                 dcn=[pods, 1], pods=pods, ga=cand.ga,
                 lam=round(lam, 4), remat=cand.remat,
                 cost_ms=round(cand.t * 1e3, 4),
                 mem_mib=round(cand.mem / 2 ** 20, 1),
                 feasible=bool(feasible),
                 accepted=bool(accepted),
                 best_ms=round((sweep_best if sweep_best != float("inf")
                                else cand.t) * 1e3, 4))
    stats["repriced"] = stats.get("repriced", 0) + len(repriced)
    stats["ici_memo_hits"] = solver.hits
    stats["ici_memo_misses"] = solver.misses
    if not repriced:
        return None

    if mem_budget is not None:
        ok = [r for _c, r in repriced if r.sim_memory <= mem_budget]
        if ok:
            return min(ok, key=lambda r: r.sim_time)
        return min((r for _c, r in repriced),
                   key=lambda r: r.sim_memory)
    return min((r for _c, r in repriced), key=lambda r: r.sim_time)


def _reprice_exact(base_pcg, sim, machine, pods, batch, lam, cand,
                   space, xfers, budget, alpha, protected_guids,
                   split_threshold, slog, static_on, solver):
    """Exact pricing of one DCN candidate: the same calls the flat sweep
    makes at the candidate's topology, served almost entirely from the
    dcn-keyed caches the ICI solve warmed. Returns None when ShardLint
    rejects the repriced assignment — the (pods, 1) pricing can steer
    the DP/rewrites to a different assignment than the pod-local solve,
    so the static gate re-runs here exactly like the flat sweep's."""
    from .unity import (SearchResult, assignment_to_strategy,
                        best_first_optimize)

    sol = cand.sol
    saved_topo = (sim.dp_dcn, sim.tp_dcn)
    try:
        sim.set_axis_topology(pods, 1)
        g, a, s, t = best_first_optimize(
            base_pcg, sim, sol.dp_total, sol.tp, batch, xfers,
            budget=max(budget // 4, 4), alpha=alpha, space=space,
            lam=lam, protected_guids=protected_guids,
            split_threshold=split_threshold, search_log=slog,
            remat=cand.remat)
        strat = assignment_to_strategy(g, a, s, sol.dp_total, sol.tp,
                                       machine=machine, dcn=(pods, 1))
        strat.remat = cand.remat
        if static_on:
            from ..analysis import analyze_candidate

            rep = analyze_candidate(g, strat)
            if rep.errors:
                pk = (sol.dp_total, sol.tp, cand.remat)
                if pk not in solver._pruned_keys:
                    solver._pruned_keys.add(pk)
                    solver.pruned_static += 1
                    slog.log(event="pruned_static", dp=sol.dp_total,
                             tp=sol.tp, dcn=[pods, 1],
                             lam=round(lam, 4), remat=cand.remat,
                             level="dcn", rules=rep.rules_fired(),
                             first=rep.errors[0].format_line()[:300])
                return None
        _, mem = sim.simulate(g, a, s)
        if cand.ga > 1:
            # inside the topology scope: every op_cost lookup hits the
            # entries the simulate() above just touched
            w_res, _ = _sync_profile(sim, g, a)
            mem = w_res + -(-max(mem - w_res, 0) // cand.ga)
    finally:
        sim.set_axis_topology(*saved_topo)
    t += _accum_overhead(sol, cand.ga, sim)
    cand.exact, cand.t, cand.mem = True, t, mem
    strat.pods = (pods, "dp", cand.ga)
    return SearchResult(
        strategy=strat, assignment=a, sim_time=t, sim_memory=mem,
        mesh_shape=(sol.dp_total, sol.tp), pcg=g, states=s,
        dcn=(pods, 1), remat=cand.remat, pod_plan=(pods, "dp", cand.ga))


def assert_selfcheck_matches_flat(hier_best, flat_best) -> None:
    """FLEXFLOW_TPU_SEARCH_SELFCHECK extension (docs/multipod.md): on a
    mesh small enough to enumerate both ways, the two-level decomposition
    must choose the same plan as the flat sweep — same mesh, DCN
    placement and remat level. A mismatch means either the composition
    law mis-ranked the candidates or the decomposition's pruning
    assumption (tensor parallelism never spans DCN) cost the winner."""
    if hier_best is None or flat_best is None:
        if (hier_best is None) != (flat_best is None):
            raise AssertionError(
                "multipod selfcheck: hierarchical and flat sweeps "
                f"disagree on feasibility: hier={hier_best!r} "
                f"flat={flat_best!r}")
        return
    h = (tuple(hier_best.mesh_shape), tuple(hier_best.dcn),
         hier_best.remat)
    f = (tuple(flat_best.mesh_shape), tuple(flat_best.dcn),
         flat_best.remat)
    if h != f:
        raise AssertionError(
            "multipod selfcheck: hierarchical winner "
            f"(mesh, dcn, remat)={h} != flat search_all winner {f} — "
            "the DCN x ICI composition law diverged from flat pricing "
            "(or the winner needed a DCN placement outside the "
            "decomposition's space)")


def naive_dp_pods_time(pcg: PCG, sim: Simulator,
                       machine: TPUMachineModel) -> float:
    """Simulated step time of the naive baseline at pod scale: pure data
    parallelism over every chip with the pod factor on the data axis —
    what running the single-pod default at dp x pods would cost. The
    bench leg's denominator."""
    from .simulator import OpSharding
    from .unity import simulate_best

    n = machine.num_chips
    pods = machine.pods
    assignment = {node.guid: OpSharding(dp=n)
                  for node in pcg.compute_nodes()}
    saved_topo = (sim.dp_dcn, sim.tp_dcn)
    try:
        sim.set_axis_topology(pods, 1)
        return simulate_best(sim, pcg, assignment, {})
    finally:
        sim.set_axis_topology(*saved_topo)
