"""Unity-style auto-parallelization search, TPU-native.

Rebuild of the reference's search stack (SURVEY §2.1 L4a): GraphSearchHelper's
outer substitution loop (substitution.cc:1898, base_optimize :2229),
SearchHelper's DP over per-node MachineViews (graph.h:170-283), memory-aware λ
search (graph.cc:2060-2133), and the legacy MCMC fallback (model.cc:3285).

TPU-native reformulation (SURVEY §7): the reference searches over graph
substitutions that insert partition/combine/replicate/reduction nodes and
assigns 1-D divisor-degree MachineViews (register_all_machine_views,
graph.cc:2329). Under XLA SPMD that space is: (a) a mesh factorization
(dp, tp) of the chip count, and (b) a per-op choice of how the tp axis is
applied, with resharding transitions between choices. The per-op state is the
activation's sharding class:

  'R'  batch-sharded over dp only (replicated over the model axis)
  'S'  additionally sharded over the hidden (last) dim      — Megatron TP
  'Q'  additionally sharded over the sequence dim           — sequence/SP

and the per-op kinds: none | col | row | heads | table | expert | ring.
Transitions pay the collective the matching parallel op would run
(Repartition = free slice, Combine = all-gather, AllToAll for S<->Q —
src/parallel_ops/), and ``insert_parallel_ops`` materializes those transitions
as first-class parallel-op PCG nodes, matching the reference's search output.

  outer best-first loop over GraphXfer rewrites  == base_optimize
  outer loop over (dp, tp) factorizations        == enumerating MachineViews
  per-graph DP over {R,S,Q} sharding states      == graph_cost<T>
  transition costs from the Simulator            == estimate_xfer_cost
  alpha pruning + budget                         == base_optimize's prune
  memory λ binary search                         == graph_optimize_task λ loop
  remat level (none|selective|full) per strategy == beyond ref (docs/remat.md)
  MCMC fallback                                  == FFModel::mcmc_optimize

The output is a Strategy (per-op shardings) — the artifact the reference
serializes as optimal_views.
"""
from __future__ import annotations

import dataclasses
import heapq
import itertools
import math
import random
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..ffconst import OperatorType
from ..machine_view import MachineView
from ..parallel.pcg import PCG, PCGNode
from ..parallel.strategy import Strategy
from ..utils.recursive_logger import RecursiveLogger
from .machine_model import TPUMachineModel
from .simulator import OpSharding, Simulator, selfcheck_enabled

_log = RecursiveLogger("unity")

# state-preserving ops (elementwise etc.): pass R through; pass S/Q through
# when the sharded dim divides
_STATE_PRESERVING = {
    OperatorType.OP_RELU, OperatorType.OP_GELU, OperatorType.OP_TANH,
    OperatorType.OP_SIGMOID, OperatorType.OP_ELU, OperatorType.OP_IDENTITY,
    OperatorType.OP_DROPOUT, OperatorType.OP_SCALAR_MULTIPLY,
    OperatorType.OP_SCALAR_ADD, OperatorType.OP_SCALAR_SUB,
    OperatorType.OP_SCALAR_TRUE_DIV, OperatorType.OP_CAST,
    OperatorType.OP_EXP, OperatorType.OP_POW,
}
_ELEMENTWISE_BINARY = {
    OperatorType.OP_EW_ADD, OperatorType.OP_EW_SUB, OperatorType.OP_EW_MUL,
    OperatorType.OP_EW_DIV, OperatorType.OP_EW_MAX, OperatorType.OP_EW_MIN,
}


@dataclasses.dataclass
class SearchSpace:
    """Which parallelism families the search may use. The reference's
    enable_{parameter,attribute}_parallel flags gate only the legacy MCMC
    space (linear.cc:727,777 get_random_parallel_config /
    is_valid_parallel_config); the Unity graph search always explores the full
    space — mirrored here by ``full()`` vs ``from_config()``."""

    parameter: bool = True   # col/row linear, table-sharded embedding
    attribute: bool = True   # head-parallel attention
    sequence: bool = True    # ring attention + Q states (TPU-native extension)
    expert: bool = True      # expert-parallel MoE

    @staticmethod
    def full() -> "SearchSpace":
        return SearchSpace()

    @staticmethod
    def from_config(config) -> "SearchSpace":
        return SearchSpace(
            parameter=config.enable_parameter_parallel,
            attribute=config.enable_attribute_parallel,
            sequence=getattr(config, "enable_sequence_parallel", True),
            expert=config.enable_parameter_parallel)


@dataclasses.dataclass
class RankedCandidate:
    """One entry of ``SearchResult.ranked`` — the strategy-safety layer's
    fallback chain (ISSUE 5). ``strategy_json`` is the candidate's Strategy
    serialized against its OWN (possibly rewritten) graph, so a fallback
    compile can re-map it by node name onto a freshly built PCG
    (``Strategy.from_json``); the winner (rank 0) and pipeline candidates
    carry None — the winner is already compiled, and the GPipe trainer is
    outside the cascade's SPMD re-entry path."""

    mesh_shape: Tuple[int, int]
    dcn: Tuple[int, int] = (1, 1)
    remat: str = "none"
    sim_time: float = 0.0
    sim_memory: int = 0
    feasible: bool = True
    pipeline: Optional[Tuple[int, int, int]] = None
    # pipeline schedule of the candidate (ISSUE 10): gpipe | 1f1b |
    # interleaved ("" for SPMD candidates), with the interleaved virtual
    # chunk count — distinct schedules of one grid are distinct candidates
    schedule: str = ""
    virtual_stages: int = 1
    # pod-level assignment of the hierarchical multi-pod search (ISSUE 15;
    # docs/multipod.md): (pod count, "dp"|"pipeline", grad-accum factor),
    # None for flat-searched / single-pod candidates
    pods: Optional[Tuple[int, str, int]] = None
    strategy_json: Optional[str] = None

    def describe(self) -> str:
        # same vocabulary as Strategy.describe(), so a plan reads the same
        # in fallback events whether described from the chain or the model
        bits = [f"mesh={tuple(self.mesh_shape)}"]
        if self.pipeline:
            bits.append(f"pipeline={tuple(self.pipeline)}")
            from ..parallel.pipeline import describe_schedule

            sched = describe_schedule(self.schedule, self.virtual_stages)
            if sched:
                bits.append(f"schedule={sched}")
        if self.remat and self.remat != "none":
            bits.append(f"remat={self.remat}")
        if tuple(self.dcn) != (1, 1):
            bits.append(f"dcn={tuple(self.dcn)}")
        if self.pods:
            from ..parallel.strategy import describe_pods

            bits.append(describe_pods(self.pods))
        return " ".join(bits)


@dataclasses.dataclass
class SearchResult:
    strategy: Strategy
    assignment: Dict[int, OpSharding]
    sim_time: float
    sim_memory: int
    mesh_shape: Tuple[int, int]
    pcg: Optional[PCG] = None          # rewritten graph (xfers applied)
    states: Optional[Dict[int, str]] = None
    # (dp_dcn, tp_dcn): the DCN-spanning subfactor of each mesh axis on a
    # multi-host machine ((1, 1) = single slice)
    dcn: Tuple[int, int] = (1, 1)
    # activation-remat level the winning plan trains under (ISSUE 3):
    # none | selective | full — also stamped on strategy.remat so the
    # Executor/PipelineTrainer apply the matching jax.checkpoint policy
    remat: str = "none"
    # delta-cost engine telemetry, filled by unity_search: total search wall
    # seconds, number of costed candidates, and the Simulator's cache
    # hit/miss counters (bench.py's search_wall_s / search_candidates_per_s)
    search_wall_s: Optional[float] = None
    candidates: int = 0
    cache_stats: Optional[Dict] = None
    # ranked top-K candidate chain (ISSUE 5): rank 0 is the winner; the
    # rest are the best distinct runners-up, each restorable by name via
    # strategy_json — what the executor's fallback cascade degrades
    # through when the winner fails to compile / OOMs / fails the audit
    ranked: List[RankedCandidate] = dataclasses.field(default_factory=list)
    # candidates ShardLint rejected before simulation (ISSUE 7): free
    # rejections — none of these paid an op_cost/simulate call
    pruned_static: int = 0
    # pod-level assignment from the hierarchical multi-pod search
    # (ISSUE 15): (pod count, "dp"|"pipeline", grad-accum factor); the
    # same triple is stamped on strategy.pods
    pod_plan: Optional[Tuple[int, str, int]] = None
    # hierarchical-search telemetry (docs/multipod.md): ICI sub-solution
    # memo hits/misses, DCN candidates composed, op_cost misses during
    # the DCN enumeration (the memo law's ground truth — must be 0),
    # exactly-repriced candidate count
    multipod_stats: Optional[Dict] = None
    # the WARM simulator that priced this search (ISSUE 8): the drift
    # sentinel's closed loop repairs THIS ruler in place (selective
    # delta-cost invalidation) and re-ranks `ranked` with its hot tables;
    # an elastic restart hands it back in for cache reuse
    sim: Optional[Simulator] = dataclasses.field(default=None, repr=False)


def dcn_placements(dp: int, tp: int, num_hosts: int
                   ) -> List[Tuple[int, int]]:
    """How the host factor can map onto a (dp, tp) mesh: every split
    h_dp * h_tp == num_hosts with h_dp | dp and h_tp | tp. The DCN factor of
    an axis must not split an ICI ring, so it is an outer factor (reference:
    inter-node placement in EnhancedMachineModel; jax:
    mesh_utils.create_hybrid_device_mesh's same constraint)."""
    if num_hosts <= 1:
        return [(1, 1)]
    out = []
    for h_dp in range(1, num_hosts + 1):
        if num_hosts % h_dp:
            continue
        h_tp = num_hosts // h_dp
        if dp % h_dp == 0 and tp % h_tp == 0:
            out.append((h_dp, h_tp))
    return out


def factorizations(n: int) -> List[Tuple[int, int]]:
    """(dp, tp) pairs with dp*tp == n (reference: divisor-degree views)."""
    out = []
    for tp in range(1, n + 1):
        if n % tp == 0:
            out.append((n // tp, tp))
    return out


def node_options(node: PCGNode, tp: int,
                 in_shapes: List[Tuple[int, ...]],
                 space: Optional[SearchSpace] = None
                 ) -> List[Tuple[str, str, str]]:
    """Per-op (kind, in_state, out_state) choices — the valid-MachineView
    enumeration of the reference (get_valid_machine_views, graph.h:230) over
    the TPU state space. Divisibility checks inline."""
    space = space or SearchSpace.full()
    ot = node.op.op_type
    a = node.op.attrs
    out = node.out_shapes[0] if node.out_shapes else ()

    def q_ok(shape):  # sequence dim shardable
        return len(shape) >= 3 and shape[1] % tp == 0

    def s_ok(shape):  # hidden (last) dim shardable
        return len(shape) >= 2 and shape[-1] % tp == 0

    def h_ok(shape):  # spatial height (NCHW dim 2) shardable
        return len(shape) == 4 and shape[2] % tp == 0

    opts: List[Tuple[str, str, str]] = [("none", "R", "R")]
    if tp <= 1:
        return opts
    if ot == OperatorType.OP_LINEAR:
        if space.parameter and a["out_dim"] % tp == 0:
            opts.append(("col", "R", "S"))
        if space.parameter and in_shapes and in_shapes[0][-1] % tp == 0:
            opts.append(("row", "S", "R"))
        if space.sequence and in_shapes and q_ok(in_shapes[0]) and q_ok(out):
            opts.append(("none", "Q", "Q"))  # dense is per-token
    elif ot == OperatorType.OP_MULTIHEAD_ATTENTION:
        if space.attribute and a["num_heads"] % tp == 0:
            opts.append(("heads", "R", "R"))
        if space.sequence and in_shapes and q_ok(in_shapes[0]) \
                and len(node.inputs) == 3 \
                and len({g for g, _ in node.inputs}) == 1:
            # self-attention only; dropout is fine — ring/Ulysses share the
            # flash kernel's counter-based in-kernel dropout stream
            # (kernels/ring_attention.py:49-56, ops/attention.py:113-129),
            # so the search must not refuse SP to dropout models
            opts.append(("ring", "Q", "Q"))
    elif ot == OperatorType.OP_EMBEDDING:
        if space.parameter and a["num_entries"] % tp == 0:
            opts.append(("table", "R", "R"))
    elif ot == OperatorType.OP_CONV2D:
        if space.parameter and a["out_channels"] % tp == 0:
            opts.append(("col", "R", "S"))
        if space.attribute and h_ok(out) and in_shapes \
                and h_ok(in_shapes[0]):
            # spatial (height) attribute parallelism — the reference's main
            # Unity lever for CNNs (create_mapping_xfers<Conv2D>,
            # substitution.cc:1797); XLA SPMD inserts the halo exchange
            opts.append(("spatial", "H", "H"))
    elif ot == OperatorType.OP_POOL2D:
        if space.attribute and h_ok(out) and in_shapes \
                and h_ok(in_shapes[0]):
            # create_mapping_xfers<Pool2D> (substitution.cc:1798)
            opts.append(("spatial", "H", "H"))
    elif ot == OperatorType.OP_BATCHNORM:
        if space.attribute and h_ok(out):
            # per-channel stats reduce over (b, h, w): XLA psums the
            # spatial partials — pass-through in H
            opts.append(("none", "H", "H"))
    elif ot == OperatorType.OP_EXPERTS:
        if space.expert and a["n"] % tp == 0:
            opts.append(("expert", "R", "R"))
    elif ot == OperatorType.OP_LAYERNORM:
        axes = [x % len(out) for x in a.get("axes", [len(out) - 1])] \
            if out else []
        if space.sequence and q_ok(out) and 1 not in axes:
            opts.append(("none", "Q", "Q"))
    elif ot == OperatorType.OP_SOFTMAX:
        axis = a.get("axis", -1) % len(out) if out else -1
        if space.sequence and q_ok(out) and axis != 1:
            opts.append(("none", "Q", "Q"))
    elif ot in _ELEMENTWISE_BINARY:
        if s_ok(out):
            opts.append(("none", "S", "S"))
        if space.sequence and q_ok(out):
            opts.append(("none", "Q", "Q"))
        if space.attribute and h_ok(out):
            opts.append(("none", "H", "H"))
    elif ot in _STATE_PRESERVING and len(node.inputs) == 1:
        if s_ok(out):
            opts.append(("none", "S", "S"))
        if space.sequence and q_ok(out):
            opts.append(("none", "Q", "Q"))
        if space.attribute and h_ok(out):
            opts.append(("none", "H", "H"))
    return opts


def _space_key(space: Optional[SearchSpace]) -> Tuple[bool, bool, bool, bool]:
    space = space or SearchSpace.full()
    return (space.parameter, space.attribute, space.sequence, space.expert)


def _node_cost_entries(sim: Simulator, node: PCGNode,
                       in_shapes: List[Tuple[int, ...]], dp: int, tp: int,
                       space: Optional[SearchSpace], remat: str = "none"):
    """Materialize the per-node cost table the DP mixes over: one entry
    ``(kind, in_state, out_state, time_s, resident_mem_bytes)`` per valid
    sharding option, plus the unsharded fallback row. Held in the
    Simulator's bounded LRU keyed by (op params key, in-shapes, dp, tp,
    dcn, search-space, remat level) — guid-independent, so the 24
    identical BERT layers share one entry and the table survives
    factorization sweeps, λ iterations and rewrite candidates (the
    delta-cost engine's unit of reuse; reference analog: simulator.cc's
    cached task costs). The remat level shapes both sides of the entry:
    recompute time inside ``op_cost`` (OpSharding.remat is part of ITS
    key) and the keep-fraction-scaled resident memory."""
    key = ("dp_table", node.op.params_key(), tuple(map(tuple, in_shapes)),
           dp, tp, sim.dp_dcn, sim.tp_dcn, _space_key(space), remat)
    hit = sim.table_get(key)
    if hit is not None:
        return hit
    entries = []
    for kind, in_state, out_state in node_options(node, tp, in_shapes, space):
        eff_tp = tp if kind != "none" else 1
        act_tp = tp if (kind == "none"
                        and out_state in ("S", "Q", "H")) else 1
        sh = OpSharding(dp=dp, tp=eff_tp, kind=kind, act_tp=act_tp,
                        remat=remat)
        cm = sim.op_cost(node, in_shapes, sh)
        # liveness-aware per-node resident memory — the same per-node
        # formula Simulator.simulate's peak sums; the DP objective is a
        # LOWER bound on the full peak (the global transient max-term
        # cannot decompose per node) and the λ loop's accept/reject uses
        # the full simulate() model, which includes it
        entries.append((kind, in_state, out_state, cm.total_time(),
                        sim.node_resident_bytes(node, cm, remat)))
    sh = OpSharding(dp=dp, tp=1, kind="none", remat=remat)
    cm = sim.op_cost(node, in_shapes, sh)
    value = (tuple(entries),
             ("none", "R", "R", cm.total_time(),
              sim.node_resident_bytes(node, cm, remat)))
    sim.table_put(key, value)
    return value


def dp_assign(pcg: PCG, sim: Simulator, dp: int, tp: int,
              batch_size: int, space: Optional[SearchSpace] = None,
              lam: float = 1.0, remat: str = "none"
              ) -> Tuple[Dict[int, OpSharding], Dict[int, str], float]:
    """Viterbi DP over the topo order: per node, a table keyed by output
    sharding state; transitions pay resharding collectives (reference:
    find_optimal_sequence_graph_time + estimate_xfer_cost).

    ``lam`` mixes runtime and per-chip memory into the DP objective
    (reference: the MemoryOptimConfig run_time_cost_factor,
    memory_optimization.h:24-100): obj = lam * time_ms + (1-lam) * mem_GiB.
    lam=1.0 is the pure-runtime search. The per-node (time, mem) inputs to
    the mix come from ``_node_cost_entries``' memoized tables, so re-running
    at a different λ is a pure remix: zero new ``op_cost`` calls.

    Fan-in nodes sum their producers' table costs (shared ancestors are
    counted once per branch — an over-estimate the final ``simulate`` pass
    corrects); fan-out states are chosen by the first consumer walked back,
    other consumers pay conversions. Sink nodes are pinned to state R (the
    loss consumes replicated logits, reference: final-op label matching
    model.cc:3090-3124).

    ``remat`` (ISSUE 3) is the rematerialization level every emitted
    OpSharding carries: the DP's per-node (time, mem) entries are priced at
    that level, so the memory-λ mix can trade recompute flops for dropped
    activation bytes exactly like it trades collective time for sharding."""
    assignment, states, _table = _dp_core(pcg, sim, dp, tp, space, lam,
                                          remat=remat)
    sim_time = simulate_best(sim, pcg, assignment, states)
    return assignment, states, sim_time


def _dp_core(pcg: PCG, sim: Simulator, dp: int, tp: int,
             space: Optional[SearchSpace] = None, lam: float = 1.0,
             prior: Optional[Dict[int, Dict]] = None,
             dirty: Optional[Set[int]] = None, remat: str = "none"
             ) -> Tuple[Dict[int, OpSharding], Dict[int, str],
                        Dict[int, Dict]]:
    """The DP mix + backtrack behind ``dp_assign``. Returns
    (assignment, states, dp_table) so callers can reuse the table for
    incremental re-costing: with ``prior`` (the parent graph's dp_table at
    the same dp/tp/dcn/space/λ) and ``dirty`` (guids whose rows must be
    recomputed — the rewritten segment plus its resharding frontier), rows
    of clean nodes are copied verbatim. Exact, not approximate: a clean
    node's ancestor cone is untouched by construction (dirty is closed
    under descendants), so its recomputed row would be bit-identical."""
    from ..ffconst import size_of_datatype

    nodes = pcg.compute_nodes()
    sink_guids = {n.guid for n in pcg.sinks()}

    def mix(time_s: float, mem_bytes: float) -> float:
        return lam * time_s * 1e3 + (1.0 - lam) * mem_bytes / 2 ** 30

    INF = float("inf")
    # table[guid][state] = (obj, time, mem, (kind, in_state), srcs)
    table: Dict[int, Dict[str, Tuple[float, float, float, Tuple[str, str],
                                     Dict[int, str]]]] = {}
    reuse_rows = prior is not None and dirty is not None
    for node in nodes:
        if reuse_rows and node.guid not in dirty and node.guid in prior:
            table[node.guid] = prior[node.guid]
            continue
        in_shapes = [pcg.nodes[g].out_shapes[i] for g, i in node.inputs]
        opts, fallback = _node_cost_entries(sim, node, in_shapes, dp, tp,
                                            space, remat)
        if node.guid in sink_guids:
            opts = tuple(o for o in opts if o[2] == "R") or opts

        def prev_cost(state: str
                      ) -> Tuple[float, float, float, Dict[int, str]]:
            """Sum of producers' best (obj, time, mem) to deliver ``state``,
            plus the per-producer OUTPUT state that achieved it — the
            cheapest delivery may come from a producer in a different state
            via a reshard (e.g. an R consumer fed by a Q region through one
            allgather), and backtracking must reconstruct that same choice
            or the emitted strategy silently diverges from the DP's
            objective (round-5 bug: every Q region upstream of the R-pinned
            sink collapsed to all-R at backtrack)."""
            tot_o = tot_t = tot_m = 0.0
            srcs: Dict[int, str] = {}
            for g, i in node.inputs:
                p = pcg.nodes[g]
                if p.op.op_type in (OperatorType.OP_INPUT,
                                    OperatorType.OP_WEIGHT):
                    continue
                ptab = table.get(g)
                if ptab is None:
                    continue
                nbytes = int(np.prod(p.out_shapes[i])) * \
                    size_of_datatype(p.op.data_type)
                best = None
                for src_state, (po, pt, pm, _bp, _srcs) in ptab.items():
                    if po >= INF:
                        continue
                    if g in srcs and src_state != srcs[g]:
                        # a producer reached through several edges (e.g. a
                        # multi-output split) gets ONE state: later edges
                        # must price the state the first edge committed to,
                        # or pricing and backtrack diverge again
                        continue
                    # x2: the backward pass runs the transposed resharding
                    xfer = 2 * sim.resharding_cost(nbytes, src_state, state,
                                                   dp, tp)
                    cand = (po + mix(xfer, 0.0), pt + xfer, pm, src_state)
                    if best is None or cand[0] < best[0]:
                        best = cand
                if best is None:
                    return (INF, INF, INF, srcs)
                tot_o += best[0]
                tot_t += best[1]
                tot_m += best[2]
                if g in srcs:
                    # producer obj already counted by the first edge; keep
                    # only this edge's xfer increment
                    tot_o -= ptab[srcs[g]][0]
                    tot_t -= ptab[srcs[g]][1]
                    tot_m -= ptab[srcs[g]][2]
                srcs[g] = best[3]
            return (tot_o, tot_t, tot_m, srcs)

        tab: Dict[str, Tuple[float, float, float, Tuple[str, str],
                             Dict[int, str]]] = {}
        for kind, in_state, out_state, op_time, node_mem in opts:
            base_o, base_t, base_m, srcs = prev_cost(in_state)
            if base_o >= INF:
                continue
            t = base_t + op_time
            mem = base_m + node_mem
            obj = base_o + mix(op_time, node_mem)
            if out_state not in tab or obj < tab[out_state][0]:
                tab[out_state] = (obj, t, mem, (kind, in_state), srcs)
        if not tab:  # fallback: unsharded
            _kind, _in, _out, op_time, node_mem = fallback
            base_o, base_t, base_m, srcs = prev_cost("R")
            tab["R"] = (base_o + mix(op_time, node_mem),
                        base_t + op_time, base_m + node_mem,
                        ("none", "R"), srcs)
        table[node.guid] = tab

    # backtrack: choose best final state, then walk back per node
    assignment: Dict[int, OpSharding] = {}
    states: Dict[int, str] = {}
    chosen: Dict[int, str] = {}
    for node in reversed(nodes):
        tab = table[node.guid]
        if node.guid not in chosen:
            chosen[node.guid] = min(tab, key=lambda s: tab[s][0])
        st = chosen[node.guid]
        kind, _in_state = tab[st][3]
        srcs = tab[st][4]
        eff_tp = tp if kind != "none" else 1
        act_tp = tp if (kind == "none" and st in ("S", "Q", "H")) else 1
        assignment[node.guid] = OpSharding(dp=dp, tp=eff_tp, kind=kind,
                                           act_tp=act_tp, remat=remat)
        states[node.guid] = st
        for g, _ in node.inputs:
            p = pcg.nodes[g]
            if p.op.op_type not in (OperatorType.OP_INPUT,
                                    OperatorType.OP_WEIGHT) \
                    and g not in chosen:
                ptab = table[g]
                # the producer state prev_cost actually priced (may differ
                # from the op's declared in_state when a reshard was cheaper)
                chosen[g] = srcs[g] if srcs.get(g) in ptab else \
                    min(ptab, key=lambda s: ptab[s][0])
    # the caller recomputes total time via the simulator (simulate_best) so
    # resharding edges and shared subgraphs are counted exactly once
    return assignment, states, table


_warned_once: Set[str] = set()


def _warn_once(key: str, msg: str, *args) -> None:
    if key not in _warned_once:
        _warned_once.add(key)
        _log.warning(msg, *args)


def simulate_best(sim: Simulator, pcg: PCG,
                  assignment: Dict[int, OpSharding],
                  states: Dict[int, str]) -> float:
    """Event-driven makespan via the native core (reference:
    simulate_runtime's per-device timelines); falls back to the additive
    model only when the C++ extension is unavailable — a native-core
    runtime bug propagates rather than silently re-ranking candidates."""
    try:
        return sim.simulate_event_driven(pcg, assignment, states)
    except (ImportError, OSError) as e:
        _warn_once("native-sim", "native task-graph core unavailable (%s); "
                   "falling back to the additive cost model", e)
        return sim.simulate(pcg, assignment, states)[0]


def pipeline_microbatch_safe(pcg: PCG, batch: int) -> bool:
    """Whether GPipe microbatching preserves the graph's semantics: ops
    that bake the global batch size into their attributes or capacity math
    (reshape targets, MoE dispatch buffers, cache state) would compute
    wrong shapes on a microbatch — those graphs keep SPMD strategies."""
    unsafe_types = {OperatorType.OP_GROUP_BY, OperatorType.OP_AGGREGATE,
                    OperatorType.OP_AGG_SPEC, OperatorType.OP_EXPERTS,
                    OperatorType.OP_CACHE}
    for n in pcg.compute_nodes():
        ot = n.op.op_type
        if ot in unsafe_types:
            return False
        if ot == OperatorType.OP_RESHAPE and batch > 1:
            tgt = tuple(n.op.attrs.get("shape", ()))
            in_shape = (pcg.nodes[n.inputs[0][0]].out_shapes[n.inputs[0][1]]
                        if n.inputs else ())
            if tgt and in_shape and in_shape[0] == batch:
                # the input carries the batch: an all-explicit target bakes
                # the global batch volume (ReshapeOp asserts on a
                # microbatch), and a -1 wildcard anywhere but the leading
                # batch position silently absorbs the microbatch factor
                # into the wrong dim
                wild = [i for i, d in enumerate(tgt) if d == -1]
                if not wild:
                    return False
                per_sample = max(int(np.prod(in_shape)) // batch, 1)
                rest = int(np.prod([d for d in tgt if d != -1])) \
                    if len(tgt) > 1 else 1
                if in_shape[0] != batch or wild[0] != 0 or \
                        (rest > 0 and per_sample % rest):
                    return False
            elif tgt and isinstance(tgt[0], (int, np.integer)) and \
                    tgt[0] > 0 and tgt[0] % batch == 0:
                # input batch dim already merged away (e.g. (b*s, h)): an
                # explicit leading batch-derived target — the unflatten
                # back to (b, s, h) — still bakes the global batch
                return False
        if ot == OperatorType.OP_SLICE:
            items = n.op.attrs.get("items", ())
            if items and not (items[0][0] == "slice" and
                              items[0][1] == "none" and
                              items[0][2] == "none" and
                              items[0][3] in ("none", 1)):
                return False  # indexing/striding into the batch dim
    return True


def simulate_pipeline(sim: Simulator, pcg: PCG, pp: int, dp: int,
                      n_micro: int, remat: str = "full",
                      schedule: str = "gpipe", v: int = 1
                      ) -> Tuple[float, int]:
    """(step time, per-chip memory) for a pipelined (pp, dp) grid with
    ``n_micro`` microbatches, at stage-remat level ``remat`` (default
    ``full`` — the classic GPipe recompute-the-stage recipe) under
    ``schedule`` in {gpipe, 1f1b, interleaved} (``v`` virtual chunks per
    device for interleaved — docs/pipeline.md).

    The schedule is built as a TASK GRAPH and run through the SAME
    event-driven native engine that costs SPMD candidates (reference prices
    every strategy through simulate_runtime, simulator.cc:815 — one cost
    engine, unbiased decision boundary): per-(microbatch, chunk) forward
    and remat+backward tasks on per-device compute streams, boundary
    activation/gradient hops on per-link devices, weight-grad allreduce +
    optimizer update after each chunk's flush. 1f1b/interleaved graphs
    additionally chain each device's tasks in the order
    ``parallel.pipeline.pipeline_schedule`` emits — the SAME generator the
    trainer's host loop dispatches from, so the simulator prices exactly
    the execution order the trainer runs; the bubble (and interleaved's
    ~v-fold fill shrink) emerges from the schedule, no closed forms.
    Falls back to the additive closed form only when the native core is
    unavailable.

    Multi-host layout: device rows are laid out contiguously over the
    machine's chips, so row d's dp group occupies chips [d*dp, (d+1)*dp) —
    each row's host span (DCN factor of its gradient sync) and each
    boundary's medium (ICI within a host, DCN across) come from those
    cumulative chip positions, covering pp < hosts and hosts∤pp alike.

    Memory = the heaviest device row's weights + grads (replicated over
    its dp group) + the SCHEDULE's in-flight boundary activations
    (``pipeline_in_flight`` — n_micro for gpipe's flush, ~pp for 1f1b;
    the trainer retains exactly this set, releasing a microbatch's stage
    inputs/outputs as its backward completes) + the full-batch model
    inputs staged on their feeding rows (the trainer device_puts them
    once, microbatch-stacked) + one microbatch's backward-jit peak: the
    remat level's kept residuals (keep-fraction from
    ``Simulator.remat_keep_fraction`` — the SAME helper the SPMD memory
    model uses) plus the recompute working set. Kept residuals never span
    microbatches here — the trainer's fwd and bwd are separate jits."""
    from ..ffconst import size_of_datatype
    from ..parallel.pipeline import (build_stage_specs, pipeline_in_flight,
                                     split_stages)

    if schedule != "interleaved":
        v = 1
    n_chunks = pp * v
    stages = split_stages(pcg, n_chunks)
    machine = sim.machine
    hosts = machine.num_hosts
    cph = machine.chips_per_host

    def dev_of(c: int) -> int:
        return c % pp

    def first_host(d: int) -> int:
        return (d * dp) // cph

    def row_host_span(d: int) -> int:
        return ((d + 1) * dp - 1) // cph - first_host(d) + 1

    # per-chunk op costs, each priced at its device row's own host span;
    # the remat level rides the OpSharding so op_cost's backward includes
    # the level's recompute (full: one extra forward per op — exactly what
    # `stage_bwd += fwd + bwd` hand-rolled before remat was leveled)
    saved_topo = (sim.dp_dcn, sim.tp_dcn)
    stage_fwd = [0.0] * n_chunks
    stage_bwd = [0.0] * n_chunks  # includes the level's forward recompute
    stage_sync = [0.0] * n_chunks
    stage_upd = [0.0] * n_chunks
    stage_w = [0] * n_chunks
    stage_act = [0] * n_chunks
    stage_keep = [0] * n_chunks  # activations the remat level keeps resident
    try:
        for s in range(n_chunks):
            span = row_host_span(dev_of(s)) if hosts > 1 else 1
            sim.set_axis_topology(
                dp_dcn=span if (span > 1 and dp % span == 0) else 1)
            for g in stages[s]:
                node = pcg.nodes[g]
                in_shapes = [pcg.nodes[gg].out_shapes[i]
                             for gg, i in node.inputs]
                c = sim.op_cost(node, in_shapes,
                                OpSharding(dp=dp, remat=remat))
                stage_fwd[s] += c.forward_time
                # the trainer's bwd jit re-traces the stage forward at every
                # level (fwd and bwd are separate jits, residuals cannot
                # cross); under `full` op_cost already priced that recompute
                # inside backward_time — adding it again would double-count
                stage_bwd[s] += c.backward_time + (
                    c.forward_time if remat != "full" else 0.0)
                stage_sync[s] += c.sync_time
                stage_upd[s] += c.update_time
                stage_w[s] += c.weights_memory
                act = c.inputs_memory + c.outputs_memory
                stage_act[s] += act
                stage_keep[s] += int(
                    act * sim.remat_keep_fraction(node, remat))
    finally:
        sim.set_axis_topology(*saved_topo)

    # per-microbatch boundary hop time (the SAME boundary set the trainer
    # transfers — build_stage_specs exposes every cross-chunk tensor,
    # residual skips included). Interleaved pays a hop at EVERY chunk cut
    # (adjacent chunks live on different device rows) — the schedule's
    # known communication tax, priced here.
    specs = build_stage_specs(pcg, stages)
    bnd_micro = [0.0] * max(n_chunks - 1, 0)
    bnd_bytes_micro = [0] * max(n_chunks - 1, 0)  # per-microbatch bytes
    for s in range(n_chunks - 1):
        same_dev = dev_of(s) == dev_of(s + 1)
        medium = "dcn" if (hosts > 1 and
                           first_host(dev_of(s)) !=
                           first_host(dev_of(s + 1))) else "ici"
        for g, i in specs[s].outputs:
            node = pcg.nodes[g]
            # at least 1 byte: integer flooring to 0 would price the hop at
            # pure latency and make tiny cross-stage tensors free (ADVICE r4)
            nbytes = max(int(np.prod(node.out_shapes[i])) *
                         size_of_datatype(node.op.data_type)
                         // (max(dp, 1) * max(n_micro, 1)), 1)
            bnd_bytes_micro[s] += nbytes
            if not same_dev:
                bnd_micro[s] += machine.p2p_time(nbytes, medium)

    m_f = [t / max(n_micro, 1) for t in stage_fwd]
    m_b = [t / max(n_micro, 1) for t in stage_bwd]

    # ---- memory: per device row, weights + grads, the schedule's
    # in-flight boundary activations, the staged full-batch inputs, and
    # one microbatch's backward-jit peak (kept residuals + recompute
    # working set — nothing kept by the policy survives across
    # microbatches: the trainer's fwd and bwd are separate jits)
    in_flight = pipeline_in_flight(schedule, pp, n_micro, v)
    row_w = [0] * pp
    row_peak = [0] * pp   # one-microbatch backward peak (keep + act)
    row_bnd = [0] * pp    # per-microbatch boundary residency (in + out)
    row_inputs = [0] * pp  # full-batch model inputs staged on the row
    input_bytes = {n.guid: max(int(np.prod(n.out_shapes[0])) *
                               size_of_datatype(n.op.data_type)
                               // max(dp, 1), 1)
                   for n in pcg.input_nodes()}
    for s in range(n_chunks):
        d = dev_of(s)
        row_w[d] += stage_w[s]
        # a row's chunks run their backwards ONE at a time (same devices),
        # so only the widest chunk's backward-jit peak is live — max, not
        # sum (summing would overcharge interleaved rows by ~v x)
        row_peak[d] = max(row_peak[d],
                          (stage_keep[s] + stage_act[s]) //
                          max(n_micro, 1))
        # boundary tensors this chunk holds per in-flight microbatch: its
        # incoming cut (stage inputs) + its outgoing cut (stage outputs,
        # kept for the backward's cotangent accumulation)
        if s > 0:
            row_bnd[d] += bnd_bytes_micro[s - 1]
        if s < n_chunks - 1:
            row_bnd[d] += bnd_bytes_micro[s]
        for feed in specs[s].feeds:
            if feed[0] == "model":
                row_inputs[d] += input_bytes.get(feed[1], 0)
    mem = max(2 * w + in_flight * bnd + peak + inp
              for w, bnd, peak, inp in
              zip(row_w, row_bnd, row_peak, row_inputs))

    try:
        # ONE builder for every schedule: per-device order chains from the
        # shared generator, so gpipe/1f1b/interleaved makespans are
        # apples-to-apples models of the trainer's real dispatch order
        # (an unchained gpipe graph lets the engine reorder a device's
        # tasks work-conservingly — slightly optimistic, and unfair to
        # the chained schedules under uneven stage costs)
        t = _pipeline_taskgraph_makespan_sched(
            pp, v, n_micro, m_f, m_b, bnd_micro, stage_sync,
            stage_upd, schedule)
    except (ImportError, OSError) as e:
        _warn_once("native-pipe-sim", "native core unavailable for the "
                   "pipeline candidate (%s); using the additive bound", e)
        micro = [f + b for f, b in zip(m_f, m_b)]
        # diagonal fill through every chunk + steady state on the busiest
        # device row (row d owns chunks d, d+pp, ... under interleaving)
        t = (sum(micro) + (n_micro - 1) * max(
            sum(micro[d::pp]) for d in range(pp))
            + 2 * n_micro * sum(bnd_micro)
            + max(s + u for s, u in zip(stage_sync, stage_upd)))
    return t, mem


def _pipeline_taskgraph_makespan(pp: int, n_micro: int,
                                 m_f: List[float], m_b: List[float],
                                 bnd_micro: List[float],
                                 stage_sync: List[float],
                                 stage_upd: List[float]) -> float:
    """Event-driven makespan of the GPipe schedule. Devices: [0, pp) stage
    compute streams, [pp, 2pp-1) boundary links, [2pp-1, 3pp-1) per-stage
    collective streams (disjoint chip groups sync concurrently)."""
    from ..native import simulate_taskgraph

    costs: List[float] = []
    devs: List[int] = []
    esrc: List[int] = []
    edst: List[int] = []

    def add(cost: float, dev: int) -> int:
        costs.append(cost)
        devs.append(dev)
        return len(costs) - 1

    def edge(a: int, b: int) -> None:
        esrc.append(a)
        edst.append(b)

    link = lambda s: pp + s           # noqa: E731
    coll = lambda s: 2 * pp - 1 + s   # noqa: E731

    fwd_id: Dict[Tuple[int, int], int] = {}
    for m in range(n_micro):
        prev = None
        for s in range(pp):
            f = add(m_f[s], s)
            if prev is not None:
                edge(prev, f)
            fwd_id[(m, s)] = f
            if s < pp - 1:
                c = add(bnd_micro[s], link(s))
                edge(f, c)
                prev = c
            else:
                prev = f
    bwd_ids: List[List[int]] = [[] for _ in range(pp)]
    for m in reversed(range(n_micro)):  # flush: last microbatch first
        prev = None
        for s in reversed(range(pp)):
            b = add(m_b[s], s)
            edge(fwd_id[(m, s)], b)  # remat consumes the stored stage input
            if prev is not None:
                edge(prev, b)
            bwd_ids[s].append(b)
            if s > 0:
                c = add(bnd_micro[s - 1], link(s - 1))
                edge(b, c)
                prev = c
            else:
                prev = b
    for s in range(pp):
        if not bwd_ids[s]:
            continue
        tail = bwd_ids[s][-1]
        if stage_sync[s] > 0:
            # grad allreduce waits for the stage's ENTIRE backward flush —
            # every microbatch contributes to the weight grads
            sy = add(stage_sync[s], coll(s))
            for b in bwd_ids[s]:
                edge(b, sy)
            tail = sy
        if stage_upd[s] > 0:
            up = add(stage_upd[s], s)
            if tail == bwd_ids[s][-1]:  # no sync: update waits on all bwds
                for b in bwd_ids[s]:
                    edge(b, up)
            else:
                edge(tail, up)
    return simulate_taskgraph(
        np.asarray(costs), np.asarray(devs), 3 * pp - 1,
        np.asarray(esrc, dtype=np.int32),
        np.asarray(edst, dtype=np.int32))


def _pipeline_taskgraph_makespan_sched(pp: int, v: int, n_micro: int,
                                       m_f: List[float], m_b: List[float],
                                       bnd_micro: List[float],
                                       stage_sync: List[float],
                                       stage_upd: List[float],
                                       schedule: str) -> float:
    """Event-driven makespan of a pipeline schedule (gpipe, 1f1b or
    interleaved). Devices: [0, pp) device-row compute streams,
    [pp, pp + n_chunks - 1) boundary links, then pp per-row collective
    streams. The per-row execution order comes from
    ``parallel.pipeline.pipeline_schedule`` — the SAME generator the
    trainer dispatches from — encoded as chain edges between a row's
    consecutive tasks, so the makespan is the makespan of exactly the
    order the trainer runs (not an idealized work-conserving bound), and
    the three schedules are compared apples-to-apples."""
    from ..native import simulate_taskgraph
    from ..parallel.pipeline import pipeline_schedule

    n_chunks = pp * (v if schedule == "interleaved" else 1)
    last = n_chunks - 1
    costs: List[float] = []
    devs: List[int] = []
    esrc: List[int] = []
    edst: List[int] = []

    def add(cost: float, dev: int) -> int:
        costs.append(cost)
        devs.append(dev)
        return len(costs) - 1

    def edge(a: int, b: int) -> None:
        esrc.append(a)
        edst.append(b)

    # boundary links are FULL-DUPLEX (ICI): the activation hop forward and
    # the gradient hop back ride separate directional streams — sharing
    # one stream would falsely serialize 1f1b's steady state, where the
    # two directions of a cut are busy simultaneously (gpipe's fill and
    # drain phases never overlap, so it would never pay that artifact)
    n_links = max(n_chunks - 1, 0)
    link_f = lambda c: pp + c                 # noqa: E731
    link_b = lambda c: pp + n_links + c       # noqa: E731
    coll = lambda d: pp + 2 * n_links + d     # noqa: E731

    fid: Dict[Tuple[int, int], int] = {}
    bid: Dict[Tuple[int, int], int] = {}
    prev_on_row: Dict[int, int] = {}
    for phase, m, c in pipeline_schedule(schedule, pp, n_micro, v):
        d = c % pp
        tid = add(m_f[c] if phase == "F" else m_b[c], d)
        (fid if phase == "F" else bid)[(m, c)] = tid
        if d in prev_on_row:  # the row executes in schedule order
            edge(prev_on_row[d], tid)
        prev_on_row[d] = tid
    bwd_ids: List[List[int]] = [[] for _ in range(n_chunks)]
    for m in range(n_micro):
        for c in range(n_chunks):
            f = fid[(m, c)]
            b = bid[(m, c)]
            edge(f, b)  # remat consumes the stored chunk input
            if c < last:
                # activation hop to the next chunk's forward
                fc = add(bnd_micro[c], link_f(c))
                edge(f, fc)
                edge(fc, fid[(m, c + 1)])
                # gradient hop back from the next chunk's backward
                bc = add(bnd_micro[c], link_b(c))
                edge(bid[(m, c + 1)], bc)
                edge(bc, b)
            bwd_ids[c].append(b)
    for c in range(n_chunks):
        tail = bwd_ids[c][-1]
        if stage_sync[c] > 0:
            # grad allreduce waits for the chunk's ENTIRE backward flush —
            # every microbatch contributes to the weight grads
            sy = add(stage_sync[c], coll(c % pp))
            for b in bwd_ids[c]:
                edge(b, sy)
            tail = sy
        if stage_upd[c] > 0:
            up = add(stage_upd[c], c % pp)
            if tail == bwd_ids[c][-1]:  # no sync: update waits on all bwds
                for b in bwd_ids[c]:
                    edge(b, up)
            else:
                edge(tail, up)
    return simulate_taskgraph(
        np.asarray(costs), np.asarray(devs),
        2 * pp + 2 * n_links,
        np.asarray(esrc, dtype=np.int32),
        np.asarray(edst, dtype=np.int32))


# ------------------------------------------------------------------ strategies
def assignment_to_strategy(pcg: PCG, assignment: Dict[int, OpSharding],
                           states: Dict[int, str], dp: int, tp: int,
                           data_axis: str = "data",
                           model_axis: str = "model",
                           machine: Optional[TPUMachineModel] = None,
                           dcn: Tuple[int, int] = (1, 1)) -> Strategy:
    """Materialize the search result as weight/output shardings (the
    reference's convert_graph_to_operators + optimal_views). ``machine``
    enables sequence-schedule selection (ring vs alltoall) consistent with
    the simulator's costs; without it the ring schedule is kept. ``dcn``
    records each axis's DCN subfactor on a multi-host machine — the executor
    builds the mesh via build_hybrid_mesh so the DCN factor never splits an
    ICI ring."""
    if tp == 1:
        s = Strategy(mesh_shape=(dp,), axis_names=(data_axis,),
                     data_axis=data_axis)
        if dcn[0] > 1:
            s.hybrid = ((dp // dcn[0],), (dcn[0],))
    else:
        s = Strategy(mesh_shape=(dp, tp), axis_names=(data_axis, model_axis),
                     data_axis=data_axis)
        if dcn != (1, 1):
            s.hybrid = ((dp // dcn[0], tp // dcn[1]), tuple(dcn))
    view = MachineView(dim=(dp, tp) if tp > 1 else (dp,),
                       stride=(tp, 1) if tp > 1 else (1,))

    def state_spec(state: str, ndim: int):
        if state == "S" and ndim >= 2:
            return (data_axis,) + (None,) * (ndim - 2) + (model_axis,)
        if state == "Q" and ndim >= 3:
            return (data_axis, model_axis) + (None,) * (ndim - 2)
        if state == "H" and ndim >= 4:  # NCHW spatial height
            return (data_axis, None, model_axis) + (None,) * (ndim - 3)
        return (data_axis,) + (None,) * (ndim - 1)

    for node in pcg.topo_order():
        ns = s.for_node(node.guid)
        ns.view = view
        sh = assignment.get(node.guid)
        if sh is None:
            continue
        ndim = len(node.out_shapes[0]) if node.out_shapes else 0
        state = states.get(node.guid, "R")
        # state-preserving ops keep their sharded state pinned so XLA does
        # not round-trip through replicated layouts
        if sh.kind == "none" and state in ("S", "Q", "H") and ndim >= 2 \
                and tp > 1:
            ns.output_spec = state_spec(state, ndim)
            continue
        if sh.kind == "none" or sh.tp == 1:
            continue
        ot = node.op.op_type
        if ot == OperatorType.OP_LINEAR:
            if sh.kind == "col":
                ns.weight_specs = {"kernel": (None, model_axis),
                                   "bias": (model_axis,)}
                ns.output_spec = state_spec("S", ndim)
            elif sh.kind == "row":
                ns.weight_specs = {"kernel": (model_axis, None),
                                   "bias": (None,)}
                ns.output_spec = state_spec("R", ndim)
        elif ot == OperatorType.OP_MULTIHEAD_ATTENTION:
            if sh.kind == "heads":
                ns.weight_specs = {"wq": (None, model_axis, None),
                                   "wk": (None, model_axis, None),
                                   "wv": (None, model_axis, None),
                                   "wo": (model_axis, None, None),
                                   "bo": (None,)}
                ns.output_spec = state_spec("R", ndim)
            elif sh.kind == "ring":
                ns.extra["sequence_parallel_axis"] = model_axis
                if machine is not None:
                    # the SAME rule the simulator costed with
                    # (simulator.sequence_schedule): alltoall only when
                    # cheaper on comm AND its (s, s) score block fits HBM
                    from .simulator import sequence_schedule

                    in_shapes = [pcg.nodes[g].out_shapes[i]
                                 for g, i in node.inputs]
                    # same divisibility clamp as Simulator.op_cost, so the
                    # emitted schedule is chosen at the costed topology
                    tp_dcn = dcn[1] if dcn[1] > 0 and \
                        sh.tp % dcn[1] == 0 else 1
                    sched, _ = sequence_schedule(node, in_shapes, sh,
                                                 machine, tp_dcn=tp_dcn)
                    if sched != "ring":
                        ns.extra["sequence_parallel_mode"] = sched
                ns.output_spec = state_spec("Q", ndim)
        elif ot == OperatorType.OP_EMBEDDING:
            ns.weight_specs = {"weight": (model_axis, None)}
            ns.output_spec = state_spec("R", ndim)
        elif ot == OperatorType.OP_CONV2D:
            if sh.kind == "spatial":
                # weights replicated; activations height-sharded — XLA SPMD
                # inserts the halo exchange the cost model priced
                ns.output_spec = state_spec("H", ndim)
            else:  # out-channel "col" sharding
                ns.weight_specs = {"kernel": (None, None, None, model_axis),
                                   "bias": (model_axis,)}
        elif ot == OperatorType.OP_POOL2D and sh.kind == "spatial":
            ns.output_spec = state_spec("H", ndim)
        elif ot == OperatorType.OP_EXPERTS:
            # expert parallel: dim 0 is the expert dim, not batch — weights
            # and activations ride the model axis; XLA inserts the token
            # all-to-all at the dispatch/combine boundaries
            ns.weight_specs = {"kernel": (model_axis, None, None),
                               "bias": (model_axis, None)}
            ns.output_spec = (model_axis,) + (None,) * (ndim - 1)
    return s


# ----------------------------------------------------------- parallel-op nodes
_PARALLEL_OP_FOR_TRANSITION = {
    # (src_state, dst_state) -> (OperatorType, which tensor dim moves)
    ("S", "R"): (OperatorType.OP_COMBINE, -1),
    ("Q", "R"): (OperatorType.OP_COMBINE, 1),
    ("H", "R"): (OperatorType.OP_COMBINE, 2),
    ("R", "S"): (OperatorType.OP_REPARTITION, -1),
    ("R", "Q"): (OperatorType.OP_REPARTITION, 1),
    ("R", "H"): (OperatorType.OP_REPARTITION, 2),
    ("S", "Q"): (OperatorType.OP_ALLTOALL, 1),
    ("Q", "S"): (OperatorType.OP_ALLTOALL, -1),
    ("H", "S"): (OperatorType.OP_ALLTOALL, -1),
    ("S", "H"): (OperatorType.OP_ALLTOALL, 2),
    ("H", "Q"): (OperatorType.OP_ALLTOALL, 1),
    ("Q", "H"): (OperatorType.OP_ALLTOALL, 2),
}


def insert_parallel_ops(pcg: PCG, assignment: Dict[int, OpSharding],
                        states: Dict[int, str], strategy: Strategy,
                        sim: Simulator, dp: int, tp: int) -> int:
    """Materialize sharding-state transitions as first-class parallel-op
    nodes (reference: the search output's Repartition/Combine/Replicate/
    Reduction nodes, src/parallel_ops/). Each inserted node carries the
    transition's collective cost (visible in the DOT export) and an
    output_spec constraint that lowers to ``with_sharding_constraint`` —
    the same data movement, now explicit in the IR. Returns #inserted."""
    from ..ffconst import size_of_datatype
    from ..ops.base import op_class_for

    if tp <= 1:
        return 0
    model_axis = strategy.axis_names[-1]
    data_axis = strategy.data_axis
    inserted = 0

    # 1) Reduction nodes after partial-sum producers (reference: the
    # Reduction parallel op following a row-parallel Linear,
    # src/parallel_ops/reduction.cc; for head-parallel attention the wo
    # projection's contraction over sharded heads is the same pattern)
    for node in list(pcg.compute_nodes()):
        sh = assignment.get(node.guid)
        if sh is None or sh.kind not in ("row", "heads", "table") \
                or sh.tp <= 1:
            continue
        shape = node.out_shapes[0]
        nbytes = int(np.prod(shape)) * size_of_datatype(node.op.data_type)
        tp_dcn = sim.tp_dcn if tp % sim.tp_dcn == 0 else 1
        cost = sim.machine.hier_allreduce_time(
            nbytes // max(dp, 1), tp // tp_dcn, tp_dcn,
            nic_sharers=sim._nic_sharers(tp // tp_dcn))
        op = op_class_for(OperatorType.OP_REDUCTION)(
            f"reduction_{node.guid}",
            {"dim": 0, "degree": tp, "axes": (model_axis,),
             "comm_cost_us": round(cost * 1e6, 2)},
            node.op.data_type, num_inputs=1)
        consumers = [c for c in pcg.consumers(node.guid)]
        if not consumers:
            continue
        new = pcg.insert_node_on_edge(
            consumers[0],
            [slot for slot, (g, _i) in
             enumerate(pcg.nodes[consumers[0]].inputs)
             if g == node.guid][0], op)
        for c in consumers[1:]:
            cn = pcg.nodes[c]
            cn.inputs = [(new.guid, 0) if g == node.guid else (g, i)
                         for g, i in cn.inputs]
        ns = strategy.for_node(new.guid)
        prod_ns = strategy.node_strategies.get(node.guid)
        if prod_ns is not None:
            ns.view = prod_ns.view
            # the reduced-output constraint belongs to the Reduction node
            ns.output_spec = prod_ns.output_spec
            prod_ns.output_spec = None
        states[new.guid] = states.get(node.guid, "R")
        assignment[new.guid] = OpSharding(dp=dp, tp=1, kind="none")
        inserted += 1
    # group edges by (producer, out_idx, dst_state): one node serves all
    # consumers needing the same conversion
    reuse: Dict[Tuple[int, int, str], int] = {}
    for node in list(pcg.compute_nodes()):
        if getattr(node.op, "is_parallel_op", False):
            continue
        my_state = _in_state_of(node, assignment, states)
        for slot, (g, i) in enumerate(list(node.inputs)):
            p = pcg.nodes[g]
            if p.op.op_type in (OperatorType.OP_INPUT,
                                OperatorType.OP_WEIGHT):
                continue
            src_state = states.get(g, "R")
            if src_state == my_state:
                continue
            key = (g, i, my_state)
            if key in reuse:
                node.inputs[slot] = (reuse[key], 0)
                continue
            trans = _PARALLEL_OP_FOR_TRANSITION.get((src_state, my_state))
            if trans is None:
                continue
            op_type, dim = trans
            shape = p.out_shapes[i]
            nbytes = int(np.prod(shape)) * size_of_datatype(p.op.data_type)
            cost = sim.resharding_cost(nbytes, src_state, my_state, dp, tp)
            op = op_class_for(op_type)(
                f"{op_type.name.lower()}_{g}_{node.guid}",
                {"dim": dim % len(shape) if shape else 0, "degree": tp,
                 "axes": (model_axis,),
                 "comm_cost_us": round(cost * 1e6, 2)},
                p.op.data_type, num_inputs=1)
            new = pcg.insert_node_on_edge(node.guid, slot, op)
            ns = strategy.for_node(new.guid)
            ns.view = strategy.node_strategies[node.guid].view \
                if node.guid in strategy.node_strategies else ns.view
            ndim = len(shape)
            if my_state == "S" and ndim >= 2:
                ns.output_spec = (data_axis,) + (None,) * (ndim - 2) + (
                    model_axis,)
            elif my_state == "Q" and ndim >= 3:
                ns.output_spec = (data_axis, model_axis) + (None,) * (ndim - 2)
            else:
                ns.output_spec = (data_axis,) + (None,) * (ndim - 1)
            states[new.guid] = my_state
            assignment[new.guid] = OpSharding(dp=dp, tp=1, kind="none")
            reuse[key] = new.guid
            inserted += 1
    return inserted


def _in_state_of(node: PCGNode, assignment: Dict[int, OpSharding],
                 states: Dict[int, str]) -> str:
    """The input state the node's chosen option consumes."""
    from .simulator import op_in_state

    return op_in_state(assignment.get(node.guid), states.get(node.guid, "R"))


# ------------------------------------------------------------ best-first xfers
def apply_all_matches(pcg: PCG, xfers,
                      protected_guids: Sequence[int] = ()) -> Tuple[PCG, int]:
    """Greedily apply every match of always-beneficial rewrites (activation
    fusion strictly removes an op under the roofline model — the reference
    applies such monotonic rules as simplification passes, Graph::simplify,
    rather than spending base_optimize budget). Returns (graph, #applied)."""
    g = pcg
    applied = 0
    changed = True
    while changed and applied < len(pcg.nodes):
        changed = False
        for xfer in xfers:
            matches = xfer.find_matches(g)
            for match in matches:
                if any(guid in protected_guids for guid in match.values()):
                    continue
                try:
                    g = xfer.apply(g, match)
                except (ValueError, KeyError) as e:
                    # structurally inapplicable match (shape/attr mismatch
                    # only visible at apply time) — skip, but say so once
                    _warn_once(f"xfer-apply:{xfer.name}",
                               "xfer %s: match not applicable (%s)",
                               xfer.name, e)
                    continue
                applied += 1
                changed = True
                break  # re-match on the rewritten graph
            if changed:
                break
    return g, applied


def _segment_map(pcg: PCG, threshold: int) -> Dict[int, int]:
    """guid -> rewrite-segment index: the graph is split at bottleneck nodes
    into segments of at most ``threshold`` compute nodes where bottleneck
    spacing allows (reference: GraphSearchHelper::find_split_node,
    substitution.cc:2095 — graphs above base_optimize_threshold are split at
    a post-dominator and optimized piecewise)."""
    bns = set(pcg.bottlenecks())
    seg: Dict[int, int] = {}
    idx = 0
    count = 0
    for n in pcg.topo_order():
        seg[n.guid] = idx
        if n.op.op_type not in (OperatorType.OP_INPUT,
                                OperatorType.OP_WEIGHT):
            count += 1  # compute nodes only, matching compute_nodes()
        if count >= threshold and n.guid in bns:
            idx += 1
            count = 0
    return seg


def _dirty_after_rewrite(g2: PCG, touched: Sequence[int],
                         parent_sinks: Set[int]) -> Set[int]:
    """Guids whose DP rows must be recomputed after a rewrite: the touched
    (newly created) nodes plus every descendant — the rewritten segment and
    its resharding frontier. Clean nodes keep their ancestor cone untouched
    (dirty is closed under consumers), so their parent-graph DP rows are
    exact, not approximate. Sink-status flips seed the set too: a rule that
    drops an input can orphan a clean producer into a sink, changing its
    R pinning."""
    seeds = {t for t in touched if t in g2.nodes}
    new_sinks = {n.guid for n in g2.sinks()}
    for guid in new_sinks.symmetric_difference(parent_sinks):
        if guid in g2.nodes:
            seeds.add(guid)
    consumers: Dict[int, List[int]] = {}
    for n in g2.nodes.values():
        for pg, _ in n.inputs:
            consumers.setdefault(pg, []).append(n.guid)
    dirty: Set[int] = set()
    stack = list(seeds)
    while stack:
        x = stack.pop()
        if x in dirty:
            continue
        dirty.add(x)
        stack.extend(consumers.get(x, ()))
    return dirty


def best_first_optimize(pcg: PCG, sim: Simulator, dp: int, tp: int,
                        batch: int, xfers, budget: int, alpha: float,
                        space: Optional[SearchSpace] = None,
                        lam: float = 1.0,
                        protected_guids: Sequence[int] = (),
                        split_threshold: int = 0,
                        search_log=None, remat: str = "none"
                        ) -> Tuple[PCG, Dict[int, OpSharding],
                                   Dict[int, str], float]:
    """The reference's base_optimize (substitution.cc:2229-2306): best-first
    search over GraphXfer applications, each candidate costed by the DP, with
    alpha pruning and a budget on explored graphs. Above ``split_threshold``
    compute nodes, rewrites are confined to bottleneck-delimited segments —
    the reference's recursive split at find_split_node; matches spanning a
    split point are not explored (the reference optimizes the pieces
    separately). ``search_log`` (obs.SearchLog) records every explored
    rewrite candidate.

    Delta re-costing (ISSUE 2): every candidate carries its DP table, and a
    rewrite re-runs the DP only over ``GraphXfer.apply``'s touched guids
    plus their descendants (the resharding frontier) — clean rows are
    copied from the parent. Falls back to a full re-cost when no parent
    table is available. Under ``FLEXFLOW_TPU_SEARCH_SELFCHECK`` the delta
    result is shadowed by a full DP and asserted identical."""
    assignment, states, table = _dp_core(pcg, sim, dp, tp, space, lam,
                                         remat=remat)
    t = simulate_best(sim, pcg, assignment, states)
    best = (pcg, assignment, states, t)
    if not xfers:
        return best
    counter = itertools.count()
    heap = [(t, next(counter), pcg, table)]
    seen: Set[int] = {pcg.hash()}
    explored = 0
    while heap and explored < budget:
        cost, _, g, gtable = heapq.heappop(heap)
        if cost > best[3] * alpha:
            continue  # prune (reference: substitution.cc:2288)
        seg = (_segment_map(g, split_threshold) if split_threshold
               and len(g.compute_nodes()) > split_threshold else None)
        parent_sinks = {n.guid for n in g.sinks()}
        for xfer in xfers:
            for match in xfer.find_matches(g):
                if any(guid in protected_guids for guid in match.values()):
                    continue
                if seg is not None and len(
                        {seg.get(guid, -1) for guid in match.values()}) > 1:
                    continue  # spans a split point
                try:
                    g2, touched = xfer.apply(g, match, return_touched=True)
                except (ValueError, KeyError) as e:
                    _warn_once(f"xfer-apply:{xfer.name}",
                               "xfer %s: match not applicable (%s)",
                               xfer.name, e)
                    continue
                h = g2.hash()
                if h in seen:
                    continue
                seen.add(h)
                explored += 1
                dirty = _dirty_after_rewrite(g2, touched, parent_sinks)
                a2, s2, table2 = _dp_core(g2, sim, dp, tp, space, lam,
                                          prior=gtable, dirty=dirty,
                                          remat=remat)
                t2 = simulate_best(sim, g2, a2, s2)
                if selfcheck_enabled():
                    fa, fs, _ft = _dp_core(g2, sim, dp, tp, space, lam,
                                           remat=remat)
                    if (fa, fs) != (a2, s2):
                        raise AssertionError(
                            f"delta-cost selfcheck: incremental DP after "
                            f"xfer {xfer.name} diverged from the full "
                            f"re-cost (dirty={len(dirty)}/"
                            f"{len(g2.compute_nodes())} nodes)")
                _log.info("xfer %s: %.3f ms -> %.3f ms", xfer.name,
                          best[3] * 1e3, t2 * 1e3)
                if search_log is not None:
                    search_log.log(event="xfer", xfer=xfer.name, dp=dp,
                                   tp=tp, cost_ms=round(t2 * 1e3, 4),
                                   accepted=bool(t2 < best[3]),
                                   best_ms=round(min(t2, best[3]) * 1e3, 4),
                                   recost_nodes=len(dirty),
                                   total_nodes=len(g2.compute_nodes()))
                if t2 < best[3]:
                    best = (g2, a2, s2, t2)
                if t2 < best[3] * alpha:
                    heapq.heappush(heap, (t2, next(counter), g2, table2))
                if explored >= budget:
                    break
            if explored >= budget:
                break
    return best


# ----------------------------------------------------------- ranked top-K
# fallback-chain length the search persists (winner + K-1 runners-up); the
# cascade rarely needs more than a couple before the dp+full-remat last
# resort, and each extra entry costs one strategy JSON serialization
RANKED_TOP_K = 5


def _build_ranked(best: SearchResult,
                  spmd_pool: Dict[Tuple, Tuple[bool, SearchResult]],
                  pipe_cands: List[RankedCandidate],
                  mem_budget: Optional[int], k: int = RANKED_TOP_K
                  ) -> List[RankedCandidate]:
    """Collapse the deduped candidate pool into the ranked fallback chain:
    one best entry per (mesh, dcn, remat | pipeline grid), runners-up
    ordered feasible-first by simulated time (ties broken on the plan key,
    so the ranking is deterministic). ``spmd_pool`` is maintained
    incrementally by the search (one retained SearchResult per plan key),
    so a long memory search never accumulates per-λ graph copies."""
    entries: Dict[Tuple, Tuple[bool, float, int, Optional[SearchResult],
                               Optional[RankedCandidate]]] = {}

    def consider(key, feas, t, mem, res, pre):
        cur = entries.get(key)
        if cur is None or (feas and not cur[0]) or \
                (feas == cur[0] and t < cur[1]):
            entries[key] = (feas, t, mem, res, pre)

    for (mesh, dcn, remat, pods), (feas, r) in spmd_pool.items():
        consider((mesh, dcn, remat, pods, None), feas, r.sim_time,
                 r.sim_memory, r, None)
    for c in pipe_cands:
        # distinct schedules of one (grid, remat) are distinct fallback
        # candidates: a 1f1b plan that fails can degrade to its gpipe twin
        consider((tuple(c.mesh_shape), tuple(c.dcn), c.remat, c.pods,
                  tuple(c.pipeline), c.schedule, c.virtual_stages),
                 c.feasible, c.sim_time, c.sim_memory, None, c)

    win_pods = getattr(best, "pod_plan", None)
    win_pipe = (tuple(best.strategy.pipeline)
                if getattr(best.strategy, "pipeline", None) else None)
    win_sched = (getattr(best.strategy, "schedule", "") or "gpipe") \
        if win_pipe else ""
    win_v = int(getattr(best.strategy, "virtual_stages", 1) or 1) \
        if win_pipe else 1
    if win_pipe:
        win_key: Tuple = (tuple(best.mesh_shape), tuple(best.dcn),
                          best.remat, win_pods, win_pipe, win_sched,
                          win_v)
    else:
        win_key = (tuple(best.mesh_shape), tuple(best.dcn), best.remat,
                   win_pods, None)
    ranked = [RankedCandidate(
        mesh_shape=tuple(best.mesh_shape), dcn=tuple(best.dcn),
        remat=best.remat, sim_time=best.sim_time, sim_memory=best.sim_memory,
        feasible=bool(mem_budget is None or best.sim_memory <= mem_budget),
        pipeline=win_pipe, schedule=win_sched, virtual_stages=win_v,
        pods=win_pods)]
    others = sorted(((key, v) for key, v in entries.items()
                     if key != win_key),
                    key=lambda kv: (not kv[1][0], kv[1][1], repr(kv[0])))
    for key, (feas, t, mem, res, pre) in others[:max(k - 1, 0)]:
        if pre is not None:
            ranked.append(pre)
            continue
        sjson = None
        if res is not None and res.pcg is not None:
            sjson = res.strategy.to_json(res.pcg)
        ranked.append(RankedCandidate(
            mesh_shape=key[0], dcn=key[1], remat=key[2], pods=key[3],
            sim_time=t, sim_memory=mem, feasible=feas,
            strategy_json=sjson))
    return ranked


# ------------------------------------------------------------------ top level
def unity_search(pcg: PCG, config, n_dev: int,
                 machine: Optional[TPUMachineModel] = None,
                 return_result: bool = False, calibrate: bool = False,
                 protected_guids: Sequence[int] = (),
                 insert_ir_nodes: bool = True,
                 sim: Optional[Simulator] = None):
    """Top-level search (reference: graph_optimize_task, graph.cc:2047).

    Enumerates mesh factorizations x graph rewrites, runs the {R,S,Q} DP for
    each, applies alpha pruning, then the memory-λ binary search
    (graph.cc:2060-2133) when ``--memory-search`` is on. The λ search is a
    *remix* under the delta-cost engine: the λ=1.0 sweep populates the
    Simulator's memoized per-node (time, mem) tables, and each subsequent λ
    iteration re-runs only the DP mix ``lam*time + (1-lam)*mem`` over
    cached entries — zero new ``op_cost`` calls (λ is not part of any cache
    key, so every lookup hits). When ``calibrate``
    the per-op cost model is first grounded by on-device measurement
    (reference: simulator.cc:489). The best strategy's sharding transitions
    are materialized as parallel-op IR nodes in ``pcg`` (mutated in place).
    Returns a Strategy (or the full SearchResult)."""
    if machine is None:
        if config.machine_model_version == 1 and config.machine_model_file:
            machine = TPUMachineModel.from_file(config.machine_model_file,
                                               n_dev)
        else:
            machine = TPUMachineModel.detect(n_dev)
        # --pods / --dcn-gbps multi-pod overrides (docs/multipod.md);
        # an explicitly passed machine is already the caller's topology
        machine.apply_pod_overrides(
            int(getattr(config, "num_pods", 0) or 0),
            float(getattr(config, "dcn_gbps", 0.0) or 0.0))
    if sim is None:
        from .calibration import dtype_label

        # --collective-overlap on prices the per-block hidden sync
        # fraction (simulator.simulate's block model); the legacy
        # --overlap knob keeps its own coarse hiding model untouched
        sim = Simulator(machine,
                        bool(config.search_overlap_backward_update),
                        calibration_dir=getattr(config, "calibration_dir",
                                                "") or None,
                        dtype_label=dtype_label(config))
        sim.block_overlap = (getattr(config, "collective_overlap", "off")
                             or "off") == "on"
    # the simulator must price full-remat blocks at the SAME size the
    # Executor will cut them (execution/remat.py's one-segmentation rule)
    sim.remat_segment_size = int(
        getattr(config, "remat_segment_size", 8) or 8)
    if calibrate:
        n_measured = sim.calibrate_from_pcg(pcg)
        _log.info("calibrated %d op shapes on device", n_measured)
    # --calibrate-from-trace (ISSUE 8, docs/calibration.md): replay a
    # --profile-ops JSONL into the per-key calibration BEFORE ranking, so
    # the search prices candidates with the measured ruler
    trace_path = getattr(config, "calibrate_from_trace", "") or ""
    if trace_path:
        from .calibration import calibrate_sim_from_trace

        rep = calibrate_sim_from_trace(sim, pcg, trace_path)
        _log.info("calibrated from trace %s: %d keys matched, %d updated",
                  trace_path, rep["matched"], rep["updated"])

    xfers = _load_xfers(config)
    # monotonic rewrites (activation fusion) apply greedily up front — one
    # pass instead of budgeted re-search per factorization; the best-first
    # loop keeps the cost-gated rules (--substitution-json)
    from .substitution import builtin_xfers

    fusion_names = {x.name for x in builtin_xfers()}
    greedy = [x for x in xfers if x.name in fusion_names]
    xfers = [x for x in xfers if x.name not in fusion_names]
    base_pcg, n_fused = apply_all_matches(pcg, greedy, protected_guids)
    # the Unity graph search explores the full parameter/attribute space like
    # the reference's (the enable_* flags gate only MCMC, linear.cc:727);
    # sequence parallelism is a TPU-native extension with its own opt-out
    space = SearchSpace.full()
    space.sequence = getattr(config, "enable_sequence_parallel", True)
    batch = config.batch_size
    alpha = config.search_alpha
    budget = config.search_budget if config.search_budget > 0 else 64

    # rematerialization axis (ISSUE 3): `--remat` forces one level;
    # otherwise the memory search explores every level — priced from the
    # FIRST (λ=1.0) sweep so the λ binary search below stays a pure remix
    # (the remat-extended tables are fully populated before any λ
    # iteration; the zero-new-misses counter contract of ISSUE 2 holds).
    # Without memory pressure remat only adds recompute time, so the
    # runtime-only search keeps the single `none` level.
    from ..execution.remat import REMAT_LEVELS

    forced_remat = (getattr(config, "remat", "") or "").strip()
    if forced_remat and forced_remat not in REMAT_LEVELS:
        raise ValueError(
            f"--remat {forced_remat!r} not in {REMAT_LEVELS}")
    if forced_remat:
        remat_levels: Tuple[str, ...] = (forced_remat,)
    elif config.perform_memory_search:
        remat_levels = REMAT_LEVELS
    else:
        remat_levels = ("none",)

    hbm_budget = machine.hbm_capacity
    if getattr(config, "device_memory_mb", 0):
        hbm_budget = config.device_memory_mb * 2 ** 20  # -ll:fsize analog

    # per-iteration search telemetry: JSONL when --search-log is set, tracer
    # events when tracing is on (reference analog: the exported-strategy
    # workflow, but for the search's decision sequence itself)
    from ..obs import SearchLog, get_tracer

    tracer = get_tracer()
    slog = SearchLog(getattr(config, "search_log_file", "") or None,
                     kind="unity")

    # deduped candidate pool for the ranked fallback chain (ISSUE 5): one
    # retained SearchResult per (mesh, dcn, remat) — folding each sweep in
    # incrementally keeps retention O(distinct plans), not O(λ iterations)
    ranked_pool: Dict[Tuple, Tuple[bool, SearchResult]] = {}
    rank_budget = hbm_budget if config.perform_memory_search else None
    pipe_cands: List[RankedCandidate] = []

    # ShardLint candidate pruning (ISSUE 7): statically ill-formed
    # candidates (FF001 partial-sum defects, FF006 indivisible shardings)
    # are rejected after the DP optimizer assigns shardings but BEFORE
    # the final simulate/memory pricing and the ranked pool — a broken
    # rewrite/substitution rule can never win the search or ride a
    # ranked fallback chain. Every lambda's assignment is analyzed (the
    # trade-off changes the per-node shardings), but a pruned PLAN is
    # counted/logged once — pruned_static reports distinct plans, like
    # the ranked pool's dedup.
    static_on = (getattr(config, "static_analysis", "on") or "on") != "off"
    if static_on:
        from ..analysis import analyze_candidate
    pruned_static = [0]
    pruned_keys: set = set()

    # hierarchical multi-pod decomposition (ISSUE 15, docs/multipod.md):
    # when the machine spans pods and the scale warrants it (or
    # --hierarchical-search on), the SPMD sweep runs the two-level
    # DCN x ICI search instead of the flat enumeration; the pod-local
    # sub-solution memo and its counters live on the solver
    from . import multipod

    use_hier = multipod.hierarchical_enabled(config, machine, n_dev)
    hier_solver = multipod.ICISubSolver(sim) if use_hier else None
    hier_stats: Dict = {}

    def pool_consider(r: SearchResult) -> None:
        feas = rank_budget is None or r.sim_memory <= rank_budget
        key = (tuple(r.mesh_shape), tuple(r.dcn), r.remat,
               getattr(r, "pod_plan", None))
        cur = ranked_pool.get(key)
        if cur is None or (feas and not cur[0]) or \
                (feas == cur[0] and r.sim_time < cur[1].sim_time):
            ranked_pool[key] = (feas, r)

    def search_all(lam: float, mem_budget: Optional[int] = None,
                   hierarchical: Optional[bool] = None
                   ) -> Optional[SearchResult]:
        """One sweep over factorizations at a fixed λ. With a memory budget,
        the best FEASIBLE candidate by time wins (falling back to minimum
        memory — reference: is_valid_strategy, graph.cc:1984-2032). On a
        multi-pod machine the sweep dispatches to the two-level
        hierarchical decomposition (multipod.hierarchical_sweep)."""
        if hierarchical is None:
            hierarchical = use_hier
        if hierarchical:
            return multipod.hierarchical_sweep(
                base_pcg, sim, machine, n_dev, batch, lam, mem_budget,
                space, remat_levels, xfers, budget, alpha,
                protected_guids,
                getattr(config, "base_optimize_threshold", 0), slog,
                hier_solver, static_on, pool_consider, hier_stats)
        results: List[SearchResult] = []
        # per-sweep log state: `accepted` must mirror THIS sweep's actual
        # selection rule (feasibility included) — a global best across λ
        # sweeps would mislabel a sweep's real winner as rejected
        sweep_best = [float("inf")]
        # restore under try/finally: an exception mid-sweep (a raising
        # cost model, a broken rewrite) must not leak a candidate's DCN
        # topology into a warm shared simulator (ISSUE 15 satellite)
        saved_topo = (sim.dp_dcn, sim.tp_dcn)
        try:
            for dp, tp in factorizations(n_dev):
                if batch % dp != 0:
                    continue
                for dp_dcn, tp_dcn in dcn_placements(dp, tp,
                                                     machine.num_hosts):
                    sim.set_axis_topology(dp_dcn, tp_dcn)
                    for remat in remat_levels:
                        g, a, s, t = best_first_optimize(
                            base_pcg, sim, dp, tp, batch, xfers,
                            budget=max(budget // 4, 4), alpha=alpha,
                            space=space,
                            lam=lam, protected_guids=protected_guids,
                            split_threshold=getattr(
                                config, "base_optimize_threshold", 0),
                            search_log=slog, remat=remat)
                        strat = assignment_to_strategy(
                            g, a, s, dp, tp, machine=machine,
                            dcn=(dp_dcn, tp_dcn))
                        strat.remat = remat
                        if static_on:
                            rep = analyze_candidate(g, strat)
                            if rep.errors:
                                key = (dp, tp, dp_dcn, tp_dcn, remat)
                                if key not in pruned_keys:
                                    pruned_keys.add(key)
                                    pruned_static[0] += 1
                                    slog.log(
                                        event="pruned_static", dp=dp,
                                        tp=tp,
                                        dcn=[dp_dcn, tp_dcn],
                                        lam=round(lam, 4), remat=remat,
                                        rules=rep.rules_fired(),
                                        first=rep.errors[0]
                                        .format_line()[:300])
                                continue
                        _, mem = sim.simulate(g, a, s)
                        _log.info(
                            "mesh dp=%d tp=%d dcn=(%d,%d) lam=%.2f "
                            "remat=%s -> %.3f ms, %.1f MiB/chip", dp, tp,
                            dp_dcn, tp_dcn,
                            lam, remat, t * 1e3, mem / 2 ** 20)
                        feasible = mem_budget is None or mem <= mem_budget
                        accepted = feasible and t < sweep_best[0]
                        if accepted:
                            sweep_best[0] = t
                        slog.log(event="candidate", dp=dp, tp=tp,
                                 dcn=[dp_dcn, tp_dcn], lam=round(lam, 4),
                                 remat=remat,
                                 cost_ms=round(t * 1e3, 4),
                                 mem_mib=round(mem / 2 ** 20, 1),
                                 feasible=bool(feasible),
                                 accepted=bool(accepted),
                                 best_ms=round(
                                     (sweep_best[0]
                                      if sweep_best[0] != float("inf")
                                      else t) * 1e3, 4))
                        results.append(SearchResult(
                            strategy=strat,
                            assignment=a, sim_time=t, sim_memory=mem,
                            mesh_shape=(dp, tp), pcg=g, states=s,
                            dcn=(dp_dcn, tp_dcn), remat=remat))
        finally:
            sim.set_axis_topology(*saved_topo)
        for r in results:
            pool_consider(r)
        if not results:
            return None
        if mem_budget is not None:
            ok = [r for r in results if r.sim_memory <= mem_budget]
            chosen = (min(ok, key=lambda r: r.sim_time) if ok
                      else min(results, key=lambda r: r.sim_memory))
        else:
            chosen = min(results, key=lambda r: r.sim_time)
        slog.log(event="sweep_result", lam=round(lam, 4),
                 mesh=list(chosen.mesh_shape), remat=chosen.remat,
                 cost_ms=round(chosen.sim_time * 1e3, 4),
                 mem_mib=round(chosen.sim_memory / 2 ** 20, 1),
                 feasible=bool(mem_budget is None
                               or chosen.sim_memory <= mem_budget),
                 # delta-cost engine counters: a λ remix sweep shows hits
                 # growing while misses stay flat (zero new op_cost work)
                 cost_cache_hits=sim.cost_cache_hits,
                 cost_cache_misses=sim.cost_cache_misses)
        return chosen

    t_search0 = time.perf_counter()
    # snapshot the cache counters: the reported stats must be THIS search's
    # deltas, not the Simulator's lifetime totals (a shared sim arrives
    # pre-warmed by calibration or baseline costing — bench.py does both)
    cache0 = (sim.cost_cache_hits, sim.cost_cache_misses,
              sim.table_hits, sim.table_misses)
    with _log.scope("unity_search n_dev=%d" % n_dev), \
            tracer.span("search", n_dev=n_dev):
        best = search_all(lam=1.0)
        if use_hier and selfcheck_enabled() and \
                n_dev <= multipod.SELFCHECK_MAX_DEV:
            # two-level vs flat equivalence gate (docs/multipod.md): on a
            # mesh small enough to enumerate both ways, the hierarchical
            # winner must be the flat search_all winner. The shadow flat
            # sweep must VERIFY, not perturb: snapshot/restore the ranked
            # pool, prune dedup and event counters so selfcheck-on runs
            # rank and report identically to selfcheck-off runs
            pool_snap = dict(ranked_pool)
            counts_snap = dict(slog.counts)
            pruned_snap = (pruned_static[0], set(pruned_keys))
            try:
                flat_best = search_all(lam=1.0, hierarchical=False)
            finally:
                ranked_pool.clear()
                ranked_pool.update(pool_snap)
                slog.counts.clear()
                slog.counts.update(counts_snap)
                pruned_static[0] = pruned_snap[0]
                pruned_keys.clear()
                pruned_keys.update(pruned_snap[1])
            multipod.assert_selfcheck_matches_flat(best, flat_best)
        # memory-aware λ binary search (reference: graph.cc:2060-2133):
        # find the largest λ (most runtime-weighted) whose best strategy
        # still fits per-chip HBM
        if best is not None and config.perform_memory_search and \
                best.sim_memory > hbm_budget:
            lo, hi = 0.0, 1.0
            feasible = None
            for _ in range(6):
                mid = (lo + hi) / 2
                cand = search_all(lam=mid, mem_budget=hbm_budget)
                if cand is not None and cand.sim_memory <= hbm_budget:
                    feasible, lo = cand, mid
                else:
                    hi = mid
            if feasible is None:
                cand = search_all(lam=0.0, mem_budget=hbm_budget)
                if cand is not None and cand.sim_memory <= hbm_budget:
                    feasible = cand
            if feasible is not None:
                best = feasible

        # GPipe pipeline candidate (beyond the reference, which only
        # reserves OP_PIPELINE): the same op-cost model prices (pp, dp)
        # GPipe grids — per-stage weight placement removes the full-model
        # gradient allreduce, so pipeline wins for weight-heavy graphs
        if best is not None and n_dev >= 2 and \
                getattr(config, "enable_pipeline_parallel", True) and \
                batch % n_dev == 0 and \
                pipeline_microbatch_safe(base_pcg, batch):
            # batch % n_dev: the companion eval/predict strategy is DP
            # over all n_dev devices — same guard search_all applies
            n_nodes = len(base_pcg.compute_nodes())
            # stage remat is leveled too (PipelineTrainer runs the same
            # policy machinery): a forced level wins; the memory search
            # explores all levels; otherwise keep the classic GPipe full
            # remat the trainer always ran pre-leveling
            pipe_levels = ((forced_remat,) if forced_remat
                           else remat_levels
                           if config.perform_memory_search else ("full",))
            # the pipeline SCHEDULE is a searched axis too (ISSUE 10):
            # gpipe/1f1b sweep always; interleaved (v=2 virtual chunks per
            # device) when the graph has enough nodes to cut pp*v chunks.
            # --schedule forces one schedule, like --remat forces a level.
            forced_sched = (getattr(config, "schedule", "") or "").strip()
            forced_v = int(getattr(config, "pipeline_virtual_stages", 0)
                           or 0)
            # pod-aligned grids on a hierarchical multi-pod machine (pods
            # as pipeline stages — the DCN-level pipeline axis, with the
            # schedule per cut searched below); the classic (2, 4, 8)
            # sweep otherwise
            pipe_pods = ((machine.pods, "pipeline", 1)
                         if use_hier else None)
            for pp in multipod.pipeline_grids(n_dev, machine, use_hier):
                if n_dev % pp != 0 or pp > min(n_nodes, n_dev) or pp < 2:
                    continue
                pdp = n_dev // pp
                micro = next((m for m in (2 * pp, pp, 2)
                              if batch % m == 0 and
                              (batch // m) % max(pdp, 1) == 0), None)
                if micro is None:
                    continue
                if forced_sched:
                    # v only applies to interleaved: a stray
                    # --virtual-stages with a forced 1f1b/gpipe must not
                    # leak into the winner (preflight would reject it)
                    v = (forced_v or 2) \
                        if forced_sched == "interleaved" else 1
                    pipe_scheds = [(forced_sched, v)] if (
                        pp * v <= n_nodes and
                        (forced_sched != "interleaved"
                         or micro % pp == 0)) else []
                else:
                    pipe_scheds = [("gpipe", 1), ("1f1b", 1)]
                    # interleaved needs pp*v chunks to cut and microbatch
                    # rounds of pp (preflight names the same constraints)
                    if 2 * pp <= n_nodes and micro % pp == 0:
                        pipe_scheds.append(("interleaved", 2))
                for lv in pipe_levels:
                    for sched, sv in pipe_scheds:
                        t_pipe, m_pipe = simulate_pipeline(
                            sim, base_pcg, pp, pdp, micro, remat=lv,
                            schedule=sched, v=sv)
                        _log.info(
                            "pipeline pp=%d dp=%d m=%d remat=%s "
                            "schedule=%s v=%d -> %.3f ms, %.1f MiB",
                            pp, pdp, micro, lv, sched, sv,
                            t_pipe * 1e3, m_pipe / 2 ** 20)
                        # accepted must mirror the ACTUAL decision below,
                        # memory budget included, or replaying the log
                        # reconstructs a different search than the one
                        # that ran. Ties on time (1f1b's makespan equals
                        # gpipe's under uniform stages — the bubble
                        # fraction is the same (S-1)/(M+S-1); memory is
                        # its win) break toward LOWER memory; an exact
                        # tie on both (the swept n_micro == pp regime,
                        # where in-flight counts coincide) still prefers
                        # the non-gpipe schedule — 1f1b DOMINATES gpipe
                        # (never worse, strictly less in-flight memory
                        # once the fit loop re-derives n_micro = 2*pp
                        # for a real batch), so the tie is not a toss-up.
                        feas = (not config.perform_memory_search
                                or m_pipe <= hbm_budget)
                        is_pipe_best = bool(
                            getattr(best.strategy, "pipeline", None))
                        best_sched = (getattr(best.strategy, "schedule",
                                              "") or "gpipe")
                        pipe_ok = feas and (
                            t_pipe < best.sim_time * (1 - 1e-9)
                            or (is_pipe_best
                                and t_pipe <= best.sim_time * (1 + 1e-9)
                                and (m_pipe < best.sim_memory
                                     or (m_pipe <= best.sim_memory
                                         and best_sched == "gpipe"
                                         and sched != "gpipe"))))
                        # mesh recorded as the winner convention
                        # (n_dev, 1) so an accepted grid's entry dedupes
                        # against its own SearchResult in the ranking
                        pipe_cands.append(RankedCandidate(
                            mesh_shape=(n_dev, 1), remat=lv,
                            sim_time=t_pipe, sim_memory=m_pipe,
                            feasible=bool(feas),
                            pipeline=(pp, pdp, micro),
                            schedule=sched, virtual_stages=sv,
                            pods=pipe_pods))
                        slog.log(event="pipeline_candidate", pp=pp,
                                 dp=pdp, n_micro=micro, remat=lv,
                                 schedule=sched, virtual_stages=sv,
                                 cost_ms=round(t_pipe * 1e3, 4),
                                 mem_mib=round(m_pipe / 2 ** 20, 1),
                                 accepted=bool(pipe_ok),
                                 best_ms=round((t_pipe if pipe_ok
                                                else best.sim_time)
                                               * 1e3, 4))
                        if pipe_ok:
                            from ..parallel.strategy import \
                                data_parallel_strategy

                            strat = data_parallel_strategy(pcg, n_dev)
                            strat.pipeline = (pp, pdp, micro)
                            strat.schedule = sched
                            strat.virtual_stages = sv
                            strat.remat = lv
                            strat.pods = pipe_pods
                            best = SearchResult(
                                strategy=strat, assignment={},
                                sim_time=t_pipe, sim_memory=m_pipe,
                                mesh_shape=(n_dev, 1), pcg=None,
                                states=None, remat=lv,
                                pod_plan=pipe_pods)

    # delta-cost engine telemetry: wall time, throughput and cache counters
    # land on the SearchResult (bench.py's search_wall_s metric) and in the
    # final SearchLog record
    search_wall_s = time.perf_counter() - t_search0
    candidates = sum(slog.counts.get(k, 0) for k in
                     ("candidate", "xfer", "pipeline_candidate",
                      "dcn_candidate"))
    d_hits = sim.cost_cache_hits - cache0[0]
    d_misses = sim.cost_cache_misses - cache0[1]
    cache_stats = {
        "cost_cache_hits": d_hits,
        "cost_cache_misses": d_misses,
        "cost_cache_hit_rate": round(d_hits / (d_hits + d_misses), 4)
        if d_hits + d_misses else 0.0,
        "table_hits": sim.table_hits - cache0[2],
        "table_misses": sim.table_misses - cache0[3],
    }
    if best is not None:
        best.search_wall_s = search_wall_s
        best.candidates = candidates
        best.cache_stats = cache_stats
        best.pruned_static = pruned_static[0]
        if use_hier:
            if hier_solver is not None:
                pruned_static[0] += hier_solver.pruned_static
                best.pruned_static = pruned_static[0]
            best.multipod_stats = dict(hier_stats)
        # ranked fallback chain (ISSUE 5): persisted on the result AND in
        # the search log, so the compile-time cascade (and a post-mortem of
        # one) can replay which plans were next in line
        best.ranked = _build_ranked(best, ranked_pool, pipe_cands,
                                    rank_budget)
        slog.log(event="ranked", candidates=[
            {"rank": i, "mesh": list(c.mesh_shape), "dcn": list(c.dcn),
             "remat": c.remat,
             "pipeline": list(c.pipeline) if c.pipeline else None,
             "schedule": c.schedule or None,
             "virtual_stages": c.virtual_stages,
             "pods": list(c.pods) if c.pods else None,
             "cost_ms": round(c.sim_time * 1e3, 4),
             "mem_mib": round(c.sim_memory / 2 ** 20, 1),
             "feasible": bool(c.feasible)}
            for i, c in enumerate(best.ranked)])
        slog.log(event="result", cost_ms=round(best.sim_time * 1e3, 4),
                 mem_mib=round(best.sim_memory / 2 ** 20, 1),
                 mesh=list(best.mesh_shape), remat=best.remat,
                 pipeline=(list(best.strategy.pipeline)
                           if getattr(best.strategy, "pipeline", None)
                           else None),
                 schedule=(getattr(best.strategy, "schedule", "") or None),
                 virtual_stages=int(
                     getattr(best.strategy, "virtual_stages", 1) or 1),
                 pods=(list(best.pod_plan) if best.pod_plan else None),
                 search_wall_s=round(search_wall_s, 4),
                 candidates=candidates,
                 candidates_per_s=round(candidates / search_wall_s, 2)
                 if search_wall_s > 0 else None,
                 pruned_static=pruned_static[0],
                 **(dict(best.multipod_stats)
                    if best.multipod_stats else {}),
                 **cache_stats)
    slog.close()
    if best is None:
        from ..parallel.strategy import data_parallel_strategy

        return data_parallel_strategy(pcg, n_dev)

    # adopt the rewritten graph + materialize transitions as parallel-op nodes
    if best.pcg is not None and best.pcg is not pcg:
        pcg.nodes = best.pcg.nodes
        pcg._order = best.pcg._order
    if insert_ir_nodes and best.states is not None:
        dp, tp = best.mesh_shape
        try:
            # annotate at the winner's topology; restore even when an
            # insertion fails so a warm shared simulator stays clean
            sim.set_axis_topology(*best.dcn)
            insert_parallel_ops(pcg, best.assignment, best.states,
                                best.strategy, sim, dp, tp)
        finally:
            sim.set_axis_topology(1, 1)
    best.sim = sim
    return (best if return_result else best.strategy)


def _load_xfers(config):
    from .substitution import builtin_xfers, load_substitution_json

    xfers = list(builtin_xfers())
    if config.substitution_json_path:
        xfers.extend(load_substitution_json(config.substitution_json_path))
    return xfers


def search_all(pcg: PCG, config, n_dev: int, objective: str = "training",
               **kwargs):
    """Objective-dispatching search façade (ISSUE 6): the training
    objective runs the classic Unity step-time search (``unity_search``);
    ``objective="serving"`` optimizes latency-bounded throughput for the
    DECODE graph instead — tokens/sec subject to simulated p99 <=
    ``--slo-p99-ms`` — via ``serving.search.serving_search`` (which
    returns a ServingPlan rather than a Strategy; the plan's
    ``to_strategy`` materializes executor shardings). Both objectives
    share the Simulator's delta-cost caches when a warm ``sim=`` is
    passed."""
    if objective == "serving":
        from ..serving.search import serving_search

        return serving_search(pcg, config, n_dev, **kwargs)
    if objective != "training":
        raise ValueError(
            f"unknown search objective {objective!r}: "
            "expected 'training' or 'serving'")
    return unity_search(pcg, config, n_dev, **kwargs)


# ---------------------------------------------------------------- legacy MCMC
def mcmc_optimize(pcg: PCG, config, n_dev: int,
                  machine: Optional[TPUMachineModel] = None,
                  iterations: int = 500, temperature: float = 1e-4,
                  seed: int = 0) -> Strategy:
    """Legacy simulated-annealing search over per-op shardings
    (reference: FFModel::mcmc_optimize, model.cc:3285 — random per-op
    ParallelConfig rewrites accepted by Metropolis criterion). Honors
    enable_parameter_parallel / enable_attribute_parallel exactly like the
    reference's get_random_parallel_config (linear.cc:727)."""
    machine = machine or TPUMachineModel.detect(n_dev)
    sim = Simulator(machine)
    rng = random.Random(seed)
    batch = config.batch_size
    space = SearchSpace.from_config(config)

    facts = [f for f in factorizations(n_dev) if batch % f[0] == 0]
    dp, tp = facts[0]
    nodes = pcg.compute_nodes()

    def random_choice(node):
        in_shapes = [pcg.nodes[g].out_shapes[i] for g, i in node.inputs]
        valid = node_options(node, tp, in_shapes, space)
        return rng.choice(valid or [("none", "R", "R")])

    current = {n.guid: OpSharding(dp=dp, tp=tp if k != "none" else 1, kind=k)
               for n in nodes for k, _, _ in [random_choice(n)]}
    # candidates are costed by the SAME engine as unity_search
    # (simulate_best -> native event-driven makespan when available), so
    # the two search modes rank any candidate identically (VERDICT r4
    # weak #5; reference: one simulator prices everything, simulator.cc:815)
    cur_t = simulate_best(sim, pcg, current, {})
    # best carries ITS OWN factorization: the restart below re-rolls
    # (dp, tp), and the final strategy must be built around the mesh the
    # best assignment was actually found under
    best, best_t, best_fact = dict(current), cur_t, (dp, tp)
    from ..obs import SearchLog

    slog = SearchLog(getattr(config, "search_log_file", "") or None,
                     kind="mcmc")
    for it in range(iterations):
        # occasionally rewrite the mesh factorization (reference: restart)
        if it % 100 == 99 and len(facts) > 1:
            dp, tp = rng.choice(facts)
            current = {n.guid: OpSharding(
                dp=dp, tp=tp if k != "none" else 1, kind=k)
                for n in nodes for k, _, _ in [random_choice(n)]}
            cur_t = simulate_best(sim, pcg, current, {})
            if cur_t < best_t:
                best, best_t, best_fact = dict(current), cur_t, (dp, tp)
        node = rng.choice(nodes)
        kind, _, _ = random_choice(node)
        cand = dict(current)
        cand[node.guid] = OpSharding(dp=dp, tp=tp if kind != "none" else 1,
                                     kind=kind)
        t = simulate_best(sim, pcg, cand, {})
        accepted = (t < cur_t
                    or rng.random() < math.exp(-(t - cur_t) / temperature))
        slog.log(event="mcmc", cost_ms=round(t * 1e3, 4),
                 accepted=bool(accepted), temperature=temperature,
                 dp=dp, tp=tp, best_ms=round(min(t, best_t) * 1e3, 4))
        if accepted:
            current, cur_t = cand, t
            if t < best_t:
                best, best_t, best_fact = dict(cand), t, (dp, tp)
    slog.log(event="result", cost_ms=round(best_t * 1e3, 4),
             mesh=list(best_fact))
    slog.close()
    states = {n.guid: "R" for n in nodes}
    return assignment_to_strategy(pcg, best, states, *best_fact,
                                  machine=machine)
