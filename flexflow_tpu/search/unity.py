"""Unity-style auto-parallelization search, TPU-native.

Rebuild of the reference's search stack (SURVEY §2.1 L4a): GraphSearchHelper's
outer optimization (substitution.cc:1898), SearchHelper's DP over per-node
MachineViews (graph.h:170-283), memory-aware λ search (graph.cc:2060-2133),
and the legacy MCMC fallback (model.cc:3285).

TPU-native reformulation (SURVEY §7): the reference searches over graph
substitutions that insert partition/combine/replicate/reduction nodes and
assigns 1-D divisor-degree MachineViews (register_all_machine_views,
graph.cc:2329). Under XLA SPMD that space is exactly: (a) a mesh factorization
(dp, tp) of the chip count, and (b) a per-op choice of how the tp axis is
applied (none / column / row / heads / table / expert) with resharding
transitions between choices. The search here:

  outer loop over (dp, tp) factorizations     == enumerating MachineView grids
  per-chain Viterbi DP over sharding states   == find_optimal_sequence_graph_time
  transition costs from the Simulator         == estimate_xfer_cost
  alpha pruning + budget                      == base_optimize's best-first prune
  memory λ binary search                      == graph_optimize_task λ loop
  MCMC fallback (--search-budget, no DP)      == FFModel::mcmc_optimize

The output is a Strategy (per-op shardings) — the same artifact the reference
serializes as optimal_views.
"""
from __future__ import annotations

import dataclasses
import math
import random
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..ffconst import OperatorType
from ..machine_view import MachineView
from ..parallel.pcg import PCG, PCGNode
from ..parallel.strategy import NodeStrategy, Strategy
from ..utils.recursive_logger import RecursiveLogger
from .machine_model import TPUMachineModel
from .simulator import OpSharding, Simulator

_log = RecursiveLogger("unity")

# per-op tp options: (kind, required input state, produced output state)
#   states: 'R' = batch-sharded only; 'S' = also sharded over the model axis
_TP_OPTIONS: Dict[OperatorType, List[Tuple[str, str, str]]] = {
    OperatorType.OP_LINEAR: [("none", "R", "R"), ("col", "R", "S"),
                             ("row", "S", "R")],
    OperatorType.OP_MULTIHEAD_ATTENTION: [("none", "R", "R"),
                                          ("heads", "R", "R")],
    OperatorType.OP_EMBEDDING: [("none", "R", "R"), ("table", "R", "R")],
    OperatorType.OP_CONV2D: [("none", "R", "R"), ("col", "R", "S")],
}
# state-preserving ops (elementwise etc.) pass S through; everything else
# demands R input
_STATE_PRESERVING = {
    OperatorType.OP_RELU, OperatorType.OP_GELU, OperatorType.OP_TANH,
    OperatorType.OP_SIGMOID, OperatorType.OP_ELU, OperatorType.OP_IDENTITY,
    OperatorType.OP_DROPOUT, OperatorType.OP_SCALAR_MULTIPLY,
    OperatorType.OP_SCALAR_ADD, OperatorType.OP_SCALAR_SUB,
    OperatorType.OP_SCALAR_TRUE_DIV, OperatorType.OP_CAST,
    OperatorType.OP_EXP, OperatorType.OP_POW,
}


@dataclasses.dataclass
class SearchResult:
    strategy: Strategy
    assignment: Dict[int, OpSharding]
    sim_time: float
    sim_memory: int
    mesh_shape: Tuple[int, int]


def factorizations(n: int) -> List[Tuple[int, int]]:
    """(dp, tp) pairs with dp*tp == n (reference: divisor-degree views)."""
    out = []
    for tp in range(1, n + 1):
        if n % tp == 0:
            out.append((n // tp, tp))
    return out


def _tp_valid(node: PCGNode, kind: str, tp: int,
              in_shapes: List[Tuple[int, ...]]) -> bool:
    """Divisibility checks (reference: get_valid_machine_views)."""
    a = node.op.attrs
    if kind == "none":
        return True
    if node.op.op_type == OperatorType.OP_LINEAR:
        if kind == "col":
            return a["out_dim"] % tp == 0
        if kind == "row":
            return in_shapes[0][-1] % tp == 0
    if node.op.op_type == OperatorType.OP_MULTIHEAD_ATTENTION:
        return a["num_heads"] % tp == 0
    if node.op.op_type == OperatorType.OP_EMBEDDING:
        return a["num_entries"] % tp == 0
    if node.op.op_type == OperatorType.OP_CONV2D:
        return a["out_channels"] % tp == 0
    return False


def dp_assign(pcg: PCG, sim: Simulator, dp: int, tp: int,
              batch_size: int) -> Tuple[Dict[int, OpSharding],
                                        Dict[int, str], float]:
    """Viterbi DP over the topo order: per node, cost table keyed by output
    state; transitions pay resharding (reference:
    find_optimal_sequence_graph_time + estimate_xfer_cost). At fan-out/fan-in
    points the state is pinned to 'R' (the reference's sequence-split
    bottlenecks are exactly such points).

    Note on sequence splits: the reference recursively splits the graph at
    bottleneck nodes (generic_sequence_optimize, substitution.h:276) because
    its per-node choice space (all MachineViews) is huge. Here the DP state
    space is two values, so the per-node table already carries every
    bottleneck boundary condition exactly — no explicit split is needed.
    ``PCG.bottlenecks``/``split_at_node`` expose the same machinery for
    observability and for the substitution engine."""
    from ..ffconst import size_of_datatype

    nodes = pcg.compute_nodes()
    consumers: Dict[int, int] = {}
    for n in nodes:
        for g, _ in n.inputs:
            consumers[g] = consumers.get(g, 0) + 1

    # dp over (node, out_state) -> (cost, back-pointer (choice, in_state))
    INF = float("inf")
    table: Dict[int, Dict[str, Tuple[float, Tuple[str, str]]]] = {}
    for node in nodes:
        in_shapes = [pcg.nodes[g].out_shapes[i] for g, i in node.inputs]
        opts = _TP_OPTIONS.get(node.op.op_type)
        if opts is None:
            if node.op.op_type in _STATE_PRESERVING and len(node.inputs) == 1:
                opts = [("none", "R", "R"), ("none", "S", "S")]
            else:
                opts = [("none", "R", "R")]
        # producer state tables (compute nodes only; sources are state R)
        def prev_cost(state: str) -> float:
            total = 0.0
            for g, i in node.inputs:
                p = pcg.nodes[g]
                if p.op.op_type in (OperatorType.OP_INPUT,
                                    OperatorType.OP_WEIGHT):
                    continue
                ptab = table.get(g)
                if ptab is None:
                    continue
                if state in ptab and ptab[state][0] < INF:
                    total += ptab[state][0]
                else:
                    # pay an all-gather to convert
                    other = "S" if state == "R" else "R"
                    if other not in ptab or ptab[other][0] >= INF:
                        return INF
                    nbytes = int(np.prod(p.out_shapes[i])) * \
                        size_of_datatype(p.op.data_type)
                    total += ptab[other][0] + sim.resharding_cost(
                        nbytes, other, state, dp, tp)
            return total

        # multi-consumer producers or multi-input nodes pin states to R
        multi_in = len([1 for g, _ in node.inputs
                        if pcg.nodes[g].op.op_type not in
                        (OperatorType.OP_INPUT, OperatorType.OP_WEIGHT)]) > 1

        tab: Dict[str, Tuple[float, Tuple[str, str]]] = {}
        for kind, in_state, out_state in opts:
            if multi_in and in_state != "R":
                continue
            if consumers.get(node.guid, 0) > 1 and out_state != "R":
                continue
            eff_tp = tp if kind != "none" else 1
            if not _tp_valid(node, kind, tp, in_shapes):
                continue
            sh = OpSharding(dp=dp, tp=eff_tp, kind=kind)
            cm = sim.op_cost(node, in_shapes, sh)
            base = prev_cost(in_state)
            if base >= INF:
                continue
            c = base + cm.total_time()
            if out_state not in tab or c < tab[out_state][0]:
                tab[out_state] = (c, (kind, in_state))
        if not tab:  # fallback: unsharded
            sh = OpSharding(dp=dp, tp=1, kind="none")
            cm = sim.op_cost(node, in_shapes, sh)
            tab["R"] = (prev_cost("R") + cm.total_time(), ("none", "R"))
        table[node.guid] = tab

    # backtrack: choose best final state, then walk back greedily per node
    # (the chain DP is exact on chains; at joins states were pinned to R)
    assignment: Dict[int, OpSharding] = {}
    states: Dict[int, str] = {}
    # choose states from sinks backwards
    chosen: Dict[int, str] = {}
    for node in reversed(nodes):
        tab = table[node.guid]
        if node.guid not in chosen:
            # unconstrained: pick cheapest state
            st = min(tab, key=lambda s: tab[s][0])
            chosen[node.guid] = st
        st = chosen[node.guid]
        kind, in_state = tab[st][1]
        eff_tp = tp if kind != "none" else 1
        assignment[node.guid] = OpSharding(dp=dp, tp=eff_tp, kind=kind)
        states[node.guid] = st
        for g, _ in node.inputs:
            p = pcg.nodes[g]
            if p.op.op_type not in (OperatorType.OP_INPUT,
                                    OperatorType.OP_WEIGHT) \
                    and g not in chosen:
                ptab = table[g]
                chosen[g] = in_state if in_state in ptab else \
                    min(ptab, key=lambda s: ptab[s][0])
    # total time: recompute via simulate so resharding edges are counted once
    sim_time, _ = sim.simulate(pcg, assignment, states)
    return assignment, states, sim_time


def assignment_to_strategy(pcg: PCG, assignment: Dict[int, OpSharding],
                           states: Dict[int, str], dp: int, tp: int,
                           data_axis: str = "data",
                           model_axis: str = "model") -> Strategy:
    """Materialize the search result as weight/output shardings (the
    reference's convert_graph_to_operators + optimal_views)."""
    if tp == 1:
        s = Strategy(mesh_shape=(dp,), axis_names=(data_axis,),
                     data_axis=data_axis)
    else:
        s = Strategy(mesh_shape=(dp, tp), axis_names=(data_axis, model_axis),
                     data_axis=data_axis)
    view = MachineView(dim=(dp, tp) if tp > 1 else (dp,),
                       stride=(tp, 1) if tp > 1 else (1,))
    for node in pcg.topo_order():
        ns = s.for_node(node.guid)
        ns.view = view
        sh = assignment.get(node.guid)
        if sh is None or sh.kind == "none" or sh.tp == 1:
            continue
        ot = node.op.op_type
        if ot == OperatorType.OP_LINEAR:
            if sh.kind == "col":
                ns.weight_specs = {"kernel": (None, model_axis),
                                   "bias": (model_axis,)}
                ndim = len(node.out_shapes[0])
                ns.output_spec = (data_axis,) + (None,) * (ndim - 2) + (
                    model_axis,)
            elif sh.kind == "row":
                ns.weight_specs = {"kernel": (model_axis, None),
                                   "bias": (None,)}
                ndim = len(node.out_shapes[0])
                ns.output_spec = (data_axis,) + (None,) * (ndim - 1)
        elif ot == OperatorType.OP_MULTIHEAD_ATTENTION:
            ns.weight_specs = {"wq": (None, model_axis, None),
                               "wk": (None, model_axis, None),
                               "wv": (None, model_axis, None),
                               "wo": (model_axis, None, None),
                               "bo": (None,)}
            ndim = len(node.out_shapes[0])
            ns.output_spec = (data_axis,) + (None,) * (ndim - 1)
        elif ot == OperatorType.OP_EMBEDDING:
            ns.weight_specs = {"weight": (model_axis, None)}
            ndim = len(node.out_shapes[0])
            ns.output_spec = (data_axis,) + (None,) * (ndim - 1)
        elif ot == OperatorType.OP_CONV2D:
            ns.weight_specs = {"kernel": (None, None, None, model_axis),
                               "bias": (model_axis,)}
    return s


def unity_search(pcg: PCG, config, n_dev: int,
                 machine: Optional[TPUMachineModel] = None,
                 return_result: bool = False):
    """Top-level search (reference: graph_optimize_task, graph.cc:2047).

    Enumerates mesh factorizations, runs the per-op DP for each, applies
    alpha pruning, then the memory-λ feasibility loop. Returns a Strategy.
    """
    if machine is None:
        if config.machine_model_version == 1 and config.machine_model_file:
            machine = TPUMachineModel.from_file(config.machine_model_file,
                                               n_dev)
        else:
            machine = TPUMachineModel.detect(n_dev)
    sim = Simulator(machine, config.search_overlap_backward_update)

    batch = config.batch_size
    best: Optional[SearchResult] = None
    alpha = config.search_alpha
    budget = config.search_budget if config.search_budget > 0 else 10 ** 9
    explored = 0
    with _log.scope("unity_search n_dev=%d" % n_dev):
        for dp, tp in factorizations(n_dev):
            if batch % dp != 0:
                continue
            if explored >= budget:
                break
            explored += 1
            assignment, states, t = dp_assign(pcg, sim, dp, tp, batch)
            _, mem = sim.simulate(pcg, assignment, states)
            _log.info("mesh dp=%d tp=%d -> %.3f ms, %.1f MiB/chip",
                      dp, tp, t * 1e3, mem / 2 ** 20)
            if best is not None and t > best.sim_time * alpha:
                continue
            if best is None or t < best.sim_time:
                best = SearchResult(
                    strategy=assignment_to_strategy(pcg, assignment, states,
                                                    dp, tp),
                    assignment=assignment, sim_time=t, sim_memory=mem,
                    mesh_shape=(dp, tp))

    # memory-aware λ loop (reference: graph.cc:2060-2133): if the best
    # strategy exceeds per-chip HBM, penalize memory until one fits
    if best is not None and config.perform_memory_search and \
            best.sim_memory > machine.hbm_capacity:
        feasible = [r for r in _all_results(pcg, sim, n_dev, batch)
                    if r.sim_memory <= machine.hbm_capacity]
        if feasible:
            best = min(feasible, key=lambda r: r.sim_time)

    if best is None:
        from ..parallel.strategy import data_parallel_strategy

        return data_parallel_strategy(pcg, n_dev)
    return (best if return_result else best.strategy)


def _all_results(pcg, sim, n_dev, batch):
    out = []
    for dp, tp in factorizations(n_dev):
        if batch % dp != 0:
            continue
        assignment, states, t = dp_assign(pcg, sim, dp, tp, batch)
        _, mem = sim.simulate(pcg, assignment, states)
        out.append(SearchResult(
            strategy=assignment_to_strategy(pcg, assignment, states, dp, tp),
            assignment=assignment, sim_time=t, sim_memory=mem,
            mesh_shape=(dp, tp)))
    return out


# ---------------------------------------------------------------- legacy MCMC
def mcmc_optimize(pcg: PCG, config, n_dev: int,
                  machine: Optional[TPUMachineModel] = None,
                  iterations: int = 500, temperature: float = 1e-4,
                  seed: int = 0) -> Strategy:
    """Legacy simulated-annealing search over per-op shardings
    (reference: FFModel::mcmc_optimize, model.cc:3285 — random per-op
    ParallelConfig rewrites accepted by Metropolis criterion)."""
    machine = machine or TPUMachineModel.detect(n_dev)
    sim = Simulator(machine)
    rng = random.Random(seed)
    batch = config.batch_size

    facts = [f for f in factorizations(n_dev) if batch % f[0] == 0]
    dp, tp = facts[0]
    nodes = pcg.compute_nodes()

    def random_choice(node):
        opts = _TP_OPTIONS.get(node.op.op_type, [("none", "R", "R")])
        in_shapes = [pcg.nodes[g].out_shapes[i] for g, i in node.inputs]
        valid = [o for o in opts if _tp_valid(node, o[0], tp, in_shapes)]
        return rng.choice(valid or [("none", "R", "R")])

    current = {n.guid: OpSharding(dp=dp, tp=tp if k != "none" else 1, kind=k)
               for n in nodes for k, _, _ in [random_choice(n)]}
    cur_t, _ = sim.simulate(pcg, current)
    best, best_t = dict(current), cur_t
    for it in range(iterations):
        # occasionally rewrite the mesh factorization (reference: restart)
        if it % 100 == 99 and len(facts) > 1:
            dp, tp = rng.choice(facts)
            current = {n.guid: OpSharding(
                dp=dp, tp=tp if k != "none" else 1, kind=k)
                for n in nodes for k, _, _ in [random_choice(n)]}
            cur_t, _ = sim.simulate(pcg, current)
        node = rng.choice(nodes)
        kind, _, _ = random_choice(node)
        cand = dict(current)
        cand[node.guid] = OpSharding(dp=dp, tp=tp if kind != "none" else 1,
                                     kind=kind)
        t, _ = sim.simulate(pcg, cand)
        if t < cur_t or rng.random() < math.exp(-(t - cur_t) / temperature):
            current, cur_t = cand, t
            if t < best_t:
                best, best_t = dict(cand), t
    states = {n.guid: "R" for n in nodes}
    return assignment_to_strategy(pcg, best, states, dp, tp)
