"""TPU machine/topology model for the cost simulator.

Rebuild of the reference's MachineModel hierarchy (include/flexflow/
simulator.h:212-606, src/runtime/machine_model.cc, network.cc): the simulator
needs per-device compute rates and link bandwidths/latencies to cost candidate
strategies. The reference models membus/UPI/NIC/PCIe/NVLink
(machine_config_example:1-30); here the hierarchy is TPU-native:

* per-chip: peak FLOP/s (bf16 and f32), HBM bandwidth and capacity
* ICI: torus links within a slice (per-link GB/s, hop latency)
* DCN: bisection bandwidth across slices

Version selection mirrors the reference (graph.cc:1908-1922):
``machine_model_version == 0`` -> SimpleTPUMachineModel from generation
defaults; ``1`` -> parsed from ``--machine-model-file``.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple


# generation defaults: (peak bf16 FLOP/s, HBM GB/s, HBM GiB,
#                       ICI GB/s per link per direction, ici links/chip)
TPU_GENERATIONS = {
    "v4": (275e12, 1228e9, 32, 50e9, 6),
    "v5e": (197e12, 819e9, 16, 50e9, 4),
    "v5p": (459e12, 2765e9, 95, 100e9, 6),
    "v6e": (918e12, 1640e9, 32, 100e9, 4),
}


def detect_generation(device_kind: str):
    """Normalize a jax ``device_kind`` string to a TPU_GENERATIONS key
    ('TPU v5 lite' -> 'v5e'), or None when unrecognized. The ONE place the
    kind-string matching lives — TPUMachineModel.detect and the flash
    crossover table (ops/attention.FLASH_TUNING) both key off it."""
    kind = device_kind.lower().replace(" ", "").replace("lite", "e")
    for gen in TPU_GENERATIONS:
        if gen in kind:
            return gen
    return None


@dataclasses.dataclass
class TPUMachineModel:
    """Analog of MachineModel v0/v1 with TPU parameters."""

    num_chips: int = 1
    # hosts/slices connected by DCN; chips within a host share an ICI torus.
    # Mirrors the reference's inter-node vs intra-node split
    # (EnhancedMachineModel, simulator.h:212-606; machine_config_example's
    # NIC vs NVLink rows).
    num_hosts: int = 1
    # multi-pod topologies (docs/multipod.md): a POD is one ICI domain —
    # the DCN island the hierarchical search's ICI level solves within.
    # 0 = pods follow ``num_hosts`` (every DCN island is one pod, the
    # single-level machines that predate the pod axis); >= 2 records an
    # explicit pod count, which in this cost model IS the DCN split
    # (``num_hosts`` is kept equal — one DCN level, priced by the
    # hier_* closed forms below).
    num_pods: int = 0
    generation: str = "v5e"
    peak_flops: float = 197e12  # bf16
    peak_flops_f32: float = 98.5e12
    hbm_bandwidth: float = 819e9  # bytes/s
    hbm_capacity: int = 16 * 1024 ** 3  # bytes
    ici_bandwidth: float = 50e9  # bytes/s per link per direction
    ici_links_per_chip: int = 4
    ici_latency: float = 1e-6  # seconds per hop
    torus: Tuple[int, ...] = (1,)  # ICI torus dims, prod == chips per slice
    dcn_bandwidth: float = 25e9  # bytes/s per host across slices
    dcn_latency: float = 10e-6
    # fraction of peak realistically achieved by large matmuls
    matmul_efficiency: float = 0.6
    # fraction of HBM bandwidth achieved by fused elementwise ops
    hbm_efficiency: float = 0.8
    # fraction achieved by the 7-stream optimizer update (4 concurrent
    # reads + 3 writes): measured on v5e — a fused Adam moves 705 MB in
    # 1.63 ms (~435 GB/s) and the BERT-Large profile shows ~495 GB/s, far
    # below the single-stream 0.8. Overridable per machine via machine.cfg.
    update_hbm_efficiency: float = 0.55

    @staticmethod
    def from_generation(gen: str, num_chips: int = 1,
                        torus: Optional[Tuple[int, ...]] = None,
                        num_hosts: int = 1) -> "TPUMachineModel":
        peak, hbm_bw, hbm_gib, ici_bw, links = TPU_GENERATIONS.get(
            gen, TPU_GENERATIONS["v5e"])
        if torus is None:
            torus = _default_torus(num_chips // max(num_hosts, 1))
        return TPUMachineModel(
            num_chips=num_chips, num_hosts=num_hosts, generation=gen,
            peak_flops=peak,
            peak_flops_f32=peak / 2, hbm_bandwidth=hbm_bw,
            hbm_capacity=hbm_gib * 1024 ** 3, ici_bandwidth=ici_bw,
            ici_links_per_chip=links, torus=torus)

    @staticmethod
    def from_file(path: str, num_chips: int = 1) -> "TPUMachineModel":
        """v1: key = value lines (analog of machine_config_example).

        Multi-pod fields (docs/multipod.md): ``num_pods`` declares the
        pod count (each pod one ICI domain; pods connected by DCN) and
        ``dcn_bisection_gbps`` the per-pod DCN bandwidth in GB/s —
        both validated at parse time with errors naming the bad field,
        so a typo'd topology file fails before a 4096-chip search prices
        a machine that doesn't exist."""
        kv: Dict[str, str] = {}
        with open(path) as f:
            for line in f:
                line = line.split("#")[0].strip()
                if "=" in line:
                    k, v = line.split("=", 1)
                    kv[k.strip()] = v.strip()

        def _bad(field: str, why: str):
            return ValueError(
                f"machine model file {path}: field {field!r} = "
                f"{kv[field]!r} is invalid: {why}")

        num_pods = 0
        if "num_pods" in kv:
            try:
                num_pods = int(kv["num_pods"])
            except ValueError:
                raise _bad("num_pods", "expected an integer pod count")
            if num_pods < 1:
                raise _bad("num_pods", "the machine needs >= 1 pod")
            if num_chips % num_pods:
                raise _bad(
                    "num_pods",
                    f"must divide num_chips={num_chips} — a pod is a "
                    "whole ICI domain, chips cannot straddle pods")
        # num_hosts feeds the default-torus computation (invariant:
        # prod(torus) == chips per slice), so parse it BEFORE construction
        num_hosts = int(kv.get("num_hosts", 1))
        if num_pods:
            if "num_hosts" in kv and num_hosts != num_pods:
                raise _bad(
                    "num_pods",
                    f"conflicts with num_hosts={num_hosts}: this cost "
                    "model has ONE DCN level, so pods ARE the DCN "
                    "islands — drop one field or make them equal")
            num_hosts = num_pods
        m = TPUMachineModel.from_generation(kv.get("generation", "v5e"),
                                            num_chips, num_hosts=num_hosts)
        m.num_pods = num_pods
        if "dcn_bisection_gbps" in kv:
            try:
                gbps = float(kv["dcn_bisection_gbps"])
            except ValueError:
                raise _bad("dcn_bisection_gbps",
                           "expected a number (GB/s per pod across DCN)")
            if gbps <= 0:
                raise _bad("dcn_bisection_gbps",
                           "DCN bandwidth must be > 0 GB/s")
            m.dcn_bandwidth = gbps * 1e9
        for field in ("peak_flops", "hbm_bandwidth", "ici_bandwidth",
                      "dcn_bandwidth", "ici_latency", "dcn_latency",
                      "matmul_efficiency", "hbm_efficiency",
                      "update_hbm_efficiency"):
            if field in kv:
                setattr(m, field, float(kv[field]))
        if "hbm_capacity" in kv:
            m.hbm_capacity = int(float(kv["hbm_capacity"]))
        if "torus" in kv:
            m.torus = tuple(int(x) for x in kv["torus"].split("x"))
        return m

    @staticmethod
    def multipod(generation: str, num_pods: int, chips_per_pod: int,
                 dcn_gbps: float = 0.0) -> "TPUMachineModel":
        """A simulated multi-pod machine: ``num_pods`` ICI domains of
        ``chips_per_pod`` chips each, connected by DCN (cost model only —
        the hierarchical search's regression topologies run on CPU)."""
        if num_pods < 1:
            raise ValueError(f"multipod: num_pods must be >= 1, got "
                             f"{num_pods}")
        if chips_per_pod < 1:
            raise ValueError(f"multipod: chips_per_pod must be >= 1, got "
                             f"{chips_per_pod}")
        m = TPUMachineModel.from_generation(
            generation, num_pods * chips_per_pod, num_hosts=num_pods)
        m.num_pods = num_pods
        if dcn_gbps:
            if dcn_gbps <= 0:
                raise ValueError(
                    f"multipod: dcn_gbps must be > 0, got {dcn_gbps}")
            m.dcn_bandwidth = dcn_gbps * 1e9
        return m

    def apply_pod_overrides(self, num_pods: int = 0,
                            dcn_gbps: float = 0.0) -> "TPUMachineModel":
        """Apply the ``--pods`` / ``--dcn-gbps`` CLI overrides onto a
        constructed machine (unity_search's machine-from-config path)."""
        if num_pods:
            if num_pods < 1:
                raise ValueError(
                    f"--pods must be >= 1, got {num_pods}")
            if self.num_chips % num_pods:
                raise ValueError(
                    f"--pods {num_pods} does not divide the machine's "
                    f"{self.num_chips} chips — a pod is a whole ICI "
                    "domain, chips cannot straddle pods")
            self.set_num_hosts(num_pods)
            self.num_pods = num_pods
        if dcn_gbps:
            if dcn_gbps <= 0:
                raise ValueError(
                    f"--dcn-gbps must be > 0, got {dcn_gbps}")
            self.dcn_bandwidth = dcn_gbps * 1e9
        return self

    def set_num_hosts(self, num_hosts: int) -> "TPUMachineModel":
        """Re-split the machine into ``num_hosts`` DCN-connected slices,
        recomputing the per-slice torus (mutating ``num_hosts`` directly
        would leave ``torus`` spanning the whole machine)."""
        self.num_hosts = max(num_hosts, 1)
        self.torus = _default_torus(self.chips_per_host)
        return self

    @staticmethod
    def detect(num_chips: Optional[int] = None,
               num_hosts: Optional[int] = None) -> "TPUMachineModel":
        """Build from the visible JAX devices (CPU test mesh gets v5e params
        so search decisions are deterministic on CI)."""
        import os

        import jax

        devs = jax.devices()
        n = num_chips or len(devs)
        # multi-host runs: each process owns one slice's worth of chips, so
        # the DCN factor is the process count (hosts == slices here)
        hosts = num_hosts or \
            (jax.process_count() if n == len(devs) else 1)
        if n % max(hosts, 1) != 0:
            # silent reset would hand an explicit multi-host caller a
            # single-host cost model with no signal (ADVICE r4)
            import warnings

            warnings.warn(
                f"TPUMachineModel.detect: num_hosts={hosts} does not divide "
                f"num_chips={n}; falling back to a single-host model",
                stacklevel=2)
            hosts = 1
        gen = detect_generation(devs[0].device_kind) or \
            os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
        return TPUMachineModel.from_generation(gen, n, num_hosts=hosts)

    @property
    def chips_per_host(self) -> int:
        return max(self.num_chips // max(self.num_hosts, 1), 1)

    @property
    def pods(self) -> int:
        """Pod count of the machine: the explicit ``num_pods`` when set,
        else the host count (single-level machines: every DCN island is
        one pod)."""
        return max(self.num_pods or self.num_hosts, 1)

    @property
    def chips_per_pod(self) -> int:
        return max(self.num_chips // self.pods, 1)

    # ---- communication cost primitives (α-β model over the torus) -----------
    # ``medium``: "ici" (within a slice) or "dcn" (across hosts). DCN is a
    # per-HOST NIC shared by every chip of the slice — ``nic_sharers`` is the
    # number of chips on one host participating in concurrent distinct
    # collective groups, dividing the NIC bandwidth between them (reference:
    # EnhancedMachineModel's shared NIC channel, simulator.h:311-364).
    def _link(self, medium: str, nic_sharers: int, links: int
              ) -> Tuple[float, float]:
        if medium == "dcn":
            return (self.dcn_bandwidth / max(nic_sharers, 1),
                    self.dcn_latency)
        return (self.ici_bandwidth * links, self.ici_latency)

    def _ici_ring(self, num_participants: int) -> Tuple[int, int]:
        """(usable links, per-round latency hops) for a ring collective over
        ``num_participants`` chips laid out contiguously on the ICI torus.

        Torus-aware analog of the reference's topology-driven routing
        (NetworkedMachineModel topology generators + routing strategies,
        include/flexflow/simulator.h:383-606, src/runtime/network.cc): a
        group spanning k torus axes runs k concurrent bidirectional rings
        (2k links per chip), and the ring phases are per-axis, so the hop
        count is the sum of axis extents, not the flat group size."""
        rem = max(num_participants, 1)
        axes = 0
        hops = 0
        for d in self.torus:
            if d <= 1:
                continue  # degenerate axis: no ring exists along it
            if rem <= 1 or rem % d:
                break
            axes += 1
            hops += d - 1
            rem //= d
        if rem > 1:
            # leftover that doesn't fill an axis rides a single embedded
            # ring — extra hops, no extra concurrent rings
            hops += rem - 1
        links = min(2 * max(axes, 1), self.ici_links_per_chip)
        return links, max(hops, 1)

    def allreduce_time(self, bytes_per_chip: int, num_participants: int,
                       medium: str = "ici", nic_sharers: int = 1) -> float:
        """Ring all-reduce: 2*(n-1)/n * bytes over the per-chip link
        bandwidth. On ICI the torus shape decides how many bidirectional
        rings run concurrently (one per spanned axis — 2 links each)."""
        if num_participants <= 1 or bytes_per_chip == 0:
            return 0.0
        if medium == "ici":
            links, hops = self._ici_ring(num_participants)
            eff_bw, lat = self._link(medium, nic_sharers, links)
            n = num_participants
            return (lat * 2 * hops
                    + 2 * (n - 1) / n * bytes_per_chip / eff_bw)
        eff_bw, lat = self._link(medium, nic_sharers, 2)
        steps = 2 * (num_participants - 1)
        return (lat * steps
                + steps / num_participants * bytes_per_chip / eff_bw)

    def allgather_time(self, bytes_per_chip: int, num_participants: int,
                       medium: str = "ici", nic_sharers: int = 1) -> float:
        if num_participants <= 1 or bytes_per_chip == 0:
            return 0.0
        if medium == "ici":
            links, hops = self._ici_ring(num_participants)
            eff_bw, lat = self._link(medium, nic_sharers, links)
            n = num_participants
            return (lat * hops
                    + (n - 1) * bytes_per_chip / eff_bw)
        eff_bw, lat = self._link(medium, nic_sharers, 2)
        steps = num_participants - 1
        return (lat * steps
                + steps * bytes_per_chip / eff_bw)

    def alltoall_time(self, bytes_per_chip: int, num_participants: int,
                      medium: str = "ici", nic_sharers: int = 1) -> float:
        if num_participants <= 1 or bytes_per_chip == 0:
            return 0.0
        # each chip exchanges (n-1)/n of its data over its links
        eff_bw, lat = self._link(medium, nic_sharers,
                                 self.ici_links_per_chip)
        return (lat * (num_participants - 1)
                + bytes_per_chip * (num_participants - 1)
                / num_participants / eff_bw)

    def p2p_time(self, num_bytes: int, medium: str = "ici") -> float:
        if medium == "dcn":
            return self.dcn_latency + num_bytes / self.dcn_bandwidth
        return self.ici_latency + num_bytes / self.ici_bandwidth

    # ---- hierarchical (ICI within a slice, DCN across) ----------------------
    # The standard multi-slice algorithm: reduce within the slice first so
    # only 1/ici_n of the data crosses DCN, then the cross-slice phase, then
    # the local broadcast (the reduce-scatter + allgather pair costs the same
    # as one local allreduce in ring terms).
    def hier_allreduce_time(self, bytes_per_chip: int, ici_n: int,
                            dcn_n: int, nic_sharers: int = 1) -> float:
        if dcn_n <= 1:
            return self.allreduce_time(bytes_per_chip, ici_n)
        t = self.allreduce_time(bytes_per_chip, ici_n)
        t += self.allreduce_time(bytes_per_chip // max(ici_n, 1), dcn_n,
                                 medium="dcn", nic_sharers=nic_sharers)
        return t

    def hier_allgather_time(self, bytes_per_chip: int, ici_n: int,
                            dcn_n: int, nic_sharers: int = 1) -> float:
        if dcn_n <= 1:
            return self.allgather_time(bytes_per_chip, ici_n)
        # gather across DCN first (small shards), then flood the slice
        t = self.allgather_time(bytes_per_chip, dcn_n, medium="dcn",
                                nic_sharers=nic_sharers)
        t += self.allgather_time(bytes_per_chip * dcn_n, ici_n)
        return t

    def hier_alltoall_time(self, bytes_per_chip: int, ici_n: int,
                           dcn_n: int, nic_sharers: int = 1) -> float:
        if dcn_n <= 1:
            return self.alltoall_time(bytes_per_chip, ici_n)
        # (dcn_n-1)/dcn_n of each chip's data crosses DCN; the rest rides ICI
        dcn_frac = (dcn_n - 1) / dcn_n
        t = self.alltoall_time(int(bytes_per_chip * dcn_frac) + 1, dcn_n,
                               medium="dcn", nic_sharers=nic_sharers)
        t += self.alltoall_time(bytes_per_chip // max(dcn_n, 1), ici_n)
        return t


def _default_torus(n: int) -> Tuple[int, ...]:
    # closest-to-square 2D torus
    import math

    a = int(math.isqrt(n))
    while a > 1 and n % a:
        a -= 1
    return (a, n // a) if a > 1 else (n,)
