"""Persistent, shareable simulator calibration (ISSUE 8).

The Simulator's per-key measured/analytical ratios (``_key_calibration``,
``_key_bwd_ratio``) are process-local; this module gives them a durable
home so a fleet of heterogeneous pods shares measurements instead of each
re-deriving them: one JSON table per **(chip generation, compute dtype)**
under ``--calibration-dir``, entries keyed by the op signature
(``repr(Simulator._op_key(node, in_shapes))`` — the same join key the
op-cost cache and ``--profile-ops`` records use, docs/calibration.md).

Design constraints the tests pin down (test_housekeeping_r10):

* **round-trip fidelity** — a table written by one Simulator loads
  bit-identically on a fresh one (sorted-key JSON, atomic writes);
* **forward compatibility** — unknown top-level fields AND unknown
  per-entry fields written by a future version survive a load+merge+save
  cycle untouched, so the schema can grow without breaking old readers;
* **merge, don't clobber** — ``store_persistent_calibration`` merges into
  the existing table (sample counts accumulate), so concurrent runs on
  different models extend one shared store.
"""
from __future__ import annotations

import contextlib
import json
import os
from typing import Any, Dict, Optional

FORMAT_VERSION = 1


@contextlib.contextmanager
def _table_lock(path: str):
    """Serialize load-merge-save cycles on one table: without it, two
    runs sharing a --calibration-dir both read the same base, each add
    their keys, and the second ``os.replace`` silently drops the first
    run's entries (last-writer-wins over the whole table). Advisory
    ``fcntl`` lock on a sidecar file; on platforms without fcntl the
    atomic replace still guarantees an uncorrupted (if last-writer-wins)
    table."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(f"{path}.lock", "w") as lf:
        try:
            import fcntl

            fcntl.flock(lf, fcntl.LOCK_EX)
        except ImportError:  # pragma: no cover — non-POSIX best effort
            pass
        yield


def dtype_label(config) -> str:
    """Short compute-dtype tag for the table filename ("bf16", "f32",
    ...): calibration measured under bf16 matmuls must never price an f32
    run (different MXU paths, different ratios)."""
    from ..ffconst import DataType

    cd = getattr(config, "compute_dtype", None)
    if cd is None or cd == DataType.DT_NONE:
        return "f32"
    name = getattr(cd, "name", str(cd)).lower()
    return name.replace("dt_", "").replace("float", "f").replace(
        "bfloat", "bf").replace("half", "f16")


def table_path(calibration_dir: str, generation: str, dtype: str) -> str:
    return os.path.join(calibration_dir,
                        f"calibration_{generation or 'unknown'}_"
                        f"{dtype or 'f32'}.json")


def load_table(path: str) -> Dict[str, Any]:
    """Read a calibration table, tolerating unknown future fields (they
    are preserved verbatim for the next save). Returns an empty skeleton
    when the file is missing or unreadable — a corrupt table must never
    take calibration down with it."""
    try:
        with open(path) as f:
            d = json.load(f)
        if not isinstance(d, dict):
            return {"format_version": FORMAT_VERSION, "entries": {}}
        d.setdefault("format_version", FORMAT_VERSION)
        if not isinstance(d.get("entries"), dict):
            d["entries"] = {}
        return d
    except (OSError, ValueError):
        return {"format_version": FORMAT_VERSION, "entries": {}}


def save_table(path: str, table: Dict[str, Any]) -> str:
    """Atomic, deterministic (sorted keys) write — byte-identical for
    identical content, so round-trip tests and dedup tooling can diff
    tables textually."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(table, f, sort_keys=True, indent=1, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_persistent_calibration(sim) -> int:
    """Fill ``sim._persisted_calibration`` from the (generation, dtype)
    table under ``sim.calibration_dir``; entries are adopted lazily per
    key on the uncached op-cost path. Returns the entry count."""
    if not sim.calibration_dir:
        return 0
    path = table_path(sim.calibration_dir,
                      getattr(sim.machine, "generation", "") or "unknown",
                      sim.dtype_label)
    table = load_table(path)
    entries = {k: v for k, v in table.get("entries", {}).items()
               if isinstance(v, dict)}
    sim._persisted_calibration = entries
    sim._persist_checked = set()
    return len(entries)


def store_persistent_calibration(sim) -> Optional[str]:
    """Merge the simulator's in-memory per-key calibration into the
    persistent table and write it back. Existing entries for the same key
    are updated (the newest measurement wins; ``samples`` accumulates);
    entries for OTHER keys — other models measured by other runs — and
    any unknown fields are preserved."""
    if not sim.calibration_dir:
        return None
    gen = getattr(sim.machine, "generation", "") or "unknown"
    path = table_path(sim.calibration_dir, gen, sim.dtype_label)
    with _table_lock(path):
        table = load_table(path)
        table["generation"] = gen
        table["dtype"] = sim.dtype_label
        entries = table["entries"]
        for key, cal in sim._key_calibration.items():
            krepr = repr(key)
            ent = entries.get(krepr)
            if not isinstance(ent, dict):
                ent = entries[krepr] = {}
            ent["calibration"] = float(cal)
            b = sim._key_bwd_ratio.get(key)
            if b is not None:
                ent["bwd_ratio"] = float(b)
            ent["samples"] = int(ent.get("samples", 0)) + 1
        save_table(path, table)
    # the just-written state IS the persisted state: refresh the lazy-
    # adoption view so a later invalidation re-adopts current values
    sim._persisted_calibration = {k: dict(v) for k, v in entries.items()
                                  if isinstance(v, dict)}
    return path


def calibrate_sim_from_trace(sim, pcg, path: str,
                             min_rel_change: float = 0.05
                             ) -> Dict[str, Any]:
    """``--calibrate-from-trace`` entry point: replay a ``--profile-ops``
    JSONL into ``Simulator.calibrate_from_profile`` against ``pcg``. The
    file must exist (parse-time validation enforces it for the flag; a
    programmatic call gets the same error)."""
    from ..obs.profile import OpProfile

    if not os.path.isfile(path):
        raise FileNotFoundError(
            f"--calibrate-from-trace {path!r}: no such profile file "
            "(produce one with --profile-ops)")
    profile = OpProfile.read_jsonl(path)
    return sim.calibrate_from_profile(profile, pcg,
                                      min_rel_change=min_rel_change)


def build_calibrated_sim(model):
    """The fit loop's drift-sentinel Simulator. When a searched strategy
    is live the model holds the search's WARM simulator
    (``model._search_sim``) — the sentinel judges (and, in auto mode,
    repairs) the exact ruler the search ranked with, and
    ``calibrate_from_profile``'s selective invalidation acts on the real
    delta-cost caches instead of an empty clone. Otherwise a fresh
    Simulator is built with unity_search's recipe: detected machine for
    the live device count, persistent tables attached
    (``--calibration-dir``), a ``--calibrate-from-trace`` profile
    applied, the executor's remat segmentation mirrored."""
    from .machine_model import TPUMachineModel
    from .simulator import Simulator

    cfg = model.config
    sim = getattr(model, "_search_sim", None)
    if sim is None:
        n = 1
        if model.mesh is not None:
            n = int(model.mesh.devices.size)
        sim = Simulator(
            TPUMachineModel.detect(n),
            calibration_dir=getattr(cfg, "calibration_dir", "") or None,
            dtype_label=dtype_label(cfg))
        sim.remat_segment_size = int(
            getattr(cfg, "remat_segment_size", 8) or 8)
        trace = getattr(cfg, "calibrate_from_trace", "") or ""
        if trace and model.pcg is not None:
            calibrate_sim_from_trace(sim, model.pcg, trace)
    return sim


def rerank_candidates(model, sim) -> bool:
    """Re-rank the search's top-K fallback chain (PR 5's
    ``SearchResult.ranked``) against REPAIRED costs: each candidate is
    re-priced by the SAME engines that ranked it originally —
    ``dp_assign`` for SPMD plans, ``simulate_pipeline`` for pipeline
    grids — on the model's live (winner-rewritten) graph under the
    repaired per-key calibration. When ``sim`` is the warm search
    simulator this is a near-pure remix: only the moved keys were
    invalidated, every other table row hits. The runners-up are
    re-sorted feasible-first by time (the cascade's original order
    contract); rank 0 — the LIVE strategy — keeps its place
    (hot-swapping a training run's plan is the cascade's job, not the
    sentinel's), but a ``calibration_rerank`` obs event reports whether
    it would still win. Returns True when any candidate's simulated
    time changed."""
    cands = list(getattr(model, "_strategy_candidates", []) or [])
    if len(cands) < 2 or model.pcg is None:
        return False
    from ..obs import get_tracer
    from .unity import SearchSpace, dp_assign, simulate_pipeline

    space = SearchSpace.full()
    space.sequence = getattr(model.config, "enable_sequence_parallel",
                             True)
    batch = int(getattr(model.config, "batch_size", 1) or 1)
    changed = False
    for c in cands:
        old = (sim.dp_dcn, sim.tp_dcn)
        sim.set_axis_topology(*tuple(c.dcn or (1, 1)))
        try:
            if c.pipeline:
                pp, pdp, n_micro = tuple(c.pipeline)
                # the candidate's SCHEDULE is part of its identity
                # (ISSUE 10): re-price the same task graph + in-flight
                # memory the original ranking used, not gpipe's
                t, mem = simulate_pipeline(
                    sim, model.pcg, pp, pdp, n_micro, remat=c.remat,
                    schedule=(c.schedule or "gpipe"),
                    v=int(getattr(c, "virtual_stages", 1) or 1))
            else:
                dp, tp = tuple(c.mesh_shape)
                if batch % max(dp, 1):
                    continue  # unpriceable at this batch; keep old cost
                assignment, states, t = dp_assign(
                    model.pcg, sim, dp, tp, batch, space=space,
                    remat=c.remat)
                _, mem = sim.simulate(model.pcg, assignment, states)
        finally:
            sim.set_axis_topology(*old)
        if abs(t - c.sim_time) > 1e-12:
            changed = True
        c.sim_time, c.sim_memory = t, int(mem)
    head, tail = cands[0], cands[1:]
    tail.sort(key=lambda c: (not c.feasible, c.sim_time))
    model._strategy_candidates = [head] + tail
    feas = [c.sim_time for c in tail if c.feasible]
    winner_still_best = not feas or head.sim_time <= min(feas) * 1.001
    tracer = get_tracer()
    if tracer.enabled:
        tracer.event(
            "calibration_rerank", changed=bool(changed),
            winner_still_best=bool(winner_still_best),
            live=head.describe(),
            order=[{"strategy": c.describe(),
                    "cost_ms": round(c.sim_time * 1e3, 4),
                    "feasible": bool(c.feasible)} for c in tail[:8]])
    return changed
