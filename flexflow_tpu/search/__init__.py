"""Auto-parallelization search: TPU machine model, cost simulator, Unity DP
search, MCMC fallback, substitution engine (SURVEY §2.1 L4a/L4b)."""
from .machine_model import TPUMachineModel  # noqa: F401
from .simulator import CostMetrics, OpSharding, Simulator  # noqa: F401
from .unity import unity_search, mcmc_optimize, factorizations  # noqa: F401
from .multipod import (ICISubSolver, hierarchical_enabled,  # noqa: F401
                       simulated_multipod_machine)
