"""Cost model + task-graph simulator for candidate parallelization strategies.

Rebuild of the reference's Simulator (src/runtime/simulator.cc:1880 —
``measure_operator_cost`` caching per-(op,view) timings and
``simulate_runtime`` event-driven execution of the task graph with comm
tasks). TPU-native differences (SURVEY §7 hard-part 3):

* Per-op cost comes from a **roofline model** over the TPUMachineModel
  (max(FLOPs/peak, bytes/HBM-bw)) instead of cudaEvent microbenchmarks —
  XLA fusion makes isolated per-op timing misleading; the analytical model is
  calibrated against measured end-to-end steps (``calibrate``).
* Communication is costed with α-β collective formulas over ICI instead of
  per-link event simulation — SPMD collectives are compiler-scheduled, not
  runtime-scheduled.
* Optional measured mode (``measure_operator_cost``) jit-times a single op
  standalone on the real chip and caches by (op params, sharding), mirroring
  the reference's cache keyed by op + MachineView.
* Delta-cost engine (ISSUE 2): ``op_cost`` and the DP search's per-node
  option tables are memoized in bounded LRUs keyed by
  (op params, in-shapes, sharding, dcn), persisting across factorization
  sweeps, λ iterations and rewrite candidates — the TPU analog of the
  reference re-simulating only *deltas* (simulator.cc's cached task costs).
  Calibration and memory-model knob changes flush the tables; the
  ``FLEXFLOW_TPU_SEARCH_SELFCHECK`` env var enables a test-only gate that
  re-derives every hit and asserts equality. See ``docs/search.md``.
* Remat axis (ISSUE 3): ``OpSharding.remat`` prices activation
  rematerialization — recompute time in backward, saved bytes scaled by
  ``remat_keep_fraction`` (shared with unity's DP tables and pipeline
  stage estimate), and ``simulate``'s full-remat peaks priced on the SAME
  remat blocks the Executor checkpoints. See ``docs/remat.md``.
"""
from __future__ import annotations

import dataclasses
import math
import os
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from ..execution.remat import REMAT_SAVEABLE_OPS, remat_segments
from ..ffconst import OperatorType, size_of_datatype
from ..parallel.pcg import PCG, PCGNode
from .machine_model import TPUMachineModel

# ops whose cost is MXU-bound — the same contraction family whose outputs
# the `selective` remat policy saves; ONE set (execution/remat.py) so the
# roofline classification and the analytic keep-fraction can never drift
# from the dots_saveable policy's actual save set
_MATMUL_OPS = REMAT_SAVEABLE_OPS


@dataclasses.dataclass
class CostMetrics:
    """Per-op costs (reference: simulator.h:54-88)."""

    forward_time: float = 0.0  # seconds
    backward_time: float = 0.0
    sync_time: float = 0.0  # gradient allreduce
    comm_time: float = 0.0  # activation resharding
    update_time: float = 0.0  # optimizer step (HBM-bound elementwise)
    inputs_memory: int = 0
    outputs_memory: int = 0
    weights_memory: int = 0

    def total_time(self) -> float:
        return (self.forward_time + self.backward_time + self.sync_time
                + self.comm_time + self.update_time)


@dataclasses.dataclass(frozen=True)
class OpSharding:
    """The search's per-op decision: data-parallel degree, model(tensor)
    degree, and how the model degree is applied. TPU-native MachineView
    (SURVEY §7: the searched space of the reference's
    register_all_machine_views is 1-D divisor-degree views — (dp, tp)
    factorizations cover it).

    ``act_tp`` covers pass-through sharded states (kind == "none" but the
    activation rides the model axis in state S or Q): the op's compute and
    activation memory shard over dp*act_tp while its weights stay
    replicated — e.g. a per-token dense inside a sequence-parallel region.

    ``remat`` is the activation-rematerialization level this op trains
    under (execution.remat.REMAT_LEVELS): it is part of the op-cost cache
    key by construction (this dataclass is the key component), so costs
    priced at one level are never served at another."""

    dp: int = 1
    tp: int = 1
    kind: str = "none"  # none|col|row|heads|table|expert|ring
    act_tp: int = 1
    remat: str = "none"  # none|selective|full (jax.checkpoint level)

    @property
    def degree(self) -> int:
        return self.dp * (self.tp if self.kind != "none" else self.act_tp)


def op_in_state(sh: Optional["OpSharding"], out_state: str) -> str:
    """The sharding state an op's chosen kind CONSUMES (col eats R and emits
    S; row eats S and emits R; ring eats/emits Q; state-preserving kinds eat
    what they emit). Used to price resharding on the true input edge, not
    the producer-out vs consumer-out mismatch."""
    if sh is None:
        return "R"
    if sh.kind == "col":
        return "R"
    if sh.kind == "row":
        return "S"
    if sh.kind == "ring":
        return "Q"
    if sh.kind == "spatial":
        return "H"
    if sh.kind in ("heads", "table", "expert"):
        return "R"
    return out_state


def sequence_schedule(node: PCGNode, in_shapes, sh: "OpSharding",
                      machine, tp_dcn: int = 1) -> Tuple[str, float]:
    """Pick the sequence-parallel schedule for a ring-kind attention op and
    return (schedule, comm_time): "ring" (k/v rotation,
    kernels/ring_attention.py) or "alltoall" (Ulysses head re-partition,
    kernels/ulysses_attention.py). All-to-all moves ~P/2x less data but
    materializes the full (s, s) score block per local head group, so it is
    eligible only when the head count divides the axis AND that block fits
    comfortably in HBM (<= 1/8 capacity) — long-context configs keep ring's
    O((s/P)^2) memory. Both ``Simulator.op_cost`` and the strategy emission
    (unity.assignment_to_strategy) use THIS function, so the search's costs
    always match the executed schedule."""
    el = size_of_datatype(node.op.data_type)
    in_bytes = sum(int(np.prod(s)) for s in in_shapes) * el
    deg = max(sh.degree, 1)
    tp_ici = max(sh.tp // max(tp_dcn, 1), 1)
    # concurrent ring groups per host share the NIC (same formula as
    # Simulator._nic_sharers, so sim and emission price identically)
    sharers = max(machine.chips_per_host // tp_ici, 1)
    # k+v are 2 of the 3 equally-sized self-attention inputs
    kv_per_chip = int(2 * in_bytes / 3) // deg
    ring_t = machine.hier_allgather_time(kv_per_chip, tp_ici, tp_dcn,
                                         nic_sharers=sharers)
    heads = node.op.attrs.get("num_heads", 0)
    if not heads or heads % sh.tp != 0:
        return "ring", ring_t
    b, s = in_shapes[0][0], in_shapes[0][1]
    score_bytes = (b / max(sh.dp, 1)) * (heads / sh.tp) * s * s * 4  # f32
    if score_bytes > machine.hbm_capacity / 8:
        return "ring", ring_t
    # 4 all-to-alls (q, k, v in; out back) of the local activation volume
    aa_t = 4 * machine.hier_alltoall_time(int(in_bytes / 3) // deg,
                                          tp_ici, tp_dcn,
                                          nic_sharers=sharers)
    if aa_t < ring_t:
        return "alltoall", aa_t
    return "ring", ring_t


# test-only equivalence gate for the delta-cost engine: when set, every
# cache hit is re-derived from scratch and compared, and the incremental DP
# in unity.best_first_optimize is shadowed by a full re-cost — identical
# chosen strategies and costs (within float tolerance) are asserted.
SELFCHECK_ENV = "FLEXFLOW_TPU_SEARCH_SELFCHECK"


def selfcheck_enabled() -> bool:
    return bool(os.environ.get(SELFCHECK_ENV))


def _assert_cost_close(fresh: "CostMetrics", cached: "CostMetrics",
                       key: Tuple) -> None:
    for f in dataclasses.fields(CostMetrics):
        a = getattr(fresh, f.name)
        b = getattr(cached, f.name)
        if not math.isclose(a, b, rel_tol=1e-9, abs_tol=1e-12):
            raise AssertionError(
                f"delta-cost selfcheck: cached {f.name}={b!r} != "
                f"fresh {a!r} for key {key!r} — a cost knob changed "
                f"without invalidate_cost_tables()")


_KNOB_UNSET = object()


def _cost_knob(name: str, doc: str = ""):
    """A Simulator attribute that parameterizes memoized costs: setting it
    to a NEW value flushes the delta-cost tables, so stale entries priced
    under the old calibration/memory model can never be served."""
    attr = "_knob_" + name

    def fget(self):
        return getattr(self, attr)

    def fset(self, value):
        old = getattr(self, attr, _KNOB_UNSET)
        setattr(self, attr, value)
        if old is not _KNOB_UNSET and old != value:
            self.invalidate_cost_tables()

    return property(fget, fset, doc=doc)


class Simulator:
    # cost knobs: every memoized (time, mem, comm) entry is a function of
    # these, so assignment auto-flushes the caches (delta-cost engine)
    calibration = _cost_knob(
        "calibration", "global measured/analytical scale factor")
    update_bytes_factor = _cost_knob("update_bytes_factor")
    op_overhead = _cost_knob("op_overhead")
    opt_state_words = _cost_knob("opt_state_words")
    activation_el = _cost_knob(
        "activation_el", "bytes per saved-activation element (compute dtype)")
    remat_segment_size = _cost_knob(
        "remat_segment_size",
        "compute nodes per full-remat block — MUST match the Executor's "
        "config.remat_segment_size or the analytic boundary/transient "
        "pricing diverges from the blocks actually checkpointed "
        "(unity_search threads it through)")

    def __init__(self, machine: TPUMachineModel,
                 overlap_backward_update: bool = False,
                 cost_cache_size: int = 1 << 17,
                 calibration_dir: Optional[str] = None,
                 dtype_label: Optional[str] = None):
        self.machine = machine
        self.overlap = overlap_backward_update
        # per-remat-block psum overlap pricing (--collective-overlap on):
        # set by unity_search; distinct from the legacy coarse `overlap`
        # knob — see simulate()'s two hiding models
        self.block_overlap = False
        self._measure_cache: Dict[Tuple, float] = {}
        # ---- delta-cost engine (reference: simulator.cc's cached task
        # costs making delta re-simulation tractable). Bounded LRUs keyed by
        # (op params key, in-shapes, sharding, dcn): entries persist across
        # factorization sweeps, λ iterations and rewrite candidates; the
        # dcn topology is part of the key (set_axis_topology never serves a
        # stale entry), while calibration/knob changes flush everything via
        # invalidate_cost_tables(). cost_cache_size <= 0 disables caching
        # (full re-costing — the equivalence baseline in tests).
        self.cost_cache_size = cost_cache_size
        self._cost_cache: "OrderedDict[Tuple, CostMetrics]" = OrderedDict()
        self._table_cache: "OrderedDict[Tuple, Any]" = OrderedDict()
        self._reshard_cache: "OrderedDict[Tuple, float]" = OrderedDict()
        self.cost_cache_hits = 0
        self.cost_cache_misses = 0
        self.table_hits = 0
        self.table_misses = 0
        self.calibration = 1.0  # global measured/analytical scale factor
        # per-op-key measured/analytical ratios (reference: the per-(op,view)
        # cost cache of simulator.cc:489; here per op-shape, scaled
        # analytically across shardings)
        self._key_calibration: Dict[Tuple, float] = {}
        # persistent calibration tables (ISSUE 8, docs/calibration.md):
        # repr(op key) -> {"calibration": r, "bwd_ratio": b} loaded from the
        # per-(chip generation, dtype) JSON store under --calibration-dir
        # and adopted lazily the first time a key is priced (repr() of the
        # key stays off the memoized hot path; op_cost's LRU bounds how
        # often the uncached path runs)
        self.calibration_dir = calibration_dir
        self.dtype_label = dtype_label or "f32"
        self._persisted_calibration: Dict[str, Dict] = {}
        self._persist_checked: Set[Tuple] = set()
        if calibration_dir:
            from .calibration import load_persistent_calibration

            load_persistent_calibration(self)
        # per-op-key MEASURED backward/forward ratios (reference times
        # backward explicitly: inner_measure_operator_cost runs both
        # directions, simulator.cc:537 / model.cu:38). Keys absent here
        # fall back to the analytical 2x/1x heuristic.
        self._key_bwd_ratio: Dict[Tuple, float] = {}
        # optimizer-update HBM traffic per weight byte: Adam-style reads
        # w+g+m+v and writes w+m+v -> ~7 bytes moved per weight byte
        # (reference: optimizer_kernel.cu adam_update_task). Set 0 to price
        # bare SGD (in-place w -= lr*g streams ~3x).
        self.update_bytes_factor = 7.0
        # fixed per-op scheduling overhead (s): the reference's measured
        # task costs inherently include the Legion task-launch overhead
        # (Unity's simulator times whole task bodies, simulator.cc:489);
        # XLA's analog is sub-microsecond per-HLO scheduling. This term is
        # what makes op-count-reducing rewrites (activation fusions, the
        # TASO collection's shrinking rules) properly valued — without it
        # merging two elementwise ops is cost-neutral in a pure roofline.
        self.op_overhead = 5e-7
        # optimizer state words per weight word resident all step (Adam m+v
        # = 2; bare SGD = 0); weights count x(1 + opt_state_words) in the
        # peak-memory model
        self.opt_state_words = 2
        # bytes per saved-activation element under mixed precision (set by
        # calibrate_from_pcg from its compute_dtype; None = the op dtype) —
        # XLA saves residuals in the COMPUTE dtype, so bf16 halves the
        # activation term of the peak-memory model
        self.activation_el: Optional[int] = None
        # full-remat block size for simulate()'s boundary/transient pricing
        # (RematPlan.segment_size default; unity_search overrides from
        # config so sim and executor cut identical blocks)
        self.remat_segment_size = 8
        # per-graph segmentation memo (bottleneck analysis is O(V+E) and
        # simulate() sits in the search's hottest loop); weak keys — a
        # dead candidate graph drops its entry, and object identity avoids
        # the guid-mismatch a structural-hash key would allow between
        # isomorphic graphs with different guids
        import weakref

        self._segment_memo: "weakref.WeakKeyDictionary[PCG, Dict]" = \
            weakref.WeakKeyDictionary()
        self._dispatch_overhead: Optional[float] = None
        # which mesh axis carries the machine's DCN factor for the candidate
        # being costed (reference: intra- vs inter-node pricing in
        # EnhancedMachineModel, simulator.h:212-606). dp_dcn * tp_dcn ==
        # machine.num_hosts when a hybrid placement is being evaluated.
        self.dp_dcn = 1
        self.tp_dcn = 1

    def set_axis_topology(self, dp_dcn: int = 1, tp_dcn: int = 1) -> None:
        """Declare how the candidate mesh maps onto hosts: ``dp_dcn`` /
        ``tp_dcn`` are the DCN-spanning subfactors of the data and model
        axes. Collective costs for an axis with a DCN factor pay DCN
        latency/bandwidth for the cross-host phase."""
        self.dp_dcn = max(dp_dcn, 1)
        self.tp_dcn = max(tp_dcn, 1)

    def scaled_bytes(self, nbytes: int, node: PCGNode) -> int:
        """Re-price ``nbytes`` (computed at the op's declared dtype) into
        the COMPUTE dtype: under mixed precision both the saved residuals
        and the weight grads live in ``activation_el``-byte elements."""
        if self.activation_el is None:
            return nbytes
        el = size_of_datatype(node.op.data_type)
        return int(nbytes * self.activation_el // max(el, 1))

    def act_bytes(self, node: PCGNode, cm: "CostMetrics") -> int:
        """This node's saved-activation bytes in the compute dtype."""
        return self.scaled_bytes(cm.outputs_memory, node)

    @staticmethod
    def remat_keep_fraction(node: PCGNode, level: str) -> float:
        """Fraction of this node's saved-for-backward activation that stays
        resident under a remat level — THE shared accounting all three
        memory consumers price with (simulate's peak, unity's DP tables,
        simulate_pipeline's stage estimate; see execution/remat.py):
        ``none`` keeps everything; ``selective`` keeps only contraction
        outputs (the dots_saveable policy's save set) and recomputes the
        cheap tail; ``full`` keeps nothing per node — block boundaries and
        the recompute transient are priced separately in ``simulate``."""
        if level == "none" or level not in ("selective", "full"):
            return 1.0
        if level == "selective":
            return 1.0 if node.op.op_type in REMAT_SAVEABLE_OPS else 0.0
        return 0.0

    def node_resident_bytes(self, node: PCGNode, cm: "CostMetrics",
                            remat: str = "none") -> int:
        """Per-node resident memory under the liveness-aware model — the
        SAME formula ``simulate``'s peak sums (saved activation in the
        compute dtype scaled by the remat keep-fraction + f32 master
        weights with optimizer moments + the weight grad in the compute
        dtype), shared so the memory-λ DP and the feasibility check price
        one model. Under ``full`` remat the per-node activation term is 0
        (a LOWER bound — simulate() adds back block boundaries and the
        recompute transient, which do not decompose per node)."""
        keep = self.remat_keep_fraction(node, remat)
        return (int(self.act_bytes(node, cm) * keep)
                + cm.weights_memory * (1 + self.opt_state_words)
                + self.scaled_bytes(cm.weights_memory, node))

    def _remat_segments_for(self, pcg: PCG):
        """Memoized ``remat_segments`` at the simulator's block size —
        identical cuts to the Executor's; keyed by graph identity so the
        memo can never serve another graph's guids."""
        per = self._segment_memo.get(pcg)
        if per is None:
            per = {}
            self._segment_memo[pcg] = per
        size = self.remat_segment_size
        segs = per.get(size)
        if segs is None:
            per[size] = segs = remat_segments(pcg, size)
        return segs

    def _nic_sharers(self, group_ici: int) -> int:
        """Concurrent distinct collective groups per host sharing the NIC:
        every chip of the host participates in some group; groups with
        ``group_ici`` local members leave chips_per_host/group_ici distinct
        groups contending for the host's DCN bandwidth."""
        return max(self.machine.chips_per_host // max(group_ici, 1), 1)

    # ------------------------------------------------- delta-cost cache API
    def invalidate_cost_tables(self) -> None:
        """Flush every memoized cost: the op-cost LRU, the per-node DP
        option tables (unity._node_cost_entries), and the resharding memo.
        Called automatically when a cost knob changes and by the
        calibration paths — cached entries priced under stale calibration
        would silently re-rank candidates otherwise."""
        self._cost_cache.clear()
        self._table_cache.clear()
        self._reshard_cache.clear()

    def _adopt_persisted(self, key: Tuple) -> float:
        """Lazy adoption of a persisted calibration entry for ``key``
        (ISSUE 8): the JSON store is repr-keyed, so the string lookup
        happens at most once per distinct key on the UNCACHED path; a hit
        installs the ratio (and measured bwd/fwd ratio, when stored) into
        the in-memory per-key maps."""
        if not self._persisted_calibration or key in self._persist_checked:
            return self.calibration
        self._persist_checked.add(key)
        ent = self._persisted_calibration.get(repr(key))
        if ent is None:
            return self.calibration
        cal = float(ent.get("calibration", self.calibration))
        self._key_calibration[key] = cal
        b = ent.get("bwd_ratio")
        if b is not None:
            self._key_bwd_ratio.setdefault(key, float(b))
        return cal

    def invalidate_op_keys(self, op_keys) -> Dict[str, int]:
        """Selective delta-cost invalidation (ISSUE 8): drop exactly the
        memoized entries whose ``(op params, in-shapes)`` key is in
        ``op_keys`` — every cached CostMetrics for that key at ANY
        sharding/dcn, and every per-node DP option table built over it —
        leaving the rest of the caches warm (the whole point of per-key
        recalibration vs the knob setters' full flush). The resharding
        memo is untouched: it is a pure machine-model quantity with no
        per-key calibration term. Under ``FLEXFLOW_TPU_SEARCH_SELFCHECK``
        any entry this SHOULD have dropped but didn't is caught by the
        hit-re-derivation gate in ``op_cost``. Returns removal counts."""
        op_keys = set(op_keys)
        stale_cost = [k for k in self._cost_cache
                      if (k[0], k[1]) in op_keys]
        for k in stale_cost:
            del self._cost_cache[k]
        # pod-level ICI sub-solutions (search/multipod.py) aggregate MANY
        # ops' costs under one graph-hash key, so any recalibrated op may
        # have moved any of them — drop them all (cheap: re-solving is a
        # handful of DP passes, serving a stale pod plan is silent)
        stale_table = [k for k in self._table_cache
                       if (len(k) >= 3 and (k[1], k[2]) in op_keys)
                       or (k and k[0] == "ici_pod_solution")]
        for k in stale_table:
            del self._table_cache[k]
        return {"cost_entries": len(stale_cost),
                "table_entries": len(stale_table)}

    def table_get(self, key: Tuple):
        """Look up an opaque per-node cost table (the DP search's per-node
        option entries) in the bounded LRU; None on miss."""
        v = self._table_cache.get(key)
        if v is None:
            self.table_misses += 1
            return None
        self._table_cache.move_to_end(key)
        self.table_hits += 1
        return v

    def table_put(self, key: Tuple, value) -> None:
        if self.cost_cache_size <= 0:
            return
        self._table_cache[key] = value
        if len(self._table_cache) > self.cost_cache_size:
            self._table_cache.popitem(last=False)

    def cache_stats(self) -> Dict[str, Any]:
        """Hit/miss counters for the SearchLog/tracer and bench.py."""
        total = self.cost_cache_hits + self.cost_cache_misses
        return {
            "cost_cache_hits": self.cost_cache_hits,
            "cost_cache_misses": self.cost_cache_misses,
            "cost_cache_hit_rate": round(self.cost_cache_hits / total, 4)
            if total else 0.0,
            "table_hits": self.table_hits,
            "table_misses": self.table_misses,
        }

    # ------------------------------------------------------------ per-op cost
    def op_cost(self, node: PCGNode, in_shapes: List[Tuple[int, ...]],
                sh: OpSharding) -> CostMetrics:
        """Memoized per-op cost: (op params key, in-shapes, sharding, dcn)
        → CostMetrics, held in a bounded LRU that persists across
        factorizations, λ iterations and rewrite candidates (the delta-cost
        engine's ground layer; reference: measure_operator_cost's per-
        (op, MachineView) cache, simulator.cc:489). The returned
        CostMetrics is shared — callers must not mutate it."""
        key = (node.op.params_key(), tuple(map(tuple, in_shapes)), sh,
               self.dp_dcn, self.tp_dcn)
        cached = self._cost_cache.get(key)
        if cached is not None:
            self._cost_cache.move_to_end(key)
            self.cost_cache_hits += 1
            if selfcheck_enabled():
                _assert_cost_close(
                    self._op_cost_uncached(node, in_shapes, sh), cached, key)
            return cached
        self.cost_cache_misses += 1
        cm = self._op_cost_uncached(node, in_shapes, sh)
        if self.cost_cache_size > 0:
            self._cost_cache[key] = cm
            if len(self._cost_cache) > self.cost_cache_size:
                self._cost_cache.popitem(last=False)
        return cm

    def _op_cost_uncached(self, node: PCGNode,
                          in_shapes: List[Tuple[int, ...]],
                          sh: OpSharding) -> CostMetrics:
        m = self.machine
        op = node.op
        out_shapes = node.out_shapes
        el = size_of_datatype(op.data_type)

        flops = op.flops(in_shapes, out_shapes)
        in_bytes = sum(int(np.prod(s)) for s in in_shapes) * el
        out_bytes = sum(int(np.prod(s)) for s in out_shapes) * el
        w_bytes = sum(int(np.prod(spec[0]))
                      for spec in op.weight_specs(in_shapes).values()) * el

        deg = max(sh.degree, 1)
        w_shard_kinds = ("col", "row", "heads", "table", "expert")
        w_div = max(sh.tp if sh.kind in w_shard_kinds else 1, 1)
        shard_flops = flops / deg
        shard_bytes = (in_bytes + out_bytes) / deg + w_bytes / w_div

        if op.op_type in _MATMUL_OPS:
            compute = shard_flops / (m.peak_flops * m.matmul_efficiency)
        else:
            compute = shard_flops / (m.peak_flops_f32 * m.matmul_efficiency)
        mem_time = shard_bytes / (m.hbm_bandwidth * m.hbm_efficiency)
        key = self._op_key(node, in_shapes)
        cal = self._key_calibration.get(key)
        if cal is None:
            cal = self._adopt_persisted(key)
        fwd = max(compute, mem_time) * cal + self.op_overhead
        # backward: measured per-key ratio when calibrated on device
        # (calibrate_from_pcg times value_and_grad standalone); analytical
        # 2x/1x heuristic otherwise
        bwd = fwd * self._key_bwd_ratio.get(
            key, 2.0 if w_bytes else 1.0)
        # rematerialization recompute rides the backward pass: `full`
        # re-runs every forward once inside the VJP (the GPipe stage-remat
        # trade simulate_pipeline previously hand-rolled); `selective`
        # (dots_saveable) re-runs only the non-contraction tail. Block
        # boundaries under `full` are saved, not recomputed — one node per
        # ~segment_size, absorbed into this per-node bound.
        if sh.remat == "full" or (sh.remat == "selective"
                                  and self.remat_keep_fraction(
                                      node, "selective") < 1.0):
            bwd += fwd

        # DCN subfactors of each axis for the candidate being costed (clamped
        # when this op's sharding does not span the full axis)
        tp_dcn = self.tp_dcn if sh.tp % self.tp_dcn == 0 else 1
        tp_ici = max(sh.tp // tp_dcn, 1)

        # intra-op collective: row-parallel / head-parallel psum of the output
        comm = 0.0
        if sh.kind in ("row", "heads", "table") and sh.tp > 1:
            comm = m.hier_allreduce_time(
                out_bytes // max(sh.dp, 1), tp_ici, tp_dcn,
                nic_sharers=self._nic_sharers(tp_ici))
        elif sh.kind == "ring" and sh.tp > 1:
            # sequence parallel: cost the schedule the emission will pick
            # (ring k/v rotation or all-to-all head re-partition) so the
            # DP's numbers match the executed program
            _, comm = sequence_schedule(node, in_shapes, sh, m,
                                        tp_dcn=tp_dcn)
        elif sh.kind == "expert" and sh.tp > 1:
            # expert parallel: all-to-all token exchange in and out
            comm = 2 * m.hier_alltoall_time(
                in_bytes // deg, tp_ici, tp_dcn,
                nic_sharers=self._nic_sharers(tp_ici))
        elif sh.kind == "spatial" and sh.tp > 1:
            # spatial (height) partition: halo exchange of (kernel_h - 1)
            # boundary input rows with ring neighbors per step (reference:
            # the ghost regions of create_mapping_xfers<Conv2D/Pool2D>,
            # substitution.cc:1797-1800; XLA SPMD materializes them as
            # collective-permutes)
            kh = int(op.attrs.get("kernel_h", 1))
            in0 = in_shapes[0] if in_shapes else None
            if in0 is not None and len(in0) == 4 and in0[2] > 0 and kh > 1:
                row_bytes = int(np.prod(in0)) * el // in0[2]
                comm = m.p2p_time((kh - 1) * row_bytes // max(sh.dp, 1),
                                  "ici")

        # every forward activation collective has a mirror in backward
        # (Megatron's f/g conjugate operators; ring attention re-rotates k/v
        # and reduces dk/dv; EP re-runs the token all-to-all) — the
        # reference prices fwd and bwd comm separately (simulator.cc:489,537)
        comm *= 2.0

        # gradient sync: weights replicated over dp -> allreduce over dp;
        # ring attention, spatial partitioning and pass-through SP states
        # replicate weights over tp too, so their grads reduce over dp*tp
        sync = 0.0
        sync_n = sh.dp * (sh.tp if sh.kind in ("ring", "spatial")
                          else sh.act_tp)
        if w_bytes and sync_n > 1:
            spans_tp = sh.kind in ("ring", "spatial") or sh.act_tp > 1
            sync_dcn = (self.dp_dcn if sh.dp % self.dp_dcn == 0 else 1) * \
                (tp_dcn if spans_tp else 1)
            if sync_n % sync_dcn != 0:
                sync_dcn = 1
            sync_ici = sync_n // sync_dcn
            sync = m.hier_allreduce_time(
                w_bytes // w_div, sync_ici, sync_dcn,
                nic_sharers=self._nic_sharers(sync_ici))

        # optimizer step: elementwise over this op's weight shard, HBM-bound
        # (reference prices update explicitly via optimizer kernels,
        # src/runtime/optimizer_kernel.cu) — at BERT-Large scale Adam moves
        # ~7x the weight bytes and is a double-digit % of the step
        # the 7-stream update runs at the machine's MEASURED multi-stream
        # HBM fraction, not the single-stream hbm_efficiency (2.3x DLRM
        # under-pricing otherwise — see TPUMachineModel.update_hbm_efficiency)
        update = 0.0
        if w_bytes:
            update = (self.update_bytes_factor * w_bytes / w_div
                      / (m.hbm_bandwidth * m.update_hbm_efficiency))

        return CostMetrics(
            forward_time=fwd, backward_time=bwd, sync_time=sync,
            comm_time=comm, update_time=update,
            inputs_memory=int(in_bytes / deg),
            outputs_memory=int(out_bytes / deg),
            weights_memory=int(w_bytes / w_div))

    # ----------------------------------------------------- transition costs
    def resharding_cost(self, bytes_total: int, src_state: str,
                        dst_state: str, dp: int, tp: int) -> float:
        """Cost of moving an activation between sharding states.

        States: 'R' = sharded over data only (replicated over model axis),
        'S' = additionally sharded over the model (hidden) axis, 'Q' =
        additionally sharded over the sequence dim, 'H' = over the spatial
        height dim (NCHW CNNs). These transitions are the Repartition/
        Combine/AllToAll parallel ops of the reference (src/parallel_ops/):
        R->{S,Q,H} is a local slice (free), {S,Q,H}->R is an all-gather
        over tp, and any sharded<->differently-sharded pair is an
        all-to-all over tp.
        """
        if src_state == dst_state or tp <= 1:
            return 0.0
        key = (bytes_total, src_state, dst_state, dp, tp, self.tp_dcn)
        cached = self._reshard_cache.get(key)
        if cached is not None:
            self._reshard_cache.move_to_end(key)
            return cached
        per_chip = bytes_total // max(dp * tp, 1)
        tp_dcn = self.tp_dcn if tp % self.tp_dcn == 0 else 1
        tp_ici = max(tp // tp_dcn, 1)
        sharers = self._nic_sharers(tp_ici)
        if dst_state == "R":
            cost = self.machine.hier_allgather_time(per_chip, tp_ici, tp_dcn,
                                                    nic_sharers=sharers)
        elif src_state == "R":
            cost = 0.0  # R->S / R->Q: local slice
        else:  # S<->Q
            cost = self.machine.hier_alltoall_time(per_chip, tp_ici, tp_dcn,
                                                   nic_sharers=sharers)
        if self.cost_cache_size > 0:
            self._reshard_cache[key] = cost
            if len(self._reshard_cache) > self.cost_cache_size:
                self._reshard_cache.popitem(last=False)
        return cost

    # ------------------------------------------------------- whole-graph sim
    def simulate(self, pcg: PCG,
                 assignment: Dict[int, OpSharding],
                 states: Optional[Dict[int, str]] = None
                 ) -> Tuple[float, int]:
        """Estimate one training-step time (s) and per-chip memory (bytes)
        for a full per-op assignment (reference: simulate_runtime,
        simulator.cc:815). Sequential compute + exposed communication; with
        ``--overlap`` gradient sync hides behind backward compute."""
        total_compute = 0.0
        total_comm = 0.0
        total_sync = 0.0
        total_bwd = 0.0
        total_update = 0.0
        resident_w = 0
        resident_act = 0
        transient = 0
        states = states or {}
        el_cache: Dict[int, CostMetrics] = {}
        for node in pcg.compute_nodes():
            sh = assignment.get(node.guid, OpSharding())
            in_shapes = [pcg.nodes[g].out_shapes[i] for g, i in node.inputs]
            cm = self.op_cost(node, in_shapes, sh)
            el_cache[node.guid] = cm
            total_compute += cm.forward_time + cm.backward_time
            total_bwd += cm.backward_time
            total_comm += cm.comm_time
            total_sync += cm.sync_time
            total_update += cm.update_time
            # Per-chip peak memory, liveness-aware (validated against XLA's
            # Compiled.memory_analysis peak, which is ~ arguments + temps
            # with donated outputs aliased):
            #  - weights: master param + optimizer moments resident all step
            #    (f32 p/m/v under Adam = x(1 + opt_state_words)), plus every
            #    weight GRAD in the compute dtype — XLA materializes all of
            #    them before the optimizer-update phase consumes them
            #  - activations: every saved-for-backward output is live at
            #    once when backward starts, in the COMPUTE dtype (bf16
            #    halves it under mixed precision) — x1, not x2: activation
            #    grads are freed as backward consumes them. Remat scales
            #    this by the keep-fraction; `full`-level nodes keep nothing
            #    here (block boundaries + recompute transient added below)
            #  - transient: the widest node's working set (its output grad +
            #    recomputed output + weight grad)
            act = self.act_bytes(node, cm)
            wgrad = self.scaled_bytes(cm.weights_memory, node)
            resident_act += int(act * self.remat_keep_fraction(node,
                                                               sh.remat))
            resident_w += cm.weights_memory * (1 + self.opt_state_words) \
                + wgrad
            transient = max(transient, 2 * act + wgrad)
            # resharding on input edges (against the state the op consumes)
            my_state = op_in_state(sh, states.get(node.guid, "R"))
            for g, i in node.inputs:
                src = pcg.nodes[g]
                if src.op.op_type in (OperatorType.OP_INPUT,
                                      OperatorType.OP_WEIGHT):
                    continue
                src_state = states.get(g, "R")
                nbytes = int(np.prod(src.out_shapes[i])) * size_of_datatype(
                    src.op.data_type)
                # x2: the backward pass runs the transposed resharding
                total_comm += 2 * self.resharding_cost(
                    nbytes, src_state, my_state, sh.dp, sh.tp)
        # `full`-remat blocks: jax.checkpoint(nothing_saveable) over the
        # SAME segments the Executor cuts (execution.remat.remat_segments —
        # one segmentation, two consumers) saves only each block's exposed
        # boundary outputs; during a block's backward the whole block's
        # activations rematerialize transiently. Price exactly that: every
        # cross-block-consumed tensor (the Executor's `needed` set — a
        # forced, non-bottleneck cut can expose several per boundary, e.g.
        # a skip connection) plus the graph sinks stay resident, and the
        # widest block is the transient floor.
        full_guids = {g for g, s in assignment.items()
                      if getattr(s, "remat", "none") == "full"}
        if full_guids:
            segs = self._remat_segments_for(pcg)
            seg_of = {g: k for k, seg in enumerate(segs) for g in seg}
            boundary: Set[int] = set()
            for n in pcg.compute_nodes():
                k = seg_of.get(n.guid)
                for pg, _i in n.inputs:
                    pk = seg_of.get(pg)
                    if pk is not None and pk != k:
                        boundary.add(pg)
            boundary.update(n.guid for n in pcg.sinks()
                            if n.guid in seg_of)
            for seg in segs:
                seg_live = sum(self.act_bytes(pcg.nodes[g], el_cache[g])
                               for g in seg
                               if g in full_guids and g in el_cache)
                transient = max(transient, seg_live)
            resident_act += sum(
                self.act_bytes(pcg.nodes[g], el_cache[g])
                for g in boundary if g in full_guids and g in el_cache)
        if getattr(self, "block_overlap", False):
            # collective-compute overlap (--collective-overlap on):
            # gradient psums issue per remat block as each block's
            # backward completes (executor._blockwise_value_and_grad), so
            # all but the LAST block's sync hides behind the remaining
            # backward compute; the tail block's reduction is always
            # exposed (nothing left to hide behind — with ONE block the
            # executor genuinely hides nothing). K is the executor's own
            # block count — the same segmentation, two consumers
            # (execution.remat.remat_segments).
            k = max(len(self._remat_segments_for(pcg)), 1)
            total_sync = max(total_sync - total_bwd * (k - 1) / k,
                             total_sync / k)
        elif self.overlap:
            # legacy --overlap (overlap backward with optimizer update):
            # the coarse pre-ISSUE 10 hiding model, kept verbatim so
            # existing --overlap users' rankings don't shift
            total_sync = max(0.0, total_sync - 0.7 * total_bwd)
        return (total_compute + total_comm + total_sync + total_update,
                resident_w + resident_act + transient)

    def simulate_event_driven(self, pcg: PCG,
                              assignment: Dict[int, OpSharding],
                              states: Optional[Dict[int, str]] = None
                              ) -> float:
        """Event-driven makespan via the native task-graph core
        (reference: simulate_runtime's per-device timelines). Two logical
        execution units per chip: the compute stream (0) and the async
        collective/DMA stream (1) — collectives overlap independent compute,
        which the additive model in simulate() cannot express."""
        from ..ffconst import size_of_datatype
        from ..native import simulate_taskgraph

        states = states or {}
        nodes = pcg.compute_nodes()
        idx = {}
        costs: List[float] = []
        devs: List[int] = []
        esrc: List[int] = []
        edst: List[int] = []
        cm_cache: Dict[int, CostMetrics] = {}

        def add_task(cost: float, dev: int) -> int:
            costs.append(cost)
            devs.append(dev)
            return len(costs) - 1

        for node in nodes:
            sh = assignment.get(node.guid, OpSharding())
            in_shapes = [pcg.nodes[g].out_shapes[i] for g, i in node.inputs]
            cm = self.op_cost(node, in_shapes, sh)
            cm_cache[node.guid] = cm
            fwd = add_task(cm.forward_time, 0)
            idx[node.guid] = fwd
            if cm.comm_time > 0:
                comm = add_task(cm.comm_time, 1)
                esrc.append(fwd)
                edst.append(comm)
                idx[node.guid] = comm  # consumers wait for the collective
            my_state = op_in_state(sh, states.get(node.guid, "R"))
            for g, i in node.inputs:
                if g not in idx:
                    continue
                src_task = idx[g]
                # resharding between states rides the collective stream
                # (reference: comm SimTasks between differently-viewed
                # producer/consumer shards, simulator.cc:815)
                src_state = states.get(g, "R")
                if src_state != my_state:
                    src_node = pcg.nodes[g]
                    nbytes = int(np.prod(src_node.out_shapes[i])) * \
                        size_of_datatype(src_node.op.data_type)
                    # x2: the backward pass runs the transposed resharding
                    xfer = 2 * self.resharding_cost(
                        nbytes, src_state, my_state, sh.dp, sh.tp)
                    if xfer > 0:
                        r = add_task(xfer, 1)
                        esrc.append(src_task)
                        edst.append(r)
                        src_task = r
                esrc.append(src_task)
                edst.append(fwd)
        # backward + sync: mirror the forward chain; grad allreduces go on the
        # collective stream and overlap the rest of the backward pass
        bwd_prev = None
        for node in reversed(nodes):
            cm = cm_cache[node.guid]
            bwd = add_task(cm.backward_time, 0)
            if bwd_prev is not None:
                esrc.append(bwd_prev)
                edst.append(bwd)
            else:
                esrc.append(idx[nodes[-1].guid])
                edst.append(bwd)
            bwd_prev = bwd
            last = bwd
            if cm.sync_time > 0:
                sync = add_task(cm.sync_time, 1)
                esrc.append(bwd)
                edst.append(sync)
                last = sync
            if cm.update_time > 0:
                # optimizer update streams HBM on the compute stream once
                # the (synced) grads are ready
                upd = add_task(cm.update_time, 0)
                esrc.append(last)
                edst.append(upd)
        return simulate_taskgraph(
            np.asarray(costs), np.asarray(devs), 2,
            np.asarray(esrc, dtype=np.int32),
            np.asarray(edst, dtype=np.int32))

    # -------------------------------------------- measured mode (on device)
    @staticmethod
    def _op_key(node: PCGNode, in_shapes: List[Tuple[int, ...]]) -> Tuple:
        return (node.op.params_key(), tuple(map(tuple, in_shapes)))

    def calibrate_from_pcg(self, pcg: PCG, max_ops: int = 64,
                           compute_dtype=None) -> int:
        """Measure every distinct op shape in the graph on the current backend
        and store per-key measured/analytical ratios, so ``op_cost`` returns
        device-calibrated times (reference: Simulator::measure_operator_cost
        ground truth feeding graph_cost, simulator.cc:489). Returns the number
        of distinct ops measured. Cheap on repetitive graphs: BERT-Large has
        ~7 distinct op shapes across 24 layers.

        Also records the compute dtype's element size for the peak-memory
        model (saved activations live in the compute dtype)."""
        # flush the delta-cost tables on both sides of calibration: entries
        # priced before the per-key ratios land are stale the moment they do
        self.invalidate_cost_tables()
        if compute_dtype is not None:
            import jax.numpy as jnp

            self.activation_el = jnp.dtype(compute_dtype).itemsize
        from ..obs import get_tracer

        tracer = get_tracer()
        measured = 0
        for node in pcg.compute_nodes():
            in_shapes = [pcg.nodes[g].out_shapes[i] for g, i in node.inputs]
            key = self._op_key(node, in_shapes)
            if key in self._key_calibration:
                continue
            if measured >= max_ops:
                break
            # calibrate against the ROOFLINE term alone: op_cost predicts
            # roofline*cal + op_overhead, so the ratio must be computed on
            # (measured - overhead)/roofline or calibrated predictions
            # would not reproduce the measurement for small ops
            analytical = self.op_cost(node, in_shapes,
                                      OpSharding()).forward_time \
                - self.op_overhead
            if analytical <= 0:
                continue
            try:
                t = self.measure_operator_cost(node, in_shapes,
                                               compute_dtype=compute_dtype)
            except Exception:
                continue  # op not measurable standalone (e.g. host-side)
            if t > 0:
                self._key_calibration[key] = \
                    max(t - self.op_overhead, 0.1 * t) / analytical
                measured += 1
                if tracer.enabled:
                    # calibration record: how far the roofline was off for
                    # this op shape (the search's ground-truth anchor)
                    tracer.event(
                        "op_calibration", op=node.name,
                        op_type=node.op.op_type.name,
                        measured_us=round(t * 1e6, 2),
                        analytical_us=round(
                            (analytical + self.op_overhead) * 1e6, 2),
                        ratio=round(self._key_calibration[key], 4))
                # measured backward: time fwd+bwd together (what training
                # compiles) and store the bwd/fwd ratio, replacing the
                # flat 2x heuristic (reference: simulator.cc:537)
                try:
                    tg = self.measure_operator_cost(
                        node, in_shapes, compute_dtype=compute_dtype,
                        direction="grad")
                except Exception:
                    continue  # not differentiable standalone — keep 2x
                if tg > t:
                    # clamp to the physically plausible band (bwd recomputes
                    # ~2 forward-sized passes plus extra HBM traffic) so a
                    # noisy micro-measurement cannot distort the ranking
                    self._key_bwd_ratio[key] = min(
                        max((tg - t) / t, 0.25), 4.0)
        self.invalidate_cost_tables()
        return measured

    def calibrate_from_profile(self, profile, pcg: PCG,
                               min_rel_change: float = 0.05
                               ) -> Dict[str, Any]:
        """Fold MEASURED per-op timings (an ``obs.profile.OpProfile`` —
        the ProfiledStep pass of a live fit, or a ``--profile-ops`` JSONL
        replayed via ``--calibrate-from-trace``) back into the per-key
        calibration, closing the loop the PR 1 tracer opened (ISSUE 8,
        ROADMAP item 2): records join the graph on
        ``repr(_op_key(node, in_shapes))`` — the SAME signature the
        op-cost cache is keyed by — and each matched key's ratio is
        re-derived from the measurement at the record's own sharding/dcn.

        Only keys whose calibration moves by more than ``min_rel_change``
        (relative) are updated, and ONLY their delta-cost cache entries
        are invalidated (``invalidate_op_keys`` — no full flush; the
        selfcheck env gate re-derives every later hit, so a stale entry
        cannot survive unnoticed). Returns ``{matched, updated,
        invalidated, updates}``; ``updates`` lists
        ``(key_repr, old_cal, new_cal)``."""
        records = getattr(profile, "latest_by_key", None)
        by_key = (records() if records is not None
                  else {r.key: r for r in profile})
        node_map: Dict[str, Tuple[PCGNode, List, Tuple]] = {}
        for node in pcg.compute_nodes():
            in_shapes = [pcg.nodes[g].out_shapes[i] for g, i in node.inputs]
            k = self._op_key(node, in_shapes)
            node_map.setdefault(repr(k), (node, in_shapes, k))
        matched = 0
        moved: Dict[Tuple, Tuple[float, float]] = {}
        updates = []
        for krepr, rec in by_key.items():
            ent = node_map.get(krepr)
            if ent is None:
                continue
            node, in_shapes, key = ent
            matched += 1
            sh_d = dict(rec.sharding or {})
            sh = OpSharding(
                dp=int(sh_d.get("dp", 1)), tp=int(sh_d.get("tp", 1)),
                kind=str(sh_d.get("kind", "none")),
                act_tp=int(sh_d.get("act_tp", 1)),
                remat=str(sh_d.get("remat", "none")))
            old_dcn = (self.dp_dcn, self.tp_dcn)
            self.set_axis_topology(*(rec.dcn or (1, 1)))
            try:
                predicted = self.op_cost(node, in_shapes, sh).forward_time
            finally:
                self.set_axis_topology(*old_dcn)
            cal_old = self._key_calibration.get(key, self.calibration)
            roofline = (predicted - self.op_overhead) / max(cal_old, 1e-12)
            t = float(rec.measured_fwd_s)
            if roofline <= 0 or t <= 0:
                continue
            cal_new = max(t - self.op_overhead, 0.1 * t) / roofline
            if abs(cal_new - cal_old) <= min_rel_change * \
                    max(abs(cal_old), 1e-12):
                continue
            self._key_calibration[key] = cal_new
            moved[key] = (cal_old, cal_new)
            updates.append((krepr, cal_old, cal_new))
        inval = (self.invalidate_op_keys(moved)
                 if moved else {"cost_entries": 0, "table_entries": 0})
        from ..obs import get_tracer

        tracer = get_tracer()
        if tracer.enabled and moved:
            tracer.event(
                "calibration_applied", matched=matched, updated=len(moved),
                cost_entries_invalidated=inval["cost_entries"],
                table_entries_invalidated=inval["table_entries"])
        return {"matched": matched, "updated": len(moved),
                "invalidated": inval, "updates": updates}

    def measure_operator_cost(self, node: PCGNode,
                              in_shapes: List[Tuple[int, ...]],
                              iters: Optional[int] = None,
                              compute_dtype=None,
                              direction: str = "fwd") -> float:
        """Time one op standalone on the current backend, cached by params key
        (reference: measure_operator_cost, simulator.cc:489 — cudaEvents;
        ``direction="grad"`` mirrors inner_measure_operator_cost running both
        directions, model.cu:38 — it times value_and_grad, i.e. fwd+bwd
        together, the shape XLA actually compiles in training).

        All ``iters`` applications run inside ONE jitted ``lax.scan`` whose
        carry chains each iteration's inputs to the previous output's
        sum-of-squares — the data dependency serializes iterations and
        defeats both CSE and XLA's slice/reduction factoring (a plain sum of
        a matmul is algebraically reducible to a cheap vector dot; a [0]
        slice computes one element). Tunneled TPU platforms add a ~75 ms
        round trip per call under which async dispatch hides device work, so
        ``iters`` is sized from the analytical estimate to push total device
        time well past the round trip, which is separately measured with an
        identity jit and subtracted."""
        key = self._op_key(node, in_shapes) + (str(compute_dtype), direction)
        if key in self._measure_cache:
            return self._measure_cache[key]
        import time

        import jax
        import jax.numpy as jnp

        from ..ffconst import dtype_to_jnp
        from ..ops.base import OpContext

        op = node.op
        dt = compute_dtype or dtype_to_jnp(op.data_type)
        xs = [jnp.ones(s, dt) for s in in_shapes]
        params = {}
        key_rng = jax.random.PRNGKey(0)
        for wname, (shape, wdt, init) in op.weight_specs(in_shapes).items():
            w = init(key_rng, shape, dtype_to_jnp(wdt))
            if compute_dtype is not None and jnp.issubdtype(
                    w.dtype, jnp.floating):
                w = w.astype(compute_dtype)
            params[wname] = w
        ctx = OpContext(training=False)
        float_ix = [i for i, x in enumerate(xs)
                    if jnp.issubdtype(x.dtype, jnp.floating)]
        if direction == "grad" and not params and not float_ix:
            raise ValueError(f"{op.name}: nothing differentiable to time")

        def make_f(n_iters):
            @jax.jit
            def f(params, xs):
                def body(carry, _):
                    cur, acc = carry
                    outs = op.forward(params, cur, ctx)
                    leaf = jax.tree_util.tree_leaves(outs)[0].astype(
                        jnp.float32)
                    s = jnp.vdot(leaf, leaf) * 1e-30
                    nxt = [x * (1.0 + s).astype(x.dtype) if jnp.issubdtype(
                        x.dtype, jnp.floating) else x for x in cur]
                    return (nxt, acc + s), ()

                (_, acc), _ = jax.lax.scan(body, (list(xs), jnp.zeros(())),
                                           None, length=n_iters)
                return acc

            if direction != "grad":
                return f

            @jax.jit
            def g(params, xs):
                def body(carry, _):
                    cur, acc = carry

                    def loss(p, fl):
                        full = list(cur)
                        for j, i in enumerate(float_ix):
                            full[i] = fl[j]
                        outs = op.forward(p, full, ctx)
                        leaf = jax.tree_util.tree_leaves(outs)[0].astype(
                            jnp.float32)
                        return jnp.vdot(leaf, leaf)

                    val, (gp, gx) = jax.value_and_grad(loss, argnums=(0, 1))(
                        params, [cur[i] for i in float_ix])
                    # fold EVERY grad leaf into the carry: an unused leaf
                    # would let XLA dead-code-eliminate its slice of the
                    # backward pass (e.g. the dgrad matmul) and under-count
                    # the ratio
                    gleaves = jax.tree_util.tree_leaves((gp, gx))
                    gsum = val
                    for gl in gleaves:
                        glf = gl.astype(jnp.float32)
                        gsum = gsum + jnp.vdot(glf, glf)
                    s = gsum * 1e-30
                    nxt = [x * (1.0 + s).astype(x.dtype) if jnp.issubdtype(
                        x.dtype, jnp.floating) else x for x in cur]
                    return (nxt, acc + s), ()

                (_, acc), _ = jax.lax.scan(body, (list(xs), jnp.zeros(())),
                                           None, length=n_iters)
                return acc
            return g

        def timed(fn, *args):
            out = fn(*args)  # compile + settle
            _ = float(np.asarray(out))
            best = float("inf")
            for _i in range(3):
                t0 = time.perf_counter()
                out = fn(*args)
                _ = float(np.asarray(out))
                best = min(best, time.perf_counter() - t0)
            return best

        if self._dispatch_overhead is None:
            ident = jax.jit(lambda x: x * 1.000001)
            probe = jnp.ones((8, 8), jnp.float32)
            self._dispatch_overhead = timed(
                lambda x: jnp.sum(ident(x)), probe)
        overhead = self._dispatch_overhead
        if iters is None:
            if overhead < 0.01:
                # local backend (CPU mesh / directly-attached chip): a small
                # probe gives real per-iter signal without long scans — the
                # analytical estimate uses TPU peak rates and would oversize
                # the iteration count by ~1000x on CPU
                iters = 8
            else:
                # tunneled TPU: ~75 ms RTT hides device work under async
                # dispatch, so size total device time well past it from the
                # analytical estimate (near-truth on the real chip)
                est = self.op_cost(node, in_shapes,
                                   OpSharding()).forward_time
                if direction == "grad":
                    est *= 3.0
                target = max(5.0 * overhead, 0.4)
                iters = int(min(max(target / max(est, 1e-6), 16), 4096))
        total = timed(make_f(iters), params, xs)
        t = max((total - overhead) / iters, 1e-7)
        self._measure_cache[key] = t
        return t

    def calibrate(self, measured_step: float, simulated_step: float) -> None:
        """Scale the analytical model so simulated == measured for a known
        config (replaces cudaEvent ground truth)."""
        if simulated_step > 0:
            self.calibration *= measured_step / simulated_step
