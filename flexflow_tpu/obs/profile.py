"""Per-op measured profiling: the observability loop's ground-truth side.

``Executor.profile_ops`` (the ProfiledStep mode, ISSUE 8) times every
``jax.named_scope``'d compute node on device — block-until-ready per node,
amortized over N repeats, dispatch overhead subtracted — and this module
turns those raw timings into :class:`OpRecord`\\ s keyed by the SAME
``(op params, in-shapes, OpSharding, dcn)`` signature the Simulator's
op-cost cache uses (``Simulator.op_cost``'s key, docs/search.md), so
measured and predicted costs join on one key with no fuzzy matching.

Records flow three ways (docs/calibration.md):

* the process tracer — one retroactive Perfetto span per profiled op;
* a JSONL profile file (``--profile-ops PATH``) — the artifact
  ``--calibrate-from-trace`` replays into ``calibrate_from_profile``;
* the in-process drift sentinel (``obs.drift``) — predicted-vs-measured
  ratios, the ``calibration`` telemetry block, and (opt-in) closed-loop
  simulator recalibration.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple


@dataclasses.dataclass
class OpRecord:
    """One profiled op shape. ``key`` is ``repr(Simulator._op_key(node,
    in_shapes))`` — the string form of the per-key calibration index, and
    the join column between a JSONL profile and a live graph's cost
    model. ``sharding``/``dcn`` complete the op-cost cache signature the
    measurement was taken under."""

    name: str
    op_type: str
    key: str
    in_shapes: List[List[int]]
    sharding: Dict[str, Any]
    dcn: Tuple[int, int]
    measured_fwd_s: float
    predicted_fwd_s: Optional[float] = None
    count: int = 1  # nodes sharing this key (BERT's 24 layers -> 1 record)
    step: int = 0
    generation: str = ""
    dtype: str = ""

    def to_json(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["event"] = "op_profile"
        d["dcn"] = list(self.dcn)
        return d

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "OpRecord":
        fields = {f.name for f in dataclasses.fields(OpRecord)}
        kw = {k: v for k, v in d.items() if k in fields}
        kw["dcn"] = tuple(kw.get("dcn") or (1, 1))
        kw["in_shapes"] = [list(s) for s in kw.get("in_shapes", [])]
        return OpRecord(**kw)


class OpProfile:
    """A set of :class:`OpRecord`\\ s — what ``calibrate_from_profile``
    consumes and what ``--profile-ops`` streams as JSONL (one record per
    line, append mode: successive profiled passes of one run land in one
    file, distinguished by ``step``)."""

    def __init__(self, records: Optional[List[OpRecord]] = None):
        self.records: List[OpRecord] = list(records or [])

    def __len__(self) -> int:
        return len(self.records)

    def latest_by_key(self) -> Dict[str, OpRecord]:
        """Last-written record per join key — later profiled passes
        supersede earlier ones when a file holds several."""
        out: Dict[str, OpRecord] = {}
        for r in self.records:
            out[r.key] = r
        return out

    def write_jsonl(self, path: str, append: bool = True) -> str:
        with open(path, "a" if append else "w") as f:
            for r in self.records:
                f.write(json.dumps(r.to_json(), default=str) + "\n")
        return path

    @staticmethod
    def read_jsonl(path: str) -> "OpProfile":
        """Load a profile file; unknown event kinds and malformed lines
        are skipped (the tracer's JSONL sink interleaves other events)."""
        records = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    d = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if d.get("event") not in (None, "op_profile") or \
                        "measured_fwd_s" not in d or "key" not in d:
                    continue
                try:
                    records.append(OpRecord.from_json(d))
                except (TypeError, ValueError):
                    # valid JSON but not a complete record (hand-edited,
                    # foreign writer): skipped like any malformed line
                    continue
        return OpProfile(records)


def live_assignment(model) -> Tuple[Dict[int, Any], Tuple[int, int]]:
    """Per-node ``OpSharding`` of the LIVE plan plus its dcn placement —
    what keys this model's measured costs against the simulator's.

    A searched compile keeps the winner's exact per-op assignment on
    ``model._search_result`` (unity_search adopts the rewritten graph into
    the model's PCG in place, so the guids align); a data-parallel or
    imported strategy falls back to ``OpSharding(dp=<data-axis size>)``
    with the resolved remat level — the same sharding the dp baseline is
    priced under."""
    from ..search.simulator import OpSharding

    pcg = model.pcg
    plan = getattr(model.executor, "remat_plan", None)
    if plan is not None:
        remat = plan.level
    else:
        remat = (getattr(model.strategy, "remat", "") or "none")
    res = getattr(model, "_search_result", None)
    if res is not None and res.assignment:
        a = {g: sh for g, sh in res.assignment.items() if g in pcg.nodes}
        if a:
            out = {n.guid: a.get(n.guid, OpSharding(remat=remat))
                   for n in pcg.compute_nodes()}
            return out, tuple(res.dcn)
    dp = 1
    if model.mesh is not None and model.strategy is not None:
        try:
            dp = int(model.mesh.shape[model.strategy.data_axis])
        except (KeyError, TypeError):
            dp = 1
    return ({n.guid: OpSharding(dp=dp, remat=remat)
             for n in pcg.compute_nodes()}, (1, 1))


def profile_model(model, device_xs, iters: int = 3, step: int = 0,
                  sim=None) -> List[OpRecord]:
    """Run one ProfiledStep pass over the model's graph and assemble
    join-keyed :class:`OpRecord`\\ s. ``device_xs`` is one input batch at
    the compiled batch size (device-put with the executor's shardings).
    When ``sim`` is given each record also carries the simulator's
    predicted forward time under the live sharding — the profile file is
    then self-contained for post-hoc drift analysis."""
    from ..search.simulator import Simulator

    raw = model.executor.profile_ops(model.params, device_xs, iters=iters)
    assignment, dcn = live_assignment(model)
    generation = ""
    dtype = ""
    if sim is not None:
        generation = getattr(sim.machine, "generation", "") or ""
        dtype = getattr(sim, "dtype_label", "") or ""
    records: List[OpRecord] = []
    for r in raw:
        node = model.pcg.nodes[r["guid"]]
        sh = assignment.get(r["guid"])
        if sh is None:
            continue
        predicted = None
        if sim is not None:
            old = (sim.dp_dcn, sim.tp_dcn)
            sim.set_axis_topology(*dcn)
            try:
                predicted = sim.op_cost(node, r["in_shapes"],
                                        sh).forward_time
            finally:
                sim.set_axis_topology(*old)
        records.append(OpRecord(
            name=r["name"], op_type=r["op_type"],
            key=repr(Simulator._op_key(node, r["in_shapes"])),
            in_shapes=[list(s) for s in r["in_shapes"]],
            sharding=dataclasses.asdict(sh), dcn=tuple(dcn),
            measured_fwd_s=r["measured_fwd_s"],
            predicted_fwd_s=predicted, count=r["count"], step=step,
            generation=generation, dtype=dtype))
    return records
