"""flexflow_tpu.obs: tracing + telemetry subsystem.

The runtime's observability layer (the Legion Prof / per-op ``--profiling``
analog, SURVEY §1 L0):

* ``trace``: thread-safe span/event tracer with Chrome trace-event JSON
  export (Perfetto-loadable) and a JSONL event sink. Disabled by default via
  a no-op singleton — ``enable()`` swaps in a live tracer.
* ``telemetry``: per-step training telemetry (wall times, loss history,
  compile-vs-steady split, samples/sec, estimated MFU, XLA peak memory) and
  the Unity/MCMC per-iteration search log.
* ``reqtrace``: request-level distributed tracing for the serving stack
  (ISSUE 16) — per-request lifecycle timelines finalized into a versioned
  ``RequestRecord`` JSONL stream + Perfetto spans, plus the fleet's
  per-tick ``FleetTimeSeries`` ring buffers. Disabled by default via the
  same no-op-singleton idiom — ``enable_reqtrace()`` swaps in a live
  recorder.
* xprof passthroughs: ``start_server`` / ``start_trace`` / ``stop_trace`` /
  ``trace`` wrap ``jax.profiler`` so per-op ``jax.named_scope`` annotations
  (Executor.forward_outputs) show up in XLA/xprof traces.

Nothing in this package allocates in the jitted path; all instrumentation is
host-side and gated on ``get_tracer().enabled``.
"""
from .trace import (NoopTracer, Tracer, atomic_write_json,  # noqa: F401
                    disable, enable, get_tracer, set_tracer)
from .reqtrace import (FleetTimeSeries, NoopRequestTrace,  # noqa: F401
                       RequestTrace, disable_reqtrace, enable_reqtrace,
                       get_reqtrace, set_reqtrace)
from .telemetry import (SearchLog, StepTelemetry,  # noqa: F401
                        capture_memory_analysis, detect_peak_flops,
                        model_flops_per_step)


def start_server(port: int = 9012):
    """Start the xprof/TensorBoard profiler server (jax.profiler
    passthrough); connect with TensorBoard's profile tab or xprof."""
    import jax

    return jax.profiler.start_server(port)


def start_trace(log_dir: str, **kwargs) -> None:
    """Begin an XLA profiler trace into ``log_dir`` (jax.profiler
    passthrough). Per-op names from Executor's ``jax.named_scope`` wrapping
    appear in the resulting xprof timeline."""
    import jax

    jax.profiler.start_trace(log_dir, **kwargs)


def stop_trace() -> None:
    import jax

    jax.profiler.stop_trace()


def trace(log_dir: str, **kwargs):
    """Context manager variant: ``with obs.trace(dir): ...`` (jax.profiler
    passthrough)."""
    import jax

    return jax.profiler.trace(log_dir, **kwargs)


trace_dir = trace  # surface alias: obs.trace_dir(dir) reads naturally too
