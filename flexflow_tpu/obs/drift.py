"""Sim-vs-measured drift sentinel + the closed calibration loop.

``DriftSentinel`` (ISSUE 8, docs/calibration.md) compares the Simulator's
predicted per-op forward cost against measured ProfiledStep timings — the
two sides join on the op-cost cache key (``obs.profile``) — and maintains
rolling per-key ratios. Drift beyond ``--drift-tolerance`` becomes a
first-class, alertable signal: ``calibration_drift`` tracer events per
out-of-band key, a ``calibration`` block in StepTelemetry, and the
trace_summary digest — instead of a post-hoc bench artifact (the
BENCH sim_vs_measured trajectory VERDICT.md flagged at 1.271x).

``CalibrationLoop`` is the fit loop's orchestrator: one ProfiledStep pass
per fit (amortized per-op timings), sentinel evaluation, and — with
``--auto-recalibrate`` — closed-loop repair: ``calibrate_from_profile``
folds the measured ratios into the per-key calibration, invalidating ONLY
the delta-cost cache entries whose keys moved, persists the repaired
table (``--calibration-dir``), and re-ranks the search's top-K fallback
chain against the repaired costs when a searched strategy is live.

Ratio convention: ``measured / predicted`` — 1.0 is a perfect ruler,
> 1 means the simulator under-prices the op. A key is out of band when
its rolling ratio leaves ``[1/(1+tol), 1+tol]``.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .trace import get_tracer


class DriftSentinel:
    """Rolling predicted-vs-measured comparison for one (sim, graph)."""

    WINDOW = 8  # rolling ratio window per key

    def __init__(self, sim, pcg, tolerance: float = 0.25):
        self.sim = sim
        self.pcg = pcg
        self.tolerance = float(tolerance)
        self._node_map: Optional[Dict[str, Tuple]] = None
        # key_repr -> recent per-pass ratios (newest last)
        self.history: Dict[str, List[float]] = {}

    def _nodes(self) -> Dict[str, Tuple]:
        if self._node_map is None:
            m: Dict[str, Tuple] = {}
            for node in self.pcg.compute_nodes():
                in_shapes = [self.pcg.nodes[g].out_shapes[i]
                             for g, i in node.inputs]
                m.setdefault(repr(self.sim._op_key(node, in_shapes)),
                             (node, in_shapes))
            self._node_map = m
        return self._node_map

    def _predict(self, rec) -> Optional[float]:
        from ..search.simulator import OpSharding

        ent = self._nodes().get(rec.key)
        if ent is None:
            return None
        node, in_shapes = ent
        sh_d = dict(rec.sharding or {})
        sh = OpSharding(
            dp=int(sh_d.get("dp", 1)), tp=int(sh_d.get("tp", 1)),
            kind=str(sh_d.get("kind", "none")),
            act_tp=int(sh_d.get("act_tp", 1)),
            remat=str(sh_d.get("remat", "none")))
        old = (self.sim.dp_dcn, self.sim.tp_dcn)
        self.sim.set_axis_topology(*(rec.dcn or (1, 1)))
        try:
            return self.sim.op_cost(node, in_shapes, sh).forward_time
        finally:
            self.sim.set_axis_topology(*old)

    def ratios(self, records) -> Dict[str, Any]:
        """One-shot predicted-vs-measured evaluation (no history, no
        events) — also the post-repair verification pass: the measured
        side is unchanged, so re-predicting under repaired calibration
        gives the repaired ratio without re-profiling."""
        per_key: Dict[str, Dict[str, Any]] = {}
        tot_meas = 0.0
        tot_pred = 0.0
        for rec in records:
            predicted = self._predict(rec)
            if predicted is None or predicted <= 0:
                continue
            r = rec.measured_fwd_s / predicted
            per_key[rec.key] = {"name": rec.name, "ratio": r,
                                "measured_s": rec.measured_fwd_s,
                                "predicted_s": predicted,
                                "count": rec.count}
            tot_meas += rec.measured_fwd_s * rec.count
            tot_pred += predicted * rec.count
        return {
            "per_key": per_key,
            "aggregate_ratio": (tot_meas / tot_pred) if tot_pred else None,
        }

    def in_band(self, ratio: float) -> bool:
        return 1.0 / (1.0 + self.tolerance) <= ratio <= \
            1.0 + self.tolerance

    def observe(self, records, step: int = 0) -> Dict[str, Any]:
        """Evaluate one profiled pass: fold per-key ratios into the
        rolling history, emit a ``calibration_drift`` tracer event per
        out-of-band key plus an aggregate gauge, and return the summary
        the telemetry block / auto-recalibration consume."""
        ev = self.ratios(records)
        tracer = get_tracer()
        out_of_band: List[str] = []
        worst_key = None
        worst_ratio = None
        worst_dev = -1.0
        for krepr, d in ev["per_key"].items():
            h = self.history.setdefault(krepr, [])
            h.append(d["ratio"])
            del h[:-self.WINDOW]
            rolling = sum(h) / len(h)
            d["rolling_ratio"] = rolling
            dev = max(rolling, 1.0 / rolling) - 1.0 if rolling > 0 \
                else float("inf")
            if dev > worst_dev:
                worst_dev, worst_key, worst_ratio = dev, d["name"], rolling
            if not self.in_band(rolling):
                out_of_band.append(krepr)
                if tracer.enabled:
                    tracer.event(
                        "calibration_drift", op=d["name"], step=step,
                        ratio=round(rolling, 4),
                        measured_us=round(d["measured_s"] * 1e6, 2),
                        predicted_us=round(d["predicted_s"] * 1e6, 2),
                        tolerance=self.tolerance)
        agg = ev["aggregate_ratio"]
        if tracer.enabled and agg is not None:
            tracer.gauge("calibration_aggregate_ratio", round(agg, 4))
        return {
            "profiled_keys": len(ev["per_key"]),
            "aggregate_ratio": agg,
            "worst_key": worst_key,
            "worst_ratio": worst_ratio,
            "out_of_band": out_of_band,
            "tolerance": self.tolerance,
        }

    def forget(self, key_reprs) -> None:
        """Drop rolling history for repaired keys: post-repair passes
        must judge the new ruler, not average it against the old one."""
        for k in key_reprs:
            self.history.pop(k, None)


class CalibrationLoop:
    """Fit-side orchestrator of the closed observability loop."""

    def __init__(self, model):
        from ..search.calibration import build_calibrated_sim

        self.model = model
        cfg = model.config
        # one sim per model, reused across fits (the rolling history and
        # repaired calibration persist); tests inject a perturbed sim here
        sim = getattr(model, "_calibration_sim", None)
        if sim is None:
            sim = build_calibrated_sim(model)
            model._calibration_sim = sim
        self.sim = sim
        self.tolerance = float(
            getattr(cfg, "drift_tolerance", 0.25) or 0.25)
        sent = getattr(model, "_drift_sentinel", None)
        if sent is None or sent.sim is not sim or sent.pcg is not model.pcg:
            sent = DriftSentinel(sim, model.pcg, tolerance=self.tolerance)
            model._drift_sentinel = sent
        sent.tolerance = self.tolerance
        self.sentinel = sent
        self.auto = bool(getattr(cfg, "auto_recalibrate", False))
        self.profile_path = getattr(cfg, "profile_ops", "") or ""
        self.iters = 3
        self.recalibrations = 0
        self.invalidated = 0
        self.ratio_after: Optional[float] = None
        self.last: Optional[Dict[str, Any]] = None

    @classmethod
    def maybe_create(cls, model) -> Optional["CalibrationLoop"]:
        """Armed only by ``--profile-ops`` (SPMD fit path; the GPipe
        trainer is out of scope like the rest of the resilience stack).
        A plain fit pays one getattr."""
        if not (getattr(model.config, "profile_ops", "") or ""):
            return None
        if getattr(model, "_pipeline_trainer", None) is not None:
            return None
        return cls(model)

    def run_pass(self, xs, batch_size: int, telemetry,
                 step: int = 0) -> Optional[Dict[str, Any]]:
        """One ProfiledStep pass: measure -> export (JSONL + tracer
        spans) -> sentinel -> (opt-in) repair + persist + re-rank ->
        telemetry."""
        import jax
        import numpy as np

        from .profile import OpProfile, profile_model

        model = self.model
        n = int(np.asarray(xs[0]).shape[0])
        if n < batch_size:
            import warnings

            warnings.warn(
                f"--profile-ops: dataset ({n} samples) smaller than the "
                f"batch ({batch_size}); skipping the profiled pass")
            return None
        ex = model.executor
        bx = [jax.device_put(np.asarray(a[:batch_size]),
                             ex.batch_sharding(np.asarray(a).ndim))
              for a in xs]
        tracer = get_tracer()
        records = profile_model(model, bx, iters=self.iters, step=step,
                                sim=self.sim)
        if self.profile_path:
            OpProfile(records).write_jsonl(self.profile_path)
        if tracer.enabled:
            for r in records:
                # retroactive Perfetto span per profiled op (ends "now",
                # lasting the measured wall — a readable per-op lane)
                tracer.complete(f"op_profile:{r.name}", r.measured_fwd_s,
                                op_type=r.op_type, count=r.count,
                                step=step)
        drift = self.sentinel.observe(records, step=step)
        if self.auto and drift["out_of_band"]:
            # min_rel_change stays at the simulator's default (0.05), NOT
            # the alert tolerance: the band is multiplicative ([1/(1+tol),
            # 1+tol]) while min_rel_change is relative, so gating repairs
            # at the tolerance leaves a dead zone on the low side (ratio
            # 0.78 at tol=0.25 alerts forever but moves cal only 22% —
            # never repaired, never converges)
            rep = self.sim.calibrate_from_profile(
                OpProfile(records), model.pcg)
            if rep["updated"]:
                self.recalibrations += 1
                self.invalidated += (rep["invalidated"]["cost_entries"]
                                     + rep["invalidated"]["table_entries"])
                self.sentinel.forget(k for k, _o, _n in rep["updates"])
                post = self.sentinel.ratios(records)
                self.ratio_after = post["aggregate_ratio"]
                drift["ratio_after"] = self.ratio_after
                if tracer.enabled:
                    tracer.event(
                        "calibration_repair", step=step,
                        updated=rep["updated"],
                        invalidated=rep["invalidated"],
                        aggregate_ratio_before=drift["aggregate_ratio"],
                        aggregate_ratio_after=self.ratio_after)
                from ..search.calibration import (rerank_candidates,
                                                  store_persistent_calibration)

                if getattr(model.config, "calibration_dir", ""):
                    store_persistent_calibration(self.sim)
                rerank_candidates(model, self.sim)
        self.last = drift
        self._merge_telemetry(telemetry, drift)
        return drift

    def _merge_telemetry(self, telemetry, drift: Dict[str, Any]) -> None:
        if telemetry is None or drift is None:
            return
        telemetry.calib_profiled_keys = drift["profiled_keys"]
        telemetry.calib_aggregate_ratio = drift["aggregate_ratio"]
        telemetry.calib_worst_key = drift["worst_key"]
        telemetry.calib_worst_ratio = drift["worst_ratio"]
        telemetry.calib_out_of_band = len(drift["out_of_band"])
        telemetry.calib_tolerance = drift["tolerance"]
        telemetry.calib_recalibrations = self.recalibrations
        telemetry.calib_invalidated = self.invalidated
        telemetry.calib_ratio_after = self.ratio_after
