"""Step and search telemetry: machine-readable training-run records.

``StepTelemetry`` is filled by ``FFModel.fit``/``eval``: per-step wall time,
loss/metric history, samples/sec, the first-step (jit compile) time split
from steady state, estimated MFU from the analytic cost model, and the
XLA-compiled peak memory (``Executor.train_step_memory_analysis``). The
summary is a plain-JSON dict written to ``--telemetry-file``.

``SearchLog`` is the Unity/MCMC per-iteration log (candidate cost,
accept/reject, temperature, best-so-far), streamed as JSONL when
``--search-log`` is set and mirrored to the process tracer — the machine-
readable replacement for watching the search's debug logging scroll by
(reference: the strategy-export workflow plus Legion Prof's search phase).
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from .trace import get_tracer

# per-chip peak bf16 FLOP/s by TPU generation — the canonical copy
# (bench.py imports this table; keep new generations here)
PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def detect_peak_flops() -> Optional[float]:
    """Per-chip peak FLOP/s of the current backend, or None off-TPU (an MFU
    against a CPU 'peak' would be meaningless). Unknown TPU generations fall
    back to PALLAS_AXON_TPU_GEN, then v5e — the ONE implementation bench.py
    delegates to, so bench MFU and telemetry MFU always use the same peak."""
    try:
        import jax

        dev = jax.devices()[0]
        if dev.platform != "tpu":
            return None
        kind = dev.device_kind.lower()
        for gen, peak in PEAK_FLOPS.items():
            if gen in kind:
                return peak
        gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
        return PEAK_FLOPS.get(gen, PEAK_FLOPS["v5e"])
    except Exception:
        return None


def model_flops_per_step(pcg, backward: bool = True) -> int:
    """Analytic model FLOPs for one training step from the existing per-op
    cost hooks (Op.flops; reference: measure_operator_cost's analytical
    side). Backward is costed as 2x forward — the standard grad-of-matmul
    accounting the simulator also uses."""
    total = 0
    for node in pcg.compute_nodes():
        in_shapes = [pcg.nodes[g].out_shapes[i] for g, i in node.inputs]
        try:
            total += int(node.op.flops(in_shapes, list(node.out_shapes)))
        except Exception:
            continue  # ops without a cost hook contribute 0
    return total * 3 if backward else total


class StepTelemetry:
    """Accumulates per-step records host-side; nothing device-facing happens
    here (the caller hands in already-transferred host scalars)."""

    def __init__(self, batch_size: int = 0, phase: str = "train"):
        self.phase = phase
        self.batch_size = batch_size
        self.step_wall_s: List[float] = []
        self.loss_history: List[float] = []
        self.epoch_loss: List[float] = []
        self.metric_history: List[Dict[str, float]] = []
        self.flops_per_step: Optional[int] = None
        self.peak_flops: Optional[float] = None
        self.device_memory: Optional[Dict[str, int]] = None
        self.total_wall_s: float = 0.0
        # resilience counters (ISSUE 4): filled by the fit loop's
        # ResilienceSession at close — fault events (non-finite steps,
        # preemption signals), recovery events (resume/rollback/flush),
        # steps the sentinel skipped, checkpoints committed, and the step
        # the run last resumed/rolled back to
        self.fault_events: int = 0
        self.recovery_events: int = 0
        self.skipped_steps: int = 0
        self.checkpoints_saved: int = 0
        self.last_resume_step: Optional[int] = None
        # strategy-safety counters (ISSUE 5): filled by the fit loop's
        # StrategyCascade — compile-time fallbacks taken, parallel-
        # correctness audits run/failed, and the strategy the run actually
        # trained under (which may not be the search winner)
        self.strategy_fallbacks: int = 0
        self.audit_runs: int = 0
        self.audit_failures: int = 0
        self.final_strategy: Optional[str] = None
        # static-analysis counters (ISSUE 7): ShardLint runs from cascade
        # stage 0 — analyses run, candidates statically rejected, and the
        # rule IDs (FF001..FF006) that fired
        self.static_checks: int = 0
        self.static_rejects: int = 0
        self.static_rules: List[str] = []
        # calibration counters (ISSUE 8): filled by the fit loop's
        # CalibrationLoop after each ProfiledStep pass — profiled key
        # count, sim-vs-measured aggregate/worst ratios, keys outside the
        # --drift-tolerance band, recalibrations applied (with the exact
        # delta-cost cache invalidation count) and the post-repair ratio
        self.calib_profiled_keys: int = 0
        self.calib_aggregate_ratio: Optional[float] = None
        self.calib_worst_key: Optional[str] = None
        self.calib_worst_ratio: Optional[float] = None
        self.calib_out_of_band: int = 0
        self.calib_tolerance: Optional[float] = None
        self.calib_recalibrations: int = 0
        self.calib_invalidated: int = 0
        self.calib_ratio_after: Optional[float] = None
        # serving counters (ISSUE 6): filled by the ServingEngine after a
        # serve() run — requests completed, tokens emitted, the bounded
        # admission queue's high-water mark and the per-token latency
        # percentiles, mirroring the resilience / strategy_safety blocks
        self.requests_served: int = 0
        self.tokens_generated: int = 0
        self.queue_depth_hwm: int = 0
        self.serving_p50_token_ms: Optional[float] = None
        self.serving_p99_token_ms: Optional[float] = None
        self.serving_tokens_per_s: Optional[float] = None
        # host-overhead split (ISSUE 16): fraction of serve-loop wall the
        # HOST spent dispatching + bookkeeping (vs blocked on the device)
        # — the ROADMAP "host overhead" baseline, per engine and fleet
        self.serving_host_overhead_fraction: Optional[float] = None
        # sequence-parallel decode (ISSUE 18): mean per-step occupied KV
        # bytes one shard chip holds (pool bytes at measured fill /
        # seq_shards) — the recorded number behind "KV provably exceeds
        # one chip"
        self.serving_kv_hbm_per_chip_bytes: Optional[int] = None
        # serving-resilience counters (ISSUE 9): the outcome ledger of a
        # serve() run (every request under exactly one of ok |
        # deadline_exceeded | shed | decode_fault | preempted) plus the
        # shed/deadline/quarantine/drain/replan event counts — filled by
        # ServingEngine._merge_telemetry
        self.serving_outcomes: Dict[str, int] = {}
        self.serving_sheds: int = 0
        self.serving_deadline_misses: int = 0
        self.serving_quarantines: int = 0
        self.serving_drains: int = 0
        self.serving_replans: int = 0
        # prefix-cache / chunked-prefill counters (ISSUE 14): the
        # ``serving_prefix`` block — trie hits, prompt tokens whose
        # prefill was served from cache vs computed, LRU evictions and
        # chunk-prefill dispatches — filled by
        # ServingEngine._merge_telemetry
        self.serving_prefix_hits: int = 0
        self.serving_prefix_tokens_reused: int = 0
        self.serving_prefill_tokens_computed: int = 0
        self.serving_cache_evictions: int = 0
        self.serving_chunked_prefills: int = 0
        # fleet counters (ISSUE 11): the multi-replica router's run —
        # fleet-wide outcome ledger, per-replica dispatch split,
        # migrations/hedges/failovers and the health machinery's
        # probe/circuit activity — filled by ServingFleet._merge_telemetry
        self.fleet_replicas: int = 0
        self.fleet_ticks: int = 0
        self.fleet_requests: int = 0
        self.fleet_tokens_generated: int = 0
        self.fleet_outcomes: Dict[str, int] = {}
        self.fleet_sheds: int = 0
        self.fleet_dispatches: List[int] = []
        self.fleet_migrations: int = 0
        self.fleet_hedges: int = 0
        self.fleet_hedge_twin_wins: int = 0
        self.fleet_affinity_hits: int = 0
        self.fleet_probes: int = 0
        self.fleet_circuit_opens: int = 0
        self.fleet_failovers: int = 0
        self.fleet_health_transitions: int = 0
        self.fleet_host_overhead_fraction: Optional[float] = None
        # multi-tenant + autoscale (ISSUE 19): per-tenant rows
        # {tenant: {requests, tokens, outcomes}} and the autoscaler's
        # action counts — filled by ServingFleet._merge_telemetry
        self.fleet_tenants: Dict[str, Any] = {}
        self.fleet_quota_sheds: int = 0
        self.fleet_autoscale_ups: int = 0
        self.fleet_autoscale_downs: int = 0
        # request-journal counters (ISSUE 20): the ``serving_journal``
        # block — write-ahead records appended / group-commit fsyncs /
        # rids replayed at recovery / door dedupe hits / segments
        # compacted away / torn-tail records truncated on open, plus the
        # recovery wall — filled by ServingFleet._merge_telemetry when
        # --request-journal is on
        self.journal_appended: int = 0
        self.journal_syncs: int = 0
        self.journal_replayed: int = 0
        self.journal_dedupe_hits: int = 0
        self.journal_compacted_segments: int = 0
        self.journal_truncated_records: int = 0
        self.journal_recovery_wall_s: float = 0.0
        self._t_start = time.perf_counter()

    # -- recording ----------------------------------------------------------
    def record_step(self, wall_s: float, loss: Optional[float] = None,
                    metrics: Optional[Dict[str, float]] = None) -> None:
        self.step_wall_s.append(wall_s)
        if loss is not None:
            self.loss_history.append(float(loss))
        if metrics:
            self.metric_history.append(
                {k: float(v) for k, v in metrics.items()})

    def record_epoch(self, loss: Optional[float] = None) -> None:
        if loss is not None:
            self.epoch_loss.append(float(loss))

    def finalize(self) -> None:
        self.total_wall_s = time.perf_counter() - self._t_start

    # -- derived numbers ----------------------------------------------------
    @property
    def steps(self) -> int:
        return len(self.step_wall_s)

    def first_step_s(self) -> Optional[float]:
        """First-step wall time — dominated by jit compile."""
        return self.step_wall_s[0] if self.step_wall_s else None

    def steady_step_s(self) -> Optional[float]:
        """Median steady-state step time, compile step excluded. None when
        only the compile step was recorded — deriving throughput/MFU from a
        wall that is mostly XLA compile would be silently misleading."""
        rest = sorted(self.step_wall_s[1:])
        return rest[len(rest) // 2] if rest else None

    def samples_per_sec(self) -> Optional[float]:
        st = self.steady_step_s()
        if not st or not self.batch_size:
            return None
        return self.batch_size / st

    def mfu(self) -> Optional[float]:
        st = self.steady_step_s()
        if not st or not self.flops_per_step or not self.peak_flops:
            return None
        return (self.flops_per_step / st) / self.peak_flops

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "phase": self.phase,
            "steps": self.steps,
            "batch_size": self.batch_size,
            "total_wall_s": round(self.total_wall_s, 4),
            "loss_history": self.loss_history,
            "epoch_loss": self.epoch_loss,
        }
        if self.step_wall_s:
            out["first_step_s"] = round(self.first_step_s(), 6)
            steady = self.steady_step_s()
            if steady is not None:
                out["steady_step_s"] = round(steady, 6)
                out["compile_overhead_s"] = round(
                    max(self.first_step_s() - steady, 0.0), 6)
        sps = self.samples_per_sec()
        if sps is not None:
            out["samples_per_sec"] = round(sps, 2)
        if self.flops_per_step:
            out["model_flops_per_step"] = self.flops_per_step
        mfu = self.mfu()
        if mfu is not None:
            out["estimated_mfu"] = round(mfu, 4)
            out["peak_flops"] = self.peak_flops
        if self.device_memory:
            out["device_memory"] = self.device_memory
        if self.metric_history:
            out["metric_history"] = self.metric_history
        if (self.fault_events or self.recovery_events or self.skipped_steps
                or self.checkpoints_saved
                or self.last_resume_step is not None):
            res: Dict[str, Any] = {
                "fault_events": self.fault_events,
                "recovery_events": self.recovery_events,
                "skipped_steps": self.skipped_steps,
                "checkpoints_saved": self.checkpoints_saved,
            }
            if self.last_resume_step is not None:
                res["last_resume_step"] = self.last_resume_step
            out["resilience"] = res
        if (self.strategy_fallbacks or self.audit_runs
                or self.final_strategy is not None):
            ss: Dict[str, Any] = {
                "fallbacks": self.strategy_fallbacks,
                "audit_runs": self.audit_runs,
                "audit_failures": self.audit_failures,
            }
            if self.final_strategy is not None:
                ss["final_strategy"] = self.final_strategy
            out["strategy_safety"] = ss
        if self.static_checks:
            out["strategy_static"] = {
                "checks": self.static_checks,
                "rejects": self.static_rejects,
                "rules": list(self.static_rules),
            }
        if self.calib_profiled_keys:
            cal: Dict[str, Any] = {
                "profiled_keys": self.calib_profiled_keys,
                "out_of_band": self.calib_out_of_band,
                "recalibrations": self.calib_recalibrations,
                "invalidated_entries": self.calib_invalidated,
            }
            if self.calib_aggregate_ratio is not None:
                cal["aggregate_ratio"] = round(self.calib_aggregate_ratio, 4)
            if self.calib_worst_key is not None:
                cal["worst_key"] = self.calib_worst_key
            if self.calib_worst_ratio is not None:
                cal["worst_ratio"] = round(self.calib_worst_ratio, 4)
            if self.calib_tolerance is not None:
                cal["tolerance"] = self.calib_tolerance
            if self.calib_ratio_after is not None:
                cal["ratio_after"] = round(self.calib_ratio_after, 4)
            out["calibration"] = cal
        if self.requests_served or self.tokens_generated:
            sv: Dict[str, Any] = {
                "requests_served": self.requests_served,
                "tokens_generated": self.tokens_generated,
                "queue_depth_hwm": self.queue_depth_hwm,
            }
            if self.serving_tokens_per_s is not None:
                sv["tokens_per_s"] = self.serving_tokens_per_s
            if self.serving_p50_token_ms is not None:
                sv["p50_token_ms"] = round(self.serving_p50_token_ms, 3)
            if self.serving_p99_token_ms is not None:
                sv["p99_token_ms"] = round(self.serving_p99_token_ms, 3)
            if self.serving_host_overhead_fraction is not None:
                sv["host_overhead_fraction"] = round(
                    self.serving_host_overhead_fraction, 4)
            if self.serving_kv_hbm_per_chip_bytes is not None:
                sv["kv_hbm_per_chip_bytes"] = \
                    int(self.serving_kv_hbm_per_chip_bytes)
            out["serving"] = sv
        if self.fleet_replicas:
            total = max(sum(self.fleet_outcomes.values()), 1)
            fl: Dict[str, Any] = {
                "replicas": self.fleet_replicas,
                "ticks": self.fleet_ticks,
                "requests": self.fleet_requests,
                "tokens_generated": self.fleet_tokens_generated,
                "outcomes": dict(self.fleet_outcomes),
                "shed_rate": round(self.fleet_sheds / total, 4),
                "dispatches": list(self.fleet_dispatches),
                "migrations": self.fleet_migrations,
                "hedges": self.fleet_hedges,
                "hedge_twin_wins": self.fleet_hedge_twin_wins,
                "affinity_hits": self.fleet_affinity_hits,
                "probes": self.fleet_probes,
                "circuit_opens": self.fleet_circuit_opens,
                "failovers": self.fleet_failovers,
                "health_transitions": self.fleet_health_transitions,
            }
            if self.fleet_host_overhead_fraction is not None:
                fl["host_overhead_fraction"] = round(
                    self.fleet_host_overhead_fraction, 4)
            if self.fleet_tenants:
                fl["tenants"] = {t: dict(v) for t, v
                                 in self.fleet_tenants.items()}
            if self.fleet_quota_sheds:
                fl["quota_sheds"] = self.fleet_quota_sheds
            if self.fleet_autoscale_ups or self.fleet_autoscale_downs:
                fl["autoscale"] = {"ups": self.fleet_autoscale_ups,
                                   "downs": self.fleet_autoscale_downs}
            out["fleet"] = fl
        if (self.serving_prefix_hits or self.serving_prefix_tokens_reused
                or self.serving_prefill_tokens_computed
                or self.serving_cache_evictions
                or self.serving_chunked_prefills):
            total = (self.serving_prefix_tokens_reused
                     + self.serving_prefill_tokens_computed)
            out["serving_prefix"] = {
                "hits": self.serving_prefix_hits,
                "tokens_reused": self.serving_prefix_tokens_reused,
                "tokens_computed": self.serving_prefill_tokens_computed,
                "reuse_rate": round(
                    self.serving_prefix_tokens_reused / total, 4)
                if total else 0.0,
                "evictions": self.serving_cache_evictions,
                "chunked_prefills": self.serving_chunked_prefills,
            }
        if (self.serving_outcomes or self.serving_sheds
                or self.serving_deadline_misses or self.serving_quarantines
                or self.serving_drains or self.serving_replans):
            total = max(sum(self.serving_outcomes.values()), 1)
            out["serving_resilience"] = {
                "outcomes": dict(self.serving_outcomes),
                "shed_rate": round(self.serving_sheds / total, 4),
                "deadline_miss_rate": round(
                    self.serving_deadline_misses / total, 4),
                "quarantines": self.serving_quarantines,
                "drains": self.serving_drains,
                "replans": self.serving_replans,
            }
        if self.journal_appended or self.journal_replayed:
            out["serving_journal"] = {
                "appended": self.journal_appended,
                "syncs": self.journal_syncs,
                "replayed": self.journal_replayed,
                "dedupe_hits": self.journal_dedupe_hits,
                "compacted_segments": self.journal_compacted_segments,
                "truncated_records": self.journal_truncated_records,
                "recovery_wall_s": round(
                    self.journal_recovery_wall_s, 6),
            }
        return out

    def write(self, path: str) -> str:
        from .trace import atomic_write_json

        return atomic_write_json(path, self.summary())


def peak_memory_bytes(ma) -> Optional[int]:
    """XLA peak memory from a CompiledMemoryStats, across jax versions:
    newer jaxlibs expose ``peak_memory_in_bytes`` directly; older ones only
    the component sizes, from which arguments + outputs + temps minus
    aliased (donated) buffers is the standard reconstruction."""
    if ma is None:
        return None
    v = getattr(ma, "peak_memory_in_bytes", None)
    if v is not None and int(v) > 0:
        return int(v)
    try:
        tot = (int(ma.argument_size_in_bytes) + int(ma.output_size_in_bytes)
               + int(ma.temp_size_in_bytes)
               - int(getattr(ma, "alias_size_in_bytes", 0)))
        return tot if tot > 0 else None
    except AttributeError:
        return None


def capture_memory_analysis(executor, params, opt_state, xs, labels
                            ) -> Optional[Dict[str, int]]:
    """Best-effort XLA compiled-memory capture for the telemetry record.
    Never raises: memory stats are advisory and some backends don't expose
    them."""
    try:
        ma = executor.train_step_memory_analysis(params, opt_state, xs,
                                                 labels)
        if ma is None:
            return None
        out = {}
        for field in ("argument_size_in_bytes", "output_size_in_bytes",
                      "temp_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(ma, field, None)
            if v is not None:
                out[field] = int(v)
        peak = peak_memory_bytes(ma)
        if peak is not None:
            out["peak_memory_in_bytes"] = peak
        return out or None
    except Exception:
        return None


class SearchLog:
    """Per-iteration search telemetry sink. Every ``log()`` lands as a JSONL
    line (when ``path`` is set) and as an instant event on the process tracer
    (when tracing is enabled) — one call site, both sinks. Safe to construct
    unconditionally: with no path and tracing disabled it degrades to a
    counter."""

    def __init__(self, path: Optional[str] = None, kind: str = "unity"):
        self.path = path
        self.kind = kind
        self.iterations = 0
        # per-event-type record counts (e.g. "candidate", "xfer",
        # "pipeline_candidate"): unity_search derives its candidates/sec
        # metric from these, so the rate in the final record always matches
        # what the log actually streamed
        self.counts: Dict[str, int] = {}
        self._fh = None  # set BEFORE open(): __del__ must find the attr
        # even when open() raises on a bad path
        if path:
            # line-buffered: the log is for WATCHING a live search (tail
            # -f) and must survive a mid-search kill
            self._fh = open(path, "a", buffering=1)

    def log(self, **rec) -> None:
        self.iterations += 1
        ev = rec.get("event")
        if ev:
            self.counts[ev] = self.counts.get(ev, 0) + 1
        rec.setdefault("search", self.kind)
        rec.setdefault("iter", self.iterations)
        if self._fh is not None:
            self._fh.write(json.dumps(rec, default=str) + "\n")
        tracer = get_tracer()
        if tracer.enabled:
            tracer.event(f"{self.kind}_iter", **rec)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.flush()
            self._fh.close()
            self._fh = None

    def __del__(self):
        # a search that raises mid-run drops its SearchLog frame without
        # reaching the explicit close(); refcount collection closes the fd
        # (writes are line-buffered, so no records are lost either way)
        self.close()
