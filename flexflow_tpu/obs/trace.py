"""Structured span/event tracer with Chrome trace-event export.

The observability analog of the reference's Legion Prof integration
(``-lg:prof``) plus the per-op ``--profiling`` kernel-timing prints: nested
spans for compile / train-step / epoch / eval / search phases, instant
events, counters and gauges, exported as Chrome trace-event JSON
(Perfetto-loadable, ``chrome://tracing``) and optionally streamed to a JSONL
event sink as spans complete.

Disabled-by-default design: the module-level singleton starts as a
``NoopTracer`` whose ``span()`` returns one shared, reusable null context
manager — entering it allocates nothing, so instrumented hot loops pay a
single attribute load + truth test when tracing is off. Nothing here runs
inside jitted code; all timestamps are host wall-clock (``time.perf_counter``
against the tracer's epoch).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional


def atomic_write_json(path: str, obj) -> str:
    """Write ``obj`` as JSON via a same-directory temp file + rename, so a
    killed process never leaves a truncated artifact. The pid in the temp
    name keeps two concurrent writers from clobbering each other's staging
    file. Shared by every JSON artifact this subsystem emits."""
    path = os.path.abspath(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, default=str)
    os.replace(tmp, path)
    return path


class _NullSpan:
    """Allocation-free context manager returned by the disabled tracer."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NoopTracer:
    """Disabled tracer: every method is a no-op and ``span`` returns the one
    shared null context manager (no per-call allocation in hot loops)."""

    enabled = False
    events: tuple = ()

    def span(self, name: str, **args):
        return _NULL_SPAN

    def event(self, name: str, **args) -> None:
        pass

    def complete(self, name: str, wall_s: float, **args) -> None:
        pass

    def span_at(self, name: str, ts_us: float, dur_us: float,
                tid=None, **args) -> None:
        pass

    def event_at(self, name: str, ts_us: float, tid=None, **args) -> None:
        pass

    def counter(self, name: str, value) -> None:
        pass

    def gauge(self, name: str, value) -> None:
        pass

    def to_chrome_trace(self) -> Dict[str, Any]:
        return {"traceEvents": []}

    def write(self, path: Optional[str] = None) -> None:
        pass

    def close(self) -> None:
        pass


class _Span:
    """One live span; appended to the tracer as a complete ('ph': 'X') event
    on exit. Nesting is expressed by timestamp containment, which is how the
    Chrome trace format renders stacks for same-tid complete events."""

    __slots__ = ("tracer", "name", "args", "t0")

    def __init__(self, tracer: "Tracer", name: str, args: Dict[str, Any]):
        self.tracer = tracer
        self.name = name
        self.args = args
        self.t0 = 0.0

    def __enter__(self):
        self.t0 = self.tracer._now_us()
        self.tracer._enter_span()
        return self

    def __exit__(self, exc_type, exc, tb):
        end = self.tracer._now_us()
        depth = self.tracer._exit_span()
        self.tracer._emit({
            "name": self.name, "cat": "flexflow", "ph": "X",
            "ts": round(self.t0, 3), "dur": round(end - self.t0, 3),
            "pid": self.tracer.pid, "tid": threading.get_ident(),
            "args": dict(self.args, depth=depth) if self.args
            else {"depth": depth},
        })
        return False


class Tracer:
    """Thread-safe span/event recorder.

    * ``span(name, **args)``: context manager; emits a complete ('X') event.
    * ``event(name, **args)``: instant ('i') event.
    * ``counter(name, value)`` / ``gauge``: 'C' events Perfetto plots as
      time series.
    * ``to_chrome_trace()`` / ``write(path)``: Chrome trace-event JSON.
    * ``jsonl_file``: when set, every emitted event is also appended to this
      file as one JSON object per line (the machine-readable event sink).
    """

    enabled = True

    # in-memory event cap: a multi-day fit with tracing on emits one event
    # per step — unbounded growth would eat host RAM and make every
    # trace-file rewrite slower. Oldest events roll off (the JSONL sink,
    # when set, still has them all); dropped count lands in otherData.
    DEFAULT_MAX_EVENTS = 500_000

    def __init__(self, trace_file: Optional[str] = None,
                 jsonl_file: Optional[str] = None, pid: int = 0,
                 max_events: int = DEFAULT_MAX_EVENTS):
        import collections

        self._lock = threading.Lock()
        self._local = threading.local()
        self.events = collections.deque(maxlen=max_events)
        self.dropped_events = 0
        self.trace_file = trace_file
        self.jsonl_file = jsonl_file
        self._jsonl_fh = None
        self.pid = pid
        self._t0 = time.perf_counter()

    # -- clock / span-stack internals -------------------------------------
    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _enter_span(self) -> int:
        d = getattr(self._local, "depth", 0)
        self._local.depth = d + 1
        return d

    def _exit_span(self) -> int:
        d = getattr(self._local, "depth", 1) - 1
        self._local.depth = d
        return d

    @property
    def depth(self) -> int:
        """Current nesting depth on the calling thread."""
        return getattr(self._local, "depth", 0)

    def _emit(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            if self.events.maxlen is not None and \
                    len(self.events) == self.events.maxlen:
                self.dropped_events += 1  # deque drops the oldest
            self.events.append(ev)
            if self.jsonl_file is not None:
                if self._jsonl_fh is None:
                    # line-buffered: the sink is tail-able mid-run and
                    # survives a crash without losing buffered events
                    self._jsonl_fh = open(self.jsonl_file, "a", buffering=1)
                self._jsonl_fh.write(json.dumps(ev, default=str) + "\n")

    # -- public recording API ---------------------------------------------
    def span(self, name: str, **args) -> _Span:
        return _Span(self, name, args)

    def event(self, name: str, **args) -> None:
        self._emit({"name": name, "cat": "flexflow", "ph": "i", "s": "t",
                    "ts": round(self._now_us(), 3), "pid": self.pid,
                    "tid": threading.get_ident(), "args": args})

    def complete(self, name: str, wall_s: float, **args) -> None:
        """Retroactive complete ('X') event ending now and lasting
        ``wall_s`` — for hot loops that time a phase themselves and report
        it afterwards instead of holding a span open."""
        end = self._now_us()
        self._emit({"name": name, "cat": "flexflow", "ph": "X",
                    "ts": round(max(end - wall_s * 1e6, 0.0), 3),
                    "dur": round(wall_s * 1e6, 3), "pid": self.pid,
                    "tid": threading.get_ident(), "args": args})

    def span_at(self, name: str, ts_us: float, dur_us: float,
                tid=None, **args) -> None:
        """Complete ('X') event at an EXPLICIT timestamp (µs). The
        request tracer (obs/reqtrace.py) uses this to export spans on
        the scheduler's injectable clock — deterministic under a fake
        clock — instead of the tracer's own perf_counter epoch; such
        spans carry their own time base (one pid lane per source), so
        nesting is judged within a lane, never across lanes."""
        self._emit({"name": name, "cat": "flexflow", "ph": "X",
                    "ts": round(float(ts_us), 3),
                    "dur": round(max(float(dur_us), 0.0), 3),
                    "pid": self.pid,
                    "tid": threading.get_ident() if tid is None else tid,
                    "args": args})

    def event_at(self, name: str, ts_us: float, tid=None, **args) -> None:
        """Instant ('i') event at an explicit timestamp (µs) — the
        ``event()`` analog of :meth:`span_at`."""
        self._emit({"name": name, "cat": "flexflow", "ph": "i", "s": "t",
                    "ts": round(float(ts_us), 3), "pid": self.pid,
                    "tid": threading.get_ident() if tid is None else tid,
                    "args": args})

    def counter(self, name: str, value) -> None:
        self._emit({"name": name, "cat": "flexflow", "ph": "C",
                    "ts": round(self._now_us(), 3), "pid": self.pid,
                    "tid": threading.get_ident(),
                    "args": {name: value}})

    gauge = counter  # same Chrome event shape; kept as a semantic alias

    # -- export ------------------------------------------------------------
    def to_chrome_trace(self) -> Dict[str, Any]:
        with self._lock:
            events = list(self.events)
            dropped = self.dropped_events
        other: Dict[str, Any] = {"tracer": "flexflow_tpu.obs"}
        if dropped:
            other["dropped_oldest_events"] = dropped
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": other}

    def write(self, path: Optional[str] = None) -> str:
        path = path or self.trace_file
        if not path:
            raise ValueError("no trace file path given")
        return atomic_write_json(path, self.to_chrome_trace())

    def close(self) -> None:
        with self._lock:
            if self._jsonl_fh is not None:
                self._jsonl_fh.close()
                self._jsonl_fh = None
        if self.trace_file:
            self.write(self.trace_file)


# ------------------------------------------------------------- the singleton
_TRACER = NoopTracer()


def get_tracer():
    """The process-wide tracer (NoopTracer unless ``enable()`` was called)."""
    return _TRACER


def set_tracer(tracer) -> None:
    global _TRACER
    _TRACER = tracer


def enable(trace_file: Optional[str] = None,
           jsonl_file: Optional[str] = None) -> Tracer:
    """Install (and return) a live Tracer as the process singleton. If one is
    already installed it is returned unchanged, so a config-driven enable and
    an explicit user enable compose."""
    global _TRACER
    if not _TRACER.enabled:
        _TRACER = Tracer(trace_file=trace_file, jsonl_file=jsonl_file)
    return _TRACER


def disable():
    """Swap the singleton back to the NoopTracer; returns the previous tracer
    (so a caller can still ``write()`` it). JSONL sinks are closed."""
    global _TRACER
    prev = _TRACER
    if prev.enabled:
        with prev._lock:
            if prev._jsonl_fh is not None:
                prev._jsonl_fh.close()
                prev._jsonl_fh = None
    _TRACER = NoopTracer()
    return prev
