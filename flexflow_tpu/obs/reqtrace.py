"""Request-level distributed tracing for the serving stack (ISSUE 16).

The obs layer saw training steps and search iterations; the serving
fleet — continuous batching, chunked prefill, prefix cache, migration,
hedging across N replicas — exposed only end-of-run aggregates. This
module is the Dapper-style per-request causal timeline applied to the
token-serving data path: every lifecycle edge (submit → queue wait →
admission → prefix hit/COW → per-chunk prefill → per-tick decode →
quarantine/retry/migration/hedge hops across replicas → terminal
outcome) lands as a timestamped note on ONE timeline per request, and
each finished request is finalized exactly once into:

* a ``RequestRecord`` — one JSON object (schema version
  ``RECORD_VERSION``) on the JSONL stream: arrival time, prompt /
  new-token lengths, per-phase durations (queue / prefill / decode /
  stall), replica hops, terminal outcome. This stream doubles as the
  ROADMAP-item-4 replayable trace format: a capacity planner can re-run
  the arrival process and per-request token counts against a synthetic
  fleet.
* Perfetto-compatible spans through the process :class:`~.trace.Tracer`
  (``span_at`` / ``event_at`` — explicit timestamps on the scheduler's
  injectable clock, so a fake-clock test renders the same trace every
  run): a ``request`` umbrella span per request (tid = rid) with
  ``req_queue`` / ``req_prefill`` / ``req_decode`` / ``req_stall``
  phase spans nested inside it and ``req_hop`` / ``req_shed`` /
  ``req_outcome`` instants at the edges.

Zero-overhead contract (the PR 9 tracer idiom): the module singleton
starts as :class:`NoopRequestTrace`; instrumented hot paths pay one
attribute load + truth test (``if rt.enabled:``) when tracing is off,
and the request path stays bitwise-identical and allocation-free
(pinned in tests/test_reqtrace.py).

Hedge causality: a hedged twin is ``link()``-ed to its primary at
launch, so every note the twin makes folds into the primary's timeline
(parent-span causality — a hedged or migrated request is one connected
timeline ending in exactly one outcome, whichever copy finishes first).
Migration needs no linking: the same Request object (same rid) crosses
replicas, each admission note carrying its replica id.

Phase decomposition is a deterministic walk of the note timeline:
``queue`` is the wait before the FIRST admission; ``stall`` is every
later wait (quarantine requeue, migration, hedge re-dispatch);
``prefill`` runs from each admission to the first token committed after
it; ``decode`` is the rest. While hedge copies run concurrently the
walk attributes elapsed time to the most recent edge — an approximation
(the copies overlap in wall time) that stays exact for the common
un-hedged case and deterministic always.

``FleetTimeSeries`` rides along: bounded per-tick ring buffers of door
queue depth, per-replica occupancy/health, tokens per tick, and a
backlog EWMA, sampled once per :meth:`ServingFleet.run` loop iteration
when request tracing is enabled.
"""
from __future__ import annotations

import json
import threading
from collections import deque
from typing import Any, Dict, List, Optional

from .trace import get_tracer

RECORD_VERSION = 1

# phase-bucket name -> Perfetto span name (literal names also live in
# _finalize below so scripts/check_trace_events.py can extract them)
_PHASE_SPANS = {
    "queue": "req_queue",
    "prefill": "req_prefill",
    "decode": "req_decode",
    "stall": "req_stall",
}

# notes a request timeline can carry; anything else raises in note()
# so a typo'd edge never silently vanishes from the record
NOTE_KINDS = ("submit", "admit", "chunk", "cow", "token", "quarantine",
              "migrate", "hedge", "replay", "finish")

# a runaway decode could otherwise grow one request's note list without
# bound; past the cap notes are counted, not stored
MAX_NOTES_PER_REQUEST = 100_000


class NoopRequestTrace:
    """Disabled request tracer: every method is a no-op; the hot-path
    guard is ``rt.enabled`` (one attribute load, no allocation)."""

    __slots__ = ()
    enabled = False

    def note(self, rid: int, kind: str, ts_ms: float, **fields) -> None:
        pass

    def link(self, twin_rid: int, primary_rid: int) -> None:
        pass

    def finish(self, rid: int, ts_ms: float, outcome: str,
               **fields) -> None:
        pass

    def records(self) -> list:
        return []


class RequestTrace:
    """Per-request timeline recorder (module docstring has the design).

    ``note()`` appends one timestamped edge; ``link()`` folds a hedge
    twin's future notes into its primary's timeline; ``finish()``
    finalizes the timeline exactly once (idempotent per rid — the first
    terminal note wins, which by construction is the winning hedge
    copy's) into a RequestRecord + Perfetto spans.
    """

    enabled = True

    def __init__(self, jsonl_file: Optional[str] = None,
                 tracer=None, max_records: int = 100_000):
        self._lock = threading.Lock()
        self._notes: Dict[int, List[tuple]] = {}
        self._dropped: Dict[int, int] = {}
        self._alias: Dict[int, int] = {}     # twin rid -> primary rid
        self._linked: Dict[int, List[int]] = {}  # primary -> twin rids
        self._done: set = set()
        self._records: deque = deque(maxlen=max_records)
        self.dropped_records = 0
        self.jsonl_file = jsonl_file
        self._jsonl_fh = None
        self._tracer = tracer

    # ------------------------------------------------------------ recording
    def note(self, rid: int, kind: str, ts_ms: float, **fields) -> None:
        """Append one lifecycle edge to ``rid``'s timeline (``ts_ms`` on
        the scheduler clock). Notes on a linked twin fold into the
        primary's timeline."""
        if kind not in NOTE_KINDS:
            raise ValueError(f"unknown request-trace note kind {kind!r}")
        with self._lock:
            rid = self._alias.get(rid, rid)
            if rid in self._done:
                return  # post-terminal stragglers (losing hedge copy)
            notes = self._notes.setdefault(rid, [])
            if len(notes) >= MAX_NOTES_PER_REQUEST:
                self._dropped[rid] = self._dropped.get(rid, 0) + 1
                return
            notes.append((float(ts_ms), kind, fields))

    def link(self, twin_rid: int, primary_rid: int) -> None:
        """Fold ``twin_rid``'s timeline into ``primary_rid``'s (hedge
        parent-span causality): notes the twin already made are moved
        over, future ones are redirected, and the twin never finalizes
        a record of its own."""
        with self._lock:
            primary_rid = self._alias.get(primary_rid, primary_rid)
            self._alias[twin_rid] = primary_rid
            self._linked.setdefault(primary_rid, []).append(twin_rid)
            moved = self._notes.pop(twin_rid, None)
            if moved:
                notes = self._notes.setdefault(primary_rid, [])
                notes.extend(moved)
                notes.sort(key=lambda n: n[0])

    def finish(self, rid: int, ts_ms: float, outcome: str,
               **fields) -> None:
        """Terminal edge + finalization. Idempotent: a second terminal
        note for the same timeline (the losing hedge copy, the fleet's
        defensive re-finish) is dropped — every request ends in exactly
        one outcome."""
        with self._lock:
            rid = self._alias.get(rid, rid)
            if rid in self._done:
                return
            notes = self._notes.pop(rid, [])
            notes.append((float(ts_ms), "finish",
                          dict(fields, outcome=outcome)))
            self._done.add(rid)
            record = self._build_record(rid, notes)
            if len(self._records) == self._records.maxlen:
                self.dropped_records += 1
            self._records.append(record)
            if self.jsonl_file is not None:
                if self._jsonl_fh is None:
                    # line-buffered: tail-able mid-run, crash-safe
                    self._jsonl_fh = open(self.jsonl_file, "a",
                                          buffering=1)
                self._jsonl_fh.write(
                    json.dumps(record, default=str) + "\n")
        self._export_spans(record, notes)

    # ----------------------------------------------------------- finalizing
    def _build_record(self, rid: int, notes: List[tuple]
                      ) -> Dict[str, Any]:
        buckets = {"queue": 0.0, "prefill": 0.0, "decode": 0.0,
                   "stall": 0.0}
        state: Optional[str] = None
        t_state = 0.0
        arrival = None
        first_token = None
        finish_ts = None
        outcome = None
        reason = None
        prompt_len = None
        max_new = None
        deadline = None
        hit = 0
        chunks = 0
        cow = False
        ticks = 0
        occ_sum = 0
        hops: List[Dict[str, Any]] = []
        replicas: List[Any] = []
        shed: Optional[Dict[str, Any]] = None
        seen_admit = False
        tenant = None

        def close(ts: float) -> None:
            nonlocal t_state
            if state is not None:
                buckets[state] += max(ts - t_state, 0.0)
            t_state = ts

        def saw_replica(fields: Dict[str, Any]) -> None:
            rep = fields.get("replica")
            if rep is not None and rep not in replicas:
                replicas.append(rep)

        for ts, kind, fields in notes:
            if kind == "submit":
                close(ts)
                if arrival is None:
                    arrival = ts
                    prompt_len = fields.get("prompt_len")
                    max_new = fields.get("max_new")
                    deadline = fields.get("deadline_ms")
                if tenant is None:
                    tenant = fields.get("tenant")
                state = "stall" if seen_admit else "queue"
            elif kind == "admit":
                close(ts)
                state = "prefill"
                seen_admit = True
                hit = max(hit, int(fields.get("hit", 0) or 0))
                cow = cow or bool(fields.get("cow"))
                saw_replica(fields)
            elif kind == "token":
                close(ts)
                if first_token is None:
                    first_token = ts
                state = "decode"
                ticks += 1
                occ_sum += int(fields.get("occ", 0) or 0)
            elif kind in ("quarantine", "migrate", "hedge", "replay"):
                if kind not in ("hedge", "replay"):
                    # the primary keeps running while its hedge
                    # launches; a replay note precedes its re-submit
                    # (ISSUE 20) so it opens no phase of its own
                    close(ts)
                    state = "stall"
                hops.append(dict(fields, t=round(ts, 3), kind=kind))
                saw_replica(fields)
            elif kind == "chunk":
                chunks += 1
            elif kind == "cow":
                cow = True
            elif kind == "finish":
                close(ts)
                state = None
                finish_ts = ts
                outcome = fields.get("outcome")
                reason = fields.get("reason")
                saw_replica(fields)
                if outcome == "shed":
                    shed = {k: v for k, v in fields.items()
                            if k not in ("outcome", "reason", "replica")}
        finish_fields = notes[-1][2] if notes else {}
        return {
            "v": RECORD_VERSION,
            "kind": "request",
            "rid": rid,
            "arrival_ms": arrival,
            "prompt_len": prompt_len,
            "max_new_tokens": max_new,
            "deadline_ms": deadline,
            # ISSUE 19: additive field, RECORD_VERSION unchanged — old
            # readers ignore it, trace_summary degrades when absent
            "tenant": tenant,
            "new_tokens": finish_fields.get("new_tokens", ticks),
            "outcome": outcome,
            "finish_reason": reason,
            "first_token_ms": first_token,
            "finish_ms": finish_ts,
            "queue_ms": round(buckets["queue"], 3),
            "prefill_ms": round(buckets["prefill"], 3),
            "decode_ms": round(buckets["decode"], 3),
            "stall_ms": round(buckets["stall"], 3),
            "decode_ticks": ticks,
            "occupancy_avg": round(occ_sum / ticks, 3) if ticks else 0.0,
            "prefix_hit_tokens": hit,
            "chunks": chunks,
            "cow": cow,
            "hops": hops,
            "replicas": replicas,
            "hedged": bool(self._linked.get(rid)),
            "dropped_notes": self._dropped.pop(rid, 0),
            "shed": shed,
        }

    def _export_spans(self, record: Dict[str, Any],
                      notes: List[tuple]) -> None:
        tracer = self._tracer if self._tracer is not None \
            else get_tracer()
        if not tracer.enabled:
            return
        rid = record["rid"]
        arrival = record["arrival_ms"]
        finish_ts = record["finish_ms"]
        if arrival is not None and finish_ts is not None:
            tracer.span_at("request", arrival * 1e3,
                           (finish_ts - arrival) * 1e3, tid=rid,
                           rid=rid, outcome=record["outcome"])
        # phase spans: replay the same walk, emitting each closed episode
        state: Optional[str] = None
        t_state = 0.0
        seen_admit = False

        def close(ts: float) -> None:
            nonlocal t_state
            if state is not None:
                tracer.span_at(_PHASE_SPANS[state], t_state * 1e3,
                               (ts - t_state) * 1e3, tid=rid, rid=rid)
            t_state = ts

        for ts, kind, fields in notes:
            if kind == "submit":
                close(ts)
                state = "queue" if not seen_admit else "stall"
            elif kind == "admit":
                close(ts)
                state = "prefill"
                seen_admit = True
            elif kind == "token":
                if state != "decode":
                    close(ts)
                    state = "decode"
            elif kind in ("quarantine", "migrate"):
                close(ts)
                state = "stall"
                tracer.event_at("req_hop", ts * 1e3, tid=rid, rid=rid,
                                hop=kind, **fields)
            elif kind in ("hedge", "replay"):
                tracer.event_at("req_hop", ts * 1e3, tid=rid, rid=rid,
                                hop=kind, **fields)
            elif kind == "finish":
                close(ts)
                state = None
                if fields.get("outcome") == "shed":
                    tracer.event_at("req_shed", ts * 1e3, tid=rid,
                                    rid=rid, **fields)
                tracer.event_at("req_outcome", ts * 1e3, tid=rid,
                                rid=rid, outcome=fields.get("outcome"))

    # -------------------------------------------------------------- reading
    def records(self) -> List[Dict[str, Any]]:
        """Finalized RequestRecords, oldest first (bounded)."""
        with self._lock:
            return list(self._records)

    def open_timelines(self) -> List[int]:
        """rids with notes but no terminal outcome yet — empty after a
        clean run (every admitted request must end exactly once)."""
        with self._lock:
            return sorted(self._notes)

    def write(self, path: str) -> str:
        """Dump every finalized record as JSONL to ``path``."""
        with self._lock, open(path, "w") as f:
            for rec in self._records:
                f.write(json.dumps(rec, default=str) + "\n")
        return path

    def close(self) -> None:
        with self._lock:
            if self._jsonl_fh is not None:
                self._jsonl_fh.close()
                self._jsonl_fh = None


class FleetTimeSeries:
    """Bounded per-tick ring buffers of fleet state, sampled once per
    :meth:`ServingFleet.run` loop iteration: door queue depth,
    per-replica occupancy fraction and health, tokens committed that
    tick, and an EWMA of the fleet-wide backlog drain estimate. Ring
    buffers (not full history) so a long-lived fleet cannot eat host
    memory; ``summary()`` digests what is retained."""

    EWMA_ALPHA = 0.2

    def __init__(self, maxlen: int = 4096):
        self.maxlen = int(maxlen)
        self.ticks: deque = deque(maxlen=self.maxlen)
        self.queue_depth: deque = deque(maxlen=self.maxlen)
        self.tokens: deque = deque(maxlen=self.maxlen)
        self.backlog_ewma_ms: deque = deque(maxlen=self.maxlen)
        self.occupancy: deque = deque(maxlen=self.maxlen)
        self.health: deque = deque(maxlen=self.maxlen)
        # per-tenant door depth rows (ISSUE 19): {tenant: queued} per
        # tick, {} when the traffic carries no tenant labels
        self.tenant_queue: deque = deque(maxlen=self.maxlen)
        self._ewma: Optional[float] = None

    def sample(self, tick: int, queue_depth: int, tokens: int,
               backlog_ms: float, occupancy, health,
               tenants: Optional[Dict[str, int]] = None) -> None:
        """Append one tick: ``occupancy`` is a per-replica sequence of
        live-slot fractions, ``health`` the matching health strings,
        ``tenants`` the door depth per explicit tenant."""
        b = float(backlog_ms)
        self._ewma = b if self._ewma is None else \
            self.EWMA_ALPHA * b + (1 - self.EWMA_ALPHA) * self._ewma
        self.ticks.append(int(tick))
        self.queue_depth.append(int(queue_depth))
        self.tokens.append(int(tokens))
        self.backlog_ewma_ms.append(round(self._ewma, 3))
        self.occupancy.append(tuple(round(float(o), 4)
                                    for o in occupancy))
        self.health.append(tuple(health))
        self.tenant_queue.append(dict(tenants or {}))

    def __len__(self) -> int:
        return len(self.ticks)

    def summary(self) -> Dict[str, Any]:
        n = len(self.ticks)
        if not n:
            return {"ticks": 0}
        occ_flat = [o for tick in self.occupancy for o in tick]
        return {
            "ticks": n,
            "queue_depth_last": self.queue_depth[-1],
            "queue_depth_max": max(self.queue_depth),
            "tokens_total": sum(self.tokens),
            "backlog_ewma_ms_last": self.backlog_ewma_ms[-1],
            "occupancy_mean": round(sum(occ_flat) / len(occ_flat), 4)
            if occ_flat else 0.0,
            "unhealthy_ticks": sum(
                1 for tick in self.health
                if any(h != "healthy" for h in tick)),
        }

    def tenant_summary(self) -> Dict[str, Dict[str, int]]:
        """Per-tenant door-depth digest over retained ticks: max and
        last queued per tenant ({} on pre-tenant series)."""
        out: Dict[str, Dict[str, int]] = {}
        for row in self.tenant_queue:
            for t, n in row.items():
                d = out.setdefault(t, {"queued_max": 0, "queued_last": 0})
                d["queued_max"] = max(d["queued_max"], int(n))
        if self.tenant_queue:
            last = self.tenant_queue[-1]
            for t, d in out.items():
                d["queued_last"] = int(last.get(t, 0))
        return out


# ------------------------------------------------------------- the singleton
_REQTRACE = NoopRequestTrace()


def get_reqtrace():
    """The process-wide request tracer (:class:`NoopRequestTrace` unless
    :func:`enable_reqtrace` was called)."""
    return _REQTRACE


def set_reqtrace(rt) -> None:
    global _REQTRACE
    _REQTRACE = rt


def enable_reqtrace(jsonl_file: Optional[str] = None,
                    tracer=None) -> RequestTrace:
    """Install (and return) a live :class:`RequestTrace` as the process
    singleton; a second enable returns the existing instance unchanged
    (the trace.py composition rule)."""
    global _REQTRACE
    if not _REQTRACE.enabled:
        _REQTRACE = RequestTrace(jsonl_file=jsonl_file, tracer=tracer)
    return _REQTRACE


def disable_reqtrace():
    """Swap back to the no-op singleton; returns the previous tracer (a
    caller can still read ``records()`` / ``write()`` it)."""
    global _REQTRACE
    prev = _REQTRACE
    if prev.enabled:
        prev.close()
    _REQTRACE = NoopRequestTrace()
    return prev
