"""Launcher: ``python -m flexflow_tpu user_script.py [flags]`` — the analog of
the reference's ``flexflow_python`` driver (python/flexflow/driver.py,
python/flexflow_python.py), which boots the runtime and then runs the user
script as the top-level task. Here there is no runtime to boot; the launcher
just makes the reference-style invocation work unchanged: the script sees the
remaining argv (picked up by ``FFConfig()``) and the framework on sys.path.
"""
import os
import runpy
import sys


def main() -> None:
    argv = sys.argv[1:]
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m flexflow_tpu <script.py> [flags]\n"
              "Flags after the script are visible to FFConfig "
              "(-b, -e, --budget, --only-data-parallel, -ll:tpu N, ...).")
        return
    script = argv[0]
    if not os.path.exists(script):
        raise SystemExit(f"flexflow_tpu launcher: no such script: {script}")
    sys.argv = argv  # script name + its flags, reference-style
    sys.path.insert(0, os.path.dirname(os.path.abspath(script)))
    runpy.run_path(script, run_name="__main__")


if __name__ == "__main__":
    main()
