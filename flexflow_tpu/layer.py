"""Layer: an untyped node of the user-facing graph built by FFModel builder calls.

Analog of the reference's ``Layer`` (include/flexflow/layer.h, src/runtime/layer.cc).
A Layer records the op type, attributes, inputs, and declared weight shapes; it is
converted to a typed `Op` in the Parallel Computation Graph by
``FFModel.compile`` (reference: create_operators_from_layers, src/runtime/model.cc:2785).
"""
from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

from .ffconst import DataType, OperatorType
from .tensor import Tensor

_layer_guid = itertools.count(100)


class Layer:
    def __init__(
        self,
        op_type: OperatorType,
        dtype: DataType,
        name: Optional[str],
        inputs: List[Tensor],
        numWeights: int = 0,
        numOutputs: int = 1,
        attrs: Optional[Dict[str, Any]] = None,
        index: Optional[int] = None,
    ):
        self.guid = next(_layer_guid)
        self.op_type = op_type
        self.data_type = dtype
        base = name or op_type.name.lower().replace("op_", "")
        # deterministic per-model naming (index = position in the model) so
        # checkpoints/strategies transfer between identical models
        self.name = f"{base}_{self.guid if index is None else index}"
        self.inputs: List[Tensor] = list(inputs)
        self.outputs: List[Tensor] = []
        self.num_weights = numWeights
        self.attrs: Dict[str, Any] = dict(attrs or {})
        # weight declarations: name -> (shape, dtype, initializer)
        self.weight_specs: Dict[str, Tuple[Tuple[int, ...], DataType, Any]] = {}
        # weight Tensors surfaced to the user (reference: Layer::weights)
        self.weights: List[Tensor] = []

    def add_weight(self, wname, shape, dtype, initializer) -> Tensor:
        self.weight_specs[wname] = (tuple(int(s) for s in shape), dtype, initializer)
        t = Tensor(shape, dtype, owner_layer=self, owner_idx=-len(self.weight_specs),
                   name=f"{self.name}.{wname}")
        self.weights.append(t)
        return t

    def get_parameter_by_id(self, idx: int) -> Tensor:
        return self.weights[idx]

    # named accessors (reference: flexflow_cffi.py Linear/Conv2D layer
    # wrappers :175-215 — get_weight/bias/input/output_tensor)
    def get_weight_tensor(self) -> Tensor:
        return self.weights[0]

    def get_bias_tensor(self) -> Tensor:
        assert len(self.weights) > 1, f"{self.name} has no bias"
        return self.weights[1]

    def get_input_tensor(self, idx: int = 0) -> Tensor:
        return self.inputs[idx]

    def get_output_tensor(self, idx: int = 0) -> Tensor:
        return self.outputs[idx]

    def __repr__(self) -> str:
        return f"Layer({self.name}, {self.op_type.name}, in={[t.name for t in self.inputs]})"
