"""Native (C++) runtime core, loaded via ctypes.

Builds lazily with g++ on first use (no pybind11 in the image; plain C ABI).
Every entry point has a pure-Python fallback so the framework works without a
compiler — but the native path is the default where it matters (dataloader
gather, search-time task-graph simulation).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "ffnative.cpp")
_SO = os.path.join(_HERE, "libffnative.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
             _SRC, "-o", _SO],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            if not _build():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _build_failed = True
            return None
        lib.gather_rows.restype = ctypes.c_int
        lib.gather_rows.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int]
        lib.simulate_taskgraph.restype = ctypes.c_double
        lib.simulate_taskgraph.argtypes = [
            ctypes.c_int64, ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32)]
        lib.pipeline_create.restype = ctypes.c_void_p
        lib.pipeline_create.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int]
        lib.pipeline_next.restype = ctypes.c_int64
        lib.pipeline_next.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_void_p)]
        lib.pipeline_destroy.restype = None
        lib.pipeline_destroy.argtypes = [ctypes.c_void_p]
        lib.imm_dominators_native.restype = ctypes.c_int
        lib.imm_dominators_native.argtypes = [
            ctypes.c_int32, ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32)]
        _lib = lib
        return _lib


def gather_rows(src: np.ndarray, indices: np.ndarray,
                n_threads: int = 4) -> np.ndarray:
    """dst[i] = src[indices[i]] — native multithreaded gather with numpy
    fallback (the dataloader's shuffled-batch staging hot loop)."""
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    n = src.shape[0]
    if idx.size:
        lo, hi = int(idx.min()), int(idx.max())
        if lo < -n or hi >= n:
            raise IndexError(
                f"gather_rows: index out of range for {n} rows "
                f"(min {lo}, max {hi})")
        if lo < 0:  # numpy negative-index semantics on both paths
            idx = np.where(idx < 0, idx + n, idx)
    lib = get_lib()
    if lib is None:
        return src[idx]
    out_shape = (len(idx),) + src.shape[1:]
    dst = np.empty(out_shape, dtype=src.dtype)
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], initial=1))
    rc = lib.gather_rows(
        src.ctypes.data_as(ctypes.c_void_p),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        dst.ctypes.data_as(ctypes.c_void_p),
        len(idx), row_bytes, n_threads)
    if rc != 0:
        return src[idx]
    return dst


def simulate_taskgraph(costs: np.ndarray, device: np.ndarray,
                       n_devices: int, edges_src: np.ndarray,
                       edges_dst: np.ndarray) -> float:
    """Event-driven task-graph makespan (native; Python fallback)."""
    costs = np.ascontiguousarray(costs, dtype=np.float64)
    device = np.ascontiguousarray(device, dtype=np.int32)
    esrc = np.ascontiguousarray(edges_src, dtype=np.int32)
    edst = np.ascontiguousarray(edges_dst, dtype=np.int32)
    lib = get_lib()
    if lib is not None:
        r = lib.simulate_taskgraph(
            len(costs), costs.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            device.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n_devices, len(esrc),
            esrc.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            edst.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if r < 0:
            raise ValueError(
                "simulate_taskgraph: invalid task graph "
                "(cycle, bad edge, or device id out of range)")
        return float(r)
    return _simulate_py(costs, device, n_devices, esrc, edst)


class BatchPipeline:
    """Double-buffered shuffled-batch staging with a native gather thread:
    batch b+1 is assembled in C++ while Python ships batch b to the device
    (the reference overlaps its zcmem->fbmem batch copy with compute the same
    way).

    With ``copy=True`` (default) each yielded batch is an owned array, safe to
    retain. ``copy=False`` yields zero-copy views into the native double
    buffer — only valid until the next batch is pulled and only for consumers
    that ship the batch to the device before advancing.

    Falls back to synchronous numpy gather when the native library is
    unavailable."""

    def __init__(self, arrays, indices: np.ndarray, batch_size: int,
                 n_threads: int = 4, copy: bool = True):
        self.copy = copy
        self.arrays = [np.ascontiguousarray(a) for a in arrays]
        self.indices = np.ascontiguousarray(indices, dtype=np.int64)
        self.batch_size = int(batch_size)
        self.num_batches = len(self.indices) // self.batch_size
        self._lib = get_lib()
        self._h = None
        if self._lib is not None and self.num_batches > 0:
            n = len(self.arrays)
            self._src_ptrs = (ctypes.c_void_p * n)(
                *[a.ctypes.data_as(ctypes.c_void_p).value
                  for a in self.arrays])
            self._row_bytes = (ctypes.c_int64 * n)(
                *[a.dtype.itemsize * int(np.prod(a.shape[1:], initial=1))
                  for a in self.arrays])
            self._h = self._lib.pipeline_create(
                n, self._src_ptrs, self._row_bytes,
                self.indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                len(self.indices), self.batch_size, n_threads)

    def __iter__(self):
        if self._h is None:  # fallback: synchronous gather
            for b in range(self.num_batches):
                sl = self.indices[b * self.batch_size:(b + 1) *
                                  self.batch_size]
                yield [a[sl] for a in self.arrays]
            return
        n = len(self.arrays)
        out_ptrs = (ctypes.c_void_p * n)()
        try:
            while True:
                b = self._lib.pipeline_next(self._h, out_ptrs)
                if b < 0:
                    break
                views = []
                for i, a in enumerate(self.arrays):
                    shape = (self.batch_size,) + a.shape[1:]
                    buf = (ctypes.c_char * (
                        self.batch_size * self._row_bytes[i])).from_address(
                        out_ptrs[i])
                    v = np.frombuffer(buf, dtype=a.dtype).reshape(shape)
                    views.append(v.copy() if self.copy else v)
                yield views
        finally:
            self.close()

    def close(self):
        if self._h is not None:
            self._lib.pipeline_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def imm_dominators_edges(n: int, edges):
    """Immediate dominators of an int-id DAG. edges: iterable of (src, dst).
    Returns an int32 array with -1 for roots, or None when the native library
    is unavailable. Raises ValueError on cycles."""
    lib = get_lib()
    if lib is None:
        return None
    esrc = np.ascontiguousarray([e[0] for e in edges], dtype=np.int32)
    edst = np.ascontiguousarray([e[1] for e in edges], dtype=np.int32)
    out = np.empty(n, dtype=np.int32)
    rc = lib.imm_dominators_native(
        n, len(esrc),
        esrc.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        edst.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc == -2:
        raise ValueError("imm_dominators: graph has a cycle")
    if rc != 0:
        raise ValueError("imm_dominators: invalid edge list")
    return out


def _simulate_py(costs, device, n_devices, esrc, edst) -> float:
    import heapq

    n = len(costs)
    out = [[] for _ in range(n)]
    indeg = [0] * n
    for s, d in zip(esrc, edst):
        out[s].append(int(d))
        indeg[d] += 1
    if any(int(d) < 0 or int(d) >= n_devices for d in device):
        raise ValueError("simulate_taskgraph: device id out of range")
    ready = [0.0] * n
    dev_free = [0.0] * max(n_devices, 1)
    q = [(0.0, i) for i in range(n) if indeg[i] == 0]
    heapq.heapify(q)
    makespan = 0.0
    done = 0
    while q:
        rt, t = heapq.heappop(q)
        dev = int(device[t])
        start = max(rt, dev_free[dev])
        finish = start + float(costs[t])
        dev_free[dev] = finish
        makespan = max(makespan, finish)
        done += 1
        for c in out[t]:
            ready[c] = max(ready[c], finish)
            indeg[c] -= 1
            if indeg[c] == 0:
                heapq.heappush(q, (ready[c], c))
    if done != n:
        raise ValueError("simulate_taskgraph: task graph has a cycle")
    return makespan
