// Native runtime core for flexflow_tpu.
//
// TPU-native equivalents of the reference's host-side C++ runtime pieces:
//  * gather_rows: multithreaded batch gather/staging — the hot loop of the
//    dataloader (reference: python/flexflow_dataloader.cc:574, which stages
//    batches from zero-copy memory with index-launched copies; here the
//    host-side gather feeding jax.device_put).
//  * simulate_taskgraph: event-driven list-scheduling simulation of a task
//    graph with per-task costs and dependency edges — the inner loop of the
//    strategy simulator (reference: Simulator::simulate_runtime,
//    src/runtime/simulator.cc:815), called thousands of times by the search.
//
// Built as a plain shared library, loaded via ctypes (no pybind11 in image).
#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <queue>
#include <thread>
#include <vector>

extern "C" {

// Gather rows from src into dst: dst[i] = src[indices[i]] for row_bytes-sized
// rows. Multithreaded memcpy; returns 0 on success.
int gather_rows(const void* src, const int64_t* indices, void* dst,
                int64_t n_rows, int64_t row_bytes, int n_threads) {
  if (!src || !dst || !indices || n_rows < 0 || row_bytes <= 0) return -1;
  if (n_threads <= 0) n_threads = 1;
  n_threads = std::min<int64_t>(n_threads, std::max<int64_t>(n_rows, 1));
  const char* s = static_cast<const char*>(src);
  char* d = static_cast<char*>(dst);

  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      std::memcpy(d + i * row_bytes, s + indices[i] * row_bytes, row_bytes);
    }
  };
  if (n_threads == 1) {
    worker(0, n_rows);
    return 0;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (n_rows + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min<int64_t>(lo + chunk, n_rows);
    if (lo >= hi) break;
    threads.emplace_back(worker, lo, hi);
  }
  for (auto& th : threads) th.join();
  return 0;
}

// Event-driven simulation of a task graph (list scheduling).
//   n_tasks: number of tasks; costs[i]: execution time of task i
//   device[i]: device id executing task i (tasks on one device serialize)
//   n_edges edges src[e] -> dst[e] (dst depends on src)
// Returns the makespan, or -1 on error (e.g. cycle).
double simulate_taskgraph(int64_t n_tasks, const double* costs,
                          const int32_t* device, int32_t n_devices,
                          int64_t n_edges, const int32_t* esrc,
                          const int32_t* edst) {
  if (n_tasks <= 0) return 0.0;
  if (!costs || !device || n_devices <= 0) return -1.0;
  for (int64_t i = 0; i < n_tasks; ++i)
    if (device[i] < 0 || device[i] >= n_devices) return -1.0;
  std::vector<std::vector<int32_t>> out(n_tasks);
  std::vector<int32_t> indeg(n_tasks, 0);
  for (int64_t e = 0; e < n_edges; ++e) {
    if (esrc[e] < 0 || esrc[e] >= n_tasks || edst[e] < 0 ||
        edst[e] >= n_tasks)
      return -1.0;
    out[esrc[e]].push_back(edst[e]);
    indeg[edst[e]]++;
  }
  // ready time per task (dependency-driven), busy-until per device
  std::vector<double> ready(n_tasks, 0.0);
  std::vector<double> dev_free(n_devices, 0.0);
  // priority queue of (ready_time, task) over tasks with indeg 0
  using QE = std::pair<double, int32_t>;
  std::priority_queue<QE, std::vector<QE>, std::greater<QE>> q;
  for (int64_t i = 0; i < n_tasks; ++i)
    if (indeg[i] == 0) q.emplace(0.0, (int32_t)i);
  double makespan = 0.0;
  int64_t done = 0;
  while (!q.empty()) {
    auto [rt, t] = q.top();
    q.pop();
    int32_t dev = device[t];
    double start = std::max(rt, dev_free[dev]);
    double finish = start + costs[t];
    dev_free[dev] = finish;
    makespan = std::max(makespan, finish);
    ++done;
    for (int32_t c : out[t]) {
      ready[c] = std::max(ready[c], finish);
      if (--indeg[c] == 0) q.emplace(ready[c], c);
    }
  }
  if (done != n_tasks) return -1.0;  // cycle
  return makespan;
}

}  // extern "C"
