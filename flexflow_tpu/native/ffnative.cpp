// Native runtime core for flexflow_tpu.
//
// TPU-native equivalents of the reference's host-side C++ runtime pieces:
//  * gather_rows: multithreaded batch gather/staging — the hot loop of the
//    dataloader (reference: python/flexflow_dataloader.cc:574, which stages
//    batches from zero-copy memory with index-launched copies; here the
//    host-side gather feeding jax.device_put).
//  * simulate_taskgraph: event-driven list-scheduling simulation of a task
//    graph with per-task costs and dependency edges — the inner loop of the
//    strategy simulator (reference: Simulator::simulate_runtime,
//    src/runtime/simulator.cc:815), called thousands of times by the search.
//
// Built as a plain shared library, loaded via ctypes (no pybind11 in image).
#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

extern "C" {

// Gather rows from src into dst: dst[i] = src[indices[i]] for row_bytes-sized
// rows. Multithreaded memcpy; returns 0 on success.
int gather_rows(const void* src, const int64_t* indices, void* dst,
                int64_t n_rows, int64_t row_bytes, int n_threads) {
  if (!src || !dst || !indices || n_rows < 0 || row_bytes <= 0) return -1;
  if (n_threads <= 0) n_threads = 1;
  n_threads = std::min<int64_t>(n_threads, std::max<int64_t>(n_rows, 1));
  const char* s = static_cast<const char*>(src);
  char* d = static_cast<char*>(dst);

  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      std::memcpy(d + i * row_bytes, s + indices[i] * row_bytes, row_bytes);
    }
  };
  if (n_threads == 1) {
    worker(0, n_rows);
    return 0;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (n_rows + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min<int64_t>(lo + chunk, n_rows);
    if (lo >= hi) break;
    threads.emplace_back(worker, lo, hi);
  }
  for (auto& th : threads) th.join();
  return 0;
}

// Event-driven simulation of a task graph (list scheduling).
//   n_tasks: number of tasks; costs[i]: execution time of task i
//   device[i]: device id executing task i (tasks on one device serialize)
//   n_edges edges src[e] -> dst[e] (dst depends on src)
// Returns the makespan, or -1 on error (e.g. cycle).
double simulate_taskgraph(int64_t n_tasks, const double* costs,
                          const int32_t* device, int32_t n_devices,
                          int64_t n_edges, const int32_t* esrc,
                          const int32_t* edst) {
  if (n_tasks <= 0) return 0.0;
  if (!costs || !device || n_devices <= 0) return -1.0;
  for (int64_t i = 0; i < n_tasks; ++i)
    if (device[i] < 0 || device[i] >= n_devices) return -1.0;
  std::vector<std::vector<int32_t>> out(n_tasks);
  std::vector<int32_t> indeg(n_tasks, 0);
  for (int64_t e = 0; e < n_edges; ++e) {
    if (esrc[e] < 0 || esrc[e] >= n_tasks || edst[e] < 0 ||
        edst[e] >= n_tasks)
      return -1.0;
    out[esrc[e]].push_back(edst[e]);
    indeg[edst[e]]++;
  }
  // ready time per task (dependency-driven), busy-until per device
  std::vector<double> ready(n_tasks, 0.0);
  std::vector<double> dev_free(n_devices, 0.0);
  // priority queue of (ready_time, task) over tasks with indeg 0
  using QE = std::pair<double, int32_t>;
  std::priority_queue<QE, std::vector<QE>, std::greater<QE>> q;
  for (int64_t i = 0; i < n_tasks; ++i)
    if (indeg[i] == 0) q.emplace(0.0, (int32_t)i);
  double makespan = 0.0;
  int64_t done = 0;
  while (!q.empty()) {
    auto [rt, t] = q.top();
    q.pop();
    int32_t dev = device[t];
    double start = std::max(rt, dev_free[dev]);
    double finish = start + costs[t];
    dev_free[dev] = finish;
    makespan = std::max(makespan, finish);
    ++done;
    for (int32_t c : out[t]) {
      ready[c] = std::max(ready[c], finish);
      if (--indeg[c] == 0) q.emplace(ready[c], c);
    }
  }
  if (done != n_tasks) return -1.0;  // cycle
  return makespan;
}

// ---------------------------------------------------------------------------
// Batch pipeline: double-buffered multi-array shuffled-batch staging with a
// background gather thread — the dataloader's "stage next batch while the
// device runs the current one" loop (reference: the index-launched batch copy
// in python/flexflow_dataloader.cc:208 overlapping with compute).
// ---------------------------------------------------------------------------

struct BatchPipeline {
  std::vector<const char*> srcs;
  std::vector<int64_t> row_bytes;
  std::vector<int64_t> indices;
  int64_t batch_size = 0;
  int64_t num_batches = 0;
  int n_threads = 1;

  // two buffer sets; buffers[s][a] holds batch_size rows of array a
  std::vector<std::vector<std::vector<char>>> buffers;
  int64_t produced = 0;  // next batch index the worker will fill
  int64_t consumed = 0;  // first batch index NOT yet released by the consumer
  int64_t handed = -1;   // batch the consumer currently holds pointers into
  bool stop = false;
  std::mutex mu;
  std::condition_variable cv_produce, cv_consume;
  std::thread worker;

  void gather_batch(int64_t b, int slot) {
    const int64_t lo = b * batch_size;
    const int64_t hi = std::min<int64_t>(lo + batch_size,
                                         (int64_t)indices.size());
    for (size_t a = 0; a < srcs.size(); ++a) {
      char* dst = buffers[slot][a].data();
      const char* s = srcs[a];
      const int64_t rb = row_bytes[a];
      gather_rows(s, indices.data() + lo, dst, hi - lo, rb, n_threads);
    }
  }

  void run() {
    while (true) {
      std::unique_lock<std::mutex> lk(mu);
      cv_produce.wait(lk, [&] {
        return stop || (produced < num_batches && produced - consumed < 2);
      });
      if (stop || produced >= num_batches) return;
      int64_t b = produced;
      lk.unlock();
      gather_batch(b, (int)(b % 2));
      lk.lock();
      produced = b + 1;
      cv_consume.notify_one();
    }
  }
};

BatchPipeline* pipeline_create(int n_arrays, const void** srcs,
                               const int64_t* row_bytes,
                               const int64_t* indices, int64_t n_rows,
                               int64_t batch_size, int n_threads) {
  if (n_arrays <= 0 || !srcs || !row_bytes || !indices || n_rows < 0 ||
      batch_size <= 0)
    return nullptr;
  auto* p = new BatchPipeline();
  for (int a = 0; a < n_arrays; ++a) {
    p->srcs.push_back(static_cast<const char*>(srcs[a]));
    p->row_bytes.push_back(row_bytes[a]);
  }
  p->indices.assign(indices, indices + n_rows);
  p->batch_size = batch_size;
  p->num_batches = n_rows / batch_size;  // drop remainder
  p->n_threads = n_threads > 0 ? n_threads : 1;
  p->buffers.resize(2);
  for (int s = 0; s < 2; ++s) {
    p->buffers[s].resize(n_arrays);
    for (int a = 0; a < n_arrays; ++a)
      p->buffers[s][a].resize((size_t)batch_size * row_bytes[a]);
  }
  p->worker = std::thread([p] { p->run(); });
  return p;
}

// Blocks until the next batch is staged; fills out_ptrs with one pointer per
// array into the ready buffer (valid until the NEXT pipeline_next call).
// Returns the batch index, or -1 when exhausted. The buffer slot of the
// PREVIOUSLY returned batch is released here — not when it was handed out —
// so the worker can never overwrite a batch the consumer still holds.
int64_t pipeline_next(BatchPipeline* p, void** out_ptrs) {
  if (!p || !out_ptrs) return -1;
  std::unique_lock<std::mutex> lk(p->mu);
  if (p->handed >= 0) {
    p->consumed = p->handed + 1;
    p->cv_produce.notify_one();
  }
  const int64_t b = (p->handed >= 0) ? p->handed + 1 : 0;
  if (b >= p->num_batches) return -1;
  p->cv_consume.wait(lk, [&] { return p->produced > b; });
  for (size_t a = 0; a < p->srcs.size(); ++a)
    out_ptrs[a] = p->buffers[b % 2][a].data();
  p->handed = b;
  return b;
}

void pipeline_destroy(BatchPipeline* p) {
  if (!p) return;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
  }
  p->cv_produce.notify_all();
  if (p->worker.joinable()) p->worker.join();
  delete p;
}

// ---------------------------------------------------------------------------
// Immediate (post-)dominators on an int32 edge-list DAG — the structural
// analysis behind bottleneck-based sequence splits (reference:
// include/flexflow/dominators.h, Graph::find_bottleneck_node). Iterative
// Cooper-Harvey-Kennedy on a reverse-post-order.
// Returns 0 on success; out_idom[i] = immediate dominator, or -1 for roots /
// unreachable nodes. For post-dominators, call with the edge list reversed.
// ---------------------------------------------------------------------------

int imm_dominators_native(int32_t n, int64_t n_edges, const int32_t* esrc,
                          const int32_t* edst, int32_t* out_idom) {
  if (n <= 0 || !out_idom) return -1;
  // virtual super-root R = n with an edge to every real root, so the
  // intersect walk has a single fixed point even with multiple roots
  const int32_t R = n;
  std::vector<std::vector<int32_t>> preds(n + 1), succs(n + 1);
  std::vector<int32_t> indeg(n + 1, 0);
  for (int64_t e = 0; e < n_edges; ++e) {
    if (esrc[e] < 0 || esrc[e] >= n || edst[e] < 0 || edst[e] >= n) return -1;
    preds[edst[e]].push_back(esrc[e]);
    succs[esrc[e]].push_back(edst[e]);
    indeg[edst[e]]++;
  }
  for (int32_t i = 0; i < n; ++i)
    if (preds[i].empty()) {
      preds[i].push_back(R);
      succs[R].push_back(i);
      indeg[i]++;
    }
  // topological order (Kahn); doubles as reverse-post-order for a DAG
  std::vector<int32_t> topo;
  topo.reserve(n + 1);
  std::queue<int32_t> q;
  q.push(R);
  std::vector<int32_t> deg = indeg;
  while (!q.empty()) {
    int32_t u = q.front();
    q.pop();
    topo.push_back(u);
    for (int32_t v : succs[u])
      if (--deg[v] == 0) q.push(v);
  }
  if ((int32_t)topo.size() != n + 1) return -2;  // cycle
  std::vector<int32_t> order(n + 1);
  for (size_t i = 0; i < topo.size(); ++i) order[topo[i]] = (int32_t)i;

  std::vector<int32_t> idom(n + 1, -1);
  idom[R] = R;
  auto intersect = [&](int32_t a, int32_t b) {
    while (a != b) {
      while (order[a] > order[b]) a = idom[a];
      while (order[b] > order[a]) b = idom[b];
    }
    return a;
  };
  bool changed = true;
  while (changed) {
    changed = false;
    for (int32_t u : topo) {
      if (u == R) continue;
      int32_t new_idom = -1;
      for (int32_t p : preds[u]) {
        if (idom[p] == -1) continue;  // not yet processed
        new_idom = (new_idom == -1) ? p : intersect(p, new_idom);
      }
      if (new_idom != -1 && idom[u] != new_idom) {
        idom[u] = new_idom;
        changed = true;
      }
    }
  }
  for (int32_t i = 0; i < n; ++i)
    out_idom[i] = (idom[i] == R || idom[i] == -1) ? -1 : idom[i];
  return 0;
}

}  // extern "C"
