"""Hand-written parallelization strategies.

These are the TPU-native counterparts of the reference's manually-constructed
substitution outputs (create_replicate_linear_combine substitution.cc:3226,
create_partition_attention_combine :3169, DLRM's pre-searched strategy
protobufs examples/cpp/DLRM/strategies/*.pb): known-good hybrid shardings that
(a) validate the parallel IR before the search exists, (b) serve as search
seeds, and (c) are what `--import-strategy` files look like.
"""
from __future__ import annotations

from typing import Optional

from ..ffconst import OperatorType
from ..machine_view import MachineView
from .pcg import PCG
from .strategy import NodeStrategy, Strategy


def hybrid_data_tensor_strategy(pcg: PCG, dp: int, tp: int,
                                data_axis: str = "data",
                                model_axis: str = "model") -> Strategy:
    """Megatron-style DP x TP over a (data, model) mesh.

    Per block: attention q/k/v projections sharded over heads (the reference's
    attribute parallelism), output projection row-sharded (psum by XLA);
    MLP fc1 column-parallel, fc2 row-parallel; embedding tables row
    (vocab)-sharded. Batch dim sharded over ``data`` everywhere.
    """
    s = Strategy(mesh_shape=(dp, tp), axis_names=(data_axis, model_axis),
                 data_axis=data_axis)
    view = MachineView(dim=(dp, tp), stride=(tp, 1))
    axis_sizes = {data_axis: dp, model_axis: tp}

    col_parallel_prev: set = set()  # guids of col-parallel linears
    for node in pcg.topo_order():
        ns = s.for_node(node.guid)
        ns.view = view
        op = node.op
        if op.op_type == OperatorType.OP_MULTIHEAD_ATTENTION:
            ns.weight_specs = {
                "wq": (None, model_axis, None),
                "wk": (None, model_axis, None),
                "wv": (None, model_axis, None),
                "wo": (model_axis, None, None),
                "bo": (None,),
            }
            # output fully reduced, batch-sharded (Reduction semantics)
            ndim = len(node.out_shapes[0])
            ns.output_spec = (data_axis,) + (None,) * (ndim - 1)
        elif op.op_type == OperatorType.OP_LINEAR:
            producer = _transitive_producer(pcg, node)
            if producer in col_parallel_prev:
                # row-parallel: contract the sharded dim; XLA inserts psum
                ns.weight_specs = {"kernel": (model_axis, None),
                                   "bias": (None,)}
                ndim = len(node.out_shapes[0])
                ns.output_spec = (data_axis,) + (None,) * (ndim - 1)
            else:
                # column-parallel
                ns.weight_specs = {"kernel": (None, model_axis),
                                   "bias": (model_axis,)}
                col_parallel_prev.add(node.guid)
        elif op.op_type == OperatorType.OP_EMBEDDING:
            # table-sharded over vocab (DLRM-style parameter parallelism);
            # XLA handles the masked gather + psum
            ns.weight_specs = {"weight": (model_axis, None)}
            ndim = len(node.out_shapes[0])
            ns.output_spec = (data_axis,) + (None,) * (ndim - 1)
        elif op.op_type == OperatorType.OP_CONV2D:
            # channel-out (parameter) parallel
            ns.weight_specs = {"kernel": (None, None, None, model_axis),
                               "bias": (model_axis,)}
        _validate_node_specs(pcg, node, ns, axis_sizes)
    return s


def _validate_node_specs(pcg: PCG, node, ns: NodeStrategy, axis_sizes) -> None:
    """Drop shardings whose dim isn't divisible by the axis size (the
    reference's get_valid_machine_views plays this role, graph.h:230)."""
    in_shapes = [pcg.nodes[g].out_shapes[i] for g, i in node.inputs]
    wspecs = node.op.weight_specs(in_shapes)
    for wname in list(ns.weight_specs):
        if wname not in wspecs:
            del ns.weight_specs[wname]
            continue
        shape = wspecs[wname][0]
        entries = list(ns.weight_specs[wname])
        for d, ax in enumerate(entries):
            if ax is None or d >= len(shape):
                continue
            size = axis_sizes.get(ax, 1)
            if shape[d] % size != 0:
                entries[d] = None
        ns.weight_specs[wname] = tuple(entries)
    if ns.output_spec is not None:
        oshape = node.out_shapes[0]
        entries = list(ns.output_spec)
        for d, ax in enumerate(entries):
            if ax is not None and oshape[d] % axis_sizes.get(ax, 1) != 0:
                entries[d] = None
        ns.output_spec = tuple(entries)


def _transitive_producer(pcg: PCG, node) -> Optional[int]:
    """Walk back through unary/elementwise ops to the producing heavy op."""
    passthrough = {
        OperatorType.OP_RELU, OperatorType.OP_GELU, OperatorType.OP_TANH,
        OperatorType.OP_SIGMOID, OperatorType.OP_ELU, OperatorType.OP_DROPOUT,
        OperatorType.OP_IDENTITY, OperatorType.OP_SCALAR_MULTIPLY,
        OperatorType.OP_SCALAR_ADD, OperatorType.OP_CAST,
    }
    g, i = node.inputs[0] if node.inputs else (None, 0)
    while g is not None:
        prod = pcg.nodes[g]
        if prod.op.op_type in passthrough and prod.inputs:
            g, i = prod.inputs[0]
            continue
        return prod.guid
    return None


def long_context_strategy(pcg: PCG, dp: int, sp: int,
                          data_axis: str = "data",
                          seq_axis: str = "seq",
                          mode: str = "ring") -> Strategy:
    """Sequence/context parallelism: activations sharded over the seq dim,
    attention computed over the ``seq`` mesh axis with one of two schedules
    — ``mode="ring"`` (k/v rotation, kernels/ring_attention.py, O((s/P)^2)
    score memory) or ``mode="alltoall"`` (Ulysses head re-partition,
    kernels/ulysses_attention.py, 4 all-to-alls; needs heads % sp == 0).
    No reference analog (SURVEY §5) — the long-context extension the
    reference lacks."""
    assert mode in ("ring", "alltoall"), \
        f"mode must be 'ring' or 'alltoall', got {mode!r}"
    s = Strategy(mesh_shape=(dp, sp), axis_names=(data_axis, seq_axis),
                 data_axis=data_axis)
    view = MachineView(dim=(dp, sp), stride=(sp, 1))
    for node in pcg.topo_order():
        ns = s.for_node(node.guid)
        ns.view = view
        ot = node.op.op_type
        if ot == OperatorType.OP_MULTIHEAD_ATTENTION:
            ns.extra["sequence_parallel_axis"] = seq_axis
            if mode != "ring":
                ns.extra["sequence_parallel_mode"] = mode
            # output stays seq-sharded: (batch, seq, hidden)
            ns.output_spec = (data_axis, seq_axis, None)
        elif len(node.out_shapes[0]) >= 3 and \
                node.out_shapes[0][1] % max(sp, 1) == 0:
            # keep 3D activations sharded over seq between blocks
            ndim = len(node.out_shapes[0])
            ns.output_spec = (data_axis, seq_axis) + (None,) * (ndim - 2)
    return s


def expert_parallel_strategy(pcg: PCG, dp: int, ep: int,
                             data_axis: str = "data",
                             expert_axis: str = "expert") -> Strategy:
    """Shard MoE expert Linears over an expert axis: expert i's weights live on
    mesh column i % ep (reference: per-expert MachineViews on group_by outputs).
    Realized by replicating the expert dense weights only over ``data`` and
    round-robin-sharding via distinct submesh specs is not expressible in pure
    SPMD — instead we shard each expert's weight over ``expert`` jointly, which
    XLA turns into balanced expert placement."""
    s = Strategy(mesh_shape=(dp, ep), axis_names=(data_axis, expert_axis),
                 data_axis=data_axis)
    view = MachineView(dim=(dp, ep), stride=(ep, 1))
    for node in pcg.topo_order():
        ns = s.for_node(node.guid)
        ns.view = view
        if node.op.op_type == OperatorType.OP_LINEAR and \
                "moe_expert" in node.name:
            # shard each expert's FFN over the expert axis (out-dim); the
            # grouped batch stays replicated over ep — tokens meet weights
            # where they live
            ns.weight_specs = {"kernel": (None, expert_axis),
                               "bias": (expert_axis,)}
    return s
