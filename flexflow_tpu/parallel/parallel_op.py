"""Parallel operators — the parallelism vocabulary of the PCG.

Reference: src/parallel_ops/ — Repartition/Combine/Replicate/Reduction/
FusedParallelOp are first-class graph nodes inserted by the search; each
realizes data movement via a Legion partition + copy kernel
(e.g. combine_kernels.cu:27, reduction_kernels.cu:24-34).

TPU-native lowering (SURVEY §7): a parallel op is a **resharding node**. Under
``jax.jit`` + SPMD it emits ``lax.with_sharding_constraint`` with the op's
target sharding; XLA's partitioner then materializes the minimal collective
(all-gather for Combine, slice/all-to-all for Repartition, broadcast for
Replicate, reduce-scatter/psum for Reduction) over ICI — replacing the
reference's hand-built partitions. The nodes stay first-class so the Unity
search can insert/remove/fuse them and cost their communication exactly like
the reference does.

attrs (all): ``dim`` (tensor dim), ``degree``, ``axes`` (mesh axes involved).
The node's target ParallelTensorShape is attached by the strategy assignment
(``target_pts``).
"""
from __future__ import annotations

from typing import Optional

from ..ffconst import OperatorType
from ..ops.base import Op, OpContext, register_op
from ..parallel_tensor import ParallelTensorShape


class ParallelOpBase(Op):
    """Common base (reference: include/flexflow/parallel_ops/parallel_op.h)."""

    is_parallel_op = True

    def __init__(self, name, attrs, dtype, num_inputs=1):
        super().__init__(name, attrs, dtype, num_inputs)
        self.target_pts: Optional[ParallelTensorShape] = None

    def infer_output_shapes(self, input_shapes):
        # parallel ops never change the *global* logical shape
        return [input_shapes[0]]

    def _constrain(self, x, ctx: OpContext):
        if ctx.mesh is None or self.target_pts is None:
            return x
        import jax.lax as lax
        from jax.sharding import NamedSharding

        spec = self.target_pts.partition_spec()
        return lax.with_sharding_constraint(x, NamedSharding(ctx.mesh, spec))

    def forward(self, params, inputs, ctx: OpContext):
        return [self._constrain(inputs[0], ctx)]

    # comm-volume hook for the simulator: bytes moved per device
    def comm_bytes(self, input_shape, dtype_size: int, num_devices: int) -> int:
        raise NotImplementedError


@register_op(OperatorType.OP_REPARTITION)
class RepartitionOp(ParallelOpBase):
    """Split dim ``dim`` into ``degree`` parts (reference: partition.cc).
    Fwd comm: resharding scatter; XLA emits slice or all-to-all."""

    def comm_bytes(self, input_shape, dtype_size, num_devices):
        import numpy as np

        # worst case: every element moves once
        return int(np.prod(input_shape)) * dtype_size // max(num_devices, 1)


@register_op(OperatorType.OP_COMBINE)
class CombineOp(ParallelOpBase):
    """Merge shards of dim ``dim`` back, degree /= k (reference: combine.cc).
    Fwd comm: all-gather of the dim."""

    def comm_bytes(self, input_shape, dtype_size, num_devices):
        import numpy as np

        deg = self.attrs.get("degree", 1)
        return int(np.prod(input_shape)) * dtype_size * (deg - 1) // max(deg, 1)


@register_op(OperatorType.OP_REPLICATE)
class ReplicateOp(ParallelOpBase):
    """Add/grow a replica dim — broadcast fwd, grad-sum bwd
    (reference: replicate.cc). XLA: broadcast on fwd, psum in autodiff."""

    def comm_bytes(self, input_shape, dtype_size, num_devices):
        import numpy as np

        deg = self.attrs.get("degree", 1)
        return int(np.prod(input_shape)) * dtype_size * (deg - 1) // max(deg, 1)


@register_op(OperatorType.OP_REDUCTION)
class ReductionOp(ParallelOpBase):
    """Sum over a replica dim, e.g. after a row-parallel linear
    (reference: reduction.cc). XLA: reduce-scatter/psum emitted when the
    contraction dim was sharded; the node pins the reduced output sharding."""

    def comm_bytes(self, input_shape, dtype_size, num_devices):
        import numpy as np

        deg = self.attrs.get("degree", 1)
        return int(np.prod(input_shape)) * dtype_size * (deg - 1) // max(deg, 1)


@register_op(OperatorType.OP_FUSED_PARALLEL)
class FusedParallelOp(ParallelOpBase):
    """A pipeline of parallel ops collapsed into one resharding
    (reference: fused_parallel_op.cc; built by fuse_parallel_ops,
    graph.h:285-290). attrs: ``ops`` = list of (OperatorType, dim, degree).
    Under XLA one with_sharding_constraint to the final sharding subsumes the
    chain — exactly the fusion the reference implements by hand."""

    def comm_bytes(self, input_shape, dtype_size, num_devices):
        import numpy as np

        return int(np.prod(input_shape)) * dtype_size


@register_op(OperatorType.OP_ALLTOALL)
class AllToAllOp(ParallelOpBase):
    """TPU-native extension: explicit all-to-all resharding for expert/sequence
    parallelism (no reference analog; OP_PIPELINE-style enum slot). Swaps the
    sharded dim: attrs ``src_dim`` -> ``dst_dim``."""

    def comm_bytes(self, input_shape, dtype_size, num_devices):
        import numpy as np

        return int(np.prod(input_shape)) * dtype_size
