"""Parallelization strategy: per-op sharding assignments over the mesh.

This is the TPU-native form of the reference's search output — the map
``op -> MachineView`` (``optimal_views``, graph.cc:2163-2320) plus the
parallel-op placements. A ``Strategy`` assigns every PCG node:

* ``view``: a MachineView (kept for parity/serialization),
* per-weight PartitionSpec entries,
* an optional output sharding constraint (what parallel ops pin).

Strategies serialize to JSON for ``--export-strategy`` / ``--import-strategy``
(reference: config.h:143-144, README.md:84-86).
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Optional, Sequence, Tuple

from ..machine_view import MachineView
from .pcg import PCG

# A spec entry is None or a mesh-axis name or tuple of names, one per tensor dim
SpecT = Tuple[Optional[Any], ...]


@dataclasses.dataclass
class NodeStrategy:
    view: MachineView = dataclasses.field(
        default_factory=lambda: MachineView(dim=(1,)))
    weight_specs: Dict[str, SpecT] = dataclasses.field(default_factory=dict)
    output_spec: Optional[SpecT] = None  # constraint on output 0
    # op-level overrides applied at lowering (e.g. sequence_parallel_axis for
    # ring attention); merged into the op's attrs by the Executor
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Strategy:
    mesh_shape: Tuple[int, ...]
    axis_names: Tuple[str, ...]
    node_strategies: Dict[int, NodeStrategy] = dataclasses.field(
        default_factory=dict)
    # input batch sharding axis (the data-parallel dim)
    data_axis: str = "data"
    # GPipe pipeline selected by the search: (pp, dp, n_micro). Training
    # routes through parallel.pipeline.PipelineTrainer; None = pure SPMD.
    pipeline: Optional[Tuple[int, int, int]] = None
    # pipeline schedule the search chose (ISSUE 10): gpipe | 1f1b |
    # interleaved, or "" = unset (strategy predates the schedule axis /
    # was not searched — the trainer then runs the classic gpipe
    # fill-drain). Only meaningful when ``pipeline`` is set; ``--schedule``
    # overrides either way (parallel.pipeline.resolve_schedule).
    schedule: str = ""
    # virtual stage chunks per pipeline device for the interleaved
    # schedule (Megatron interleaved-1F1B's v); 1 for gpipe/1f1b
    virtual_stages: int = 1
    # activation-rematerialization level the search chose (ISSUE 3):
    # none | selective | full, or "" = unset (strategy predates the remat
    # axis / was not searched). The distinction matters: an explicit
    # "none" is a searched decision, while "" lets the execution defaults
    # apply — Executor blocks default to none, PipelineTrainer stages to
    # the classic GPipe full remat. ``--remat`` overrides either way.
    remat: str = ""
    # multi-host placement: (ici_shape, dcn_shape) with
    # ici[i] * dcn[i] == mesh_shape[i]; the mesh is then built with
    # build_hybrid_mesh so an axis's DCN factor never splits an ICI ring
    # (reference: inter- vs intra-node placement, simulator.h:212-606)
    hybrid: Optional[Tuple[Tuple[int, ...], Tuple[int, ...]]] = None
    # pod-level assignment from the hierarchical multi-pod search
    # (docs/multipod.md): (pod count, mode, grad accumulation factor)
    # where mode is "dp" (FSDP-style cross-pod data parallel) or
    # "pipeline" (pods as pipeline stages — the grid itself rides
    # ``pipeline``/``schedule``). None = single-pod / flat-searched.
    pods: Optional[Tuple[int, str, int]] = None

    def for_node(self, guid: int) -> NodeStrategy:
        return self.node_strategies.setdefault(guid, NodeStrategy())

    def describe(self) -> str:
        """Compact human-readable plan id ("mesh=(4, 2) remat=selective"),
        used by strategy-fallback telemetry/obs events and error diagnoses
        (resilience/fallback.py, docs/strategy_safety.md)."""
        bits = [f"mesh={tuple(self.mesh_shape)}"]
        if self.pipeline:
            bits.append(f"pipeline={tuple(self.pipeline)}")
            from .pipeline import describe_schedule

            sched = describe_schedule(self.schedule, self.virtual_stages)
            if sched:
                bits.append(f"schedule={sched}")
        if self.remat and self.remat != "none":
            bits.append(f"remat={self.remat}")
        if self.hybrid:
            bits.append(f"dcn={tuple(self.hybrid[1])}")
        if self.pods:
            bits.append(describe_pods(self.pods))
        return " ".join(bits)

    # -- serialization (reference: export_strategy_file) ------------------------
    def to_json(self, pcg: PCG) -> str:
        out = {
            "mesh_shape": list(self.mesh_shape),
            "axis_names": list(self.axis_names),
            "data_axis": self.data_axis,
            "pipeline": list(self.pipeline) if self.pipeline else None,
            "schedule": self.schedule,
            "virtual_stages": self.virtual_stages,
            "remat": self.remat,
            "hybrid": [list(self.hybrid[0]), list(self.hybrid[1])]
            if self.hybrid else None,
            "pods": list(self.pods) if self.pods else None,
            "nodes": {},
        }
        for guid, ns in self.node_strategies.items():
            if guid not in pcg.nodes:
                continue
            name = pcg.nodes[guid].name
            out["nodes"][name] = {
                "view": {"dim": list(ns.view.dim),
                         "stride": list(ns.view.stride),
                         "start": ns.view.start_device_id},
                "weight_specs": {k: list(v) for k, v in ns.weight_specs.items()},
                "output_spec": list(ns.output_spec) if ns.output_spec else None,
                "extra": {k: v for k, v in ns.extra.items()
                          if isinstance(v, (str, int, float, bool))},
            }
        return json.dumps(out, indent=2)

    @staticmethod
    def from_json(text: str, pcg: PCG) -> "Strategy":
        d = json.loads(text)
        s = Strategy(mesh_shape=tuple(d["mesh_shape"]),
                     axis_names=tuple(d["axis_names"]),
                     data_axis=d.get("data_axis", "data"),
                     pipeline=tuple(d["pipeline"])
                     if d.get("pipeline") else None,
                     schedule=d.get("schedule", "") or "",
                     virtual_stages=int(d.get("virtual_stages", 1) or 1),
                     remat=d.get("remat", "") or "",
                     hybrid=(tuple(d["hybrid"][0]), tuple(d["hybrid"][1]))
                     if d.get("hybrid") else None,
                     pods=(int(d["pods"][0]), str(d["pods"][1]),
                           int(d["pods"][2]))
                     if d.get("pods") else None)
        by_name = {n.name: n.guid for n in pcg.topo_order()}
        for name, nd in d["nodes"].items():
            if name not in by_name:
                continue
            v = nd["view"]
            ns = NodeStrategy(
                view=MachineView(dim=tuple(v["dim"]), stride=tuple(v["stride"]),
                                 start_device_id=v.get("start", 0)),
                weight_specs={k: _despec(x) for k, x in
                              nd.get("weight_specs", {}).items()},
                output_spec=_despec(nd["output_spec"])
                if nd.get("output_spec") else None,
                extra=dict(nd.get("extra", {})))
            s.node_strategies[by_name[name]] = ns
        return s


def _despec(entries):
    return tuple(tuple(e) if isinstance(e, list) else e for e in entries)


def describe_pods(pods: Tuple[int, str, int]) -> str:
    """Compact pod-plan id ("pods=2:dp" / "pods=2:dp(ga=4)") shared by
    Strategy.describe, RankedCandidate.describe and trace_summary — one
    vocabulary for the pod-level assignment everywhere it prints."""
    n, mode, ga = pods
    s = f"pods={n}:{mode}"
    if int(ga or 1) > 1:
        s += f"(ga={ga})"
    return s


def data_parallel_strategy(pcg: PCG, num_devices: int,
                           axis_names: Sequence[str] = ("data",),
                           ) -> Strategy:
    """The reference's default DataParallelism strategy (config.h:95-100,
    mapper.cc:414-427): batch dim sharded over all devices, weights replicated.
    """
    s = Strategy(mesh_shape=(num_devices,), axis_names=tuple(axis_names)[:1],
                 data_axis=tuple(axis_names)[0])
    view = MachineView.data_parallel(num_devices)
    for node in pcg.topo_order():
        ns = s.for_node(node.guid)
        ns.view = view
    return s
