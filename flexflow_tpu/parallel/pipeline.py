"""Pipeline parallelism: stage-partitioned GPipe training over submeshes.

The reference reserves the vocabulary but ships nothing: ``OP_PIPELINE`` is an
enum + task IDs only (ffconst.h:159, model.h:191-193; SURVEY §2.3 "pipeline
parallelism is NOT implemented in this snapshot"). This module goes beyond
reference parity with a working TPU-native design:

* ``split_stages``: contiguous, flops-balanced partition of the PCG's compute
  nodes (cuts preferentially at graph bottlenecks, found via the same
  immediate-post-dominator machinery the reference's sequence splits use).
* ``PipelineTrainer``: GPipe schedule — the global batch is split into
  microbatches; each stage lives on its own submesh of a (pipe, data) device
  grid, with data parallelism inside the stage. Stage backward runs through
  a leveled ``jax.checkpoint`` policy (``remat=`` none|selective|full,
  execution/remat.py — the same machinery as the Executor's remat blocks);
  ``full`` is the classic GPipe recompute-the-stage recipe and the default.
  Stage-boundary activations move between submeshes via ``jax.device_put``
  (ICI transfers on real hardware); JAX's async dispatch overlaps microbatch
  k's stage-s compute with microbatch k+1's stage-(s-1) compute — the GPipe
  bubble is the only serialization, exactly as in the paper.

Gradient semantics match non-pipelined training: with equal microbatches and
mean-reduced losses, the mean of microbatch gradients equals the full-batch
gradient, so ``PipelineTrainer`` is numerically equivalent to ``Executor``'s
fused step (see tests/test_pipeline.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ffconst import LossType, OperatorType, dtype_to_jnp
from .pcg import PCG, PCGNode

BoundaryT = Tuple[int, int]  # (guid, out_idx)


def split_stages(pcg: PCG, n_stages: int) -> List[List[int]]:
    """Contiguous flops-balanced partition of compute nodes into stages.

    Cut points snap to graph bottlenecks when one is within a half-stage of
    the balanced position (minimizes cross-stage traffic: a bottleneck's
    output is the only live tensor at that point)."""
    nodes = pcg.compute_nodes()
    assert n_stages >= 1
    if n_stages == 1 or len(nodes) <= n_stages:
        # degenerate: one node per stage (or single stage)
        if n_stages == 1:
            return [[n.guid for n in nodes]]
        return [[n.guid] for n in nodes][:n_stages - 1] + \
            [[n.guid for n in nodes[n_stages - 1:]]]

    def node_cost(n: PCGNode) -> float:
        in_shapes = [pcg.nodes[g].out_shapes[i] for g, i in n.inputs]
        return float(max(n.op.flops(in_shapes, n.out_shapes), 1))

    costs = [node_cost(n) for n in nodes]
    total = sum(costs)
    bset = set(pcg.bottlenecks())
    pos_of = {n.guid: i for i, n in enumerate(nodes)}
    bot_positions = sorted(pos_of[g] for g in bset if g in pos_of)

    cuts: List[int] = []  # cut AFTER index c
    cum = 0.0
    target = total / n_stages
    half_stage = max(len(nodes) // (2 * n_stages), 1)
    for i, c in enumerate(costs):
        cum += c
        if len(cuts) < n_stages - 1 and cum >= target * (len(cuts) + 1):
            cut = i
            # snap to the nearest bottleneck position within half a stage
            near = [b for b in bot_positions
                    if abs(b - i) <= half_stage and
                    (not cuts or b > cuts[-1]) and b < len(nodes) - 1]
            if near:
                cut = min(near, key=lambda b: abs(b - i))
            if cuts and cut <= cuts[-1]:
                cut = cuts[-1] + 1
            if cut >= len(nodes) - (n_stages - 1 - len(cuts)):
                cut = len(nodes) - (n_stages - 1 - len(cuts)) - 1
            cuts.append(cut)
    while len(cuts) < n_stages - 1:  # pathological cost skew
        nxt = (cuts[-1] + 1) if cuts else 0
        cuts.append(min(nxt, len(nodes) - (n_stages - 1 - len(cuts))))
    out: List[List[int]] = []
    lo = 0
    for c in cuts:
        out.append([n.guid for n in nodes[lo:c + 1]])
        lo = c + 1
    out.append([n.guid for n in nodes[lo:]])
    assert all(out), (cuts, [len(s) for s in out])
    return out


@dataclasses.dataclass
class StageSpec:
    """One pipeline stage: its sub-PCG + boundary wiring."""

    sub_pcg: PCG
    # how to feed the stage, in sub_pcg input-node order:
    #   ("model", input_guid)          — a model input (microbatch slice)
    #   ("stage", src_stage, out_pos)  — output `out_pos` of an earlier stage
    feeds: List[Tuple]
    # which (guid, out_idx) this stage exposes, in order
    outputs: List[BoundaryT]


def build_stage_specs(pcg: PCG, stages: List[List[int]]) -> List[StageSpec]:
    from ..ops.noop import InputOp

    stage_of: Dict[int, int] = {}
    for s, guids in enumerate(stages):
        for g in guids:
            stage_of[g] = s
    model_inputs = {n.guid for n in pcg.input_nodes()}
    final = [n for n in pcg.sinks()
             if n.op.op_type != OperatorType.OP_INPUT][-1]

    # boundary tensors: produced in stage s, consumed in stage > s (or final)
    exposed: List[List[BoundaryT]] = [[] for _ in stages]
    exposed_pos: Dict[BoundaryT, Tuple[int, int]] = {}

    def expose(ref: BoundaryT, s: int):
        if ref not in exposed_pos:
            exposed_pos[ref] = (s, len(exposed[s]))
            exposed[s].append(ref)

    for node in pcg.compute_nodes():
        s = stage_of[node.guid]
        for g, i in node.inputs:
            if g in model_inputs:
                continue
            ps = stage_of[g]
            if ps != s:
                expose((g, i), ps)
    expose((final.guid, 0), stage_of[final.guid])

    specs: List[StageSpec] = []
    for s, guids in enumerate(stages):
        sub = PCG()
        feeds: List[Tuple] = []
        gset = set(guids)
        # placeholders for every external reference, in deterministic order
        ext_refs: List[Tuple[int, int]] = []
        seen = set()
        for g in guids:
            for pg, i in pcg.nodes[g].inputs:
                if pg in gset:
                    continue
                if (pg, i) not in seen:
                    seen.add((pg, i))
                    ext_refs.append((pg, i))
        for pg, i in ext_refs:
            src = pcg.nodes[pg]
            op = InputOp(name=f"s{s}_in_{pg}_{i}",
                         attrs={"shape": src.out_shapes[i],
                                "dtype": src.out_dtypes[i]},
                         dtype=src.out_dtypes[i], num_inputs=0)
            node = PCGNode(guid=-(len(sub.nodes) + 1) * 1000 - pg, op=op,
                           inputs=[],
                           out_shapes=[src.out_shapes[i]],
                           out_dtypes=[src.out_dtypes[i]])
            sub.nodes[node.guid] = node
            sub._order.append(node.guid)
            if pg in model_inputs:
                feeds.append(("model", pg))
            else:
                src_stage, out_pos = exposed_pos[(pg, i)]
                feeds.append(("stage", src_stage, out_pos))
        # map (ext pg, i) -> placeholder guid
        ph = {ref: g for ref, g in zip(ext_refs, list(sub._order))}
        for g in guids:
            n = pcg.nodes[g]
            nn = PCGNode(
                guid=g, op=n.op,
                inputs=[(pg, i) if pg in gset else (ph[(pg, i)], 0)
                        for pg, i in n.inputs],
                out_shapes=list(n.out_shapes), out_dtypes=list(n.out_dtypes))
            sub.nodes[g] = nn
            sub._order.append(g)
        specs.append(StageSpec(sub_pcg=sub, feeds=feeds, outputs=exposed[s]))
    return specs


class PipelineTrainer:
    """GPipe training of an FFModel over a (pipe, data) device grid.

    Usage::

        ff = FFModel(config); ...build layers...; ff.compile(...)  # optional
        trainer = PipelineTrainer(ff, pp=4, dp=2, n_micro=8,
                                  optimizer=AdamOptimizer(ff),
                                  loss_type=LossType...)
        loss = trainer.train_step(x_batch, y_batch)
    """

    def __init__(self, ffmodel, pp: int, dp: int = 1,
                 n_micro: Optional[int] = None, optimizer=None,
                 loss_type: LossType =
                 LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                 devices: Optional[Sequence] = None,
                 init_params: bool = True, remat: str = "full"):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ..execution.optimizers import SGDOptimizer
        from ..execution.remat import REMAT_LEVELS

        if remat not in REMAT_LEVELS:
            raise ValueError(f"remat {remat!r} not in {REMAT_LEVELS}")
        # stage-remat level: the SAME jax.checkpoint policy machinery the
        # Executor's remat blocks use (execution/remat.py) — `full` is the
        # classic GPipe recipe this trainer previously hard-coded as a
        # hand-rolled VJP; `selective` keeps contraction outputs across the
        # stage backward; `none` saves every stage residual in-jit
        self.remat = remat
        self.loss_type = loss_type
        self.pp, self.dp = pp, dp
        self.n_micro = n_micro or pp
        self.optimizer = optimizer or SGDOptimizer(None)

        pcg = ffmodel.pcg if ffmodel.pcg is not None else ffmodel.create_pcg()
        # pipeline over the PRE-fusion graph for clean stage cuts
        self.pcg = pcg
        self.stages = split_stages(pcg, pp)
        self.specs = build_stage_specs(pcg, self.stages)
        self.model_input_order = [n.guid for n in pcg.input_nodes()]
        final = [n for n in pcg.sinks()
                 if n.op.op_type != OperatorType.OP_INPUT][-1]
        self.final_ref = (final.guid, 0)
        self.final_dtype = final.out_dtypes[0]

        devices = list(devices if devices is not None else jax.devices())
        assert len(devices) >= pp * dp, \
            f"need {pp * dp} devices, have {len(devices)}"
        grid = np.array(devices[:pp * dp]).reshape(pp, dp)
        self.meshes = [Mesh(grid[s], ("data",)) for s in range(pp)]
        self.batch_shardings = [
            NamedSharding(self.meshes[s], P("data"))
            for s in range(pp)]
        self._P = P
        self._NamedSharding = NamedSharding

        self._build_stage_fns()
        if init_params:
            self.params = self._init_params()
            self.opt_states = [self.optimizer.init_state(p)
                               for p in self.params]
        else:  # caller seeds via load_params (skips the jitted stage init)
            self.params = None
            self.opt_states = None

    # ------------------------------------------------------------- stage fns
    def _build_stage_fns(self):
        import jax

        from ..execution.losses import loss_value
        from ..ops.base import OpContext

        self._fwd = []
        self._bwd = []
        self._ph_guids = []  # per stage: placeholder guids in feed order

        for s, spec in enumerate(self.specs):
            sub = spec.sub_pcg
            ph_guids = [n.guid for n in sub.topo_order()
                        if n.op.op_type == OperatorType.OP_INPUT]
            self._ph_guids.append(ph_guids)
            out_refs = spec.outputs

            # batch-shaped constants (the gpt2-style position-id pattern,
            # serving/kvcache.is_position_constant) are baked at the FULL
            # batch; a microbatched stage must slice them to its rows or
            # the first elementwise consumer fails to broadcast
            from ..serving.kvcache import is_position_constant

            mb_const = {n.guid for n in sub.topo_order()
                        if n.op.op_type == OperatorType.OP_CONSTANT
                        and is_position_constant(n.op.attrs.get("value"))}

            def make_forward(sub=sub, ph_guids=ph_guids, out_refs=out_refs,
                             mb_const=mb_const):
                def f(params, ins, rng):
                    ctx = OpContext(training=True, rng=rng, aux_losses=[])
                    mb = ins[0].shape[0] if ins else None
                    values: Dict[int, List[Any]] = {}
                    for g, x in zip(ph_guids, ins):
                        values[g] = [x]
                    for node in sub.topo_order():
                        if node.op.op_type == OperatorType.OP_INPUT:
                            continue
                        inputs = [values[g][i] for g, i in node.inputs]
                        node_ctx = OpContext(
                            training=True,
                            rng=(jax.random.fold_in(ctx.rng, node.guid)
                                 if ctx.rng is not None else None),
                            aux_losses=ctx.aux_losses)
                        outs = node.op.forward(
                            params.get(node.name, {}), inputs, node_ctx)
                        if node.guid in mb_const and mb is not None and \
                                outs[0].shape[0] > mb:
                            outs = [outs[0][:mb]] + list(outs[1:])
                        values[node.guid] = outs
                    outs = tuple(values[g][i] for g, i in out_refs)
                    aux = sum(ctx.aux_losses) if ctx.aux_losses else 0.0
                    return outs, aux
                return f

            # leveled stage remat: wrap the stage forward in jax.checkpoint
            # with the trainer's policy, so every differentiation below
            # (mid-stage VJP and last-stage value_and_grad alike) saves
            # only what the level keeps and recomputes the rest
            from ..execution.remat import wrap_remat

            f = wrap_remat(make_forward(), self.remat)
            is_last = (s == len(self.specs) - 1)
            if is_last:
                final_pos = out_refs.index(self.final_ref)
                loss_type = self.loss_type

                def last_fwd(params, ins, labels, rng, _f=f,
                             _pos=final_pos):
                    outs, aux = _f(params, ins, rng)
                    logits = outs[_pos]
                    return loss_value(loss_type, logits, labels) + aux, logits

                def last_bwd(params, ins, labels, rng, _fn=last_fwd):
                    (loss, logits), grads = jax.value_and_grad(
                        _fn, argnums=(0, 1), has_aux=True)(
                            params, ins, labels, rng)
                    return loss, logits, grads[0], grads[1]

                self._fwd.append(jax.jit(last_fwd))
                self._bwd.append(jax.jit(last_bwd))
            else:
                def mid_fwd(params, ins, rng, _f=f):
                    outs, _aux = _f(params, ins, rng)
                    return outs

                def mid_bwd(params, ins, rng, cots, _f=f):
                    # VJP through the policy-wrapped stage forward: what is
                    # saved vs recomputed between the in-jit forward and
                    # backward is the checkpoint policy's call, not ours
                    import jax.numpy as jnp

                    def run(p, i):
                        outs, aux = _f(p, i, rng)
                        return outs, jnp.asarray(aux, jnp.float32)

                    (_outs, _aux), vjp = jax.vjp(run, params, ins)
                    # aux losses add directly to the total loss -> cotangent 1
                    dparams, dins = vjp((cots, jnp.float32(1.0)))
                    return dparams, dins

                self._fwd.append(jax.jit(mid_fwd))
                self._bwd.append(jax.jit(mid_bwd))

        # per-stage jitted optimizer update
        opt = self.optimizer

        def upd(params, grads, state):
            return opt.update(params, grads, state)

        self._upd = [jax.jit(upd) for _ in self.specs]

    # --------------------------------------------------------------- params
    def _init_params(self):
        import jax

        params = []
        for s, spec in enumerate(self.specs):
            sub = spec.sub_pcg

            def init_fn(key, sub=sub):
                out: Dict[str, Dict[str, Any]] = {}
                for node in sub.topo_order():
                    if node.op.op_type == OperatorType.OP_INPUT:
                        continue
                    in_shapes = [sub.nodes[g].out_shapes[i]
                                 for g, i in node.inputs]
                    for i, (wname, (shape, dt, init)) in enumerate(
                            node.op.weight_specs(in_shapes).items()):
                        sub_key = jax.random.fold_in(
                            jax.random.fold_in(key, node.guid), i)
                        out.setdefault(node.name, {})[wname] = init(
                            sub_key, shape, dtype_to_jnp(dt))
                return out

            with self.meshes[s]:
                p = jax.jit(init_fn)(jax.random.PRNGKey(0))
            p = jax.device_put(p, self._NamedSharding(
                self.meshes[s], self._P()))
            params.append(p)
        return params

    def load_params(self, full_params: Dict[str, Dict[str, Any]]):
        """Install externally-initialized params (e.g. from an Executor model
        with the same layer graph) — names match by construction."""
        import jax

        new = []
        for s, spec in enumerate(self.specs):
            names = {n.name for n in spec.sub_pcg.topo_order()
                     if n.op.op_type != OperatorType.OP_INPUT}
            p = {k: v for k, v in full_params.items() if k in names}
            new.append(jax.device_put(
                p, self._NamedSharding(self.meshes[s], self._P())))
        self.params = new
        self.opt_states = [self.optimizer.init_state(p) for p in self.params]

    def export_params(self) -> Dict[str, Dict[str, Any]]:
        """Inverse of load_params: gather the trained per-stage params back
        into one {layer: {weight: host array}} pytree (fit copies them into
        the Executor's params so eval/predict/checkpoint see the training)."""
        out: Dict[str, Dict[str, Any]] = {}
        for p in self.params:
            for lname, ws in p.items():
                out[lname] = {k: np.asarray(v) for k, v in ws.items()}
        return out

    # ---------------------------------------------------------------- train
    def _microbatches(self, arrays: List[np.ndarray]) -> List[List[Any]]:
        n = arrays[0].shape[0]
        mb = n // self.n_micro
        assert mb * self.n_micro == n, \
            f"batch {n} not divisible by n_micro {self.n_micro}"
        assert mb % self.dp == 0, f"microbatch {mb} not divisible by dp"
        return [[a[m * mb:(m + 1) * mb] for a in arrays]
                for m in range(self.n_micro)]

    def train_step(self, x, y, rng_seed: int = 0) -> float:
        """One GPipe step: forward all microbatches through all stages,
        backward in reverse, accumulate grads, apply the optimizer."""
        import jax
        import jax.numpy as jnp

        xs = x if isinstance(x, (list, tuple)) else [x]
        micro = self._microbatches(list(xs) + [y])
        S = len(self.specs)
        key = jax.random.PRNGKey(rng_seed)

        # ---- forward (fill): stage outputs per (microbatch, stage)
        stage_ins: List[List[Tuple]] = [[None] * S for _ in range(self.n_micro)]
        stage_outs: List[List[Tuple]] = [[None] * S
                                         for _ in range(self.n_micro)]
        losses = []
        labels_per_m = []
        for m, arrays in enumerate(micro):
            feed_arrays = dict(zip(self.model_input_order, arrays[:-1]))
            labels_per_m.append(arrays[-1])
            mkey = jax.random.fold_in(key, m)
            for s in range(S):
                ins = []
                for feed in self.specs[s].feeds:
                    if feed[0] == "model":
                        v = jax.device_put(feed_arrays[feed[1]],
                                           self.batch_shardings[s])
                    else:
                        _, src_stage, out_pos = feed
                        v = stage_outs[m][src_stage][out_pos]
                        if src_stage != s:  # cross-submesh transfer
                            v = jax.device_put(
                                v, self.batch_shardings[s])
                    ins.append(v)
                ins = tuple(ins)
                stage_ins[m][s] = ins
                if s < S - 1:
                    stage_outs[m][s] = self._fwd[s](
                        self.params[s], ins, mkey)
                # last stage forward happens fused with backward below

        # ---- backward (drain): reverse stage order per microbatch
        grad_acc: List[Any] = [None] * S
        for m in range(self.n_micro):
            mkey = jax.random.fold_in(key, m)
            labels = jax.device_put(labels_per_m[m],
                                    self.batch_shardings[S - 1])
            loss, logits, dparams, dins = self._bwd[S - 1](
                self.params[S - 1], stage_ins[m][S - 1], labels, mkey)
            losses.append(loss)
            grad_acc[S - 1] = dparams if grad_acc[S - 1] is None else \
                jax.tree_util.tree_map(jnp.add, grad_acc[S - 1], dparams)
            # cotangents flow back through earlier stages; accumulate on the
            # PRODUCING stage's submesh so multi-consumer adds colocate
            cots: Dict[Tuple[int, int], Any] = {}

            def add_cot(src_stage, out_pos, val):
                val = jax.device_put(val, self.batch_shardings[src_stage])
                prev = cots.get((src_stage, out_pos))
                cots[(src_stage, out_pos)] = val if prev is None else \
                    jax.tree_util.tree_map(jnp.add, prev, val)

            for pos, feed in enumerate(self.specs[S - 1].feeds):
                if feed[0] == "stage":
                    add_cot(feed[1], feed[2], dins[pos])
            for s in range(S - 2, -1, -1):
                out_cots = []
                for out_pos in range(len(self.specs[s].outputs)):
                    c = cots.get((s, out_pos))
                    # every exposed output has a later-stage consumer whose
                    # backward already ran
                    assert c is not None, (s, out_pos)
                    out_cots.append(c)
                dparams, dins = self._bwd[s](
                    self.params[s], stage_ins[m][s], mkey, tuple(out_cots))
                grad_acc[s] = dparams if grad_acc[s] is None else \
                    jax.tree_util.tree_map(jnp.add, grad_acc[s], dparams)
                for pos, feed in enumerate(self.specs[s].feeds):
                    if feed[0] == "stage":
                        add_cot(feed[1], feed[2], dins[pos])

        # ---- update: mean of microbatch grads == full-batch grad
        inv = 1.0 / self.n_micro
        for s in range(S):
            grads = jax.tree_util.tree_map(lambda g: g * inv, grad_acc[s])
            self.params[s], self.opt_states[s] = self._upd[s](
                self.params[s], grads, self.opt_states[s])
        return float(jnp.mean(jnp.stack(
            [jax.device_get(l) for l in losses])))

    def fit(self, x, y, epochs: int = 1, batch_size: Optional[int] = None,
            shuffle: bool = False) -> List[float]:
        xs = x if isinstance(x, (list, tuple)) else [x]
        n = xs[0].shape[0]
        bs = batch_size or n
        losses = []
        from ..data.dataloader import batch_iterator

        step = 0
        for ep in range(epochs):
            for arrays in batch_iterator(list(xs) + [y], bs, shuffle=shuffle,
                                         seed=ep):
                loss = self.train_step(arrays[:-1], arrays[-1],
                                       rng_seed=step)
                losses.append(loss)
                step += 1
        return losses
