"""Pipeline parallelism: stage-partitioned pipeline training over submeshes.

The reference reserves the vocabulary but ships nothing: ``OP_PIPELINE`` is an
enum + task IDs only (ffconst.h:159, model.h:191-193; SURVEY §2.3 "pipeline
parallelism is NOT implemented in this snapshot"). This module goes beyond
reference parity with a working TPU-native design:

* ``split_stages``: contiguous, flops-balanced partition of the PCG's compute
  nodes (cuts preferentially at graph bottlenecks, found via the same
  immediate-post-dominator machinery the reference's sequence splits use).
* ``pipeline_schedule``: the schedule generator — ONE source of the
  (phase, microbatch, chunk) execution order for all three schedules
  (``gpipe`` fill/drain, ``1f1b`` PipeDream-flush, ``interleaved``
  Megatron-style virtual chunks), consumed both by the trainer's host
  dispatch loop below and by the simulator's task-graph makespan
  (search/unity.py) — the simulator prices exactly the order the trainer
  runs (the repo's one-artifact-two-consumers rule, like remat segments).
* ``PipelineTrainer``: the global batch is split into microbatches; each
  stage chunk lives on a submesh of a (pipe, data) device grid, with data
  parallelism inside the stage. ``schedule=`` selects the step
  orchestration: ``gpipe`` forwards every microbatch then drains the
  backwards (in-flight boundary activations scale with ``n_micro``);
  ``1f1b`` interleaves microbatch k's backward with microbatch k+pp's
  forward in steady state, capping in-flight activations at ``pp``
  (Narayanan et al., SOSP'19); ``interleaved`` assigns ``v`` virtual stage
  chunks per device round-robin (chunk c on device c % pp, Narayanan et
  al., SC'21), shrinking the pipeline bubble by ~v at a boundary-traffic
  premium. Grad accumulation order (ascending microbatch per chunk) and
  the microbatch-mean update are IDENTICAL across schedules — same stage
  functions, same dispatches, different interleaving — so gpipe and 1f1b
  updates are bitwise-equal (tests/test_pipeline_schedules.py).
  Stage backward runs through a leveled ``jax.checkpoint`` policy
  (``remat=`` none|selective|full, execution/remat.py — the same machinery
  as the Executor's remat blocks); ``full`` is the classic GPipe
  recompute-the-stage recipe and the default. Stage-boundary activations
  move between submeshes via ``jax.device_put`` (ICI transfers on real
  hardware); JAX's async dispatch overlaps the schedule's concurrent
  tasks — the schedule's bubble is the only serialization.

Gradient semantics match non-pipelined training: with equal microbatches and
mean-reduced losses, the mean of microbatch gradients equals the full-batch
gradient, so ``PipelineTrainer`` is numerically equivalent to ``Executor``'s
fused step (see tests/test_pipeline.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ffconst import LossType, OperatorType, dtype_to_jnp
from .pcg import PCG, PCGNode

BoundaryT = Tuple[int, int]  # (guid, out_idx)

# the searched schedule axis (docs/pipeline.md); order = sweep order in
# search/unity.py's pipeline candidates
PIPELINE_SCHEDULES = ("gpipe", "1f1b", "interleaved")


def resolve_schedule(config, strategy) -> Tuple[str, int]:
    """(schedule, virtual_stages) the trainer runs: the ``--schedule`` flag
    wins, then the searched ``strategy.schedule``, then the classic
    ``gpipe``. ``virtual_stages`` (v) is only meaningful for
    ``interleaved`` (``--virtual-stages`` flag > searched value > 2) and is
    pinned to 1 for the single-chunk schedules."""
    sched = (getattr(config, "schedule", "") or "").strip() or \
        (getattr(strategy, "schedule", "") or "") or "gpipe"
    if sched not in PIPELINE_SCHEDULES:
        raise ValueError(
            f"schedule {sched!r} not in {PIPELINE_SCHEDULES}")
    if sched != "interleaved":
        return sched, 1
    v = int(getattr(config, "pipeline_virtual_stages", 0) or 0)
    if v < 2:
        sv = int(getattr(strategy, "virtual_stages", 0) or 0)
        v = sv if sv >= 2 else 2
    return sched, v


def describe_schedule(schedule: str, v: int = 1) -> str:
    """The one display rule for a schedule suffix: '' for gpipe/unset
    (the default needs no annotation), the schedule name otherwise, with
    the interleaved virtual-chunk count appended ('interleaved(v=2)').
    Shared by Strategy.describe, RankedCandidate.describe and
    trace_summary so the three renderings cannot drift."""
    if not schedule or schedule == "gpipe":
        return ""
    if schedule == "interleaved" and int(v or 1) > 1:
        return f"{schedule}(v={v})"
    return schedule


def pipeline_schedule(schedule: str, pp: int, n_micro: int, v: int = 1
                      ) -> List[Tuple[str, int, int]]:
    """The (phase, microbatch, chunk) execution order of one training step,
    phase in {"F", "B"}; chunk c executes on pipeline device c % pp.

    The returned sequence is a valid topological order of the microbatch
    dataflow (F(m,c) after F(m,c-1); B(m,c) after F(m,c) and B(m,c+1)),
    and its per-device projection IS the schedule's device-local order —
    the two properties the trainer's async host dispatch and the
    simulator's per-device order chains respectively rely on.

    ``gpipe`` is the closed-form fill/drain. ``1f1b``/``interleaved`` come
    out of a unit-cost list-scheduling pass with backward-first,
    oldest-microbatch-first device priority: with one chunk per device
    that greedy IS PipeDream-flush 1F1B (a backward becomes runnable
    exactly pp tasks after its forward and preempts younger forwards);
    with v chunks per device it yields the interleaved order (microbatch
    m's chunk c+pp forward becomes ready before microbatch m+pp's chunk
    c). Per chunk, backwards run in ascending microbatch order in every
    schedule — the property that keeps grad accumulation bitwise-stable
    across schedules."""
    if schedule not in PIPELINE_SCHEDULES:
        raise ValueError(
            f"schedule {schedule!r} not in {PIPELINE_SCHEDULES}")
    n_chunks = pp * (v if schedule == "interleaved" else 1)
    if schedule == "gpipe":
        ev = [("F", m, c) for m in range(n_micro) for c in range(n_chunks)]
        ev += [("B", m, c) for m in range(n_micro)
               for c in reversed(range(n_chunks))]
        return ev

    last = n_chunks - 1
    deps: Dict[Tuple[str, int, int], List[Tuple[str, int, int]]] = {}
    for m in range(n_micro):
        for c in range(n_chunks):
            deps[("F", m, c)] = [("F", m, c - 1)] if c else []
            d = [("F", m, c)]
            if c < last:
                d.append(("B", m, c + 1))
            deps[("B", m, c)] = d

    if schedule == "interleaved":
        if n_micro % pp:
            raise ValueError(
                f"interleaved schedule needs n_micro % pp == 0 "
                f"(n_micro={n_micro}, pp={pp}): microbatches advance in "
                "rounds of pp through the virtual chunks — use 1f1b, or "
                "a microbatch count the pipeline depth divides")
        orders = [_interleaved_device_order(pp, d, n_micro, v)
                  for d in range(pp)]
        return _merge_device_orders(orders, deps)

    # 1f1b: unit-cost list scheduling with backward-first priority AND the
    # in-flight cap that makes 1F1B 1F1B — device d may hold at most
    # pp - d microbatches awaiting backward (the PipeDream-flush warmup
    # depth); past the cap it IDLES for its next backward instead of
    # issuing a younger forward. Without the cap a greedy fills stalls
    # with forwards and early stages balloon to ~2pp in-flight — exactly
    # the gpipe memory behavior the schedule exists to avoid. The cap is
    # what pipeline_in_flight charges and the trainer's
    # release-after-backward then actually holds.
    pending: List[List[Tuple[str, int, int]]] = [[] for _ in range(pp)]
    for t in deps:
        pending[t[2] % pp].append(t)
    done_round: Dict[Tuple[str, int, int], int] = {}
    outstanding = [0] * pp  # forwards issued minus backwards completed
    order: List[Tuple[str, int, int]] = []
    total = len(deps)
    rnd = 0
    while len(order) < total:
        if rnd > 2 * total + n_chunks:  # loop guard, not an assert: a
            # stalled generator under python -O must fail loudly, not hang
            raise RuntimeError(
                f"pipeline schedule generator stalled "
                f"({schedule}, pp={pp}, n_micro={n_micro}, v={v})")
        for dev in range(pp):
            cap = pp - dev
            ready = [t for t in pending[dev]
                     if all(done_round.get(x, rnd) < rnd
                            for x in deps[t])
                     and (t[0] == "B" or outstanding[dev] < cap)]
            if not ready:
                continue
            # backward-first (the 1F1B rule), then oldest microbatch
            t = min(ready, key=lambda tk: (tk[0] != "B", tk[1], tk[2]))
            pending[dev].remove(t)
            done_round[t] = rnd
            outstanding[dev] += 1 if t[0] == "F" else -1
            order.append(t)
        rnd += 1
    return order


def _interleaved_device_order(pp: int, d: int, n_micro: int, v: int
                              ) -> List[Tuple[str, int, int]]:
    """Device d's canonical interleaved-1F1B order (Narayanan et al.,
    SC'21; Megatron-LM's forward_backward_pipelining_with_interleaving):
    microbatches advance in rounds of pp through the v virtual chunks —
    forward unit i maps to chunk ((i // pp) % v) of microbatch
    ((i // (pp*v)) * pp + i % pp); backwards mirror with the chunk order
    reversed. Warmup depth (pp - d - 1)*2 + (v - 1)*pp forward units, then
    steady 1F1B alternation, then the cooldown backwards. Chunk c here is
    the GLOBAL chunk id k*pp + d of the device's k-th virtual chunk."""
    N = n_micro * v

    def f_unit(i: int) -> Tuple[str, int, int]:
        k = (i // pp) % v
        m = (i // (pp * v)) * pp + i % pp
        return ("F", m, k * pp + d)

    def b_unit(j: int) -> Tuple[str, int, int]:
        k = v - 1 - (j // pp) % v
        m = (j // (pp * v)) * pp + j % pp
        return ("B", m, k * pp + d)

    warmup = min((pp - d - 1) * 2 + (v - 1) * pp, N)
    seq = [f_unit(i) for i in range(warmup)]
    for j in range(N - warmup):
        seq.append(f_unit(warmup + j))
        seq.append(b_unit(j))
    seq.extend(b_unit(j) for j in range(N - warmup, N))
    return seq


def _merge_device_orders(orders: List[List[Tuple[str, int, int]]],
                         deps: Dict[Tuple[str, int, int],
                                    List[Tuple[str, int, int]]]
                         ) -> List[Tuple[str, int, int]]:
    """Linearize per-device orders into one global sequence that is a
    valid topological order of ``deps`` while preserving every device's
    relative order (what the trainer's per-device FIFO dispatch needs)."""
    order: List[Tuple[str, int, int]] = []
    emitted = set()
    idx = [0] * len(orders)
    total = sum(len(o) for o in orders)
    while len(order) < total:
        progressed = False
        for d, seq in enumerate(orders):
            while idx[d] < len(seq):
                t = seq[idx[d]]
                if any(x not in emitted for x in deps[t]):
                    break
                order.append(t)
                emitted.add(t)
                idx[d] += 1
                progressed = True
        if not progressed:  # loop guard, not an assert (python -O)
            raise RuntimeError("interleaved device orders deadlocked")
    return order


def pipeline_in_flight(schedule: str, pp: int, n_micro: int, v: int = 1
                       ) -> int:
    """Peak in-flight microbatches per pipeline device under ``schedule`` —
    how many microbatches' boundary activations a device holds awaiting
    backward. THE shared memory-accounting term: the trainer retains
    exactly this many (it releases a microbatch's stage inputs/outputs as
    its backward completes) and ``simulate_pipeline`` charges exactly this
    many (docs/pipeline.md). ``gpipe`` drains nothing until the flush
    (n_micro); ``1f1b`` caps at the pipeline depth pp; ``interleaved``
    pays an extra ~pp/v of warmup depth for its shorter fill:
    pp*(2v-1)/v, which degenerates to pp at v=1."""
    if schedule == "gpipe":
        return max(n_micro, 1)
    if schedule == "1f1b":
        return max(min(pp, n_micro), 1)
    v = max(v, 1)
    return max(min((pp * (2 * v - 1) + v - 1) // v, n_micro), 1)


def split_stages(pcg: PCG, n_stages: int) -> List[List[int]]:
    """Contiguous flops-balanced partition of compute nodes into stages.

    Cut points snap to graph bottlenecks when one is within a half-stage of
    the balanced position (minimizes cross-stage traffic: a bottleneck's
    output is the only live tensor at that point)."""
    nodes = pcg.compute_nodes()
    assert n_stages >= 1
    if n_stages == 1 or len(nodes) <= n_stages:
        # degenerate: one node per stage (or single stage)
        if n_stages == 1:
            return [[n.guid for n in nodes]]
        return [[n.guid] for n in nodes][:n_stages - 1] + \
            [[n.guid for n in nodes[n_stages - 1:]]]

    def node_cost(n: PCGNode) -> float:
        in_shapes = [pcg.nodes[g].out_shapes[i] for g, i in n.inputs]
        return float(max(n.op.flops(in_shapes, n.out_shapes), 1))

    costs = [node_cost(n) for n in nodes]
    total = sum(costs)
    bset = set(pcg.bottlenecks())
    pos_of = {n.guid: i for i, n in enumerate(nodes)}
    bot_positions = sorted(pos_of[g] for g in bset if g in pos_of)

    cuts: List[int] = []  # cut AFTER index c
    cum = 0.0
    target = total / n_stages
    half_stage = max(len(nodes) // (2 * n_stages), 1)
    for i, c in enumerate(costs):
        cum += c
        if len(cuts) < n_stages - 1 and cum >= target * (len(cuts) + 1):
            cut = i
            # snap to the nearest bottleneck position within half a stage
            near = [b for b in bot_positions
                    if abs(b - i) <= half_stage and
                    (not cuts or b > cuts[-1]) and b < len(nodes) - 1]
            if near:
                cut = min(near, key=lambda b: abs(b - i))
            if cuts and cut <= cuts[-1]:
                cut = cuts[-1] + 1
            if cut >= len(nodes) - (n_stages - 1 - len(cuts)):
                cut = len(nodes) - (n_stages - 1 - len(cuts)) - 1
            cuts.append(cut)
    while len(cuts) < n_stages - 1:  # pathological cost skew
        nxt = (cuts[-1] + 1) if cuts else 0
        cuts.append(min(nxt, len(nodes) - (n_stages - 1 - len(cuts))))
    out: List[List[int]] = []
    lo = 0
    for c in cuts:
        out.append([n.guid for n in nodes[lo:c + 1]])
        lo = c + 1
    out.append([n.guid for n in nodes[lo:]])
    assert all(out), (cuts, [len(s) for s in out])
    return out


@dataclasses.dataclass
class StageSpec:
    """One pipeline stage: its sub-PCG + boundary wiring."""

    sub_pcg: PCG
    # how to feed the stage, in sub_pcg input-node order:
    #   ("model", input_guid)          — a model input (microbatch slice)
    #   ("stage", src_stage, out_pos)  — output `out_pos` of an earlier stage
    feeds: List[Tuple]
    # which (guid, out_idx) this stage exposes, in order
    outputs: List[BoundaryT]


def build_stage_specs(pcg: PCG, stages: List[List[int]]) -> List[StageSpec]:
    from ..ops.noop import InputOp

    stage_of: Dict[int, int] = {}
    for s, guids in enumerate(stages):
        for g in guids:
            stage_of[g] = s
    model_inputs = {n.guid for n in pcg.input_nodes()}
    final = [n for n in pcg.sinks()
             if n.op.op_type != OperatorType.OP_INPUT][-1]

    # boundary tensors: produced in stage s, consumed in stage > s (or final)
    exposed: List[List[BoundaryT]] = [[] for _ in stages]
    exposed_pos: Dict[BoundaryT, Tuple[int, int]] = {}

    def expose(ref: BoundaryT, s: int):
        if ref not in exposed_pos:
            exposed_pos[ref] = (s, len(exposed[s]))
            exposed[s].append(ref)

    for node in pcg.compute_nodes():
        s = stage_of[node.guid]
        for g, i in node.inputs:
            if g in model_inputs:
                continue
            ps = stage_of[g]
            if ps != s:
                expose((g, i), ps)
    expose((final.guid, 0), stage_of[final.guid])

    specs: List[StageSpec] = []
    for s, guids in enumerate(stages):
        sub = PCG()
        feeds: List[Tuple] = []
        gset = set(guids)
        # placeholders for every external reference, in deterministic order
        ext_refs: List[Tuple[int, int]] = []
        seen = set()
        for g in guids:
            for pg, i in pcg.nodes[g].inputs:
                if pg in gset:
                    continue
                if (pg, i) not in seen:
                    seen.add((pg, i))
                    ext_refs.append((pg, i))
        for pg, i in ext_refs:
            src = pcg.nodes[pg]
            op = InputOp(name=f"s{s}_in_{pg}_{i}",
                         attrs={"shape": src.out_shapes[i],
                                "dtype": src.out_dtypes[i]},
                         dtype=src.out_dtypes[i], num_inputs=0)
            node = PCGNode(guid=-(len(sub.nodes) + 1) * 1000 - pg, op=op,
                           inputs=[],
                           out_shapes=[src.out_shapes[i]],
                           out_dtypes=[src.out_dtypes[i]])
            sub.nodes[node.guid] = node
            sub._order.append(node.guid)
            if pg in model_inputs:
                feeds.append(("model", pg))
            else:
                src_stage, out_pos = exposed_pos[(pg, i)]
                feeds.append(("stage", src_stage, out_pos))
        # map (ext pg, i) -> placeholder guid
        ph = {ref: g for ref, g in zip(ext_refs, list(sub._order))}
        for g in guids:
            n = pcg.nodes[g]
            nn = PCGNode(
                guid=g, op=n.op,
                inputs=[(pg, i) if pg in gset else (ph[(pg, i)], 0)
                        for pg, i in n.inputs],
                out_shapes=list(n.out_shapes), out_dtypes=list(n.out_dtypes))
            sub.nodes[g] = nn
            sub._order.append(g)
        specs.append(StageSpec(sub_pcg=sub, feeds=feeds, outputs=exposed[s]))
    return specs


class PipelineTrainer:
    """Pipeline training of an FFModel over a (pipe, data) device grid.

    Usage::

        ff = FFModel(config); ...build layers...; ff.compile(...)  # optional
        trainer = PipelineTrainer(ff, pp=4, dp=2, n_micro=8,
                                  optimizer=AdamOptimizer(ff),
                                  loss_type=LossType...,
                                  schedule="1f1b")
        loss = trainer.train_step(x_batch, y_batch)
    """

    def __init__(self, ffmodel, pp: int, dp: int = 1,
                 n_micro: Optional[int] = None, optimizer=None,
                 loss_type: LossType =
                 LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                 devices: Optional[Sequence] = None,
                 init_params: bool = True, remat: str = "full",
                 schedule: str = "gpipe", virtual_stages: int = 1):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ..execution.optimizers import SGDOptimizer
        from ..execution.remat import REMAT_LEVELS

        if remat not in REMAT_LEVELS:
            raise ValueError(f"remat {remat!r} not in {REMAT_LEVELS}")
        if schedule not in PIPELINE_SCHEDULES:
            raise ValueError(
                f"schedule {schedule!r} not in {PIPELINE_SCHEDULES}")
        v = int(virtual_stages or 1)
        if schedule == "interleaved":
            if v < 2:
                raise ValueError(
                    f"interleaved schedule needs virtual_stages >= 2 "
                    f"(got {v}); v=1 IS the 1f1b schedule — use "
                    "schedule='1f1b'")
        elif v != 1:
            raise ValueError(
                f"virtual_stages={v} only applies to the interleaved "
                f"schedule (got schedule={schedule!r})")
        # stage-remat level: the SAME jax.checkpoint policy machinery the
        # Executor's remat blocks use (execution/remat.py) — `full` is the
        # classic GPipe recipe this trainer previously hard-coded as a
        # hand-rolled VJP; `selective` keeps contraction outputs across the
        # stage backward; `none` saves every stage residual in-jit
        self.remat = remat
        self.schedule = schedule
        self.v = v
        self.loss_type = loss_type
        self.pp, self.dp = pp, dp
        self.n_micro = n_micro or pp
        self.optimizer = optimizer or SGDOptimizer(None)

        pcg = ffmodel.pcg if ffmodel.pcg is not None else ffmodel.create_pcg()
        # pipeline over the PRE-fusion graph for clean stage cuts; the
        # interleaved schedule cuts pp*v chunks and lays them round-robin
        # over the pp device rows (chunk c on row c % pp)
        self.pcg = pcg
        self.n_chunks = pp * v
        n_nodes = len(pcg.compute_nodes())
        if self.n_chunks > n_nodes:
            raise ValueError(
                f"schedule {schedule!r} needs pp*v = {pp}*{v} = "
                f"{self.n_chunks} stage chunks but the graph has only "
                f"{n_nodes} compute nodes; lower --virtual-stages (v) "
                "or the pipeline depth")
        self.stages = split_stages(pcg, self.n_chunks)
        self.specs = build_stage_specs(pcg, self.stages)
        self.chunk_dev = [c % pp for c in range(self.n_chunks)]
        self.model_input_order = [n.guid for n in pcg.input_nodes()]
        final = [n for n in pcg.sinks()
                 if n.op.op_type != OperatorType.OP_INPUT][-1]
        self.final_ref = (final.guid, 0)
        self.final_dtype = final.out_dtypes[0]

        devices = list(devices if devices is not None else jax.devices())
        assert len(devices) >= pp * dp, \
            f"need {pp * dp} devices, have {len(devices)}"
        grid = np.array(devices[:pp * dp]).reshape(pp, dp)
        self.meshes = [Mesh(grid[d], ("data",)) for d in range(pp)]
        self.batch_shardings = [
            NamedSharding(self.meshes[d], P("data"))
            for d in range(pp)]
        # microbatch-stacked host inputs: (n_micro, mb, ...) sharded
        # (None, "data") so per-microbatch rows slice ON DEVICE — one
        # host->device transfer per (chunk, feed) per step, not n_micro
        self.micro_shardings = [
            NamedSharding(self.meshes[d], P(None, "data"))
            for d in range(pp)]
        self._P = P
        self._NamedSharding = NamedSharding
        # event-order memo keyed by n_micro (fit() re-derives n_micro per
        # real batch): the generator is pure host-side Python — rebuilding
        # the 1f1b greedy every step would put dead O(events^2) work in
        # the dispatch loop the async pipeline is meant to hide
        self._order_cache: Dict[int, List[Tuple[str, int, int]]] = {}

        self._build_stage_fns()
        if init_params:
            self.params = self._init_params()
            self.opt_states = [self.optimizer.init_state(p)
                               for p in self.params]
        else:  # caller seeds via load_params (skips the jitted stage init)
            self.params = None
            self.opt_states = None

    # ------------------------------------------------------------- stage fns
    def _build_stage_fns(self):
        import jax

        from ..execution.losses import loss_value
        from ..ops.base import OpContext

        self._fwd = []
        self._bwd = []
        self._ph_guids = []  # per stage: placeholder guids in feed order

        for s, spec in enumerate(self.specs):
            sub = spec.sub_pcg
            ph_guids = [n.guid for n in sub.topo_order()
                        if n.op.op_type == OperatorType.OP_INPUT]
            self._ph_guids.append(ph_guids)
            out_refs = spec.outputs

            # batch-shaped constants (the gpt2-style position-id pattern,
            # serving/kvcache.is_position_constant) are baked at the FULL
            # batch; a microbatched stage must slice them to its rows or
            # the first elementwise consumer fails to broadcast
            from ..serving.kvcache import is_position_constant

            mb_const = {n.guid for n in sub.topo_order()
                        if n.op.op_type == OperatorType.OP_CONSTANT
                        and is_position_constant(n.op.attrs.get("value"))}

            def make_forward(sub=sub, ph_guids=ph_guids, out_refs=out_refs,
                             mb_const=mb_const):
                def f(params, ins, rng):
                    ctx = OpContext(training=True, rng=rng, aux_losses=[])
                    mb = ins[0].shape[0] if ins else None
                    values: Dict[int, List[Any]] = {}
                    for g, x in zip(ph_guids, ins):
                        values[g] = [x]
                    for node in sub.topo_order():
                        if node.op.op_type == OperatorType.OP_INPUT:
                            continue
                        inputs = [values[g][i] for g, i in node.inputs]
                        node_ctx = OpContext(
                            training=True,
                            rng=(jax.random.fold_in(ctx.rng, node.guid)
                                 if ctx.rng is not None else None),
                            aux_losses=ctx.aux_losses)
                        outs = node.op.forward(
                            params.get(node.name, {}), inputs, node_ctx)
                        if node.guid in mb_const and mb is not None and \
                                outs[0].shape[0] > mb:
                            outs = [outs[0][:mb]] + list(outs[1:])
                        values[node.guid] = outs
                    outs = tuple(values[g][i] for g, i in out_refs)
                    aux = sum(ctx.aux_losses) if ctx.aux_losses else 0.0
                    return outs, aux
                return f

            # leveled stage remat: wrap the stage forward in jax.checkpoint
            # with the trainer's policy, so every differentiation below
            # (mid-stage VJP and last-stage value_and_grad alike) saves
            # only what the level keeps and recomputes the rest
            from ..execution.remat import wrap_remat

            f = wrap_remat(make_forward(), self.remat)
            is_last = (s == len(self.specs) - 1)
            if is_last:
                final_pos = out_refs.index(self.final_ref)
                loss_type = self.loss_type

                def last_fwd(params, ins, labels, rng, _f=f,
                             _pos=final_pos):
                    outs, aux = _f(params, ins, rng)
                    logits = outs[_pos]
                    return loss_value(loss_type, logits, labels) + aux, logits

                def last_bwd(params, ins, labels, rng, _fn=last_fwd):
                    (loss, logits), grads = jax.value_and_grad(
                        _fn, argnums=(0, 1), has_aux=True)(
                            params, ins, labels, rng)
                    return loss, logits, grads[0], grads[1]

                self._fwd.append(jax.jit(last_fwd))
                self._bwd.append(jax.jit(last_bwd))
            else:
                def mid_fwd(params, ins, rng, _f=f):
                    outs, _aux = _f(params, ins, rng)
                    return outs

                def mid_bwd(params, ins, rng, cots, _f=f):
                    # VJP through the policy-wrapped stage forward: what is
                    # saved vs recomputed between the in-jit forward and
                    # backward is the checkpoint policy's call, not ours
                    import jax.numpy as jnp

                    def run(p, i):
                        outs, aux = _f(p, i, rng)
                        return outs, jnp.asarray(aux, jnp.float32)

                    (_outs, _aux), vjp = jax.vjp(run, params, ins)
                    # aux losses add directly to the total loss -> cotangent 1
                    dparams, dins = vjp((cots, jnp.float32(1.0)))
                    return dparams, dins

                self._fwd.append(jax.jit(mid_fwd))
                self._bwd.append(jax.jit(mid_bwd))

        # per-stage jitted optimizer update
        opt = self.optimizer

        def upd(params, grads, state):
            return opt.update(params, grads, state)

        self._upd = [jax.jit(upd) for _ in self.specs]

    # --------------------------------------------------------------- params
    def _init_params(self):
        import jax

        params = []
        for s, spec in enumerate(self.specs):
            sub = spec.sub_pcg

            def init_fn(key, sub=sub):
                out: Dict[str, Dict[str, Any]] = {}
                for node in sub.topo_order():
                    if node.op.op_type == OperatorType.OP_INPUT:
                        continue
                    in_shapes = [sub.nodes[g].out_shapes[i]
                                 for g, i in node.inputs]
                    for i, (wname, (shape, dt, init)) in enumerate(
                            node.op.weight_specs(in_shapes).items()):
                        sub_key = jax.random.fold_in(
                            jax.random.fold_in(key, node.guid), i)
                        out.setdefault(node.name, {})[wname] = init(
                            sub_key, shape, dtype_to_jnp(dt))
                return out

            with self.meshes[self.chunk_dev[s]]:
                p = jax.jit(init_fn)(jax.random.PRNGKey(0))
            p = jax.device_put(p, self._NamedSharding(
                self.meshes[self.chunk_dev[s]], self._P()))
            params.append(p)
        return params

    def load_params(self, full_params: Dict[str, Dict[str, Any]]):
        """Install externally-initialized params (e.g. from an Executor model
        with the same layer graph) — names match by construction."""
        import jax

        new = []
        for s, spec in enumerate(self.specs):
            names = {n.name for n in spec.sub_pcg.topo_order()
                     if n.op.op_type != OperatorType.OP_INPUT}
            p = {k: v for k, v in full_params.items() if k in names}
            new.append(jax.device_put(
                p, self._NamedSharding(self.meshes[self.chunk_dev[s]],
                                       self._P())))
        self.params = new
        self.opt_states = [self.optimizer.init_state(p) for p in self.params]

    def export_params(self) -> Dict[str, Dict[str, Any]]:
        """Inverse of load_params: gather the trained per-stage params back
        into one {layer: {weight: host array}} pytree (fit copies them into
        the Executor's params so eval/predict/checkpoint see the training)."""
        out: Dict[str, Dict[str, Any]] = {}
        for p in self.params:
            for lname, ws in p.items():
                out[lname] = {k: np.asarray(v) for k, v in ws.items()}
        return out

    # ---------------------------------------------------------------- train
    def _stacked_inputs(self, arrays: List[Any]):
        """One host->device transfer per (chunk, feed): the full input
        arrays go up microbatch-major ``(n_micro, mb, ...)`` sharded
        ``(None, "data")`` — each dp shard then slices its OWN microbatch
        rows on device (no cross-device traffic, no per-(microbatch,
        stage, feed) ``device_put`` of host-sliced numpy — the old host
        loop paid n_micro * stages transfers per step)."""
        import jax

        n = int(np.asarray(arrays[0]).shape[0])
        mb = n // self.n_micro
        assert mb * self.n_micro == n, \
            f"batch {n} not divisible by n_micro {self.n_micro}"
        assert mb % self.dp == 0, f"microbatch {mb} not divisible by dp"
        feed_arrays = dict(zip(self.model_input_order, arrays[:-1]))
        stacked: Dict[Tuple[int, int], Any] = {}
        for c, spec in enumerate(self.specs):
            dev = self.chunk_dev[c]
            for feed in spec.feeds:
                if feed[0] != "model":
                    continue
                a = np.asarray(feed_arrays[feed[1]])
                stacked[(c, feed[1])] = jax.device_put(
                    a.reshape((self.n_micro, mb) + a.shape[1:]),
                    self.micro_shardings[dev])
        lab = np.asarray(arrays[-1])
        labels = jax.device_put(
            lab.reshape((self.n_micro, mb) + lab.shape[1:]),
            self.micro_shardings[self.chunk_dev[len(self.specs) - 1]])
        return stacked, labels

    def train_step(self, x, y, rng_seed: int = 0) -> float:
        """One pipelined step in ``self.schedule``'s order: forwards and
        backwards interleave per :func:`pipeline_schedule`, grads
        accumulate per chunk in ascending microbatch order (bitwise-stable
        across schedules), then the microbatch-mean update applies. A
        microbatch's boundary activations are RELEASED as its backward
        completes — in-flight activation memory follows
        :func:`pipeline_in_flight` (n_micro for gpipe, ~pp for 1f1b)."""
        import jax
        import jax.numpy as jnp

        from ..obs import get_tracer

        xs = x if isinstance(x, (list, tuple)) else [x]
        stacked, labels = self._stacked_inputs(list(xs) + [y])
        S = len(self.specs)
        key = jax.random.PRNGKey(rng_seed)
        tracer = get_tracer()
        trace = tracer.enabled

        stage_ins: Dict[Tuple[int, int], Tuple] = {}   # (m, chunk) -> ins
        stage_outs: Dict[Tuple[int, int], Tuple] = {}
        # (m, src_chunk, out_pos) -> accumulated cotangent
        cots: Dict[Tuple[int, int, int], Any] = {}
        grad_acc: List[Any] = [None] * S
        acc_m: List[int] = [0] * S  # per-chunk microbatch accumulation cursor
        losses = []

        def add_cot(m, src_chunk, out_pos, val):
            # accumulate on the PRODUCING chunk's submesh so
            # multi-consumer adds colocate
            val = jax.device_put(
                val, self.batch_shardings[self.chunk_dev[src_chunk]])
            prev = cots.get((m, src_chunk, out_pos))
            cots[(m, src_chunk, out_pos)] = val if prev is None else \
                jax.tree_util.tree_map(jnp.add, prev, val)

        def gather_ins(m, c):
            ins = []
            for feed in self.specs[c].feeds:
                if feed[0] == "model":
                    ins.append(stacked[(c, feed[1])][m])
                else:
                    _, src_chunk, out_pos = feed
                    val = stage_outs[(m, src_chunk)][out_pos]
                    if self.chunk_dev[src_chunk] != self.chunk_dev[c]:
                        # cross-submesh boundary transfer (ICI on hardware)
                        val = jax.device_put(
                            val, self.batch_shardings[self.chunk_dev[c]])
                    ins.append(val)
            return tuple(ins)

        order = self._order_cache.get(self.n_micro)
        if order is None:
            order = self._order_cache[self.n_micro] = pipeline_schedule(
                self.schedule, self.pp, self.n_micro, self.v)
        for phase, m, c in order:
            mkey = jax.random.fold_in(key, m)
            if phase == "F":
                stage_ins[(m, c)] = gather_ins(m, c)
                if c == S - 1:
                    continue  # last chunk's forward fuses with its backward
                if trace:
                    # per-(microbatch, stage, phase) spans: block so the
                    # span is the stage's real wall and the Perfetto
                    # timeline shows the bubble (observer effect: tracing
                    # serializes the async dispatch — docs/pipeline.md)
                    with tracer.span("pipeline_fwd", micro=m, stage=c,
                                     device=self.chunk_dev[c],
                                     schedule=self.schedule):
                        out = self._fwd[c](self.params[c],
                                           stage_ins[(m, c)], mkey)
                        jax.block_until_ready(out)
                else:
                    out = self._fwd[c](self.params[c], stage_ins[(m, c)],
                                       mkey)
                stage_outs[(m, c)] = out
                continue
            # ---- backward of (m, c)
            def run_bwd():
                if c == S - 1:
                    loss, _logits, dp_, di_ = self._bwd[c](
                        self.params[c], stage_ins[(m, c)], labels[m], mkey)
                    losses.append(loss)
                    return dp_, di_
                out_cots = []
                for out_pos in range(len(self.specs[c].outputs)):
                    # every exposed output has a later-chunk consumer whose
                    # backward already ran (the schedule's B(m,c+1) chain)
                    out_cots.append(cots.pop((m, c, out_pos)))
                return self._bwd[c](self.params[c], stage_ins[(m, c)],
                                    mkey, tuple(out_cots))

            if trace:
                with tracer.span("pipeline_bwd", micro=m, stage=c,
                                 device=self.chunk_dev[c],
                                 schedule=self.schedule):
                    dparams, dins = run_bwd()
                    jax.block_until_ready(dparams)
            else:
                dparams, dins = run_bwd()
            # ascending-microbatch accumulation per chunk: the invariant
            # every schedule preserves, keeping the grad sums bitwise-equal
            # across gpipe/1f1b/interleaved
            assert acc_m[c] == m, (self.schedule, c, m, acc_m[c])
            acc_m[c] += 1
            grad_acc[c] = dparams if grad_acc[c] is None else \
                jax.tree_util.tree_map(jnp.add, grad_acc[c], dparams)
            for pos, feed in enumerate(self.specs[c].feeds):
                if feed[0] == "stage":
                    add_cot(m, feed[1], feed[2], dins[pos])
            # release the microbatch's boundary activations: this is the
            # schedule's memory lever (pipeline_in_flight)
            stage_ins.pop((m, c), None)
            stage_outs.pop((m, c), None)

        # ---- update: mean of microbatch grads == full-batch grad
        inv = 1.0 / self.n_micro
        for s in range(S):
            grads = jax.tree_util.tree_map(lambda g: g * inv, grad_acc[s])
            self.params[s], self.opt_states[s] = self._upd[s](
                self.params[s], grads, self.opt_states[s])
        return float(jnp.mean(jnp.stack(
            [jax.device_get(l) for l in losses])))

    def fit(self, x, y, epochs: int = 1, batch_size: Optional[int] = None,
            shuffle: bool = False) -> List[float]:
        xs = x if isinstance(x, (list, tuple)) else [x]
        n = xs[0].shape[0]
        bs = batch_size or n
        losses = []
        from ..data.dataloader import batch_iterator

        step = 0
        for ep in range(epochs):
            for arrays in batch_iterator(list(xs) + [y], bs, shuffle=shuffle,
                                         seed=ep):
                loss = self.train_step(arrays[:-1], arrays[-1],
                                       rng_seed=step)
                losses.append(loss)
                step += 1
        return losses
