from .pcg import PCG, PCGNode  # noqa: F401
from .strategy import Strategy, NodeStrategy, data_parallel_strategy  # noqa: F401
from .mesh import build_mesh  # noqa: F401
from . import parallel_op  # noqa: F401
