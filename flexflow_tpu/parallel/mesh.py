"""Device mesh construction.

Replaces the reference's machine model + FFMapper placement (src/mapper/) with
a ``jax.sharding.Mesh``. The reference's MachineView device grids become
shardings over named mesh axes; start_device_id offsets are not representable
under whole-program SPMD (SURVEY §7 hard-part 1) and are absorbed into axis
assignment.

Axis convention: ``data`` (batch/sample parallel), ``model`` (tensor/attribute
parallel), optional ``expert`` and ``seq`` axes for EP/SP strategies.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def build_mesh(config=None, mesh_shape: Optional[Sequence[int]] = None,
               axis_names: Optional[Sequence[str]] = None,
               devices=None):
    """Build the global Mesh.

    Defaults to a 1-D data-parallel mesh over all visible devices (the
    reference's default DataParallelism strategy, config.h:95-100).
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if mesh_shape is None and config is not None:
        mesh_shape = config.mesh_shape
    if axis_names is None:
        axis_names = (config.mesh_axis_names if config is not None
                      else ("data", "model"))
    n = len(devices)
    if mesh_shape is None:
        mesh_shape = (n, 1) if len(axis_names) == 2 else (n,) + (1,) * (
            len(axis_names) - 1)
    mesh_shape = tuple(int(s) for s in mesh_shape)
    total = int(np.prod(mesh_shape))
    assert total <= n, f"mesh {mesh_shape} needs {total} devices, have {n}"
    axis_names = tuple(axis_names)[:len(mesh_shape)]
    if len(axis_names) < len(mesh_shape):
        axis_names = axis_names + tuple(
            f"ax{i}" for i in range(len(axis_names), len(mesh_shape)))
    dev_array = np.asarray(devices[:total]).reshape(mesh_shape)
    return Mesh(dev_array, axis_names)


def mesh_for_strategy(config, strategy):
    """Build the mesh a Strategy calls for: hybrid ICI x DCN layout when the
    search placed an axis factor across hosts, plain mesh otherwise."""
    if getattr(strategy, "hybrid", None):
        return build_hybrid_mesh(strategy.hybrid[0], strategy.hybrid[1],
                                 strategy.axis_names)
    return build_mesh(config, mesh_shape=strategy.mesh_shape,
                      axis_names=strategy.axis_names)


def mesh_axis_size(mesh, axis: str) -> int:
    return mesh.shape[axis] if axis in mesh.shape else 1


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> int:
    """Join the jax distributed runtime for multi-host SPMD (the reference's
    control replication + GASNet/UCX inter-node transport, CMakeLists.txt:
    47-52 — here one call: XLA then runs collectives over ICI within a slice
    and DCN across hosts automatically).

    Call BEFORE any other jax use (device queries, computation). On TPU pods
    the arguments are auto-detected from the environment; on other platforms
    pass them explicitly. Returns the process index. Idempotent after a
    successful init; plain single-host auto mode is a no-op. Any real
    failure — bad coordinator, unreachable hosts, or calling too late —
    propagates: silently degrading to independent single-host runs would
    corrupt a multi-host job.
    """
    import jax

    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except RuntimeError as e:
        # ONLY "already initialized" is benign; everything else (incl.
        # "must be called before any JAX computations", which means init did
        # NOT happen) must propagate — silently degrading to independent
        # single-host runs would corrupt a multi-host job
        if "already initialized" not in str(e).lower():
            raise
    except ValueError:
        if coordinator_address is not None:
            raise
    return jax.process_index()


def build_hybrid_mesh(ici_shape: Sequence[int], dcn_shape: Sequence[int],
                      axis_names: Sequence[str]):
    """Multi-slice mesh via jax's create_hybrid_device_mesh: ``ici_shape``
    and ``dcn_shape`` must have EQUAL rank; axis i of the result has size
    ici_shape[i] * dcn_shape[i], with devices laid out so the DCN factor of
    an axis never splits an ICI ring. Put the dcn factor on data-parallel
    axes (e.g. ici (1, 8), dcn (2, 1) for 2 slices x 8 chips = mesh (2, 8))
    and keep tensor/sequence axes ICI-only."""
    ici_shape = tuple(ici_shape)
    dcn_shape = tuple(dcn_shape)
    if len(ici_shape) != len(dcn_shape):
        raise ValueError(
            f"ici_shape {ici_shape} and dcn_shape {dcn_shape} must have "
            f"equal rank (axis i spans ici*dcn)")
    if len(tuple(axis_names)) != len(ici_shape):
        raise ValueError(
            f"need exactly {len(ici_shape)} axis names, got {axis_names}")
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    shape = tuple(i * d for i, d in zip(ici_shape, dcn_shape))
    try:
        dev = mesh_utils.create_hybrid_device_mesh(ici_shape, dcn_shape)
    except ValueError as e:
        if "slice_index" not in str(e):
            raise
        # virtual CPU devices carry no slice topology: plain row-major
        # placement (layout only matters on real multi-slice hardware)
        n = int(np.prod(shape))
        dev = np.asarray(jax.devices()[:n]).reshape(shape)
    return Mesh(dev, tuple(axis_names))
