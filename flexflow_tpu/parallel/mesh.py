"""Device mesh construction.

Replaces the reference's machine model + FFMapper placement (src/mapper/) with
a ``jax.sharding.Mesh``. The reference's MachineView device grids become
shardings over named mesh axes; start_device_id offsets are not representable
under whole-program SPMD (SURVEY §7 hard-part 1) and are absorbed into axis
assignment.

Axis convention: ``data`` (batch/sample parallel), ``model`` (tensor/attribute
parallel), optional ``expert`` and ``seq`` axes for EP/SP strategies.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def build_mesh(config=None, mesh_shape: Optional[Sequence[int]] = None,
               axis_names: Optional[Sequence[str]] = None,
               devices=None):
    """Build the global Mesh.

    Defaults to a 1-D data-parallel mesh over all visible devices (the
    reference's default DataParallelism strategy, config.h:95-100).
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if mesh_shape is None and config is not None:
        mesh_shape = config.mesh_shape
    if axis_names is None:
        axis_names = (config.mesh_axis_names if config is not None
                      else ("data", "model"))
    n = len(devices)
    if mesh_shape is None:
        mesh_shape = (n, 1) if len(axis_names) == 2 else (n,) + (1,) * (
            len(axis_names) - 1)
    mesh_shape = tuple(int(s) for s in mesh_shape)
    total = int(np.prod(mesh_shape))
    assert total <= n, f"mesh {mesh_shape} needs {total} devices, have {n}"
    axis_names = tuple(axis_names)[:len(mesh_shape)]
    if len(axis_names) < len(mesh_shape):
        axis_names = axis_names + tuple(
            f"ax{i}" for i in range(len(axis_names), len(mesh_shape)))
    dev_array = np.asarray(devices[:total]).reshape(mesh_shape)
    return Mesh(dev_array, axis_names)


def mesh_axis_size(mesh, axis: str) -> int:
    return mesh.shape[axis] if axis in mesh.shape else 1
