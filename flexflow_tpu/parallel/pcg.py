"""Parallel Computation Graph (PCG).

Analog of the reference's search-time ``PCG::Graph`` (include/flexflow/graph.h:293,
src/runtime/graph.cc:2753): a graph of (Op, guid) nodes over edges carrying
tensor indices. The same structure serves (a) lowering to a jax function,
(b) the Unity search (which mutates copies of it thousands of times — hence
cheap structural hashing, reference Graph::hash), and (c) strategy
(de)serialization.
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

from ..ffconst import DataType, OperatorType
from ..machine_view import MachineView
from ..ops.base import Op

_node_guid = itertools.count(1)


@dataclasses.dataclass
class PCGNode:
    guid: int
    op: Op
    # each input is (producer_guid, producer_output_idx)
    inputs: List[Tuple[int, int]]
    out_shapes: List[Tuple[int, ...]] = dataclasses.field(default_factory=list)
    out_dtypes: List[DataType] = dataclasses.field(default_factory=list)
    machine_view: Optional[MachineView] = None

    @property
    def name(self) -> str:
        return self.op.name


class PCG:
    """Node/edge container with topo order and structural hash."""

    def __init__(self):
        self.nodes: Dict[int, PCGNode] = {}
        self._order: List[int] = []  # insertion == topological order

    # -- construction -----------------------------------------------------------
    def add_node(self, op: Op, inputs: Sequence[Tuple[int, int]]) -> PCGNode:
        guid = next(_node_guid)
        in_shapes = [self.nodes[g].out_shapes[i] for g, i in inputs]
        in_dtypes = [self.nodes[g].out_dtypes[i] for g, i in inputs]
        node = PCGNode(guid=guid, op=op, inputs=list(inputs))
        if op.op_type in (OperatorType.OP_INPUT, OperatorType.OP_WEIGHT):
            node.out_shapes = [tuple(op.attrs["shape"])]
            node.out_dtypes = [op.attrs.get("dtype", DataType.DT_FLOAT)]
        else:
            node.out_shapes = [tuple(s) for s in op.infer_output_shapes(in_shapes)]
            node.out_dtypes = op.output_dtypes(in_dtypes, len(node.out_shapes))
        self.nodes[guid] = node
        self._order.append(guid)
        return node

    # -- queries ----------------------------------------------------------------
    def topo_order(self) -> List[PCGNode]:
        return [self.nodes[g] for g in self._order]

    def in_edges(self, guid: int) -> List[Tuple[int, int]]:
        return self.nodes[guid].inputs

    def consumers(self, guid: int) -> List[int]:
        return [n.guid for n in self.nodes.values()
                if any(g == guid for g, _ in n.inputs)]

    def sources(self) -> List[PCGNode]:
        return [n for n in self.topo_order() if not n.inputs]

    def sinks(self) -> List[PCGNode]:
        consumed = {g for n in self.nodes.values() for g, _ in n.inputs}
        return [n for n in self.topo_order() if n.guid not in consumed]

    def input_nodes(self) -> List[PCGNode]:
        return [n for n in self.topo_order()
                if n.op.op_type == OperatorType.OP_INPUT]

    def weight_nodes(self) -> List[PCGNode]:
        return [n for n in self.topo_order()
                if n.op.op_type == OperatorType.OP_WEIGHT]

    def compute_nodes(self) -> List[PCGNode]:
        return [n for n in self.topo_order()
                if n.op.op_type not in (OperatorType.OP_INPUT,
                                        OperatorType.OP_WEIGHT)]

    def insert_node_on_edge(self, consumer_guid: int, input_idx: int,
                            op: Op) -> PCGNode:
        """Insert ``op`` on the edge feeding ``consumer_guid``'s input slot
        ``input_idx`` (reference: the search inserting parallel ops into the
        PCG, substitution.cc GraphXfer::run). The new node is placed in the
        order right before the consumer, preserving topological validity."""
        consumer = self.nodes[consumer_guid]
        g, i = consumer.inputs[input_idx]
        src = self.nodes[g]
        node = PCGNode(guid=next(_node_guid), op=op, inputs=[(g, i)],
                       out_shapes=[src.out_shapes[i]],
                       out_dtypes=[src.out_dtypes[i]])
        self.nodes[node.guid] = node
        self._order.insert(self._order.index(consumer_guid), node.guid)
        consumer.inputs[input_idx] = (node.guid, 0)
        return node

    def retopo(self) -> None:
        """Restore ``_order`` to a topological order (Kahn) after a rewrite
        appended nodes out of place."""
        indeg: Dict[int, int] = {g: 0 for g in self.nodes}
        outs: Dict[int, List[int]] = {g: [] for g in self.nodes}
        for n in self.nodes.values():
            for g, _ in n.inputs:
                indeg[n.guid] += 1
                outs[g].append(n.guid)
        ready = [g for g in self._order if indeg[g] == 0]
        order: List[int] = []
        while ready:
            g = ready.pop(0)
            order.append(g)
            for c in outs[g]:
                indeg[c] -= 1
                if indeg[c] == 0:
                    ready.append(c)
        assert len(order) == len(self.nodes), "cycle after rewrite"
        self._order = order

    # -- structural hash (reference: Graph::hash) -------------------------------
    def hash(self) -> int:
        h = 17
        remap = {g: i for i, g in enumerate(self._order)}
        for g in self._order:
            n = self.nodes[g]
            key = (n.op.params_key(),
                   tuple((remap[pg], pi) for pg, pi in n.inputs),
                   n.machine_view.hash() if n.machine_view else 0)
            h = hash((h, key))
        return h

    def copy(self) -> "PCG":
        import copy as _copy

        g = PCG()
        g.nodes = {k: dataclasses.replace(
            v, inputs=list(v.inputs), out_shapes=list(v.out_shapes),
            out_dtypes=list(v.out_dtypes)) for k, v in self.nodes.items()}
        g._order = list(self._order)
        return g

    # -- search-time splitting (reference: Graph::split_at_node,
    # src/runtime/graph.cc:958) -------------------------------------------------
    def split_at_node(self, guid: int) -> Tuple["PCG", "PCG"]:
        """Split into (pre, post) subgraphs at a bottleneck node: ``pre``
        contains the node and everything it depends on; ``post`` contains
        the rest, with the bottleneck's producers re-rooted as inputs."""
        assert guid in self.nodes, guid
        anc: set = set()
        stack = [guid]
        while stack:
            g = stack.pop()
            if g in anc:
                continue
            anc.add(g)
            stack.extend(pg for pg, _ in self.nodes[g].inputs)
        pre, post = PCG(), PCG()
        for g in self._order:
            n = self.nodes[g]
            target = pre if g in anc else post
            target.nodes[g] = dataclasses.replace(
                n, inputs=list(n.inputs), out_shapes=list(n.out_shapes),
                out_dtypes=list(n.out_dtypes))
            target._order.append(g)
        # post-side consumers of pre-side nodes keep the guid reference;
        # materialize those producers as input placeholders in `post`
        from ..ops.noop import InputOp

        needed = {pg for g in post._order for pg, _ in post.nodes[g].inputs
                  if pg in anc}
        for pg in sorted(needed):
            src = self.nodes[pg]
            op = InputOp(name=f"split_in_{pg}",
                         attrs={"shape": src.out_shapes[0],
                                "dtype": src.out_dtypes[0]},
                         dtype=src.out_dtypes[0], num_inputs=0)
            node = PCGNode(guid=pg, op=op, inputs=[],
                           out_shapes=list(src.out_shapes),
                           out_dtypes=list(src.out_dtypes))
            post.nodes[pg] = node
            post._order.insert(0, pg)
        return pre, post

    def bottlenecks(self) -> List[int]:
        """Compute-node guids every source-to-sink path passes through
        (reference: find_bottleneck_node via imm_post_dominators,
        graph.cc:610-623)."""
        from ..utils.graph_utils import find_bottlenecks, pcg_basic_graph

        g = pcg_basic_graph(self)
        sinks = set(x.guid for x in self.sinks())
        return [b for b in find_bottlenecks(g) if b not in sinks]

    # -- observability (reference: export_strategy_computation_graph) -----------
    def to_dot(self, include_costs: bool = False, costs=None) -> str:
        lines = ["digraph PCG {"]
        for n in self.topo_order():
            label = f"{n.name}\\n{n.op.op_type.name}"
            if n.machine_view:
                label += f"\\nview={n.machine_view.dim}"
            if include_costs and costs and n.guid in costs:
                label += f"\\ncost={costs[n.guid]:.1f}us"
            lines.append(f'  n{n.guid} [label="{label}"];')
            for pg, pi in n.inputs:
                lines.append(f"  n{pg} -> n{n.guid} [label=\"{pi}\"];")
        lines.append("}")
        return "\n".join(lines)

    def __len__(self) -> int:
        return len(self.nodes)
