"""MLP_Unify, XDL, and CANDLE-Uno model builders.

Reference apps: examples/cpp/MLP_Unify/mlp.cc (two 8x8192 dense towers added
then softmaxed), examples/cpp/XDL/xdl.cc (N 1M-entry embeddings + dense
stack — an ads-CTR model like DLRM), examples/cpp/candle_uno/candle_uno.cc
(multi-tower drug-response regression: per-feature 8x4192 towers, concat,
4x4192 head, scalar output).
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

from ..ffconst import ActiMode, AggrMode, DataType
from ..model import FFModel


def build_mlp_unify(ff: FFModel, batch_size: int = 64,
                    input_dim: int = 1024,
                    hidden_dims: Sequence[int] = (8192,) * 8):
    """reference: examples/cpp/MLP_Unify/mlp.cc:33-53 — two parallel dense
    towers (ReLU except last), summed, softmax."""
    relu, none = ActiMode.AC_MODE_RELU, ActiMode.AC_MODE_NONE
    x1 = ff.create_tensor((batch_size, input_dim), name="mlp_in1")
    x2 = ff.create_tensor((batch_size, input_dim), name="mlp_in2")
    t1, t2 = x1, x2
    for i, h in enumerate(hidden_dims):
        acti = none if i + 1 == len(hidden_dims) else relu
        t1 = ff.dense(t1, h, acti, use_bias=False, name=f"t1_d{i}")
        t2 = ff.dense(t2, h, acti, use_bias=False, name=f"t2_d{i}")
    t = ff.add(t1, t2)
    return [x1, x2], ff.softmax(t)


def build_xdl(ff: FFModel, batch_size: int = 64,
              num_embeddings: int = 4, vocab_size: int = 1000000,
              sparse_feature_size: int = 64,
              dense_dims: Sequence[int] = (512, 512, 256, 1)):
    """reference: examples/cpp/XDL/xdl.cc — embedding bags (vocab 1e6,
    dim 64, sum-aggregated) concatenated then MLP to a scalar CTR logit."""
    relu, none = ActiMode.AC_MODE_RELU, ActiMode.AC_MODE_NONE
    sparse_inputs = []
    embedded = []
    for i in range(num_embeddings):
        s = ff.create_tensor((batch_size, 1), dtype=DataType.DT_INT32,
                             name=f"xdl_sparse_{i}")
        sparse_inputs.append(s)
        e = ff.embedding(s, vocab_size, sparse_feature_size,
                         AggrMode.AGGR_MODE_SUM, name=f"xdl_emb_{i}")
        embedded.append(e)
    t = ff.concat(embedded, axis=-1)
    for i, d in enumerate(dense_dims):
        acti = none if i + 1 == len(dense_dims) else relu
        t = ff.dense(t, d, acti, name=f"xdl_d{i}")
    return sparse_inputs, ff.sigmoid(t)


# CANDLE-Uno defaults (candle_uno.cc:29-46)
_UNO_FEATURE_SHAPES = {
    "dose": 1,
    "cell.rnaseq": 942,
    "drug.descriptors": 5270,
    "drug.fingerprints": 2048,
}
_UNO_INPUT_FEATURES = {
    "dose1": "dose",
    "dose2": "dose",
    "cell.rnaseq": "cell.rnaseq",
    "drug1.descriptors": "drug.descriptors",
    "drug1.fingerprints": "drug.fingerprints",
    "drug2.descriptors": "drug.descriptors",
    "drug2.fingerprints": "drug.fingerprints",
}


def build_candle_uno(ff: FFModel, batch_size: int = 64,
                     dense_layers: Sequence[int] = (4192,) * 4,
                     dense_feature_layers: Sequence[int] = (4192,) * 8,
                     feature_shapes: Optional[Dict[str, int]] = None,
                     input_features: Optional[Dict[str, str]] = None):
    """reference: examples/cpp/candle_uno/candle_uno.cc:104-131 — per-feature
    encoder towers (shared per feature *type*), concat, dense head, scalar
    regression output (MSE loss)."""
    relu, none = ActiMode.AC_MODE_RELU, ActiMode.AC_MODE_NONE
    feature_shapes = feature_shapes or dict(_UNO_FEATURE_SHAPES)
    input_features = input_features or dict(_UNO_INPUT_FEATURES)

    inputs = []
    # towers are shared per feature TYPE (candle_uno.cc:104-131 builds one
    # feature_model per type and reuses it for drug1/drug2): stack all inputs
    # of a type along batch, run the tower once, split back per key
    by_type: Dict[str, list] = {}
    order = []
    for key, ftype in input_features.items():
        dim = feature_shapes[ftype]
        x = ff.create_tensor((batch_size, dim),
                             name=f"uno_{key.replace('.', '_')}")
        inputs.append(x)
        by_type.setdefault(ftype, []).append(x)
        order.append((key, ftype))

    encoded_by_type: Dict[str, list] = {}
    for ftype, xs in by_type.items():
        safe = ftype.replace('.', '_')
        if ftype == "dose":  # dose passes through raw (candle_uno.cc:115-121)
            encoded_by_type[ftype] = list(xs)
            continue
        t = xs[0] if len(xs) == 1 else ff.concat(xs, axis=0)
        for i, h in enumerate(dense_feature_layers):
            t = ff.dense(t, h, relu, use_bias=False, name=f"enc_{safe}_d{i}")
        if len(xs) == 1:
            encoded_by_type[ftype] = [t]
        else:
            encoded_by_type[ftype] = ff.split(t, [batch_size] * len(xs),
                                              axis=0)
    counters = {ftype: 0 for ftype in by_type}
    encoded = []
    for key, ftype in order:
        encoded.append(encoded_by_type[ftype][counters[ftype]])
        counters[ftype] += 1
    t = ff.concat(encoded, axis=-1)
    for i, h in enumerate(dense_layers):
        t = ff.dense(t, h, relu, use_bias=False, name=f"head_d{i}")
    out = ff.dense(t, 1, none, use_bias=False, name="uno_out")
    return inputs, out
