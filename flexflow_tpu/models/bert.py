"""BERT-Large proxy model — the flagship benchmark config.

Reference: examples/python/native/bert_proxy_native.py:12-17 (seq 512,
hidden 1024, 16 heads, 24 layers, intermediate 4096) built with
multi_head_attention + dense calls; same builder calls here. The encoder block
is pre-LN free (post-LN like BERT); classification head added for the training
loss (the reference proxy trains against random labels, README.md:73).
"""
from __future__ import annotations

import dataclasses

from ..ffconst import ActiMode
from ..model import FFModel


@dataclasses.dataclass
class BertConfig:
    batch_size: int = 8
    seq_len: int = 512
    hidden: int = 1024
    num_heads: int = 16
    num_layers: int = 24
    intermediate: int = 4096
    num_classes: int = 2
    dropout: float = 0.0  # reference proxy runs without dropout

    @staticmethod
    def large() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def tiny(batch_size: int = 8) -> "BertConfig":
        """CI-sized config for CPU-mesh tests and dry runs."""
        return BertConfig(batch_size=batch_size, seq_len=16, hidden=64,
                          num_heads=4, num_layers=2, intermediate=128)


def build_bert(ff: FFModel, cfg: BertConfig):
    """Build the encoder stack; returns (input_tensor, logits_tensor)."""
    x = ff.create_tensor((cfg.batch_size, cfg.seq_len, cfg.hidden),
                         name="bert_input")
    t = x
    for layer in range(cfg.num_layers):
        attn = ff.multihead_attention(
            t, t, t, embed_dim=cfg.hidden, num_heads=cfg.num_heads,
            dropout=cfg.dropout, name=f"l{layer}_attn")
        t2 = ff.add(attn, t)
        t2 = ff.layer_norm(t2, axes=[2], name=f"l{layer}_ln1")
        ffn = ff.dense(t2, cfg.intermediate, ActiMode.AC_MODE_GELU,
                       name=f"l{layer}_fc1")
        ffn = ff.dense(ffn, cfg.hidden, name=f"l{layer}_fc2")
        t = ff.layer_norm(ff.add(ffn, t2), axes=[2], name=f"l{layer}_ln2")
    pooled = ff.mean(t, dims=[1], name="pool")
    logits = ff.dense(pooled, cfg.num_classes, name="cls")
    return x, ff.softmax(logits)


def bert_param_count(cfg: BertConfig) -> int:
    per_layer = (4 * cfg.hidden * cfg.hidden + cfg.hidden  # qkv+o (+bo)
                 + 2 * cfg.hidden * cfg.intermediate
                 + cfg.intermediate + cfg.hidden  # fc biases
                 + 4 * cfg.hidden)  # 2 layernorms
    head = cfg.hidden * cfg.num_classes + cfg.num_classes
    return cfg.num_layers * per_layer + head


def bert_train_flops_per_step(cfg: BertConfig) -> int:
    """Model FLOPs per training step (fwd+bwd = 3x fwd): 6*P*tokens for the
    matmuls + 12*L*B*S^2*H for attention scores/values (the MFU convention —
    BASELINE.md measurement harness)."""
    tokens = cfg.batch_size * cfg.seq_len
    matmul = 6 * bert_param_count(cfg) * tokens
    attn = 12 * cfg.num_layers * cfg.batch_size * cfg.seq_len ** 2 * cfg.hidden
    return matmul + attn
