"""Transformer encoder (the OSDI'22 BERT-proxy benchmark model), a causal
decoder variant for the serving engine, and the MoE net.

Reference: examples/cpp/Transformer/transformer.cc:33-85 — 12 layers, hidden
1024, 16 heads, seq 512; each layer = MHA + residual + 2-layer FFN (no
layernorm in the reference's proxy — kept optional here);
examples/cpp/mixture_of_experts/moe.cc — MNIST MLP with an MoE layer.
``build_transformer_decoder`` is the autoregressive member of the family
(ISSUE 6): the same block stack with CAUSAL self-attention, token/position
embeddings and a per-token vocab head — what prefill/decode serving needs
(the bidirectional encoder cannot be decoded incrementally).
"""
from __future__ import annotations

import dataclasses

import numpy as np

from ..ffconst import ActiMode, DataType
from ..model import FFModel


@dataclasses.dataclass
class TransformerConfig:
    batch_size: int = 8
    seq_len: int = 512
    hidden: int = 1024
    num_heads: int = 16
    num_layers: int = 12
    use_layernorm: bool = False  # the reference proxy omits LN
    dropout: float = 0.0  # attention dropout (in-kernel on flash/ring/Ulysses)

    @staticmethod
    def tiny(batch_size: int = 8) -> "TransformerConfig":
        return TransformerConfig(batch_size=batch_size, seq_len=16, hidden=32,
                                 num_heads=4, num_layers=2)


def build_transformer(ff: FFModel, cfg: TransformerConfig):
    """reference transformer.cc create_attention_encoder: MHA -> dense(relu)
    -> dense."""
    x = ff.create_tensor((cfg.batch_size, cfg.seq_len, cfg.hidden),
                         name="transformer_input")
    t = x
    for layer in range(cfg.num_layers):
        attn = ff.multihead_attention(t, t, t, embed_dim=cfg.hidden,
                                      num_heads=cfg.num_heads,
                                      dropout=cfg.dropout,
                                      name=f"t{layer}_attn")
        if cfg.use_layernorm:
            attn = ff.layer_norm(ff.add(attn, t), axes=[2],
                                 name=f"t{layer}_ln1")
        h = ff.dense(attn, cfg.hidden, ActiMode.AC_MODE_RELU,
                     name=f"t{layer}_fc1")
        h = ff.dense(h, cfg.hidden, name=f"t{layer}_fc2")
        t = ff.layer_norm(ff.add(h, attn), axes=[2], name=f"t{layer}_ln2") \
            if cfg.use_layernorm else h
    # per-token LM-style head to keep the output shape (reference trains
    # against a replicated label tensor)
    pooled = ff.mean(t, dims=[1], name="pool")
    logits = ff.dense(pooled, 2, name="head")
    return x, ff.softmax(logits)


def build_transformer_decoder(ff: FFModel, cfg: TransformerConfig,
                              vocab_size: int = 256):
    """Causal decoder-only variant of the proxy (ISSUE 6): token + learned
    position embeddings, the same MHA/FFN block stack with ``causal=True``
    attention, and an untied per-token vocab head. Returns
    (input_ids tensor, logits tensor (b, s, vocab)) — the shape contract
    the ServingEngine's prefill/decode split requires."""
    ids = ff.create_tensor((cfg.batch_size, cfg.seq_len),
                           dtype=DataType.DT_INT32, name="dec_input_ids")
    tok = ff.embedding(ids, vocab_size, cfg.hidden, name="dec_wte")
    pos_ids = ff.constant(
        np.broadcast_to(np.arange(cfg.seq_len, dtype=np.int32),
                        (cfg.batch_size, cfg.seq_len)), name="dec_pos_ids")
    pos = ff.embedding(pos_ids, cfg.seq_len, cfg.hidden, name="dec_wpe")
    t = ff.add(tok, pos)
    for layer in range(cfg.num_layers):
        attn = ff.multihead_attention(t, t, t, embed_dim=cfg.hidden,
                                      num_heads=cfg.num_heads,
                                      dropout=cfg.dropout, causal=True,
                                      name=f"d{layer}_attn")
        if cfg.use_layernorm:
            attn = ff.layer_norm(ff.add(attn, t), axes=[2],
                                 name=f"d{layer}_ln1")
        h = ff.dense(attn, cfg.hidden, ActiMode.AC_MODE_RELU,
                     name=f"d{layer}_fc1")
        h = ff.dense(h, cfg.hidden, name=f"d{layer}_fc2")
        t = ff.layer_norm(ff.add(h, attn), axes=[2], name=f"d{layer}_ln2") \
            if cfg.use_layernorm else h
    logits = ff.dense(t, vocab_size, use_bias=False, name="dec_head")
    return ids, logits


def build_moe_mlp(ff: FFModel, batch_size: int = 64, in_dim: int = 784,
                  num_classes: int = 10, num_exp: int = 8,
                  num_select: int = 2, expert_hidden: int = 64,
                  alpha: float = 2.0, lambda_bal: float = 0.04):
    """reference: examples/cpp/mixture_of_experts/moe.cc top_level_task."""
    x = ff.create_tensor((batch_size, in_dim), name="moe_input")
    t = ff.dense(x, 64, ActiMode.AC_MODE_RELU)
    t = ff.moe(t, num_exp=num_exp, num_select=num_select,
               expert_hidden_size=expert_hidden, alpha=alpha,
               lambda_bal=lambda_bal)
    t = ff.dense(t, num_classes)
    return x, ff.softmax(t)
