"""Transformer encoder (the OSDI'22 BERT-proxy benchmark model) and MoE net.

Reference: examples/cpp/Transformer/transformer.cc:33-85 — 12 layers, hidden
1024, 16 heads, seq 512; each layer = MHA + residual + 2-layer FFN (no
layernorm in the reference's proxy — kept optional here);
examples/cpp/mixture_of_experts/moe.cc — MNIST MLP with an MoE layer.
"""
from __future__ import annotations

import dataclasses

from ..ffconst import ActiMode
from ..model import FFModel


@dataclasses.dataclass
class TransformerConfig:
    batch_size: int = 8
    seq_len: int = 512
    hidden: int = 1024
    num_heads: int = 16
    num_layers: int = 12
    use_layernorm: bool = False  # the reference proxy omits LN
    dropout: float = 0.0  # attention dropout (in-kernel on flash/ring/Ulysses)

    @staticmethod
    def tiny(batch_size: int = 8) -> "TransformerConfig":
        return TransformerConfig(batch_size=batch_size, seq_len=16, hidden=32,
                                 num_heads=4, num_layers=2)


def build_transformer(ff: FFModel, cfg: TransformerConfig):
    """reference transformer.cc create_attention_encoder: MHA -> dense(relu)
    -> dense."""
    x = ff.create_tensor((cfg.batch_size, cfg.seq_len, cfg.hidden),
                         name="transformer_input")
    t = x
    for layer in range(cfg.num_layers):
        attn = ff.multihead_attention(t, t, t, embed_dim=cfg.hidden,
                                      num_heads=cfg.num_heads,
                                      dropout=cfg.dropout,
                                      name=f"t{layer}_attn")
        if cfg.use_layernorm:
            attn = ff.layer_norm(ff.add(attn, t), axes=[2],
                                 name=f"t{layer}_ln1")
        h = ff.dense(attn, cfg.hidden, ActiMode.AC_MODE_RELU,
                     name=f"t{layer}_fc1")
        h = ff.dense(h, cfg.hidden, name=f"t{layer}_fc2")
        t = ff.layer_norm(ff.add(h, attn), axes=[2], name=f"t{layer}_ln2") \
            if cfg.use_layernorm else h
    # per-token LM-style head to keep the output shape (reference trains
    # against a replicated label tensor)
    pooled = ff.mean(t, dims=[1], name="pool")
    logits = ff.dense(pooled, 2, name="head")
    return x, ff.softmax(logits)


def build_moe_mlp(ff: FFModel, batch_size: int = 64, in_dim: int = 784,
                  num_classes: int = 10, num_exp: int = 8,
                  num_select: int = 2, expert_hidden: int = 64,
                  alpha: float = 2.0, lambda_bal: float = 0.04):
    """reference: examples/cpp/mixture_of_experts/moe.cc top_level_task."""
    x = ff.create_tensor((batch_size, in_dim), name="moe_input")
    t = ff.dense(x, 64, ActiMode.AC_MODE_RELU)
    t = ff.moe(t, num_exp=num_exp, num_select=num_select,
               expert_hidden_size=expert_hidden, alpha=alpha,
               lambda_bal=lambda_bal)
    t = ff.dense(t, num_classes)
    return x, ff.softmax(t)
