"""Vision models: AlexNet, ResNet-50, InceptionV3-style stem.

Reference builders: examples/cpp/AlexNet/alexnet.cc:40-126 (conv stack +
4096-dense head), examples/cpp/ResNet/resnet.cc (bottleneck blocks),
bootcamp_demo/ff_alexnet_cifar10.py (CIFAR-10 variant). Same FFModel builder
calls, NCHW layout.
"""
from __future__ import annotations

from ..ffconst import ActiMode, PoolType
from ..model import FFModel


def build_alexnet(ff: FFModel, batch_size: int = 64, image_size: int = 224,
                  num_classes: int = 1000):
    """reference: examples/cpp/AlexNet/alexnet.cc (conv 64/192/384/256/256)."""
    x = ff.create_tensor((batch_size, 3, image_size, image_size),
                         name="alexnet_input")
    t = ff.conv2d(x, 64, 11, 11, 4, 4, 2, 2, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.conv2d(t, 192, 5, 5, 1, 1, 2, 2, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.conv2d(t, 384, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 4096, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 4096, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, num_classes)
    return x, ff.softmax(t)


def build_alexnet_cifar10(ff: FFModel, batch_size: int = 64):
    """CIFAR-10 AlexNet (reference: bootcamp_demo/ff_alexnet_cifar10.py):
    smaller strides for 32x32 inputs."""
    x = ff.create_tensor((batch_size, 3, 32, 32), name="cifar_input")
    t = ff.conv2d(x, 64, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.conv2d(t, 192, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.conv2d(t, 384, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 512, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 10)
    return x, ff.softmax(t)


def _bottleneck(ff: FFModel, t, out_channels: int, stride: int,
                projection: bool, name: str):
    """ResNet bottleneck (reference: examples/cpp/ResNet BottleneckBlock)."""
    shortcut = t
    c = ff.conv2d(t, out_channels, 1, 1, 1, 1, 0, 0, name=f"{name}_c1")
    c = ff.batch_norm(c, relu=True, name=f"{name}_bn1")
    c = ff.conv2d(c, out_channels, 3, 3, stride, stride, 1, 1,
                  name=f"{name}_c2")
    c = ff.batch_norm(c, relu=True, name=f"{name}_bn2")
    c = ff.conv2d(c, 4 * out_channels, 1, 1, 1, 1, 0, 0, name=f"{name}_c3")
    c = ff.batch_norm(c, relu=False, name=f"{name}_bn3")
    if projection:
        shortcut = ff.conv2d(shortcut, 4 * out_channels, 1, 1, stride, stride,
                             0, 0, name=f"{name}_proj")
        shortcut = ff.batch_norm(shortcut, relu=False, name=f"{name}_bnp")
    out = ff.add(c, shortcut)
    return ff.relu(out)


def build_resnet50(ff: FFModel, batch_size: int = 64, image_size: int = 224,
                   num_classes: int = 1000, stages=(3, 4, 6, 3)):
    x = ff.create_tensor((batch_size, 3, image_size, image_size),
                         name="resnet_input")
    t = ff.conv2d(x, 64, 7, 7, 2, 2, 3, 3, name="stem")
    t = ff.batch_norm(t, relu=True, name="stem_bn")
    t = ff.pool2d(t, 3, 3, 2, 2, 1, 1)
    channels = 64
    for stage, blocks in enumerate(stages):
        for b in range(blocks):
            stride = 2 if (stage > 0 and b == 0) else 1
            t = _bottleneck(ff, t, channels, stride, projection=(b == 0),
                            name=f"s{stage}b{b}")
        channels *= 2
    # global average pool: kernel = remaining spatial extent (the reference
    # hardcodes 7x7 for 224px inputs)
    _, _, fh, fw = t.dims
    t = ff.pool2d(t, fh, fw, 1, 1, 0, 0, PoolType.POOL_AVG)
    t = ff.flat(t)
    t = ff.dense(t, num_classes)
    return x, ff.softmax(t)
