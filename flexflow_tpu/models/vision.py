"""Vision models: AlexNet, ResNet-50, InceptionV3-style stem.

Reference builders: examples/cpp/AlexNet/alexnet.cc:40-126 (conv stack +
4096-dense head), examples/cpp/ResNet/resnet.cc (bottleneck blocks),
bootcamp_demo/ff_alexnet_cifar10.py (CIFAR-10 variant). Same FFModel builder
calls, NCHW layout.
"""
from __future__ import annotations

from ..ffconst import ActiMode, PoolType
from ..model import FFModel


def build_alexnet(ff: FFModel, batch_size: int = 64, image_size: int = 224,
                  num_classes: int = 1000):
    """reference: examples/cpp/AlexNet/alexnet.cc (conv 64/192/384/256/256)."""
    x = ff.create_tensor((batch_size, 3, image_size, image_size),
                         name="alexnet_input")
    t = ff.conv2d(x, 64, 11, 11, 4, 4, 2, 2, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.conv2d(t, 192, 5, 5, 1, 1, 2, 2, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.conv2d(t, 384, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 4096, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 4096, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, num_classes)
    return x, ff.softmax(t)


def build_alexnet_cifar10(ff: FFModel, batch_size: int = 64):
    """CIFAR-10 AlexNet (reference: bootcamp_demo/ff_alexnet_cifar10.py):
    smaller strides for 32x32 inputs."""
    x = ff.create_tensor((batch_size, 3, 32, 32), name="cifar_input")
    t = ff.conv2d(x, 64, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.conv2d(t, 192, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.conv2d(t, 384, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 512, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 10)
    return x, ff.softmax(t)


def _bottleneck(ff: FFModel, t, out_channels: int, stride: int,
                projection: bool, name: str):
    """ResNet bottleneck (reference: examples/cpp/ResNet BottleneckBlock)."""
    shortcut = t
    c = ff.conv2d(t, out_channels, 1, 1, 1, 1, 0, 0, name=f"{name}_c1")
    c = ff.batch_norm(c, relu=True, name=f"{name}_bn1")
    c = ff.conv2d(c, out_channels, 3, 3, stride, stride, 1, 1,
                  name=f"{name}_c2")
    c = ff.batch_norm(c, relu=True, name=f"{name}_bn2")
    c = ff.conv2d(c, 4 * out_channels, 1, 1, 1, 1, 0, 0, name=f"{name}_c3")
    c = ff.batch_norm(c, relu=False, name=f"{name}_bn3")
    if projection:
        shortcut = ff.conv2d(shortcut, 4 * out_channels, 1, 1, stride, stride,
                             0, 0, name=f"{name}_proj")
        shortcut = ff.batch_norm(shortcut, relu=False, name=f"{name}_bnp")
    out = ff.add(c, shortcut)
    return ff.relu(out)


def build_resnet50(ff: FFModel, batch_size: int = 64, image_size: int = 224,
                   num_classes: int = 1000, stages=(3, 4, 6, 3)):
    x = ff.create_tensor((batch_size, 3, image_size, image_size),
                         name="resnet_input")
    t = ff.conv2d(x, 64, 7, 7, 2, 2, 3, 3, name="stem")
    t = ff.batch_norm(t, relu=True, name="stem_bn")
    t = ff.pool2d(t, 3, 3, 2, 2, 1, 1)
    channels = 64
    for stage, blocks in enumerate(stages):
        for b in range(blocks):
            stride = 2 if (stage > 0 and b == 0) else 1
            t = _bottleneck(ff, t, channels, stride, projection=(b == 0),
                            name=f"s{stage}b{b}")
        channels *= 2
    # global average pool: kernel = remaining spatial extent (the reference
    # hardcodes 7x7 for 224px inputs)
    _, _, fh, fw = t.dims
    t = ff.pool2d(t, fh, fw, 1, 1, 0, 0, PoolType.POOL_AVG)
    t = ff.flat(t)
    t = ff.dense(t, num_classes)
    return x, ff.softmax(t)


# --------------------------------------------------------------- InceptionV3
# Reference: examples/cpp/InceptionV3/inception.cc — block builders
# InceptionA (:26), InceptionB (:50), InceptionC (:64), InceptionD, InceptionE.
def _inception_a(ff, t, pool_features, name):
    relu = ActiMode.AC_MODE_RELU
    t1 = ff.conv2d(t, 64, 1, 1, 1, 1, 0, 0, relu, name=f"{name}_b1")
    t2 = ff.conv2d(t, 48, 1, 1, 1, 1, 0, 0, relu, name=f"{name}_b2a")
    t2 = ff.conv2d(t2, 64, 5, 5, 1, 1, 2, 2, relu, name=f"{name}_b2b")
    t3 = ff.conv2d(t, 64, 1, 1, 1, 1, 0, 0, relu, name=f"{name}_b3a")
    t3 = ff.conv2d(t3, 96, 3, 3, 1, 1, 1, 1, relu, name=f"{name}_b3b")
    t3 = ff.conv2d(t3, 96, 3, 3, 1, 1, 1, 1, relu, name=f"{name}_b3c")
    t4 = ff.pool2d(t, 3, 3, 1, 1, 1, 1, PoolType.POOL_AVG)
    t4 = ff.conv2d(t4, pool_features, 1, 1, 1, 1, 0, 0, relu,
                   name=f"{name}_b4")
    return ff.concat([t1, t2, t3, t4], 1)


def _inception_b(ff, t, name):
    relu = ActiMode.AC_MODE_RELU
    t1 = ff.conv2d(t, 384, 3, 3, 2, 2, 0, 0, relu, name=f"{name}_b1")
    t2 = ff.conv2d(t, 64, 1, 1, 1, 1, 0, 0, relu, name=f"{name}_b2a")
    t2 = ff.conv2d(t2, 96, 3, 3, 1, 1, 1, 1, relu, name=f"{name}_b2b")
    t2 = ff.conv2d(t2, 96, 3, 3, 2, 2, 0, 0, relu, name=f"{name}_b2c")
    t3 = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    return ff.concat([t1, t2, t3], 1)


def _inception_c(ff, t, channels, name):
    relu = ActiMode.AC_MODE_RELU
    t1 = ff.conv2d(t, 192, 1, 1, 1, 1, 0, 0, relu, name=f"{name}_b1")
    t2 = ff.conv2d(t, channels, 1, 1, 1, 1, 0, 0, relu, name=f"{name}_b2a")
    t2 = ff.conv2d(t2, channels, 1, 7, 1, 1, 0, 3, relu, name=f"{name}_b2b")
    t2 = ff.conv2d(t2, 192, 7, 1, 1, 1, 3, 0, relu, name=f"{name}_b2c")
    t3 = ff.conv2d(t, channels, 1, 1, 1, 1, 0, 0, relu, name=f"{name}_b3a")
    t3 = ff.conv2d(t3, channels, 7, 1, 1, 1, 3, 0, relu, name=f"{name}_b3b")
    t3 = ff.conv2d(t3, channels, 1, 7, 1, 1, 0, 3, relu, name=f"{name}_b3c")
    t3 = ff.conv2d(t3, channels, 7, 1, 1, 1, 3, 0, relu, name=f"{name}_b3d")
    t3 = ff.conv2d(t3, 192, 1, 7, 1, 1, 0, 3, relu, name=f"{name}_b3e")
    t4 = ff.pool2d(t, 3, 3, 1, 1, 1, 1, PoolType.POOL_AVG)
    t4 = ff.conv2d(t4, 192, 1, 1, 1, 1, 0, 0, relu, name=f"{name}_b4")
    return ff.concat([t1, t2, t3, t4], 1)


def _inception_d(ff, t, name):
    relu = ActiMode.AC_MODE_RELU
    t1 = ff.conv2d(t, 192, 1, 1, 1, 1, 0, 0, relu, name=f"{name}_b1a")
    t1 = ff.conv2d(t1, 320, 3, 3, 2, 2, 0, 0, relu, name=f"{name}_b1b")
    t2 = ff.conv2d(t, 192, 1, 1, 1, 1, 0, 0, relu, name=f"{name}_b2a")
    t2 = ff.conv2d(t2, 192, 1, 7, 1, 1, 0, 3, relu, name=f"{name}_b2b")
    t2 = ff.conv2d(t2, 192, 7, 1, 1, 1, 3, 0, relu, name=f"{name}_b2c")
    t2 = ff.conv2d(t2, 192, 3, 3, 2, 2, 0, 0, relu, name=f"{name}_b2d")
    t3 = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    return ff.concat([t1, t2, t3], 1)


def _inception_e(ff, t, name):
    relu = ActiMode.AC_MODE_RELU
    t1 = ff.conv2d(t, 320, 1, 1, 1, 1, 0, 0, relu, name=f"{name}_b1")
    t2i = ff.conv2d(t, 384, 1, 1, 1, 1, 0, 0, relu, name=f"{name}_b2a")
    t2a = ff.conv2d(t2i, 384, 1, 3, 1, 1, 0, 1, relu, name=f"{name}_b2b")
    t2b = ff.conv2d(t2i, 384, 3, 1, 1, 1, 1, 0, relu, name=f"{name}_b2c")
    t3i = ff.conv2d(t, 448, 1, 1, 1, 1, 0, 0, relu, name=f"{name}_b3a")
    t3i = ff.conv2d(t3i, 384, 3, 3, 1, 1, 1, 1, relu, name=f"{name}_b3b")
    t3a = ff.conv2d(t3i, 384, 1, 3, 1, 1, 0, 1, relu, name=f"{name}_b3c")
    t3b = ff.conv2d(t3i, 384, 3, 1, 1, 1, 1, 0, relu, name=f"{name}_b3d")
    t4 = ff.pool2d(t, 3, 3, 1, 1, 1, 1, PoolType.POOL_AVG)
    t4 = ff.conv2d(t4, 192, 1, 1, 1, 1, 0, 0, relu, name=f"{name}_b4")
    return ff.concat([t1, t2a, t2b, t3a, t3b, t4], 1)


def build_inception_v3(ff: FFModel, batch_size: int = 64,
                       image_size: int = 299, num_classes: int = 1000):
    """InceptionV3 (reference: examples/cpp/InceptionV3/inception.cc)."""
    relu = ActiMode.AC_MODE_RELU
    x = ff.create_tensor((batch_size, 3, image_size, image_size),
                         name="inception_input")
    t = ff.conv2d(x, 32, 3, 3, 2, 2, 0, 0, relu, name="stem1")
    t = ff.conv2d(t, 32, 3, 3, 1, 1, 0, 0, relu, name="stem2")
    t = ff.conv2d(t, 64, 3, 3, 1, 1, 1, 1, relu, name="stem3")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = ff.conv2d(t, 80, 1, 1, 1, 1, 0, 0, relu, name="stem4")
    t = ff.conv2d(t, 192, 3, 3, 1, 1, 1, 1, relu, name="stem5")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = _inception_a(ff, t, 32, "a1")
    t = _inception_a(ff, t, 64, "a2")
    t = _inception_a(ff, t, 64, "a3")
    t = _inception_b(ff, t, "b1")
    t = _inception_c(ff, t, 128, "c1")
    t = _inception_c(ff, t, 160, "c2")
    t = _inception_c(ff, t, 160, "c3")
    t = _inception_c(ff, t, 192, "c4")
    t = _inception_d(ff, t, "d1")
    t = _inception_e(ff, t, "e1")
    t = _inception_e(ff, t, "e2")
    _, _, fh, fw = t.dims
    t = ff.pool2d(t, fh, fw, 1, 1, 0, 0, PoolType.POOL_AVG)
    t = ff.flat(t)
    t = ff.dense(t, num_classes)
    return x, ff.softmax(t)


# --------------------------------------------------------------- ResNeXt-50
def _resnext_block(ff: FFModel, t, stride: int, out_channels: int,
                   groups: int, name: str):
    """Grouped-conv bottleneck (reference: examples/cpp/resnext50/
    resnext.cc:12-30)."""
    relu = ActiMode.AC_MODE_RELU
    shortcut = t
    in_channels = t.dims[1]
    c = ff.conv2d(t, out_channels, 1, 1, 1, 1, 0, 0, relu, name=f"{name}_c1")
    c = ff.conv2d(c, out_channels, 3, 3, stride, stride, 1, 1, relu,
                  groups=groups, name=f"{name}_c2")
    c = ff.conv2d(c, 2 * out_channels, 1, 1, 1, 1, 0, 0, name=f"{name}_c3")
    if in_channels != 2 * out_channels or stride > 1:
        shortcut = ff.conv2d(shortcut, 2 * out_channels, 1, 1, stride, stride,
                             0, 0, name=f"{name}_proj")
    return ff.relu(ff.add(c, shortcut))


def build_resnext50(ff: FFModel, batch_size: int = 64, image_size: int = 224,
                    num_classes: int = 1000):
    """ResNeXt-50 32x4d (reference: examples/cpp/resnext50/resnext.cc:58-84)."""
    relu = ActiMode.AC_MODE_RELU
    x = ff.create_tensor((batch_size, 3, image_size, image_size),
                         name="resnext_input")
    t = ff.conv2d(x, 64, 7, 7, 2, 2, 3, 3, relu, name="stem")
    t = ff.pool2d(t, 3, 3, 2, 2, 1, 1)
    for b in range(3):
        t = _resnext_block(ff, t, 1, 128, 32, f"s1b{b}")
    for b in range(4):
        t = _resnext_block(ff, t, 2 if b == 0 else 1, 256, 32, f"s2b{b}")
    for b in range(6):
        t = _resnext_block(ff, t, 2 if b == 0 else 1, 512, 32, f"s3b{b}")
    for b in range(3):
        t = _resnext_block(ff, t, 2 if b == 0 else 1, 1024, 32, f"s4b{b}")
    _, _, fh, fw = t.dims
    t = ff.pool2d(t, fh, fw, 1, 1, 0, 0, PoolType.POOL_AVG)
    t = ff.flat(t)
    t = ff.dense(t, num_classes)
    return x, ff.softmax(t)
