"""GPT-2-style decoder-only causal LM builder.

Reference analog: the torch frontend traces the HF family generally
(python/flexflow/torch/model.py:2427) — decoder-only models are first-class
there via GPT2LMHeadModel; this native builder gives the same family as
FFModel calls. Pre-LN blocks with CAUSAL multi-head attention: on TPU the
causal core lowers to the Pallas flash kernel (kernels/flash_attention.py)
whenever the sequence admits >=256-wide blocks — the flash-causal path the
VERDICT r3 item 6 Done criterion names.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from ..ffconst import ActiMode, DataType
from ..model import FFModel


@dataclasses.dataclass
class GPT2Config:
    batch_size: int = 8
    seq_len: int = 512
    hidden: int = 768
    num_heads: int = 12
    num_layers: int = 12
    intermediate: int = 3072
    vocab_size: int = 50257
    dropout: float = 0.0

    @staticmethod
    def small() -> "GPT2Config":
        return GPT2Config()

    @staticmethod
    def tiny(batch_size: int = 8) -> "GPT2Config":
        """CI-sized config for CPU-mesh tests and dry runs."""
        return GPT2Config(batch_size=batch_size, seq_len=16, hidden=64,
                          num_heads=4, num_layers=2, intermediate=128,
                          vocab_size=100)


def build_gpt2(ff: FFModel, cfg: GPT2Config):
    """Decoder-only LM: token + learned position embeddings, pre-LN blocks
    (ln -> causal MHA -> residual; ln -> gelu MLP -> residual), final LN,
    untied vocab head. Returns (input_ids tensor, logits tensor
    (b, s, vocab))."""
    ids = ff.create_tensor((cfg.batch_size, cfg.seq_len),
                           dtype=DataType.DT_INT32, name="input_ids")
    tok = ff.embedding(ids, cfg.vocab_size, cfg.hidden, name="wte")
    pos_ids = ff.constant(
        np.broadcast_to(np.arange(cfg.seq_len, dtype=np.int32),
                        (cfg.batch_size, cfg.seq_len)), name="pos_ids")
    pos = ff.embedding(pos_ids, cfg.seq_len, cfg.hidden, name="wpe")
    t = ff.add(tok, pos)
    for layer in range(cfg.num_layers):
        h = ff.layer_norm(t, axes=[2], name=f"h{layer}_ln1")
        attn = ff.multihead_attention(
            h, h, h, embed_dim=cfg.hidden, num_heads=cfg.num_heads,
            dropout=cfg.dropout, causal=True, name=f"h{layer}_attn")
        t = ff.add(t, attn)
        h = ff.layer_norm(t, axes=[2], name=f"h{layer}_ln2")
        m = ff.dense(h, cfg.intermediate, ActiMode.AC_MODE_GELU,
                     name=f"h{layer}_fc1")
        m = ff.dense(m, cfg.hidden, name=f"h{layer}_fc2")
        t = ff.add(t, m)
    t = ff.layer_norm(t, axes=[2], name="ln_f")
    logits = ff.dense(t, cfg.vocab_size, use_bias=False, name="lm_head")
    return ids, logits


def gpt2_param_count(cfg: GPT2Config) -> int:
    per_layer = (4 * cfg.hidden * cfg.hidden + cfg.hidden  # qkv+o (+bo)
                 + 2 * cfg.hidden * cfg.intermediate
                 + cfg.intermediate + cfg.hidden  # fc biases
                 + 4 * cfg.hidden)  # two layer norms
    emb = (cfg.vocab_size + cfg.seq_len) * cfg.hidden
    head = cfg.hidden * cfg.vocab_size
    return cfg.num_layers * per_layer + emb + head + 2 * cfg.hidden


def gpt2_train_flops_per_step(cfg: GPT2Config) -> int:
    """Model FLOPs per training step (fwd + bwd = 3x fwd), matmuls only."""
    tokens = cfg.batch_size * cfg.seq_len
    per_layer = (2 * tokens * 4 * cfg.hidden * cfg.hidden
                 + 2 * 2 * tokens * cfg.hidden * cfg.intermediate
                 + 2 * 2 * tokens * cfg.seq_len * cfg.hidden)
    head = 2 * tokens * cfg.hidden * cfg.vocab_size
    fwd = cfg.num_layers * per_layer + head
    return 3 * fwd
