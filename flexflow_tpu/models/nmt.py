"""NMT: LSTM encoder-decoder sequence-to-sequence model.

Reference: nmt/ (rnn.h:31-32 — batchSize/hiddenSize/embedSize/vocabSize/
numLayers/seqLength; embed.cu, lstm.cu, linear.cu) — the legacy pre-FFModel
LSTM NMT app. Rebuilt on the modern builder API: encoder embed + stacked
LSTM; decoder embed + stacked LSTM initialized from the encoder's final
state (the hand-off nmt.cc wires manually between per-node LSTM chunks);
projection to target vocab + softmax, trained with teacher forcing.
"""
from __future__ import annotations

import dataclasses

from ..ffconst import AggrMode, DataType
from ..model import FFModel


@dataclasses.dataclass
class NMTConfig:
    batch_size: int = 64
    src_vocab: int = 32000
    tgt_vocab: int = 32000
    embed_size: int = 1024   # rnn.h embedSize
    hidden_size: int = 1024  # rnn.h hiddenSize
    num_layers: int = 2      # rnn.h numLayers
    src_len: int = 40        # rnn.h seqLength
    tgt_len: int = 40

    @staticmethod
    def tiny(batch_size: int = 8) -> "NMTConfig":
        return NMTConfig(batch_size=batch_size, src_vocab=100, tgt_vocab=100,
                         embed_size=16, hidden_size=16, num_layers=2,
                         src_len=6, tgt_len=5)


def build_nmt(ff: FFModel, cfg: NMTConfig):
    """Returns ([src_tokens, tgt_tokens], per-token probs of shape
    (batch*tgt_len, tgt_vocab)). Loss: sparse CCE over flattened
    (batch*tgt_len,) labels — drive with executor.make_train_step and
    labels.reshape(-1) (see tests/test_model_zoo.py), reassigning the
    returned params/opt_state back to ff.params/ff.opt_state each step
    (the step donates its input buffers, so the old arrays are deleted on
    TPU); FFModel.fit slices labels by batch rows, so flattened token
    labels don't fit it."""
    src = ff.create_tensor((cfg.batch_size, cfg.src_len),
                           dtype=DataType.DT_INT32, name="nmt_src")
    tgt = ff.create_tensor((cfg.batch_size, cfg.tgt_len),
                           dtype=DataType.DT_INT32, name="nmt_tgt")

    # encoder
    t = ff.embedding(src, cfg.src_vocab, cfg.embed_size,
                     AggrMode.AGGR_MODE_NONE, name="enc_embed")
    states = []
    for i in range(cfg.num_layers):
        t, state = ff.lstm(t, cfg.hidden_size, name=f"enc_lstm{i}")
        states.append(state)

    # decoder: each layer starts from the matching encoder layer's final
    # state (nmt.cc's chunk-to-chunk hidden hand-off)
    d = ff.embedding(tgt, cfg.tgt_vocab, cfg.embed_size,
                     AggrMode.AGGR_MODE_NONE, name="dec_embed")
    for i in range(cfg.num_layers):
        d, _ = ff.lstm(d, cfg.hidden_size, initial_state=states[i],
                       name=f"dec_lstm{i}")

    logits = ff.dense(d, cfg.tgt_vocab, name="nmt_proj")
    # flatten (batch, tgt_len) so sparse-CCE sees per-token rows
    logits = ff.reshape(logits, (cfg.batch_size * cfg.tgt_len, cfg.tgt_vocab))
    probs = ff.softmax(logits)
    return [src, tgt], probs
