"""Model zoo matching the reference's benchmark configs (BASELINE.md):
AlexNet/CIFAR-10, ResNet-50, Transformer NMT, BERT-Large, DLRM, MoE."""
from .bert import BertConfig, build_bert, bert_param_count  # noqa: F401
