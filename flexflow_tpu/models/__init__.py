"""Model zoo matching the reference's benchmark configs (BASELINE.md):
AlexNet/CIFAR-10, ResNet-50, Transformer NMT, BERT-Large, DLRM, MoE."""
from .bert import BertConfig, build_bert, bert_param_count  # noqa: F401
from .vision import (build_alexnet, build_alexnet_cifar10,  # noqa: F401
                     build_resnet50)
from .dlrm import build_dlrm  # noqa: F401
from .transformer import (TransformerConfig, build_transformer,  # noqa: F401
                          build_moe_mlp)
