"""Model zoo matching the reference's example apps (SURVEY §2.5):
AlexNet/CIFAR-10, ResNet-50, ResNeXt-50, InceptionV3, Transformer, BERT-Large,
GPT-2 (decoder-only causal LM), DLRM, XDL, MLP_Unify, CANDLE-Uno, MoE,
NMT (LSTM seq2seq)."""
from .bert import BertConfig, build_bert, bert_param_count  # noqa: F401
from .gpt2 import (GPT2Config, build_gpt2,  # noqa: F401
                   gpt2_param_count, gpt2_train_flops_per_step)
from .vision import (build_alexnet, build_alexnet_cifar10,  # noqa: F401
                     build_resnet50, build_resnext50, build_inception_v3)
from .dlrm import build_dlrm  # noqa: F401
from .transformer import (TransformerConfig, build_transformer,  # noqa: F401
                          build_moe_mlp)
from .misc import (build_mlp_unify, build_xdl,  # noqa: F401
                   build_candle_uno)
from .nmt import NMTConfig, build_nmt  # noqa: F401
