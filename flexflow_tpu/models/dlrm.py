"""DLRM: embedding bags + bottom/top MLPs + feature interaction.

Reference: examples/cpp/DLRM/dlrm.cc — sparse inputs feed table-sharded
embedding bags (SUM aggregation), dense features the bottom MLP; the
interaction layer concatenates and takes pairwise dot products via
batch_matmul; top MLP -> sigmoid. The pre-searched 8/16-GPU strategies
(examples/cpp/DLRM/strategies/*.pb) are the table-parameter-parallel
placements our hybrid strategy / unity search reproduce via 'table' sharding.
"""
from __future__ import annotations

from typing import Sequence

from ..ffconst import ActiMode, AggrMode, DataType
from ..model import FFModel


def build_dlrm(ff: FFModel, batch_size: int = 64,
               embedding_sizes: Sequence[int] = (1000,) * 8,
               embedding_bag_size: int = 1, embedding_dim: int = 64,
               dense_dim: int = 16,
               mlp_bot: Sequence[int] = (512, 256, 64),
               mlp_top: Sequence[int] = (512, 256, 1)):
    """Returns (sparse_inputs, dense_input, prediction)."""
    sparse_inputs = []
    emb_outputs = []
    for i, n_entries in enumerate(embedding_sizes):
        s = ff.create_tensor((batch_size, embedding_bag_size),
                             DataType.DT_INT64, name=f"sparse_{i}")
        sparse_inputs.append(s)
        emb = ff.embedding(s, n_entries, embedding_dim,
                           AggrMode.AGGR_MODE_SUM, name=f"emb_{i}")
        emb_outputs.append(emb)

    dense_input = ff.create_tensor((batch_size, dense_dim), name="dense_input")
    t = dense_input
    for i, h in enumerate(mlp_bot):
        t = ff.dense(t, h, ActiMode.AC_MODE_RELU, name=f"bot_{i}")
    bot_out = t  # (batch, embedding_dim) if mlp_bot[-1] == embedding_dim

    # interact_features (dlrm.cc): concat features, pairwise dots
    features = emb_outputs + [bot_out]
    n_f = len(features)
    cat = ff.concat(features, axis=1)  # (batch, n_f * dim)
    mat = ff.reshape(cat, (batch_size, n_f, embedding_dim))
    matT = ff.transpose(mat, (0, 2, 1))
    inter = ff.batch_matmul(mat, matT)  # (batch, n_f, n_f)
    inter_flat = ff.reshape(inter, (batch_size, n_f * n_f))
    top_in = ff.concat([bot_out, inter_flat], axis=1)

    t = top_in
    for i, h in enumerate(mlp_top[:-1]):
        t = ff.dense(t, h, ActiMode.AC_MODE_RELU, name=f"top_{i}")
    out = ff.dense(t, mlp_top[-1], ActiMode.AC_MODE_SIGMOID, name="top_out")
    return sparse_inputs, dense_input, out
