from .dataloader import SingleDataLoader, batch_iterator  # noqa: F401
