"""Data loading: host numpy -> sharded device batches.

Reference: python/flexflow_dataloader.cc (574 LoC) — the full dataset is pinned
in zero-copy memory and an index task copies each batch slice to framebuffer
per iteration (load_entire_dataset_from_numpy:324, next_batch:208). TPU-native:
the dataset stays in host RAM; each batch is ``jax.device_put`` with the batch
NamedSharding (each chip receives exactly its shard — the same
one-copy-per-iteration pattern), with lookahead prefetch to overlap host->HBM
transfer with the previous step (replacing zero-copy staging).
"""
from __future__ import annotations

import threading
from queue import Queue
from typing import Any, Iterator, List, Optional, Sequence

import numpy as np


class SingleDataLoader:
    """API-parity loader for one tensor (reference: flexflow_cffi.py:2447)."""

    def __init__(self, ffmodel, batch_tensor, full_array: np.ndarray,
                 num_samples: Optional[int] = None):
        self.ffmodel = ffmodel
        self.batch_tensor = batch_tensor
        self.full_array = np.asarray(full_array)
        self.num_samples = num_samples or self.full_array.shape[0]
        self.batch_size = batch_tensor.dims[0]
        self._idx = 0

    def reset(self) -> None:
        self._idx = 0

    def next_batch(self, ffmodel=None) -> np.ndarray:
        lo = self._idx
        hi = lo + self.batch_size
        if hi > self.num_samples:
            self.reset()
            lo, hi = 0, self.batch_size
        self._idx = hi
        return self.full_array[lo:hi]

    @property
    def num_batches(self) -> int:
        return self.num_samples // self.batch_size


def batch_iterator(arrays: Sequence[np.ndarray], batch_size: int,
                   shuffle: bool = False, seed: int = 0,
                   drop_remainder: bool = True,
                   start_batch: int = 0) -> Iterator[List[np.ndarray]]:
    """``start_batch`` skips the first k batches of the (seed-determined)
    stream without materializing them — the exact-resume path: a run
    restored mid-epoch replays the same shuffle and continues at the batch
    cursor the checkpoint recorded (resilience/session.py)."""
    n = arrays[0].shape[0]
    idx = np.arange(n)
    if shuffle:
        np.random.default_rng(seed).shuffle(idx)
    if start_batch > 0:
        # trim AFTER the shuffle: the remaining stream is identical to the
        # tail of an uninterrupted epoch at the same seed
        idx = idx[start_batch * batch_size:]
    m = len(idx)
    if shuffle:
        # native double-buffered staging: C++ gathers batch b+1 while batch b
        # ships to the device (flexflow_tpu/native BatchPipeline; falls back
        # to synchronous gather without the library)
        from ..native import BatchPipeline

        if drop_remainder or m % batch_size == 0:
            yield from BatchPipeline(arrays, idx, batch_size)
            return
        from ..native import gather_rows

        arrays = [np.ascontiguousarray(a) for a in arrays]
        take = gather_rows
    else:
        def take(a, sl):
            return a[sl]
    nb = m // batch_size if drop_remainder else -(-m // batch_size)
    for b in range(nb):
        sl = idx[b * batch_size:(b + 1) * batch_size]
        yield [take(a, sl) for a in arrays]


def device_put_batch(arrays: List[np.ndarray], shardings: List[Any]):
    import jax

    if shardings and shardings[0] is not None:
        return [jax.device_put(a, s) for a, s in zip(arrays, shardings)]
    return [jax.device_put(a) for a in arrays]


def prefetch_iterator(it: Iterator, shardings: List[Any], depth: int = 2):
    """Background-thread prefetch of device batches (double buffering).

    Abandoning the generator early (e.g. fit breaking out on a dynamic
    recompile) stops the producer promptly and JOINS it — without the stop
    flag it would stay blocked on ``q.put`` for the rest of the process,
    pinning its in-flight device batches; and without the join, a producer
    mid-``device_put`` could still race one more item into a queue nobody
    will drain. Producer errors (a raising source iterator, a failed device
    transfer) propagate to the consumer via the same stop-aware queue path
    instead of dying silently in the thread — every ``put``, the terminal
    sentinel and the error included, gives up once the consumer is gone."""
    from queue import Empty, Full

    q: Queue = Queue(maxsize=depth)
    stop = threading.Event()
    _END = object()

    def put_or_stop(item) -> bool:
        """Blocking put that abandons ship when the consumer left; True if
        the item landed."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except Full:
                continue
        return False

    def producer():
        try:
            for batch in it:
                staged = device_put_batch(batch, shardings)
                if not put_or_stop(staged):
                    return
            put_or_stop(_END)
        except BaseException as e:  # propagate to the consumer, don't swallow
            put_or_stop(e)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        # drain-and-join loop: draining unblocks a producer mid-put, and
        # every put path above is stop-aware, so the thread exits promptly
        # — unless it is blocked inside the SOURCE iterator or a device
        # transfer, which cannot observe the stop flag; bound the wait
        # (short: this sits on fit's recompile path) and fall back to
        # leaking the daemon thread (the pre-fix behavior) rather than
        # stalling the training process in generator close
        import time as _time

        deadline = _time.monotonic() + 1.0
        while t.is_alive() and _time.monotonic() < deadline:
            try:
                while True:
                    q.get_nowait()
            except Empty:
                pass
            t.join(timeout=0.1)
        # final drain drops any last raced-in item's device buffers
        try:
            while True:
                q.get_nowait()
        except Empty:
            pass
