"""Keras-frontend dataset loaders (reference:
python/flexflow/keras/datasets/{mnist,cifar10,cifar,reuters}.py).

Same ``load_data()`` API and array shapes/dtypes. The reference downloads
from S3 via ``get_file``; here a local cache is honored first
(``$FF_DATASET_DIR`` or ``~/.keras/datasets``, same file names) and when the
file is absent — e.g. on air-gapped TPU pods — a deterministic synthetic
dataset with the exact real shapes/dtypes/class counts is generated so every
example script runs end-to-end (the reference's own examples fall back to
random tensors when ``--dataset`` is absent, README.md:73)."""
from __future__ import annotations

import os
from types import SimpleNamespace

import numpy as np


def _cache_path(fname: str):
    for base in (os.environ.get("FF_DATASET_DIR"),
                 os.path.expanduser("~/.keras/datasets")):
        if base:
            p = os.path.join(base, fname)
            if os.path.exists(p):
                return p
    return None


def _mnist_load_data(path: str = "mnist.npz"):
    """reference: datasets/mnist.py load_data — returns
    (x_train (60000, 28, 28) uint8, y_train (60000,) uint8), (x_test ...)."""
    cached = _cache_path(path)
    if cached:
        with np.load(cached, allow_pickle=True) as f:
            return ((f["x_train"], f["y_train"]),
                    (f["x_test"], f["y_test"]))
    rng = np.random.default_rng(0)
    x_train = rng.integers(0, 256, size=(60000, 28, 28), dtype=np.uint8)
    y_train = rng.integers(0, 10, size=(60000,), dtype=np.uint8)
    x_test = rng.integers(0, 256, size=(10000, 28, 28), dtype=np.uint8)
    y_test = rng.integers(0, 10, size=(10000,), dtype=np.uint8)
    return (x_train, y_train), (x_test, y_test)


def _cifar10_load_data(num_samples=40000):
    """reference: datasets/cifar10.py load_data(num_samples=40000) — returns
    channels-first (num_samples, 3, 32, 32) uint8 train / (10000, 3, 32, 32)
    test, truncated to num_samples train rows (the examples call
    cifar10.load_data(10000)); same 40000-row default as the reference."""
    (tr, te) = _cifar10_load_all()
    if num_samples is not None:
        tr = (tr[0][:num_samples], tr[1][:num_samples])
    return tr, te


def _cifar10_load_all():
    cached = _cache_path("cifar-10-batches-py")
    if cached:
        from pickle import load

        xs, ys = [], []
        for i in range(1, 6):
            with open(os.path.join(cached, f"data_batch_{i}"), "rb") as f:
                d = load(f, encoding="bytes")
            xs.append(d[b"data"].reshape(-1, 3, 32, 32))
            ys.append(np.asarray(d[b"labels"]))
        with open(os.path.join(cached, "test_batch"), "rb") as f:
            d = load(f, encoding="bytes")
        x_test = d[b"data"].reshape(-1, 3, 32, 32)
        y_test = np.asarray(d[b"labels"]).reshape(-1, 1)
        return ((np.concatenate(xs), np.concatenate(ys).reshape(-1, 1)),
                (x_test, y_test))
    rng = np.random.default_rng(0)
    x_train = rng.integers(0, 256, size=(50000, 3, 32, 32), dtype=np.uint8)
    y_train = rng.integers(0, 10, size=(50000, 1), dtype=np.uint8)
    x_test = rng.integers(0, 256, size=(10000, 3, 32, 32), dtype=np.uint8)
    y_test = rng.integers(0, 10, size=(10000, 1), dtype=np.uint8)
    return (x_train, y_train), (x_test, y_test)


def _reuters_load_data(path: str = "reuters.npz", num_words=None,
                       skip_top: int = 0, maxlen=None, test_split: float = 0.2,
                       seed: int = 113, start_char: int = 1,
                       oov_char: int = 2, index_from: int = 3):
    """reference: datasets/reuters.py load_data — variable-length word-id
    sequences + 46-topic labels."""
    cached = _cache_path(path)
    if cached:
        with np.load(cached, allow_pickle=True) as f:
            xs, labels = f["x"], f["y"]
    else:
        rng = np.random.default_rng(seed)
        n = 11228
        vocab = num_words or 10000
        lengths = rng.integers(12, 200, size=n)
        xs = np.asarray([
            [start_char] + list(rng.integers(index_from + 1, vocab,
                                             size=ln))
            for ln in lengths], dtype=object)
        labels = rng.integers(0, 46, size=n)
    if num_words is not None:
        xs = np.asarray([[w if w < num_words else oov_char for w in seq]
                         for seq in xs], dtype=object)
    if maxlen is not None:
        keep = [i for i, seq in enumerate(xs) if len(seq) < maxlen]
        xs, labels = xs[keep], labels[keep]
    split = int(len(xs) * (1.0 - test_split))
    return ((xs[:split], labels[:split]), (xs[split:], labels[split:]))


mnist = SimpleNamespace(load_data=_mnist_load_data)
cifar10 = SimpleNamespace(load_data=_cifar10_load_data)
reuters = SimpleNamespace(load_data=_reuters_load_data)
