"""ML frontends (SURVEY §2.4): torch-fx importer, Keras clone, ONNX importer."""
from .torch_fx import PyTorchModel, copy_torch_weights  # noqa: F401
from . import keras  # noqa: F401
