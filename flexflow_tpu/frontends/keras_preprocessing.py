"""Keras-frontend preprocessing (reference:
python/flexflow/keras/preprocessing/ — the subset the example scripts use:
``text.Tokenizer.sequences_to_matrix`` for reuters, ``sequence.pad_sequences``
for imdb-style inputs)."""
from __future__ import annotations

from types import SimpleNamespace

import numpy as np


class Tokenizer:
    """reference: preprocessing/text.py Tokenizer (the modes
    sequences_to_matrix supports there: binary/count/freq)."""

    def __init__(self, num_words=None):
        self.num_words = num_words

    def sequences_to_matrix(self, sequences, mode: str = "binary"):
        assert self.num_words, "Tokenizer(num_words=...) required"
        m = np.zeros((len(sequences), self.num_words), dtype=np.float32)
        for i, seq in enumerate(sequences):
            for w in seq:
                if w < self.num_words:
                    if mode == "binary":
                        m[i, w] = 1.0
                    else:
                        m[i, w] += 1.0
        if mode == "freq":
            m = m / np.maximum(m.sum(axis=1, keepdims=True), 1.0)
        return m


def pad_sequences(sequences, maxlen=None, dtype="int32", padding="pre",
                  truncating="pre", value=0):
    """reference: preprocessing/sequence.py pad_sequences."""
    maxlen = maxlen or max(len(s) for s in sequences)
    out = np.full((len(sequences), maxlen), value, dtype=dtype)
    for i, seq in enumerate(sequences):
        seq = list(seq)
        if len(seq) > maxlen:
            seq = seq[-maxlen:] if truncating == "pre" else seq[:maxlen]
        if padding == "pre":
            out[i, maxlen - len(seq):] = seq
        else:
            out[i, :len(seq)] = seq
    return out


text = SimpleNamespace(Tokenizer=Tokenizer)
sequence = SimpleNamespace(pad_sequences=pad_sequences)
