"""Keras-frontend initializers (reference:
python/flexflow/keras/initializers.py — thin wrappers binding keras names to
the core initializer objects). The reference's RandomNormal mistakenly binds
UniformInitializer (initializers.py:49-54); here it is a real normal."""
from __future__ import annotations

from ..execution.initializers import (ConstantInitializer,
                                      GlorotUniformInitializer,
                                      NormInitializer, UniformInitializer,
                                      ZeroInitializer)


class Initializer:
    """reference: initializers.py Initializer — carries the core handle."""

    def __init__(self):
        self._ffhandle = None

    @property
    def ffhandle(self):
        return self._ffhandle


class DefaultInitializer(Initializer):
    pass


class Zeros(Initializer):
    def __init__(self):
        super().__init__()
        self._ffhandle = ZeroInitializer()


class GlorotUniform(Initializer):
    def __init__(self, seed=0):
        super().__init__()
        self.seed = seed
        self._ffhandle = GlorotUniformInitializer(seed or 0)


class RandomUniform(Initializer):
    def __init__(self, minval=-0.05, maxval=0.05, seed=None):
        super().__init__()
        self._ffhandle = UniformInitializer(seed or 0, minval, maxval)


class RandomNormal(Initializer):
    def __init__(self, mean=0.0, stddev=0.05, seed=None):
        super().__init__()
        self._ffhandle = NormInitializer(seed or 0, mean, stddev)


class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__()
        self._ffhandle = ConstantInitializer(value)


def resolve(init):
    """keras object / core initializer / None -> core initializer or None."""
    if init is None:
        return None
    if isinstance(init, Initializer):
        return init.ffhandle
    return init  # already a core initializer
