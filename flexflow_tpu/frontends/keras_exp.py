"""keras_exp: trace *real* tf.keras models into FFModel (reference:
python/flexflow/keras_exp/models/{model,tensor}.py — walks a built tf.keras
model's layer DAG and replays it as FFModel calls).

The module is import-gated on the tensorflow package the same way the ONNX
frontend gates on onnx. ``KerasExpModel(tf_model)`` converts Dense/Conv2D/
Pool/Flatten/BatchNorm/Activation/Add/Concatenate layers via the same
builder mapping as ``frontends.keras``; exercised against real tf.keras
(Keras 3) functional models in tests/test_keras_exp.py, plus a fake-tf
fixture so the walker stays covered on images without tensorflow.
"""
from __future__ import annotations

from typing import Dict, List

from ..model import FFModel


def _require_tf():
    try:
        import tensorflow as tf  # noqa: F401

        return tf
    except ImportError as e:
        raise ImportError(
            "tensorflow package is required for the keras_exp frontend "
            "(traces real tf.keras models); install tensorflow or use "
            "flexflow_tpu.frontends.keras, the tf-free Keras-style API"
        ) from e


class KerasExpModel:
    """Trace a built tf.keras model into FFModel builder calls."""

    def __init__(self, tf_model):
        self.tf = _require_tf()
        self.tf_model = tf_model

    def apply(self, ffmodel: FFModel, input_tensors: List) -> List:
        tf = self.tf
        keras = tf.keras
        env: Dict[int, object] = {}
        model = self.tf_model
        for t, inp in zip(model.inputs, input_tensors):
            env[id(t)] = inp

        for layer in model.layers:
            if isinstance(layer, keras.layers.InputLayer):
                continue
            node = layer._inbound_nodes[-1]
            in_ts = node.input_tensors if isinstance(
                node.input_tensors, (list, tuple)) else [node.input_tensors]
            args = [env[id(t)] for t in in_ts]
            out = self._convert(ffmodel, layer, args)
            outs = node.output_tensors if isinstance(
                node.output_tensors, (list, tuple)) else [node.output_tensors]
            env[id(outs[0])] = out
        return [env[id(t)] for t in model.outputs]

    def _convert(self, ff: FFModel, layer, args):
        from ..ffconst import ActiMode, PoolType

        keras = self.tf.keras
        acti = {"relu": ActiMode.AC_MODE_RELU,
                "sigmoid": ActiMode.AC_MODE_SIGMOID,
                "tanh": ActiMode.AC_MODE_TANH,
                "gelu": ActiMode.AC_MODE_GELU,
                None: ActiMode.AC_MODE_NONE,
                "linear": ActiMode.AC_MODE_NONE}
        x = args[0]
        if isinstance(layer, keras.layers.Dense):
            name = getattr(layer.activation, "__name__", None)
            if name == "softmax":
                return ff.softmax(ff.dense(x, layer.units,
                                           use_bias=layer.use_bias,
                                           name=layer.name))
            return ff.dense(x, layer.units, acti.get(name,
                                                     ActiMode.AC_MODE_NONE),
                            use_bias=layer.use_bias, name=layer.name)
        if isinstance(layer, keras.layers.Conv2D):
            kh, kw = layer.kernel_size
            sh, sw = layer.strides
            ph = kh // 2 if layer.padding == "same" else 0
            pw = kw // 2 if layer.padding == "same" else 0
            name = getattr(layer.activation, "__name__", None)
            return ff.conv2d(x, layer.filters, kh, kw, sh, sw, ph, pw,
                             acti.get(name, ActiMode.AC_MODE_NONE),
                             use_bias=layer.use_bias, name=layer.name)
        if isinstance(layer, (keras.layers.MaxPooling2D,
                              keras.layers.AveragePooling2D)):
            # keras 'same' pads to ceil(n/stride) windows:
            # total = max(0, (ceil(n/s)-1)*s + pool - n); pool2d takes
            # symmetric padding, so reject layers needing asymmetric pads
            ph = pw = 0
            if layer.padding == "same":
                in_shape = layer.input.shape  # (batch, C, H, W) or NHWC
                spatial = (in_shape[2], in_shape[3]) if len(in_shape) == 4 \
                    else (None, None)
                pads = []
                for n, p, s in zip(spatial, layer.pool_size, layer.strides):
                    if n is None:
                        pads.append(0)
                        continue
                    total = max(0, (-(-int(n) // s) - 1) * s + p - int(n))
                    if total % 2:
                        raise NotImplementedError(
                            "keras_exp: asymmetric 'same' pooling padding")
                    pads.append(total // 2)
                ph, pw = pads
            pt = (PoolType.POOL_MAX
                  if isinstance(layer, keras.layers.MaxPooling2D)
                  else PoolType.POOL_AVG)
            return ff.pool2d(x, *layer.pool_size, *layer.strides, ph, pw,
                             pt, name=layer.name)
        if isinstance(layer, keras.layers.Flatten):
            return ff.flat(x, name=layer.name)
        if isinstance(layer, keras.layers.BatchNormalization):
            return ff.batch_norm(x, relu=False, name=layer.name)
        if isinstance(layer, keras.layers.Add):
            return ff.add(args[0], args[1], name=layer.name)
        if isinstance(layer, keras.layers.Concatenate):
            return ff.concat(list(args), axis=layer.axis, name=layer.name)
        if isinstance(layer, keras.layers.Activation):
            name = getattr(layer.activation, "__name__", None)
            if name == "softmax":
                return ff.softmax(x, name=layer.name)
            fn = {"relu": ff.relu, "sigmoid": ff.sigmoid,
                  "tanh": ff.tanh, "gelu": ff.gelu}.get(name)
            if fn is None:
                raise NotImplementedError(f"activation {name}")
            return fn(x, name=layer.name)
        if isinstance(layer, keras.layers.Dropout):
            return ff.dropout(x, rate=layer.rate, name=layer.name)
        raise NotImplementedError(
            f"keras_exp: layer {type(layer).__name__}")
