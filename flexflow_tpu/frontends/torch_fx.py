"""PyTorch frontend: torch.fx symbolic trace -> FFModel builder calls.

Rebuild of the reference's torch frontend (python/flexflow/torch/model.py,
2607 LoC): ``PyTorchModel`` traces an ``nn.Module`` with torch.fx (the
reference also supports HuggingFace's symbolic trace, :2427) and walks the fx
graph emitting FFModel ops (``torch_to_ff``, :2496). Weights are copied from
the torch module so numerics match — the basis of the reference's strongest
correctness tier, tests/align (SURVEY §4).
"""
from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional

import numpy as np

from ..ffconst import ActiMode, AggrMode, DataType, PoolType
from ..model import FFModel
from ..tensor import Tensor


@contextlib.contextmanager
def _hf_trace_compat():
    """Context manager unblocking decoder-only HF fx tracing (reference
    traces the HF family generally, python/flexflow/torch/model.py:2427;
    upstream transformers >= 4.5x breaks it in two places):

    1. ``masking_utils._vmap_for_bhqkv`` builds attention masks through
       ``torch.vmap``, which cannot map over HFProxy inputs. Swapped for a
       broadcasting equivalent — every stock mask_function is elementwise
       arithmetic / advanced indexing, so reshaping the index vectors to
       (b,1,1,1)/(1,h,1,1)/(1,1,q,1)/(1,1,1,kv) computes the identical
       mask.
    2. ``(*states.shape[:-1], -1, head_dim)`` unpacks a shape proxy, which
       ``Tracer.iter`` rejects. When the proxy's installed metadata is a
       concrete ``torch.Size``, iterate SYMBOLIC ``obj[i]`` getitems (not
       the metadata values — those are the tracer's dummy dims and must
       not be baked into the graph).

    Both patches are restored on exit; eager execution is untouched.

    Known-good families under transformers 4.57: BERT, DistilBERT, T5/mT5,
    GPT-2 (+LMHead), GPT-Neo. Still blocked upstream by OTHER layers:
    OPT (HF fx bytecode wrapping: "co_varnames is too small") and
    LLaMA-style models (@check_model_inputs decorator dereferences kwargs
    that torch.fx passes as None).
    """
    import torch

    try:
        from transformers import masking_utils
        from transformers.utils import fx as hf_fx
    except ImportError:
        yield
        return

    def broadcast_for_bhqkv(mask_function, bh_indices=True):
        def fn(batch_idx, head_idx, q_idx, kv_idx):
            if bh_indices:
                q = q_idx.reshape(1, 1, -1, 1)
                kv = kv_idx.reshape(1, 1, 1, -1)
                if batch_idx is not None:
                    batch_idx = batch_idx.reshape(-1, 1, 1, 1)
                if head_idx is not None:
                    head_idx = head_idx.reshape(1, -1, 1, 1)
            else:
                q = q_idx.reshape(-1, 1)
                kv = kv_idx.reshape(1, -1)
            return mask_function(batch_idx, head_idx, q, kv)
        return fn

    orig_vmap = getattr(masking_utils, "_vmap_for_bhqkv", None)
    orig_iter = hf_fx.HFTracer.iter

    def iter_with_meta(self, obj):
        meta = getattr(obj, "_metadata", None)
        if isinstance(meta, (torch.Size, tuple)):
            return iter([obj[i] for i in range(len(meta))])
        return orig_iter(self, obj)

    if orig_vmap is not None:
        masking_utils._vmap_for_bhqkv = broadcast_for_bhqkv
    hf_fx.HFTracer.iter = iter_with_meta
    try:
        yield
    finally:
        if orig_vmap is not None:
            masking_utils._vmap_for_bhqkv = orig_vmap
        hf_fx.HFTracer.iter = orig_iter


class PyTorchModel:
    """reference: python/flexflow/torch/model.py:2408."""

    def __init__(self, module, is_hf_model: bool = False):
        self.module = module
        self.is_hf_model = is_hf_model

    def torch_to_ff(self, ffmodel: FFModel, input_tensors: List[Tensor],
                    input_names: Optional[List[str]] = None):
        """Trace the module and emit FFModel ops; returns output tensors — a
        list, or a dict for HF models returning ModelOutput dicts (reference:
        torch_to_ff, model.py:2496; hf_symbolic_trace support :2427).

        Shape arithmetic and mask plumbing in the traced graph (size/getitem/
        ones/expand/masked_fill on host values) are evaluated eagerly as
        numpy; only real tensor compute becomes graph ops. Traced buffers
        (position_ids) surface as OP_CONSTANT nodes."""
        import torch
        import torch.fx as fx

        if self.is_hf_model:
            from transformers.utils.fx import symbolic_trace as hf_trace

            with _hf_trace_compat():
                traced = hf_trace(self.module,
                                  input_names=input_names or ["input_ids"])
        else:
            traced = fx.symbolic_trace(self.module)

        env: Dict[str, Any] = {}
        inputs = list(input_tensors)
        outputs: Any = []
        modules = dict(traced.named_modules())

        for node in traced.graph.nodes:
            if node.op == "placeholder":
                env[node.name] = inputs.pop(0)
            elif node.op == "call_module":
                mod = modules[node.target]
                env[node.name] = _convert_module(
                    ffmodel, mod, _args(env, node.args), node.target)
            elif node.op == "call_function" or node.op == "call_method":
                env[node.name] = _convert_function(
                    ffmodel, node, _args(env, node.args),
                    {k: _lookup(env, v) for k, v in node.kwargs.items()})
                if node.op == "call_function" and \
                        getattr(node.target, "__name__", "") == "setitem":
                    # host setitem may have had to copy a read-only view;
                    # later uses reference the SOURCE node, so rebind it
                    src = node.args[0]
                    if isinstance(src, fx.Node):
                        env[src.name] = env[node.name]
            elif node.op == "get_attr":
                attr = _fetch_attr(self.module, node.target)
                if isinstance(attr, torch.Tensor):
                    attr = _np(attr)  # buffers stay eager until consumed
                env[node.name] = attr
            elif node.op == "output":
                out = node.args[0]
                if isinstance(out, dict):
                    outputs = {k: _lookup(env, v) for k, v in out.items()}
                elif isinstance(out, (tuple, list)):
                    outputs = [_lookup(env, o) for o in out]
                else:
                    outputs = [_lookup(env, out)]
        return outputs

    def apply(self, ffmodel: FFModel, input_tensors: List[Tensor]):
        return self.torch_to_ff(ffmodel, input_tensors)

    # ---- .ff model file format (reference: torch/model.py torch_to_string
    # :2597 / file_to_ff :2540 — "name; in,; out,; OPTYPE; params..." lines,
    # IR_DELIMITER "; ", INOUT_NODE_DELIMITER ",") ------------------------
    def torch_to_string(self) -> List[str]:
        """Serialize the traced graph to .ff IR lines (reference:
        PyTorchModel.torch_to_string). Field orders per node type match the
        reference's parse() implementations so files interchange."""
        import torch.fx as fx

        traced = fx.symbolic_trace(self.module)
        modules = dict(traced.named_modules())
        lines = []
        for node in traced.graph.nodes:
            lines.append(_node_to_ir(node, modules))
        return [ln for ln in lines if ln is not None]

    def torch_to_file(self, filename: str) -> None:
        """reference: torch/model.py:2597."""
        with open(filename, "w") as f:
            for line in self.torch_to_string():
                f.write(line + "\n")

    @staticmethod
    def file_to_ff(filename: str, ffmodel: FFModel,
                   input_tensors: List[Tensor]):
        """Rebuild an FFModel graph from a .ff file (reference:
        torch/model.py:2540 — per-line dispatch on the OPTYPE field)."""
        with open(filename) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        node_to_output: Dict[str, Any] = {}
        output_tensors: List[Tensor] = []
        input_index = 0
        for line in lines:
            items = [s.strip() for s in line.split(";")]
            name, in_s, _out_s, op = items[0], items[1], items[2], items[3]
            innodes = [s for s in in_s.split(",") if s.strip()]
            ins = [node_to_output[n] for n in innodes]
            if op == "INPUT":
                node_to_output[name] = input_tensors[input_index]
                input_index += 1
                continue
            if op == "OUTPUT":
                output_tensors.extend(ins)
                continue
            node_to_output[name] = _ir_to_op(ffmodel, op, name, ins, items)
        return output_tensors


_IR_DELIM = "; "


def _io_str(names) -> str:
    return ",".join(names) + "," if names else ""


def _node_to_ir(node, modules) -> Optional[str]:
    """One fx node -> one .ff line (field orders: reference torch/model.py
    LinearNode.parse :253, Conv2dNode :301, Pool2dNode :372, EmbeddingNode,
    DropoutMNode, ConcatNode, module activations)."""
    import operator

    import torch
    import torch.nn as nn
    import torch.nn.functional as F

    ins = [a.name for a in node.args
           if hasattr(a, "name") and not isinstance(a, str)] \
        if node.op != "output" else None
    outs = [u.name for u in node.users]

    def line(op: str, *params) -> str:
        return _IR_DELIM.join([node.name, _io_str(ins), _io_str(outs), op]
                              + [str(p) for p in params])

    if node.op == "placeholder":
        return line("INPUT")
    if node.op == "output":
        args = node.args[0]
        args = args if isinstance(args, (tuple, list)) else (args,)
        ins = [a.name for a in args if hasattr(a, "name")]
        return _IR_DELIM.join([node.name, _io_str(ins), "", "OUTPUT"])
    if node.op == "call_module":
        mod = modules[node.target]
        if isinstance(mod, nn.Linear):
            return line("LINEAR", mod.out_features,
                        ActiMode.AC_MODE_NONE.value,
                        int(mod.bias is not None))
        if isinstance(mod, nn.Conv2d):
            return line("CONV2D", mod.out_channels, mod.kernel_size[0],
                        mod.kernel_size[1], mod.stride[0], mod.stride[1],
                        mod.padding[0], mod.padding[1],
                        ActiMode.AC_MODE_NONE.value, mod.groups,
                        int(mod.bias is not None))
        if isinstance(mod, (nn.MaxPool2d, nn.AvgPool2d)):
            k = mod.kernel_size if isinstance(mod.kernel_size, int) \
                else mod.kernel_size[0]
            st = mod.stride if isinstance(mod.stride, int) else \
                (mod.stride[0] if mod.stride else k)
            p = mod.padding if isinstance(mod.padding, int) \
                else mod.padding[0]
            pt = PoolType.POOL_MAX if isinstance(mod, nn.MaxPool2d) \
                else PoolType.POOL_AVG
            return line("POOL2D", k, st, p, pt.value,
                        ActiMode.AC_MODE_NONE.value)
        if isinstance(mod, nn.BatchNorm2d):
            return line("BATCH_NORM")
        if isinstance(mod, nn.LayerNorm):
            return line("LAYER_NORM")
        if isinstance(mod, nn.Embedding):
            return line("EMBEDDING", mod.num_embeddings, mod.embedding_dim)
        if isinstance(mod, nn.Dropout):
            return line("DROPOUT", mod.p)
        if isinstance(mod, nn.Flatten):
            return line("FLAT")
        simple = {nn.ReLU: "RELU", nn.Sigmoid: "SIGMOID", nn.Tanh: "TANH",
                  nn.GELU: "GELU", nn.Identity: "IDENTITY",
                  nn.Softmax: "SOFTMAX"}
        for cls, opname in simple.items():
            if isinstance(mod, cls):
                return line(opname)
        raise NotImplementedError(
            f".ff export: module {type(mod).__name__}")
    if node.op in ("call_function", "call_method"):
        t = node.target
        if t in (operator.add, torch.add):
            return line("ADD")
        if t in (operator.mul, torch.mul):
            return line("MULTIPLY")
        if t is torch.cat:
            tensors = node.args[0]
            ins = [a.name for a in tensors]
            axis = node.kwargs.get("dim", node.args[1]
                                   if len(node.args) > 1 else 0)
            return line("CONCAT", axis)
        if t is torch.flatten or t == "flatten":
            return line("FLAT")
        if t in (F.relu, torch.relu) or t == "relu":
            return line("RELU")
        if t is F.gelu or t == "gelu":
            return line("GELU")
        if t in (torch.sigmoid, F.sigmoid) or t == "sigmoid":
            return line("SIGMOID")
        if t in (torch.tanh, F.tanh) or t == "tanh":
            return line("TANH")
        if t in (F.softmax, torch.softmax) or t == "softmax":
            return line("SOFTMAX")
        raise NotImplementedError(f".ff export: function {t}")
    raise NotImplementedError(f".ff export: node op {node.op}")


def _ir_to_op(ffmodel: FFModel, op: str, name: str, ins, items):
    """One .ff line -> one FFModel builder call (reference string_to_ff
    field orders: LINEAR items[4:7]=out_dim/acti/bias, CONV2D items[4:14],
    POOL2D items[4:9], EMBEDDING items[4:6], DROPOUT items[4], CONCAT
    items[4])."""
    if op == "LINEAR":
        return ffmodel.dense(ins[0], int(items[4]),
                             activation=ActiMode(int(items[5])),
                             use_bias=bool(int(items[6])), name=name)
    if op == "CONV2D":
        return ffmodel.conv2d(
            ins[0], int(items[4]), int(items[5]), int(items[6]),
            int(items[7]), int(items[8]), int(items[9]), int(items[10]),
            activation=ActiMode(int(items[11])), groups=int(items[12]),
            use_bias=bool(int(items[13])), name=name)
    if op == "POOL2D":
        k, st, p = int(items[4]), int(items[5]), int(items[6])
        return ffmodel.pool2d(ins[0], k, k, st, st, p, p,
                              PoolType(int(items[7])), name=name)
    if op == "EMBEDDING":
        return ffmodel.embedding(ins[0], int(items[4]), int(items[5]),
                                 AggrMode.AGGR_MODE_NONE, name=name)
    if op == "DROPOUT":
        return ffmodel.dropout(ins[0], rate=float(items[4]), name=name)
    if op == "CONCAT":
        return ffmodel.concat(list(ins), axis=int(items[4]), name=name)
    if op == "BATCH_NORM":
        return ffmodel.batch_norm(ins[0], relu=False, name=name)
    if op == "LAYER_NORM":
        # the reference importer degrades this to identity (its layernorm
        # was unsupported, model.py LayerNormNode.string_to_ff); here the
        # real op exists, normalized over the trailing dim
        return ffmodel.layer_norm(ins[0], axes=[-1], name=name)
    if op == "ADD":
        return ffmodel.add(ins[0], ins[1], name=name)
    if op == "MULTIPLY":
        return ffmodel.multiply(ins[0], ins[1], name=name)
    simple = {"RELU": "relu", "SIGMOID": "sigmoid", "TANH": "tanh",
              "GELU": "gelu", "IDENTITY": "identity", "FLAT": "flat",
              "SOFTMAX": "softmax"}
    if op in simple:
        return getattr(ffmodel, simple[op])(ins[0], name=name)
    raise NotImplementedError(f".ff import: op {op}")


# module-level alias matching the reference (model.py:2607)
file_to_ff = PyTorchModel.file_to_ff


def _args(env, args):
    return [_lookup(env, a) for a in args]


def _lookup(env, a):
    import torch.fx as fx

    if isinstance(a, fx.Node):
        return env[a.name]
    if isinstance(a, (tuple, list)):
        return type(a)(_lookup(env, x) for x in a)
    if isinstance(a, slice):  # traced shapes appear inside slice bounds
        return slice(_lookup(env, a.start), _lookup(env, a.stop),
                     _lookup(env, a.step))
    return a


def _fetch_attr(module, target: str):
    obj = module
    for part in target.split("."):
        obj = getattr(obj, part)
    return obj


def _np(t) -> np.ndarray:
    return t.detach().cpu().numpy()


def _set_weight(ffmodel: FFModel, layer_out: Tensor, arrays: Dict[str, np.ndarray]):
    """Stash torch weights for copy after compile()."""
    layer = layer_out.owner_layer
    pending = getattr(ffmodel, "_pending_torch_weights", None)
    if pending is None:
        pending = {}
        ffmodel._pending_torch_weights = pending
    pending[layer.name] = arrays


def copy_torch_weights(ffmodel: FFModel) -> None:
    """Copy traced-module weights into the compiled model's params (call after
    ffmodel.compile)."""
    import jax

    pending = getattr(ffmodel, "_pending_torch_weights", {})
    for lname, arrays in pending.items():
        if lname not in ffmodel.params:
            continue
        for wname, arr in arrays.items():
            cur = ffmodel.params[lname][wname]
            arr = np.asarray(arr, dtype=np.asarray(cur).dtype)
            assert arr.shape == cur.shape, (lname, wname, arr.shape, cur.shape)
            ffmodel.params[lname][wname] = jax.device_put(arr, cur.sharding)


def _host_cmp_table():
    import operator

    import torch

    # NOTE: no operator.eq here — the dedicated eq branch keeps python
    # scalar/tuple == semantics (shape checks must yield a bool, not an
    # elementwise array)
    return {operator.lt: np.less, operator.gt: np.greater,
            operator.le: np.less_equal, operator.ge: np.greater_equal,
            operator.ne: np.not_equal,
            torch.lt: np.less, torch.gt: np.greater,
            torch.le: np.less_equal, torch.ge: np.greater_equal,
            torch.ne: np.not_equal, torch.eq: np.equal}


try:
    _HOST_CMP = _host_cmp_table()
except ImportError:  # torch not installed: frontend import stays lazy
    _HOST_CMP = {}


def _is_ff(v) -> bool:
    return isinstance(v, Tensor)


def _as_ff(ffmodel: FFModel, v, int_ids: bool = False):
    """Promote an eager numpy/scalar value to a graph constant when it meets
    real tensor compute."""
    if _is_ff(v):
        return v
    arr = np.asarray(v)
    if int_ids and arr.dtype != np.int32:
        # int64 ids would be truncated by jax (x64 disabled) with a warning
        arr = arr.astype(np.int32)
    return ffmodel.constant(arr)


def _torch_dtype_of(v):
    """torch dtype of a traced value — lets torch.finfo/torch.tensor(...,
    dtype=x.dtype) evaluate eagerly."""
    import torch

    if _is_ff(v):
        from ..ffconst import dtype_to_jnp

        return getattr(torch, np.dtype(str(dtype_to_jnp(v.dtype))).name,
                       torch.float32)
    return getattr(torch, str(np.asarray(v).dtype), torch.float32)


def _convert_module(ffmodel: FFModel, mod, args, name: str):
    import torch.nn as nn

    name = name.replace(".", "_")
    x = args[0]
    if isinstance(mod, nn.Embedding) and not _is_ff(x):
        x = _as_ff(ffmodel, x, int_ids=True)  # traced buffer ids
    if not _is_ff(x):
        x = _as_ff(ffmodel, x)
    if isinstance(mod, nn.Linear):
        out = ffmodel.dense(x, mod.out_features, use_bias=mod.bias is not None,
                            name=name)
        w = {"kernel": _np(mod.weight).T}
        if mod.bias is not None:
            w["bias"] = _np(mod.bias)
        _set_weight(ffmodel, out, w)
        return out
    if isinstance(mod, nn.Conv2d):
        out = ffmodel.conv2d(
            x, mod.out_channels, mod.kernel_size[0], mod.kernel_size[1],
            mod.stride[0], mod.stride[1], mod.padding[0], mod.padding[1],
            groups=mod.groups, use_bias=mod.bias is not None, name=name)
        # torch OIHW -> HWIO
        w = {"kernel": _np(mod.weight).transpose(2, 3, 1, 0)}
        if mod.bias is not None:
            w["bias"] = _np(mod.bias)
        _set_weight(ffmodel, out, w)
        return out
    if isinstance(mod, nn.BatchNorm2d):
        out = ffmodel.batch_norm(x, relu=False, name=name)
        _set_weight(ffmodel, out, {"scale": _np(mod.weight),
                                   "bias": _np(mod.bias)})
        return out
    if isinstance(mod, nn.LayerNorm):
        axes = list(range(-len(mod.normalized_shape), 0))
        out = ffmodel.layer_norm(x, axes=axes, eps=mod.eps, name=name)
        if mod.elementwise_affine:
            _set_weight(ffmodel, out, {"scale": _np(mod.weight),
                                       "bias": _np(mod.bias)})
        return out
    if isinstance(mod, nn.Embedding):
        out = ffmodel.embedding(x, mod.num_embeddings, mod.embedding_dim,
                                AggrMode.AGGR_MODE_NONE, name=name)
        _set_weight(ffmodel, out, {"weight": _np(mod.weight)})
        return out
    if isinstance(mod, nn.ReLU):
        return ffmodel.relu(x, name=name)
    if isinstance(mod, nn.GELU):
        return ffmodel.gelu(x, name=name)
    if isinstance(mod, nn.Sigmoid):
        return ffmodel.sigmoid(x, name=name)
    if isinstance(mod, nn.Tanh):
        return ffmodel.tanh(x, name=name)
    if isinstance(mod, nn.Softmax):
        return ffmodel.softmax(x, axis=mod.dim if mod.dim is not None else -1,
                               name=name)
    if isinstance(mod, nn.Dropout):
        return ffmodel.dropout(x, rate=mod.p, name=name)
    if isinstance(mod, nn.MaxPool2d):
        k = mod.kernel_size if isinstance(mod.kernel_size, tuple) else \
            (mod.kernel_size, mod.kernel_size)
        st = mod.stride if isinstance(mod.stride, tuple) else \
            (mod.stride or k[0], mod.stride or k[1])
        p = mod.padding if isinstance(mod.padding, tuple) else \
            (mod.padding, mod.padding)
        return ffmodel.pool2d(x, k[0], k[1], st[0], st[1], p[0], p[1],
                              PoolType.POOL_MAX, name=name)
    if isinstance(mod, nn.AvgPool2d):
        k = mod.kernel_size if isinstance(mod.kernel_size, tuple) else \
            (mod.kernel_size, mod.kernel_size)
        st = mod.stride if isinstance(mod.stride, tuple) else \
            (mod.stride or k[0], mod.stride or k[1])
        p = mod.padding if isinstance(mod.padding, tuple) else \
            (mod.padding, mod.padding)
        return ffmodel.pool2d(x, k[0], k[1], st[0], st[1], p[0], p[1],
                              PoolType.POOL_AVG, name=name)
    if isinstance(mod, nn.AdaptiveAvgPool2d):
        # static shapes under XLA: lower to a plain AvgPool whose kernel is
        # derived from the incoming spatial dims (torchvision resnet's
        # AdaptiveAvgPool2d((1, 1)) head)
        oh, ow = mod.output_size if isinstance(mod.output_size, tuple) else \
            (mod.output_size, mod.output_size)
        _b, _c, ih, iw = x.dims
        assert ih % oh == 0 and iw % ow == 0, \
            f"AdaptiveAvgPool2d: {ih}x{iw} not divisible by {oh}x{ow}"
        kh, kw = ih // oh, iw // ow
        return ffmodel.pool2d(x, kh, kw, kh, kw, 0, 0,
                              PoolType.POOL_AVG, name=name)
    if isinstance(mod, nn.Flatten):
        return ffmodel.flat(x, name=name)
    if isinstance(mod, nn.Identity):
        return ffmodel.identity(x, name=name)
    raise NotImplementedError(f"torch module {type(mod).__name__}")


def _convert_function(ffmodel: FFModel, node, args, kwargs):
    import operator

    import torch
    import torch.nn.functional as F

    t = node.target
    if node.op == "call_method":
        x = args[0]
        # ---- shape/meta queries: always eager (static shapes) -------------
        if t == "size":
            dims = tuple(x.dims) if _is_ff(x) else np.asarray(x).shape
            return dims[args[1]] if len(args) > 1 else dims
        if t == "dim":
            return len(x.dims) if _is_ff(x) else np.asarray(x).ndim
        # ---- eager numpy receivers (mask plumbing, traced buffers) --------
        if not _is_ff(x):
            x = np.asarray(x)
            if t == "expand":
                sizes = list(args[1:])
                off = len(sizes) - x.ndim  # torch aligns sizes to trailing dims
                shape = [x.shape[i - off] if a == -1 else int(a)
                         for i, a in enumerate(sizes)]
                return np.broadcast_to(x, shape)
            if t == "to":
                target = args[1] if len(args) > 1 else kwargs.get("dtype")
                try:
                    return x.astype(_np_dtype(target))
                except (TypeError, ValueError):
                    return x  # .to(device) and friends
            if t == "masked_fill":
                mask = np.asarray(args[1])
                return np.where(mask, args[2], x)
            if t in ("view", "reshape"):
                return x.reshape([int(a) for a in args[1:]])
            if t == "transpose":
                perm = list(range(x.ndim))
                i, j = args[1], args[2]
                perm[i], perm[j] = perm[j], perm[i]
                return np.transpose(x, perm)
            if t == "float":
                return x.astype(np.float32)
            if t == "long":
                return x.astype(np.int64)
            if t == "type_as":
                other = args[1]
                if _is_ff(other):
                    from ..ffconst import dtype_to_jnp

                    # dtype_to_jnp returns a usable dtype object (incl.
                    # ml_dtypes bfloat16, which np.dtype(str) can't resolve)
                    return x.astype(dtype_to_jnp(other.dtype))
                return x.astype(np.asarray(other).dtype)
            if t == "abs":
                return np.abs(x)
            if t == "bool":
                return x.astype(bool)
            if t == "int":
                return x.astype(np.int32)
            if t == "repeat":
                reps = args[1:] if len(args) > 2 or not isinstance(
                    args[1], (tuple, list)) else args[1]
                return np.tile(x, [int(r) for r in reps])
            if t == "unsqueeze":
                return np.expand_dims(x, int(args[1]))
            if t == "squeeze":
                if len(args) > 1:
                    dim = int(args[1])
                    # torch semantics: squeeze of a non-1 dim is a no-op
                    return np.squeeze(x, dim) if x.shape[dim] == 1 else x
                return np.squeeze(x)
            if t in ("contiguous", "clone", "detach"):
                return x
            if t == "cumsum":
                dim = kwargs.get("dim", args[1] if len(args) > 1 else -1)
                return np.cumsum(x, axis=int(dim))
            if t == "ne":
                return x != np.asarray(args[1])
            if t == "eq":
                return x == np.asarray(args[1])
            if t == "flatten":
                start = int(kwargs.get("start_dim",
                                       args[1] if len(args) > 1 else 0))
                end = int(kwargs.get("end_dim",
                                     args[2] if len(args) > 2 else -1))
                end = end % x.ndim
                sh = list(x.shape)
                new = sh[:start] + \
                    [int(np.prod(sh[start:end + 1]))] + sh[end + 1:]
                return x.reshape(new)
            if t in ("new_ones", "new_zeros", "new_full"):
                shape = args[1] if isinstance(args[1], (tuple, list)) \
                    else args[1:] if t != "new_full" else args[1]
                shape = [int(s) for s in shape]
                dt = kwargs.get("dtype")
                np_dt = _np_dtype(dt) if dt is not None else x.dtype
                if t == "new_full":
                    return np.full(shape, args[2], dtype=np_dt)
                fill = np.ones if t == "new_ones" else np.zeros
                return fill(shape, dtype=np_dt)
            raise NotImplementedError(f"torch method {t} on host value")
        # ---- graph ops on Tensors -----------------------------------------
        if t == "view" or t == "reshape":
            shape = [a for a in args[1:]]
            if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
                shape = list(shape[0])
            return ffmodel.reshape(x, [int(s) if isinstance(
                s, (int, np.integer)) else -1 for s in shape])
        if t == "permute":
            perm = list(args[1:])
            if len(perm) == 1 and isinstance(perm[0], (list, tuple)):
                perm = [int(p) for p in perm[0]]
            return ffmodel.transpose(x, perm)
        if t == "transpose":
            perm = list(range(len(x.dims)))
            i, j = args[1], args[2]
            perm[i], perm[j] = perm[j], perm[i]
            return ffmodel.transpose(x, perm)
        if t == "flatten":
            return ffmodel.flat(x)
        if t == "mean":
            dims = kwargs.get("dim", args[1] if len(args) > 1 else None)
            if dims is None:
                raise NotImplementedError("full-reduce mean")
            return ffmodel.mean(x, dims=_reduce_dims(dims),
                                keepdims=kwargs.get("keepdim", False))
        if t == "sum":
            dims = kwargs.get("dim", args[1] if len(args) > 1 else None)
            if dims is None:
                raise NotImplementedError("full-reduce sum")
            return ffmodel.reduce_sum(x, axes=_reduce_dims(dims),
                                      keepdims=kwargs.get("keepdim", False))
        if t == "pow":
            return ffmodel.pow(x, args[1])
        if t == "rsqrt":
            return ffmodel.rsqrt(x)
        if t == "exp":
            return ffmodel.exp(x)
        if t in ("split", "chunk"):
            return _convert_split(ffmodel, x, args[1:], kwargs,
                                  is_chunk=(t == "chunk"))
        if t == "unsqueeze":
            return _convert_unsqueeze(ffmodel, x, args[1:], kwargs)
        if t == "squeeze":
            return _convert_squeeze(ffmodel, x, args[1:], kwargs)
        if t == "to":
            target = args[1] if len(args) > 1 else kwargs.get("dtype")
            from ..ffconst import jnp_to_dtype

            try:
                return ffmodel.cast(x, jnp_to_dtype(_np_dtype(target)))
            except (TypeError, ValueError):
                return x
        if t == "float":
            from ..ffconst import DataType

            return ffmodel.cast(x, DataType.DT_FLOAT)
        if t == "type_as":
            other = args[1]
            if _is_ff(other):
                return ffmodel.cast(x, other.dtype)
            from ..ffconst import jnp_to_dtype

            return ffmodel.cast(x, jnp_to_dtype(np.asarray(other).dtype))
        if t == "contiguous" or t == "clone" or t == "detach":
            return x
        raise NotImplementedError(f"torch method {t}")

    # ---- eager host-side builtins (shape arithmetic / mask construction) ---
    if t is getattr:
        obj, attr = args[0], args[1]
        if attr == "shape":
            return tuple(obj.dims) if _is_ff(obj) else np.asarray(obj).shape
        if attr == "dtype":
            return _torch_dtype_of(obj)
        if attr == "device":
            return torch.device("cpu")
        return getattr(obj, attr)  # finfo.min etc. — eager objects
    if t is operator.getitem:
        obj = args[0]
        if _is_ff(obj):
            items = args[1] if isinstance(args[1], tuple) else (args[1],)
            return ffmodel.slice_tensor(obj, items)
        return obj[args[1]]
    if t is operator.setitem:
        obj, key, val = args[0], args[1], args[2]
        keys = key if isinstance(key, tuple) else (key,)
        if not _is_ff(obj) and not _is_ff(val) and \
                not any(_is_ff(k) for k in keys):
            obj = np.asarray(obj)
            if obj.flags.writeable:
                obj[key] = val  # in place: views created earlier stay live
                return obj
            # read-only (broadcast) source: copy + rebind in the trace loop.
            # Views taken BEFORE this write won't observe it — warn.
            import warnings

            warnings.warn("fx setitem on a read-only host view: copying; "
                          "earlier-created aliases will not see this write")
            obj = np.array(obj)
            obj[key] = val
            return obj
        raise NotImplementedError("setitem involving graph tensors")
    if t is torch.ones:
        shape = args[0] if isinstance(args[0], (tuple, list)) else args
        return np.ones([int(s) for s in shape], dtype=np.float32)
    if t is torch.zeros:
        shape = args[0] if isinstance(args[0], (tuple, list)) else args
        return np.zeros([int(s) for s in shape], dtype=np.float32)
    if t is torch.tensor:
        return np.asarray(args[0],
                          dtype=_np_dtype(kwargs.get("dtype")) if
                          kwargs.get("dtype") is not None else None)
    if t is torch.finfo:
        return torch.finfo(args[0])
    if t is operator.eq:
        if not _is_ff(args[0]) and not _is_ff(args[1]):
            return args[0] == args[1]
    # ---- eager host arithmetic for static index computations (T5-style
    # relative-position buckets: arange/abs/comparisons/log/min/where all
    # run on host numpy at trace time; only the bias embedding lookup
    # enters the graph, via the nn.Embedding constant-promotion path) ------
    if t is torch.arange:
        vals = list(args if len(args) > 1 or not isinstance(
            args[0], (tuple, list)) else args[0])
        if all(float(a) == int(a) for a in vals):
            vals = [int(a) for a in vals]
            default_dt = np.int64
        else:  # float arange (frequency tables etc.) keeps real values
            default_dt = np.float32
        return np.arange(*vals, dtype=_np_dtype(kwargs.get("dtype"))
                         if kwargs.get("dtype") is not None else default_dt)
    if t is torch.abs and not _is_ff(args[0]):
        return np.abs(np.asarray(args[0]))
    if t in _HOST_CMP and not _is_ff(args[0]) and not _is_ff(args[1]):
        a, b = args[0], args[1]
        if isinstance(a, (tuple, list)) or isinstance(b, (tuple, list)):
            # shape comparisons must yield a python bool, not elementwise
            import operator as _op

            py = {np.less: _op.lt, np.greater: _op.gt,
                  np.less_equal: _op.le, np.greater_equal: _op.ge,
                  np.not_equal: _op.ne, np.equal: _op.eq}
            return py[_HOST_CMP[t]](a, b)
        return _HOST_CMP[t](np.asarray(a), np.asarray(b))
    if t is torch.log:
        if _is_ff(args[0]):
            return ffmodel.log(args[0])
        return np.log(np.asarray(args[0]))
    # elementwise two-array form only: torch.min(x, dim:int) is a reduction
    # returning (values, indices) — not supported here
    if t is torch.min and len(args) == 2 and not _is_ff(args[0]) \
            and not _is_ff(args[1]) and np.ndim(args[1]) > 0:
        return np.minimum(np.asarray(args[0]), np.asarray(args[1]))
    if t is torch.max and len(args) == 2 and not _is_ff(args[0]) \
            and not _is_ff(args[1]) and np.ndim(args[1]) > 0:
        return np.maximum(np.asarray(args[0]), np.asarray(args[1]))
    if t is torch.full_like and not _is_ff(args[0]):
        dt = kwargs.get("dtype")
        return np.full_like(np.asarray(args[0]), args[1],
                            dtype=_np_dtype(dt) if dt is not None else None)
    if t is torch.full:
        shape = [int(s) for s in args[0]]
        fill = args[1] if len(args) > 1 else kwargs["fill_value"]
        dt = kwargs.get("dtype")
        if dt is not None:
            np_dt = _np_dtype(dt)
        else:  # torch defaults float fills to f32 (not numpy's f64)
            np_dt = np.float32 if isinstance(fill, float) else None
        return np.full(shape, fill, dtype=np_dt)
    if t is torch.zeros_like and not _is_ff(args[0]):
        return np.zeros_like(np.asarray(args[0]))
    if t is getattr(torch, "diff", None) and not _is_ff(args[0]):
        # packed-sequence detection in masking_utils runs on host indices
        extra = {}
        for kw in ("prepend", "append"):
            if kwargs.get(kw) is not None:
                extra[kw] = np.asarray(kwargs[kw])
        n = int(kwargs.get("n", args[1] if len(args) > 1 else 1))
        return np.diff(np.asarray(args[0]), n=n,
                       axis=kwargs.get("dim", args[2] if len(args) > 2
                                       else -1), **extra)
    if t is torch.ones_like and not _is_ff(args[0]):
        return np.ones_like(np.asarray(args[0]))
    if t in (operator.and_, operator.or_) and not _is_ff(args[0]) \
            and not _is_ff(args[1]):
        # boolean mask combination (masking_utils.and_masks/or_masks)
        op_np = np.logical_and if t is operator.and_ else np.logical_or
        return op_np(np.asarray(args[0]), np.asarray(args[1]))
    if t in (operator.invert, torch.logical_not) and not _is_ff(args[0]):
        return np.logical_not(np.asarray(args[0]))
    if t in (torch.all, torch.any) and not _is_ff(args[0]):
        red = np.all if t is torch.all else np.any
        dim = kwargs.get("dim", args[1] if len(args) > 1 else None)
        return red(np.asarray(args[0])) if dim is None else \
            red(np.asarray(args[0]), axis=int(dim))
    if t is torch.where and not any(_is_ff(a) for a in args[:3]):
        return np.where(np.asarray(args[0]), np.asarray(args[1]),
                        np.asarray(args[2]))
    if t is torch.where and not _is_ff(args[0]) and _is_ff(args[1]):
        # graph select with a host condition (gpt-neo causal masking:
        # where(mask, scores, finfo.min)) — lower to mask arithmetic
        m = np.asarray(args[0]).astype(np.float32)
        left = ffmodel.multiply(args[1], _as_ff(ffmodel, m))
        if _is_ff(args[2]):
            return ffmodel.add(left, ffmodel.multiply(
                args[2], _as_ff(ffmodel, 1.0 - m)))
        other = np.asarray(args[2], dtype=np.float32) * (1.0 - m)
        return ffmodel.add(left, _as_ff(ffmodel, other))
    if t is torch.triu and not _is_ff(args[0]):
        return np.triu(np.asarray(args[0]), k=kwargs.get(
            "diagonal", args[1] if len(args) > 1 else 0))
    if t is torch.tril and not _is_ff(args[0]):
        return np.tril(np.asarray(args[0]), k=kwargs.get(
            "diagonal", args[1] if len(args) > 1 else 0))
    if t is torch.nn.functional.scaled_dot_product_attention or \
            (getattr(t, "__name__", "") == "scaled_dot_product_attention"):
        # torch signature: (query, key, value, attn_mask=None, dropout_p=0.0,
        # is_causal=False, *, scale=None) — args may arrive positionally
        q, k, v = args[0], args[1], args[2]
        mask = kwargs.get("attn_mask", args[3] if len(args) > 3 else None)
        dropout_p = kwargs.get("dropout_p",
                               args[4] if len(args) > 4 else 0.0)
        is_causal = kwargs.get("is_causal",
                               args[5] if len(args) > 5 else False)
        if mask is not None and not _is_ff(mask):
            mask = np.asarray(mask)
            if mask.dtype == bool:
                # torch bool semantics: True = attend, False = -inf
                mask = None if mask.all() else _as_ff(ffmodel, mask)
            else:
                mask = mask.astype(np.float32)
                # all-zero additive mask: no-op
                mask = None if not mask.any() else _as_ff(ffmodel, mask)
        return ffmodel.sdpa(q, k, v, attn_mask=mask, dropout=dropout_p,
                            causal=is_causal, scale=kwargs.get("scale"))

    if t is torch.addmm and _is_ff(args[1]) and not _is_ff(args[0]) \
            and not _is_ff(args[2]):
        # HF Conv1D (gpt2): addmm(bias, x_2d, weight) with weight (in, out)
        # — a dense layer whose kernel is already in our layout
        w = np.asarray(args[2])
        out = ffmodel.dense(args[1], w.shape[1], use_bias=True,
                            name=node.name)
        _set_weight(ffmodel, out, {"kernel": w,
                                   "bias": np.asarray(args[0])})
        return out
    if t in (operator.add, torch.add):
        if isinstance(args[0], (tuple, list)) and \
                isinstance(args[1], (tuple, list)):
            # shape arithmetic: size()[:-1] + (nf,) concatenates
            return tuple(args[0]) + tuple(args[1])
        return _binary(ffmodel, "add", args)
    if t in (operator.sub, torch.sub):
        return _binary(ffmodel, "subtract", args)
    if t in (operator.mul, torch.mul):
        return _binary(ffmodel, "multiply", args)
    if t in (operator.truediv, torch.div):
        return _binary(ffmodel, "divide", args)
    if getattr(t, "__name__", "") == "gelu":  # torch._C._nn.gelu builtin
        return ffmodel.gelu(args[0])
    if t in (torch.matmul, torch.bmm):
        return ffmodel.batch_matmul(args[0], args[1])
    if t is F.relu or t is torch.relu:
        return ffmodel.relu(args[0])
    if t is F.gelu:
        return ffmodel.gelu(args[0])
    if t is torch.sigmoid or t is F.sigmoid:
        return ffmodel.sigmoid(args[0])
    if t is torch.tanh or t is F.tanh:
        return ffmodel.tanh(args[0])
    if t is F.softmax or t is torch.softmax:
        axis = kwargs.get("dim", args[1] if len(args) > 1 else -1)
        return ffmodel.softmax(args[0], axis=axis)
    if t is torch.cat:
        tensors = args[0]
        axis = kwargs.get("dim", args[1] if len(args) > 1 else 0)
        return ffmodel.concat(list(tensors), axis=axis)
    if t is torch.flatten:
        return ffmodel.flat(args[0])
    if t is torch.mean:
        dims = kwargs.get("dim", args[1] if len(args) > 1 else None)
        if dims is None:
            raise NotImplementedError("full-reduce mean")
        return ffmodel.mean(args[0], dims=_reduce_dims(dims),
                            keepdims=kwargs.get("keepdim", False))
    if t is F.dropout:
        return ffmodel.dropout(args[0], rate=kwargs.get("p", 0.5))
    if t is getattr(torch, "pow", None) or t is operator.pow:
        return ffmodel.pow(args[0], args[1])
    if t is torch.rsqrt:
        return ffmodel.rsqrt(args[0])
    if t is torch.exp:
        return ffmodel.exp(args[0])
    if t is torch.sin:
        return ffmodel.sin(args[0])
    if t is torch.cos:
        return ffmodel.cos(args[0])
    if t is operator.neg:
        if not _is_ff(args[0]):
            return -args[0]
        return ffmodel.scalar_multiply(args[0], -1.0)
    if t is torch.sum:
        dims = kwargs.get("dim", args[1] if len(args) > 1 else None)
        if dims is None:
            raise NotImplementedError("full-reduce sum")
        return ffmodel.reduce_sum(args[0], axes=_reduce_dims(dims),
                                  keepdims=kwargs.get("keepdim", False))
    if t in (torch.split, torch.chunk):
        return _convert_split(ffmodel, args[0], args[1:], kwargs,
                              is_chunk=(t is torch.chunk))
    if t is torch.unsqueeze:
        return _convert_unsqueeze(ffmodel, args[0], args[1:], kwargs)
    if t is torch.squeeze:
        return _convert_squeeze(ffmodel, args[0], args[1:], kwargs)
    raise NotImplementedError(f"torch function {t}")


# ---- shared torch-semantics helpers (reference: SplitChunkNode — one node
# class serves both x.split/x.chunk and the torch.* functions) ---------------
def _reduce_dims(dims) -> list:
    return [dims] if isinstance(dims, int) else list(dims)


def _convert_split(ffmodel: FFModel, x, rest, kwargs, is_chunk: bool):
    dim = kwargs.get("dim", rest[1] if len(rest) > 1 else 0)
    total = x.dims[dim]
    if is_chunk:
        n = rest[0]
        per = -(-total // n)  # torch.chunk: ceil division
        sizes = []
        left = total
        while left > 0:
            sizes.append(min(per, left))
            left -= per
    else:
        sizes = rest[0]
        if isinstance(sizes, int):
            # torch.split: last chunk carries the remainder
            per = sizes
            sizes = [per] * (total // per)
            if total % per:
                sizes.append(total % per)
    return tuple(ffmodel.split(x, list(sizes), axis=dim))


def _convert_unsqueeze(ffmodel: FFModel, x, rest, kwargs):
    dim = kwargs.get("dim", rest[0] if rest else None)
    assert dim is not None, "unsqueeze requires a dim"
    shape = list(x.dims)
    a = dim if dim >= 0 else len(shape) + dim + 1
    shape.insert(a, 1)
    return ffmodel.reshape(x, shape)


def _convert_squeeze(ffmodel: FFModel, x, rest, kwargs):
    dim = kwargs.get("dim", rest[0] if rest else None)
    shape = list(x.dims)
    if dim is not None:
        a = dim % len(shape)
        if shape[a] == 1:
            shape.pop(a)
    else:
        shape = [s for s in shape if s != 1] or [1]
    return ffmodel.reshape(x, shape)


def _np_dtype(torch_dtype):
    """torch dtype object -> numpy dtype (eager mask/buffer arithmetic)."""
    import torch

    if torch_dtype is None:
        return np.float32
    if isinstance(torch_dtype, np.dtype) or isinstance(torch_dtype, type):
        return np.dtype(torch_dtype)
    if torch_dtype is torch.bool:
        return np.dtype(bool)
    return np.dtype(str(torch_dtype).replace("torch.", ""))


def _binary(ffmodel: FFModel, opname: str, args):
    a, b = args[0], args[1]
    if not _is_ff(a) and not _is_ff(b):
        # both host values (shape arithmetic / mask construction): eager
        fn = {"add": np.add, "subtract": np.subtract,
              "multiply": np.multiply, "divide": np.true_divide}[opname]
        r = fn(a, b)
        if np.ndim(r) == 0 and not isinstance(a, np.ndarray) \
                and not isinstance(b, np.ndarray):
            return r.item()
        return r
    if _is_ff(a) and isinstance(b, (int, float)):
        scalar_map = {"add": "scalar_add", "subtract": "scalar_sub",
                      "multiply": "scalar_multiply",
                      "divide": "scalar_true_divide"}
        return getattr(ffmodel, scalar_map[opname])(a, float(b))
    if _is_ff(b) and isinstance(a, (int, float)) and opname in ("add",
                                                                "multiply"):
        scalar_map = {"add": "scalar_add", "multiply": "scalar_multiply"}
        return getattr(ffmodel, scalar_map[opname])(b, float(a))
    return getattr(ffmodel, opname)(_as_ff(ffmodel, a), _as_ff(ffmodel, b))
