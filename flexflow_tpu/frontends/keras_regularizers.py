"""Keras-frontend weight regularizers (reference:
python/flexflow/keras/regularizers.py — L1/L2 carrying (RegularizerMode,
lambda)). Unlike the reference, the penalty is actually applied here: layers
record a ("l1"|"l2", lambda) spec on their kernel attrs and the op's forward
adds lambda * ||W||_1 or lambda * sum(W^2) to the training loss through the
aux-loss hook (the same channel as the MoE load-balance term)."""
from __future__ import annotations

from ..ffconst import RegularizerMode


class Regularizer:
    def __init__(self):
        self.type = RegularizerMode.REG_MODE_NONE
        self._lambda = 0.0

    def spec(self):
        if self.type == RegularizerMode.REG_MODE_L1:
            return ("l1", self._lambda)
        if self.type == RegularizerMode.REG_MODE_L2:
            return ("l2", self._lambda)
        return None


class L1(Regularizer):
    def __init__(self, l1: float):
        super().__init__()
        self.type = RegularizerMode.REG_MODE_L1
        self._lambda = float(l1)


class L2(Regularizer):
    def __init__(self, l2: float):
        super().__init__()
        self.type = RegularizerMode.REG_MODE_L2
        self._lambda = float(l2)


def resolve(reg):
    """keras Regularizer / "l1"/"l2" string / spec tuple / None ->
    ("l1"|"l2", lambda) or None."""
    if reg is None:
        return None
    if isinstance(reg, Regularizer):
        return reg.spec()
    if isinstance(reg, str):  # keras string shorthand, default rate 0.01
        if reg not in ("l1", "l2"):
            raise ValueError(f"unknown regularizer {reg!r}")
        return (reg, 0.01)
    kind, lam = reg
    if kind not in ("l1", "l2"):
        raise ValueError(f"unknown regularizer kind {kind!r}")
    return (kind, float(lam))
