"""Keras-frontend callbacks (reference: python/flexflow/keras/callbacks.py).

Same surface: ``Callback`` base with the six hooks, ``LearningRateScheduler``
(epoch-indexed schedule driving optimizer.set_learning_rate),
``VerifyMetrics`` (asserts final accuracy) and ``EpochVerifyMetrics``
(per-epoch accuracy check with early stop). Callbacks are invoked by the
keras models' ``fit`` (models drive FFModel.fit one epoch at a time so the
epoch hooks fire exactly like the reference's base_model.py loop).
"""
from __future__ import annotations

import numpy as np


class Callback:
    """reference: callbacks.py Callback."""

    def __init__(self):
        self.validation_data = None
        self.model = None
        self.params = None

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_begin(self, batch, logs=None):
        pass

    def on_batch_end(self, batch, logs=None):
        pass

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass


class LearningRateScheduler(Callback):
    """reference: callbacks.py LearningRateScheduler — calls
    ``optimizer.set_learning_rate(schedule(epoch))`` each epoch begin."""

    def __init__(self, schedule):
        super().__init__()
        self.schedule = schedule

    def on_epoch_begin(self, epoch, logs=None):
        optimizer = self.model.ffmodel.optimizer
        if not hasattr(optimizer, "lr") and not hasattr(optimizer, "alpha"):
            raise ValueError('Optimizer must have a "lr" attribute.')
        lr = self.schedule(epoch)
        if not isinstance(lr, (float, np.float32, np.float64)):
            raise ValueError('The output of the "schedule" function '
                             'should be float.')
        optimizer.set_learning_rate(lr)
        print("set learning rate ", lr)


class VerifyMetrics(Callback):
    """reference: callbacks.py VerifyMetrics — asserts accuracy at train
    end. Accepts a float or an enum-like object with ``.value``."""

    def __init__(self, accuracy):
        super().__init__()
        self.accuracy = getattr(accuracy, "value", accuracy)

    def on_train_end(self, logs=None):
        perf = self.model.ffmodel.get_perf_metrics()
        accuracy = perf.get_accuracy()
        if accuracy < self.accuracy:
            assert 0, "Accuracy is wrong"


class TelemetryCallback(Callback):
    """Streams epoch/train progress into the obs tracer and (optionally)
    writes a merged telemetry summary at train end — the Keras-surface entry
    point to the tracing/telemetry subsystem (no reference analog; the
    reference's callbacks only print).

    The keras fit loop drives ``ffmodel.fit(epochs=1)`` once per epoch, so
    each epoch yields its own StepTelemetry; this callback collects every
    epoch's summary and writes one ``{"epochs": [...]}`` record (only the
    first epoch's first step carries the jit compile)."""

    def __init__(self, telemetry_file=None):
        super().__init__()
        self.telemetry_file = telemetry_file
        self.epoch_summaries = []

    def _tracer(self):
        from ..obs import get_tracer

        return get_tracer()

    def on_train_begin(self, logs=None):
        # the callback's telemetry_file IS an observability opt-in: flag the
        # model so fit() records a StepTelemetry even with no config sinks
        if self.telemetry_file and self.model is not None:
            self.model.ffmodel._telemetry_requested = True
        self.epoch_summaries = []
        self._tracer().event("keras_train_begin")

    def on_epoch_end(self, epoch, logs=None):
        tel = self.model.ffmodel.get_telemetry()
        if tel is not None:
            tel.finalize()
            self.epoch_summaries.append(dict(tel.summary(), epoch=epoch))
        if self.telemetry_file:
            # the keras fit loop drives one ffmodel.fit per epoch and each
            # fit CONSUMES the request flag — re-arm for the next epoch
            self.model.ffmodel._telemetry_requested = True
        tracer = self._tracer()
        if not tracer.enabled:
            return
        perf = self.model.ffmodel.get_perf_metrics()
        tracer.event("keras_epoch_end", epoch=epoch,
                     accuracy=round(perf.accuracy(), 4),
                     train_all=perf.train_all)

    def on_train_end(self, logs=None):
        self._tracer().event("keras_train_end")
        if self.model is not None:
            # scoped opt-in: a later fit() without this callback must not
            # stay instrumented (telemetry costs a per-step device sync)
            self.model.ffmodel._telemetry_requested = False
        if self.telemetry_file and self.epoch_summaries:
            from ..obs import atomic_write_json

            atomic_write_json(self.telemetry_file,
                              {"phase": "train",
                               "epochs": self.epoch_summaries})


class EpochVerifyMetrics(Callback):
    """reference: callbacks.py EpochVerifyMetrics — early-stops once the
    per-epoch accuracy passes the bar."""

    def __init__(self, accuracy, early_stop=True):
        super().__init__()
        self.accuracy = getattr(accuracy, "value", accuracy)
        self.early_stop = early_stop

    def on_epoch_end(self, epoch=None, logs=None):
        perf = self.model.ffmodel.get_perf_metrics()
        accuracy = perf.get_accuracy()
        if not self.early_stop:
            return False
        return accuracy > self.accuracy
