"""Keras-frontend callbacks (reference: python/flexflow/keras/callbacks.py).

Same surface: ``Callback`` base with the six hooks, ``LearningRateScheduler``
(epoch-indexed schedule driving optimizer.set_learning_rate),
``VerifyMetrics`` (asserts final accuracy) and ``EpochVerifyMetrics``
(per-epoch accuracy check with early stop). Callbacks are invoked by the
keras models' ``fit`` (models drive FFModel.fit one epoch at a time so the
epoch hooks fire exactly like the reference's base_model.py loop).
"""
from __future__ import annotations

import numpy as np


class Callback:
    """reference: callbacks.py Callback."""

    def __init__(self):
        self.validation_data = None
        self.model = None
        self.params = None

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_begin(self, batch, logs=None):
        pass

    def on_batch_end(self, batch, logs=None):
        pass

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass


class LearningRateScheduler(Callback):
    """reference: callbacks.py LearningRateScheduler — calls
    ``optimizer.set_learning_rate(schedule(epoch))`` each epoch begin."""

    def __init__(self, schedule):
        super().__init__()
        self.schedule = schedule

    def on_epoch_begin(self, epoch, logs=None):
        optimizer = self.model.ffmodel.optimizer
        if not hasattr(optimizer, "lr") and not hasattr(optimizer, "alpha"):
            raise ValueError('Optimizer must have a "lr" attribute.')
        lr = self.schedule(epoch)
        if not isinstance(lr, (float, np.float32, np.float64)):
            raise ValueError('The output of the "schedule" function '
                             'should be float.')
        optimizer.set_learning_rate(lr)
        print("set learning rate ", lr)


class VerifyMetrics(Callback):
    """reference: callbacks.py VerifyMetrics — asserts accuracy at train
    end. Accepts a float or an enum-like object with ``.value``."""

    def __init__(self, accuracy):
        super().__init__()
        self.accuracy = getattr(accuracy, "value", accuracy)

    def on_train_end(self, logs=None):
        perf = self.model.ffmodel.get_perf_metrics()
        accuracy = perf.get_accuracy()
        if accuracy < self.accuracy:
            assert 0, "Accuracy is wrong"


class EpochVerifyMetrics(Callback):
    """reference: callbacks.py EpochVerifyMetrics — early-stops once the
    per-epoch accuracy passes the bar."""

    def __init__(self, accuracy, early_stop=True):
        super().__init__()
        self.accuracy = getattr(accuracy, "value", accuracy)
        self.early_stop = early_stop

    def on_epoch_end(self, epoch=None, logs=None):
        perf = self.model.ffmodel.get_perf_metrics()
        accuracy = perf.get_accuracy()
        if not self.early_stop:
            return False
        return accuracy > self.accuracy
