"""Keras backend functional ops (reference:
python/flexflow/keras/backend/{internal,backend_functions}.py — BatchMatmul,
Sin, Cos, Exp, Pow, ReduceSum, Rsqrt, Gather as layers plus the functional
aliases the examples use: ``out = rsqrt(x + inp2)``)."""
from __future__ import annotations

from .keras import Layer, _Node


class _Unary(Layer):
    """Maps to an FFModel unary builder by name."""

    builder: str = ""
    attrs: dict = {}

    def apply(self, ff, inputs):
        return getattr(ff, self.builder)(inputs[0], name=self.name,
                                         **self.attrs)


class Sin(_Unary):
    builder = "sin"


class Cos(_Unary):
    builder = "cos"


class Exp(_Unary):
    builder = "exp"


class Rsqrt(_Unary):
    builder = "rsqrt"


class Pow(Layer):
    def __init__(self, a: float, name=None):
        super().__init__(name)
        self.a = a

    def apply(self, ff, inputs):
        return ff.pow(inputs[0], self.a, name=self.name)


class ReduceSum(Layer):
    def __init__(self, axis=None, keepdims: bool = False, name=None):
        super().__init__(name)
        self.axis = axis
        self.keepdims = keepdims

    def apply(self, ff, inputs):
        ndim = len(inputs[0].dims)
        if self.axis is None:
            axes = list(range(1, ndim))  # all but batch (keras contract)
        elif isinstance(self.axis, (list, tuple)):
            axes = list(self.axis)
        else:
            axes = [self.axis]
        return ff.reduce_sum(inputs[0], axes, keepdims=self.keepdims,
                             name=self.name)


class BatchMatmul(Layer):
    def apply(self, ff, inputs):
        return ff.batch_matmul(inputs[0], inputs[1], name=self.name)


class Gather(Layer):
    def __init__(self, axis: int, name=None):
        super().__init__(name)
        self.axis = axis

    def apply(self, ff, inputs):
        return ff.gather(inputs[0], inputs[1], self.axis, name=self.name)


# ------------------------------------------------- functional aliases
def sin(x):
    return Sin()(x)


def cos(x):
    return Cos()(x)


def exp(x):
    return Exp()(x)


def rsqrt(x):
    return Rsqrt()(x)


def pow(x, a):  # noqa: A001  (reference name)
    return Pow(a)(x)


def sum(x, axis=None, keepdims=False):  # noqa: A001  (reference name)
    return ReduceSum(axis=axis, keepdims=keepdims)(x)


def batch_dot(x, y):
    return BatchMatmul()([x, y])


def gather(x, indices, axis):
    return Gather(axis)([x, indices])


# ------------------------------------- node arithmetic (models/tensor.py:131)
class _Scalar(Layer):
    """node-with-python-scalar arithmetic lowers to the scalar ops."""

    def __init__(self, builder: str, scalar: float, name=None):
        super().__init__(name)
        self.builder = builder
        self.scalar = float(scalar)

    def apply(self, ff, inputs):
        return getattr(ff, self.builder)(inputs[0], self.scalar,
                                         name=self.name)


def _arith(self, other, merge_cls_name: str, scalar_builder: str):
    if isinstance(other, (int, float)):
        return _Scalar(scalar_builder, other)(self)
    from . import keras as K

    if not isinstance(other, (_Node, K.Input)):
        return NotImplemented
    return getattr(K, merge_cls_name)()([self, other])


def _node_add(self, other):
    return _arith(self, other, "Add", "scalar_add")


def _node_sub(self, other):
    return _arith(self, other, "Subtract", "scalar_sub")


def _node_rsub(self, other):
    # scalar - node == (-1) * node + scalar
    if isinstance(other, (int, float)):
        return _Scalar("scalar_add", other)(
            _Scalar("scalar_multiply", -1.0)(self))
    return NotImplemented


def _node_mul(self, other):
    return _arith(self, other, "Multiply", "scalar_multiply")


def _node_div(self, other):
    return _arith(self, other, "Divide", "scalar_true_divide")


_Node.__add__ = _node_add
_Node.__radd__ = _node_add
_Node.__sub__ = _node_sub
_Node.__rsub__ = _node_rsub
_Node.__mul__ = _node_mul
_Node.__rmul__ = _node_mul
_Node.__truediv__ = _node_div
