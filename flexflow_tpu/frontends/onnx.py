"""ONNX frontend: onnx protobuf graph -> FFModel builder calls.

Rebuild of the reference's ONNX importer (python/flexflow/onnx/model.py:57-375,
``ONNXModel.apply`` walking graph.node and dispatching per op_type). Gated on
the ``onnx`` package (not baked into every image); raises a clear error when
absent.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ..ffconst import PoolType
from ..model import FFModel
from ..tensor import Tensor


def _require_onnx():
    try:
        import onnx  # noqa: F401

        return onnx
    except ImportError as e:
        raise ImportError(
            "the onnx package is required for the ONNX frontend; "
            "install onnx or use the torch/keras frontends") from e


class ONNXModel:
    """reference: python/flexflow/onnx/model.py:57."""

    def __init__(self, filename_or_model):
        onnx = _require_onnx()
        if isinstance(filename_or_model, str):
            self.model = onnx.load(filename_or_model)
        else:
            self.model = filename_or_model
        self.inputs: Dict[str, Any] = {}
        self.initializers: Dict[str, np.ndarray] = {}

    def apply(self, ffmodel: FFModel, input_tensors: Dict[str, Tensor]):
        onnx = _require_onnx()
        from onnx import numpy_helper

        env: Dict[str, Any] = dict(input_tensors)
        for init in self.model.graph.initializer:
            self.initializers[init.name] = numpy_helper.to_array(init)

        def attr(node, name, default=None):
            for a in node.attribute:
                if a.name == name:
                    if a.type == onnx.AttributeProto.INT:
                        return a.i
                    if a.type == onnx.AttributeProto.INTS:
                        return list(a.ints)
                    if a.type == onnx.AttributeProto.FLOAT:
                        return a.f
                    if a.type == onnx.AttributeProto.STRING:
                        return a.s.decode()
            return default

        for node in self.model.graph.node:
            op = node.op_type
            ins = [env.get(i) for i in node.input]
            custom = self._custom_handler(op)
            if custom is not None:
                env[node.output[0]] = custom(ffmodel, node, ins, attr)
                continue
            if op == "Gemm" or op == "MatMul":
                w = self.initializers[node.input[1]]
                out_dim = w.shape[1] if op == "MatMul" else (
                    w.shape[0] if attr(node, "transB", 0) else w.shape[1])
                use_bias = len(node.input) > 2
                t = ffmodel.dense(ins[0], int(out_dim), use_bias=use_bias)
            elif op == "Conv":
                w = self.initializers[node.input[1]]
                kh, kw = attr(node, "kernel_shape", [w.shape[2], w.shape[3]])
                st = attr(node, "strides", [1, 1])
                pads = attr(node, "pads", [0, 0, 0, 0])
                t = ffmodel.conv2d(ins[0], int(w.shape[0]), kh, kw, st[0],
                                   st[1], pads[0], pads[1],
                                   groups=attr(node, "group", 1),
                                   use_bias=len(node.input) > 2)
            elif op == "MaxPool" or op == "AveragePool":
                k = attr(node, "kernel_shape")
                st = attr(node, "strides", k)
                pads = attr(node, "pads", [0, 0, 0, 0])
                pt = PoolType.POOL_MAX if op == "MaxPool" else PoolType.POOL_AVG
                t = ffmodel.pool2d(ins[0], k[0], k[1], st[0], st[1], pads[0],
                                   pads[1], pt)
            elif op == "Relu":
                t = ffmodel.relu(ins[0])
            elif op == "Sigmoid":
                t = ffmodel.sigmoid(ins[0])
            elif op == "Tanh":
                t = ffmodel.tanh(ins[0])
            elif op == "Softmax":
                t = ffmodel.softmax(ins[0], axis=attr(node, "axis", -1))
            elif op == "Add":
                t = ffmodel.add(ins[0], ins[1])
            elif op == "Sub":
                t = ffmodel.subtract(ins[0], ins[1])
            elif op == "Mul":
                t = ffmodel.multiply(ins[0], ins[1])
            elif op == "Concat":
                t = ffmodel.concat([i for i in ins if i is not None],
                                   axis=attr(node, "axis", 1))
            elif op == "Split":
                # reference: handleSplit (model.py:103) — sizes from the
                # `split` attr (opset <13), the second input initializer
                # (opset >=13), or an even split
                sizes = attr(node, "split")
                if sizes is None and len(node.input) > 1 \
                        and node.input[1] in self.initializers:
                    sizes = self.initializers[node.input[1]].tolist()
                axis = attr(node, "axis", 0)
                if sizes is None:
                    n_out = len(node.output)
                    dim = ins[0].dims[axis]
                    sizes = [dim // n_out] * n_out
                outs = ffmodel.split(ins[0], sizes, axis=axis)
                for name_i, t_i in zip(node.output, outs):
                    env[name_i] = t_i
                continue
            elif op == "GlobalAveragePool":
                # reference: handleGlobalAveragePool (model.py:137) —
                # pool over the full spatial extent
                h, w = ins[0].dims[2], ins[0].dims[3]
                t = ffmodel.pool2d(ins[0], h, w, 1, 1, 0, 0,
                                   PoolType.POOL_AVG)
            elif op == "BatchNormalization":
                t = ffmodel.batch_norm(ins[0], relu=False)
            elif op == "Pad":
                # reference: handlePad (model.py:229) — treated as identity
                # (FlexFlow pads inside conv/pool)
                t = ins[0]
            elif op == "Unsqueeze":
                axes = attr(node, "axes")
                if axes is None and len(node.input) > 1:
                    axes = self.initializers[node.input[1]].tolist()
                shape = list(ins[0].dims)
                for a in sorted(axes or []):
                    shape.insert(a if a >= 0 else len(shape) + a + 1, 1)
                t = ffmodel.reshape(ins[0], shape)
            elif op == "Constant":
                val = None
                for a in node.attribute:
                    if a.name == "value":
                        val = numpy_helper.to_array(a.t)
                env[node.output[0]] = val
                continue
            elif op == "Range":
                # reference: handleRange (model.py:279) — eager host value
                start = env.get(node.input[0], 0)
                limit = env.get(node.input[1])
                delta = env.get(node.input[2], 1)
                env[node.output[0]] = np.arange(start, limit, delta)
                continue
            elif op == "Flatten":
                t = ffmodel.flat(ins[0])
            elif op == "Reshape":
                shape = self.initializers[node.input[1]].tolist()
                t = ffmodel.reshape(ins[0], shape)
            elif op == "Transpose":
                t = ffmodel.transpose(ins[0], attr(node, "perm"))
            elif op == "Dropout":
                t = ffmodel.dropout(ins[0], attr(node, "ratio", 0.5))
            elif op == "ReduceMean":
                t = ffmodel.mean(ins[0], dims=attr(node, "axes", [-1]),
                                 keepdims=bool(attr(node, "keepdims", 1)))
            elif op == "Cast" or op == "Identity":
                t = ins[0]
            else:
                raise NotImplementedError(f"ONNX op {op}")
            env[node.output[0]] = t
        return [env[o.name] for o in self.model.graph.output]

    def _custom_handler(self, op: str):
        """Subclass hook: return a handler(ffmodel, node, ins, attr) to
        override the default dispatch for ``op`` (ONNXModelKeras)."""
        return None


class ONNXModelKeras(ONNXModel):
    """Importer for keras-exported ONNX graphs (reference:
    python/flexflow/onnx/model.py:340 ``ONNXModelKeras``): keras exporters
    put a Transpose on the dense-weight path (the kernel is stored
    transposed) — that Transpose is resolved at import time by aliasing the
    transposed initializer under its output name, so the downstream
    Gemm/MatMul sees the right out_dim; activation-path Transposes stay real
    ops. Reshape flattens like the reference's handleReshape ->
    handleFlatten; Add with a bias-initializer operand (the
    Dense(use_bias=True) export) promotes the bias to a graph constant —
    the reference's ``_create_initializer_tensor`` behavior.
    ``ffconfig``/``ffmodel`` are accepted for reference API compat only."""

    def __init__(self, filename_or_model, ffconfig=None, ffmodel=None):
        super().__init__(filename_or_model)

    def _custom_handler(self, op: str):
        if op == "Transpose":
            def handle_transpose(ffmodel, node, ins, attr):
                src = node.input[0]
                if src in self.initializers:
                    w = self.initializers[src]
                    perm = attr(node, "perm", list(range(w.ndim))[::-1])
                    self.initializers[node.output[0]] = \
                        np.transpose(w, perm)
                    return None  # weight path: no graph op
                # ONNX default perm = reversed axes
                ndim = len(ins[0].dims)
                perm = attr(node, "perm", list(range(ndim))[::-1])
                return ffmodel.transpose(ins[0], perm)

            return handle_transpose
        if op == "Reshape":
            return lambda ffmodel, node, ins, attr: ffmodel.flat(ins[0])
        if op == "Add":
            def handle_add(ffmodel, node, ins, attr):
                # keras Dense(use_bias=True) exports MatMul + Add(h, bias)
                # with the bias as an initializer — promote it to a graph
                # constant (the reference creates constant tensors for this,
                # onnx/model.py ONNXModelKeras._create_initializer_tensor)
                vals = []
                for name, v in zip(node.input, ins):
                    if v is None and name in self.initializers:
                        v = ffmodel.constant(self.initializers[name])
                    vals.append(v)
                return ffmodel.add(vals[0], vals[1])

            return handle_add
        return None
