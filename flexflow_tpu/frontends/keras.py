"""Keras frontend: tf.keras-style Sequential/functional API over FFModel.

Rebuild of the reference's Keras clone (python/flexflow/keras/: Sequential and
functional Model whose ``compile`` builds an FFModel + optimizer and ``fit``
drives the training loop — models/base_model.py:128,198; layer classes under
keras/layers/). Compact single-module version with the same user surface:
string loss/metric/optimizer names resolve exactly like the reference's
losses.py/metrics.py/optimizers.py.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..config import FFConfig
from ..ffconst import ActiMode, AggrMode, DataType, LossType, MetricsType, PoolType
from ..model import FFModel
from ..execution.optimizers import AdamOptimizer, SGDOptimizer

_LOSS_MAP = {
    "categorical_crossentropy": LossType.LOSS_CATEGORICAL_CROSSENTROPY,
    "sparse_categorical_crossentropy":
        LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
    "mse": LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
    "identity": LossType.LOSS_IDENTITY,
}
_METRIC_MAP = {
    "accuracy": MetricsType.METRICS_ACCURACY,
    "categorical_crossentropy": MetricsType.METRICS_CATEGORICAL_CROSSENTROPY,
    "sparse_categorical_crossentropy":
        MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": MetricsType.METRICS_MEAN_SQUARED_ERROR,
    "mse": MetricsType.METRICS_MEAN_SQUARED_ERROR,
    "root_mean_squared_error": MetricsType.METRICS_ROOT_MEAN_SQUARED_ERROR,
    "mean_absolute_error": MetricsType.METRICS_MEAN_ABSOLUTE_ERROR,
}
_ACTI_MAP = {
    None: ActiMode.AC_MODE_NONE, "linear": ActiMode.AC_MODE_NONE,
    "relu": ActiMode.AC_MODE_RELU, "sigmoid": ActiMode.AC_MODE_SIGMOID,
    "tanh": ActiMode.AC_MODE_TANH, "gelu": ActiMode.AC_MODE_GELU,
}


class Layer:
    name_counter = 0

    def __init__(self, name: Optional[str] = None):
        type(self).name_counter += 1
        self.name = name or f"{type(self).__name__.lower()}_{type(self).name_counter}"

    def __call__(self, prev):
        """Functional composition: returns a _Node. A raw ``Input`` layer is
        accepted where a node is expected (the reference keras examples write
        ``Dense(...)(input0)`` with input0 = Input(shape=...))."""
        def as_node(p):
            return _Node(p, []) if isinstance(p, Input) else p

        if isinstance(prev, (list, tuple)):
            return _Node(self, [as_node(p) for p in prev])
        return _Node(self, [as_node(prev)])

    def apply(self, ff: FFModel, inputs):
        raise NotImplementedError

    # -- weight access for net2net-style transfer (reference:
    #    keras/layers get_weights/set_weights used by the *_net2net
    #    examples). ``_ff_tensor`` is recorded by the model build. --------
    def _ff_params(self, ffmodel):
        ff = ffmodel.ffmodel if hasattr(ffmodel, "ffmodel") else ffmodel
        tensors = getattr(self, "_ff_tensors", [])
        assert tensors, \
            f"{self.name}: layer not built yet (compile the model first)"
        assert len(tensors) == 1, (
            f"{self.name}: applied at {len(tensors)} graph positions — "
            "each call instantiates separate weights here (no keras-style "
            "sharing), so per-layer get/set_weights would be ambiguous")
        return ff, tensors[0].owner_layer.name

    def get_weights(self, ffmodel):
        """Returns (kernel, bias) — or a 1-tuple for bias-less layers."""
        import numpy as np

        ff, lname = self._ff_params(ffmodel)
        ws = ff.params[lname]
        out = [np.asarray(ws[k]) for k in ("kernel", "bias") if k in ws]
        return tuple(out) if out else tuple(
            np.asarray(v) for v in ws.values())

    def set_weights(self, ffmodel, kernel, bias=None):
        """Positional write mirroring get_weights' order: kernel/bias where
        declared, else the layer's params in declaration order (so e.g.
        BatchNormalization scale/bias round-trip too)."""
        import jax
        import numpy as np

        ff, lname = self._ff_params(ffmodel)
        ws = ff.params[lname]
        keys = [k for k in ("kernel", "bias") if k in ws] or list(ws)
        vals = [kernel] + ([] if bias is None else [bias])
        assert len(vals) == len(keys), (
            f"{lname}: set_weights got {len(vals)} arrays for params "
            f"{keys} — pass every declared weight")
        for k, arr in zip(keys, vals):
            cur = ws[k]
            arr = np.asarray(arr, dtype=np.asarray(cur).dtype)
            assert arr.shape == cur.shape, (lname, k, arr.shape, cur.shape)
            ws[k] = jax.device_put(
                arr, cur.sharding if hasattr(cur, "sharding") else None)


class _Node:
    def __init__(self, layer: Layer, inputs: List["_Node"]):
        self.layer = layer
        self.inputs = inputs


class Input(Layer):
    def __init__(self, shape: Sequence[int], dtype: str = "float32",
                 name=None):
        super().__init__(name)
        self.shape = tuple(shape)
        self.dtype = dtype

    def __call__(self, *a, **k):  # Input is a source, already a node
        raise TypeError("Input is not callable")


def InputTensor(shape, dtype="float32", name=None) -> _Node:
    layer = Input(shape, dtype, name)
    return _Node(layer, [])


class Dense(Layer):
    def __init__(self, units: int, activation=None, use_bias: bool = True,
                 kernel_initializer=None, bias_initializer=None,
                 kernel_regularizer=None, name=None, **kw):
        super().__init__(name)
        self.units = units
        self.activation = activation
        self.use_bias = use_bias
        self.kernel_initializer = kernel_initializer
        self.bias_initializer = bias_initializer
        self.kernel_regularizer = kernel_regularizer

    def apply(self, ff, inputs):
        from . import keras_initializers as KI
        from . import keras_regularizers as KR

        return ff.dense(inputs[0], self.units, _ACTI_MAP[self.activation],
                        self.use_bias,
                        kernel_initializer=KI.resolve(self.kernel_initializer),
                        bias_initializer=KI.resolve(self.bias_initializer),
                        kernel_regularizer=KR.resolve(self.kernel_regularizer),
                        name=self.name)


class Conv2D(Layer):
    def __init__(self, filters: int, kernel_size, strides=(1, 1),
                 padding="valid", activation=None, groups: int = 1,
                 use_bias: bool = True, name=None, **kw):
        super().__init__(name)
        self.filters = filters
        ks = kernel_size if isinstance(kernel_size, (tuple, list)) else \
            (kernel_size, kernel_size)
        st = strides if isinstance(strides, (tuple, list)) else \
            (strides, strides)
        self.kernel_size, self.strides = tuple(ks), tuple(st)
        self.padding = padding
        self.activation = activation
        self.groups = groups
        self.use_bias = use_bias

    def apply(self, ff, inputs):
        kh, kw_ = self.kernel_size
        if self.padding == "same":
            ph, pw = kh // 2, kw_ // 2
        elif self.padding == "valid":
            ph, pw = 0, 0
        else:
            ph, pw = self.padding
        return ff.conv2d(inputs[0], self.filters, kh, kw_, self.strides[0],
                         self.strides[1], ph, pw, _ACTI_MAP[self.activation],
                         self.groups, self.use_bias, name=self.name)


class _Pool2D(Layer):
    pool_type = PoolType.POOL_MAX

    def __init__(self, pool_size=(2, 2), strides=None, padding="valid",
                 name=None):
        super().__init__(name)
        ps = pool_size if isinstance(pool_size, (tuple, list)) else \
            (pool_size, pool_size)
        self.pool_size = tuple(ps)
        self.strides = tuple(strides) if strides else self.pool_size
        self.padding = padding

    def apply(self, ff, inputs):
        ph = self.pool_size[0] // 2 if self.padding == "same" else 0
        pw = self.pool_size[1] // 2 if self.padding == "same" else 0
        return ff.pool2d(inputs[0], self.pool_size[0], self.pool_size[1],
                         self.strides[0], self.strides[1], ph, pw,
                         self.pool_type, name=self.name)


class MaxPooling2D(_Pool2D):
    pool_type = PoolType.POOL_MAX


class AveragePooling2D(_Pool2D):
    pool_type = PoolType.POOL_AVG


class Flatten(Layer):
    def apply(self, ff, inputs):
        return ff.flat(inputs[0], name=self.name)


class Activation(Layer):
    def __init__(self, activation: str, name=None):
        super().__init__(name)
        self.activation = activation

    def apply(self, ff, inputs):
        x = inputs[0]
        if self.activation == "softmax":
            return ff.softmax(x, name=self.name)
        fn = {"relu": ff.relu, "sigmoid": ff.sigmoid, "tanh": ff.tanh,
              "gelu": ff.gelu, "elu": ff.elu}[self.activation]
        return fn(x, name=self.name)


class Dropout(Layer):
    def __init__(self, rate: float, name=None):
        super().__init__(name)
        self.rate = rate

    def apply(self, ff, inputs):
        return ff.dropout(inputs[0], self.rate, name=self.name)


class Embedding(Layer):
    def __init__(self, input_dim: int, output_dim: int, name=None, **kw):
        super().__init__(name)
        self.input_dim = input_dim
        self.output_dim = output_dim

    def apply(self, ff, inputs):
        return ff.embedding(inputs[0], self.input_dim, self.output_dim,
                            AggrMode.AGGR_MODE_NONE, name=self.name)


class BatchNormalization(Layer):
    def apply(self, ff, inputs):
        return ff.batch_norm(inputs[0], relu=False, name=self.name)


class LayerNormalization(Layer):
    def __init__(self, axis=-1, epsilon=1e-5, name=None):
        super().__init__(name)
        self.axis = axis if isinstance(axis, (list, tuple)) else [axis]
        self.epsilon = epsilon

    def apply(self, ff, inputs):
        return ff.layer_norm(inputs[0], axes=list(self.axis),
                             eps=self.epsilon, name=self.name)


class Concatenate(Layer):
    def __init__(self, axis: int = 1, name=None):
        super().__init__(name)
        self.axis = axis

    def apply(self, ff, inputs):
        return ff.concat(list(inputs), axis=self.axis, name=self.name)


class Add(Layer):
    def apply(self, ff, inputs):
        return ff.add(inputs[0], inputs[1], name=self.name)


class Subtract(Layer):
    def apply(self, ff, inputs):
        return ff.subtract(inputs[0], inputs[1], name=self.name)


class Multiply(Layer):
    def apply(self, ff, inputs):
        return ff.multiply(inputs[0], inputs[1], name=self.name)


class Divide(Layer):
    def apply(self, ff, inputs):
        return ff.divide(inputs[0], inputs[1], name=self.name)


class Maximum(Layer):
    """reference: examples/python/keras/elementwise_max_min.py."""

    def apply(self, ff, inputs):
        return ff.max(inputs[0], inputs[1], name=self.name)


class Minimum(Layer):
    def apply(self, ff, inputs):
        return ff.min(inputs[0], inputs[1], name=self.name)


class Reshape(Layer):
    """target_shape excludes the batch dim (keras contract; reference:
    python/flexflow/keras/layers/core.py Reshape)."""

    def __init__(self, target_shape, name=None):
        super().__init__(name)
        self.target_shape = tuple(int(d) for d in target_shape)

    def apply(self, ff, inputs):
        batch = inputs[0].dims[0]
        return ff.reshape(inputs[0], (batch,) + self.target_shape,
                          name=self.name)


def concatenate(tensors, axis: int = 1, name=None):
    """Functional alias (reference: keras layers concatenate())."""
    return Concatenate(axis=axis, name=name)(tensors)


# --------------------------------------------------------------------- models
class _BaseModel:
    """reference: python/flexflow/keras/models/base_model.py."""

    def __init__(self, name: Optional[str] = None):
        self.name = name
        self.ffmodel: Optional[FFModel] = None
        self.ffconfig = FFConfig()

    def _resolve_optimizer(self, optimizer):
        if isinstance(optimizer, str):
            return {"sgd": SGDOptimizer(None, lr=0.01),
                    "adam": AdamOptimizer(None)}[optimizer.lower()]
        if isinstance(optimizer, dict):  # keras config dict
            name = optimizer.get("class_name", "SGD").lower()
            cfg = optimizer.get("config", {})
            if name == "sgd":
                return SGDOptimizer(None, lr=cfg.get("learning_rate", 0.01),
                                    momentum=cfg.get("momentum", 0.0),
                                    nesterov=cfg.get("nesterov", False))
            return AdamOptimizer(None, alpha=cfg.get("learning_rate", 1e-3))
        return optimizer

    def compile(self, optimizer="sgd", loss="sparse_categorical_crossentropy",
                metrics=("accuracy",), **kw):
        """reference: base_model.py:128 — builds FFModel and compiles."""
        ff = FFModel(self.ffconfig)
        self._build(ff)
        ff.compile(optimizer=self._resolve_optimizer(optimizer),
                   loss_type=_LOSS_MAP[loss],
                   metrics=[_METRIC_MAP[m] for m in metrics])
        self.ffmodel = ff

    def fit(self, x, y, batch_size: Optional[int] = None,
            epochs: int = 1, callbacks=None, **kw):
        """reference: base_model.py:198 — drives FFModel.fit one epoch at a
        time so epoch-level callbacks (callbacks.py) fire exactly like the
        reference's loop; EpochVerifyMetrics-style callbacks early-stop by
        returning True from on_epoch_end."""
        assert self.ffmodel is not None, "compile the model first"
        callbacks = list(callbacks or [])
        for cb in callbacks:
            cb.set_model(self)
            cb.on_train_begin()
        perf = None
        for epoch in range(epochs):
            for cb in callbacks:
                cb.on_epoch_begin(epoch)
            opt = self.ffmodel.optimizer
            if getattr(opt, "_lr_changed", False):
                # jitted steps baked the old rate in as a constant; rebuild
                # them all (the guarded sentinel variant included)
                self.ffmodel.executor.invalidate_jit_cache()
                opt._lr_changed = False
            perf = self.ffmodel.fit(x, y, batch_size=batch_size, epochs=1)
            if getattr(self.ffmodel, "_preempted_at_step", None) is not None:
                # the inner fit flushed its preemption checkpoint and
                # returned; looping on would burn the grace window
                break
            stop = False
            for cb in callbacks:
                if cb.on_epoch_end(epoch):
                    stop = True
            if stop:
                break
        for cb in callbacks:
            cb.on_train_end()
        return perf

    def evaluate(self, x, y, batch_size: Optional[int] = None):
        return self.ffmodel.eval(x, y, batch_size=batch_size)

    def predict(self, x, batch_size: Optional[int] = None):
        return self.ffmodel.predict(x, batch_size=batch_size)

    def _build(self, ff: FFModel) -> None:
        raise NotImplementedError


class Sequential(_BaseModel):
    def __init__(self, layers: Optional[List[Layer]] = None, name=None):
        super().__init__(name)
        self.layers: List[Layer] = list(layers or [])

    def add(self, layer: Layer) -> None:
        self.layers.append(layer)

    def _build(self, ff: FFModel) -> None:
        assert isinstance(self.layers[0], Input), \
            "first layer must be Input(shape=...)"
        inp = self.layers[0]
        dtype = DataType.DT_INT32 if "int" in inp.dtype else DataType.DT_FLOAT
        t = ff.create_tensor((self.ffconfig.batch_size,) + inp.shape, dtype)
        for layer in self.layers[1:]:
            layer._ff_tensors = []  # recompile starts a fresh record
        for layer in self.layers[1:]:
            t = layer.apply(ff, [t])
            layer._ff_tensors = layer._ff_tensors + \
                [t[0] if isinstance(t, list) else t]


class Model(_BaseModel):
    """Functional API: Model(inputs=[node...], outputs=node)."""

    def __init__(self, inputs, outputs, name=None):
        super().__init__(name)
        self.inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self.outputs = outputs if isinstance(outputs, (list, tuple)) else \
            [outputs]

    def _build(self, ff: FFModel) -> None:
        built: Dict[int, Any] = {}

        def reset_records(node: _Node):
            if id(node) in seen_reset:
                return
            seen_reset.add(id(node))
            node.layer._ff_tensors = []
            for i in node.inputs:
                reset_records(i)

        seen_reset: set = set()
        for out in self.outputs:
            reset_records(out)

        def build_node(node: _Node):
            # Input tensors key by the LAYER: the same Input may be wrapped
            # in several _Node shells (one per consumer call) and must build
            # exactly one graph input
            key = (id(node.layer) if isinstance(node.layer, Input)
                   else id(node))
            if key in built:
                return built[key]
            if isinstance(node.layer, Input):
                inp = node.layer
                dtype = DataType.DT_INT32 if "int" in inp.dtype else \
                    DataType.DT_FLOAT
                t = ff.create_tensor(
                    (self.ffconfig.batch_size,) + inp.shape, dtype)
            else:
                ins = [build_node(i) for i in node.inputs]
                t = node.layer.apply(ff, ins)
                node.layer._ff_tensors = getattr(
                    node.layer, "_ff_tensors", []) + \
                    [t[0] if isinstance(t, list) else t]
            built[key] = t
            return t

        # declared input order fixes the fit(x=[...]) binding order,
        # independent of output-traversal order
        for inp in self.inputs:
            build_node(inp if isinstance(inp, _Node) else _Node(inp, []))
        for out in self.outputs:
            build_node(out)


# -- reference-parity submodules (python/flexflow/keras/{callbacks,datasets,
# preprocessing}) exposed under the frontend namespace -------------------------
from . import keras_backend as backend  # noqa: E402
from . import keras_callbacks as callbacks  # noqa: E402
from . import keras_datasets as datasets  # noqa: E402
from . import keras_initializers as initializers  # noqa: E402
from . import keras_preprocessing as preprocessing  # noqa: E402
from . import keras_regularizers as regularizers  # noqa: E402
from .keras_callbacks import (Callback, EpochVerifyMetrics,  # noqa: E402
                              LearningRateScheduler, VerifyMetrics)
from .keras_initializers import (GlorotUniform, RandomNormal,  # noqa: E402
                                 RandomUniform, Zeros)
from .keras_regularizers import L1, L2  # noqa: E402
