"""HuggingFace model via the torch-fx frontend (reference: the mt5 pipeline
in examples/python/pytorch/mt5/ and hf_symbolic_trace support in
python/flexflow/torch/model.py:2427): trace a transformers BertModel, copy its
weights, and fine-tune a classification head on synthetic data."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np  # noqa: E402


def main(argv=None, hf_cfg=None):
    from transformers import BertConfig, BertModel

    from flexflow_tpu import (AdamOptimizer, DataType, FFConfig, FFModel,
                              LossType, MetricsType)
    from flexflow_tpu.frontends.torch_fx import (PyTorchModel,
                                                 copy_torch_weights)

    hf_cfg = hf_cfg or BertConfig(
        hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
        intermediate_size=128, vocab_size=1000, max_position_embeddings=64,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    module = BertModel(hf_cfg)
    module.eval()

    config = FFConfig()
    if argv:
        config.parse_args(argv)
    ff = FFModel(config)
    bs, seq = config.batch_size, 16
    ids_t = ff.create_tensor((bs, seq), dtype=DataType.DT_INT32,
                             name="input_ids")
    outputs = PyTorchModel(module, is_hf_model=True).torch_to_ff(
        ff, [ids_t], input_names=["input_ids"])
    logits = ff.dense(outputs["pooler_output"], 2, name="cls_head")
    probs = ff.softmax(logits)
    ff.compile(optimizer=AdamOptimizer(ff, alpha=1e-3),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY], final_tensor=probs)
    copy_torch_weights(ff)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, hf_cfg.vocab_size, size=(bs * 2, seq)
                       ).astype(np.int32)
    y = rng.integers(0, 2, size=(bs * 2,)).astype(np.int32)
    perf = ff.fit(ids, y, epochs=config.epochs)
    print(f"train accuracy = {perf.accuracy():.4f}")
    return ff, perf


if __name__ == "__main__":
    main(sys.argv[1:])
