"""Define the CIFAR-10 CNN as a torch nn.Module and export it to the .ff
file format (reference: examples/python/pytorch/cifar10_cnn_torch.py).
The companion cifar10_cnn.py loads the file with file_to_ff and trains."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import torch  # noqa: E402
import torch.nn as nn  # noqa: E402

from flexflow_tpu.frontends.torch_fx import PyTorchModel  # noqa: E402


class CNN(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 32, 3, 1, padding=1)
        self.conv2 = nn.Conv2d(32, 32, 3, 1, padding=1)
        self.pool1 = nn.MaxPool2d(2, 2)
        self.conv3 = nn.Conv2d(32, 64, 3, 1, padding=1)
        self.conv4 = nn.Conv2d(64, 64, 3, 1, padding=1)
        self.pool2 = nn.MaxPool2d(2, 2)
        self.flat1 = nn.Flatten()
        self.linear1 = nn.Linear(4096, 512)
        self.linear2 = nn.Linear(512, 10)
        self.relu = nn.ReLU()

    def forward(self, x):
        y = self.relu(self.conv1(x))
        y = self.relu(self.conv2(y))
        y = self.pool1(y)
        y = self.relu(self.conv3(y))
        y = self.relu(self.conv4(y))
        y = self.pool2(y)
        y = self.flat1(y)
        y = self.relu(self.linear1(y))
        return self.linear2(y)


def main(out_path="cnn.ff"):
    ff_torch_model = PyTorchModel(CNN())
    ff_torch_model.torch_to_file(out_path)
    print(f"exported {out_path}")
    return out_path


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "cnn.ff")
