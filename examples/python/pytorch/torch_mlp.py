"""PyTorch-frontend example (reference: examples/python/pytorch/ — trace a
torch.nn.Module via torch.fx and train it in the framework)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np  # noqa: E402
import torch  # noqa: E402

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,  # noqa: E402
                          SGDOptimizer)
from flexflow_tpu.frontends.torch_fx import (PyTorchModel,  # noqa: E402
                                             copy_torch_weights)


class MLP(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = torch.nn.Linear(784, 512)
        self.fc2 = torch.nn.Linear(512, 10)

    def forward(self, x):
        x = torch.relu(self.fc1(x))
        return torch.softmax(self.fc2(x), dim=-1)


def main(argv=None):
    config = FFConfig()
    if argv:
        config.parse_args(argv)
    ff = FFModel(config)
    bs = config.batch_size
    x_t = ff.create_tensor((bs, 784))
    PyTorchModel(MLP()).torch_to_ff(ff, [x_t])
    ff.compile(optimizer=SGDOptimizer(ff, lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    copy_torch_weights(ff)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(bs * 4, 784)).astype(np.float32)
    y = rng.integers(0, 10, size=(bs * 4,)).astype(np.int32)
    perf = ff.fit(x, y, epochs=config.epochs)
    print(f"train accuracy = {perf.accuracy():.4f}")
    return ff, perf


if __name__ == "__main__":
    main(sys.argv[1:])
