"""ResNet defined in pure torch, traced through the fx frontend, trained on
synthetic CIFAR-10-shaped data (reference:
examples/python/pytorch/resnet_torch.py + resnet.py — there via torchvision;
the BasicBlock stack is defined inline here since torchvision is not a
dependency)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np  # noqa: E402
import torch  # noqa: E402
import torch.nn as nn  # noqa: E402

from flexflow_tpu import (FFConfig, FFModel, LossType,  # noqa: E402
                          MetricsType, SGDOptimizer)
from flexflow_tpu.frontends.torch_fx import PyTorchModel  # noqa: E402


class BasicBlock(nn.Module):
    def __init__(self, cin, cout, stride=1):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(cout)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(cout)
        self.down = (nn.Conv2d(cin, cout, 1, stride, bias=False)
                     if stride != 1 or cin != cout else nn.Identity())

    def forward(self, x):
        y = self.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        return self.relu(y + self.down(x))


class ResNetCifar(nn.Module):
    """resnet18-shaped stack at CIFAR scale (2 blocks per stage)."""

    def __init__(self, num_classes=10, width=16):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv2d(3, width, 3, 1, 1, bias=False),
            nn.BatchNorm2d(width), nn.ReLU())
        self.layer1 = nn.Sequential(BasicBlock(width, width),
                                    BasicBlock(width, width))
        self.layer2 = nn.Sequential(BasicBlock(width, 2 * width, 2),
                                    BasicBlock(2 * width, 2 * width))
        self.layer3 = nn.Sequential(BasicBlock(2 * width, 4 * width, 2),
                                    BasicBlock(4 * width, 4 * width))
        self.pool = nn.AdaptiveAvgPool2d((1, 1))
        self.flat = nn.Flatten()
        self.fc = nn.Linear(4 * width, num_classes)

    def forward(self, x):
        y = self.layer3(self.layer2(self.layer1(self.stem(x))))
        return self.fc(self.flat(self.pool(y)))


def main(argv=None, num_samples=None):
    config = FFConfig()
    if argv:
        config.parse_args(argv)
    b = config.batch_size
    ff = FFModel(config)
    x_t = ff.create_tensor((b, 3, 32, 32))
    net = ResNetCifar().eval()
    outs = PyTorchModel(net).torch_to_ff(ff, [x_t])
    ff.softmax(outs[0] if isinstance(outs, list) else outs)
    ff.compile(optimizer=SGDOptimizer(ff, lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    n = num_samples or b * 4
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 3, 32, 32)).astype(np.float32)
    y = rng.integers(0, 10, size=n).astype(np.int32)
    perf = ff.fit(x, y, epochs=config.epochs)
    print(f"train accuracy = {perf.accuracy():.4f}")
    return ff, perf


if __name__ == "__main__":
    main(sys.argv[1:])
