"""Load the .ff file exported by cifar10_cnn_torch.py and train on CIFAR-10
(reference: examples/python/pytorch/cifar10_cnn.py — file_to_ff + cifar10
loader + create_data_loader)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

from flexflow_tpu import (DataType, FFConfig, FFModel, LossType,  # noqa: E402
                          MetricsType, SGDOptimizer)
from flexflow_tpu.frontends.keras_datasets import cifar10  # noqa: E402
from flexflow_tpu.frontends.torch_fx import file_to_ff  # noqa: E402


def main(argv=None, ff_file=None, num_samples=256):
    config = FFConfig()
    if argv:
        config.parse_args(argv)
    b = config.batch_size
    ff = FFModel(config)
    input_tensor = ff.create_tensor((b, 3, 32, 32), DataType.DT_FLOAT)
    out_tensors = file_to_ff(
        ff_file or os.path.join(os.path.dirname(__file__), "cnn.ff"),
        ff, [input_tensor])
    ff.softmax(out_tensors[-1])

    ff.compile(optimizer=SGDOptimizer(ff, lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY,
                        MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY])

    (x_train, y_train), _ = cifar10.load_data(num_samples)
    x_train = x_train.astype("float32") / 255
    y_train = y_train.astype("int32")
    dl_x = ff.create_data_loader(input_tensor, x_train)
    dl_y = ff.create_data_loader(ff.label_tensor, y_train)
    ff.init_layers()

    n = (num_samples // b) * b
    ts_start = config.get_current_time()
    perf = ff.fit(x_train[:n], y_train[:n], epochs=config.epochs)
    run_time = 1e-6 * (config.get_current_time() - ts_start)
    print(f"epochs {config.epochs}, ELAPSED TIME = {run_time:.4f}s, "
          f"THROUGHPUT = {n * config.epochs / run_time:.2f} samples/s")
    print(f"train accuracy = {perf.accuracy():.4f}")
    assert dl_x.num_samples == dl_y.num_samples == num_samples
    return ff, perf


if __name__ == "__main__":
    ff_file = os.path.join(os.path.dirname(__file__), "cnn.ff")
    if not os.path.exists(ff_file):
        from cifar10_cnn_torch import main as export

        export(ff_file)
    main(sys.argv[1:], ff_file=ff_file)
