"""T5/mT5 seq2seq through the fx frontend (reference:
examples/python/pytorch/mt5/mt5_ff.py — there google/mt5-small pretrained +
Sinhala-English data; here a from-config T5 with synthetic ids since the
environment has no network/weights, same trace + train path).

The encoder-decoder trace exercises: host-side relative-position bucket
arithmetic (arange/abs/lt/log/min/where at trace time), the relative
attention bias as a constant-index embedding lookup, mask plumbing, and the
tied lm_head."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", "..", ".."))

import numpy as np  # noqa: E402

from flexflow_tpu import (AdamOptimizer, DataType, FFConfig,  # noqa: E402
                          FFModel, LossType, MetricsType)
from flexflow_tpu.frontends.torch_fx import (PyTorchModel,  # noqa: E402
                                             copy_torch_weights)

SEQ = 16


def build_t5(vocab=256, d_model=64, layers=2, heads=4):
    from transformers import T5Config, T5ForConditionalGeneration

    cfg = T5Config(vocab_size=vocab, d_model=d_model,
                   d_kv=d_model // heads, d_ff=2 * d_model,
                   num_layers=layers, num_heads=heads,
                   decoder_start_token_id=0, dropout_rate=0.0)
    return T5ForConditionalGeneration(cfg).eval(), cfg


def main(argv=None, num_samples=None):
    config = FFConfig()
    if argv:
        config.parse_args(argv)
    b = config.batch_size
    module, hf_cfg = build_t5()

    ff = FFModel(config)
    ids = ff.create_tensor((b, SEQ), DataType.DT_INT32, name="input_ids")
    mask = ff.create_tensor((b, SEQ), DataType.DT_INT32,
                            name="attention_mask")
    dec = ff.create_tensor((b, SEQ), DataType.DT_INT32,
                           name="decoder_input_ids")
    outs = PyTorchModel(module, is_hf_model=True).torch_to_ff(
        ff, [ids, mask, dec],
        input_names=["input_ids", "attention_mask", "decoder_input_ids"])
    logits = outs["logits"]
    # token-level LM loss: flatten positions like the nmt model
    # (models/nmt.py — fit slices labels by batch rows, so the flattened
    # token stream drives the jitted step directly)
    lm = ff.reshape(logits, (b * SEQ, hf_cfg.vocab_size))

    ff.compile(optimizer=AdamOptimizer(ff, alpha=1e-3),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY,
                        MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY],
               final_tensor=lm)
    copy_torch_weights(ff)

    # synthetic copy task (reference trains on text pairs)
    import jax.random as jrandom

    steps = max((num_samples or b * 4) // b, 1)
    rng = np.random.default_rng(0)
    step_fn = ff.executor.make_train_step()
    params, opt_state = ff.params, ff.opt_state
    losses = []
    for i in range(steps * config.epochs):
        x_ids = rng.integers(1, hf_cfg.vocab_size,
                             size=(b, SEQ)).astype(np.int32)
        x_mask = np.ones((b, SEQ), np.int32)
        x_dec = np.roll(x_ids, 1, axis=1)
        x_dec[:, 0] = 0  # decoder_start_token_id
        y = x_ids.reshape(-1, 1)  # predict the input ids (copy task)
        params, opt_state, loss, _ = step_fn(
            params, opt_state, [x_ids, x_mask, x_dec], y,
            jrandom.PRNGKey(i))
        losses.append(float(loss))
    ff.params, ff.opt_state = params, opt_state
    print(f"t5 seq2seq trained; loss {losses[0]:.3f} -> {losses[-1]:.3f}")
    return ff, losses


if __name__ == "__main__":
    main(sys.argv[1:])
