"""ONNX-frontend example (reference: examples/python/onnx/ — import an .onnx
graph and train it). Exports a small torch MLP to ONNX first; skips cleanly if
the onnx package is not installed (it is optional in this image)."""
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np  # noqa: E402


def main(argv=None):
    try:
        import onnx  # noqa: F401
    except ImportError:
        print("onnx package not installed — skipping (frontends/onnx.py is "
              "gated on it)")
        return None, None

    import torch

    from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                              SGDOptimizer)
    from flexflow_tpu.frontends.onnx import ONNXModel

    model = torch.nn.Sequential(
        torch.nn.Linear(784, 128), torch.nn.ReLU(),
        torch.nn.Linear(128, 10), torch.nn.Softmax(dim=-1))
    path = os.path.join(tempfile.mkdtemp(), "mlp.onnx")
    torch.onnx.export(model, torch.zeros(1, 784), path,
                      input_names=["input"], output_names=["output"])

    config = FFConfig()
    if argv:
        config.parse_args(argv)
    ff = FFModel(config)
    bs = config.batch_size
    x_t = ff.create_tensor((bs, 784), name="input")
    ONNXModel(path).apply(ff, {"input": x_t})
    ff.compile(optimizer=SGDOptimizer(ff, lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    rng = np.random.default_rng(0)
    x = rng.normal(size=(bs * 2, 784)).astype(np.float32)
    y = rng.integers(0, 10, size=(bs * 2,)).astype(np.int32)
    perf = ff.fit(x, y, epochs=config.epochs)
    print(f"train accuracy = {perf.accuracy():.4f}")
    return ff, perf


if __name__ == "__main__":
    main(sys.argv[1:])
