"""Mixture-of-experts MLP (reference: examples/cpp/mixture_of_experts/moe.cc:
gate dense -> top_k -> group_by -> per-expert dense -> aggregate, with
load-balance loss lambda_bal)."""
from _common import run
from flexflow_tpu.models import build_moe_mlp


def main(argv=None):
    return run(lambda ff: build_moe_mlp(ff, ff.config.batch_size),
               [(784,)], 10, argv=argv)


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
