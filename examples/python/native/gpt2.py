"""GPT-2-style causal LM (decoder-only; reference analog: the HF-traced
decoder family of python/flexflow/torch/model.py:2427). Next-token training
on random token streams; the causal attention core lowers to the Pallas
flash kernel on TPU (flash-causal). Pass --compute-dtype bf16 for the
mixed-precision path."""
import numpy as np

import _common  # noqa: F401
from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, LossType
from flexflow_tpu.models.gpt2 import GPT2Config, build_gpt2


def main(argv=None, cfg=None):
    config = FFConfig()
    if argv:
        config.parse_args(argv)
    config.profiling = True
    cfg = cfg or GPT2Config.tiny(batch_size=config.batch_size)
    config.batch_size = cfg.batch_size
    ff = FFModel(config)
    ids, logits = build_gpt2(ff, cfg)
    probs = ff.softmax(logits)
    ff.compile(optimizer=AdamOptimizer(ff, alpha=1e-3),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               final_tensor=probs)

    n = cfg.batch_size * 2
    rng = np.random.default_rng(0)
    stream = rng.integers(0, cfg.vocab_size, size=(n, cfg.seq_len + 1))
    x = stream[:, :-1].astype(np.int32)
    y = stream[:, 1:].astype(np.int32)  # next-token targets
    perf = ff.fit(x, y)
    if config.serve:
        # --serve (ISSUE 6, docs/serving.md): after training, serve a few
        # continuations through the prefill/decode engine — training and
        # serving on the same compiled model, same process
        prompts = [row[: cfg.seq_len // 4].tolist() for row in x[:4]]
        outs = ff.generate(prompts,
                           max_new_tokens=min(8, config.max_decode_len // 2),
                           max_decode_len=min(config.max_decode_len,
                                              cfg.seq_len),
                           max_inflight=min(config.max_inflight, 4))
        for i, o in enumerate(outs):
            print(f"SERVE request {i}: generated={o}")
    return ff, perf


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
