"""CANDLE-Uno drug-response model (reference: examples/cpp/candle_uno/
candle_uno.cc — per-feature dense towers concatenated into a deep MLP)."""
import numpy as np

import _common  # noqa: F401
from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models import build_candle_uno
from flexflow_tpu.models.misc import _UNO_FEATURE_SHAPES, _UNO_INPUT_FEATURES


def main(argv=None, dense_layers=(1024,) * 2, dense_feature_layers=(1024,) * 2):
    config = FFConfig()
    if argv:
        config.parse_args(argv)
    config.profiling = True
    ff = FFModel(config)
    bs = config.batch_size
    build_candle_uno(ff, bs, dense_layers=dense_layers,
                     dense_feature_layers=dense_feature_layers)
    n = bs * 2
    rng = np.random.default_rng(0)
    xs = [rng.normal(size=(n, _UNO_FEATURE_SHAPES[f])).astype(np.float32)
          for f in _UNO_INPUT_FEATURES.values()]
    y = rng.uniform(0, 1, size=(n, 1)).astype(np.float32)
    ff.compile(optimizer=SGDOptimizer(ff, lr=0.01),
               loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
               metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR])
    perf = ff.fit(xs, y)
    print(f"train mse = {perf.mean('mse_loss'):.4f}")
    return ff, perf


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
