"""MNIST CNN (reference: examples/python/native/mnist_cnn.py —
conv 32/64 3x3 + pool + dense 128/10, SGD, sparse-CCE)."""
from _common import run  # noqa: E402  (sys.path set up by _common)
from flexflow_tpu import ActiMode


def build(ff, batch_size=64):
    x = ff.create_tensor((batch_size, 1, 28, 28), name="mnist_image")
    t = ff.conv2d(x, 32, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.conv2d(t, 64, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 128, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 10)
    return x, ff.softmax(t)


def main(argv=None):
    return run(lambda ff: build(ff, ff.config.batch_size),
               [(1, 28, 28)], 10, argv=argv)


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
