"""ResNet-50 (reference: examples/python/native/resnet.py,
examples/cpp/ResNet). Synthetic ImageNet-shaped data; use --batch-size to
scale."""
from _common import run
from flexflow_tpu.models import build_resnet50


def main(argv=None, image_size=64, num_classes=200):
    # default 64px synthetic images keep the smoke run fast; pass
    # image_size=224 for the full config
    return run(lambda ff: build_resnet50(ff, ff.config.batch_size,
                                         image_size=image_size,
                                         num_classes=num_classes),
               [(3, image_size, image_size)], num_classes, argv=argv)


if __name__ == "__main__":
    import sys

    main(sys.argv[1:], image_size=224, num_classes=1000)
