"""Transformer encoder (reference: examples/cpp/Transformer/transformer.cc:
79-85 — 12 layers, hidden 1024, 16 heads, seq 512)."""
import _common  # noqa: F401
from _common import run
from flexflow_tpu.models import TransformerConfig, build_transformer


def main(argv=None, cfg=None):
    c = [cfg]

    def build(ff):
        c[0] = cfg or TransformerConfig(batch_size=ff.config.batch_size)
        ff.config.batch_size = c[0].batch_size
        return build_transformer(ff, c[0])

    cfg0 = cfg or TransformerConfig()
    return run(build, [(cfg0.seq_len, cfg0.hidden)], 2, optimizer="adam",
               argv=argv)


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
