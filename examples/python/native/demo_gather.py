"""Gather op demo (reference: examples/python/native/demo_gather.py —
dense -> gather along dim 1 by a neighbors index tensor, MSE loss,
manual forward/backward/update loop on attached arrays)."""
import numpy as np

import _common  # noqa: F401  (sys.path setup)
from flexflow_tpu import (ActiMode, DataType, FFConfig, FFModel, LossType,
                          MetricsType, SGDOptimizer)


def main(argv=None, iters=20):
    config = FFConfig()
    if argv:
        config.parse_args(argv)
    bs = config.batch_size
    ff = FFModel(config)
    neighbors = np.array([[[0], [5], [3], [3], [7], [9]]])
    neighbors = neighbors.repeat(bs, 0).repeat(5, 2).astype(np.int32)
    x = np.full((bs, 16, 5), 0.01, np.float32)

    input = ff.create_tensor((bs, 16, 5), DataType.DT_FLOAT)
    index = ff.create_tensor((bs, 6, 5), DataType.DT_INT32)
    x0 = ff.dense(input, 5, ActiMode.AC_MODE_NONE, False)
    ff.gather(x0, index, 1)

    ff.compile(optimizer=SGDOptimizer(ff, lr=0.01),
               loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
               metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR])
    ff.init_layers()
    input.attach_numpy_array(ff, config, x)
    index.attach_numpy_array(ff, config, neighbors)
    y = np.random.default_rng(0).random((bs, 6, 5)).astype(np.float32)
    ff.label_tensor.attach_numpy_array(ff, config, y)

    losses = []
    for _ in range(iters):
        ff.forward()
        ff.backward()
        losses.append(float(ff._staged["loss"]))
        ff.update()
    print(f"gather demo: loss {losses[0]:.5f} -> {losses[-1]:.5f}")
    assert losses[-1] < losses[0]
    return ff


if __name__ == "__main__":
    print("Demo Gather")
    main()
