"""XDL CTR model (reference: examples/cpp/XDL/xdl.cc — embedding bags
concatenated into an MLP; OSDI'22 xdl benchmark)."""
import numpy as np

import _common  # noqa: F401
from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models import build_xdl


def main(argv=None, num_embeddings=4, vocab_size=100000):
    config = FFConfig()
    if argv:
        config.parse_args(argv)
    config.profiling = True
    ff = FFModel(config)
    bs = config.batch_size
    build_xdl(ff, bs, num_embeddings=num_embeddings, vocab_size=vocab_size)
    ff.compile(optimizer=SGDOptimizer(ff, lr=0.01),
               loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
               metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR])
    n = bs * 2
    rng = np.random.default_rng(0)
    xs = [rng.integers(0, vocab_size, size=(n, 1)).astype(np.int32)
          for _ in range(num_embeddings)]
    y = rng.uniform(0, 1, size=(n, 1)).astype(np.float32)
    perf = ff.fit(xs, y)
    print(f"train mse = {perf.mean('mse_loss'):.4f}")
    return ff, perf


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
