"""Hand-built multi-head attention from primitive ops (reference:
examples/python/native/multi_head_attention.py — q/k/v dense +
reshape/transpose + two batch_matmuls, MSE loss)."""
import numpy as np

import _common  # noqa: F401  (sys.path setup)
from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer)

SEQ, HIDDEN, HEADS = 16, 64, 4


def build(ff, batch_size=32, seq=SEQ, hidden=HIDDEN, heads=HEADS):
    x = ff.create_tensor((batch_size, seq, hidden), name="mha_input")
    q = ff.dense(x, hidden)
    k = ff.dense(x, hidden)
    v = ff.dense(x, hidden)
    hd = hidden // heads
    q = ff.reshape(q, (batch_size, seq, heads, hd))
    k = ff.reshape(k, (batch_size, seq, heads, hd))
    v = ff.reshape(v, (batch_size, seq, heads, hd))
    q = ff.transpose(q, (0, 2, 1, 3))
    k = ff.transpose(k, (0, 2, 3, 1))
    v = ff.transpose(v, (0, 2, 1, 3))
    logits = ff.batch_matmul(q, k)
    out = ff.batch_matmul(logits, v)
    out = ff.transpose(out, (0, 2, 1, 3))
    out = ff.reshape(out, (batch_size, seq, hidden))
    out = ff.dense(out, hidden, ActiMode.AC_MODE_RELU)
    out = ff.dense(out, hidden)
    return x, out


def main(argv=None):
    config = FFConfig()
    if argv:
        config.parse_args(argv)
    ff = FFModel(config)
    build(ff, config.batch_size)
    ff.compile(optimizer=SGDOptimizer(ff, lr=0.01),
               loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
               metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR])

    rng = np.random.default_rng(0)
    n = config.batch_size * 4
    x = rng.normal(size=(n, SEQ, HIDDEN)).astype(np.float32)
    y = x.copy()  # identity-regression target
    perf = ff.fit(x, y, epochs=config.epochs)
    if ff._last_fit_time > 0:
        print(f"THROUGHPUT = {ff._last_fit_samples / ff._last_fit_time:.2f} "
              f"samples/s")
    print(f"train MSE = {perf.mean('mse_loss'):.6f}")
    return ff, perf


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
