"""DLRM (reference: examples/python/native/dlrm.py, examples/cpp/DLRM) —
embedding bags + bottom/top MLPs + feature interaction, MSE loss on a scalar
click prediction."""
import numpy as np

import _common  # noqa: F401  (sys.path side effect)
from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models import build_dlrm


def main(argv=None, embedding_sizes=(1000,) * 8, embedding_dim=64,
         mlp_bot=None):
    config = FFConfig()
    if argv:
        config.parse_args(argv)
    config.profiling = True
    ff = FFModel(config)
    bs = config.batch_size
    # bottom MLP must end at embedding_dim (the interaction reshape
    # concatenates per-feature embedding_dim vectors, dlrm.cc)
    mlp_bot = mlp_bot or (512, 256, embedding_dim)
    sparse_inputs, dense_input, _out = build_dlrm(
        ff, bs, embedding_sizes=embedding_sizes,
        embedding_dim=embedding_dim, mlp_bot=mlp_bot)
    ff.compile(optimizer=SGDOptimizer(ff, lr=0.01),
               loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
               metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR])

    n = bs * 4
    rng = np.random.default_rng(0)
    xs = [rng.integers(0, sz, size=(n, 1)).astype(np.int64)
          for sz in embedding_sizes]
    xs.append(rng.normal(size=(n, 16)).astype(np.float32))
    y = rng.uniform(0, 1, size=(n, 1)).astype(np.float32)
    perf = ff.fit(xs, y)
    print(f"train mse = {perf.mean('mse_loss'):.4f}")
    return ff, perf


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
