"""Graph/tensor introspection demo (reference:
examples/python/native/print_layers.py + print_weight.py + print_input.py —
build a small net, map tensors host-side, print shapes/arrays, poke
weights via set_weights)."""
import numpy as np

import _common  # noqa: F401  (sys.path setup)
from flexflow_tpu import (ActiMode, DataType, FFConfig, FFModel, LossType,
                          SGDOptimizer)


def main(argv=None):
    config = FFConfig()
    if argv:
        config.parse_args(argv)
    b = config.batch_size
    ff = FFModel(config)
    input1 = ff.create_tensor((b, 3, 229, 229), DataType.DT_FLOAT)
    input2 = ff.create_tensor((b, 16), DataType.DT_FLOAT)

    t1 = ff.conv2d(input1, 64, 11, 11, 4, 4, 2, 2)
    t2 = ff.dense(input2, 8, ActiMode.AC_MODE_RELU)
    ff.concat([ff.flat(t1), t2], axis=1)
    ff.compile(optimizer=SGDOptimizer(ff, lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    label = ff.label_tensor

    for lid, layer in ff.get_layers().items():
        print(f"layer {lid}: {layer}")
        for w in layer.weights:
            print(f"   weight {w.name}: dims={w.get_dims()} "
                  f"volume={w.get_volume()}")

    # label host access (print_layers.py tail): map, read, unmap
    label.inline_map(ff, config)
    label_array = label.get_array(ff, config)
    print("label:", label_array.shape, label_array.dtype)
    label.inline_unmap(ff, config)

    # weight poke (print_weight.py): conv kernel via global parameter id
    conv_w = ff.get_tensor_by_id(0)
    arr = np.full(conv_w.get_dims(), 1.2, dtype=np.float32)
    conv_w.set_weights(ff, arr)
    back = conv_w.get_weights(ff)
    print("conv kernel after set:", back.shape, float(back.ravel()[0]))
    assert np.allclose(back, 1.2)
    return ff


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
