"""MNIST MLP — the minimum end-to-end slice (reference:
examples/python/native/mnist_mlp.py: dense 512/512/10 + softmax, SGD,
sparse-CCE)."""
import numpy as np

from _common import run  # noqa: E402  (sys.path set up by _common)
from flexflow_tpu import ActiMode


def build(ff, batch_size=64):
    x = ff.create_tensor((batch_size, 784), name="mnist_input")
    t = ff.dense(x, 512, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 512, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 10)
    return x, ff.softmax(t)


def main(argv=None):
    return run(lambda ff: build(ff, ff.config.batch_size),
               [(784,)], 10, argv=argv)


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
