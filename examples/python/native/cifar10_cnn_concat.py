"""CIFAR-10 CNN with concatenated conv towers (reference:
examples/python/native/cifar10_cnn_concat.py — three 32-filter towers
concatenated on channels, then two 64-filter towers, pool, dense 512/10).
Exercises Concat fan-in through compile + the search."""
from _common import run  # noqa: E402  (sys.path set up by _common)
from flexflow_tpu import ActiMode


def _tower(ff, x, filters):
    t = ff.conv2d(x, filters, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    return ff.conv2d(t, filters, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)


def build(ff, batch_size=64):
    x = ff.create_tensor((batch_size, 3, 32, 32), name="cifar_image")
    t = ff.concat([_tower(ff, x, 32) for _ in range(3)], axis=1)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.concat([_tower(ff, t, 64) for _ in range(2)], axis=1)
    t = ff.conv2d(t, 64, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 512, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 10)
    return x, ff.softmax(t)


def main(argv=None):
    return run(lambda ff: build(ff, ff.config.batch_size),
               [(3, 32, 32)], 10, argv=argv)


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
