"""InceptionV3 (reference: examples/python/native/inception.py,
examples/cpp/InceptionV3)."""
from _common import run
from flexflow_tpu.models import build_inception_v3


def main(argv=None, num_classes=1000):
    return run(lambda ff: build_inception_v3(ff, ff.config.batch_size,
                                             num_classes=num_classes),
               [(3, 299, 299)], num_classes, argv=argv)


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
