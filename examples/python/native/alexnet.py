"""AlexNet on CIFAR-10-shaped synthetic data (reference:
examples/python/native/alexnet.py + bootcamp_demo/ff_alexnet_cifar10.py)."""
from _common import run
from flexflow_tpu.models import build_alexnet_cifar10


def main(argv=None):
    return run(lambda ff: build_alexnet_cifar10(ff, ff.config.batch_size),
               [(3, 32, 32)], 10, argv=argv)


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
