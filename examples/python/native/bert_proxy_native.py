"""BERT-Large proxy (reference: examples/python/native/bert_proxy_native.py:
12-17 — seq 512, hidden 1024, 16 heads, 24 layers; random data). Pass
--compute-dtype bf16 for the TPU mixed-precision path."""
import numpy as np

import _common  # noqa: F401
from flexflow_tpu import (AdamOptimizer, FFConfig, FFModel, LossType,
                          MetricsType)
from flexflow_tpu.models import BertConfig, build_bert


def main(argv=None, cfg=None):
    config = FFConfig()
    if argv:
        config.parse_args(argv)
    config.profiling = True
    cfg = cfg or BertConfig(batch_size=config.batch_size)
    config.batch_size = cfg.batch_size
    ff = FFModel(config)
    build_bert(ff, cfg)
    ff.compile(optimizer=AdamOptimizer(ff, alpha=1e-4),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])

    n = cfg.batch_size * 2
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, cfg.seq_len, cfg.hidden)).astype(np.float32)
    y = rng.integers(0, cfg.num_classes, size=(n,)).astype(np.int32)
    perf = ff.fit(x, y)
    print(f"train accuracy = {perf.accuracy():.4f}")
    return ff, perf


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
