"""LSTM NMT seq2seq (reference: nmt/ — embed -> 2-layer LSTM encoder/decoder
-> per-token softmax)."""
import numpy as np

import _common  # noqa: F401
from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.models import NMTConfig, build_nmt


def main(argv=None, cfg=None):
    import jax.random as jrandom

    config = FFConfig()
    if argv:
        config.parse_args(argv)
    cfg = cfg or NMTConfig(batch_size=config.batch_size)
    config.batch_size = cfg.batch_size
    ff = FFModel(config)
    build_nmt(ff, cfg)
    ff.compile(optimizer=SGDOptimizer(ff, lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    rng = np.random.default_rng(0)
    src = rng.integers(0, cfg.src_vocab,
                       size=(cfg.batch_size, cfg.src_len)).astype(np.int32)
    tgt = rng.integers(0, cfg.tgt_vocab,
                       size=(cfg.batch_size, cfg.tgt_len)).astype(np.int32)
    labels = tgt.reshape(-1)  # per-token labels: (batch*tgt_len,)
    step = ff.executor.make_train_step()
    params, opt_state = ff.params, ff.opt_state
    for i in range(4):
        params, opt_state, loss, _ = step(params, opt_state, [src, tgt],
                                          labels, jrandom.PRNGKey(i))
        print(f"step {i}: loss={float(loss):.4f}")
    ff.params, ff.opt_state = params, opt_state
    return ff


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
