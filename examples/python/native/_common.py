"""Shared runner for the native examples (reference: examples/python/native/
scripts each build a model, create dataloaders, and call fit; synthetic data
when no --dataset is given, README.md:73)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np  # noqa: E402

from flexflow_tpu import (AdamOptimizer, FFConfig, FFModel,  # noqa: E402
                          LossType, MetricsType, SGDOptimizer)


def synthetic_classification(input_shapes, num_classes, num_samples, seed=0):
    rng = np.random.default_rng(seed)
    xs = [rng.normal(size=(num_samples,) + tuple(s)).astype(np.float32)
          for s in input_shapes]
    y = rng.integers(0, num_classes, size=(num_samples,)).astype(np.int32)
    return xs, y


def run(build_fn, input_shapes, num_classes, *, optimizer="sgd",
        loss=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        epochs=None, argv=None):
    """Build via build_fn(ff) -> final tensor, then train on synthetic
    float classification data. Models with integer (embedding-id) inputs
    hand-roll their driver instead (dlrm.py, xdl.py, nmt.py)."""
    config = FFConfig()
    if argv:
        config.parse_args(argv)
    # NOTE: --profiling (per-op timing + step prints) stays opt-in via argv;
    # the THROUGHPUT line below is unconditional like the reference examples'
    # Realm-timer prints (alexnet.cc top_level_task tail)
    ff = FFModel(config)
    build_fn(ff)
    opt = (AdamOptimizer(ff, alpha=1e-3) if optimizer == "adam"
           else SGDOptimizer(ff, lr=0.01))
    ff.compile(optimizer=opt, loss_type=loss,
               metrics=[MetricsType.METRICS_ACCURACY,
                        MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY])

    num_samples = config.batch_size * 4
    xs, y = synthetic_classification(input_shapes, num_classes, num_samples)
    perf = ff.fit(xs if len(xs) > 1 else xs[0], y,
                  epochs=epochs or config.epochs)
    if ff._last_fit_time > 0:
        print(f"THROUGHPUT = {ff._last_fit_samples / ff._last_fit_time:.2f} "
              f"samples/s")
    print(f"train accuracy = {perf.accuracy():.4f} "
          f"({perf.train_correct}/{perf.train_all})")
    return ff, perf
