"""Concat + Split demo net (reference: examples/python/native/split.py —
three conv towers concatenated on channels, split back into 3, middle
branch trained). Exercises multi-output Split through compile/search."""
from _common import run  # noqa: E402  (sys.path set up by _common)
from flexflow_tpu import ActiMode


def build(ff, batch_size=64):
    x = ff.create_tensor((batch_size, 3, 32, 32), name="split_input")
    towers = [ff.conv2d(x, 32, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
              for _ in range(3)]
    t = ff.concat(towers, axis=1)
    ts = ff.split(t, 3, axis=1)
    t = ff.conv2d(ts[1], 32, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.conv2d(t, 64, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.conv2d(t, 64, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 512, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 10)
    return x, ff.softmax(t)


def main(argv=None):
    return run(lambda ff: build(ff, ff.config.batch_size),
               [(3, 32, 32)], 10, argv=argv)


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
