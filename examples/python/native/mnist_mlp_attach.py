"""MNIST MLP driven by the attach-style manual loop (reference:
examples/python/native/mnist_mlp_attach.py — per-batch
``tensor.set_tensor`` staging + explicit forward / zero_gradients /
backward / update phases instead of fit())."""
import numpy as np

import _common  # noqa: F401  (sys.path setup)
from flexflow_tpu import (ActiMode, DataType, FFConfig, FFModel, LossType,
                          MetricsType, SGDOptimizer)


def next_batch(idx, x_train, input_tensor, config, ff):
    start = idx * config.batch_size
    ff_batch = x_train[start:start + config.batch_size]
    input_tensor.set_tensor(ff, ff_batch)


def main(argv=None):
    config = FFConfig()
    if argv:
        config.parse_args(argv)
    b = config.batch_size
    ff = FFModel(config)
    input_tensor = ff.create_tensor((b, 784), DataType.DT_FLOAT)

    t = ff.dense(input_tensor, 512, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 512, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 10)
    ff.softmax(t)

    ff.compile(optimizer=SGDOptimizer(ff, lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    label_tensor = ff.label_tensor

    # synthetic linearly-separable stand-in for the mnist arrays
    rng = np.random.default_rng(0)
    num_samples = b * 8
    x_train = rng.normal(size=(num_samples, 784)).astype(np.float32)
    w = rng.normal(size=(784, 10)).astype(np.float32)
    y_train = np.argmax(x_train @ w, axis=1).astype(np.int32)[:, None]

    ff.init_layers()
    ts_start = config.get_current_time()
    for epoch in range(config.epochs):
        ff.reset_metrics()
        for it in range(num_samples // b):
            next_batch(it, x_train, input_tensor, config, ff)
            next_batch(it, y_train, label_tensor, config, ff)
            ff.forward()
            ff.zero_gradients()
            ff.backward()
            ff.update()
    run_time = 1e-6 * (config.get_current_time() - ts_start)
    print(f"epochs {config.epochs}, ELAPSED TIME = {run_time:.4f}s, "
          f"THROUGHPUT = {num_samples * config.epochs / run_time:.2f} "
          "samples/s")

    # host readback of a staged tensor and a trained weight (the attach
    # example tail prints both via inline_map/get_array)
    label_tensor.inline_map(ff, config)
    print("label batch:", label_tensor.get_array(ff, config).shape)
    label_tensor.inline_unmap(ff, config)
    dense1 = ff.get_layer_by_id(0)
    print("dense1 kernel:", dense1.get_weight_tensor().get_weights(ff).shape)
    return ff


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
