"""ResNeXt-50 (reference: examples/cpp/resnext50)."""
from _common import run
from flexflow_tpu.models import build_resnext50


def main(argv=None, image_size=64, num_classes=200):
    return run(lambda ff: build_resnext50(ff, ff.config.batch_size,
                                          image_size=image_size,
                                          num_classes=num_classes),
               [(3, image_size, image_size)], num_classes, argv=argv)


if __name__ == "__main__":
    import sys

    main(sys.argv[1:], image_size=224, num_classes=1000)
