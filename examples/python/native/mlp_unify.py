"""MLP_Unify (reference: examples/cpp/MLP_Unify/mlp.cc — two 8x8192 dense
towers summed; the OSDI'22 MLP benchmark)."""
import numpy as np

import _common  # noqa: F401
from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models import build_mlp_unify


def main(argv=None, hidden_dims=(8192,) * 8, input_dim=1024):
    config = FFConfig()
    if argv:
        config.parse_args(argv)
    config.profiling = True
    ff = FFModel(config)
    bs = config.batch_size
    build_mlp_unify(ff, bs, input_dim=input_dim, hidden_dims=hidden_dims)
    ff.compile(optimizer=SGDOptimizer(ff, lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    n = bs * 2
    rng = np.random.default_rng(0)
    xs = [rng.normal(size=(n, input_dim)).astype(np.float32) for _ in range(2)]
    y = rng.integers(0, hidden_dims[-1], size=(n,)).astype(np.int32)
    perf = ff.fit(xs, y)
    print(f"train accuracy = {perf.accuracy():.4f}")
    return ff, perf


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
