"""GPT-2 generation through the serving engine (ISSUE 6, docs/serving.md).

Builds the decoder-only GPT-2 family model (models/gpt2.py), compiles it,
and serves a small batch of prompts through the continuous-batching
prefill/decode engine — greedy by default, temperature/top-k sampling via
flags below. Weights are randomly initialized (this demonstrates the
serving path, not a pretrained checkpoint; load real weights via
Layer.set_weights / copy_torch_weights first for meaningful text).

Run:  python examples/python/native/gpt2_generate.py \
          --max-decode-len 128 --max-inflight 4 [-b 8] [--trace-file t.json]
Sampling knobs (script-local): --temperature T --top-k K --new-tokens N
"""
import sys

import _common  # noqa: F401  (repo-root sys.path bootstrap)
import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.models.gpt2 import GPT2Config, build_gpt2
from flexflow_tpu.serving import ServingEngine


def top_level_task():
    # script-local sampling flags (everything else is FFConfig's)
    argv = sys.argv[1:]

    def flag(name, default, cast):
        return cast(argv[argv.index(name) + 1]) if name in argv else default

    temperature = flag("--temperature", 0.0, float)
    top_k = flag("--top-k", 0, int)
    new_tokens = flag("--new-tokens", 24, int)

    config = FFConfig()
    cfg = GPT2Config.tiny(batch_size=config.batch_size)
    # the position table bounds decodable length; keep them consistent
    cfg.seq_len = max(cfg.seq_len, config.max_decode_len)
    ff = FFModel(config)
    build_gpt2(ff, cfg)
    ff.compile(optimizer=SGDOptimizer(ff),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    rng = np.random.default_rng(config.seed)
    prompts = [rng.integers(0, cfg.vocab_size,
                            size=int(rng.integers(4, 12))).tolist()
               for _ in range(max(config.max_inflight, 4))]
    # prompt + generation must fit the decode ring (--max-decode-len)
    new_tokens = min(new_tokens,
                     config.max_decode_len - max(len(p) for p in prompts))
    eng = ServingEngine(ff)
    outs = eng.generate(prompts, max_new_tokens=new_tokens,
                        temperature=temperature, top_k=top_k,
                        seed=config.seed)
    for i, (p, o) in enumerate(zip(prompts, outs)):
        print(f"request {i}: prompt={p[:8]}... -> generated={o}")
    st = eng.stats
    print(f"SERVING {st.tokens_generated} tokens in {st.wall_s:.2f}s "
          f"({st.tokens_per_s():.1f} tokens/s, "
          f"occupancy {st.batch_occupancy(eng.n_slots):.2f}, "
          f"p99 {st.p99_token_ms():.2f} ms)")


if __name__ == "__main__":
    top_level_task()
