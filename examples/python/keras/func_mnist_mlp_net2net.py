"""Net2Net teacher->student weight transfer (reference:
examples/python/keras/func_mnist_mlp_net2net.py — train a teacher MLP, copy
its weights into a same-shape student via layer get_weights/set_weights,
and verify the student starts at the teacher's accuracy)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np  # noqa: E402

from flexflow_tpu.frontends.keras import (Activation, Dense, Input,  # noqa: E402
                                          Model, datasets)


def main(argv=None, num_samples=512, teacher_epochs=None):
    num_classes = 10
    (x_train, y_train), _ = datasets.mnist.load_data()
    x_train = (x_train.reshape(-1, 784).astype("float32") / 255)[:num_samples]
    y_train = np.reshape(y_train.astype("int32"),
                         (len(y_train), 1))[:num_samples]

    # teacher
    inp1 = Input(shape=(784,))
    d1 = Dense(128, activation="relu")
    d2 = Dense(128, activation="relu")
    d3 = Dense(num_classes)
    out = Activation("softmax")(d3(d2(d1(inp1))))
    teacher = Model(inp1, out)
    if argv:
        teacher.ffconfig.parse_args(argv)
    teacher.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                    metrics=("accuracy",))
    b = teacher.ffconfig.batch_size
    n = (len(x_train) // b) * b
    teacher.fit(x_train[:n], y_train[:n],
                epochs=teacher_epochs or teacher.ffconfig.epochs)
    t_eval = teacher.evaluate(x_train[:n], y_train[:n])

    d1_kernel, d1_bias = d1.get_weights(teacher)
    d2_kernel, d2_bias = d2.get_weights(teacher)
    d3_kernel, d3_bias = d3.get_weights(teacher)

    # student: same shape, weights transferred instead of re-initialized
    inp2 = Input(shape=(784,))
    sd1 = Dense(128, activation="relu")
    sd2 = Dense(128, activation="relu")
    sd3 = Dense(num_classes)
    sout = Activation("softmax")(sd3(sd2(sd1(inp2))))
    student = Model(inp2, sout)
    student.ffconfig.batch_size = b
    student.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                    metrics=("accuracy",))
    sd1.set_weights(student, d1_kernel, d1_bias)
    sd2.set_weights(student, d2_kernel, d2_bias)
    sd3.set_weights(student, d3_kernel, d3_bias)

    s_eval = student.evaluate(x_train[:n], y_train[:n])
    print(f"teacher acc = {t_eval.get_accuracy():.2f}%, "
          f"student (transferred, untrained) acc = "
          f"{s_eval.get_accuracy():.2f}%")
    assert abs(t_eval.get_accuracy() - s_eval.get_accuracy()) < 1e-3
    return teacher, student


if __name__ == "__main__":
    main(sys.argv[1:])
