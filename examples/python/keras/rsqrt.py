"""Backend functional ops + node arithmetic (reference:
examples/python/keras/rsqrt.py — out = rsqrt(x + inp2))."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np  # noqa: E402

from flexflow_tpu.frontends.keras import Dense, Input, Model  # noqa: E402
from flexflow_tpu.frontends.keras_backend import rsqrt  # noqa: E402


def main(argv=None):
    inp1 = Input(shape=(32,))
    inp2 = Input(shape=(20,))
    x = Dense(20, activation="relu")(inp1)
    out = rsqrt(x + inp2)

    model = Model([inp1, inp2], out)
    if argv:
        model.ffconfig.parse_args(argv)
    model.compile(optimizer={"class_name": "Adam",
                             "config": {"learning_rate": 0.001}},
                  loss="mean_squared_error",
                  metrics=("mean_squared_error",))
    n = model.ffconfig.batch_size * 4
    rng = np.random.default_rng(0)
    perf = model.fit(
        x=[rng.standard_normal((n, 32)).astype(np.float32),
           np.ones((n, 20), np.float32)],
        y=rng.standard_normal((n, 20)).astype(np.float32),
        epochs=model.ffconfig.epochs)
    print(f"rsqrt example MSE = {perf.mean('mse_loss'):.4f}")
    return model, perf


if __name__ == "__main__":
    main(sys.argv[1:])
