"""Keras-frontend MNIST MLP with the mnist dataset loader and callbacks
(reference: examples/python/keras/seq_mnist_mlp.py — mnist.load_data,
VerifyMetrics/EpochVerifyMetrics, LR scheduling via callbacks.py)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np  # noqa: E402

from flexflow_tpu.frontends.keras import (Activation, Dense, Dropout,  # noqa: E402
                                          Input, Sequential)
from flexflow_tpu.frontends.keras import callbacks, datasets  # noqa: E402


def main(argv=None, num_samples=4096):
    (x_train, y_train), _ = datasets.mnist.load_data()
    x_train = (x_train.reshape(-1, 784).astype("float32") / 255)[:num_samples]
    y_train = np.reshape(y_train.astype("int32"),
                         (len(y_train), 1))[:num_samples]

    from flexflow_tpu.frontends.keras import GlorotUniform, Zeros

    model = Sequential([
        Input(shape=(784,)),
        Dense(512, activation="relu", kernel_initializer=GlorotUniform(123),
              bias_initializer=Zeros()),
        Dropout(0.2),
        Dense(512, activation="relu"),
        Dropout(0.2),
        Dense(10),
        Activation("softmax"),
    ])
    if argv:
        model.ffconfig.parse_args(argv)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=("accuracy",))
    n = (len(x_train) // model.ffconfig.batch_size) * \
        model.ffconfig.batch_size
    cbs = [callbacks.LearningRateScheduler(lambda e: 0.01 * 0.9 ** e),
           callbacks.VerifyMetrics(0.0)]
    perf = model.fit(x_train[:n], y_train[:n],
                     epochs=model.ffconfig.epochs, callbacks=cbs)
    print(f"train accuracy = {perf.accuracy():.4f}")
    return model, perf


if __name__ == "__main__":
    main(sys.argv[1:])
