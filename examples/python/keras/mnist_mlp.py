"""Keras-frontend MNIST MLP (reference: examples/python/keras/seq_mnist_mlp.py)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np  # noqa: E402

from flexflow_tpu.frontends.keras import (Activation, Dense, Input,  # noqa: E402
                                          Sequential)


def main(argv=None):
    model = Sequential([
        Input(shape=(784,)),
        Dense(512, activation="relu"),
        Dense(512, activation="relu"),
        Dense(10),
        Activation("softmax"),
    ])
    if argv:
        model.ffconfig.parse_args(argv)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=("accuracy",))

    bs = model.ffconfig.batch_size
    rng = np.random.default_rng(0)
    x = rng.normal(size=(bs * 4, 784)).astype(np.float32)
    y = rng.integers(0, 10, size=(bs * 4,)).astype(np.int32)
    perf = model.fit(x, y, epochs=model.ffconfig.epochs)
    print(f"train accuracy = {perf.accuracy():.4f}")
    return model, perf


if __name__ == "__main__":
    main(sys.argv[1:])
