"""Functional CIFAR-10 CNN with concatenated towers (reference:
examples/python/keras/func_cifar10_cnn_concat.py — Concatenate merge of
three conv towers, cifar10 loader, VerifyMetrics callback)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np  # noqa: E402

from flexflow_tpu.frontends.keras import (Activation, Conv2D, Dense,  # noqa: E402
                                          Flatten, Input, MaxPooling2D,
                                          Model, callbacks, concatenate,
                                          datasets)


def main(argv=None, num_samples=512):
    (x_train, y_train), _ = datasets.cifar10.load_data(num_samples)
    x_train = x_train.astype("float32") / 255
    y_train = np.reshape(y_train.astype("int32"), (len(y_train), 1))

    inp = Input(shape=(3, 32, 32))
    towers = []
    for _ in range(3):
        t = Conv2D(32, (3, 3), padding="same", activation="relu")(inp)
        towers.append(Conv2D(32, (3, 3), padding="same",
                             activation="relu")(t))
    t = concatenate(towers, axis=1)
    t = MaxPooling2D((2, 2), strides=(2, 2))(t)
    t = Conv2D(64, (3, 3), padding="same", activation="relu")(t)
    t = MaxPooling2D((2, 2), strides=(2, 2))(t)
    t = Flatten()(t)
    t = Dense(256, activation="relu")(t)
    t = Dense(10)(t)
    out = Activation("softmax")(t)

    model = Model(inp, out)
    if argv:
        model.ffconfig.parse_args(argv)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=("accuracy",))
    n = (len(x_train) // model.ffconfig.batch_size) * \
        model.ffconfig.batch_size
    perf = model.fit(x_train[:n], y_train[:n],
                     epochs=model.ffconfig.epochs,
                     callbacks=[callbacks.VerifyMetrics(0.0)])
    print(f"train accuracy = {perf.accuracy():.4f}")
    return model, perf


if __name__ == "__main__":
    main(sys.argv[1:])
