"""Keras-frontend CIFAR-10 CNN (reference: examples/python/keras/
seq_cifar10_cnn.py)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np  # noqa: E402

from flexflow_tpu.frontends.keras import (Activation, Conv2D, Dense,  # noqa: E402
                                          Flatten, Input, MaxPooling2D,
                                          Sequential)


def main(argv=None):
    model = Sequential([
        Input(shape=(3, 32, 32)),
        Conv2D(32, (3, 3), padding="same", activation="relu"),
        Conv2D(32, (3, 3), padding="same", activation="relu"),
        MaxPooling2D((2, 2)),
        Conv2D(64, (3, 3), padding="same", activation="relu"),
        Conv2D(64, (3, 3), padding="same", activation="relu"),
        MaxPooling2D((2, 2)),
        Flatten(),
        Dense(512, activation="relu"),
        Dense(10),
        Activation("softmax"),
    ])
    if argv:
        model.ffconfig.parse_args(argv)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=("accuracy",))
    bs = model.ffconfig.batch_size
    rng = np.random.default_rng(0)
    x = rng.normal(size=(bs * 2, 3, 32, 32)).astype(np.float32)
    y = rng.integers(0, 10, size=(bs * 2,)).astype(np.int32)
    perf = model.fit(x, y, epochs=model.ffconfig.epochs)
    print(f"train accuracy = {perf.accuracy():.4f}")
    return model, perf


if __name__ == "__main__":
    main(sys.argv[1:])
