"""Keras-frontend CIFAR-10 CNN with the cifar10 dataset loader and a
verification callback (reference: examples/python/keras/seq_cifar10_cnn.py —
cifar10.load_data + VerifyMetrics)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np  # noqa: E402

from flexflow_tpu.frontends.keras import (Activation, Conv2D, Dense,  # noqa: E402
                                          Flatten, Input, MaxPooling2D,
                                          Sequential)
from flexflow_tpu.frontends.keras import callbacks, datasets  # noqa: E402


def main(argv=None, num_samples=512):
    model = Sequential([
        Input(shape=(3, 32, 32)),
        Conv2D(32, (3, 3), padding="same", activation="relu"),
        Conv2D(32, (3, 3), padding="same", activation="relu"),
        MaxPooling2D((2, 2)),
        Conv2D(64, (3, 3), padding="same", activation="relu"),
        Conv2D(64, (3, 3), padding="same", activation="relu"),
        MaxPooling2D((2, 2)),
        Flatten(),
        Dense(512, activation="relu"),
        Dense(10),
        Activation("softmax"),
    ])
    if argv:
        model.ffconfig.parse_args(argv)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=("accuracy",))
    (x_train, y_train), _ = datasets.cifar10.load_data()
    x = (x_train.astype("float32") / 255)[:num_samples]
    y = y_train.astype("int32").reshape(-1, 1)[:num_samples]
    n = (len(x) // model.ffconfig.batch_size) * model.ffconfig.batch_size
    perf = model.fit(x[:n], y[:n], epochs=model.ffconfig.epochs,
                     callbacks=[callbacks.VerifyMetrics(0.0)])
    print(f"train accuracy = {perf.accuracy():.4f}")
    return model, perf


if __name__ == "__main__":
    main(sys.argv[1:])
