"""Identity loss over a backend reduce_sum (reference:
examples/python/keras/identity_loss.py — the model output IS the loss)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np  # noqa: E402

from flexflow_tpu.frontends import keras_backend as backend  # noqa: E402
from flexflow_tpu.frontends.keras import Dense, Input, Model  # noqa: E402


def main(argv=None):
    input0 = Input(shape=(32,))
    x0 = Dense(20, activation="relu")(input0)
    out = backend.sum(x0, axis=1)  # (B,)

    model = Model(input0, out)
    if argv:
        model.ffconfig.parse_args(argv)
    model.compile(optimizer={"class_name": "Adam",
                             "config": {"learning_rate": 0.01}},
                  loss="identity", metrics=("mean_absolute_error",))
    n = model.ffconfig.batch_size * 4
    rng = np.random.default_rng(0)
    perf = model.fit(x=rng.standard_normal((n, 32)).astype(np.float32),
                     y=np.zeros((n,), np.float32),
                     epochs=model.ffconfig.epochs)
    print("identity-loss example trained")
    return model, perf


if __name__ == "__main__":
    main(sys.argv[1:])
