"""Keras-frontend Reuters topic-classification MLP with dataset loader,
Tokenizer preprocessing and callbacks (reference:
examples/python/keras/seq_reuters_mlp.py)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np  # noqa: E402

from flexflow_tpu.frontends.keras import (Activation, Dense,  # noqa: E402
                                          Input, Sequential)
from flexflow_tpu.frontends.keras import callbacks  # noqa: E402
from flexflow_tpu.frontends.keras import datasets  # noqa: E402
from flexflow_tpu.frontends.keras import preprocessing  # noqa: E402


def main(argv=None, max_words=1000, epochs=5):
    print("Loading data...")
    (x_train, y_train), (x_test, y_test) = datasets.reuters.load_data(
        num_words=max_words, test_split=0.2)
    print(len(x_train), "train sequences")
    num_classes = int(np.max(y_train)) + 1
    print(num_classes, "classes")

    print("Vectorizing sequence data...")
    tokenizer = preprocessing.text.Tokenizer(num_words=max_words)
    x_train = tokenizer.sequences_to_matrix(x_train, mode="binary")
    x_train = x_train.astype("float32")
    y_train = np.reshape(y_train.astype("int32"), (len(y_train), 1))

    model = Sequential([
        Input(shape=(max_words,)),
        Dense(512, activation="relu"),
        Dense(num_classes),
        Activation("softmax"),
    ])
    if argv:
        model.ffconfig.parse_args(argv)
    n = (len(x_train) // model.ffconfig.batch_size) * \
        model.ffconfig.batch_size
    model.compile(optimizer={"class_name": "Adam",
                             "config": {"learning_rate": 0.01}},
                  loss="sparse_categorical_crossentropy",
                  metrics=("accuracy",))
    perf = model.fit(x_train[:n], y_train[:n], epochs=epochs,
                     callbacks=[callbacks.VerifyMetrics(0.0)])
    print(f"train accuracy = {perf.accuracy():.4f}")
    return model, perf


if __name__ == "__main__":
    print("Sequential model, reuters mlp")
    main(sys.argv[1:])
