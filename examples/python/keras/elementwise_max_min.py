"""Maximum/Minimum merge layers through the functional keras API
(reference: examples/python/keras/elementwise_max_min.py)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np  # noqa: E402

from flexflow_tpu.frontends.keras import (Dense, Input, Maximum,  # noqa: E402
                                          Minimum, Model)


def _run(merge_cls, argv=None):
    input0 = Input(shape=(32,))
    input1 = Input(shape=(10,))
    x0 = Dense(20, activation="relu")(input0)
    x1 = Dense(20, activation="relu")(input1)
    f0 = merge_cls()([x0, x1])
    out = Dense(1)(f0)

    model = Model([input0, input1], out)
    if argv:
        model.ffconfig.parse_args(argv)
    model.compile(optimizer={"class_name": "Adam",
                             "config": {"learning_rate": 0.001}},
                  loss="mean_squared_error",
                  metrics=("mean_squared_error",))
    n = model.ffconfig.batch_size * 4
    rng = np.random.default_rng(0)
    return model.fit(
        x=[rng.standard_normal((n, 32)).astype(np.float32),
           rng.standard_normal((n, 10)).astype(np.float32)],
        y=rng.standard_normal((n, 1)).astype(np.float32),
        epochs=2)


def elementwise_max(argv=None):
    return _run(Maximum, argv)


def elementwise_min(argv=None):
    return _run(Minimum, argv)


if __name__ == "__main__":
    elementwise_max(sys.argv[1:])
    elementwise_min(sys.argv[1:])
    print("elementwise max/min OK")
