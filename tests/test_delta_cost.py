"""Delta-cost search engine (ISSUE 2): memoized op-cost tables, λ remix,
incremental re-costing after rewrites, and the self-check equivalence gate.

The invariant under test everywhere: caching/delta paths are pure
accelerations — the chosen strategy and its simulated cost are IDENTICAL
to full re-costing (``Simulator(cost_cache_size=0)``), and stale entries
can never be served across ``set_axis_topology`` / calibration updates."""
import numpy as np
import pytest

from flexflow_tpu import ActiMode, FFConfig, FFModel, LossType
from flexflow_tpu.models.bert import BertConfig, build_bert
from flexflow_tpu.search.machine_model import TPUMachineModel
from flexflow_tpu.search.simulator import (SELFCHECK_ENV, OpSharding,
                                           Simulator)
from flexflow_tpu.search.substitution import builtin_xfers
from flexflow_tpu.search.unity import (best_first_optimize, dp_assign,
                                       unity_search)


def _bert_tiny_pcg(batch=8):
    config = FFConfig()
    config.batch_size = batch
    ff = FFModel(config)
    build_bert(ff, BertConfig.tiny(batch_size=batch))
    return ff.create_pcg(), config


def _mlp_pcg(batch=64, width=1024, hidden=4096):
    config = FFConfig()
    config.batch_size = batch
    ff = FFModel(config)
    x = ff.create_tensor((batch, width))
    t = ff.dense(x, hidden)
    t = ff.relu(t)
    t = ff.dense(t, width)
    ff.softmax(ff.dense(t, 8))
    ff.compile(loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff.create_pcg(), config


def _linear_node(pcg):
    node = next(n for n in pcg.compute_nodes()
                if n.op.op_type.name == "OP_LINEAR")
    in_shapes = [pcg.nodes[g].out_shapes[i] for g, i in node.inputs]
    return node, in_shapes


def _shape_signature(pcg, assignment):
    """Guid-free fingerprint of a costed graph + assignment (the two runs
    being compared build structurally identical graphs with different
    guids)."""
    return sorted(
        (n.op.op_type.name, tuple(n.out_shapes[0]) if n.out_shapes else (),
         assignment[n.guid].kind, assignment[n.guid].dp, assignment[n.guid].tp)
        for n in pcg.compute_nodes())


# --------------------------------------------------------------- op-cost LRU
def test_op_cost_cache_returns_identical_metrics():
    pcg, _ = _mlp_pcg()
    node, in_shapes = _linear_node(pcg)
    m = TPUMachineModel.from_generation("v5e", 8)
    sim = Simulator(m)
    sh = OpSharding(dp=4, tp=2, kind="col")
    c1 = sim.op_cost(node, in_shapes, sh)
    assert (sim.cost_cache_hits, sim.cost_cache_misses) == (0, 1)
    c2 = sim.op_cost(node, in_shapes, sh)
    assert (sim.cost_cache_hits, sim.cost_cache_misses) == (1, 1)
    assert c1 == c2
    # and the cached value equals a cache-disabled simulator's
    sim_nc = Simulator(m, cost_cache_size=0)
    assert sim_nc.op_cost(node, in_shapes, sh) == c1
    assert not sim_nc._cost_cache  # disabled: nothing stored


def test_identical_layers_share_cache_entries():
    """Keys are guid-independent (op params + shapes), so BERT's repeated
    layers hit the same entries — the reference's per-(op, view) cache:
    doubling the layer count must add ZERO cache misses."""
    m = TPUMachineModel.from_generation("v5e", 8)
    misses = []
    for layers in (2, 4):
        config = FFConfig()
        config.batch_size = 8
        ff = FFModel(config)
        build_bert(ff, BertConfig(batch_size=8, seq_len=128, hidden=256,
                                  num_heads=4, num_layers=layers,
                                  intermediate=512))
        pcg = ff.create_pcg()
        sim = Simulator(m)
        dp_assign(pcg, sim, 2, 4, 8)
        misses.append(sim.cost_cache_misses)
        assert sim.cost_cache_hits > 0
    assert misses[0] == misses[1], misses


# ----------------------------------------------------------------- λ remix
def test_lambda_remix_equals_full_costing():
    """Each λ re-runs only the DP mix over cached entries AND lands on the
    exact strategy a from-scratch full costing picks at that λ."""
    pcg, _ = _bert_tiny_pcg()
    m = TPUMachineModel.from_generation("v5e", 8)
    sim = Simulator(m)
    dp_assign(pcg, sim, 2, 4, 8, lam=1.0)  # populates the tables
    for lam in (0.6, 0.2):
        a, s, t = dp_assign(pcg, sim, 2, 4, 8, lam=lam)
        sim_nc = Simulator(m, cost_cache_size=0)
        a_f, s_f, t_f = dp_assign(pcg, sim_nc, 2, 4, 8, lam=lam)
        assert a == a_f and s == s_f
        assert t == t_f


# ----------------------------------------- incremental re-cost of rewrites
def test_rewrite_delta_recost_equals_full(monkeypatch):
    """best_first_optimize's incremental DP (parent table + dirty set)
    chooses the same rewritten graph at the same simulated cost as full
    re-costing, with the self-check gate active the whole time."""
    monkeypatch.setenv(SELFCHECK_ENV, "1")
    m = TPUMachineModel.from_generation("v5e", 8)
    results = []
    for cache in (1 << 17, 0):
        pcg, _ = _mlp_pcg()
        sim = Simulator(m, cost_cache_size=cache)
        g, a, s, t = best_first_optimize(
            pcg, sim, dp=8, tp=1, batch=64, xfers=builtin_xfers(),
            budget=16, alpha=1.05)
        assert len(g.compute_nodes()) < len(pcg.compute_nodes())  # fused
        results.append((t, _shape_signature(g, a)))
    (t_delta, sig_delta), (t_full, sig_full) = results
    assert t_delta == t_full
    assert sig_delta == sig_full


def test_selfcheck_catches_stale_cache_entries(monkeypatch):
    """The FLEXFLOW_TPU_SEARCH_SELFCHECK gate re-derives every hit: a
    calibration edit smuggled past invalidate_cost_tables() must raise."""
    pcg, _ = _mlp_pcg()
    node, in_shapes = _linear_node(pcg)
    m = TPUMachineModel.from_generation("v5e", 8)
    sim = Simulator(m)
    sh = OpSharding(dp=8)
    sim.op_cost(node, in_shapes, sh)  # populate
    # bypass the knob properties: mutate the per-key ratios directly
    sim._key_calibration[sim._op_key(node, in_shapes)] = 7.0
    monkeypatch.setenv(SELFCHECK_ENV, "1")
    with pytest.raises(AssertionError, match="selfcheck"):
        sim.op_cost(node, in_shapes, sh)


def test_graphxfer_apply_returns_touched_guids():
    pcg, _ = _mlp_pcg()
    xfer = next(x for x in builtin_xfers() if x.name == "linear_relu_fuse")
    match = xfer.find_matches(pcg)[0]
    g2, touched = xfer.apply(pcg, match, return_touched=True)
    assert touched and all(t in g2.nodes for t in touched)
    # the touched set is exactly the dst pattern's new nodes
    assert len(touched) == len(xfer.dst)
    # 2-arg call keeps returning the graph alone (API compat)
    g3 = xfer.apply(pcg, match)
    assert not isinstance(g3, tuple)


# ------------------------------------------------- whole-search equivalence
def test_unity_search_cached_equals_uncached_on_model_zoo():
    """End-to-end equivalence gate on the model-zoo graphs: same chosen
    mesh, same simulated time and memory, with and without the engine."""
    m = TPUMachineModel.from_generation("v5e", 8)
    for build in (_bert_tiny_pcg, _mlp_pcg):
        pcg, config = build()
        runs = []
        for cache in (1 << 17, 0):
            sim = Simulator(m, cost_cache_size=cache)
            res = unity_search(pcg.copy(), config, 8, machine=m,
                               return_result=True, insert_ir_nodes=False,
                               sim=sim)
            runs.append(res)
        a, b = runs
        assert a.mesh_shape == b.mesh_shape
        assert a.sim_time == b.sim_time
        assert a.sim_memory == b.sim_memory
        assert getattr(a.strategy, "pipeline", None) == \
            getattr(b.strategy, "pipeline", None)


def test_unity_memory_search_equivalence_with_dcn(monkeypatch):
    """The λ binary search over a 2-host machine (DCN placements in play),
    under the self-check gate, matches full re-costing exactly."""
    monkeypatch.setenv(SELFCHECK_ENV, "1")
    config_budget_mb = 25
    m = TPUMachineModel.from_generation("v5e", 8, num_hosts=2)
    runs = []
    for cache in (1 << 17, 0):
        config = FFConfig()
        config.batch_size = 2048
        ff = FFModel(config)
        x = ff.create_tensor((2048, 1024))
        t = x
        for _ in range(3):
            t = ff.dense(t, 1024, ActiMode.AC_MODE_RELU)
        ff.softmax(ff.dense(t, 8))
        ff.compile(loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        pcg = ff.create_pcg()
        config.device_memory_mb = config_budget_mb
        config.perform_memory_search = True
        sim = Simulator(m, cost_cache_size=cache)
        res = unity_search(pcg.copy(), config, 8, machine=m,
                           return_result=True, insert_ir_nodes=False,
                           sim=sim)
        runs.append(res)
    a, b = runs
    assert a.mesh_shape == b.mesh_shape and a.dcn == b.dcn
    assert a.sim_time == b.sim_time and a.sim_memory == b.sim_memory
    assert a.sim_memory <= config_budget_mb * 2 ** 20


# ---------------------------------------------------------- invalidation
def test_calibration_update_flushes_cost_tables():
    pcg, _ = _mlp_pcg()
    node, in_shapes = _linear_node(pcg)
    m = TPUMachineModel.from_generation("v5e", 8)
    sim = Simulator(m)
    sh = OpSharding(dp=8)
    c1 = sim.op_cost(node, in_shapes, sh)
    sim.calibrate(measured_step=2.0, simulated_step=1.0)  # calibration x2
    assert not sim._cost_cache and not sim._table_cache  # flushed
    c2 = sim.op_cost(node, in_shapes, sh)
    assert c2.forward_time > c1.forward_time
    # the recalibrated cached value equals a fresh simulator's
    fresh = Simulator(m, cost_cache_size=0)
    fresh.calibration = 2.0
    assert fresh.op_cost(node, in_shapes, sh) == c2


def test_memory_knob_update_flushes_dp_tables():
    """activation_el (set by calibrate_from_pcg / bench) reshapes the
    resident-memory term of the cached DP tables — setting it must flush
    them, and the refreshed λ<1 result must equal a fresh simulator's."""
    pcg, _ = _bert_tiny_pcg()
    m = TPUMachineModel.from_generation("v5e", 8)
    sim = Simulator(m)
    dp_assign(pcg, sim, 2, 4, 8, lam=0.5)
    assert sim._table_cache
    sim.activation_el = 2  # bf16 activations
    assert not sim._table_cache and not sim._cost_cache
    a, s, t = dp_assign(pcg, sim, 2, 4, 8, lam=0.5)
    fresh = Simulator(m, cost_cache_size=0)
    fresh.activation_el = 2
    a_f, s_f, t_f = dp_assign(pcg, fresh, 2, 4, 8, lam=0.5)
    assert a == a_f and s == s_f and t == t_f


def test_set_axis_topology_never_serves_stale_entries():
    """The DCN topology is part of every cache key: costs priced at one
    placement are never replayed at another, and flipping back re-serves
    the original entry unchanged."""
    pcg, _ = _mlp_pcg()
    node, in_shapes = _linear_node(pcg)
    m = TPUMachineModel.from_generation("v5e", 8, num_hosts=2)
    sim = Simulator(m)
    sh = OpSharding(dp=4, tp=2, kind="row")  # row-parallel: comm depends
    c_flat = sim.op_cost(node, in_shapes, sh)  # on the tp axis's DCN factor
    sim.set_axis_topology(dp_dcn=1, tp_dcn=2)
    c_dcn = sim.op_cost(node, in_shapes, sh)
    assert c_dcn.comm_time > c_flat.comm_time  # DCN phase priced, not stale
    fresh = Simulator(m, cost_cache_size=0)
    fresh.set_axis_topology(dp_dcn=1, tp_dcn=2)
    assert fresh.op_cost(node, in_shapes, sh) == c_dcn
    sim.set_axis_topology(1, 1)
    assert sim.op_cost(node, in_shapes, sh) == c_flat
