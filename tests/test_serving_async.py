"""Async double-buffered serving runtime (ISSUE 17,
flexflow_tpu/serving/engine.py `_AsyncServeLoop`, docs/serving.md
"Async runtime"): `--serve-loop async` dispatches decode step k+1 while
step k's (tokens, ok_vec) transfer is in flight and commits at arrival,
one step behind dispatch. The sync loop is the reference
implementation; under exact decode the async loop must match it
stream-for-stream BITWISE — solo, co-batched, prefix-hit, chunked
prefill, speculative — including under the chaos harness (poison
quarantine, mid-decode kill + migration, SIGTERM drain, fleet hedge),
with at most one blocking host transfer per committed decode step
(white-box `host_syncs` counter) and host work overlapped with device
steps accounted in `host_overlap_s`, never in the overhead numerator.
All deterministic on CPU."""
import signal

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.models.gpt2 import GPT2Config, build_gpt2
from flexflow_tpu.resilience import ChaosPlan, FleetChaosPlan
from flexflow_tpu.serving import ServingEngine, ServingFleet


def _build(num_layers=2, hidden=64, seed=42):
    # the tiny family (hidden 64 / 4 heads) at seq 64 so prompts can
    # span KV blocks — prefix hits and chunked prefill need the room
    cfg = GPT2Config(batch_size=8, seq_len=64, hidden=hidden,
                     num_heads=4, num_layers=num_layers,
                     intermediate=2 * hidden, vocab_size=100)
    config = FFConfig()
    config.batch_size = cfg.batch_size
    config.seed = seed
    ff = FFModel(config)
    build_gpt2(ff, cfg)
    ff.compile(optimizer=SGDOptimizer(ff),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff, cfg


@pytest.fixture(scope="module")
def gpt2():
    return _build()


def _prompts(n, seed=0, lo=3, hi=8):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 99, size=int(rng.integers(lo, hi))).tolist()
            for _ in range(n)]


def _engine(ff, loop, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("max_decode_len", 64)
    kw.setdefault("exact_decode", True)
    kw.setdefault("kv_block_size", 8)
    return ServingEngine(ff, serve_loop=loop, **kw)


def _both(ff, prompts, max_new=6, gen_kw=None, **kw):
    """Run the same trace through both loops; return (sync_outs,
    async_outs, sync_stats, async_stats)."""
    outs, stats = {}, {}
    for loop in ("sync", "async"):
        eng = _engine(ff, loop, **kw)
        outs[loop] = eng.generate(prompts, max_new_tokens=max_new,
                                  **(gen_kw or {}))
        stats[loop] = eng.stats
    return outs["sync"], outs["async"], stats["sync"], stats["async"]


# ------------------------------------------------------------ clean parity
def test_async_matches_sync_solo_greedy(gpt2):
    ff, _ = gpt2
    s, a, _, _ = _both(ff, _prompts(1, seed=1), n_slots=1)
    assert s == a and all(len(x) == 6 for x in s)


def test_async_matches_sync_cobatched_sampled(gpt2):
    """Temperature + top-k sampling, 8 streams through 3 slots: rng
    streams key on (tag, tokens emitted), and at dispatch k+1 a slot
    with an uncommitted in-flight token samples at len(generated)+1 —
    a later-discarded draw can never desync a stream."""
    ff, _ = gpt2
    s, a, ss, sa = _both(ff, _prompts(8, seed=2), max_new=8,
                         gen_kw={"temperature": 0.7, "top_k": 5,
                                 "seed": 3})
    assert s == a, "sampled streams diverged between loops"
    assert ss.outcomes == sa.outcomes == {"ok": 8}


def test_async_matches_sync_prefix_hit(gpt2):
    """Shared-system-prompt trace with the radix trie live: the async
    loop's commit-at-arrival must not disturb trie insert/hit order."""
    ff, _ = gpt2
    sys_p = list(np.random.default_rng(7).integers(1, 99, size=20))
    prompts = [sys_p + [5, 6, 7], sys_p + [8, 9], sys_p + [5, 6, 1, 2]]
    s, a, ss, sa = _both(ff, prompts, n_slots=2)
    assert s == a
    assert ss.prefix_hits == sa.prefix_hits and sa.prefix_hits >= 1


def test_async_matches_sync_chunked_prefill(gpt2):
    """A long prompt prefilling in chunks co-scheduled with decode:
    chunk ticks and decode commits interleave differently in wall time
    but identically in token order."""
    ff, _ = gpt2
    rng = np.random.default_rng(9)
    prompts = [rng.integers(1, 99, size=40).tolist()] + \
        _prompts(3, seed=10)
    s, a, ss, sa = _both(ff, prompts, n_slots=2,
                         prefill_chunk_tokens=16)
    assert s == a
    assert ss.outcomes == sa.outcomes


def test_speculative_matches_both_loops(gpt2):
    """The speculative decoder (device-side argmax scoring, ISSUE 17
    satellite) keeps its token-identity contract against BOTH loops'
    greedy exact decode."""
    from flexflow_tpu.serving import SpeculativeDecoder

    ff, _ = gpt2
    drafter_ff, _ = _build(num_layers=1, hidden=32, seed=5)
    prompts = _prompts(3, seed=11)
    s, a, _, _ = _both(ff, prompts, max_new=8)
    spec = SpeculativeDecoder(ff, drafter_ff, gamma=3, max_context=64)
    outs = spec.generate(prompts, max_new_tokens=8)
    assert s == a == outs
    assert spec.stats.spec_rounds > 0


# ------------------------------------------------------------ chaos parity
def test_chaos_poison_quarantine_parity(gpt2):
    """A NaN-poisoned slot quarantines at the SAME logical step in both
    loops (chaos keys on the dispatch counter; sync's committed step ==
    its dispatch count at injection time), with identical retry
    streams, outcomes and quarantine counts."""
    ff, _ = gpt2
    prompts = _prompts(4, seed=12)
    s, a, ss, sa = _both(
        ff, prompts, n_slots=2,
        gen_kw={"chaos": ChaosPlan(poison_decode_at={3: 0})})
    # second identical plan for the async run (ChaosPlan hooks are
    # once-per-step): rebuild instead of reusing
    eng_a = _engine(ff, "async", n_slots=2)
    a2 = eng_a.generate(prompts, max_new_tokens=6,
                        chaos=ChaosPlan(poison_decode_at={3: 0}))
    assert a2 == s
    assert eng_a.stats.quarantines == ss.quarantines == 1
    assert eng_a.stats.outcomes == ss.outcomes


def test_chaos_device_drop_rebuild_parity(gpt2):
    """drop_devices_at mid-decode: the elastic replan (and, on a real
    DecodeStateLost, the pool rebuild) runs behind a settle point, so
    continuations stay bitwise in both loops."""
    ff, _ = gpt2
    prompts = _prompts(4, seed=13)
    base = _engine(ff, "sync").generate(prompts, max_new_tokens=5)
    for loop in ("sync", "async"):
        eng = _engine(ff, loop)
        outs = eng.generate(prompts, max_new_tokens=5,
                            chaos=ChaosPlan(drop_devices_at={2: 4}))
        assert outs == base, f"{loop} diverged after device drop"


def test_chaos_sigterm_drain_parity(gpt2):
    """Mid-serve SIGTERM drains both loops identically: the in-flight
    request finishes (the async loop settles its pending step inside
    the drain-grace check before evicting stragglers), queued requests
    come back, and the outcome ledgers match."""
    ff, _ = gpt2
    prompts = _prompts(3, seed=14)
    prev = signal.getsignal(signal.SIGTERM)
    results = {}
    for loop in ("sync", "async"):
        eng = _engine(ff, loop, n_slots=1)
        outs = eng.generate(prompts, max_new_tokens=4,
                            chaos=ChaosPlan(preempt_serving_at=1))
        results[loop] = (outs, dict(eng.stats.outcomes),
                         [r.rng_tag for r in eng.drained_requests])
        assert signal.getsignal(signal.SIGTERM) is prev
    assert results["sync"] == results["async"]
    outs, outcomes, drained = results["async"]
    assert len(outs[0]) == 4 and outcomes == {"ok": 1, "preempted": 2}
    assert drained == [1, 2]


def test_fleet_kill_migration_parity(gpt2):
    """A replica killed mid-decode under the async runtime: the harvest
    settles the victim's in-flight step first (tokens already sampled
    on-device belong to the stream), so migrated continuations stay
    bitwise across loops AND against the undisturbed baseline."""
    ff, _ = gpt2
    prompts = _prompts(8, seed=15)
    base = _engine(ff, "sync", n_slots=2).generate(prompts,
                                                   max_new_tokens=6)
    for loop in ("sync", "async"):
        fleet = ServingFleet(ff, n_replicas=2, n_slots=2,
                             max_decode_len=64, exact_decode=True,
                             serve_loop=loop)
        outs = fleet.generate(
            prompts, max_new_tokens=6,
            chaos=FleetChaosPlan(kill_replica_at={4: 0}))
        st = fleet.stats
        assert outs == base, f"{loop} migrated streams diverged"
        assert st.outcomes == {"ok": 8} and st.failovers == 1


def test_fleet_hedge_parity(gpt2):
    """Hedge twins under the async runtime: a partitioned primary's
    streams are rescued on the healthy replica with no double count,
    bitwise the undisturbed baseline."""
    ff, _ = gpt2
    config = ff.config
    prompts = _prompts(4, seed=16)
    base = _engine(ff, "sync", n_slots=2).generate(prompts,
                                                   max_new_tokens=6)
    config.hedge_after_pctl = 10.0
    try:
        for loop in ("sync", "async"):
            fleet = ServingFleet(ff, n_replicas=2, n_slots=2,
                                 max_decode_len=64, exact_decode=True,
                                 serve_loop=loop)
            for r in fleet.replicas:
                r.engine.admission.force_token_cost_ms = 1e-6
            outs = fleet.generate(
                prompts, max_new_tokens=6,
                chaos=FleetChaosPlan(partition_at={3: 0},
                                     partition_ticks=30))
            st = fleet.stats
            assert outs == base, f"{loop} hedged streams diverged"
            assert st.hedges >= 1 and st.outcomes == {"ok": 4}
            assert sum(st.outcomes.values()) == 4
    finally:
        config.hedge_after_pctl = 0.0


# --------------------------------------------------- white-box contracts
def test_async_one_blocking_sync_per_committed_step(gpt2):
    """The steady-state contract: every blocking host transfer goes
    through the loop's single `_fetch` choke point, exactly once per
    committed decode step — never more."""
    ff, _ = gpt2
    _, _, ss, sa = _both(ff, _prompts(6, seed=17), max_new=8)
    for st in (ss, sa):
        assert st.decode_steps > 0
        assert st.host_syncs == st.decode_steps, \
            (st.host_syncs, st.decode_steps)
    # the async loop runs a few extra dispatches at stream tails whose
    # in-flight results are discarded by the epoch guard — it must
    # still never fetch more than once per commit
    assert sa.host_syncs <= sa.decode_steps


def test_async_overlap_accounting(gpt2):
    """Host work performed while a dispatched step is in flight lands
    in host_overlap_s: real wall, denominator-only — the fraction's
    numerator stays (dispatch + bookkeep)."""
    ff, _ = gpt2
    _, _, ss, sa = _both(ff, _prompts(6, seed=18), max_new=8)
    assert ss.host_overlap_s == 0.0
    assert sa.host_overlap_s > 0.0, "async recorded no overlapped host work"
    num = sa.host_dispatch_s + sa.host_bookkeep_s
    den = num + sa.host_device_s + sa.host_overlap_s
    assert sa.host_overhead_fraction() == pytest.approx(num / den)
    assert "host_syncs" in sa.summary()


def test_async_finish_settles_pending(gpt2):
    """finish() is a drain point: after serve() returns there is no
    in-flight step left and every request has a terminal outcome."""
    from flexflow_tpu.serving.scheduler import (ContinuousBatchScheduler,
                                                Request)

    ff, _ = gpt2
    eng = _engine(ff, "async", n_slots=2)
    sched = ContinuousBatchScheduler(n_slots=2, max_queue=8, max_len=64,
                                     buckets=eng.buckets)
    reqs = [Request(prompt=np.asarray(p, np.int32), max_new_tokens=5,
                    rng_tag=i)
            for i, p in enumerate(_prompts(3, seed=19))]
    for r in reqs:
        eng.admit(sched, r)
    loop = eng.start_serve(sched)
    while loop.tick():
        pass
    loop.finish()
    assert loop._pending is None
    assert all(r.outcome == "ok" and len(r.generated) == 5 for r in reqs)


def test_serve_loop_validation(gpt2):
    ff, _ = gpt2
    with pytest.raises(ValueError, match="serve_loop"):
        ServingEngine(ff, n_slots=1, max_decode_len=64,
                      serve_loop="turbo")
