"""Prefix amortization (ISSUE 14, docs/serving.md "Prefix cache &
chunked prefill"): the radix-tree prefix cache over copy-on-write paged
blocks, chunked prefill, and prefix-aware fleet routing.

The acceptance contracts, all CPU-deterministic:

* a request admitted behind a trie hit produces the IDENTICAL token
  stream (exact decode) to a cold run, solo and co-batched, with
  ``prefill_tokens_computed`` strictly lower and zero block leaks after
  eviction churn;
* COW divergence isolation — a writer's clone never perturbs the
  sharer's rows;
* chunked-prefill logits/streams bitwise vs one-shot prefill;
* allocator refcount laws (alloc/share/free round trips, typed
  double-free/share-after-free errors, zero leaks under churn);
* fleet migration re-prefills consult the survivor's trie, and fleet
  dispatch routes by cache affinity;
* FF006 chunk shape laws reject misconfigurations with zero compiles.
"""
import os
import sys

import numpy as np
import pytest

from flexflow_tpu import (DataType, FFConfig, FFModel, LossType,
                          SGDOptimizer)
from flexflow_tpu.models.gpt2 import GPT2Config, build_gpt2
from flexflow_tpu.serving import (BlockAccountingError, BlockAllocator,
                                  PrefixCache, ServingEngine,
                                  ServingFleet)
from flexflow_tpu.serving.scheduler import (ContinuousBatchScheduler,
                                            Request)


def _build(seq_len=64, seed=42):
    # the GPT2Config.tiny family (hidden 64 / 4 heads) at a longer
    # sequence so prompts can span several KV blocks — the size band
    # where the exact-decode bitwise contract provably holds
    cfg = GPT2Config(batch_size=2, seq_len=seq_len, hidden=64,
                     num_heads=4, num_layers=2, intermediate=128,
                     vocab_size=100)
    config = FFConfig()
    config.batch_size = cfg.batch_size
    config.seed = seed
    ff = FFModel(config)
    build_gpt2(ff, cfg)
    ff.compile(optimizer=SGDOptimizer(ff),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff, cfg


@pytest.fixture(scope="module")
def gpt2():
    return _build()


SYS_PROMPT = list(np.random.default_rng(7).integers(1, 99, size=20))
PROMPTS = [SYS_PROMPT + [5, 6, 7], SYS_PROMPT + [8, 9],
           SYS_PROMPT + [5, 6, 1, 2]]


def _engine(ff, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("max_decode_len", 64)
    kw.setdefault("exact_decode", True)
    kw.setdefault("kv_block_size", 8)
    return ServingEngine(ff, **kw)


def _cold(ff, prompts, max_new=6, **kw):
    return _engine(ff, prefix_cache="off", **kw).generate(
        prompts, max_new_tokens=max_new)


# ------------------------------------------------------- allocator laws
def test_allocator_refcount_laws():
    a = BlockAllocator(n_blocks=9, block_size=4)
    blocks = a.alloc(3)
    assert blocks == [1, 2, 3] and a.in_use == 3
    assert all(a.refcount(b) == 1 for b in blocks)
    a.share(blocks[:2])
    assert a.refcount(1) == 2 and a.refcount(3) == 1
    a.free(blocks)  # drops to [1, 1, 0] — block 3 returns
    assert a.in_use == 2 and a.refcount(3) == 0
    a.free([1, 2])
    assert a.in_use == 0 and len(a.free_blocks) == 8
    # typed laws: double-free, share-after-free, garbage-block touch
    with pytest.raises(BlockAccountingError, match="double free"):
        a.free([1])
    with pytest.raises(BlockAccountingError, match="free"):
        a.share([2])
    with pytest.raises(BlockAccountingError, match="garbage"):
        a.share([0])
    with pytest.raises(BlockAccountingError, match="outside the pool"):
        a.free([99])


def test_allocator_churn_property():
    """Property-style churn: random alloc/share/free sequences keep the
    conservation law (in_use + free == usable, refcounts consistent)
    and end with zero leaks."""
    rng = np.random.default_rng(0)
    a = BlockAllocator(n_blocks=17, block_size=4)
    live = []  # (block, refs) — refs we still owe a free() for
    for _ in range(400):
        op = rng.integers(0, 3)
        if op == 0:
            got = a.alloc(int(rng.integers(1, 4)))
            if got is not None:
                live.extend((b, 1) for b in got)
        elif op == 1 and live:
            i = int(rng.integers(len(live)))
            b, r = live[i]
            a.share([b])
            live[i] = (b, r + 1)
        elif op == 2 and live:
            i = int(rng.integers(len(live)))
            b, r = live.pop(i)
            a.free([b])
            if r > 1:
                live.append((b, r - 1))
        assert a.in_use + len(a.free_blocks) == a.n_usable
        for b in range(1, a.n_blocks):
            owed = sum(r for blk, r in live if blk == b)
            assert a.refcount(b) == owed
    for b, r in live:
        a.free([b] * r)
    assert a.in_use == 0 and not a.leaked()


# ------------------------------------------------------------- trie unit
def test_trie_match_insert_upgrade_evict():
    a = BlockAllocator(n_blocks=33, block_size=4)
    trie = PrefixCache(a, block_size=4)
    toks = list(range(10, 24))  # 14 tokens: 3 full blocks + tail(2)
    blocks = a.alloc(4)
    adopted = trie.insert(toks, blocks)
    assert adopted == 4 and trie.n_blocks == 4
    assert all(a.refcount(b) == 2 for b in blocks)
    # exact full match, capped below the full prompt
    got, n = trie.match(toks, cap=13)
    assert n == 13 and got == blocks  # partial credit on the tail node
    # sub-block floor: a 3-token match is a miss
    got, n = trie.match([10, 11, 12, 99], cap=3)
    assert (got, n) == ([], 0)
    # divergent partial credit inside a full block
    got, n = trie.match(toks[:6] + [77, 78], cap=8)
    assert n == 6 and got == blocks[:2]
    # peek: no LRU mutation, same answer
    assert trie.peek(toks, cap=13) == 13
    # tail upgrade: longer evidence replaces the partial node's block
    toks2 = toks + [50]  # extends the 2-token tail to (22, 23, 50)
    b2 = a.alloc(4)
    trie.insert(toks2, b2)
    assert trie.n_blocks == 4  # upgraded in place, not a sibling
    got, n = trie.match(toks2, cap=15)
    assert n == 15 and got[-1] == b2[3]
    assert a.refcount(blocks[3]) == 1  # trie ref released on upgrade
    # release the requests' own refs; only the trie holds the 4 nodes
    a.free(blocks)
    a.free(b2)
    assert sorted(a.leaked()) == sorted(blocks[:3] + [b2[3]])
    # LRU eviction: leaves at refcount 1 go first, parents follow
    freed = trie.evict(10)
    assert freed == 4 and trie.n_blocks == 0
    assert trie.evictions == 4 and not a.leaked()


def test_trie_retention_cap():
    a = BlockAllocator(n_blocks=65, block_size=4)
    trie = PrefixCache(a, block_size=4, max_blocks=3)
    for i in range(4):
        toks = [100 * i + j for j in range(8)]
        blocks = a.alloc(2)
        trie.insert(toks, blocks)
        a.free(blocks)
    assert trie.n_blocks <= 3 and trie.evictions >= 1


# ------------------------------------------------- bitwise hit contracts
def test_prefix_hit_stream_bitwise_and_cheaper(gpt2):
    """Acceptance: a trie-hit admission's stream is bitwise the cold
    run's (exact decode), with prefill_tokens_computed strictly lower
    and the reuse ledger filled."""
    ff, _cfg = gpt2
    cold = _cold(ff, PROMPTS)
    eng = _engine(ff)
    r1 = eng.generate(PROMPTS, max_new_tokens=6)
    computed1 = eng.stats.prefill_tokens_computed
    r2 = eng.generate(PROMPTS, max_new_tokens=6)
    s2 = eng.stats
    assert r1 == cold and r2 == cold
    assert s2.prefix_hits == len(PROMPTS)
    assert s2.prefill_tokens_computed < computed1
    assert s2.prefix_tokens_reused > 0
    assert (s2.prefix_reuse_rate() or 0) > 0.5
    # full-prompt hits leave exactly the final token to compute
    assert s2.prefill_tokens_computed == len(PROMPTS)


def test_prefix_hit_cobatched_isolation(gpt2):
    """A hit admitted co-batched with unrelated live streams: the hit is
    bitwise its cold self AND the neighbors are bitwise theirs."""
    ff, _cfg = gpt2
    others = [[9, 8, 7, 6, 5, 4, 3, 2, 1], [33, 44, 55]]
    mixed = [PROMPTS[0], others[0], PROMPTS[1], others[1]]
    cold = _cold(ff, mixed)
    eng = _engine(ff)
    eng.generate([SYS_PROMPT + [1]], max_new_tokens=4)  # warm the trie
    out = eng.generate(mixed, max_new_tokens=6)
    assert out == cold
    assert eng.stats.prefix_hits >= 2


def test_cow_divergence_isolation(gpt2):
    """Copy-on-write: B shares A's partially-filled tail block, then
    diverges — B's clone write must never perturb A's rows (A's prompt
    re-served later is still bitwise its cold self), and B's stream is
    bitwise B-cold."""
    ff, _cfg = gpt2
    a_prompt = SYS_PROMPT[:18]            # blocks: 2 full + tail(2)
    b_prompt = SYS_PROMPT[:17] + [91, 92]  # shares 17, diverges in tail
    cold_a = _cold(ff, [a_prompt])
    cold_b = _cold(ff, [b_prompt])
    eng = _engine(ff)
    assert eng.generate([a_prompt], max_new_tokens=6) == cold_a
    out_b = eng.generate([b_prompt], max_new_tokens=6)
    assert out_b == cold_b, "COW writer diverged from its cold stream"
    assert eng.stats.prefix_hits == 1
    # the sharer's rows survived the writer's divergence bitwise
    assert eng.generate([a_prompt], max_new_tokens=6) == cold_a, \
        "sharer's cached rows were perturbed by the COW writer"


def test_prefix_eviction_churn_zero_leaks(gpt2):
    """Acceptance: under a pool small enough to force LRU trie eviction,
    streams stay bitwise-cold and no block leaks (in_use == exactly the
    trie's retained set; zero once dropped)."""
    ff, _cfg = gpt2
    rng = np.random.default_rng(3)
    churn = [rng.integers(1, 99, size=12).tolist() for _ in range(6)]
    cold = _cold(ff, churn)
    mb = -(-64 // 8)
    eng = _engine(ff, n_slots=1, kv_pool_blocks=mb + 1)
    assert eng.generate(churn, max_new_tokens=6) == cold
    assert eng.stats.cache_evictions > 0, \
        "pool pressure never exercised trie eviction"
    alc = eng.block_allocator
    assert alc.in_use == eng._prefix.n_blocks
    eng._prefix.clear(free=True)
    assert alc.in_use == 0 and not alc.leaked()


# --------------------------------------------------------- chunked prefill
def test_chunked_prefill_bitwise_vs_one_shot(gpt2):
    """Acceptance: chunked-prefill streams AND next-token logits are
    bitwise the one-shot prefill's; the chunk program compiles once per
    shape."""
    import jax

    ff, _cfg = gpt2
    rng = np.random.default_rng(4)
    longs = [rng.integers(1, 99, size=40).tolist(),
             rng.integers(1, 99, size=33).tolist(), [7, 8, 9]]
    cold = _cold(ff, longs)
    eng = _engine(ff, prefix_cache="off", prefill_chunk_tokens=16)
    out = eng.generate(longs, max_new_tokens=6)
    assert out == cold
    # 40 -> 3 chunks, 33 -> 3 chunks; the 3-token prompt stays classic
    assert eng.stats.chunked_prefills == 6
    # one-compile-per-shape law: the chunk program is warm after the
    # first run — a second run through THIS engine adds zero cache
    # entries (the executor-shared jit may hold entries for OTHER
    # engines' pool shapes; the law is per (shape, engine))
    fn = eng.executor._serving_jits.get(("chunk", 16, 64, 8, "native"))
    assert fn is not None
    warm = fn._cache_size()
    assert eng.generate(longs, max_new_tokens=6) == cold
    assert fn._cache_size() == warm, "chunk program recompiled"
    # logits-level: the final chunk's next-token logits == one-shot's
    import jax.numpy as jnp

    prompt = np.asarray(longs[0], np.int32)
    eff = len(prompt)
    bucket = next(b for b in eng.buckets if b >= eff)
    ids = np.zeros((1, bucket), np.int32)
    ids[0, :eff] = prompt
    _lg, last_ref, _cache = eng._prefill_fn(bucket)(
        ff.params, [jnp.asarray(ids)], jnp.asarray([eff], np.int32))
    sched = ContinuousBatchScheduler(n_slots=2, max_queue=8,
                                     buckets=eng.buckets, max_len=64)
    eng._attach_kv_accounting(sched)
    req = Request(prompt=prompt, max_new_tokens=6)
    sched.submit(req)
    act = sched.next_action()
    assert act == "chunked" or act[0] == "prefill_chunk"
    last = None
    while True:
        act = sched.next_action()
        if act is None or act[0] != "prefill_chunk":
            break
        _, r, slot, start, n, shape = act
        ids_c = np.zeros((1, shape), np.int32)
        ids_c[0, :n] = prompt[start:start + n]
        last, eng.state = eng._chunk_fn(shape)(
            ff.params, [jnp.asarray(ids_c)], eng.state,
            jnp.asarray(eng._table_row_for(r), jnp.int32),
            jnp.int32(start), jnp.int32(n))
        if sched.chunk_done(slot, n):
            break
    assert last is not None
    assert np.array_equal(np.asarray(jax.device_get(last)),
                          np.asarray(jax.device_get(last_ref))), \
        "chunked next-token logits diverged from one-shot prefill"


def test_chunk_actions_interleave_with_decode():
    """Scheduler law (no device): a long prompt's chunks alternate with
    the other slots' decode steps — the head-of-line stall is gone by
    construction."""
    sched = ContinuousBatchScheduler(n_slots=2, max_queue=8, max_len=64)
    sched.allocator = BlockAllocator(n_blocks=17, block_size=8)
    sched.chunk_tokens = 8
    short = Request(prompt=np.asarray([1, 2, 3], np.int32),
                    max_new_tokens=4)
    long_r = Request(prompt=np.asarray(list(range(1, 33)), np.int32),
                     max_new_tokens=4)
    sched.submit(long_r)
    sched.submit(short)
    trail = []
    for _ in range(12):
        act = sched.next_action()
        if act is None:
            break
        kind = act[0]
        trail.append(kind)
        if kind == "prefill":
            _, r, slot, _b = act
            r.prefill_pos = r.prefill_target  # engine completes it
        elif kind == "prefill_chunk":
            _, r, slot, start, n, _shape = act
            sched.chunk_done(slot, n)
        else:  # decode advances every live slot one token
            for slot, r in act[1]:
                if sched.commit_token(slot, 1):
                    break
    # the long prompt chunked; the short one-shot; decodes interleaved
    # between chunks instead of waiting for the whole long prefill
    assert "prefill_chunk" in trail and "decode" in trail
    first_chunk = trail.index("prefill_chunk")
    last_chunk = len(trail) - 1 - trail[::-1].index("prefill_chunk")
    assert "decode" in trail[first_chunk:last_chunk], \
        f"no decode between chunks: {trail}"
    assert long_r.prefill_pos == long_r.prefill_target == 32


# ------------------------------------------------------------ fleet layer
def test_fleet_affinity_routing(gpt2):
    """Dispatch routes a shared-prefix request to the replica whose trie
    holds its longest prefix, tie-broken by the load score."""
    ff, _cfg = gpt2
    fleet = ServingFleet(ff, n_replicas=2, n_slots=2, max_decode_len=64,
                         exact_decode=True)
    fleet.generate([SYS_PROMPT + [1]], max_new_tokens=4)
    # replica 0 served (and cached) the system prompt; the follow-ups
    # must all chase the warm trie despite round-robin-friendly load
    fleet.generate([SYS_PROMPT + [2], SYS_PROMPT + [3]],
                   max_new_tokens=4)
    assert fleet.stats.affinity_hits >= 2
    assert fleet.stats.affinity_tokens >= 2 * 16
    assert fleet.stats.dispatches[0] == 3, fleet.stats.dispatches


def test_fleet_migration_rehits_survivor_trie(gpt2):
    """Acceptance: a migrated stream's re-prefill consults the
    survivor's trie (prefix hit on the survivor) and continues bitwise
    (exact decode)."""
    from flexflow_tpu.resilience import FleetChaosPlan

    ff, _cfg = gpt2
    p0 = SYS_PROMPT + [1]
    p1 = SYS_PROMPT + [2]
    cold = _cold(ff, [p0], max_new=10) + _cold(ff, [p1], max_new=10)
    fleet = ServingFleet(ff, n_replicas=2, n_slots=1, max_decode_len=64,
                         exact_decode=True)
    # both replicas serve (and cache) the shared prefix: two concurrent
    # requests with 1 slot each split across the fleet
    warm = fleet.generate([p0, p1], max_new_tokens=10)
    assert warm == cold
    assert all(d > 0 for d in fleet.stats.dispatches)
    # now kill replica 0 mid-decode: its stream migrates, re-prefilling
    # prompt+committed tokens on replica 1 — whose trie holds the prefix
    hits1_before = fleet.replicas[1].sched.prefix_hits \
        if fleet.replicas[1].sched else 0
    # fleet ticks are cumulative across runs: script the kill a few
    # ticks into THIS run, while replica 0's stream is mid-decode
    kill_tick = fleet.tick_no + 4
    outs = fleet.generate([p0, p1], max_new_tokens=10,
                          chaos=FleetChaosPlan(
                              kill_replica_at={kill_tick: 0}))
    assert outs == cold, "migrated stream diverged from cold truth"
    assert fleet.stats.migrations >= 1
    assert fleet.replicas[1].sched is not None
    assert fleet.replicas[1].sched.prefix_hits > hits1_before, \
        "the survivor's trie was not consulted by the migration"


def test_poisoned_prefix_purged_from_trie(gpt2):
    """Decode poisoning NaNs the victim's blocks IN PLACE — including
    prompt blocks the trie eagerly cached at prefill completion. The
    quarantine release must purge them: the victim's retry re-prefills
    clean (recovering bitwise within its budget) instead of re-matching
    its own poisoned prefix, and no later shared-prefix admission is
    served NaN KV."""
    from flexflow_tpu.resilience import ChaosPlan

    ff, _cfg = gpt2
    prompt = SYS_PROMPT + [42]  # >= one full block: eagerly cached
    cold = _cold(ff, [prompt], max_new=8)
    eng = _engine(ff)
    out = eng.generate([prompt], max_new_tokens=8,
                       chaos=ChaosPlan(poison_decode_at={2: 0}))
    assert eng.stats.quarantines >= 1
    assert out == cold, "poisoned request did not recover bitwise"
    # the poisoned-era blocks are gone from the trie; what it holds now
    # (the clean retry's adoption) serves a fresh request bitwise
    assert eng.generate([prompt], max_new_tokens=8) == cold, \
        "trie served poisoned KV to a later shared-prefix admission"


# -------------------------------------------------- static laws and flags
def test_ff006_chunk_shape_laws(gpt2):
    """FF006 (zero compiles): chunk size not a multiple of the KV block
    size, or a pool that cannot hold one max-context request plus one
    live chunk, rejects at engine construction."""
    from flexflow_tpu.analysis import StaticAnalysisError, check_paged_kv

    ff, _cfg = gpt2
    with pytest.raises(StaticAnalysisError, match="FF006") as ei:
        ServingEngine(ff, n_slots=2, max_decode_len=64, kv_block_size=8,
                      prefill_chunk_tokens=12)
    assert "multiple of" in str(ei.value)
    mb = -(-64 // 8)
    with pytest.raises(StaticAnalysisError, match="FF006") as ei:
        ServingEngine(ff, n_slots=2, max_decode_len=64, kv_block_size=8,
                      prefill_chunk_tokens=16,
                      kv_pool_blocks=mb + 1)  # no room for the chunk
    assert "plus one live" in str(ei.value)
    # the pure-function law, directly
    diags = check_paged_kv(None, block_size=8, pool_blocks=mb + 1 + 2,
                           max_blocks_per_slot=mb, max_context=64,
                           prefill_chunk_tokens=16)
    assert not diags
    diags = check_paged_kv(None, block_size=8, pool_blocks=mb + 1,
                           max_blocks_per_slot=mb, max_context=64,
                           prefill_chunk_tokens=16)
    assert diags and all(d.rule_id == "FF006" for d in diags)


def test_prefix_flag_validation():
    cfg = FFConfig()
    cfg.parse_args(["--prefix-cache", "on", "--prefill-chunk-tokens",
                    "32", "--prefix-cache-blocks", "64"])
    assert (cfg.prefix_cache, cfg.prefill_chunk_tokens,
            cfg.prefix_cache_blocks) == ("on", 32, 64)
    with pytest.raises(ValueError, match="prefix-cache expects"):
        FFConfig().parse_args(["--prefix-cache", "maybe"])
    with pytest.raises(ValueError, match="kv-cache paged"):
        FFConfig().parse_args(["--prefix-cache", "on",
                               "--kv-cache", "ring"])
    with pytest.raises(ValueError, match="kv-cache paged"):
        FFConfig().parse_args(["--prefill-chunk-tokens", "32",
                               "--kv-cache", "ring"])
    with pytest.raises(ValueError, match="multiple of"):
        FFConfig().parse_args(["--prefill-chunk-tokens", "12"])
    with pytest.raises(ValueError, match=">= 0"):
        FFConfig().parse_args(["--prefill-chunk-tokens", "-1"])
    with pytest.raises(ValueError, match="prefix-cache on"):
        FFConfig().parse_args(["--prefix-cache-blocks", "8",
                               "--prefix-cache", "off"])


def test_lstm_graphs_gate_prefix_and_chunking():
    """ISSUE 14 scope: attention-only stateful graphs. LSTM engines get
    the prefix cache silently disabled (default) and refuse explicit
    opt-ins loudly."""
    config = FFConfig()
    config.batch_size = 4
    ff = FFModel(config)
    ids = ff.create_tensor((4, 12), dtype=DataType.DT_INT32,
                           name="pl_ids")
    t = ff.embedding(ids, 50, 16, name="pl_embed")
    t, _state = ff.lstm(t, 16, name="pl_lstm")
    ff.dense(t, 50, name="pl_head")
    ff.compile(optimizer=SGDOptimizer(ff),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    eng = ServingEngine(ff, n_slots=2, max_decode_len=12)
    assert eng._prefix is None  # default "on" silently degrades
    with pytest.raises(ValueError, match="LSTM"):
        ServingEngine(ff, n_slots=2, max_decode_len=12,
                      prefix_cache="on")
    with pytest.raises(ValueError, match="LSTM"):
        ServingEngine(ff, n_slots=2, max_decode_len=12,
                      prefill_chunk_tokens=16, kv_block_size=4)


# -------------------------------------------------- pricing, obs, resets
def test_serving_search_prices_prefill_reuse(gpt2):
    """serving_search(prefill_reuse=) scales the p99 prefill-stall term:
    a measured hit rate lowers p99, never the decode cost; the plan
    records the priced rate."""
    from flexflow_tpu.search.machine_model import TPUMachineModel
    from flexflow_tpu.serving import serving_search

    ff, _cfg = gpt2
    machine = TPUMachineModel.from_generation("v5e", 8)
    plan0 = serving_search(ff.pcg, ff.config, 8, machine=machine)
    plan6 = serving_search(ff.pcg, ff.config, 8, machine=machine,
                           prefill_reuse=0.6)
    assert plan0.prefill_reuse == 0.0 and plan6.prefill_reuse == 0.6
    assert plan6.sim_p99_ms < plan0.sim_p99_ms
    assert plan6.sim_decode_ms == plan0.sim_decode_ms
    # clamped to [0, 1]: full reuse means p99 == the decode step
    plan1 = serving_search(ff.pcg, ff.config, 8, machine=machine,
                           prefill_reuse=5.0)
    assert plan1.sim_p99_ms == pytest.approx(plan1.sim_p50_ms)


def test_prefix_telemetry_block_and_digest(gpt2, tmp_path, capsys):
    """The serving_prefix StepTelemetry block and the trace_summary
    one-line digest."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import trace_summary

    ff, _cfg = gpt2
    eng = _engine(ff)
    eng.generate(PROMPTS, max_new_tokens=4)  # warm the trie
    ff._telemetry_requested = True  # consumed per run: arm the hit run
    eng.generate(PROMPTS, max_new_tokens=4)
    tel = ff.get_telemetry()
    blk = tel.summary()["serving_prefix"]
    assert blk["hits"] == len(PROMPTS)
    assert blk["tokens_reused"] > 0 and blk["reuse_rate"] > 0.5
    f = tmp_path / "tel.json"
    tel.write(str(f))
    trace_summary.main([str(f)])
    out = capsys.readouterr().out
    assert "prefix cache: reuse" in out and "hits" in out
    ff._telemetry_requested = False


def test_pool_rebuild_and_reset_drop_trie(gpt2):
    """The trie dies with the pool: reset_decode_pool clears it (the
    allocator forgets wholesale), and a fresh _ensure_state build after
    a state loss frees its references — stale block ids must never be
    matched into a zeroed pool."""
    ff, _cfg = gpt2
    eng = _engine(ff)
    cold = _cold(ff, PROMPTS)
    eng.generate(PROMPTS, max_new_tokens=6)
    assert eng._prefix.n_blocks > 0
    eng.reset_decode_pool()
    assert eng._prefix.n_blocks == 0
    assert eng.block_allocator.in_use == 0
    # device-loss shape: state dropped WITHOUT reset — the next pool
    # build must clear the trie, returning its references
    assert eng.generate(PROMPTS, max_new_tokens=6) == cold
    assert eng._prefix.n_blocks > 0
    eng.state = None
    eng._last_tokens = None
    assert eng.generate(PROMPTS, max_new_tokens=6) == cold
    assert eng.block_allocator.in_use == eng._prefix.n_blocks


# ------------------------------------------- TTFT stamp @ commit (ISSUE 16)
def test_first_token_ms_stamps_at_commit_point():
    """ISSUE 16 satellite pin: the TTFT stamp lands at the COMMIT point
    (``commit_token``), not inside the prefill work — so any admission
    path that skips prefill compute (a full prefix hit, a hedge twin
    resuming copied tokens) still stamps the first token it commits.
    Scheduler-level, fake clock: first commit stamps, later commits
    don't move it, and the max_new_tokens=1 edge (commit and finish in
    the same call) carries both stamps."""
    t = [0.0]
    sched = ContinuousBatchScheduler(n_slots=1, max_queue=4, max_len=32,
                                     clock=lambda: t[0])
    r = Request(prompt=np.zeros(3, np.int32), max_new_tokens=2)
    sched.submit(r)
    sched.next_action()  # admitted; prefill does NOT stamp
    assert r.first_token_ms == 0.0
    t[0] = 3.0
    sched.commit_token(0, 7)
    assert r.first_token_ms == 3.0, "stamp must land at the commit"
    t[0] = 8.0
    sched.commit_token(0, 8)  # finishes (length 2)
    assert r.first_token_ms == 3.0, "first stamp wins"
    assert r.finish_ms == 8.0
    # the one-token edge: the first commit IS the terminal commit
    r1 = Request(prompt=np.zeros(3, np.int32), max_new_tokens=1)
    sched.submit(r1)
    sched.next_action()
    t[0] = 12.0
    sched.commit_token(0, 9)
    assert r1.first_token_ms == 12.0 and r1.finish_ms == 12.0


def test_full_prefix_hit_first_token_stamped(gpt2):
    """A request admitted behind a FULL prefix hit (the trie holds its
    entire prompt; admission caps the mapped hit at effective_len - 1,
    so prefill computes exactly one suffix token) must report a real
    ``first_token_ms`` — including at max_new_tokens=1, where the
    prefill tick commits the only token the request will ever emit."""
    ff, _cfg = gpt2
    eng = _engine(ff)
    warm = SYS_PROMPT + [5, 6, 7]
    eng.generate([warm], max_new_tokens=4)  # trie now spans the prompt
    for max_new in (1, 4):
        sched = ContinuousBatchScheduler(n_slots=2, max_queue=4,
                                         max_len=eng.max_decode_len)
        eng._attach_kv_accounting(sched)
        r = Request(prompt=np.asarray(warm, np.int32),
                    max_new_tokens=max_new, rng_tag=0)
        sched.submit(r)
        eng.serve(sched)
        assert r.prefix_hit_tokens >= len(warm) - 1, \
            "test setup: expected a (capped) full-prompt trie hit"
        assert r.outcome in (None, "ok") and len(r.generated) == max_new
        assert r.first_token_ms > 0, \
            f"TTFT stamp missing on full-hit path (max_new={max_new})"
        assert r.finish_ms >= r.first_token_ms


def test_chunk_overhang_past_context_stays_finite_and_bitwise(gpt2):
    """Regression: a trie-hit suffix chunk admitted deep into the
    prompt can OVERHANG the position table (start + chunk_shape >
    seq_len — here a 40-token hit leaves a 1-token suffix under a
    32-wide chunk program, rows 40..71 against a 64-entry table).
    jnp.take's fill mode turned the pad rows' position gather into NaN
    embeddings; their k/v rows landed in the garbage block and the
    gathered extent's softmax-zero x NaN poisoned the REAL row — the
    warm rerun decoded all-zero tokens and the pool stayed NaN for
    every later request. Pad positions now clamp to the chunk's last
    real row: warm rerun bitwise, pool finite."""
    import jax

    ff, cfg = gpt2
    eng = _engine(ff, prefill_chunk_tokens=32)
    prompt = list(range(1, 42))  # block-aligned 40-token hit, suffix 1
    r1 = eng.generate([prompt], max_new_tokens=8)
    r2 = eng.generate([prompt], max_new_tokens=8)
    assert eng.stats.prefix_hits >= 1
    assert r2 == r1, "overhanging suffix chunk perturbed the warm stream"
    for entry in eng.state.caches.values():
        for leaf in entry:
            assert np.isfinite(np.asarray(jax.device_get(leaf))).all(), \
                "non-finite rows leaked into the KV pool"
