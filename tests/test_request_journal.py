"""Crash-durable serving (ISSUE 20, flexflow_tpu/serving/journal.py,
docs/durability.md): the fleet-door write-ahead request journal —
segmented crc32-framed records with torn-tail truncation (property-style
churn over random corruption), group commit, compaction, the NOOP_JOURNAL
off-contract, rid-keyed client-retry dedupe, and the end-to-end loop:
crash mid-serve (FleetChaosPlan.crash_at) -> ServingFleet.recover() ->
every journaled rid under exactly one outcome, progress-journaled streams
resuming bitwise under exact decode — all deterministic on CPU."""
import json
import os

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.models.gpt2 import GPT2Config, build_gpt2
from flexflow_tpu.resilience import FleetChaosPlan
from flexflow_tpu.serving import (NOOP_JOURNAL, FleetCrashed,
                                  JournalCorruptError, NoopJournal,
                                  Request, RequestJournal, ServingEngine,
                                  ServingFleet, ServingRejection,
                                  journal_from_config)
from flexflow_tpu.serving.scheduler import reserve_rids


@pytest.fixture(scope="module")
def gpt2():
    cfg = GPT2Config.tiny(batch_size=8)
    config = FFConfig()
    config.batch_size = cfg.batch_size
    ff = FFModel(config)
    build_gpt2(ff, cfg)
    ff.compile(optimizer=SGDOptimizer(ff),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff, cfg


def _prompts(n, seed=0, lo=3, hi=6):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 100, size=int(rng.integers(lo, hi))).tolist()
            for _ in range(n)]


def _baseline(ff, cfg, prompts, max_new):
    return ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len,
                         exact_decode=True).generate(
                             prompts, max_new_tokens=max_new)


def _fleet(ff, cfg, **kw):
    kw.setdefault("n_replicas", 2)
    kw.setdefault("n_slots", 2)
    kw.setdefault("max_decode_len", cfg.seq_len)
    kw.setdefault("exact_decode", True)
    return ServingFleet(ff, **kw)


def _req(prompt, rid=None, **kw):
    kw.setdefault("max_new_tokens", 4)
    r = Request(prompt=np.asarray(prompt, dtype=np.int32), **kw)
    if rid is not None:
        r.rid = rid
    return r


def _journal_config(config, jdir, sync_ms=0.0, commit_every=0):
    """Set the journal knobs on the shared FFConfig; caller resets in
    a finally (the module fixture shares one config)."""
    config.request_journal = str(jdir)
    config.journal_sync_ms = sync_ms
    config.journal_commit_every = commit_every


def _reset_journal_config(config):
    config.request_journal = ""
    config.journal_sync_ms = 0.0
    config.journal_commit_every = 0


# ------------------------------------------------------------ journal unit
def test_journal_roundtrip_dedupe_and_reopen(tmp_path):
    """Submit/progress/outcome round-trip the segment format: a reopen
    rebuilds exactly the unfinished backlog, a duplicate submit dedupes,
    a repeated outcome is first-wins, and the outcome vocabulary is
    closed over OUTCOMES."""
    jr = RequestJournal(str(tmp_path / "j"), sync_ms=0.0,
                        commit_every=2)
    a = _req([1, 2, 3], rid=501, rng_tag=7, tenant="interactive",
             deadline_ms=250.0)
    b = _req([4, 5], rid=502)
    assert jr.log_submit(a) and jr.log_submit(b)
    assert not jr.log_submit(a)  # client retry: rid-keyed dedupe
    assert jr.dedupe_hits == 1
    a.generated.extend([11, 12])
    jr.log_progress(a)           # commit_every=2 reached -> recorded
    a.generated.extend([13])
    jr.log_progress(a)           # below the threshold -> no record
    b.outcome, b.done = "ok", True
    assert jr.log_outcome(b)
    assert not jr.log_outcome(b)  # first terminal wins
    with pytest.raises(ValueError, match="unknown outcome"):
        jr.log_outcome(a, outcome="vanished")
    jr.close()

    jr2 = RequestJournal(str(tmp_path / "j"))
    assert jr2.pending_rids() == [501]
    assert jr2.max_rid() == 502
    (rec,) = jr2.pending_requests()
    assert rec.rid == 501 and list(rec.prompt) == [1, 2, 3]
    assert rec.generated == [11, 12]  # the journaled prefix only
    assert rec.rng_tag == 7 and rec.tenant == "interactive"
    assert rec.deadline_ms == 250.0
    assert jr2.truncated_records == 0


def test_torn_tail_truncation_property(tmp_path):
    """Property-style churn (the PR 13 allocator-churn idiom): random
    byte-level tears of the LIVE segment — truncation mid-record or a
    flipped byte anywhere — always recover the longest valid record
    prefix: the reopened journal's state equals a fold of exactly the
    records wholly before the tear, the file is truncated to that
    prefix, and the journal stays appendable."""
    rng = np.random.default_rng(0)
    for it in range(25):
        root = tmp_path / f"t{it}"
        jr = RequestJournal(str(root), sync_ms=0.0, commit_every=1)
        n = int(rng.integers(2, 9))
        reqs = [_req([int(x) for x in rng.integers(0, 50, size=3)],
                     rid=1000 + i) for i in range(n)]
        for r in reqs:
            jr.log_submit(r)
        for r in reqs[:int(rng.integers(0, n))]:
            r.outcome, r.done = "ok", True
            jr.log_outcome(r)
        jr.crash()  # abandon the handle; the bytes are already synced
        (seg,) = [root / f for f in os.listdir(root)]
        data = seg.read_bytes()
        cut = int(rng.integers(1, len(data)))
        truncated = bool(rng.integers(2))
        if truncated:
            seg.write_bytes(data[:cut])        # torn mid-append
        else:
            torn = bytearray(data)
            torn[cut] ^= 0xFF                  # bit rot in the tail
            seg.write_bytes(bytes(torn))
        # the law: every record wholly before the tear survives
        keep = data.rfind(b"\n", 0, cut) + 1
        want_pending, want_outcomes = {}, set()
        for line in data[:keep].splitlines():
            p = json.loads(line.split(b" ", 1)[1])
            if p["k"] == "submit" and p["rid"] not in want_outcomes:
                want_pending.setdefault(p["rid"], [])
            elif p["k"] == "progress":
                if p["rid"] in want_pending:
                    want_pending[p["rid"]].extend(p["toks"])
            elif p["k"] == "outcome":
                want_pending.pop(p["rid"], None)
                want_outcomes.add(p["rid"])
        jr2 = RequestJournal(str(root))
        got = {r.rid: r.generated for r in jr2.pending_requests()}
        assert got == want_pending, f"iteration {it}: tear at {cut}"
        assert seg.read_bytes() == data[:keep]  # tail truncated, fsynced
        # the scanner counts a tear only when it SAW torn bytes: a cut
        # landing exactly on a record boundary leaves a clean file
        file_len = cut if truncated else len(data)
        assert (jr2.truncated_records >= 1) == (keep < file_len)
        # still appendable after surgery: a fresh record lands durably
        jr2.log_submit(_req([9], rid=4000 + it))
        jr2.close()
        assert 4000 + it in RequestJournal(str(root)).pending_rids()


def test_sealed_segment_corruption_raises(tmp_path):
    """Corruption in a SEALED (non-last) segment is not a torn tail —
    later records may depend on that history, so the scan refuses with
    JournalCorruptError naming the segment."""
    root = tmp_path / "sealed"
    jr = RequestJournal(str(root), sync_ms=0.0, segment_bytes=1 << 10)
    for i in range(40):
        jr.log_submit(_req([1, 2, 3], rid=100 + i))
    jr.close()
    segs = sorted(f for f in os.listdir(root))
    assert len(segs) >= 2, "segment rotation never fired"
    first = root / segs[0]
    blob = bytearray(first.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    first.write_bytes(bytes(blob))
    with pytest.raises(JournalCorruptError, match=segs[0]):
        RequestJournal(str(root))


def test_compaction_drops_settled_segments_only(tmp_path):
    """A sealed segment is dropped once every rid it references has an
    outcome; compaction stops at the first segment still holding a
    pending rid's history (prefix order keeps the submit/progress chain
    of every unfinished request intact)."""
    root = tmp_path / "c"
    jr = RequestJournal(str(root), sync_ms=0.0, segment_bytes=1 << 10)
    reqs = [_req([1, 2, 3], rid=200 + i) for i in range(40)]
    for r in reqs:
        jr.log_submit(r)
    for r in reqs:
        r.outcome, r.done = "ok", True
        jr.log_outcome(r)
    n_before = len(os.listdir(root))
    dropped = jr.compact()
    assert dropped >= 1
    assert jr.compacted_segments == dropped
    assert len(os.listdir(root)) == n_before - dropped
    assert RequestJournal(str(root)).pending_rids() == []
    # a pending rid in the OLDEST segment pins everything behind it
    root2 = tmp_path / "c2"
    jr2 = RequestJournal(str(root2), sync_ms=0.0, segment_bytes=1 << 10)
    jr2.log_submit(_req([7], rid=9000))  # never gets an outcome
    more = [_req([1, 2, 3], rid=300 + i) for i in range(40)]
    for r in more:
        jr2.log_submit(r)
        r.outcome, r.done = "ok", True
        jr2.log_outcome(r)
    assert len(os.listdir(root2)) >= 2
    assert jr2.compact() == 0


def test_reserve_rids_monotone():
    """reserve_rids skips the process-wide counter past every journaled
    rid (fresh submits never collide with a replayed one) and never
    moves it backwards."""
    r1 = _req([1])
    reserve_rids(r1.rid + 100)
    r2 = _req([1])
    assert r2.rid == r1.rid + 101
    reserve_rids(0)  # stale reservation must not rewind the counter
    assert _req([1]).rid > r2.rid


# ----------------------------------------------------------- off-contract
def test_journal_off_is_noop_singleton_bitwise(gpt2):
    """Journal off (the default) is the PR 16 noop contract: the fleet
    holds the one shared slotted NOOP_JOURNAL and serves bitwise
    identically to the baseline — zero durability, zero tax."""
    assert NoopJournal.__slots__ == ()
    assert journal_from_config(FFConfig()) is NOOP_JOURNAL
    ff, cfg = gpt2
    prompts = _prompts(6, seed=3)
    base = _baseline(ff, cfg, prompts, 5)
    fleet = _fleet(ff, cfg)
    assert fleet.journal is NOOP_JOURNAL
    assert fleet.journal.log_submit(None) is True  # door never blocked
    outs = fleet.generate(prompts, max_new_tokens=5)
    assert outs == base
    assert fleet.stats.outcomes == {"ok": 6}


# -------------------------------------------------- crash -> recover loop
def test_crash_recover_exactly_one_outcome_bitwise(gpt2, tmp_path):
    """Acceptance (ISSUE 20): FleetChaosPlan.crash_at fires mid-serve
    (in-process hard mode — the journal drops its un-synced buffer and
    FleetCrashed skips every flush path), ServingFleet.recover() replays
    the unfinished backlog through the real door, and after the recovery
    run every journaled rid has exactly one outcome on disk — with
    progress-journaled streams resumed BITWISE vs an undisturbed
    single-engine run under exact decode."""
    ff, cfg = gpt2
    config = ff.config
    prompts = _prompts(8, seed=4)
    base = _baseline(ff, cfg, prompts, 6)
    jdir = tmp_path / "wal"
    _journal_config(config, jdir, sync_ms=0.0, commit_every=1)
    try:
        fleet = _fleet(ff, cfg)
        for i, p in enumerate(prompts):
            fleet.submit(_req(p, max_new_tokens=6, rng_tag=i))
        chaos = FleetChaosPlan(crash_at={6: "hard"})
        with pytest.raises(FleetCrashed, match="tick 6"):
            fleet.run(chaos=chaos)
        assert chaos.crashes_fired == ["hard"]

        # what the dead process left on disk: every submit durable
        # (sync_ms=0), and the crash landed mid-stream — at least one
        # backlog entry carries a journaled committed-token prefix
        scan = RequestJournal(str(jdir), commit_every=1)
        backlog = scan.pending_requests()
        assert len(backlog) + len(scan._outcomes) == 8
        assert backlog, "crash after everything finished proves nothing"
        assert any(r.generated for r in backlog), \
            "crash tick never reached mid-stream decode"

        fleet2 = ServingFleet.recover(ff, n_replicas=2, n_slots=2,
                                      max_decode_len=cfg.seq_len,
                                      exact_decode=True)
        jr = fleet2.journal
        assert jr.replayed == len(backlog)
        assert jr.recovery_wall_s > 0
        st = fleet2.stats
        fleet2.run()
        assert st.outcomes == {"ok": len(backlog)}
        # bitwise resume: every recovered stream equals the undisturbed
        # baseline stream for its rng_tag (re-prefill + (tag, n) rng)
        rec = {r.rng_tag: list(r.generated) for r in fleet2._requests}
        assert rec == {i: base[i] for i in rec}
        jr.close()
        # the on-disk census: no journaled rid is left without exactly
        # one outcome, and settled history compacted away
        assert RequestJournal(str(jdir)).pending_rids() == []
    finally:
        _reset_journal_config(config)


def test_recover_dedupes_client_retries(gpt2, tmp_path):
    """Client retries are idempotent at the door across the whole
    lifecycle: a same-rid resubmit while pending and a same-rid resubmit
    after the outcome both dedupe instead of double-admitting."""
    ff, cfg = gpt2
    config = ff.config
    _journal_config(config, tmp_path / "d")
    try:
        fleet = _fleet(ff, cfg)
        first = _req(_prompts(1, seed=5)[0], max_new_tokens=4, rng_tag=0)
        fleet.submit(first)
        retry = _req(list(first.prompt), rid=first.rid,
                     max_new_tokens=4, rng_tag=0)
        fleet.submit(retry)  # pending retry: swallowed, not re-queued
        assert fleet.journal.dedupe_hits == 1
        fleet.run()
        assert fleet.stats.outcomes == {"ok": 1}
        late = _req(list(first.prompt), rid=first.rid,
                    max_new_tokens=4, rng_tag=0)
        fleet.submit(late)   # post-outcome retry: also swallowed
        assert fleet.journal.dedupe_hits == 2
        assert len(fleet._requests) == 1
        fleet.journal.close()
    finally:
        _reset_journal_config(config)


def test_drain_crash_recover_exactly_once(gpt2, tmp_path):
    """Satellite pin (ISSUE 20): a fleet-wide SIGTERM drain journals the
    handed-back door queue as preempted and group-commits BEFORE the
    process goes away — a recovery on the same directory replays
    nothing, and each drained request's timeline closed exactly once."""
    ff, cfg = gpt2
    config = ff.config
    _journal_config(config, tmp_path / "drain")
    try:
        fleet = _fleet(ff, cfg)
        for rep in fleet.replicas:
            rep.engine.max_queue = 0  # white-box: nothing can dispatch
        outs = fleet.generate(_prompts(3, seed=6), max_new_tokens=4,
                              chaos=FleetChaosPlan(preempt_serving_at=1))
        assert fleet.stats.outcomes == {"preempted": 3}
        assert all(o == [] for o in outs)
        assert len(fleet.drained_requests) == 3
        # the drain's outcome records are already durable: recovery on
        # the same directory finds zero unfinished rids
        fleet2 = ServingFleet.recover(ff, n_replicas=2, n_slots=2,
                                      max_decode_len=cfg.seq_len,
                                      exact_decode=True)
        assert fleet2.journal.replayed == 0
        assert fleet2.journal.pending_rids() == []
        fleet2.journal.close()
    finally:
        _reset_journal_config(config)


@pytest.mark.slow
def test_crash_sigkill_child_process_recovers(gpt2, tmp_path):
    """The real-signal mode: a child process serving with the journal on
    dies by actual SIGKILL mid-serve (crash_at sigkill), and the parent
    recovers its backlog to terminal — the tier-1 hard-mode loop without
    the in-process stand-in."""
    import subprocess
    import sys

    jdir = tmp_path / "kill"
    script = tmp_path / "serve_and_die.py"
    script.write_text(f"""
import numpy as np
from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.models.gpt2 import GPT2Config, build_gpt2
from flexflow_tpu.resilience import FleetChaosPlan
from flexflow_tpu.serving import Request, ServingFleet

cfg = GPT2Config.tiny(batch_size=8)
config = FFConfig()
config.batch_size = cfg.batch_size
config.request_journal = {str(jdir)!r}
config.journal_commit_every = 1
ff = FFModel(config)
build_gpt2(ff, cfg)
ff.compile(optimizer=SGDOptimizer(ff),
           loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
rng = np.random.default_rng(6)
fleet = ServingFleet(ff, n_replicas=2, n_slots=2,
                     max_decode_len=cfg.seq_len, exact_decode=True)
for i in range(6):
    p = rng.integers(0, 100, size=int(rng.integers(3, 6)))
    fleet.submit(Request(prompt=p.astype(np.int32), max_new_tokens=6,
                         rng_tag=i))
fleet.run(chaos=FleetChaosPlan(crash_at={{6: "sigkill"}}))
raise SystemExit("still alive after SIGKILL tick")
""")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   p for p in (repo, os.environ.get("PYTHONPATH")) if p))
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == -9, (proc.returncode, proc.stderr[-2000:])
    ff, cfg = gpt2
    config = ff.config
    _journal_config(config, jdir, commit_every=1)
    try:
        fleet = ServingFleet.recover(ff, n_replicas=2, n_slots=2,
                                     max_decode_len=cfg.seq_len,
                                     exact_decode=True)
        assert fleet.journal.replayed >= 1
        fleet.run()
        assert set(fleet.stats.outcomes) == {"ok"}
        fleet.journal.close()
        assert RequestJournal(str(jdir)).pending_rids() == []
    finally:
        _reset_journal_config(config)


# -------------------------------------------------- flags + observability
def test_journal_flags_parse_and_preflight(tmp_path):
    """--request-journal / --journal-sync-ms / --journal-commit-every:
    parse-time validation (values >= 0, tuning flags require the
    directory flag) and preflight_config's programmatic-assignment
    checks (including the parent-directory existence gate)."""
    from flexflow_tpu.resilience.preflight import (PreflightError,
                                                   preflight_config)

    cfg = FFConfig()
    assert cfg.request_journal == ""
    assert cfg.journal_sync_ms == 0.0 and cfg.journal_commit_every == 0
    cfg.parse_args(["--request-journal", str(tmp_path / "j"),
                    "--journal-sync-ms", "5", "--journal-commit-every",
                    "8"])
    assert cfg.request_journal == str(tmp_path / "j")
    assert cfg.journal_sync_ms == 5.0 and cfg.journal_commit_every == 8
    preflight_config(cfg)
    with pytest.raises(ValueError, match=">= 0"):
        FFConfig().parse_args(["--request-journal", "x",
                               "--journal-sync-ms", "-1"])
    with pytest.raises(ValueError, match=">= 0"):
        FFConfig().parse_args(["--request-journal", "x",
                               "--journal-commit-every", "-2"])
    with pytest.raises(ValueError, match="request-journal"):
        FFConfig().parse_args(["--journal-sync-ms", "5"])
    with pytest.raises(ValueError, match="request-journal"):
        FFConfig().parse_args(["--journal-commit-every", "4"])
    with pytest.raises(ValueError, match="directory"):
        FFConfig().parse_args(["--request-journal", ""])
    bad = FFConfig()
    bad.request_journal = "x"
    bad.journal_sync_ms = -3.0
    with pytest.raises(PreflightError, match=">= 0"):
        preflight_config(bad)
    tuner = FFConfig()
    tuner.journal_commit_every = 4
    with pytest.raises(PreflightError, match="request-journal"):
        preflight_config(tuner)
    orphan = FFConfig()
    orphan.request_journal = str(tmp_path / "no" / "such" / "parent")
    with pytest.raises(PreflightError, match="parent"):
        preflight_config(orphan)


def test_journal_telemetry_block_and_trace_digest(gpt2, tmp_path,
                                                  capsys):
    """The StepTelemetry ``serving_journal`` block lands next to the
    fleet block on a journaled run (and only then: the PR 16 presence
    contract) and trace_summary prints its digest."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import trace_summary

    ff, cfg = gpt2
    config = ff.config
    tel_file = tmp_path / "tel.json"
    config.telemetry_file = str(tel_file)
    _journal_config(config, tmp_path / "wal", commit_every=1)
    try:
        fleet = _fleet(ff, cfg)
        fleet.generate(_prompts(4, seed=7), max_new_tokens=4)
        fleet.journal.close()
    finally:
        config.telemetry_file = ""
        _reset_journal_config(config)
    data = json.loads(tel_file.read_text())
    blk = data["serving_journal"]
    assert blk["appended"] > 0 and blk["syncs"] >= 1
    assert blk["replayed"] == 0 and blk["truncated_records"] == 0
    trace_summary.main([str(tel_file)])
    out = capsys.readouterr().out
    assert "request journal:" in out
    # journal off -> no block (zero-overhead absence)
    tel2 = tmp_path / "tel2.json"
    config.telemetry_file = str(tel2)
    try:
        _fleet(ff, cfg).generate(_prompts(2, seed=8), max_new_tokens=3)
    finally:
        config.telemetry_file = ""
    assert "serving_journal" not in json.loads(tel2.read_text())
