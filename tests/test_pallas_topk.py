"""Pallas top-k kernel (kernels/topk.py — SURVEY §7's top-k kernel;
reference analog src/ops/kernels/topk_kernels.cu): values/indices vs
jax.lax.top_k, value-gradient vs lax.top_k's vjp, selection gate."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flexflow_tpu.kernels.topk import pallas_topk, should_use_pallas_topk


@pytest.mark.parametrize("shape,k", [((8, 128), 2), ((4, 16, 256), 4),
                                     ((6, 512), 1)])
def test_pallas_topk_matches_lax(shape, k):
    x = jax.random.normal(jax.random.PRNGKey(0), shape, jnp.float32)
    vals, idx = pallas_topk(x, k, interpret=True)
    rvals, ridx = jax.lax.top_k(x, k)
    np.testing.assert_allclose(np.asarray(vals), np.asarray(rvals),
                               rtol=1e-6, atol=0)
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(ridx))


def test_pallas_topk_ties_lowest_index():
    x = jnp.asarray([[3.0, 7.0, 7.0, 1.0]] * 8)
    x = jnp.pad(x, ((0, 0), (0, 124)), constant_values=-10.0)  # lane-align
    _, idx = pallas_topk(x, 2, interpret=True)
    np.testing.assert_array_equal(np.asarray(idx[0]), [1, 2])


def test_pallas_topk_value_gradient_matches():
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 128), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(2), (8, 3), jnp.float32)

    def loss_pallas(x):
        vals, _ = pallas_topk(x, 3, interpret=True)
        return jnp.sum(vals * w)

    def loss_ref(x):
        vals, _ = jax.lax.top_k(x, 3)
        return jnp.sum(vals * w)

    g1 = jax.grad(loss_pallas)(x)
    g2 = jax.grad(loss_ref)(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-6, atol=0)


def test_pallas_topk_bf16():
    x = jax.random.normal(jax.random.PRNGKey(3), (8, 128), jnp.bfloat16)
    vals, idx = pallas_topk(x, 2, interpret=True)
    rvals, ridx = jax.lax.top_k(x, 2)
    assert vals.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(ridx))
    np.testing.assert_allclose(np.asarray(vals, dtype=np.float32),
                               np.asarray(rvals, dtype=np.float32),
                               rtol=2e-2, atol=0)


def test_selection_gate():
    x = jnp.zeros((64, 256))
    assert not should_use_pallas_topk(x, 2)  # no opt-in
    assert not should_use_pallas_topk(x, 16, opt_in=True)  # k too large
    assert not should_use_pallas_topk(jnp.zeros((64, 100)), 2, opt_in=True)
    expected = jax.devices()[0].platform == "tpu"
    assert should_use_pallas_topk(x, 2, opt_in=True) == expected


def test_topk_op_use_pallas_attr():
    """TopKOp routes by the gate; on CPU it falls back to lax.top_k but the
    attr is accepted end-to-end through the op layer."""
    from flexflow_tpu.ops.base import OpContext
    from flexflow_tpu.ops.tensor_ops import TopKOp

    op = TopKOp("tk", {"k": 2, "use_pallas": True}, None, num_inputs=1)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 128))
    vals, idx = op.forward({}, [x], OpContext(training=False))
    rvals, ridx = jax.lax.top_k(x, 2)
    np.testing.assert_allclose(np.asarray(vals), np.asarray(rvals))
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(ridx))


def test_selection_gate_rejects_int_dtypes():
    xi = jnp.zeros((64, 256), jnp.int32)
    assert not should_use_pallas_topk(xi, 2, opt_in=True)


def test_pallas_topk_distinct_indices_with_inf_mask():
    """Rows with fewer than k finite entries still return k DISTINCT
    indices (lax.top_k contract; MoE routers mask logits with -inf)."""
    row = np.full((8, 128), -np.inf, np.float32)
    row[:, 5] = 1.0  # single finite entry
    x = jnp.asarray(row)
    vals, idx = pallas_topk(x, 3, interpret=True)
    rvals, ridx = jax.lax.top_k(x, 3)
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(ridx))
    np.testing.assert_array_equal(np.asarray(vals), np.asarray(rvals))
    # gradient scatters once per distinct index
    g = jax.grad(lambda x: jnp.sum(pallas_topk(x, 3, interpret=True)[0]
                                   * jnp.asarray([1.0, 10.0, 100.0])))(x)
    assert float(g[0, 5]) == 1.0
