"""MoE at scale (VERDICT round-1 item 8): scatter/gather dispatch
equivalence with the dense-dispatch formulation, all-k load-balance term,
batched Experts op, EP in the search space, >=8-expert training."""
import numpy as np

import jax
import jax.numpy as jnp

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, LossType
from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.ops.base import OpContext
from flexflow_tpu.ops.moe_ops import (dispatch_indices, dispatch_mask,
                                      moe_capacity)


def test_scatter_dispatch_matches_dense_dispatch():
    """Forward AND gradients of the scatter-based group_by must match the
    (t, n, cap) one-hot einsum formulation on small shapes."""
    rng = np.random.default_rng(0)
    t, d, n, cap = 24, 8, 4, 8
    x = jnp.asarray(rng.normal(size=(t, d)).astype(np.float32))
    assign = jnp.asarray(rng.integers(0, n, size=(t,)).astype(np.int32))

    def grouped_scatter(x):
        from flexflow_tpu.ops.moe_ops import _scatter_group

        return _scatter_group(x, assign, n, cap)

    def grouped_dense(x):
        disp = dispatch_mask(assign, n, cap).astype(x.dtype)
        return jnp.einsum("td,tnc->ncd", x, disp)

    np.testing.assert_allclose(grouped_scatter(x), grouped_dense(x),
                               rtol=1e-5, atol=1e-5)
    # gradients through a downstream reduction
    g1 = jax.grad(lambda x: jnp.sum(jnp.sin(grouped_scatter(x))))(x)
    g2 = jax.grad(lambda x: jnp.sum(jnp.sin(grouped_dense(x))))(x)
    np.testing.assert_allclose(g1, g2, rtol=1e-5, atol=1e-5)


def test_dispatch_drops_overflow_tokens():
    """Tokens past capacity are dropped in scan order, like the reference's
    full buffer (group_by.cu)."""
    assign = jnp.asarray([0, 0, 0, 1], dtype=jnp.int32)
    dest, keep = dispatch_indices(assign, n=2, capacity=2)
    np.testing.assert_array_equal(np.asarray(keep), [True, True, False, True])
    np.testing.assert_array_equal(np.asarray(dest)[:2], [0, 1])


def test_lambda_bal_covers_all_k():
    """The load-balance term must count every routed assignment (all k),
    not only top-1 (reference: aggregate.cu backward's lambda_bal)."""
    from flexflow_tpu.ops.moe_ops import AggregateOp

    n, batch, k, cap, d = 4, 8, 2, 8, 4
    # top-1 always expert 0; second choice spreads over experts 1..3
    gate_assign = jnp.stack(
        [jnp.zeros(batch, jnp.int32),
         jnp.asarray([1, 2, 3, 1, 2, 3, 1, 2], jnp.int32)], axis=1)
    gate_preds = jnp.full((batch, k), 0.5)
    full_gate = jnp.full((batch, n), 0.25)
    exp_preds = jnp.ones((n, cap, d))
    op = AggregateOp("agg", {"n": n, "lambda_bal": 1.0}, None, num_inputs=5)
    aux = []
    ctx = OpContext(training=True, aux_losses=aux)
    op.forward({}, [gate_preds, gate_assign, gate_assign, full_gate,
                    exp_preds], ctx)
    assert len(aux) == 1
    # all-k load = [.5, .1875, .1875, .125]; top-1-only load would be
    # [1, 0, 0, 0] giving aux = 4 * 0.25 = 1.0; all-k gives 4 * 0.25 *
    # sum(load)=1 * ... compute expected:
    load = np.asarray([0.5, 3 / 16, 3 / 16, 2 / 16])
    expected = 1.0 * n * float(np.sum(load * 0.25))
    np.testing.assert_allclose(float(aux[0]), expected, rtol=1e-5)


def test_moe_experts_trains_at_8_experts():
    """The batched-Experts MoE path trains at 8 experts / realistic batch
    and its step memory has no (t, n, cap) term."""
    config = FFConfig()
    config.batch_size = 64
    ff = FFModel(config)
    x = ff.create_tensor((64, 64), name="in")
    t = ff.dense(x, 64)
    t = ff.moe_experts(t, num_exp=8, num_select=2, expert_hidden_size=64,
                       alpha=1.5, lambda_bal=0.01)
    ff.softmax(ff.dense(t, 4))
    ff.compile(optimizer=AdamOptimizer(ff, alpha=1e-3),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(128, 64)).astype(np.float32)
    w = rng.normal(size=(64, 4)).astype(np.float32)
    ys = np.argmax(xs @ w, axis=1)[:, None].astype(np.int32)
    estep = ff.executor.make_eval_step()
    bx = [jax.device_put(xs[:64], ff.executor.batch_sharding(2))]
    by = jax.device_put(ys[:64], ff.executor.batch_sharding(2))
    loss0 = float(estep(ff.params, bx, by)[0])
    ff.fit(xs, ys, epochs=8)
    loss1 = float(estep(ff.params, bx, by)[0])
    assert loss1 < loss0, (loss0, loss1)
    # experts weights exist as one stacked tensor
    names = [k for k in ff.params if "moe_experts" in k]
    assert names and ff.params[names[0]]["kernel"].shape == (8, 64, 64)


def test_search_discovers_expert_parallelism():
    """unity_search must pick kind='expert' for the Experts op on a
    compute-heavy MoE model (VERDICT item 2's EP Done criterion)."""
    from flexflow_tpu.search.machine_model import TPUMachineModel
    from flexflow_tpu.search.simulator import OpSharding, Simulator
    from flexflow_tpu.search.unity import dp_assign, unity_search

    config = FFConfig()
    config.batch_size = 32
    ff = FFModel(config)
    x = ff.create_tensor((32, 1024), name="in")
    t = ff.moe_experts(x, num_exp=8, num_select=2,
                       expert_hidden_size=4096, alpha=1.0)
    ff.softmax(ff.dense(t, 8))
    pcg = ff.create_pcg()
    machine = TPUMachineModel.from_generation("v5e", 8)
    sim = Simulator(machine)
    assignment, states, _t = dp_assign(pcg, sim, dp=1, tp=8, batch_size=32)
    experts_nodes = [n for n in pcg.compute_nodes()
                     if n.op.op_type == OperatorType.OP_EXPERTS]
    assert experts_nodes
    assert assignment[experts_nodes[0].guid].kind == "expert"
    # and EP beats pure DP in simulation on this model
    res = unity_search(pcg, config, 8, machine=machine, return_result=True)
    dp8 = {n.guid: OpSharding(dp=8) for n in pcg.compute_nodes()}
    t_dp, _ = sim.simulate(pcg, dp8)
    assert res.sim_time <= t_dp * 1.001


def test_moe_experts_ep_strategy_executes():
    """Hand-pinned EP strategy over the (data, model) mesh executes the
    moe_experts path on the 8-device CPU mesh (all-to-all emitted by XLA)."""
    from flexflow_tpu.search.machine_model import TPUMachineModel
    from flexflow_tpu.search.unity import unity_search

    config = FFConfig()
    config.batch_size = 32
    ff = FFModel(config)
    x = ff.create_tensor((32, 128), name="in")
    t = ff.moe_experts(x, num_exp=8, num_select=2, expert_hidden_size=256,
                       alpha=1.0)
    ff.softmax(ff.dense(t, 4))
    machine = TPUMachineModel.from_generation("v5e", 8)
    ff.compile(loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               strategy_fn=lambda pcg: unity_search(pcg, config, 8,
                                                    machine=machine))
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(64, 128)).astype(np.float32)
    ys = rng.integers(0, 4, size=(64, 1)).astype(np.int32)
    ff.fit(xs, ys, epochs=1)
