"""ISSUE 12 satellite pins: paged-KV flags, speculative accounting,
typed admission rejections, and docs wiring."""
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ flag parsing
def test_kv_flags_parse():
    from flexflow_tpu.config import FFConfig

    c = FFConfig()
    c.parse_args(["--kv-cache", "ring", "--max-decode-len", "64"])
    assert c.kv_cache == "ring"
    c = FFConfig()
    c.parse_args(["--kv-block-size", "32", "--kv-pool-blocks", "9",
                  "--kv-dtype", "int8"])
    assert (c.kv_block_size, c.kv_pool_blocks, c.kv_dtype) == \
        (32, 9, "int8")


@pytest.mark.parametrize("argv,match", [
    (["--kv-cache", "circular"], "paged|ring"),
    (["--kv-dtype", "fp8"], "native|int8"),
    (["--kv-block-size", "0"], "kv-block-size"),
    (["--kv-pool-blocks", "-1"], "kv-pool-blocks"),
    (["--kv-cache", "ring", "--kv-pool-blocks", "8"], "only meaningful"),
    (["--kv-cache", "ring", "--kv-dtype", "int8"], "requires"),
])
def test_kv_flag_validation_fails_fast(argv, match):
    from flexflow_tpu.config import FFConfig

    with pytest.raises(ValueError, match=match):
        FFConfig().parse_args(argv)


def test_engine_kv_validation():
    """Engine-level validation mirrors the flags for programmatic use."""
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models.gpt2 import GPT2Config, build_gpt2
    from flexflow_tpu.serving import ServingEngine

    cfg = GPT2Config.tiny(batch_size=2)
    config = FFConfig()
    config.batch_size = 2
    ff = FFModel(config)
    build_gpt2(ff, cfg)
    ff.compile(optimizer=SGDOptimizer(ff),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    with pytest.raises(ValueError, match="paged.*ring|ring.*paged"):
        ServingEngine(ff, kv_cache="circular")
    with pytest.raises(ValueError, match="kv_dtype"):
        ServingEngine(ff, kv_dtype="fp8")
    with pytest.raises(ValueError, match="paged"):
        ServingEngine(ff, kv_cache="ring", kv_dtype="int8")


# ---------------------------------------------------------- stats + ewma
def test_stats_summary_spec_and_kv_fields_gated():
    from flexflow_tpu.serving import ServingStats

    st = ServingStats()
    s = st.summary()
    assert "spec_acceptance" not in s and "kv_bytes_per_token" not in s
    assert st.acceptance_rate() is None
    st.spec_rounds, st.spec_proposed, st.spec_accepted = 3, 9, 6
    st.tokens_generated, st.kv_bytes_read = 10, 12345
    s = st.summary()
    assert s["spec_acceptance"] == round(6 / 9, 4)
    assert s["kv_bytes_per_token"] == 1234.5
    assert s["spec_rounds"] == 3


def test_admission_controller_speculation_ewma():
    from flexflow_tpu.serving import AdmissionController

    c = AdmissionController(alpha=0.5)
    assert c.spec_acceptance is None
    c.observe_speculation(0, 0)  # no proposals: no-op
    assert c.spec_acceptance is None
    c.observe_speculation(4, 4)
    assert c.spec_acceptance == 1.0
    c.observe_speculation(0, 4)
    assert c.spec_acceptance == 0.5  # EWMA with alpha 0.5
    # the cost half needs no special casing: committed tokens per round
    # wall flow through observe_step
    c.observe_step(0.01, 5)
    assert c.token_cost_ms == pytest.approx(2.0)


def test_context_overflow_is_exported_rejection():
    from flexflow_tpu.serving import (ContextOverflowError,
                                      ServingRejection)

    assert issubclass(ContextOverflowError, ServingRejection)
    e = ContextOverflowError("too long", queued=2, active=1)
    assert (e.queued, e.active) == (2, 1)


# ------------------------------------------------------------ docs wiring
def test_decode_perf_doc_linked():
    doc = os.path.join(REPO, "docs", "decode_perf.md")
    assert os.path.exists(doc)
    body = open(doc).read()
    for needle in ("flash-decode", "int8", "speculative", "FF006"):
        assert needle.lower() in body.lower(), f"{needle} missing"
    index = open(os.path.join(REPO, "docs", "index.md")).read()
    assert "decode_perf.md" in index
    serving = open(os.path.join(REPO, "docs", "serving.md")).read()
    assert "decode_perf.md" in serving
    assert "Paged KV cache" in serving
    readme = open(os.path.join(REPO, "README.md")).read()
    assert "decode_perf.md" in readme


def test_static_analysis_doc_mentions_paged_ff006():
    body = open(os.path.join(REPO, "docs",
                             "static_analysis.md")).read()
    assert "check_paged_kv" in body
