"""Fusion pass tests (reference: FFModel::apply_fusion, model.cc:2495;
FusedOp interpreter, src/ops/fused.cu)."""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.ffconst import OperatorType


def build(config):
    ff = FFModel(config)
    x = ff.create_tensor((config.batch_size, 32), name="x")
    t = ff.dense(x, 64, name="d1")
    t = ff.relu(t)
    t = ff.dense(t, 10, name="d2")
    t = ff.softmax(t)
    return ff


def _data(config):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 32)).astype(np.float32)
    w = rng.normal(size=(32, 10)).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    return x, y


def test_apply_fusion_merges_chain():
    config = FFConfig()
    config.batch_size = 32
    config.perform_fusion = True
    config.only_data_parallel = True
    ff = build(config)
    ff.compile(optimizer=SGDOptimizer(ff, lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    fused = [n for n in ff.pcg.compute_nodes()
             if n.op.op_type == OperatorType.OP_FUSED]
    assert len(fused) == 1, [n.name for n in ff.pcg.compute_nodes()]
    # the whole dense-relu-dense-softmax chain collapsed into one region
    assert len(fused[0].op.sub_ops) == 4
    assert len(ff.pcg.compute_nodes()) == 1


def test_fused_training_matches_unfused():
    losses = {}
    for fuse in (False, True):
        config = FFConfig()
        config.batch_size = 32
        config.perform_fusion = fuse
        config.only_data_parallel = True
        ff = build(config)
        ff.compile(optimizer=SGDOptimizer(ff, lr=0.1),
                   loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        x, y = _data(config)
        ff.fit(x, y, epochs=2)
        m = ff.get_perf_metrics()
        losses[fuse] = m.train_correct / max(m.train_all, 1)
    # identical init (weight entries enumerate in the same order) ->
    # identical training trajectory
    assert losses[True] == pytest.approx(losses[False], abs=1e-6)


def test_fusion_cost_model_sees_region():
    """A fused region must cost less memory traffic than the op-by-op sum."""
    from flexflow_tpu.search.machine_model import TPUMachineModel
    from flexflow_tpu.search.simulator import OpSharding, Simulator

    config = FFConfig()
    config.batch_size = 32
    config.perform_fusion = True
    config.only_data_parallel = True
    ff = build(config)
    ff.compile(loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    node = ff.pcg.compute_nodes()[0]
    assert node.op.op_type == OperatorType.OP_FUSED
    in_shapes = [ff.pcg.nodes[g].out_shapes[i] for g, i in node.inputs]
    # flops equals the sum over sub-ops; memory_bytes only boundary traffic
    flops = node.op.flops(in_shapes, node.out_shapes)
    assert flops > 2 * 32 * 32 * 64  # at least the two matmuls
    mb = node.op.memory_bytes(in_shapes, node.out_shapes)
    el_in = int(np.prod(in_shapes[0])) * 4
    el_out = int(np.prod(node.out_shapes[0])) * 4
    assert mb == el_in + el_out


def test_fusion_stops_at_multi_consumer():
    config = FFConfig()
    config.batch_size = 16
    config.perform_fusion = True
    config.only_data_parallel = True
    ff = FFModel(config)
    x = ff.create_tensor((16, 8), name="x")
    a = ff.dense(x, 8, name="a")
    b = ff.relu(a)
    c = ff.tanh(a)  # `a` has two consumers -> cannot fuse past it
    d = ff.add(b, c)
    ff.compile(loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE)
    fused = [n for n in ff.pcg.compute_nodes()
             if n.op.op_type == OperatorType.OP_FUSED]
    names = {n.name for n in ff.pcg.compute_nodes()}
    # `a` must remain standalone (auto-named a_0)
    assert any(n.name.startswith("a") and
               n.op.op_type == OperatorType.OP_LINEAR
               for n in ff.pcg.compute_nodes()), names


def test_fusion_preserves_final_tensor_anchor():
    """compile(final_tensor=...) with --fusion must keep the anchored tensor
    addressable: the anchor acts as a fusion barrier (region tail at most)."""
    import numpy as np

    from flexflow_tpu import FFConfig, FFModel, LossType

    config = FFConfig()
    config.batch_size = 4
    config.perform_fusion = True
    ff = FFModel(config)
    x = ff.create_tensor((4, 8))
    t = ff.relu(x)
    anchor = ff.gelu(t)          # fusable chain relu->gelu
    ff.dense(anchor, 3)          # later sink that must NOT steal the anchor
    ff.compile(loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
               final_tensor=anchor)
    xs = np.random.default_rng(0).normal(size=(4, 8)).astype(np.float32)
    out = np.asarray(ff.executor.make_forward()(ff.params, [xs]))
    assert out.shape == (4, 8), out.shape
    import jax.nn as jnn
    import jax.numpy as jnp

    ref = np.asarray(jnn.gelu(jnn.relu(jnp.asarray(xs))))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
