"""Observability subsystem (flexflow_tpu/obs): tracer span nesting +
Chrome-trace export, the disabled tracer's zero-footprint contract, fit()
step telemetry (compile-vs-steady split), search iteration logs, and the
OpContext profiling threading bugfix."""
import json
import os

import numpy as np
import pytest

from flexflow_tpu import (AdamOptimizer, FFConfig, FFModel, LossType,
                          MetricsType, ActiMode)
from flexflow_tpu.obs import (NoopTracer, SearchLog, StepTelemetry, Tracer,
                              disable, enable, get_tracer, set_tracer)


@pytest.fixture(autouse=True)
def _reset_tracer():
    """Each test starts and ends with the disabled singleton."""
    disable()
    yield
    disable()


def _mlp(batch=16, epochs=2, **cfg_overrides):
    config = FFConfig()
    config.batch_size = batch
    config.epochs = epochs
    for k, v in cfg_overrides.items():
        setattr(config, k, v)
    ff = FFModel(config)
    x_t = ff.create_tensor((batch, 8))
    t = ff.dense(x_t, 16, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 4)
    t = ff.softmax(t)
    ff.compile(optimizer=AdamOptimizer(ff, alpha=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    return ff


def _data(n=64, d=8, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.integers(0, classes, size=(n,)).astype(np.int32)
    return x, y


# ------------------------------------------------------------------- tracer
def test_span_nesting_and_chrome_roundtrip(tmp_path):
    tr = Tracer()
    with tr.span("outer", phase="a"):
        assert tr.depth == 1
        with tr.span("inner"):
            assert tr.depth == 2
        tr.event("marker", k=1)
        tr.counter("gauge", 42)
    assert tr.depth == 0

    path = str(tmp_path / "trace.json")
    tr.write(path)
    data = json.loads(open(path).read())  # must round-trip via json.loads
    evs = data["traceEvents"]
    spans = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert set(spans) == {"outer", "inner"}
    for e in spans.values():
        assert isinstance(e["ts"], (int, float))
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert "tid" in e and "pid" in e
    # nesting: inner is contained in outer's [ts, ts+dur] window
    o, i = spans["outer"], spans["inner"]
    assert o["ts"] <= i["ts"]
    assert i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 1e-3
    assert i["args"]["depth"] == 1 and o["args"]["depth"] == 0
    # instant + counter events well-formed
    phs = {e["ph"] for e in evs}
    assert {"X", "i", "C"} <= phs


def test_complete_event_retroactive():
    tr = Tracer()
    tr.complete("late_span", 0.5, step=3)
    (e,) = tr.events
    assert e["ph"] == "X"
    assert abs(e["dur"] - 0.5e6) < 1.0  # 0.5 s in us
    assert e["args"]["step"] == 3


def test_disabled_tracer_is_inert_and_allocation_free():
    tr = get_tracer()
    assert isinstance(tr, NoopTracer) and not tr.enabled
    # span() returns ONE shared null context manager: the hot loop's
    # per-step cost when tracing is off is a method call, no allocation
    s1 = tr.span("a")
    s2 = tr.span("b")
    assert s1 is s2
    with s1:
        pass
    tr.event("x", y=1)
    tr.complete("x", 1.0)
    tr.counter("c", 2)
    assert len(tr.events) == 0
    tr.write()  # no-op, no file I/O (would raise on a path-less Tracer)


def test_enable_disable_singleton():
    t = enable()
    assert t.enabled and get_tracer() is t
    # second enable returns the same instance
    assert enable() is t
    prev = disable()
    assert prev is t
    assert not get_tracer().enabled


def test_jsonl_event_sink(tmp_path):
    p = str(tmp_path / "events.jsonl")
    tr = Tracer(jsonl_file=p)
    with tr.span("phase"):
        tr.event("tick", n=1)
    tr.close()
    lines = [json.loads(l) for l in open(p) if l.strip()]
    assert len(lines) == 2  # event + completed span
    assert {l["name"] for l in lines} == {"phase", "tick"}


# ------------------------------------------------------- fit tracing + tele
def test_fit_writes_chrome_trace_with_phases(tmp_path):
    trace_path = str(tmp_path / "trace.json")
    ff = _mlp(trace_file=trace_path)
    x, y = _data()
    ff.fit(x, y)
    data = json.loads(open(trace_path).read())
    names = {e["name"] for e in data["traceEvents"] if e["ph"] == "X"}
    assert {"compile", "train_step", "epoch"} <= names
    # eval flushes the trace itself — eval-only workloads get a file too
    ff.eval(x, y)
    data = json.loads(open(trace_path).read())
    names = {e["name"] for e in data["traceEvents"] if e["ph"] == "X"}
    assert "eval" in names


def test_fit_disabled_no_files_no_telemetry(tmp_path, monkeypatch):
    """Observability off: no trace/telemetry file I/O, no StepTelemetry, and
    the hot loop's tracer is the inert singleton."""
    cwd_before = set(os.listdir(tmp_path))
    monkeypatch.chdir(tmp_path)
    ff = _mlp()
    x, y = _data()
    ff.fit(x, y)
    assert ff.get_telemetry() is None
    assert set(os.listdir(tmp_path)) == cwd_before  # no files appeared
    assert len(get_tracer().events) == 0


def test_fit_telemetry_records(tmp_path):
    tel_path = str(tmp_path / "telemetry.json")
    ff = _mlp(epochs=2, telemetry_file=tel_path)
    x, y = _data()
    ff.fit(x, y)
    tel = ff.get_telemetry()
    assert tel is not None
    steps_per_epoch = 64 // 16
    assert tel.steps == steps_per_epoch * 2
    assert len(tel.loss_history) == tel.steps
    assert all(np.isfinite(v) for v in tel.loss_history)
    # compile-vs-steady split: first step carries the jit compile
    assert tel.first_step_s() > tel.steady_step_s()
    data = json.loads(open(tel_path).read())
    assert data["steps"] == tel.steps
    assert data["first_step_s"] >= data["steady_step_s"]
    assert data["compile_overhead_s"] >= 0
    assert data["samples_per_sec"] > 0
    assert len(data["epoch_loss"]) == 2
    # XLA compiled-memory capture is best-effort (CPU exposes a subset of
    # the CompiledMemoryStats fields)
    if data.get("device_memory"):
        assert all(isinstance(v, int) for v in
                   data["device_memory"].values())


def test_step_telemetry_summary_math():
    tel = StepTelemetry(batch_size=10)
    tel.record_step(1.0, 2.0)   # compile step
    tel.record_step(0.1, 1.0)
    tel.record_step(0.2, 0.5)
    tel.record_step(0.1, 0.4)
    tel.finalize()
    assert tel.first_step_s() == 1.0
    assert tel.steady_step_s() == 0.1
    assert tel.samples_per_sec() == pytest.approx(100.0)
    s = tel.summary()
    assert s["compile_overhead_s"] == pytest.approx(0.9)
    assert s["loss_history"] == [2.0, 1.0, 0.5, 0.4]


# ------------------------------------------------------------------- search
def test_search_emits_iteration_events_and_log(tmp_path):
    from flexflow_tpu.search.unity import unity_search

    log_path = str(tmp_path / "search.jsonl")
    tracer = enable()
    config = FFConfig()
    config.batch_size = 32
    config.search_log_file = log_path
    ff = FFModel(config)
    x_t = ff.create_tensor((32, 64))
    t = ff.dense(x_t, 64)
    t = ff.dense(t, 16)
    t = ff.softmax(t)
    pcg = ff.create_pcg()
    unity_search(pcg, config, 4)
    # tracer saw >=1 iteration event + the search span
    names = [e["name"] for e in tracer.events]
    assert "unity_iter" in names
    assert any(e["name"] == "search" and e["ph"] == "X"
               for e in tracer.events)
    # JSONL log is consumable: candidate records carry the required fields
    recs = [json.loads(l) for l in open(log_path) if l.strip()]
    cands = [r for r in recs if r.get("event") == "candidate"]
    assert len(cands) >= 1
    for r in cands:
        assert {"cost_ms", "accepted", "best_ms", "dp", "tp"} <= set(r)
    assert any(r.get("event") == "result" for r in recs)
    # trace_summary.py parses it
    import importlib.util as ilu

    spec = ilu.spec_from_file_location(
        "trace_summary", os.path.join(os.path.dirname(__file__), "..",
                                      "scripts", "trace_summary.py"))
    mod = ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    kind, payload = mod.load(log_path)
    assert kind == "jsonl" and len(payload) == len(recs)
    assert mod.main([log_path]) == 0


def test_mcmc_emits_iteration_log(tmp_path):
    from flexflow_tpu.search.unity import mcmc_optimize

    log_path = str(tmp_path / "mcmc.jsonl")
    config = FFConfig()
    config.batch_size = 16
    config.search_log_file = log_path
    ff = FFModel(config)
    x_t = ff.create_tensor((16, 32))
    t = ff.dense(x_t, 32)
    t = ff.softmax(t)
    pcg = ff.create_pcg()
    mcmc_optimize(pcg, config, 2, iterations=10)
    recs = [json.loads(l) for l in open(log_path) if l.strip()]
    iters = [r for r in recs if r.get("event") == "mcmc"]
    assert len(iters) == 10
    for r in iters:
        assert {"cost_ms", "accepted", "temperature", "best_ms"} <= set(r)


def test_search_log_counts_without_sinks():
    slog = SearchLog()
    slog.log(event="candidate", cost_ms=1.0)
    slog.log(event="candidate", cost_ms=2.0)
    slog.close()
    assert slog.iterations == 2


# ------------------------------------------------- OpContext profiling fix
def test_opcontext_profiling_threaded(monkeypatch):
    """executor.make_* must pass config.profiling into OpContext (it was
    silently dropped before the obs PR)."""
    ff = _mlp(epochs=1)
    ff.config.profiling = True
    ff.executor._forward_jit = None  # force a rebuild that re-captures
    seen = []
    node = next(n for n in ff.pcg.compute_nodes())
    orig = node.op.forward

    def spy(params, inputs, ctx):
        seen.append(ctx.profiling)
        return orig(params, inputs, ctx)

    monkeypatch.setattr(node.op, "forward", spy)
    x, _ = _data(n=16)
    fwd = ff.executor.make_forward()
    fwd(ff.params, [x])
    assert seen and all(seen), "profiling flag not threaded into OpContext"


def test_named_scope_in_hlo():
    """Per-op jax.named_scope makes node names visible to XLA metadata."""
    import jax

    ff = _mlp(epochs=1)
    x, _ = _data(n=16)

    def f(params, xs):
        from flexflow_tpu.ops.base import OpContext

        vals = ff.executor.forward_outputs(
            params, ff.executor._bind_inputs(xs),
            OpContext(training=False, rng=None, mesh=ff.mesh))
        return vals[ff.final_guid][0]

    hlo = jax.jit(f).lower(ff.params, [x]).as_text()
    # dense layer names appear in op metadata / scopes
    assert "dense" in hlo.lower()
