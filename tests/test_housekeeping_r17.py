"""Round-17 housekeeping (ISSUE 17 satellites):

* `--serve-loop` flag: parse-time validation, preflight validation of
  programmatic assignment, documented in python_api.md
  (check_docs_flags stays green).
* both serving bench legs emit `host_overhead_fraction` for whichever
  loop ran plus a `serve_loop` key identifying it, and the sync-vs-
  async comparison keys (static pin — the full legs are too heavy for
  tier-1, the r14 idiom).
* host-overhead math with the ISSUE 17 overlap bucket: overlapped host
  work widens the DENOMINATOR only; with no overlap the r16 fraction
  is unchanged.
"""
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))


def _read(name):
    with open(os.path.join(REPO, name)) as f:
        return f.read()


# ------------------------------------------------------------------ flag
def test_serve_loop_flag_parse_and_preflight():
    from flexflow_tpu import FFConfig
    from flexflow_tpu.resilience.preflight import (PreflightError,
                                                   preflight_config)

    cfg = FFConfig()
    assert cfg.serve_loop == "sync"  # default stays the reference loop
    cfg.parse_args(["--serve-loop", "async"])
    assert cfg.serve_loop == "async"
    with pytest.raises(ValueError, match="sync\\|async"):
        FFConfig().parse_args(["--serve-loop", "turbo"])
    bad = FFConfig()
    bad.serve_loop = "bogus"  # programmatic assignment: preflight's job
    with pytest.raises(PreflightError, match="serve-loop"):
        preflight_config(bad)
    preflight_config(FFConfig())


def test_serve_loop_flag_documented():
    import check_docs_flags

    assert check_docs_flags.main([]) == 0
    assert "--serve-loop" in _read("docs/python_api.md")


# ----------------------------------------------------------------- bench
def test_bench_serving_legs_emit_serve_loop_and_hof_keys():
    """Both serving bench legs identify the loop that ran and carry the
    sync-vs-async host-overhead comparison (static pin)."""
    src = _read("bench.py")
    for key in (
            # serving leg: headline loop id + comparison sub-leg
            # (the per-loop keys are f-string emissions over
            # ("sync", "async") — pinned as templates below)
            "serving_serve_loop", "serving_host_overhead_fraction",
            'f"serving_{loop}_tokens_per_s"',
            "serving_loop_cpu_simulated", "serving_async_hof_vs_sync",
            "serving_async_hof_below_sync", "serving_async_host_syncs",
            # fleet leg: loop id + async sub-run
            "fleet_serve_loop", "fleet_host_overhead_fraction",
            "fleet_sync_host_overhead_fraction",
            "fleet_async_host_overhead_fraction",
            "fleet_async_host_syncs"):
        assert key in src, f"bench key {key} missing"
    # the f-string emission covers both loops' hof keys
    assert 'f"serving_{loop}_host_overhead_fraction"' in src


# ------------------------------------------------------------- accounting
def test_host_overhead_fraction_overlap_math():
    """Overlap widens the denominator only; zero overlap reproduces the
    r16 fraction exactly (test_housekeeping_r16 pins that case)."""
    from flexflow_tpu.serving.engine import ServingStats
    from flexflow_tpu.serving.fleet import FleetStats

    st = ServingStats()
    st.host_dispatch_s = 1.0
    st.host_device_s = 5.0
    st.host_bookkeep_s = 1.0
    st.host_overlap_s = 1.0
    assert st.host_overhead_fraction() == 0.25
    st.host_overlap_s = 0.0
    assert st.host_overhead_fraction() == pytest.approx(2.0 / 7.0)
    fs = FleetStats(replicas=1, dispatches=[0])
    fs.host_dispatch_s = 2.0
    fs.host_device_s = 4.0
    fs.host_overlap_s = 2.0
    assert fs.host_overhead_fraction() == 0.25
    # host_syncs surfaces in both summaries only when nonzero
    assert "host_syncs" not in fs.summary()
    fs.host_syncs = 3
    assert fs.summary()["host_syncs"] == 3
    st.host_syncs = 0
    assert "host_syncs" not in st.summary()


def test_fleet_retires_overlap_and_syncs_across_loop_rebuilds():
    """A drain/rejoin rebuild must not lose the retired loop's overlap
    wall or sync count (the 4-element retired_host contract)."""
    from flexflow_tpu.serving.fleet import FleetReplica

    rep = FleetReplica(0, engine=None)
    assert rep.retired_host == [0.0, 0.0, 0.0, 0.0]
    assert rep.retired_syncs == 0
