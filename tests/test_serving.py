"""Serving engine (ISSUE 6, flexflow_tpu/serving, docs/serving.md):
prefill/decode equivalence against the whole-sequence forward, the
continuous-batching scheduler's isolation/recycling/backpressure
invariants, the recompile-free decode contract, the serving-objective
search (latency-bounded throughput, selfchecked), elastic mid-serve
re-search, and the satellite fixes (predict tail batch, CacheOp+remat
inversion, flags, telemetry serving block)."""
import json
import os

import numpy as np
import pytest

from flexflow_tpu import (AdamOptimizer, DataType, FFConfig, FFModel,
                          LossType, SGDOptimizer)
from flexflow_tpu.models.gpt2 import GPT2Config, build_gpt2
from flexflow_tpu.models.transformer import (TransformerConfig,
                                             build_transformer_decoder)
from flexflow_tpu.serving import (ContinuousBatchScheduler, QueueFullError,
                                  Request, ServingEngine, bucket_for)
from flexflow_tpu.serving.kvcache import DecodeState


def _compile_gpt2(batch=8):
    cfg = GPT2Config.tiny(batch_size=batch)
    config = FFConfig()
    config.batch_size = cfg.batch_size
    ff = FFModel(config)
    build_gpt2(ff, cfg)
    ff.compile(optimizer=SGDOptimizer(ff),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff, cfg


@pytest.fixture(scope="module")
def gpt2():
    return _compile_gpt2()


def _teacher_forced_decode(ff, seq, prompt_len, max_len, bucket):
    """Prefill ``prompt_len`` tokens, then decode with the TRUE next token
    fed back each step (teacher forcing) — returns per-position decode
    logits aligned with the full forward's rows."""
    import jax.numpy as jnp

    pre = ff.executor.make_prefill_step(bucket_len=bucket,
                                        max_decode_len=max_len)
    padded = np.zeros((1, bucket), np.int32)
    padded[0, :prompt_len] = seq[0, :prompt_len]
    logits_p, last, cache = pre(ff.params, [jnp.asarray(padded)],
                                jnp.asarray([prompt_len], np.int32))
    state = DecodeState(caches=cache,
                        lengths=jnp.asarray([prompt_len], jnp.int32))
    dec = ff.executor.make_decode_step(max_len, exact=True)
    rows = {}
    for t in range(prompt_len, seq.shape[1]):
        lg, state = dec(ff.params, [jnp.asarray(seq[:, t:t + 1])], state)
        rows[t] = np.asarray(lg)[0]
    return np.asarray(logits_p), np.asarray(last), rows


def _full_forward_logits(ff, seq, batch):
    fwd = ff.executor.make_forward()
    return np.asarray(fwd(ff.params, [np.repeat(seq, batch, axis=0)]))[0]


def test_prefill_decode_bitwise_gpt2(gpt2):
    """Acceptance gate: prefill+decode logits BITWISE-match the
    whole-sequence forward (exact decode mode routes the 1-token score
    product through the same-shape GEMM)."""
    ff, cfg = gpt2
    rng = np.random.default_rng(0)
    seq = rng.integers(0, cfg.vocab_size,
                       size=(1, cfg.seq_len)).astype(np.int32)
    full = _full_forward_logits(ff, seq, cfg.batch_size)
    L, bucket = 5, 8
    logits_p, last, rows = _teacher_forced_decode(
        ff, seq, L, cfg.seq_len, bucket)
    # prefill rows [0, L) match the full forward bitwise
    assert np.array_equal(logits_p[0, :L], full[:L])
    # the prefill's next-token logits are the row at L-1
    assert np.array_equal(last[0], full[L - 1])
    # every decoded position matches bitwise
    for t, row in rows.items():
        assert np.array_equal(row, full[t]), f"decode row {t} diverged"


def test_prefill_decode_bitwise_transformer_decoder():
    cfg = TransformerConfig.tiny(batch_size=4)
    config = FFConfig()
    config.batch_size = cfg.batch_size
    ff = FFModel(config)
    build_transformer_decoder(ff, cfg, vocab_size=60)
    ff.compile(optimizer=SGDOptimizer(ff),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    rng = np.random.default_rng(1)
    seq = rng.integers(0, 60, size=(1, cfg.seq_len)).astype(np.int32)
    full = _full_forward_logits(ff, seq, cfg.batch_size)
    logits_p, last, rows = _teacher_forced_decode(
        ff, seq, 4, cfg.seq_len, 4)
    assert np.array_equal(logits_p[0, :4], full[:4])
    for t, row in rows.items():
        assert np.array_equal(row, full[t]), f"decode row {t} diverged"


def test_lstm_decode_state():
    """The NMT-family building block: the LSTM's recurrent carry is its
    decode state. Prefill gathers the carry at the TRUE prompt length
    (not the padded tail); decode continues within float32 ulp noise of
    the whole-sequence forward and greedy tokens agree exactly."""
    config = FFConfig()
    config.batch_size = 4
    ff = FFModel(config)
    ids = ff.create_tensor((4, 12), dtype=DataType.DT_INT32, name="lm_ids")
    t = ff.embedding(ids, 50, 16, name="lm_embed")
    t, _state = ff.lstm(t, 16, name="lm_lstm")
    ff.dense(t, 50, name="lm_head")
    ff.compile(optimizer=SGDOptimizer(ff),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    rng = np.random.default_rng(0)
    seq = rng.integers(0, 50, size=(1, 12)).astype(np.int32)
    full = _full_forward_logits(ff, seq, 4)
    L = 4
    logits_p, last, rows = _teacher_forced_decode(ff, seq, L, 12, 8)
    # prefill's next-token logits come from the carry at length-1 — the
    # padded tail the scan marched through must not leak in
    assert np.array_equal(last[0], full[L - 1])
    for t_, row in rows.items():
        np.testing.assert_allclose(row, full[t_], rtol=1e-5, atol=1e-5)
        assert int(np.argmax(row)) == int(np.argmax(full[t_]))


def test_decode_recompile_free(gpt2):
    """Acceptance gate: after warmup the decode loop never recompiles —
    one jit cache entry across varied prompt lengths, slot churn and
    request mixes."""
    ff, cfg = gpt2
    eng = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len,
                        buckets=(4, 8))
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 100, size=n).tolist()
               for n in (3, 5, 7, 2, 6, 4)]
    outs = eng.generate(prompts, max_new_tokens=4)
    assert all(len(o) == 4 for o in outs)
    assert eng.decode_compiles == 1, \
        f"decode recompiled: {eng.decode_compiles} cache entries"
    # prefill compiles once per BUCKET, not per prompt length
    pre = ff.executor._serving_jits[("prefill", 4, cfg.seq_len)]
    assert pre._cache_size() == 1


def test_no_cross_request_cache_leakage(gpt2):
    """Greedy continuations are identical whether a request runs alone or
    co-batched with strangers — slots share nothing."""
    ff, cfg = gpt2
    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, 100, size=int(n)).tolist()
               for n in rng.integers(3, 8, size=5)]
    eng = ServingEngine(ff, n_slots=3, max_decode_len=cfg.seq_len)
    batched = eng.generate(prompts, max_new_tokens=5)
    for i, p in enumerate(prompts):
        solo_eng = ServingEngine(ff, n_slots=1,
                                 max_decode_len=cfg.seq_len)
        solo = solo_eng.generate([p], max_new_tokens=5)
        assert solo[0] == batched[i], f"request {i} leaked across slots"


def test_eos_slot_recycling_and_continuous_admission(gpt2):
    """More requests than slots: EOS/length-finished slots are recycled
    into the waiting queue until everything drains."""
    ff, cfg = gpt2
    eng = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 100, size=4).tolist() for _ in range(6)]
    base = eng.generate(prompts, max_new_tokens=6)
    eos = base[0][1]  # force an early stop for at least request 0
    eng2 = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len)
    outs = eng2.generate(prompts, max_new_tokens=6, eos_id=eos)
    assert len(outs) == 6 and all(len(o) >= 1 for o in outs)
    assert outs[0][-1] == eos and len(outs[0]) == 2
    for o in outs:  # eos never appears mid-stream
        assert eos not in o[:-1]
    assert eng2.stats.requests_served == 6
    assert eng2.stats.queue_depth_hwm >= 4  # queue really backed up


def test_scheduler_deterministic_under_seeded_arrival(gpt2):
    """The schedule (and therefore every token stream) is a deterministic
    function of the submission sequence — greedy results are ALSO
    invariant to the arrival order itself (per-request isolation)."""
    ff, cfg = gpt2
    rng = np.random.default_rng(4)
    prompts = [rng.integers(0, 100, size=int(n)).tolist()
               for n in rng.integers(3, 8, size=5)]
    order = np.random.default_rng(7).permutation(5)
    shuffled = [prompts[i] for i in order]

    def run(ps, temp=0.0, seed=0):
        eng = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len)
        return eng.generate(ps, max_new_tokens=4, temperature=temp,
                            top_k=3, seed=seed)

    a, b = run(shuffled), run(shuffled)
    assert a == b, "same seeded arrival produced different streams"
    plain = run(prompts)
    for i, pos in enumerate(order):  # greedy is arrival-order invariant
        assert a[i] == plain[pos]
    s1, s2 = run(shuffled, temp=0.9, seed=11), run(shuffled, temp=0.9,
                                                   seed=11)
    assert s1 == s2, "sampled decode not deterministic under a seed"
    s3 = run(shuffled, temp=0.9, seed=12)
    assert s1 != s3, "seed does not vary the sampled stream"


def test_scheduler_backpressure_and_capacity():
    sched = ContinuousBatchScheduler(n_slots=1, max_queue=2, max_len=32)
    sched.submit(Request(prompt=np.zeros(4, np.int32), max_new_tokens=4))
    sched.submit(Request(prompt=np.zeros(4, np.int32), max_new_tokens=4))
    with pytest.raises(QueueFullError):
        sched.submit(Request(prompt=np.zeros(4, np.int32),
                             max_new_tokens=4))
    with pytest.raises(ValueError, match="ring capacity"):
        ContinuousBatchScheduler(n_slots=1, max_queue=8, max_len=16).submit(
            Request(prompt=np.zeros(10, np.int32), max_new_tokens=10))
    assert bucket_for(5, (4, 8, 16)) == 8
    with pytest.raises(ValueError, match="largest prefill bucket"):
        bucket_for(99, (4, 8, 16))
    # a prompt no bucket covers is refused AT SUBMIT — never after
    # next_action() already claimed a slot (slot-pool corruption)
    narrow = ContinuousBatchScheduler(n_slots=1, max_queue=8,
                                      buckets=(4,), max_len=32)
    with pytest.raises(ValueError, match="largest prefill bucket"):
        narrow.submit(Request(prompt=np.zeros(8, np.int32),
                              max_new_tokens=2))
    assert narrow.queued == 0 and not narrow.active


def test_serving_engine_rejects_non_autoregressive():
    from flexflow_tpu.models.transformer import build_transformer

    cfg = TransformerConfig.tiny(batch_size=8)
    config = FFConfig()
    config.batch_size = 8
    ff = FFModel(config)
    build_transformer(ff, cfg)  # bidirectional encoder + pooled head
    ff.compile(optimizer=SGDOptimizer(ff),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    with pytest.raises(ValueError):
        ServingEngine(ff)


def test_serving_search_beats_naive_dp(monkeypatch):
    """Acceptance gate: search_all(objective='serving') on a simulated
    8-device mesh returns a feasible plan whose simulated tokens/sec beats
    naive dp replication while meeting the SLO, under
    FLEXFLOW_TPU_SEARCH_SELFCHECK (cached == cold pricing)."""
    from flexflow_tpu.search.machine_model import TPUMachineModel
    from flexflow_tpu.search.unity import search_all

    monkeypatch.setenv("FLEXFLOW_TPU_SEARCH_SELFCHECK", "1")
    cfg = GPT2Config()  # gpt2-small-sized graph; pcg only, no params
    config = FFConfig()
    config.batch_size = cfg.batch_size
    config.max_inflight = 8
    config.max_decode_len = 128
    config.slo_p99_ms = 50.0
    ff = FFModel(config)
    build_gpt2(ff, cfg)
    pcg = ff.create_pcg()
    machine = TPUMachineModel.from_generation("v5e", 8)
    plan = search_all(pcg, config, 8, objective="serving", machine=machine)
    assert plan.feasible
    assert plan.sim_p99_ms <= 50.0
    assert plan.sim_memory <= machine.hbm_capacity
    naive = [c for c in plan.ranked if tuple(c.mesh_shape) == (8, 1)]
    assert naive, "naive dp candidate missing from the ranked chain"
    assert plan.sim_tokens_per_s > naive[0].sim_tokens_per_s, \
        "searched serving plan does not beat naive dp"
    # the decode-state layout axis is really searched: for the winning
    # mesh, the sharded KV layout prices no worse than replicated
    twins = {c.layout: c for c in plan.ranked
             if tuple(c.mesh_shape) == tuple(plan.mesh_shape)}
    if "sharded" in twins and "replicated" in twins:
        assert twins["sharded"].sim_tokens_per_s >= \
            twins["replicated"].sim_tokens_per_s
    with pytest.raises(ValueError, match="objective"):
        search_all(pcg, config, 8, objective="latency")


def test_elastic_replan_mid_serve_keeps_answers_identical(gpt2):
    """PR 4/5 carry-over: losing chips mid-serve re-searches (warm
    delta-cost sim) and rebuilds the serving jits; the in-flight
    DecodeState survives, so continuations are bit-identical to an
    uninterrupted run."""
    ff, cfg = gpt2
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, 100, size=4).tolist() for _ in range(4)]
    eng = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len)
    base = eng.generate(prompts, max_new_tokens=5)
    eng2 = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len)
    first = eng2.generate(prompts[:2], max_new_tokens=5)
    plan = eng2.elastic_replan(4)  # half the fleet gone
    assert plan.mesh_shape[0] * plan.mesh_shape[1] <= 4
    rest = eng2.generate(prompts[2:], max_new_tokens=5)
    assert first == base[:2] and rest == base[2:]
    # the warm simulator was reused: a second replan shares its caches
    sim = eng2._search_sim
    assert sim is not None
    hits0 = sim.cost_cache_hits
    eng2.elastic_replan(2)
    assert eng2._search_sim is sim and sim.cost_cache_hits > hits0


def test_cacheop_graphs_remat(recwarn):
    """ISSUE 6 inversion of the old 'CacheOp graphs opt out of remat'
    rule: cache state now threads through the checkpointed blocks, so a
    cache-carrying model trains under --remat without a fallback."""
    config = FFConfig()
    config.batch_size = 16
    config.remat = "selective"
    from flexflow_tpu.ffconst import ActiMode

    ff = FFModel(config)
    x = ff.create_tensor((16, 32), name="in")
    h = ff.dense(x, 32, activation=ActiMode.AC_MODE_RELU, name="d1")
    h = ff.cache(h, num_batches=2, name="hcache")
    h = ff.dense(h, 32, name="d2")
    ff.softmax(ff.dense(h, 4, name="cls"))
    ff.compile(optimizer=AdamOptimizer(ff, alpha=1e-3),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(32, 32)).astype(np.float32)
    ys = rng.integers(0, 4, size=(32, 1)).astype(np.int32)
    ff.fit(xs, ys, epochs=1)
    assert ff.executor.remat_plan is not None, \
        "CacheOp graph fell back off the remat path"
    assert not [w for w in recwarn.list
                if "remat disabled" in str(w.message)]


def test_predict_pads_tail_batch_single_compile(gpt2):
    """Satellite: predict's final partial batch is padded-and-trimmed
    (one jit specialization) and host transfer happens once."""
    ff, cfg = gpt2
    ff.executor._forward_jit = None  # fresh forward: count its compiles
    rng = np.random.default_rng(6)
    x = rng.integers(0, 100, size=(13, cfg.seq_len)).astype(np.int32)
    out = ff.predict(x)
    assert out.shape[0] == 13
    fwd = ff.executor.make_forward()
    assert fwd._cache_size() == 1, "tail batch forced a second compile"
    ref = np.asarray(fwd(ff.params, [np.repeat(x[12:13], cfg.batch_size,
                                               axis=0)]))[0]
    assert np.array_equal(out[12], ref)


def test_serving_flags_parse_and_validate():
    config = FFConfig()
    config.parse_args(["--serve", "--max-decode-len", "256",
                       "--max-inflight", "16", "--slo-p99-ms", "12.5"])
    assert config.serve and config.max_decode_len == 256
    assert config.max_inflight == 16 and config.slo_p99_ms == 12.5
    with pytest.raises(ValueError, match="max-decode-len"):
        FFConfig().parse_args(["--max-decode-len", "0"])
    with pytest.raises(ValueError, match="max-inflight"):
        FFConfig().parse_args(["--max-inflight", "0"])
    with pytest.raises(ValueError, match="slo-p99-ms"):
        FFConfig().parse_args(["--slo-p99-ms", "-1"])


def test_serving_telemetry_block_and_trace_summary(gpt2, tmp_path,
                                                   capsys):
    """Obs satellite: StepTelemetry gains a 'serving' block and
    trace_summary prints the serving digest from both the telemetry
    record and the prefill/decode tracer spans."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import trace_summary

    ff, cfg = gpt2
    rng = np.random.default_rng(8)
    prompts = [rng.integers(0, 100, size=4).tolist() for _ in range(3)]
    ff._telemetry_requested = True
    eng = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len)
    eng.generate(prompts, max_new_tokens=3)
    tel = ff.get_telemetry()
    blk = tel.summary()["serving"]
    assert blk["requests_served"] == 3
    assert blk["tokens_generated"] == 9
    assert blk["queue_depth_hwm"] >= 1
    assert blk["p99_token_ms"] > 0
    # telemetry digest
    f = tmp_path / "tel.json"
    tel.write(str(f))
    trace_summary.main([str(f)])
    out = capsys.readouterr().out
    assert "serving: 3 requests, 9 tokens" in out
    # trace-span digest
    trace = {"traceEvents": [
        {"ph": "X", "name": "decode_step", "dur": 1000.0},
        {"ph": "X", "name": "decode_step", "dur": 3000.0},
        {"ph": "X", "name": "prefill", "dur": 2000.0}]}
    tf = tmp_path / "trace.json"
    tf.write_text(json.dumps(trace))
    trace_summary.main([str(tf)])
    out = capsys.readouterr().out
    assert "serving digest: 2 decode steps" in out and "1 prefills" in out


def test_serving_rejects_fused_stateful_regions():
    """--fusion folds attention/position constants into OP_FUSED regions
    the serving machinery cannot thread decode state through — the engine
    must refuse loudly instead of generating history-free garbage."""
    cfg = GPT2Config.tiny(batch_size=8)
    config = FFConfig()
    config.batch_size = 8
    config.perform_fusion = True
    config.only_data_parallel = True
    ff = FFModel(config)
    build_gpt2(ff, cfg)
    ff.compile(optimizer=SGDOptimizer(ff),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    with pytest.raises(NotImplementedError, match="fusion"):
        ServingEngine(ff, max_decode_len=cfg.seq_len)


def test_position_table_bounds_rejected_at_admission(gpt2):
    """ISSUE 12 satellite: a decode ring longer than the position-
    embedding table used to warn-and-clamp at engine construction; now
    the table bound is the engine's max supported CONTEXT and admission
    rejects a too-long request with a typed ServingRejection naming the
    limit — a request that fits still serves at full ring capacity."""
    from flexflow_tpu.serving.scheduler import (ContextOverflowError,
                                                ServingRejection)

    ff, cfg = gpt2
    # pool sized in blocks of 16 over max_decode_len 1024; the position
    # table (seq_len) is the binding context bound
    eng = ServingEngine(ff, n_slots=2, max_decode_len=1024)
    assert eng.max_context == cfg.seq_len
    assert eng.max_decode_len == 1024  # capacity no longer clamped
    # a request whose prompt + max_new exceeds the table is REJECTED at
    # admission, naming the max supported context
    outs = eng.generate([[1, 2, 3]], max_new_tokens=cfg.seq_len + 8)
    assert outs[0] == []  # shed at admission, empty continuation
    sched_probe = eng.stats
    assert sched_probe.outcomes.get("shed", 0) == 1
    from flexflow_tpu.serving.scheduler import (ContinuousBatchScheduler,
                                                Request)

    sched = ContinuousBatchScheduler(n_slots=2, max_len=1024)
    req = Request(prompt=np.arange(4, dtype=np.int32),
                  max_new_tokens=cfg.seq_len + 8)
    with pytest.raises(ContextOverflowError,
                       match="max supported context") as ei:
        eng.admit(sched, req)
    assert isinstance(ei.value, ServingRejection)
    assert str(cfg.seq_len) in str(ei.value)
    # a request inside the bound serves normally
    outs = eng.generate([[1, 2, 3]], max_new_tokens=4)
    assert len(outs[0]) == 4


def test_pipeline_microbatches_position_constants():
    """Rider fix: a GPipe stage slices batch-shaped position-id constants
    to its microbatch rows — previously gpt2 under a searched pipeline
    died on (microbatch, s, d) + (batch, s, d) broadcasting."""
    from flexflow_tpu.parallel.strategy import data_parallel_strategy

    cfg = GPT2Config.tiny(batch_size=8)
    config = FFConfig()
    config.batch_size = 8
    ff = FFModel(config)
    build_gpt2(ff, cfg)

    def strategy_fn(pcg):
        s = data_parallel_strategy(pcg, 2)
        s.pipeline = (2, 1, 4)  # pp=2, dp=1 -> 2-row microbatches
        return s

    ff.compile(optimizer=SGDOptimizer(ff),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               strategy_fn=strategy_fn)
    rng = np.random.default_rng(0)
    stream = rng.integers(0, cfg.vocab_size, size=(16, cfg.seq_len + 1))
    perf = ff.fit(stream[:, :-1].astype(np.int32),
                  stream[:, 1:].astype(np.int32), epochs=1)
    assert perf is not None


def test_model_generate_api(gpt2):
    """model.generate: greedy default, engine cached across calls, EOS
    threaded, sampling knobs accepted."""
    ff, cfg = gpt2
    prompts = [[1, 2, 3], [4, 5, 6, 7]]
    a = ff.generate(prompts, max_new_tokens=4)
    b = ff.generate(prompts, max_new_tokens=4)
    assert a == b and all(len(g) == 4 for g in a)
    assert ff._serving_engine is not None
    s = ff.generate(prompts, max_new_tokens=4, temperature=0.7, top_k=4,
                    seed=3)
    assert all(len(g) == 4 for g in s)
    # eos_id is per-call: a prior call's EOS must not leak through the
    # cached engine and truncate an eos-less call
    eos = a[0][1]
    cut = ff.generate(prompts, max_new_tokens=4, eos_id=eos)
    assert len(cut[0]) == 2 and cut[0][-1] == eos
    again = ff.generate(prompts, max_new_tokens=4)
    assert again == a, "cached engine leaked a previous call's eos_id"
