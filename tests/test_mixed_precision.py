"""Mixed-precision (bf16 compute) path.

The reference trains fp32 only; bf16 compute is the TPU-native equivalent of
its DataType surface (ffconst.h DT_HALF exists but kernels are fp32). Recipe
under test: compute_dtype=DT_BFLOAT16 casts activations/matmul inputs to bf16
inside the jitted step while master weights, loss, and normalization stay
float32 (flexflow_tpu/execution/executor.py::_cast_for_compute).
"""
import numpy as np
import pytest

from flexflow_tpu import (ActiMode, AdamOptimizer, DataType, FFConfig,
                          FFModel, LossType, MetricsType)


def _build_mlp(config):
    ff = FFModel(config)
    x = ff.create_tensor((config.batch_size, 16), dtype=DataType.DT_FLOAT)
    t = ff.dense(x, 32, activation=ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.layer_norm(t, axes=[-1], name="ln")
    t = ff.dense(t, 10, name="fc2")
    ff.softmax(t, name="out")
    return ff


def test_bf16_training_loss_decreases_and_master_weights_stay_f32():
    import jax
    import jax.random as jrandom

    config = FFConfig()
    config.batch_size = 32
    config.compute_dtype = DataType.DT_BFLOAT16
    ff = _build_mlp(config)
    ff.compile(optimizer=AdamOptimizer(ff, alpha=1e-2),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])

    for leaf in jax.tree.leaves(ff.params):
        assert leaf.dtype == np.float32

    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 16)).astype(np.float32)
    y = (x[:, :10].argmax(axis=1)).astype(np.int32)

    step = ff.executor.make_train_step()
    params, opt_state = ff.params, ff.opt_state
    losses = []
    for i in range(30):
        params, opt_state, loss, _ = step(params, opt_state, [x], y,
                                          jrandom.PRNGKey(i))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    for leaf in jax.tree.leaves(params):
        assert leaf.dtype == np.float32


def test_bf16_forward_close_to_f32():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(8, 16)).astype(np.float32)

    outs = {}
    for cd in (DataType.DT_NONE, DataType.DT_BFLOAT16):
        config = FFConfig()
        config.batch_size = 8
        config.compute_dtype = cd
        config.seed = 7
        ff = _build_mlp(config)
        ff.compile(loss_type=LossType.LOSS_CATEGORICAL_CROSSENTROPY)
        fwd = ff.executor.make_forward()
        outs[cd] = np.asarray(fwd(ff.params, [x]), dtype=np.float32)

    np.testing.assert_allclose(outs[DataType.DT_NONE],
                               outs[DataType.DT_BFLOAT16],
                               atol=3e-2, rtol=3e-2)


def test_compute_dtype_cli_flag():
    config = FFConfig()
    config.parse_args(["--compute-dtype", "bf16"])
    assert config.compute_dtype == DataType.DT_BFLOAT16
    config.parse_args(["--compute-dtype", "float32"])
    assert config.compute_dtype == DataType.DT_FLOAT
    with pytest.raises(ValueError):
        config.parse_args(["--compute-dtype", "int7"])
