"""ShardLint (ISSUE 7, flexflow_tpu/analysis, docs/static_analysis.md):
the placement-lattice abstract interpreter, rules FF001-FF006, cascade
stage 0 (statically-invalid winner degrades with ZERO compile/probe
executions), Unity-search candidate pruning, the pre-serve FF005 gate
with its runtime backstop, the graph-level wrong-reshard chaos injection
shared by the static and dynamic checks, and the CLI."""
import json

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.analysis import (BufferRef, DonationSpec,
                                   StaticAnalysisError, analyze_model,
                                   analyze_strategy, check_donation,
                                   check_remat, check_rng_streams,
                                   check_serving_graph, check_shapes,
                                   donation_spec_for_training, interpret)
from flexflow_tpu.parallel.strategies import hybrid_data_tensor_strategy
from flexflow_tpu.parallel.strategy import data_parallel_strategy
from flexflow_tpu.resilience import ChaosPlan, inject_wrong_reshard

BATCH = 8


def _mlp3(ff=None):
    """3-dense MLP (softmax head: the loss consumes probabilities) whose
    hybrid strategy has a row-parallel middle layer — a partial-sum
    producer with consumers, the graph-defect injection site."""
    ff = ff or FFModel(FFConfig())
    x = ff.create_tensor((BATCH, 16), name="x")
    t = ff.dense(x, 32, name="d1")
    t = ff.relu(t)
    t = ff.dense(t, 32, name="d2")
    t = ff.relu(t)
    t = ff.dense(t, 10, name="d3")
    t = ff.softmax(t, name="probs")
    return ff


def _pcg_and_hybrid(dp=4, tp=2):
    ff = _mlp3()
    pcg = ff.create_pcg()
    return pcg, hybrid_data_tensor_strategy(pcg, dp, tp)


def _compiled_hybrid(**cfg_kw):
    cfg = FFConfig()
    cfg.batch_size = BATCH
    for k, v in cfg_kw.items():
        setattr(cfg, k, v)
    ff = _mlp3(FFModel(cfg))
    ff.compile(optimizer=SGDOptimizer(ff, lr=0.05),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               strategy_fn=lambda p: hybrid_data_tensor_strategy(p, 4, 2))
    return ff


def _data():
    # modest input scale: random-init logits stay in softmax's live range
    # (saturated clipped cross-entropy has exactly-zero gradients, which
    # would make the audit's grad-norm comparison vacuous)
    rng = np.random.default_rng(0)
    return (0.25 * rng.normal(size=(64, 16)).astype(np.float32),
            rng.integers(0, 10, size=64).astype(np.int32))


# =================================================== clean strategies
def test_clean_strategies_zero_diagnostics():
    """dp / tp / hybrid / pipeline / remat plans all analyze clean — the
    zero-false-positive contract that lets the search prune on errors."""
    for build in (
        lambda p: data_parallel_strategy(p, 8),
        lambda p: hybrid_data_tensor_strategy(p, 1, 2),    # pure tp
        lambda p: hybrid_data_tensor_strategy(p, 4, 2),    # hybrid
    ):
        pcg = _mlp3().create_pcg()
        rep = analyze_strategy(pcg, build(pcg))
        assert rep.ok, rep.describe()
    pcg = _mlp3().create_pcg()
    s = data_parallel_strategy(pcg, 8)
    s.pipeline = (2, 4, 4)
    assert analyze_strategy(pcg, s).ok
    for level in ("none", "selective", "full"):
        pcg = _mlp3().create_pcg()
        s = data_parallel_strategy(pcg, 8)
        s.remat = level
        rep = analyze_strategy(pcg, s)
        assert rep.ok, (level, rep.describe())


def test_searched_winner_with_parallel_ops_clean():
    """A searched tp winner's PCG (Reduction/parallel-op nodes inserted by
    insert_parallel_ops) analyzes clean: every partial producer is
    matched by its Reduction."""
    cfg = FFConfig()
    cfg.batch_size = BATCH
    cfg.search_budget = 8
    ff = _mlp3(FFModel(cfg))
    ff.compile(optimizer=SGDOptimizer(ff, lr=0.05),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    rep = analyze_model(ff)
    assert rep.ok, rep.describe()
    assert set(rep.checked) >= {"FF001", "FF002", "FF003", "FF004",
                                "FF006"}


# ============================================ FF001: partial-sum defects
def test_ff001_dropped_reduction():
    pcg, s = _pcg_and_hybrid()
    desc = inject_wrong_reshard(pcg, s, mode="drop")
    assert "d2" in desc
    rep = analyze_strategy(pcg, s)
    errs = [d for d in rep.errors if d.rule_id == "FF001"]
    assert errs, rep.describe()
    # the diagnostic names the producing node and speaks partial_sum
    assert "d2" in errs[0].message and "partial_sum" in errs[0].message


def test_ff001_doubled_reduction():
    pcg, s = _pcg_and_hybrid()
    desc = inject_wrong_reshard(pcg, s, mode="duplicate")
    assert "chaos_dup_reduction" in desc
    rep = analyze_strategy(pcg, s)
    errs = [d for d in rep.errors if d.rule_id == "FF001"]
    assert errs, rep.describe()
    assert "chaos_dup_reduction" in errs[0].node
    assert "doubled reduction" in errs[0].message


def test_ff001_on_explicit_reduction_node():
    """Against a graph with a REAL OP_REDUCTION IR node (the searched
    plans' insert_parallel_ops pattern: the reducing output constraint
    lives on the Reduction node, not the producer): the pair analyzes
    clean; dropping the node leaves the partial unreduced; a duplicate
    stacked on it double-reduces."""
    from flexflow_tpu.ffconst import OperatorType
    from flexflow_tpu.ops.base import op_class_for

    pcg, s = _pcg_and_hybrid()
    d2 = [n for n in pcg.compute_nodes() if n.name.startswith("d2")][0]
    relu = pcg.consumers(d2.guid)[0]
    op = op_class_for(OperatorType.OP_REDUCTION)(
        f"reduction_{d2.guid}",
        {"dim": 0, "degree": 2, "axes": ("model",)},
        d2.op.data_type, num_inputs=1)
    red = pcg.insert_node_on_edge(relu, 0, op)
    # move the reducing constraint onto the Reduction node, as
    # insert_parallel_ops does for searched winners
    ns = s.for_node(red.guid)
    ns.output_spec = s.node_strategies[d2.guid].output_spec
    s.node_strategies[d2.guid].output_spec = None
    assert analyze_strategy(pcg, s).ok
    desc = inject_wrong_reshard(pcg, s, mode="drop")
    assert "dropped reduction node" in desc
    rep = analyze_strategy(pcg, s)
    assert any(d.rule_id == "FF001" for d in rep.errors), rep.describe()


# ====================================== FF002: donation-aliasing safety
def test_ff002_post_step_reference_to_donated_buffer():
    bad = DonationSpec(
        step="train_step", donated=("params", "opt_state"),
        post_step_refs=(BufferRef("async_checkpoint", "params",
                                  device_copy=False),))
    diags = check_donation(bad)
    assert len(diags) == 1 and diags[0].rule_id == "FF002"
    assert "donated buffer 'params'" in diags[0].message
    # a device-side snapshot (the PR 4 fix) is safe
    good = DonationSpec(
        step="train_step", donated=("params", "opt_state"),
        post_step_refs=(BufferRef("async_checkpoint", "params",
                                  device_copy=True),))
    assert check_donation(good) == []


def test_ff002_live_training_contract_clean(tmp_path):
    """The real wiring: with async checkpointing armed the retainers all
    snapshot device-side, so the live model's contract proves clean."""
    ff = _compiled_hybrid(checkpoint_dir=str(tmp_path), checkpoint_every=2)
    spec = donation_spec_for_training(ff)
    assert {r.holder for r in spec.post_step_refs} == {"CheckpointManager"}
    assert check_donation(spec) == []
    assert analyze_model(ff).ok


# ========================================= FF003: rng-stream collision
def test_ff003_duplicate_schedule_replays_stream():
    ff = FFModel(FFConfig())
    x = ff.create_tensor((BATCH, 16), name="x")
    t = ff.dense(x, 32, name="d1")
    t = ff.dropout(t, rate=0.5, name="drop")
    t = ff.dense(t, 10, name="d2")
    pcg = ff.create_pcg()
    assert check_rng_streams(pcg) == []
    drop_guid = [n.guid for n in pcg.compute_nodes()
                 if n.name.startswith("drop")][0]
    pcg._order.append(drop_guid)  # a buggy rewrite scheduling it twice
    diags = check_rng_streams(pcg)
    assert len(diags) == 1 and diags[0].rule_id == "FF003"
    assert "same guid" in diags[0].message


# ============================================ FF004: remat segmentation
def test_ff004_partition_and_backward_cut():
    pcg = _mlp3().create_pcg()
    assert check_remat(pcg, "none") == []          # no remat, no rule
    assert check_remat(pcg, "full", 2) == []       # real segmentation OK
    compute = [n.guid for n in pcg.compute_nodes()]
    # a segmentation that lost a node
    diags = check_remat(pcg, "full", segments=[compute[:-1]])
    assert any(d.rule_id == "FF004" and "misses" in d.message
               for d in diags)
    # a cut running against the topological order
    diags = check_remat(pcg, "full",
                        segments=[compute[2:], compute[:2]])
    assert any(d.rule_id == "FF004" and "against the topological order"
               in d.message for d in diags)


# ====================== FF006: preflight re-route, identical error texts
def test_ff006_matches_preflight_error_texts():
    from flexflow_tpu.resilience import PreflightError, preflight_strategy

    pcg, s = _pcg_and_hybrid()
    ns = s.node_strategies[[n.guid for n in pcg.compute_nodes()
                            if n.name.startswith("d1")][0]]
    ns.weight_specs["kernel"] = (None, "bogus")
    diags = check_shapes(pcg, s)
    assert diags and diags[0].rule_id == "FF006"
    with pytest.raises(PreflightError) as ei:
        preflight_strategy(pcg, s, n_dev=8, batch_size=BATCH)
    # the preflight error IS the analyzer's first diagnostic message
    assert str(ei.value) == diags[0].message
    assert "bogus" in str(ei.value)


def test_ff006_indivisible_dim_text():
    from flexflow_tpu.resilience import PreflightError, preflight_strategy

    ff = FFModel(FFConfig())
    x = ff.create_tensor((BATCH, 16), name="x")
    t = ff.dense(x, 30, name="odd")  # 30 % 4 != 0
    pcg = ff.create_pcg()
    s = hybrid_data_tensor_strategy(pcg, 2, 4)
    guid = [n.guid for n in pcg.compute_nodes() if n.name.startswith("odd")][0]
    s.node_strategies[guid].weight_specs["kernel"] = (None, "model")
    diags = check_shapes(pcg, s)
    assert diags and "not divisible by mesh axis 'model'" in \
        diags[0].message
    with pytest.raises(PreflightError, match="not divisible"):
        preflight_strategy(pcg, s, n_dev=8, batch_size=BATCH)


# ============================ cascade stage 0: reject without a compile
def test_cascade_stage0_rejects_statically_with_zero_compiles():
    """ISSUE 7 acceptance: the statically-invalid winner falls to a
    runner-up WITHOUT any compile/probe (compile_probes counts only the
    fallback's own verification), FF001 and the node land in the
    diagnosis, and the strategy_static telemetry block records it."""
    x, y = _data()
    ff = _compiled_hybrid(audit_strategy=True)
    winner = ff.strategy.describe()
    ff._telemetry_requested = True
    chaos = ChaosPlan(wrong_reshard=True, wrong_reshard_mode="duplicate")
    ff.fit(x, y, epochs=1, chaos=chaos)
    c = ff._last_cascade
    assert c.static_checks == 2          # bad winner + clean fallback
    assert c.static_rejects == 1
    assert c.static_rules == ["FF001"]
    # THE acceptance counter: the rejected winner never compiled; the one
    # probe belongs to the fallback candidate that passed stage 0
    assert c.compile_probes == 1
    assert c.fallbacks == 1
    assert ff.strategy.describe() != winner
    desc, reason = c.failures[0]
    assert desc == winner
    assert "FF001" in reason and "chaos_dup_reduction" in reason
    blk = ff.get_telemetry().summary()["strategy_static"]
    assert blk == {"checks": 2, "rejects": 1, "rules": ["FF001"]}
    # the run actually trained on the fallback
    losses = ff.get_telemetry().summary()["loss_history"]
    assert losses and np.isfinite(losses).all()


def test_dynamic_audit_catches_graph_defect_when_static_off():
    """The same concrete graph defect, judged dynamically: with
    --static-analysis off the doubled-reduction node reaches the
    compile/audit stages and the parallel-correctness probe diverges
    from the single-device reference (which computes the TRUE value —
    the injected node only scales under a multi-device mesh). This is
    the graph-level replacement for the legacy norm-scaling simulation."""
    x, y = _data()
    ff = _compiled_hybrid(audit_strategy=True, static_analysis="off")
    chaos = ChaosPlan(wrong_reshard=True, wrong_reshard_mode="duplicate",
                      wrong_reshard_factor=4.0)
    ff.fit(x, y, epochs=1, chaos=chaos)
    c = ff._last_cascade
    assert c.static_checks == 0
    assert c.audit_failures == 1 and c.fallbacks == 1
    assert chaos.wrong_reshards_injected == 1
    assert "chaos_dup_reduction" in chaos.injected_defect
    # once-semantics: the fallback candidate audited clean
    assert c.audit_reports[-1].passed


def test_scale_fallback_when_no_reduction_site():
    """A pure-dp graph has no reduction to break: the graph-level
    injection degrades to the legacy scale simulation with a warning
    (never silently does nothing)."""
    x, y = _data()
    cfg_kw = dict(audit_strategy=True, only_data_parallel=True)
    cfg = FFConfig()
    cfg.batch_size = BATCH
    for k, v in cfg_kw.items():
        setattr(cfg, k, v)
    ff = _mlp3(FFModel(cfg))
    ff.compile(optimizer=SGDOptimizer(ff, lr=0.05),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    chaos = ChaosPlan(wrong_reshard=True, wrong_reshard_mode="duplicate")
    with pytest.warns(UserWarning, match="no injection site"):
        ff.fit(x, y, epochs=1, chaos=chaos)
    assert chaos.wrong_reshard_mode == "scale"
    assert ff._last_cascade.audit_failures == 1  # legacy path still fires


# =============================== FF005: pre-serve static + runtime backstop
def test_ff005_fused_stateful_region_static_and_backstop():
    from flexflow_tpu.models.gpt2 import GPT2Config, build_gpt2
    from flexflow_tpu.serving import ServingEngine

    cfg = GPT2Config.tiny(batch_size=BATCH)
    config = FFConfig()
    config.batch_size = BATCH
    config.perform_fusion = True
    config.only_data_parallel = True
    ff = FFModel(config)
    build_gpt2(ff, cfg)
    ff.compile(optimizer=SGDOptimizer(ff),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    # static: the rule flags the fused region BEFORE any engine exists
    diags = check_serving_graph(ff.pcg)
    assert diags and all(d.rule_id == "FF005" for d in diags)
    # pre-serve: the engine surfaces the FF005 diagnostic
    with pytest.raises(NotImplementedError, match="FF005"):
        ServingEngine(ff, max_decode_len=cfg.seq_len)
    # analysis skipped: the original runtime refusal still fires
    ff.config.static_analysis = "off"
    with pytest.raises(NotImplementedError, match="fusion"):
        ServingEngine(ff, max_decode_len=cfg.seq_len)


# ==================================== search pruning before the simulator
def test_search_prunes_statically_invalid_candidates(tmp_path,
                                                     monkeypatch):
    """Candidates ShardLint rejects never reach the simulator: with a
    monkeypatched analyzer refusing every tp>1 plan, the search settles
    on a tp==1 winner and logs the pruned counts (SearchLog events + the
    final record + SearchResult.pruned_static)."""
    import flexflow_tpu.analysis as analysis
    from flexflow_tpu.analysis.report import AnalysisReport, Diagnostic
    from flexflow_tpu.search.machine_model import TPUMachineModel
    from flexflow_tpu.search.unity import unity_search

    real = analysis.analyze_candidate

    def veto_tp(pcg, strategy):
        if len(strategy.mesh_shape) > 1 and strategy.mesh_shape[1] > 1:
            return AnalysisReport(diagnostics=[Diagnostic(
                rule_id="FF001", node="test",
                message="vetoed for the pruning test")])
        return real(pcg, strategy)

    monkeypatch.setattr(analysis, "analyze_candidate", veto_tp)
    cfg = FFConfig()
    cfg.batch_size = BATCH
    cfg.search_budget = 8
    cfg.search_log_file = str(tmp_path / "search.jsonl")
    pcg = _mlp3(FFModel(cfg)).create_pcg()
    res = unity_search(pcg, cfg, 8,
                       machine=TPUMachineModel.from_generation("v5e", 8),
                       return_result=True, insert_ir_nodes=False)
    assert res.pruned_static > 0
    assert res.mesh_shape[1] == 1 if len(res.mesh_shape) > 1 else True
    records = [json.loads(line) for line in
               (tmp_path / "search.jsonl").read_text().splitlines()]
    pruned = [r for r in records if r.get("event") == "pruned_static"]
    assert len(pruned) == res.pruned_static
    assert pruned[0]["rules"] == ["FF001"]
    final = [r for r in records if r.get("event") == "result"][-1]
    assert final["pruned_static"] == res.pruned_static
    # no pruned candidate was simulated as a "candidate" record at tp>1
    cands = [r for r in records if r.get("event") == "candidate"]
    assert all(r["tp"] == 1 for r in cands)


def test_search_clean_run_prunes_nothing(tmp_path):
    """Well-formed candidates are untouched: the real analyzer prunes
    zero candidates on a plain search (the winner is bit-identical to a
    run with analysis off)."""
    from flexflow_tpu.search.machine_model import TPUMachineModel
    from flexflow_tpu.search.unity import unity_search

    def run(static):
        cfg = FFConfig()
        cfg.batch_size = BATCH
        cfg.search_budget = 8
        cfg.static_analysis = static
        pcg = _mlp3(FFModel(cfg)).create_pcg()
        return unity_search(
            pcg, cfg, 8,
            machine=TPUMachineModel.from_generation("v5e", 8),
            return_result=True, insert_ir_nodes=False)
    on, off = run("on"), run("off")
    assert on.pruned_static == 0
    assert tuple(on.mesh_shape) == tuple(off.mesh_shape)
    assert on.sim_time == off.sim_time


# ============================================= strict mode + CLI + digest
def test_strict_compile_rejects_broken_strategy():
    cfg = FFConfig()
    cfg.batch_size = BATCH
    cfg.static_analysis = "strict"
    ff = _mlp3(FFModel(cfg))

    def broken(pcg):
        s = hybrid_data_tensor_strategy(pcg, 4, 2)
        inject_wrong_reshard(pcg, s, mode="drop")
        return s

    with pytest.raises(StaticAnalysisError, match="FF001"):
        ff.compile(optimizer=SGDOptimizer(ff, lr=0.05),
                   loss_type=LossType.
                   LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                   strategy_fn=broken)


def test_cli_clean_and_injected(capsys):
    from flexflow_tpu.analysis.__main__ import main as cli

    assert cli(["--model", "mlp", "--strategy", "hybrid", "--tp", "2"]) \
        == 0
    out = capsys.readouterr().out
    assert "clean" in out and "FF001" in out  # rules-checked footer
    assert cli(["--model", "attention", "--strategy", "hybrid",
                "--inject", "duplicate"]) == 1
    out = capsys.readouterr().out
    assert "FF001" in out and "[fix:" in out and "FAIL" in out
    # JSON mode is machine-readable
    assert cli(["--model", "mlp", "--strategy", "dp", "--json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["errors"] == 0 and "FF001" in " ".join(data["checked"])


def test_placement_lattice_row_parallel_partial():
    """White-box: the interpreter sees the row-parallel middle layer's
    partial_sum arise and be discharged by its output constraint."""
    pcg, s = _pcg_and_hybrid()
    d2 = [n for n in pcg.compute_nodes() if n.name.startswith("d2")][0]
    values = interpret(pcg, s).values
    # discharged at the node (output_spec) — downstream is batch-sharded
    assert not values[(d2.guid, 0)].is_partial
    # strip the constraint: the partial now flows
    s.node_strategies[d2.guid].output_spec = None
    values = interpret(pcg, s).values
    assert values[(d2.guid, 0)].partial == frozenset({"model"})


def test_trace_summary_prints_static_digest(tmp_path, capsys):
    import sys as _sys
    import os as _os
    _sys.path.insert(0, _os.path.join(
        _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
        "scripts"))
    import trace_summary

    tf = tmp_path / "telemetry.json"
    tf.write_text(json.dumps({
        "phase": "train", "steps": 4, "batch_size": 8,
        "strategy_static": {"checks": 2, "rejects": 1,
                            "rules": ["FF001"]}}))
    trace_summary.main([str(tf)])
    out = capsys.readouterr().out
    assert "static analysis: 2 checks, 1 rejected" in out
    assert "FF001" in out
