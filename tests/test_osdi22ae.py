"""OSDI'22 artifact protocol smoke (reference: scripts/osdi22ae/*.sh — the
searched-vs-data-parallel comparison that is the reproducible baseline,
BASELINE.md)."""
import os
import sys

SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts", "osdi22ae")


def test_protocol_runs_both_modes():
    sys.path.insert(0, SCRIPTS)
    try:
        import run as osdi_run

        dp, searched = osdi_run.main(["mlp", "-b", "16", "--budget", "3",
                                      "--epochs", "1"])
    finally:
        sys.path.remove(SCRIPTS)
    assert dp["mode"] == "data_parallel" and dp["samples_per_sec"] > 0
    assert searched["mode"] == "unity_searched" \
        and searched["samples_per_sec"] > 0
    assert dp["mesh"] == {"data": 8}


def test_searched_beats_dp_in_simulation_bert_and_dlrm():
    """The artifact's headline claim (searched >= DP on the same hardware,
    scripts/osdi22ae/bert.sh + dlrm.sh) asserted on the simulator for both
    workloads; the bench harness repeats it with device-calibrated costs on
    the real chip (BENCH keys searched_vs_dp_8chip_sim)."""
    from flexflow_tpu import FFConfig, FFModel, LossType
    from flexflow_tpu.models import BertConfig, build_bert, build_dlrm
    from flexflow_tpu.search.machine_model import TPUMachineModel
    from flexflow_tpu.search.simulator import OpSharding, Simulator
    from flexflow_tpu.search.unity import unity_search

    machine = TPUMachineModel.from_generation("v5e", 8)

    def check(build):
        config = FFConfig()
        config.batch_size = 16
        ff = FFModel(config)
        build(ff)
        pcg = ff.create_pcg()
        sim = Simulator(machine)
        res = unity_search(pcg.copy(), config, 8, machine=machine,
                           return_result=True, insert_ir_nodes=False)
        dp8 = {n.guid: OpSharding(dp=8) for n in pcg.compute_nodes()}
        t_dp, _ = sim.simulate(pcg, dp8)
        assert res.sim_time <= t_dp * 1.001, (res.sim_time, t_dp)
        return t_dp / res.sim_time

    check(lambda ff: build_bert(ff, BertConfig(
        batch_size=16, seq_len=128, hidden=1024, num_heads=16,
        num_layers=2, intermediate=4096)))
    # DLRM with realistic tables: the searched table sharding must win big
    ratio = check(lambda ff: build_dlrm(
        ff, batch_size=16, embedding_sizes=(100000,) * 8,
        embedding_dim=64))
    assert ratio > 1.5, f"table parallelism should beat DP clearly: {ratio}"


def test_dlrm_claim_first_principles_envelope():
    """VERDICT r3 item 5: pin dlrm_searched_vs_dp inside a justified
    bytes/bandwidth envelope so the headline cannot swing with cost-model
    edits (it went 27.5x -> 19.8x -> 7.2x across rounds while unanchored).

    Bench config (bench.py DLRM leg): batch 64, 8 tables x 200000 x 64
    f32, v5e-8 (ici 50 GB/s/link, (2,4) torus -> 4 concurrent ring links
    for the full 8-chip group; HBM 819 GB/s x 0.8 eff; Adam update moves
    ~7 bytes per weight byte — optimizer_kernel.cu analog).

    First principles, DP-8 per step:
      table grads allreduce (dense, reference optimizer_kernel.cu:88):
        wire >= 2*(7/8) * table_bytes / (4 links * 50 GB/s)
      optimizer update (every chip updates ALL replicated tables):
        wire >= 7 * table_bytes / (819 GB/s * 0.8)
    Table-parallel per step (each chip owns 1 of 8 tables, no table
    sync): update >= 7 * (table_bytes/8) / (819 GB/s * 0.8).
    """
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.models import build_dlrm
    from flexflow_tpu.search.machine_model import TPUMachineModel
    from flexflow_tpu.search.simulator import OpSharding, Simulator
    from flexflow_tpu.search.unity import simulate_best, unity_search

    machine = TPUMachineModel.from_generation("v5e", 8)
    assert machine.torus == (2, 4)
    config = FFConfig()
    config.batch_size = 64
    ff = FFModel(config)
    build_dlrm(ff, batch_size=64, embedding_sizes=(200000,) * 8,
               embedding_dim=64)
    pcg = ff.create_pcg()
    sim = Simulator(machine)
    res = unity_search(pcg.copy(), config, 8, machine=machine,
                       return_result=True, insert_ir_nodes=False)
    dp8 = {n.guid: OpSharding(dp=8) for n in pcg.compute_nodes()}
    t_dp = simulate_best(sim, pcg, dp8, {})
    ratio = t_dp / res.sim_time

    # hand-computed bounds (independent arithmetic, not machine methods).
    # The grad allreduce rides ICI while the optimizer update streams HBM —
    # different wires, so they CAN fully overlap: the wall-clock floor is
    # max(...), the no-overlap ceiling sum(...) (+50% MLP/latency slack).
    table_bytes = 8 * 200000 * 64 * 4
    eff_hbm = 819e9 * 0.8
    dp_sync_wire = 2 * (7 / 8) * table_bytes / (4 * 50e9)   # ~3.58 ms
    dp_update_wire = 7 * table_bytes / eff_hbm              # ~4.38 ms
    dp_lower = max(dp_sync_wire, dp_update_wire)
    dp_upper = 1.5 * (dp_sync_wire + dp_update_wire)
    searched_lower = 7 * (table_bytes / 8) / eff_hbm        # ~0.55 ms

    assert dp_lower <= t_dp <= dp_upper, (t_dp, dp_lower, dp_upper)
    assert res.sim_time >= searched_lower, (res.sim_time, searched_lower)
    # implied envelope on the headline ratio
    assert 2.0 <= ratio <= dp_upper / searched_lower, \
        (ratio, dp_upper / searched_lower)
