"""OSDI'22 artifact protocol smoke (reference: scripts/osdi22ae/*.sh — the
searched-vs-data-parallel comparison that is the reproducible baseline,
BASELINE.md)."""
import os
import sys

SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts", "osdi22ae")


def test_protocol_runs_both_modes():
    sys.path.insert(0, SCRIPTS)
    try:
        import run as osdi_run

        dp, searched = osdi_run.main(["mlp", "-b", "16", "--budget", "3",
                                      "--epochs", "1"])
    finally:
        sys.path.remove(SCRIPTS)
    assert dp["mode"] == "data_parallel" and dp["samples_per_sec"] > 0
    assert searched["mode"] == "unity_searched" \
        and searched["samples_per_sec"] > 0
    assert dp["mesh"] == {"data": 8}
