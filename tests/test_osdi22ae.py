"""OSDI'22 artifact protocol smoke (reference: scripts/osdi22ae/*.sh — the
searched-vs-data-parallel comparison that is the reproducible baseline,
BASELINE.md)."""
import os
import sys

SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts", "osdi22ae")


def test_protocol_runs_both_modes():
    sys.path.insert(0, SCRIPTS)
    try:
        import run as osdi_run

        dp, searched = osdi_run.main(["mlp", "-b", "16", "--budget", "3",
                                      "--epochs", "1"])
    finally:
        sys.path.remove(SCRIPTS)
    assert dp["mode"] == "data_parallel" and dp["samples_per_sec"] > 0
    assert searched["mode"] == "unity_searched" \
        and searched["samples_per_sec"] > 0
    assert dp["mesh"] == {"data": 8}


def test_searched_beats_dp_in_simulation_bert_and_dlrm():
    """The artifact's headline claim (searched >= DP on the same hardware,
    scripts/osdi22ae/bert.sh + dlrm.sh) asserted on the simulator for both
    workloads; the bench harness repeats it with device-calibrated costs on
    the real chip (BENCH keys searched_vs_dp_8chip_sim)."""
    from flexflow_tpu import FFConfig, FFModel, LossType
    from flexflow_tpu.models import BertConfig, build_bert, build_dlrm
    from flexflow_tpu.search.machine_model import TPUMachineModel
    from flexflow_tpu.search.simulator import OpSharding, Simulator
    from flexflow_tpu.search.unity import unity_search

    machine = TPUMachineModel.from_generation("v5e", 8)

    def check(build):
        config = FFConfig()
        config.batch_size = 16
        ff = FFModel(config)
        build(ff)
        pcg = ff.create_pcg()
        sim = Simulator(machine)
        res = unity_search(pcg.copy(), config, 8, machine=machine,
                           return_result=True, insert_ir_nodes=False)
        dp8 = {n.guid: OpSharding(dp=8) for n in pcg.compute_nodes()}
        t_dp, _ = sim.simulate(pcg, dp8)
        assert res.sim_time <= t_dp * 1.001, (res.sim_time, t_dp)
        return t_dp / res.sim_time

    check(lambda ff: build_bert(ff, BertConfig(
        batch_size=16, seq_len=128, hidden=1024, num_heads=16,
        num_layers=2, intermediate=4096)))
    # DLRM with realistic tables: the searched table sharding must win big
    ratio = check(lambda ff: build_dlrm(
        ff, batch_size=16, embedding_sizes=(100000,) * 8,
        embedding_dim=64))
    assert ratio > 1.5, f"table parallelism should beat DP clearly: {ratio}"
