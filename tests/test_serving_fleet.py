"""Fleet of fault domains (ISSUE 11, flexflow_tpu/serving/fleet.py,
docs/fleet.md): multi-replica routing with health-checked failover,
cross-replica request migration (bitwise continuations under exact
decode), hedged retries that never double-count, fleet-level shedding
with a floored retry_after_ms, rolling drain/rejoin, per-replica plan
lint, and the fleet-wide exactly-one-outcome ledger under scripted
chaos — all deterministic on CPU."""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.models.gpt2 import GPT2Config, build_gpt2
from flexflow_tpu.resilience import ChaosPlan, FleetChaosPlan
from flexflow_tpu.serving import (FLEET_MIN_RETRY_AFTER_MS, OUTCOMES,
                                  OverloadError, Request, ServingEngine,
                                  ServingFleet, ServingRejection)
from flexflow_tpu.serving.scheduler import ContinuousBatchScheduler


@pytest.fixture(scope="module")
def gpt2():
    cfg = GPT2Config.tiny(batch_size=8)
    config = FFConfig()
    config.batch_size = cfg.batch_size
    ff = FFModel(config)
    build_gpt2(ff, cfg)
    ff.compile(optimizer=SGDOptimizer(ff),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff, cfg


def _prompts(n, seed=0, lo=3, hi=6):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 100, size=int(rng.integers(lo, hi))).tolist()
            for _ in range(n)]


def _baseline(ff, cfg, prompts, max_new):
    return ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len,
                         exact_decode=True).generate(
                             prompts, max_new_tokens=max_new)


def _fleet(ff, cfg, **kw):
    kw.setdefault("n_replicas", 2)
    kw.setdefault("n_slots", 2)
    kw.setdefault("max_decode_len", cfg.seq_len)
    kw.setdefault("exact_decode", True)
    return ServingFleet(ff, **kw)


# ------------------------------------------------------------- clean routing
def test_clean_fleet_matches_single_replica_bitwise(gpt2):
    """Load-aware dispatch over 2 replicas produces the SAME streams as
    one engine (rng keys on submission tag, not placement), spreads
    traffic across both fault domains, and the fleet ledger closes with
    every request ok."""
    ff, cfg = gpt2
    prompts = _prompts(8, seed=1)
    base = _baseline(ff, cfg, prompts, 6)
    fleet = _fleet(ff, cfg)
    outs = fleet.generate(prompts, max_new_tokens=6)
    assert outs == base, "fleet streams diverged from one-engine run"
    st = fleet.stats
    assert st.outcomes == {"ok": 8}
    assert all(d > 0 for d in st.dispatches), "a replica got no traffic"
    assert sum(st.dispatches) == 8


# ------------------------------------------------- failover + migration
def test_kill_replica_migrates_bitwise_ledger_and_recovery(gpt2):
    """Acceptance (ISSUE 11): kill_replica_at fires mid-decode — the
    fleet completes every request, the exactly-one-outcome ledger is
    conserved, migrated continuations are bitwise-equal to an
    undisturbed single-replica run, aggregate throughput recovers to
    >= (N-1)/N of the pre-kill rate within the probe interval, and the
    dead replica receives zero further dispatches."""
    ff, cfg = gpt2
    prompts = _prompts(10, seed=2)
    base = _baseline(ff, cfg, prompts, 8)
    fleet = _fleet(ff, cfg)
    chaos = FleetChaosPlan(kill_replica_at={4: 0})
    outs = fleet.generate(prompts, max_new_tokens=8, chaos=chaos)
    st = fleet.stats
    assert chaos.replicas_killed == [0]
    assert outs == base, "migrated continuations diverged"
    assert st.outcomes == {"ok": 10}
    assert sum(st.outcomes.values()) == 10  # ledger conserved
    assert st.migrations >= 1, "no in-flight stream migrated"
    assert st.failovers == 1
    assert fleet.replicas[0].health == "dead"
    # the dead replica gets zero further dispatches: every migrated
    # stream and requeued request re-dispatched onto the survivor, so
    # total dispatches = 10 first-tries + the re-dispatches
    assert st.dispatches[0] + st.dispatches[1] == \
        10 + st.migrations + st.requeued
    assert st.dispatches[1] >= st.migrations
    # throughput recovery: trailing mean tokens/tick back to >= 1/2 of
    # pre-kill within the probe interval (N=2)
    rec = st.recovery_ticks(st.kill_ticks[0], frac=0.5)
    assert rec is not None and rec <= fleet.health_probe_every, \
        f"throughput did not recover within the probe interval ({rec})"


def test_replica_fatal_error_migrates_instead_of_crashing(gpt2):
    """An error the engine's own failover cannot absorb kills only that
    fault domain: its work migrates and the fleet finishes clean."""
    ff, cfg = gpt2
    prompts = _prompts(6, seed=3)
    base = _baseline(ff, cfg, prompts, 6)
    fleet = _fleet(ff, cfg)
    orig = fleet.replicas[0].engine._dispatch_decode
    state = {"fired": False}

    def boom(*a, **kw):
        if not state["fired"] and \
                fleet.replicas[0].loop.stats.decode_steps >= 2:
            state["fired"] = True
            raise RuntimeError("replica mesh fell off the network")
        return orig(*a, **kw)

    fleet.replicas[0].engine._dispatch_decode = boom
    outs = fleet.generate(prompts, max_new_tokens=6)
    assert state["fired"]
    assert outs == base
    assert fleet.stats.outcomes == {"ok": 6}
    assert fleet.replicas[0].health == "dead"
    assert fleet.stats.failovers == 1


# ------------------------------------------------------- circuit breaker
def test_circuit_open_zero_dispatch_until_probe_passes(gpt2):
    """Acceptance (ISSUE 11): a circuit-open replica receives ZERO
    dispatches until its half-open probe passes — and once it does, the
    replica re-enters rotation."""
    ff, cfg = gpt2
    fleet = _fleet(ff, cfg)
    sick = fleet.replicas[1]
    # white-box: open the circuit with the half-open probe scheduled a
    # few ticks out; until the probe passes every dispatch must go to
    # replica 0
    sick.circuit.state = "open"
    sick.circuit.opens = 1
    sick.circuit.half_open_at = 6
    sick.health = "quarantined"
    outs = fleet.generate(_prompts(8, seed=4), max_new_tokens=6)
    st = fleet.stats
    assert all(len(o) == 6 for o in outs)
    # probe fired at tick 6 and passed (healthy engine), replica re-entered
    assert sick.circuit.state == "closed"
    assert any(t[1] == 1 and t[3] == "healthy" and t[4] == "probe_pass"
               for t in st.health_transitions), st.health_transitions
    probe_tick = min(t[0] for t in st.health_transitions
                     if t[1] == 1 and t[4] == "probe_pass")
    assert probe_tick >= 6
    # every dispatch before the probe went to replica 0: replica 1's
    # first dispatch (if any) can only have happened after re-entry, so
    # with 8 short requests mostly routed early, replica 0 dominates
    assert st.dispatches[0] >= st.dispatches[1]
    assert st.probes >= 1


def test_degraded_replica_quarantined_queue_rescued(gpt2):
    """A sustained decode-poison rate (degrade_replica_at) drives the
    passive quarantine signal: the circuit opens, the sick replica's
    queued requests are rescued to healthy replicas, and completed
    streams stay bitwise-equal to an undisturbed run."""
    ff, cfg = gpt2
    prompts = _prompts(10, seed=5)
    base = _baseline(ff, cfg, prompts, 8)
    fleet = _fleet(ff, cfg)
    chaos = FleetChaosPlan(degrade_replica_at={3: 1},
                           degrade_poison_every=1)
    outs = fleet.generate(prompts, max_new_tokens=8, chaos=chaos)
    st = fleet.stats
    assert st.degrade_poisons >= 1
    assert st.circuit_opens >= 1
    assert any(t[1] == 1 and t[3] == "quarantined"
               for t in st.health_transitions)
    assert st.requeued >= 1, "the sick replica's queue was not rescued"
    # ledger conserved; completed streams bitwise
    assert sum(st.outcomes.values()) == 10
    assert set(st.outcomes) <= set(OUTCOMES)
    done = [i for i, o in enumerate(outs) if len(o) == 8]
    assert done and all(outs[i] == base[i] for i in done)


def test_partition_heals_through_half_open_probe(gpt2):
    """A router<->replica partition opens the circuit via dispatch
    timeouts; after the partition heals, the half-open probe passes and
    the replica rejoins — all requests still finish bitwise."""
    ff, cfg = gpt2
    prompts = _prompts(8, seed=6)
    base = _baseline(ff, cfg, prompts, 8)
    fleet = _fleet(ff, cfg)
    chaos = FleetChaosPlan(partition_at={3: 0}, partition_ticks=6)
    outs = fleet.generate(prompts, max_new_tokens=8, chaos=chaos)
    st = fleet.stats
    assert outs == base
    assert st.outcomes == {"ok": 8}
    trail = [(t[3], t[4]) for t in st.health_transitions if t[1] == 0]
    assert ("quarantined", "partition_timeout") in trail
    assert ("healthy", "probe_pass") in trail


# ----------------------------------------------------------------- hedging
def test_hedge_twin_wins_no_double_count_bitwise(gpt2):
    """A partitioned primary replica stalls its streams; hedge twins on
    the healthy replica win (first new committed token), the losers are
    cancelled with NO ledger entry, and the caller-visible streams are
    bitwise-equal to an undisturbed run."""
    ff, cfg = gpt2
    config = ff.config
    prompts = _prompts(4, seed=7)
    base = _baseline(ff, cfg, prompts, 6)
    config.hedge_after_pctl = 10.0
    try:
        fleet = _fleet(ff, cfg)
        for r in fleet.replicas:
            r.engine.admission.force_token_cost_ms = 1e-6
        chaos = FleetChaosPlan(partition_at={3: 0}, partition_ticks=30)
        outs = fleet.generate(prompts, max_new_tokens=6, chaos=chaos)
        st = fleet.stats
        assert st.hedges >= 1 and st.hedge_twin_wins >= 1
        assert st.hedges_cancelled >= 1
        # no double count: exactly one outcome per submitted request,
        # twins invisible in the ledger
        assert sum(st.outcomes.values()) == 4
        assert st.outcomes == {"ok": 4}
        assert outs == base, "hedged streams diverged"
        # ISSUE 16 satellite pin: adoption mirrors the LATENCY STAMPS
        # with the tokens — every caller-held request reports a real
        # TTFT/completion time even when its winning copy was the twin
        for r in fleet._requests:
            assert r.first_token_ms > 0, "TTFT stamp lost in adoption"
            assert r.finish_ms >= r.first_token_ms > 0
    finally:
        config.hedge_after_pctl = 0.0


def test_hedge_adoption_mirrors_latency_stamps(gpt2):
    """ISSUE 16 satellite fix pin: when a hedge TWIN wins, its
    ``first_token_ms`` / ``finish_ms`` must be mirrored onto the
    caller-held primary along with the tokens — before the fix the
    primary kept stamps of 0.0, so bench TTFT went negative and the
    request trace reported a zero-latency completion."""
    from flexflow_tpu.serving.fleet import _Hedge

    ff, cfg = gpt2
    fleet = _fleet(ff, cfg)
    p = Request(prompt=np.zeros(3, np.int32), max_new_tokens=4, rng_tag=0)
    t = Request(prompt=np.zeros(3, np.int32), max_new_tokens=4, rng_tag=0,
                generated=[1, 2, 3, 4])
    t.done = True
    t.outcome = "ok"
    t.finish_reason = "length"
    t.first_token_ms = 123.0
    t.finish_ms = 456.0
    fleet._adopted.append(_Hedge(primary=p, twin=t, fork=0,
                                 primary_replica=0, twin_replica=1))
    fleet._mirror_adopted()
    assert p.generated == [1, 2, 3, 4]
    assert p.first_token_ms == 123.0, "twin's TTFT stamp not mirrored"
    assert p.finish_ms == 456.0, "twin's finish stamp not mirrored"
    # a primary that committed tokens BEFORE the hedge fork keeps its
    # own, earlier TTFT — first token is first token wherever it landed
    p2 = Request(prompt=np.zeros(3, np.int32), max_new_tokens=4,
                 rng_tag=1, generated=[9])
    p2.first_token_ms = 50.0
    t2 = Request(prompt=np.zeros(3, np.int32), max_new_tokens=4,
                 rng_tag=1, generated=[9, 10])
    t2.done = True
    t2.outcome = "ok"
    t2.finish_reason = "length"
    t2.first_token_ms = 50.0
    t2.finish_ms = 99.0
    fleet._adopted.append(_Hedge(primary=p2, twin=t2, fork=1,
                                 primary_replica=0, twin_replica=1))
    fleet._mirror_adopted()
    assert p2.first_token_ms == 50.0
    assert p2.finish_ms == 99.0


def test_hedge_cap_and_idle_target_only(gpt2):
    """Hedges are bounded (hedge_cap outstanding) and only target an
    IDLE replica — with every replica busy, no hedge launches, so
    hedging cannot amplify an overload."""
    ff, cfg = gpt2
    config = ff.config
    config.hedge_after_pctl = 1.0
    try:
        fleet = _fleet(ff, cfg, n_slots=1)
        assert fleet.hedge_cap == 1
        for r in fleet.replicas:
            r.engine.admission.force_token_cost_ms = 1e-6
        # enough work that both replicas stay busy: queues non-empty ->
        # no idle target -> hedges may only fire near the drain tail
        outs = fleet.generate(_prompts(8, seed=8), max_new_tokens=6)
        st = fleet.stats
        assert sum(st.outcomes.values()) == 8
        assert st.outcomes == {"ok": 8}
        # the ledger and streams stay clean whatever hedging did
        assert all(len(o) == 6 for o in outs)
    finally:
        config.hedge_after_pctl = 0.0


def test_partition_stranded_streams_survive_to_heal(gpt2):
    """Work stranded on a partitioned replica is PENDING, not done: the
    run loop idles until the partition heals and the streams finish
    bitwise — it must not break and truncate them one tick from
    recovery."""
    ff, cfg = gpt2
    prompts = _prompts(1, seed=15)
    base = _baseline(ff, cfg, prompts, 6)
    fleet = _fleet(ff, cfg)
    # the single request lands on replica 0; partition it mid-stream
    # with replica 1 idle (nothing else to do -> worked=False ticks)
    chaos = FleetChaosPlan(partition_at={2: 0}, partition_ticks=5)
    outs = fleet.generate(prompts, max_new_tokens=6, chaos=chaos)
    assert outs == base, "stranded stream truncated or diverged"
    assert fleet.stats.outcomes == {"ok": 1}


def test_rejoin_rescues_alive_replicas_work(gpt2):
    """rejoin() of a still-alive (degraded) replica harvests the work
    the open circuit deliberately left in place — the scheduler rebuild
    must not drop streams on the floor."""
    ff, cfg = gpt2
    prompts = _prompts(8, seed=16)
    base = _baseline(ff, cfg, prompts, 10)
    fleet = _fleet(ff, cfg)
    # sustained poison opens replica 1's circuit (~tick 5) while its
    # long streams are mid-flight; rejoin fires shortly after, with the
    # replica alive and holding work
    chaos = FleetChaosPlan(degrade_replica_at={3: 1},
                           degrade_poison_every=1, rejoin_at={7: 1})
    outs = fleet.generate(prompts, max_new_tokens=10, chaos=chaos)
    st = fleet.stats
    assert st.rejoins == 1
    # ledger conserved: nothing silently lost to the rebuild
    assert sum(st.outcomes.values()) == 8, st.outcomes
    # every truncated stream carries a real failure outcome; completed
    # ones are bitwise vs the undisturbed run
    done = [i for i, o in enumerate(outs) if len(o) == 10]
    assert done and all(outs[i] == base[i] for i in done)
    assert st.outcomes.get("ok", 0) == len(done)
    # white-box: rejoin of a replica HOLDING work harvests it — slots
    # and queue both land back in the fleet queue, in-flight first
    fleet2 = _fleet(ff, cfg)
    fleet2._start(0.0, 0, 0)
    rep = fleet2.replicas[1]
    stuck = Request(prompt=np.zeros(3, np.int32), max_new_tokens=4,
                    rng_tag=0)
    queued = Request(prompt=np.zeros(3, np.int32), max_new_tokens=4,
                     rng_tag=1)
    rep.sched.slots[0] = stuck
    rep.sched._free.remove(0)
    rep.sched.queue.append(queued)
    fleet2.rejoin(1)
    order = list(fleet2.queue)
    assert order[0] is stuck and order[1] is queued
    assert fleet2.stats.migrations == 1
    assert fleet2.stats.requeued == 1
    assert rep.sched.active == 0 and rep.sched.queued == 0


def test_door_queue_wait_burns_the_deadline_budget(gpt2):
    """The relative deadline starts at the FLEET DOOR: a request stuck
    there (every circuit open) is dropped as deadline_exceeded instead
    of being served arbitrarily late with zero misses recorded."""
    ff, cfg = gpt2
    fleet = _fleet(ff, cfg)
    for rep in fleet.replicas:
        rep.engine.max_queue = 0  # white-box: nothing can dispatch
        rep.circuit.state = "open"
        rep.circuit.half_open_at = None
    outs = fleet.generate(_prompts(2, seed=17), max_new_tokens=4,
                          deadline_ms=1e-6)
    st = fleet.stats
    assert st.outcomes == {"deadline_exceeded": 2}, st.outcomes
    assert all(o == [] for o in outs)


def test_hedge_rescues_failed_primary(gpt2):
    """A primary evicted as deadline_exceeded/decode_fault must NOT beat
    its still-viable twin — the hedge exists precisely to rescue a
    request whose first try died: the failure is withdrawn from the
    ledger and the twin streams on as the winner."""
    from flexflow_tpu.serving.fleet import _Hedge

    ff, cfg = gpt2
    fleet = _fleet(ff, cfg)
    fleet._start(0.0, 0, 0)
    p = Request(prompt=np.zeros(3, np.int32), max_new_tokens=4, rng_tag=0)
    t = Request(prompt=np.zeros(3, np.int32), max_new_tokens=4, rng_tag=0)
    p.done = True
    p.outcome = p.finish_reason = "deadline_exceeded"
    fleet.replicas[0].sched.finished.append(p)  # the eviction's ledger
    fleet.replicas[1].sched.submit(t)           # viable twin, queued
    fleet._hedges.append(_Hedge(primary=p, twin=t, fork=0,
                                primary_replica=0, twin_replica=1))
    fleet._hedged_ids.add(id(p))
    fleet._resolve_hedges()
    h = fleet._adopted[-1]
    assert h.winner is t
    assert not fleet.replicas[0].sched.finished, "failure not withdrawn"
    assert p.outcome is None and not p.done
    assert fleet.replicas[1].sched.queued == 1  # twin still in play


def test_passive_success_cannot_close_open_circuit(gpt2):
    """One clean decode of a leftover in-flight slot must not talk a
    quarantined replica back into rotation: an open circuit re-closes
    only through the half-open probe."""
    ff, cfg = gpt2
    fleet = _fleet(ff, cfg)
    rep = fleet.replicas[0]
    rep.circuit.state = "open"
    rep.circuit.opens = 1
    rep.circuit.half_open_at = 99
    rep.health = "quarantined"
    fleet._circuit_success(rep)
    assert rep.circuit.state == "open"
    assert rep.health == "quarantined"


def test_fleet_sigterm_hands_back_door_queue(gpt2):
    """Requests still in the fleet DOOR queue when a fleet-wide SIGTERM
    drain completes are handed back via drained_requests (outcome
    preempted) — not silently swallowed by the dead-end break."""
    ff, cfg = gpt2
    fleet = _fleet(ff, cfg)
    for rep in fleet.replicas:
        rep.engine.max_queue = 0  # white-box: nothing can dispatch
    prompts = _prompts(3, seed=14)
    chaos = FleetChaosPlan(preempt_serving_at=1)
    outs = fleet.generate(prompts, max_new_tokens=4, chaos=chaos)
    st = fleet.stats
    assert st.outcomes == {"preempted": 3}
    assert [r.rng_tag for r in fleet.drained_requests] == [0, 1, 2]
    assert all(o == [] for o in outs)
    assert st.drains == 1


def test_migration_preserves_deadline_budget(gpt2):
    """A migrated request's submit stamp survives the re-dispatch: the
    relative deadline budget must not silently restart exactly when a
    replica fails (a fresh request still gets stamped normally)."""
    ff, cfg = gpt2
    # scripted fleet clock so the fake submit stamp is inside its
    # deadline window (the door sweep judges with this same clock)
    fleet = _fleet(ff, cfg, clock=lambda: 1300.0)
    fleet._start(0.0, 0, 0)
    migrated = Request(prompt=np.zeros(3, np.int32), max_new_tokens=4,
                       deadline_ms=100.0)
    migrated.submit_ms = 1234.5  # stamped at its FIRST dispatch
    fresh = Request(prompt=np.zeros(3, np.int32), max_new_tokens=4)
    fleet.queue.extend([migrated, fresh])
    fleet._requests.extend([migrated, fresh])
    fleet._dispatch()
    placed = [r for rep in fleet.replicas if rep.sched is not None
              for r in rep.sched.queue]
    # identity, not ==: Request dataclasses hold ndarrays
    assert any(r is migrated for r in placed)
    assert any(r is fresh for r in placed)
    assert migrated.submit_ms == 1234.5, "deadline budget restarted"
    assert fresh.submit_ms != 0.0, "fresh request never stamped"


# --------------------------------------------------- fleet door shedding
def test_fleet_door_queue_shed_ledgered_and_hinted(gpt2):
    """The 'queue' policy graduates to the router: aggregate depth past
    the fleet high-water sheds with a typed rejection, the request is
    ledgered (outcome shed, exactly once), and the hint carries the
    fleet-derived retry_after_ms."""
    ff, cfg = gpt2
    config = ff.config
    config.shed_policy = "queue"
    try:
        fleet = _fleet(ff, cfg, max_queue=4)
        pat = []
        for i, p in enumerate(_prompts(8, seed=9)):
            r = Request(prompt=np.asarray(p, np.int32), max_new_tokens=4,
                        rng_tag=i)
            try:
                fleet.submit(r)
                pat.append("accept")
            except ServingRejection as e:
                pat.append(type(e).__name__)
                assert e.retry_after_ms >= 0.0
                assert r.outcome == "shed"
        assert pat[:2] == ["accept", "accept"]  # below high-water 4//2
        assert set(pat[2:]) == {"OverloadError"}
        st = fleet.run()
        assert st.outcomes["shed"] == 6
        assert st.outcomes["ok"] == 2
        assert sum(st.outcomes.values()) == 8
    finally:
        config.shed_policy = "off"


def test_retry_after_ms_floored_while_fleet_degraded(gpt2):
    """ISSUE 11 small fix: the fleet door's retry_after_ms must never be
    0 while any replica is draining or circuit-open — even with a cold
    EWMA the hint is floored at FLEET_MIN_RETRY_AFTER_MS, and a healthy
    fleet's hint derives from the BEST replica's drain estimate."""
    ff, cfg = gpt2
    fleet = _fleet(ff, cfg)
    # fully healthy + cold EWMA: 0 is fine (nothing degraded to protect)
    assert fleet.retry_after_ms() == 0.0
    # one circuit-open replica: floored, cold EWMA or not
    fleet.replicas[1].circuit.state = "open"
    assert fleet.retry_after_ms() >= FLEET_MIN_RETRY_AFTER_MS > 0.0
    fleet.replicas[1].circuit.state = "closed"
    # one draining replica: floored too
    fleet.replicas[0].health = "draining"
    assert fleet.retry_after_ms() >= FLEET_MIN_RETRY_AFTER_MS > 0.0
    # healthy again, warm EWMA + backlog: the hint is the BEST (minimum)
    # healthy replica's drain estimate
    fleet.replicas[0].health = "healthy"
    for rep in fleet.replicas:
        fleet._make_loop(rep)
        rep.engine.admission.force_token_cost_ms = 10.0
    busy = Request(prompt=np.zeros(4, np.int32), max_new_tokens=50)
    fleet.replicas[0].sched.slots[0] = busy  # white-box backlog
    assert fleet.retry_after_ms() == 0.0  # replica 1 is idle: best = 0
    other = Request(prompt=np.zeros(4, np.int32), max_new_tokens=10)
    fleet.replicas[1].sched.slots[0] = other
    # min(replica0: 10ms*50/2, replica1: 10ms*10/2) = 50.0
    assert fleet.retry_after_ms() == pytest.approx(50.0)


# --------------------------------------------------------- drain / rejoin
def test_rolling_drain_and_rejoin_zero_downtime(gpt2):
    """fleet.drain(replica) wraps the PR 9 graceful drain: in-flight
    requests finish, queued ones re-route to the surviving replica, and
    the drained replica rejoins through half-open probation — every
    request completes bitwise with the fleet never stopping."""
    ff, cfg = gpt2
    prompts = _prompts(10, seed=10)
    base = _baseline(ff, cfg, prompts, 8)
    fleet = _fleet(ff, cfg)
    chaos = FleetChaosPlan(drain_replica_at={2: 0}, rejoin_at={12: 0})
    outs = fleet.generate(prompts, max_new_tokens=8, chaos=chaos)
    st = fleet.stats
    assert outs == base
    assert st.outcomes == {"ok": 10}
    assert st.drains == 1 and st.rejoins == 1
    trail = [(t[3], t[4]) for t in st.health_transitions if t[1] == 0]
    assert ("draining", "drain_requested") in trail
    assert ("dead", "drained") in trail
    assert ("quarantined", "rejoin_probation") in trail
    assert ("healthy", "probe_pass") in trail


# ----------------------------------------------------------- plan lint
def test_fleet_plan_lint_names_the_bad_replica(gpt2):
    """Satellite: a heterogeneous plan set is linted per replica at
    construction (FF006 shape/divisibility) — the failure names the
    replica instead of surfacing as mid-serve garbage on 1/N of
    traffic."""
    from flexflow_tpu.analysis import StaticAnalysisError
    from flexflow_tpu.parallel.strategies import \
        hybrid_data_tensor_strategy

    ff, cfg = gpt2
    pcg = ff.executor.pcg
    bad = hybrid_data_tensor_strategy(pcg, 2, 4)
    guid = next(g for g, ns in bad.node_strategies.items()
                if ns.weight_specs)
    ns = bad.node_strategies[guid]
    wname = next(iter(ns.weight_specs))
    ns.weight_specs[wname] = (None, "bogus_axis")
    with pytest.raises(StaticAnalysisError) as ei:
        ServingFleet(ff, n_replicas=2, n_slots=2,
                     max_decode_len=cfg.seq_len, plans=[None, bad])
    msg = str(ei.value)
    assert "replica 1" in msg and "FF006" in msg
    assert "replica 0" not in msg  # the clean replica is not blamed
    # a clean plan set constructs fine
    ServingFleet(ff, n_replicas=2, n_slots=2, max_decode_len=cfg.seq_len,
                 plans=[None, hybrid_data_tensor_strategy(pcg, 2, 1)])


def test_plan_replicas_heterogeneous_generations(gpt2):
    """plan_replicas prices each replica on its OWN machine model (chip
    generation): the searched plans are valid fleet inputs and pass the
    per-replica lint."""
    from flexflow_tpu.serving import plan_replicas

    ff, cfg = gpt2
    plans = plan_replicas(ff.executor.pcg, ff.config, [4, 8],
                          generations=["v5e", "v5p"])
    assert len(plans) == 2
    assert all(p.sim_tokens_per_s > 0 for p in plans)
    fleet = ServingFleet(ff, n_replicas=2, n_slots=2,
                         max_decode_len=cfg.seq_len, plans=plans)
    assert fleet.replicas[0].plan is plans[0]


# ------------------------------------------------------- scheduler hooks
def test_scheduler_cancel_hooks_leave_no_ledger_entry():
    """cancel_slot / cancel_queued / remove_finished free capacity with
    NO terminal outcome — the hedge-loss and migration-harvest
    primitive."""
    sched = ContinuousBatchScheduler(n_slots=2, max_queue=4, max_len=32)
    a = Request(prompt=np.zeros(3, np.int32), max_new_tokens=4)
    b = Request(prompt=np.zeros(3, np.int32), max_new_tokens=4)
    sched.submit(a)
    sched.submit(b)
    assert sched.next_action()[0] == "prefill"  # a into slot 0
    got = sched.cancel_slot(0)
    assert got is a and a.outcome is None and not a.done
    assert not sched.finished and sched.active == 0
    sched.cancel_queued(b)
    assert sched.queued == 0 and not sched.finished
    assert sched.cancelled == 2
    # remove_finished withdraws a same-tick completion
    c = Request(prompt=np.zeros(3, np.int32), max_new_tokens=1)
    sched.submit(c)
    _, req, slot, _b = sched.next_action()
    sched.commit_token(slot, 7)  # finishes (length 1)
    assert sched.finished and c.outcome == "ok"
    assert sched.remove_finished(c)
    assert not sched.finished
    assert not sched.remove_finished(c)  # idempotent: already gone


# ----------------------------------------------------------- end to end
def test_fleet_chaos_end_to_end_ledger_conserved(gpt2):
    """Acceptance (ISSUE 11 satellite): a 3-replica fleet under a kill,
    a sustained degrade AND fleet-door shedding finishes with every
    submitted request under exactly one outcome — migrated/hedged
    streams included — and completed streams bitwise-equal to an
    undisturbed single-replica run."""
    ff, cfg = gpt2
    config = ff.config
    prompts = _prompts(12, seed=11)
    base = _baseline(ff, cfg, prompts, 8)
    config.shed_policy = "queue"
    try:
        fleet = _fleet(ff, cfg, n_replicas=3, max_queue=20)
        chaos = FleetChaosPlan(kill_replica_at={4: 0},
                               degrade_replica_at={6: 1},
                               degrade_poison_every=1)
        outs = fleet.generate(prompts, max_new_tokens=8, chaos=chaos)
        st = fleet.stats
        # the fleet-wide ledger: 12 submissions, each exactly once
        assert sum(st.outcomes.values()) == 12, st.outcomes
        assert set(st.outcomes) <= set(OUTCOMES)
        assert st.failovers == 1 and st.migrations >= 1
        assert st.circuit_opens >= 1
        # completed streams bitwise vs the undisturbed run
        done = [i for i, o in enumerate(outs) if len(o) == 8]
        assert done, "nothing completed under chaos"
        assert all(outs[i] == base[i] for i in done)
        # the ledger survives into telemetry semantics: ok count matches
        # the completed streams that were never shed
        assert st.outcomes.get("ok", 0) == len(done)
    finally:
        config.shed_policy = "off"


def test_fleet_telemetry_block_and_trace_digest(gpt2, tmp_path, capsys):
    """The StepTelemetry ``fleet`` block lands next to the serving
    blocks and trace_summary prints its digest."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import trace_summary

    ff, cfg = gpt2
    config = ff.config
    tel_file = tmp_path / "fleet_tel.json"
    config.telemetry_file = str(tel_file)
    try:
        fleet = _fleet(ff, cfg)
        fleet.generate(_prompts(6, seed=12), max_new_tokens=4,
                       chaos=FleetChaosPlan(kill_replica_at={3: 0}))
    finally:
        config.telemetry_file = ""
    import json

    data = json.loads(tel_file.read_text())
    blk = data["fleet"]
    assert blk["replicas"] == 2
    assert blk["outcomes"] == {"ok": 6}
    assert blk["failovers"] == 1
    assert sum(blk["dispatches"]) >= 6
    trace_summary.main([str(tel_file)])
    out = capsys.readouterr().out
    assert "fleet: 2 replicas" in out
    assert "failovers: 1" in out


def test_plain_chaosplan_fleet_run_is_clean(gpt2):
    """A fleet handed a plain ChaosPlan (no fleet hooks) runs clean —
    the chaos dispatch degrades gracefully instead of crashing."""
    ff, cfg = gpt2
    fleet = _fleet(ff, cfg)
    outs = fleet.generate(_prompts(4, seed=13), max_new_tokens=4,
                          chaos=ChaosPlan())
    assert fleet.stats.outcomes == {"ok": 4}
    assert all(len(o) == 4 for o in outs)
