"""Example scripts smoke tier (reference: tests/multi_gpu_tests.sh runs the
~50 example scripts to completion; here each runs tiny configs on the CPU
mesh)."""
import os
import sys

import pytest

# heavyweight tier: excluded from the fast tier-1 gate (-m 'not slow');
# still runs in the full suite / nightly (see pyproject [tool.pytest.ini_options])
pytestmark = pytest.mark.slow


EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples", "python")


def _load(subdir, name):
    import importlib.util

    path = os.path.join(EXAMPLES, subdir, name + ".py")
    entry = os.path.join(EXAMPLES, subdir)
    sys.path.insert(0, entry)
    try:
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    finally:
        # remove the exact entry: executing the module may itself insert at
        # position 0 (examples/_common.py adds the repo root)
        sys.path.remove(entry)


SMALL = ["-b", "8", "-e", "1"]


def test_mnist_mlp():
    _, perf = _load("native", "mnist_mlp").main(SMALL)
    assert perf.train_all == 32


def test_alexnet_cifar10():
    _, perf = _load("native", "alexnet").main(SMALL)
    assert perf.train_all == 32


def test_resnet_small():
    _, perf = _load("native", "resnet").main(["-b", "2", "-e", "1"],
                                             image_size=32, num_classes=10)
    assert perf.train_all == 8


def test_dlrm():
    _, perf = _load("native", "dlrm").main(
        SMALL, embedding_sizes=(50,) * 4, embedding_dim=16)
    assert perf.train_all == 32


def test_moe():
    _, perf = _load("native", "moe").main(SMALL)
    assert perf.train_all == 32


def test_mlp_unify_small():
    _, perf = _load("native", "mlp_unify").main(
        SMALL, hidden_dims=(64, 64, 10), input_dim=32)
    assert perf.train_all == 16


def test_xdl_small():
    _, perf = _load("native", "xdl").main(SMALL, vocab_size=500)
    assert perf.train_all == 16


def test_candle_uno_small():
    _, perf = _load("native", "candle_uno").main(
        SMALL, dense_layers=(64,), dense_feature_layers=(64,))
    assert perf.train_all == 16


def test_transformer_tiny():
    from flexflow_tpu.models import TransformerConfig

    _, perf = _load("native", "transformer").main(
        SMALL, cfg=TransformerConfig.tiny(batch_size=8))
    assert perf.train_all == 32


def test_bert_tiny():
    from flexflow_tpu.models import BertConfig

    _, perf = _load("native", "bert_proxy_native").main(
        SMALL, cfg=BertConfig.tiny(batch_size=8))
    assert perf.train_all == 16


def test_nmt_tiny():
    from flexflow_tpu.models import NMTConfig

    _load("native", "nmt").main(SMALL, cfg=NMTConfig.tiny(batch_size=4))


def test_keras_mnist():
    _, perf = _load("keras", "mnist_mlp").main(SMALL)
    assert perf.accuracy() >= 0.0


def test_keras_cifar10_cnn():
    _, perf = _load("keras", "cifar10_cnn").main(SMALL)
    assert perf.accuracy() >= 0.0


def test_torch_mlp():
    pytest.importorskip("torch")
    _, perf = _load("pytorch", "torch_mlp").main(SMALL)
    assert perf.accuracy() >= 0.0


# inception/resnext example wrappers are exercised at tiny scale by
# tests/test_model_zoo.py (same builders); full-size runs are bench-only.


def test_onnx_mlp_or_skip():
    mod = _load("onnx", "onnx_mlp")
    ff, perf = mod.main(SMALL)
    if ff is None:  # onnx not installed: gated skip is the contract
        return
    assert perf.accuracy() >= 0.0


def test_module_launcher(tmp_path):
    """python -m flexflow_tpu script.py -b 16 (flexflow_python analog)."""
    import subprocess

    script = tmp_path / "tiny.py"
    script.write_text(
        "from flexflow_tpu import FFConfig\n"
        "c = FFConfig()\n"
        "assert c.batch_size == 16, c.batch_size\n"
        "print('LAUNCHER_OK', c.batch_size)\n")
    repo_root = os.path.dirname(os.path.dirname(EXAMPLES))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo_root)
    r = subprocess.run(
        [sys.executable, "-m", "flexflow_tpu", str(script), "-b", "16"],
        capture_output=True, text=True, env=env, timeout=120)
    assert "LAUNCHER_OK 16" in r.stdout, (r.stdout, r.stderr)


def test_hf_bert_example():
    pytest.importorskip("transformers")
    _, perf = _load("pytorch", "hf_bert").main(SMALL)
    assert perf.train_all == 16


def test_mnist_cnn():
    _, perf = _load("native", "mnist_cnn").main(SMALL)
    assert perf.train_all == 32


def test_split_example():
    _, perf = _load("native", "split").main(SMALL)
    assert perf.train_all == 32


def test_cifar10_cnn_concat():
    _, perf = _load("native", "cifar10_cnn_concat").main(["-b", "4", "-e", "1"])
    assert perf.train_all == 16


def test_multi_head_attention_example():
    _, perf = _load("native", "multi_head_attention").main(SMALL)
    assert perf.train_all == 32


def test_mnist_mlp_attach():
    ff = _load("native", "mnist_mlp_attach").main(["-b", "8", "-e", "2"])
    # the manual loop trains for real: staged weight poke + readback work
    k = ff.get_layer_by_id(0).get_weight_tensor().get_weights(ff)
    assert k.shape == (784, 512)


def test_print_layers_introspection():
    ff = _load("native", "print_layers").main(["-b", "4"])
    import numpy as np

    assert np.allclose(ff.get_tensor_by_id(0).get_weights(ff), 1.2)


def test_demo_gather():
    ff = _load("native", "demo_gather").main(["-b", "4"], iters=10)
    assert ff is not None


def test_keras_elementwise_max_min():
    mod = _load("keras", "elementwise_max_min")
    perf = mod.elementwise_max(["-b", "8", "-e", "1"])
    assert perf.train_all == 32


def test_keras_func_cifar10_cnn_concat():
    _, perf = _load("keras", "func_cifar10_cnn_concat").main(
        ["-b", "4", "-e", "1"], num_samples=16)
    assert perf.train_all == 16


def test_torch_cifar10_cnn_ff_file_pair(tmp_path):
    """torch module -> .ff export -> file_to_ff -> train (reference:
    examples/python/pytorch/cifar10_cnn_torch.py + cifar10_cnn.py)."""
    pytest.importorskip("torch")
    ff_file = str(tmp_path / "cnn.ff")
    _load("pytorch", "cifar10_cnn_torch").main(ff_file)
    _, perf = _load("pytorch", "cifar10_cnn").main(
        ["-b", "8", "-e", "1"], ff_file=ff_file, num_samples=32)
    assert perf.train_all == 32


def test_torch_resnet_traced():
    pytest.importorskip("torch")
    _, perf = _load("pytorch", "resnet_torch").main(["-b", "4", "-e", "1"],
                                                    num_samples=8)
    assert perf.train_all == 8


def test_t5_mt5_example():
    pytest.importorskip("transformers")
    _load("pytorch/mt5", "mt5_ff").main(["-b", "2", "-e", "1"],
                                        num_samples=4)


def test_keras_net2net_weight_transfer():
    _, _ = _load("keras", "func_mnist_mlp_net2net").main(
        ["-b", "16", "-e", "1"], num_samples=64)


def test_gpt2_example():
    _, perf = _load("native", "gpt2").main(["-b", "4", "-e", "1"])
