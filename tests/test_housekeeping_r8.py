"""Round-8 housekeeping (ISSUE 5 satellites):

* ``scripts/check_docs_flags.py`` — every CLI flag parsed by
  ``flexflow_tpu/config.py`` must appear in ``docs/python_api.md``;
  flag/doc drift fails tier-1 here.
* the checker itself catches a missing flag (negative case) and
  whole-token matching does not let ``--budget`` satisfy
  ``--memory-budget-mb``.
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import check_docs_flags  # noqa: E402


def test_all_config_flags_documented(capsys):
    """The live repo state: zero undocumented flags."""
    assert check_docs_flags.main([]) == 0
    assert "ok: all" in capsys.readouterr().out


def test_checker_extracts_known_flags():
    flags = check_docs_flags.flags_in_config(
        os.path.join(REPO, "flexflow_tpu", "config.py"))
    # spot-check representative families: short, long, Legion-style, new
    for f in ("-e", "--batch-size", "--search-budget", "-ll:fsize",
              "-lg:prof_logfile", "--strategy-fallback", "--audit-strategy",
              "--audit-tol", "--memory-budget-mb", "--resume"):
        assert f in flags, f


def test_checker_fails_on_undocumented_flag(tmp_path, capsys):
    doc = tmp_path / "doc.md"
    doc.write_text("only `--epochs` is documented here\n")
    rc = check_docs_flags.main(
        [os.path.join(REPO, "flexflow_tpu", "config.py"), str(doc)])
    assert rc == 1
    err = capsys.readouterr().err
    assert "--batch-size" in err and "undocumented" in err


def test_checker_whole_token_matching(tmp_path):
    doc = tmp_path / "doc.md"
    doc.write_text("`--memory-budget-mb` is here but --budget is not\n")
    assert check_docs_flags.documented_in(doc.read_text(),
                                          "--memory-budget-mb")
    assert check_docs_flags.documented_in(doc.read_text(), "--budget")
    # prefix must NOT satisfy the longer flag
    assert not check_docs_flags.documented_in("has --memory only",
                                              "--memory-budget-mb")
