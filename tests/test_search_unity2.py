"""Widened Unity search: {R,S,Q} states, GraphXfer rewrites, parallel-op IR
insertion, memory-λ search, MCMC flag gating (VERDICT round-1 items 2/4/6)."""
import numpy as np
import pytest

from flexflow_tpu import ActiMode, FFConfig, FFModel, LossType
from flexflow_tpu.ffconst import OperatorType
from flexflow_tpu.models.bert import BertConfig, build_bert
from flexflow_tpu.search.machine_model import TPUMachineModel
from flexflow_tpu.search.simulator import OpSharding, Simulator
from flexflow_tpu.search.substitution import builtin_xfers
from flexflow_tpu.search.unity import (SearchSpace, best_first_optimize,
                                       dp_assign, node_options, unity_search)


def _transformer_pcg(batch=8, seq=512, hidden=1024, heads=16, layers=2,
                     inter=4096):
    config = FFConfig()
    config.batch_size = batch
    ff = FFModel(config)
    cfg = BertConfig(batch_size=batch, seq_len=seq, hidden=hidden,
                     num_heads=heads, num_layers=layers, intermediate=inter)
    build_bert(ff, cfg)
    pcg = ff.create_pcg()
    return pcg, config, ff


def test_search_discovers_megatron_interleave():
    """A residual transformer at realistic width: the DP must discover the
    Megatron pattern (col fc1 -> row fc2 and/or head-parallel attention)
    by itself — VERDICT item 2's Done criterion."""
    pcg, config, _ = _transformer_pcg(batch=8)
    # pin a 1D ring: on the default (2,4) torus the torus-aware cost model
    # gives the full-slice DP allreduce two concurrent rings, which flips
    # the DP-vs-hybrid tradeoff at this tiny depth — the discovery of the
    # megatron pattern itself is what this test pins
    machine = TPUMachineModel.from_generation("v5e", 8, torus=(8,))
    sim = Simulator(machine)
    assignment, states, t_tp = dp_assign(pcg, sim, dp=2, tp=4, batch_size=8)
    kinds = {}
    for g, a in assignment.items():
        node = pcg.nodes[g]
        if node.op.op_type == OperatorType.OP_LINEAR:
            kinds.setdefault(a.kind, 0)
            kinds[a.kind] += 1
        if node.op.op_type == OperatorType.OP_MULTIHEAD_ATTENTION:
            kinds.setdefault(f"attn_{a.kind}", 0)
            kinds[f"attn_{a.kind}"] += 1
    # fc1 col-parallel + fc2 row-parallel in every block
    assert kinds.get("col", 0) >= 2 and kinds.get("row", 0) >= 2, kinds
    # attention head-parallel (attribute parallelism)
    assert kinds.get("attn_heads", 0) >= 1, kinds
    # and the hybrid beats pure DP in simulation
    dp_assignment = {n.guid: OpSharding(dp=8) for n in pcg.compute_nodes()}
    t_dp, _ = sim.simulate(pcg, dp_assignment)
    t_hybrid, _ = sim.simulate(pcg, assignment, states)
    assert t_hybrid < t_dp, (t_hybrid, t_dp)


def test_sequence_parallel_in_search_space():
    """Ring attention (Q states) is a searchable option for self-attention
    and lowers to the sequence_parallel_axis attr."""
    pcg, config, _ = _transformer_pcg(batch=8, seq=2048, hidden=256, heads=4,
                                      layers=1, inter=512)
    attn = [n for n in pcg.compute_nodes()
            if n.op.op_type == OperatorType.OP_MULTIHEAD_ATTENTION][0]
    in_shapes = [pcg.nodes[g].out_shapes[i] for g, i in attn.inputs]
    opts = node_options(attn, 4, in_shapes)
    assert ("ring", "Q", "Q") in opts
    # seq-sharded state available on per-token ops
    lin = [n for n in pcg.compute_nodes()
           if n.op.op_type == OperatorType.OP_LINEAR][0]
    lin_shapes = [pcg.nodes[g].out_shapes[i] for g, i in lin.inputs]
    assert ("none", "Q", "Q") in node_options(lin, 4, lin_shapes)
    # disabled when the flag says so
    space = SearchSpace(sequence=False)
    assert ("ring", "Q", "Q") not in node_options(attn, 4, in_shapes, space)


def test_sequence_parallel_offered_with_dropout():
    """Regression (VERDICT r4 weak #1): round 4 built in-kernel SP dropout
    (ring/Ulysses share the flash counter stream), but a stale gate kept
    refusing ring SP to any model with attention dropout — exactly the
    realistic BERT/GPT configs (dropout 0.1). The option must be offered,
    and a full unity_search on a dropout model must be able to assign Q
    states to the attention block."""
    config = FFConfig()
    config.batch_size = 4
    ff = FFModel(config)
    # long-context shape: at seq 16384 the O(s^2) attention-compute saving
    # of seq sharding dominates the O(s) ring K/V rotation, so the DP's
    # choice of Q is cost-driven, not forced
    cfg = BertConfig(batch_size=4, seq_len=16384, hidden=256, num_heads=4,
                     num_layers=1, intermediate=512, dropout=0.1)
    build_bert(ff, cfg)
    pcg = ff.create_pcg()
    attn = [n for n in pcg.compute_nodes()
            if n.op.op_type == OperatorType.OP_MULTIHEAD_ATTENTION][0]
    assert attn.op.attrs.get("dropout") == 0.1  # the gate's old trigger
    in_shapes = [pcg.nodes[g].out_shapes[i] for g, i in attn.inputs]
    opts = node_options(attn, 4, in_shapes)
    assert ("ring", "Q", "Q") in opts, opts

    # and the DP can actually assign Q states to a dropout attention model
    # (restricted to the sequence family so the assertion pins the SP path
    # itself rather than a cost race against Megatron parameter parallelism)
    machine = TPUMachineModel.from_generation("v5e", 4, torus=(4,))
    sim = Simulator(machine)
    space = SearchSpace(parameter=False, attribute=False, sequence=True)
    assignment, states, _ = dp_assign(pcg, sim, dp=1, tp=4, batch_size=4,
                                      space=space)
    assert "Q" in set(states.values()), states
    attn_kind = assignment[attn.guid].kind
    assert attn_kind == "ring", attn_kind


def test_graphxfer_apply_fuses_activation():
    """GraphXfer.apply performs a real rewrite: dense+relu -> fused dense,
    graph shrinks, numerics preserved (VERDICT item 2a)."""
    config = FFConfig()
    config.batch_size = 4
    ff = FFModel(config)
    x = ff.create_tensor((4, 64))
    t = ff.dense(x, 32)           # activation NONE
    t = ff.relu(t)
    t = ff.dense(t, 8)
    ff.compile(loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE)
    pcg = ff.create_pcg()
    n_before = len(pcg.compute_nodes())
    xfer = [x for x in builtin_xfers() if x.name == "linear_relu_fuse"][0]
    matches = xfer.find_matches(pcg)
    assert len(matches) == 1
    g2 = xfer.apply(pcg, matches[0])
    assert len(g2.compute_nodes()) == n_before - 1
    fused = [n for n in g2.compute_nodes()
             if n.op.op_type == OperatorType.OP_LINEAR
             and n.op.attrs.get("activation") == ActiMode.AC_MODE_RELU]
    assert len(fused) == 1
    # numerics: run both graphs with identical weights
    from flexflow_tpu.execution.executor import Executor

    import jax.numpy as jnp

    from flexflow_tpu.ops.base import OpContext

    rng = np.random.default_rng(0)
    xval = jnp.asarray(rng.normal(size=(4, 64)).astype(np.float32))
    kernel1 = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32))
    bias1 = jnp.zeros(32)

    def run(pcg_in):
        vals = {}
        ctx = OpContext(training=False)
        for node in pcg_in.topo_order():
            if node.op.op_type == OperatorType.OP_INPUT:
                vals[node.guid] = [xval]
                continue
            ins = [vals[g][i] for g, i in node.inputs]
            if node.op.op_type == OperatorType.OP_LINEAR \
                    and node.op.attrs["out_dim"] == 32:
                params = {"kernel": kernel1, "bias": bias1}
            else:
                ws = node.op.weight_specs([x.shape for x in ins])
                params = {w: jnp.ones(spec[0]) * 0.01
                          for w, spec in ws.items()}
            vals[node.guid] = node.op.forward(params, ins, ctx)
        sink = [n for n in pcg_in.compute_nodes()][-1]
        return np.asarray(vals[sink.guid][0])

    np.testing.assert_allclose(run(pcg), run(g2), rtol=1e-5)


def test_best_first_applies_beneficial_xfer():
    """best_first_optimize adopts the fused graph when the simulator says it
    is cheaper (reference: base_optimize's accept-if-better)."""
    config = FFConfig()
    config.batch_size = 64
    ff = FFModel(config)
    x = ff.create_tensor((64, 1024))
    t = ff.dense(x, 4096)
    t = ff.relu(t)
    t = ff.dense(t, 1024)
    ff.compile(loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE)
    pcg = ff.create_pcg()
    machine = TPUMachineModel.from_generation("v5e", 8)
    sim = Simulator(machine)
    g, a, s, t_best = best_first_optimize(
        pcg, sim, dp=8, tp=1, batch=64, xfers=builtin_xfers(), budget=16,
        alpha=1.05)
    assert len(g.compute_nodes()) < len(pcg.compute_nodes())
    _, _, t_orig = dp_assign(pcg, sim, 8, 1, 64)
    assert t_best <= t_orig


def test_unity_search_inserts_parallel_op_nodes():
    """The searched strategy's sharding transitions appear as first-class
    parallel-op nodes with costs in the DOT export (VERDICT item 6)."""
    pcg, config, _ = _transformer_pcg(batch=8)
    machine = TPUMachineModel.from_generation("v5e", 8)
    res = unity_search(pcg, config, 8, machine=machine, return_result=True)
    if res.mesh_shape[1] == 1:
        pytest.skip("search picked pure DP; no transitions to materialize")
    par_nodes = [n for n in pcg.compute_nodes()
                 if getattr(n.op, "is_parallel_op", False)]
    assert par_nodes, "no parallel-op nodes inserted"
    dot = pcg.to_dot()
    assert any(n.name in dot for n in par_nodes)
    assert all("comm_cost_us" in n.op.attrs for n in par_nodes)


def test_memory_lambda_search_returns_feasible():
    """Unconstrained best exceeds a small HBM budget; the λ binary search
    must return a feasible (slower, smaller) strategy instead (reference:
    graph.cc:2060-2133, --memory-search + -ll:fsize). Activation-heavy MLP:
    the time-optimal mesh (dp=4,tp=2 at ~40 MiB/chip) is infeasible at a
    25 MiB budget while higher-TP strategies fit."""
    config = FFConfig()
    config.batch_size = 2048
    ff = FFModel(config)
    x = ff.create_tensor((2048, 1024))
    t = x
    for _ in range(4):
        t = ff.dense(t, 1024, ActiMode.AC_MODE_RELU)
    ff.softmax(ff.dense(t, 8))
    ff.compile(loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    pcg = ff.create_pcg()
    machine = TPUMachineModel.from_generation("v5e", 8)

    config.perform_memory_search = False
    res_free = unity_search(pcg.copy(), config, 8, machine=machine,
                            return_result=True)
    budget_mb = 25
    assert res_free.sim_memory > budget_mb * 2 ** 20, \
        "wedge vanished: unconstrained best already fits"
    config.device_memory_mb = budget_mb
    config.perform_memory_search = True
    res_mem = unity_search(pcg.copy(), config, 8, machine=machine,
                           return_result=True)
    assert res_mem.sim_memory <= budget_mb * 2 ** 20, \
        f"λ search returned infeasible {res_mem.sim_memory / 2 ** 20:.1f} MiB"
    assert res_mem.sim_time >= res_free.sim_time  # paid time for memory


def test_mcmc_honors_parallel_flags():
    """enable_parameter_parallel gates the MCMC space exactly like the
    reference (linear.cc:727 get_random_parallel_config)."""
    pcg, config, _ = _transformer_pcg(batch=8, seq=64, hidden=128, heads=4,
                                      layers=1, inter=256)
    node = [n for n in pcg.compute_nodes()
            if n.op.op_type == OperatorType.OP_LINEAR][0]
    in_shapes = [pcg.nodes[g].out_shapes[i] for g, i in node.inputs]
    space_off = SearchSpace.from_config(config)  # defaults: both False
    kinds_off = {k for k, _, _ in node_options(node, 4, in_shapes, space_off)}
    assert "col" not in kinds_off and "row" not in kinds_off
    config.enable_parameter_parallel = True
    space_on = SearchSpace.from_config(config)
    kinds_on = {k for k, _, _ in node_options(node, 4, in_shapes, space_on)}
    assert "col" in kinds_on and "row" in kinds_on


def test_searched_strategy_with_parallel_ops_executes():
    """End-to-end: a search-produced strategy (with inserted parallel-op
    nodes) trains on the 8-device CPU mesh."""
    config = FFConfig()
    config.batch_size = 8
    ff = FFModel(config)
    cfg = BertConfig(batch_size=8, seq_len=128, hidden=512, num_heads=8,
                     num_layers=1, intermediate=2048)
    build_bert(ff, cfg)
    machine = TPUMachineModel.from_generation("v5e", 8)
    ff.compile(loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               strategy_fn=lambda pcg: unity_search(pcg, config, 8,
                                                    machine=machine))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, cfg.seq_len, cfg.hidden)).astype(np.float32)
    y = rng.integers(0, 2, size=16).astype(np.int32)
    ff.fit(x, y, epochs=1)
