"""Serving under fire (ISSUE 9, flexflow_tpu/serving/resilience.py,
docs/serving.md "Serving under failure"): deadline eviction with slot
recycling, admission load shedding (shed-vs-accept determinism under a
scripted queue storm), decode-health quarantine with bit-identical
neighbors and a retried stream, graceful SIGTERM drain returning queued
requests, and automatic elastic_replan after a chaos device drop — all
driven deterministically on CPU by the ChaosPlan serving extensions."""
import signal

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.models.gpt2 import GPT2Config, build_gpt2
from flexflow_tpu.resilience import ChaosPlan
from flexflow_tpu.serving import (ContinuousBatchScheduler, OverloadError,
                                  QueueFullError, Request, ServingEngine,
                                  ServingRejection)


@pytest.fixture(scope="module")
def gpt2():
    cfg = GPT2Config.tiny(batch_size=8)
    config = FFConfig()
    config.batch_size = cfg.batch_size
    ff = FFModel(config)
    build_gpt2(ff, cfg)
    ff.compile(optimizer=SGDOptimizer(ff),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff, cfg


def _prompts(n, seed=0, lo=3, hi=6):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 100, size=int(rng.integers(lo, hi))).tolist()
            for _ in range(n)]


def _engine(ff, cfg, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("max_decode_len", cfg.seq_len)
    return ServingEngine(ff, **kw)


class _ScriptedClock:
    """Deterministic ms clock: advances a fixed amount per call, so every
    deadline/drain decision is a pure function of the call sequence."""

    def __init__(self, step_ms=5.0):
        self.t = 0.0
        self.step_ms = step_ms

    def __call__(self):
        self.t += self.step_ms
        return self.t


# ----------------------------------------------------------------- deadlines
def test_deadline_eviction_recycles_slot_neighbors_bitwise(gpt2):
    """A request whose deadline expires mid-decode is evicted (outcome
    deadline_exceeded), its slot is recycled into the waiting queue, and
    co-batched streams are bitwise-unchanged vs an undisturbed run."""
    ff, cfg = gpt2
    prompts = _prompts(3, seed=1)
    base = _engine(ff, cfg).generate(prompts, max_new_tokens=8)

    eng = _engine(ff, cfg)
    eng.resilience_clock = _ScriptedClock(step_ms=5.0)
    # per-request deadlines: request 0 gets a tight budget that expires
    # after a few decode steps; 1 and 2 are unconstrained
    res = eng._make_resilience(None)
    sched = ContinuousBatchScheduler(n_slots=2, max_queue=8,
                                     buckets=eng.buckets,
                                     max_len=eng.max_decode_len,
                                     clock=res.clock)
    reqs = []
    for i, p in enumerate(prompts):
        r = Request(prompt=np.asarray(p, np.int32), max_new_tokens=8,
                    rng_tag=i,
                    deadline_ms=60.0 if i == 0 else None)
        res.admit(sched, r)
        reqs.append(r)
    eng.serve(sched, resilience=res)

    assert reqs[0].outcome == "deadline_exceeded"
    assert 0 < len(reqs[0].generated) < 8  # started, then evicted
    # neighbors bitwise-unchanged, and the recycled slot served request 2
    assert list(reqs[1].generated) == base[1]
    assert list(reqs[2].generated) == base[2]
    assert reqs[2].outcome == "ok" and len(reqs[2].generated) == 8
    assert eng.stats.deadline_misses == 1
    assert eng.stats.outcomes == {"ok": 2, "deadline_exceeded": 1}
    # requests_served counts clean completions only — the evicted
    # request lives in the outcome ledger, not the served count
    assert eng.stats.requests_served == 2
    assert sched.evicted == 1


def test_deadline_expired_in_queue_never_costs_a_prefill(gpt2):
    """Admission-time enforcement: a request already past its deadline
    while queued is dropped before it claims prefill compute."""
    ff, cfg = gpt2
    prompts = _prompts(4, seed=2)
    eng = _engine(ff, cfg, n_slots=1)
    # 1 ms deadline, clock advancing 5 ms/call: queued requests are
    # already expired by the first sweep — only the first request (whose
    # prefill can start before any sweep runs... it too expires) may run
    eng.resilience_clock = _ScriptedClock(step_ms=5.0)
    outs = eng.generate(prompts, max_new_tokens=4, deadline_ms=1.0)
    assert all(o == [] for o in outs)
    assert eng.stats.outcomes == {"deadline_exceeded": 4}
    assert eng.stats.prefills == 0


# ------------------------------------------------------------------ shedding
def test_shed_policy_queue_deterministic_and_rejection_base(gpt2):
    """'queue' policy sheds at the max_queue//2 high-water mark with a
    typed OverloadError; the shed-vs-accept pattern is deterministic run
    to run, and ONE except clause catches both rejection types."""
    ff, cfg = gpt2
    config = ff.config
    config.shed_policy = "queue"
    try:
        def storm_pattern():
            eng = _engine(ff, cfg, n_slots=1)
            res = eng._make_resilience(None)
            sched = ContinuousBatchScheduler(n_slots=1, max_queue=4,
                                             max_len=eng.max_decode_len,
                                             clock=res.clock)
            sched.shed_policy = res.shed_policy
            pat = []
            for i in range(8):
                r = Request(prompt=np.asarray([1, 2, 3], np.int32),
                            max_new_tokens=2, rng_tag=i)
                try:
                    res.admit(sched, r)
                    pat.append("accept")
                except ServingRejection as e:  # ONE clause, both types
                    pat.append(type(e).__name__)
                    assert e.queued >= 0 and e.active >= 0
                    assert e.retry_after_ms >= 0.0
                    assert r.outcome == "shed"
            return pat, res
        a, res_a = storm_pattern()
        b, _ = storm_pattern()
        assert a == b, "shed-vs-accept pattern not deterministic"
        assert a[:2] == ["accept", "accept"]  # below high-water (4//2=2)
        assert set(a[2:]) == {"OverloadError"}
        assert res_a.sheds == 6
    finally:
        config.shed_policy = "off"


def test_shed_policy_deadline_uses_completion_estimate(gpt2):
    """'deadline' policy sheds when the EWMA completion estimate blows
    the request deadline, with a retry_after_ms drain hint."""
    ff, cfg = gpt2
    config = ff.config
    config.shed_policy = "deadline"
    try:
        eng = _engine(ff, cfg, n_slots=1)
        eng.admission.force_token_cost_ms = 10.0  # scripted cost model
        res = eng._make_resilience(None)
        sched = ContinuousBatchScheduler(n_slots=1, max_queue=16,
                                         max_len=eng.max_decode_len,
                                         clock=res.clock)
        ok = Request(prompt=np.asarray([1, 2], np.int32),
                     max_new_tokens=4, deadline_ms=100.0)
        res.admit(sched, ok)  # est = 10 * 4 = 40 <= 100
        tight = Request(prompt=np.asarray([1, 2], np.int32),
                        max_new_tokens=4, deadline_ms=50.0)
        with pytest.raises(OverloadError) as ei:
            # est = 10 * (4 queued tokens / 1 slot + 4) = 80 > 50
            res.admit(sched, tight)
        assert ei.value.retry_after_ms == pytest.approx(40.0)
        assert "deadline" in str(ei.value)
        # no deadline -> nothing to blow -> admitted
        free = Request(prompt=np.asarray([1, 2], np.int32),
                       max_new_tokens=4)
        res.admit(sched, free)
        assert sched.queued == 2 and res.sheds == 1
    finally:
        config.shed_policy = "off"


def test_queue_full_error_names_shed_policy():
    sched = ContinuousBatchScheduler(n_slots=1, max_queue=1, max_len=32)
    sched.shed_policy = "deadline"
    sched.submit(Request(prompt=np.zeros(4, np.int32), max_new_tokens=4))
    with pytest.raises(QueueFullError, match="shed policy 'deadline'") \
            as ei:
        sched.submit(Request(prompt=np.zeros(4, np.int32),
                             max_new_tokens=4))
    assert isinstance(ei.value, ServingRejection)
    assert ei.value.queued == 1


# ---------------------------------------------------------------- quarantine
def test_decode_poison_quarantined_retried_neighbors_bitwise(gpt2):
    """A NaN-poisoned decode slot is quarantined ALONE: co-batched
    streams continue bit-identically, and the poisoned request is retried
    on a fresh slot, resuming its stream exactly where the quarantine cut
    it (bitwise under exact decode numerics)."""
    ff, cfg = gpt2
    prompts = _prompts(4, seed=3)
    base = _engine(ff, cfg, exact_decode=True).generate(prompts,
                                                        max_new_tokens=5)
    eng = _engine(ff, cfg, exact_decode=True)
    chaos = ChaosPlan(poison_decode_at={2: 0})
    outs = eng.generate(prompts, max_new_tokens=5, chaos=chaos)
    assert chaos.poisoned_decode_steps == [2]
    assert outs == base, "retried/neighbor streams diverged"
    st = eng.stats
    assert st.quarantines == 1 and st.decode_retries == 1
    assert st.outcomes == {"ok": 4}
    # the guarded decode step stays recompile-free too
    assert eng._last_guard is True and eng.decode_compiles == 1


def test_repeated_poison_aborts_decode_fault(gpt2):
    """Retry budget spent -> the request aborts with outcome decode_fault
    while neighbors still finish bit-identically."""
    ff, cfg = gpt2
    prompts = _prompts(2, seed=4)
    base = _engine(ff, cfg, exact_decode=True).generate(prompts,
                                                        max_new_tokens=6)
    eng = _engine(ff, cfg, exact_decode=True)
    # slot 0 poisoned at step 1; the retry re-prefills into the only free
    # slot (0 again) and is poisoned again at step 3 — budget 1 exhausted
    chaos = ChaosPlan(poison_decode_at={1: 0, 3: 0})
    outs = eng.generate(prompts, max_new_tokens=6, chaos=chaos)
    st = eng.stats
    assert st.outcomes == {"ok": 1, "decode_fault": 1}
    assert st.quarantines == 2 and st.decode_retries == 1
    faulted = [i for i, p in enumerate(prompts)
               if len(outs[i]) < 6]
    assert len(faulted) == 1
    ok_idx = 1 - faulted[0]
    assert outs[ok_idx] == base[ok_idx], "neighbor stream diverged"


def test_decode_retry_budget_zero_aborts_immediately(gpt2):
    ff, cfg = gpt2
    config = ff.config
    config.decode_retry_budget = 0
    try:
        eng = _engine(ff, cfg)
        chaos = ChaosPlan(poison_decode_at={1: 0})
        eng.generate(_prompts(1, seed=5), max_new_tokens=6, chaos=chaos)
        st = eng.stats
        assert st.outcomes == {"decode_fault": 1}
        assert st.quarantines == 1 and st.decode_retries == 0
    finally:
        config.decode_retry_budget = 1


# --------------------------------------------------------------------- drain
def test_sigterm_drain_returns_queued_and_finishes_inflight(gpt2):
    """Mid-serve SIGTERM: admission stops, the in-flight request finishes
    its full generation, queued requests come back for re-submission —
    and re-submitting them on a fresh serve completes them."""
    ff, cfg = gpt2
    prompts = _prompts(3, seed=6)
    prev = signal.getsignal(signal.SIGTERM)
    eng = _engine(ff, cfg, n_slots=1)
    chaos = ChaosPlan(preempt_serving_at=1)
    outs = eng.generate(prompts, max_new_tokens=4, chaos=chaos)
    assert signal.getsignal(signal.SIGTERM) is prev, "handler not restored"
    assert chaos.serving_preempted_at == 1
    assert len(outs[0]) == 4, "in-flight request did not finish"
    assert outs[1] == [] and outs[2] == []
    drained = eng.drained_requests
    assert [r.rng_tag for r in drained] == [1, 2]
    assert all(r.outcome == "preempted" for r in drained)
    st = eng.stats
    assert st.drains == 1 and st.drained_returned == 2
    assert st.outcomes == {"ok": 1, "preempted": 2}
    # the drained requests are clean for re-submission elsewhere
    res = eng._make_resilience(None)
    sched = ContinuousBatchScheduler(n_slots=1, max_queue=8,
                                     max_len=eng.max_decode_len,
                                     clock=res.clock)
    for r in drained:
        r.outcome = None
        res.admit(sched, r)
    eng.serve(sched, resilience=res)
    assert all(len(r.generated) == 4 and r.outcome == "ok"
               for r in drained)


def test_drain_grace_zero_evicts_inflight_as_preempted(gpt2):
    ff, cfg = gpt2
    config = ff.config
    config.drain_grace_s = 0.0
    try:
        eng = _engine(ff, cfg, n_slots=1)
        chaos = ChaosPlan(preempt_serving_at=1)
        outs = eng.generate(_prompts(2, seed=7), max_new_tokens=6,
                            chaos=chaos)
        st = eng.stats
        assert st.outcomes == {"preempted": 2}
        assert 0 < len(outs[0]) < 6  # evicted mid-generation
        assert st.drained_returned == 1
    finally:
        config.drain_grace_s = 5.0


# ------------------------------------------------------------------ failover
def test_device_drop_auto_replans_decode_state_bitwise(gpt2):
    """ChaosPlan.drop_devices_at mid-decode triggers elastic_replan
    automatically (bounded backoff, first retry immediate); the in-flight
    DecodeState survives the hop so continuations are bit-identical to an
    undisturbed run (PR 6's replan test pattern, now self-driving)."""
    ff, cfg = gpt2
    prompts = _prompts(4, seed=8)
    base = _engine(ff, cfg).generate(prompts, max_new_tokens=5)
    eng = _engine(ff, cfg)
    chaos = ChaosPlan(drop_devices_at={2: 4})
    outs = eng.generate(prompts, max_new_tokens=5, chaos=chaos)
    assert outs == base, "DecodeState did not survive the auto-replan"
    assert chaos.devices_dropped == [2]
    assert eng.stats.replans == 1
    assert eng.plan is not None and \
        eng.plan.mesh_shape[0] * eng.plan.mesh_shape[1] <= 4
    assert eng.stats.outcomes == {"ok": 4}


def test_real_loss_with_dead_state_reprefills_bitwise(gpt2):
    """A REAL device loss raised from inside the dispatch consumes the
    donated DecodeState. The engine must not retry into 'Array has been
    deleted': it replans, rebuilds the pool, and re-prefills every live
    stream from its host-side committed tokens — continuations stay
    bit-identical (exact decode) and every request still ends ok."""
    import jax

    ff, cfg = gpt2
    prompts = _prompts(3, seed=11)
    base = _engine(ff, cfg, exact_decode=True).generate(prompts,
                                                        max_new_tokens=5)
    eng = _engine(ff, cfg, exact_decode=True)
    real = eng._decode_fn
    fired = []

    def patched(guard=False):
        fn = real(guard=guard)

        def wrapper(params, toks, state):
            if eng.stats.decode_steps == 2 and not fired:
                fired.append(True)
                for leaf in jax.tree_util.tree_leaves(
                        (state, eng._last_tokens)):
                    leaf.delete()
                raise RuntimeError("FAILED_PRECONDITION: Device is lost")
            return fn(params, toks, state)
        return wrapper

    eng._decode_fn = patched
    outs = eng.generate(prompts, max_new_tokens=5, chaos=ChaosPlan())
    assert fired, "scripted loss never fired"
    assert outs == base, "streams diverged across the state rebuild"
    assert eng.stats.replans == 1
    assert eng.stats.outcomes == {"ok": 3}


def test_direct_scheduler_submit_deadline_enforced(gpt2):
    """A caller-set Request.deadline_ms must be enforced even when the
    request was submitted straight to the scheduler (sched.submit, the
    PR 6 pattern) and never passed engine.admit — serve() arms the
    sweeps from the deadlines already in the scheduler."""
    ff, cfg = gpt2
    eng = _engine(ff, cfg, n_slots=1)
    clock = _ScriptedClock(step_ms=5.0)
    sched = ContinuousBatchScheduler(n_slots=1, max_queue=8,
                                     max_len=eng.max_decode_len,
                                     clock=clock)
    doomed = Request(prompt=np.asarray([1, 2, 3], np.int32),
                     max_new_tokens=8, rng_tag=0, deadline_ms=20.0)
    easy = Request(prompt=np.asarray([4, 5, 6], np.int32),
                   max_new_tokens=3, rng_tag=1)
    sched.submit(doomed)
    sched.submit(easy)
    eng.serve(sched)
    assert eng._last_guard is True, "direct-submit deadline did not arm"
    assert doomed.outcome == "deadline_exceeded"
    assert easy.outcome == "ok" and len(easy.generated) == 3


def test_completion_estimate_counts_inflight_backlog():
    """The admission estimate must see a saturated slot pool: in-flight
    remaining tokens delay a new request's first token exactly like a
    deep queue does (otherwise the 'deadline' policy under-sheds and
    retry_after_ms reads 0 in the busiest regime)."""
    from flexflow_tpu.serving import AdmissionController

    ctrl = AdmissionController()
    ctrl.force_token_cost_ms = 10.0
    sched = ContinuousBatchScheduler(n_slots=1, max_queue=8,
                                     buckets=(8,), max_len=64)
    busy = Request(prompt=np.zeros(4, np.int32), max_new_tokens=100)
    sched.slots[0] = busy  # white-box: pool saturated, queue empty
    req = Request(prompt=np.zeros(4, np.int32), max_new_tokens=4)
    assert ctrl.estimate_completion_ms(req, sched) == \
        pytest.approx(10.0 * (100 + 4))
    assert ctrl.retry_after_ms(sched) == pytest.approx(1000.0)


def test_non_device_loss_errors_still_propagate(gpt2):
    """The failover detector is conservative: an arbitrary error from the
    decode path must NOT be eaten by a replan loop."""
    from flexflow_tpu.serving.resilience import looks_like_device_loss

    assert not looks_like_device_loss(ValueError("shape mismatch"))
    assert looks_like_device_loss(
        RuntimeError("FAILED_PRECONDITION: Device is lost"))


# ------------------------------------------------------------- end to end
def test_chaos_end_to_end_every_request_accounted(gpt2):
    """Acceptance (ISSUE 9): one serve loop with a scripted decode-NaN, a
    queue storm through the 'queue' shed policy, and a mid-serve SIGTERM
    finishes with every request under exactly one outcome (no hangs, no
    lost requests), the quarantined request's neighbors bitwise-equal to
    an undisturbed run, and the drain returning the still-queued
    requests."""
    ff, cfg = gpt2
    config = ff.config
    prompts = _prompts(4, seed=9)
    base = _engine(ff, cfg, exact_decode=True).generate(prompts,
                                                        max_new_tokens=6)
    storm = {4: [[7, 8, 9]] * 6}
    config.shed_policy = "queue"
    try:
        # max_queue 8 -> 'queue' policy high-water 4: part of the storm
        # is accepted, the rest shed; SIGTERM lands while storm work is
        # still queued so the drain has something to hand back
        eng = _engine(ff, cfg, exact_decode=True, max_queue=8)
        chaos = ChaosPlan(poison_decode_at={3: 1},
                          storm_queue=storm,
                          storm_max_new_tokens=3,
                          preempt_serving_at=5)
        outs = eng.generate(prompts, max_new_tokens=6, chaos=chaos)
        st = eng.stats
        # ledger: 4 generate requests + 6 storm requests, each under
        # exactly one outcome
        assert sum(st.outcomes.values()) == 10
        assert set(st.outcomes) <= {"ok", "deadline_exceeded", "shed",
                                    "decode_fault", "preempted"}
        assert st.quarantines >= 1, "poison never fired"
        assert st.sheds >= 1, "storm never shed"
        assert st.drains == 1, "SIGTERM never drained"
        # neighbor isolation: every generate request that ran to
        # completion matches the undisturbed run bitwise
        for i, o in enumerate(outs):
            if len(o) == 6:
                assert o == base[i], f"request {i} diverged"
        assert any(len(o) == 6 for o in outs)
        # drain handoff: queued-at-SIGTERM requests were returned
        assert st.drained_returned == len(eng.drained_requests)
        assert all(r.outcome == "preempted"
                   for r in eng.drained_requests)
    finally:
        config.shed_policy = "off"


def test_engine_admit_state_survives_into_serve(gpt2):
    """engine.admit() without an explicit resilience accumulates on a
    pending policy object the next serve() consumes: a caller-set
    deadline stamped pre-serve arms the sweeps, and nothing is lost to a
    throwaway object."""
    ff, cfg = gpt2
    eng = _engine(ff, cfg, n_slots=1)
    sched = ContinuousBatchScheduler(n_slots=1, max_queue=8,
                                     max_len=eng.max_decode_len)
    reqs = [Request(prompt=np.asarray([1, 2, 3], np.int32),
                    max_new_tokens=4, rng_tag=i,
                    deadline_ms=1e-9 if i else None)
            for i in range(2)]
    for r in reqs:
        eng.admit(sched, r)
    assert eng._pending_resilience is not None
    assert eng._pending_resilience.deadlines_armed
    eng.serve(sched)
    assert eng._pending_resilience is None  # consumed
    assert eng._last_guard is True, "pre-serve deadline did not arm serve"
    # the nano-deadline request was enforced, its sibling completed
    assert reqs[1].outcome == "deadline_exceeded"
    assert reqs[0].outcome == "ok" and len(reqs[0].generated) == 4
    assert eng.stats.outcomes == {"ok": 1, "deadline_exceeded": 1}


def test_queue_full_policy_off_still_ledgered_as_shed(gpt2):
    """With --shed-policy off the only admission gate is the hard
    QueueFullError wall — a request rejected there must STILL leave the
    system under exactly one outcome (shed), not vanish from the
    accounting."""
    ff, cfg = gpt2
    eng = _engine(ff, cfg, n_slots=1)
    res = eng._make_resilience(None)
    assert res.shed_policy == "off"
    sched = ContinuousBatchScheduler(n_slots=1, max_queue=2,
                                     max_len=eng.max_decode_len,
                                     clock=res.clock)
    sched.shed_policy = res.shed_policy
    reqs = [Request(prompt=np.asarray([1, 2, 3], np.int32),
                    max_new_tokens=2, rng_tag=i) for i in range(6)]
    rejected = []
    for r in reqs:
        try:
            res.admit(sched, r)
        except QueueFullError:
            rejected.append(r)
    assert rejected, "queue wall never hit"
    assert all(r.outcome == "shed" for r in rejected)
    assert res.sheds == len(rejected)
    eng.serve(sched, resilience=res)
    st = eng.stats
    assert sum(st.outcomes.values()) == len(reqs)  # all 6 accounted
    assert st.outcomes["shed"] == len(rejected)
    assert st.outcomes["ok"] == len(reqs) - len(rejected)


def test_pending_admit_sheds_merge_into_explicit_resilience(gpt2):
    """A shed ledgered on the pending policy object (engine.admit with no
    explicit resilience) survives into a serve() that IS handed an
    explicit resilience object — the pending counters merge instead of
    being dropped with the throwaway."""
    ff, cfg = gpt2
    eng = _engine(ff, cfg, n_slots=1)
    sched = ContinuousBatchScheduler(n_slots=1, max_queue=1,
                                     max_len=eng.max_decode_len)
    ok_req = Request(prompt=np.asarray([1, 2, 3], np.int32),
                     max_new_tokens=2, rng_tag=0)
    eng.admit(sched, ok_req)
    overflow = Request(prompt=np.asarray([4, 5, 6], np.int32),
                       max_new_tokens=2, rng_tag=1)
    with pytest.raises(ServingRejection):
        eng.admit(sched, overflow)  # hard wall -> pending ledger
    assert eng._pending_resilience.sheds == 1
    res = eng._make_resilience(None)  # caller supplies a fresh object
    eng.serve(sched, resilience=res)
    assert eng._pending_resilience is None  # consumed, not leaked
    assert res.sheds == 1  # merged, not lost
    assert eng.stats.outcomes == {"ok": 1, "shed": 1}


def test_retry_resubmitted_to_narrow_scheduler_refused_at_submit():
    """A quarantine-retry request (committed tokens in tow) resubmitted
    to a scheduler whose buckets cannot cover prompt+generated must be
    refused AT SUBMIT — never after next_action() already claimed a slot
    (the slot-pool-corruption guard covers effective_len too)."""
    narrow = ContinuousBatchScheduler(n_slots=1, max_queue=8,
                                      buckets=(4,), max_len=32)
    retry = Request(prompt=np.zeros(3, np.int32), max_new_tokens=6,
                    generated=[5, 6, 7])  # effective_len 6 > bucket 4
    with pytest.raises(ValueError, match="largest prefill bucket"):
        narrow.submit(retry)
    assert narrow.queued == 0 and not narrow.active
    assert narrow.next_action() is None  # pool untouched


def test_plain_serve_stays_unguarded_and_rejection_free(gpt2):
    """Nothing armed -> the decode step is the unguarded program and no
    resilience bookkeeping appears in the stats (zero-overhead claim)."""
    ff, cfg = gpt2
    eng = _engine(ff, cfg)
    outs = eng.generate(_prompts(2, seed=10), max_new_tokens=3)
    assert all(len(o) == 3 for o in outs)
    assert eng._last_guard is False
    st = eng.stats
    assert st.outcomes == {"ok": 2}
    assert st.quarantines == 0 and st.sheds == 0 and st.drains == 0
