"""Model-zoo e2e smoke tests: each baseline config builds, compiles, and runs
a training step (the reference's multi_gpu_tests.sh tier, CPU-mesh sized)."""
import numpy as np
import pytest

from flexflow_tpu import (AdamOptimizer, FFConfig, FFModel, LossType,
                          MetricsType, SGDOptimizer)
from flexflow_tpu.models import (TransformerConfig, build_alexnet_cifar10,
                                 build_dlrm, build_moe_mlp, build_resnet50,
                                 build_transformer)

# heavyweight tier: excluded from the fast tier-1 gate (-m 'not slow');
# still runs in the full suite (see pyproject [tool.pytest.ini_options])
pytestmark = pytest.mark.slow



def _fit_steps(ff, xs, y, loss=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               epochs=1):
    ff.compile(optimizer=SGDOptimizer(ff, lr=0.01), loss_type=loss,
               metrics=[MetricsType.METRICS_ACCURACY])
    ff.fit(xs, y, epochs=epochs)


def test_alexnet_cifar10():
    config = FFConfig()
    config.batch_size = 8
    ff = FFModel(config)
    x_t, out = build_alexnet_cifar10(ff, batch_size=8)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 3, 32, 32)).astype(np.float32)
    y = rng.integers(0, 10, size=16).astype(np.int32)
    _fit_steps(ff, x, y)
    assert out.dims == (8, 10)


def test_resnet50_builds_and_steps():
    config = FFConfig()
    config.batch_size = 2
    ff = FFModel(config)
    x_t, out = build_resnet50(ff, batch_size=2, image_size=64, num_classes=10,
                              stages=(1, 1, 1, 1))  # depth-reduced for CI
    assert out.dims == (2, 10)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 3, 64, 64)).astype(np.float32)
    y = rng.integers(0, 10, size=4).astype(np.int32)
    _fit_steps(ff, x, y)


def test_resnet50_full_graph_shape():
    config = FFConfig()
    config.batch_size = 2
    ff = FFModel(config)
    _, out = build_resnet50(ff, batch_size=2, image_size=224)
    assert out.dims == (2, 1000)
    assert len(ff._layers) > 100  # 50-layer net with bn/add/relu nodes


def test_dlrm():
    config = FFConfig()
    config.batch_size = 8
    ff = FFModel(config)
    sparse, dense, out = build_dlrm(
        ff, batch_size=8, embedding_sizes=(100, 100, 100),
        embedding_dim=16, dense_dim=8, mlp_bot=(32, 16), mlp_top=(32, 1))
    assert out.dims == (8, 1)
    rng = np.random.default_rng(0)
    xs = [rng.integers(0, 100, size=(16, 1)).astype(np.int64)
          for _ in range(3)] + [rng.normal(size=(16, 8)).astype(np.float32)]
    y = rng.random(size=(16, 1)).astype(np.float32)
    ff.compile(optimizer=SGDOptimizer(ff, lr=0.01),
               loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
               metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR])
    ff.fit(xs, y, epochs=1)


def test_transformer():
    config = FFConfig()
    config.batch_size = 4
    ff = FFModel(config)
    cfg = TransformerConfig.tiny(batch_size=4)
    _, out = build_transformer(ff, cfg)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, cfg.seq_len, cfg.hidden)).astype(np.float32)
    y = rng.integers(0, 2, size=8).astype(np.int32)
    _fit_steps(ff, x, y)


def test_moe_mlp():
    config = FFConfig()
    config.batch_size = 16
    ff = FFModel(config)
    _, out = build_moe_mlp(ff, batch_size=16, in_dim=32, num_classes=4,
                           num_exp=4, num_select=2, expert_hidden=16)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 32)).astype(np.float32)
    y = rng.integers(0, 4, size=32).astype(np.int32)
    _fit_steps(ff, x, y)


def test_gpt2_builds_and_trains():
    """Native decoder-only causal LM (models/gpt2.py): next-token loss
    decreases over a few steps; causal masking verified against a manual
    jnp reference through the op path."""
    import jax
    import jax.random as jr

    from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, LossType
    from flexflow_tpu.models.gpt2 import GPT2Config, build_gpt2

    cfg = GPT2Config.tiny(batch_size=4)
    config = FFConfig()
    config.batch_size = cfg.batch_size
    ff = FFModel(config)
    ids, logits = build_gpt2(ff, cfg)
    probs = ff.softmax(logits)
    ff.compile(optimizer=AdamOptimizer(ff, alpha=1e-3),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               final_tensor=probs)
    rng = np.random.default_rng(0)
    stream = rng.integers(0, cfg.vocab_size,
                          size=(cfg.batch_size, cfg.seq_len + 1))
    x = stream[:, :-1].astype(np.int32)
    y = stream[:, 1:].astype(np.int32)
    step = ff.executor.make_train_step()
    xd = [jax.device_put(x, ff.executor.batch_sharding(2))]
    yd = jax.device_put(y, ff.executor.batch_sharding(2))
    p, o = ff.params, ff.opt_state
    losses = []
    for i in range(20):
        p, o, loss, _ = step(p, o, xd, yd, jr.PRNGKey(i))
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


def test_gpt2_causality():
    """Changing a future token must not change past logits (the causal
    flash/einsum gate really masks)."""
    import jax

    from flexflow_tpu import FFConfig, FFModel, LossType
    from flexflow_tpu.models.gpt2 import GPT2Config, build_gpt2

    cfg = GPT2Config.tiny(batch_size=2)
    config = FFConfig()
    config.batch_size = cfg.batch_size
    ff = FFModel(config)
    ids, logits = build_gpt2(ff, cfg)
    ff.compile(loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
               final_tensor=logits)
    fwd = ff.executor.make_forward()
    rng = np.random.default_rng(0)
    a = rng.integers(0, cfg.vocab_size,
                     size=(cfg.batch_size, cfg.seq_len)).astype(np.int32)
    b = a.copy()
    b[:, -1] = (b[:, -1] + 1) % cfg.vocab_size  # perturb the LAST token
    la = np.asarray(fwd(ff.params, [a]))
    lb = np.asarray(fwd(ff.params, [b]))
    np.testing.assert_allclose(la[:, :-1], lb[:, :-1], rtol=1e-5, atol=1e-5)
    assert not np.allclose(la[:, -1], lb[:, -1])
