"""Paged KV decode (ISSUE 12, docs/serving.md "Paged KV cache" +
docs/decode_perf.md): the bitwise equivalence matrix — paged-vs-ring
identical under exact decode for fp layouts, int8 within its pinned
tolerance band, speculative greedy output token-identical to the
baseline, fleet migration of a paged stream bitwise on the survivor —
plus allocator laws, occupancy decoupling, admission rejection, the
flash-decode kernel in interpret mode, and the FF006 paged shape
checks. All CPU-deterministic."""
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.models.gpt2 import GPT2Config, build_gpt2
from flexflow_tpu.serving import (BlockAllocator, ContextOverflowError,
                                  ServingEngine, SpeculativeDecoder)
from flexflow_tpu.serving.scheduler import (ContinuousBatchScheduler,
                                            Request, ServingRejection)

# int8 KV tolerance band (docs/decode_perf.md): decode logits of the
# quantized layout vs the fp layout on the reference tiny-GPT2 workload.
# Pinned deliberately — a band regression means the quantizer changed.
KV_INT8_LOGIT_BAND = 0.25
# and the greedy argmax must still agree on almost every step
KV_INT8_ARGMAX_AGREEMENT = 0.9


def _build(hidden=64, heads=4, layers=2, seq_len=32, vocab=100, seed=42):
    # hidden 64 / 4 heads is the GPT2Config.tiny family, where the
    # exact-decode bitwise contract provably holds (the contract is
    # XLA-lowering-sensitive: e.g. hidden 32 trips a last-ulp projection
    # difference between bucket and full-sequence shapes on CPU — a
    # pre-existing property of the ring path, not a paged regression)
    cfg = GPT2Config(batch_size=2, seq_len=seq_len, hidden=hidden,
                     num_heads=heads, num_layers=layers,
                     intermediate=hidden * 2, vocab_size=vocab)
    config = FFConfig()
    config.batch_size = cfg.batch_size
    config.seed = seed
    ff = FFModel(config)
    build_gpt2(ff, cfg)
    ff.compile(optimizer=SGDOptimizer(ff),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff, cfg


@pytest.fixture(scope="module")
def gpt2():
    return _build()


PROMPTS = [[5, 6, 7, 8, 9], [11, 12, 13], [1] * 9,
           [3, 1, 4, 1, 5, 9, 2, 6]]


def _teacher_forced_paged(ff, seq, prompt_len, max_len, **engine_kw):
    """Prefill + paged decode with the TRUE next token fed back each
    step, through the real engine machinery (allocator, table rows,
    _write_slot scatter) — per-position decode logits for the bitwise/
    band comparisons."""
    import jax
    import jax.numpy as jnp

    eng = ServingEngine(ff, n_slots=1, max_decode_len=max_len,
                        exact_decode=True, **engine_kw)
    bucket = next(b for b in eng.buckets if b >= prompt_len)
    padded = np.zeros((1, bucket), np.int32)
    padded[0, :prompt_len] = seq[0, :prompt_len]
    _lg, _last, cache = eng._prefill_fn(bucket)(
        ff.params, [jnp.asarray(padded)],
        jnp.asarray([prompt_len], np.int32))
    eng._ensure_state(cache)
    if eng._paged:
        blocks = eng.block_allocator.alloc(
            eng.block_allocator.blocks_needed(seq.shape[1]))
        row = np.zeros((eng.max_blocks_per_slot,), np.int32)
        row[:len(blocks)] = blocks
    else:
        row = None
    eng._write_slot(cache, 0, prompt_len, int(seq[0, prompt_len - 1]),
                    table_row=row)
    dec = eng._decode_fn()
    state = eng.state
    rows = {}
    for t in range(prompt_len, seq.shape[1]):
        lg, state = dec(ff.params, [jnp.asarray(seq[:1, t:t + 1])], state)
        rows[t] = np.asarray(jax.device_get(lg))[0]
    return rows


def _full_forward_logits(ff, seq):
    fwd = ff.executor.make_forward()
    return np.asarray(fwd(ff.params, [seq]))[0]


# --------------------------------------------------- the equivalence matrix
def test_paged_exact_decode_bitwise_vs_full_forward(gpt2):
    """Matrix row 1: paged fp decode under exact=True is BITWISE the
    whole-sequence forward — the gather is pure pointer chasing and
    garbage-block rows are masked to exact zeros."""
    ff, cfg = gpt2
    rng = np.random.default_rng(0)
    seq = rng.integers(0, cfg.vocab_size,
                       size=(1, cfg.seq_len)).astype(np.int32)
    full = _full_forward_logits(ff, np.repeat(seq, cfg.batch_size, 0))
    rows = _teacher_forced_paged(ff, seq, prompt_len=7,
                                 max_len=cfg.seq_len, kv_block_size=8)
    for t, row in rows.items():
        assert np.array_equal(row, full[t]), \
            f"paged decode logits diverged from full forward at pos {t}"


def test_paged_vs_ring_decode_bitwise(gpt2):
    """Matrix row 2: paged and ring decode produce IDENTICAL logits
    under exact numerics, token by token — and identical generated
    streams end to end (the engine default changed layouts without
    changing a single emitted token)."""
    ff, cfg = gpt2
    rng = np.random.default_rng(1)
    seq = rng.integers(0, cfg.vocab_size, size=(1, 20)).astype(np.int32)
    ring = _teacher_forced_paged(ff, seq, 5, cfg.seq_len,
                                 kv_cache="ring")
    paged = _teacher_forced_paged(ff, seq, 5, cfg.seq_len,
                                  kv_cache="paged", kv_block_size=8)
    for t in ring:
        assert np.array_equal(ring[t], paged[t]), f"pos {t} diverged"
    # fresh jits: the harness above traced the shared decode jit at its
    # own shapes — measure the single-compile contract from cold
    ff.executor._serving_jits = {}
    e_r = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len,
                        exact_decode=True, kv_cache="ring")
    e_p = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len,
                        exact_decode=True, kv_cache="paged",
                        kv_block_size=8)
    out_r = e_r.generate(PROMPTS, max_new_tokens=8)
    out_p = e_p.generate(PROMPTS, max_new_tokens=8)
    assert out_r == out_p
    assert e_p.decode_compiles == 1  # single-compile contract held


def test_int8_layout_within_pinned_band(gpt2):
    """Matrix row 3: the int8 KV layout's decode logits sit inside the
    pinned tolerance band of the fp layout, and greedy argmax agrees on
    >= KV_INT8_ARGMAX_AGREEMENT of positions — the precision the
    searched bandwidth win costs, made explicit."""
    ff, cfg = gpt2
    rng = np.random.default_rng(2)
    seq = rng.integers(0, cfg.vocab_size, size=(1, 24)).astype(np.int32)
    fp = _teacher_forced_paged(ff, seq, 6, cfg.seq_len, kv_block_size=8)
    q8 = _teacher_forced_paged(ff, seq, 6, cfg.seq_len, kv_block_size=8,
                               kv_dtype="int8")
    worst = 0.0
    agree = total = 0
    for t in fp:
        worst = max(worst, float(np.max(np.abs(fp[t] - q8[t]))))
        agree += int(np.argmax(fp[t]) == np.argmax(q8[t]))
        total += 1
    assert worst <= KV_INT8_LOGIT_BAND, \
        f"int8 logit error {worst:.4f} outside the pinned band " \
        f"{KV_INT8_LOGIT_BAND}"
    assert agree / total >= KV_INT8_ARGMAX_AGREEMENT, \
        f"int8 greedy argmax agreement {agree}/{total}"


def test_speculative_greedy_token_identical(gpt2):
    """Matrix row 4: speculative greedy output == the non-speculative
    baseline, token for token (verification runs the same exact-score
    forward the bitwise decode contract pins ⇒ equal argmax), for both
    a useless random drafter and the perfect drafter (the target
    itself, acceptance 1.0 — every round commits gamma + 1 tokens)."""
    ff, cfg = gpt2
    drafter, _ = _build(hidden=16, heads=2, layers=1, seed=7)
    eng = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len,
                        exact_decode=True)
    base = eng.generate(PROMPTS, max_new_tokens=10)
    spec = SpeculativeDecoder(ff, drafter, gamma=3,
                              max_context=cfg.seq_len,
                              controller=eng.admission)
    assert spec.generate(PROMPTS, max_new_tokens=10) == base
    assert spec.stats.spec_rounds > 0
    assert spec.stats.acceptance_rate() is not None
    # perfect drafter: acceptance 1.0, and FEWER verification rounds
    # than tokens (the speedup mechanism, observable on CPU as round
    # counts rather than wall clock)
    perfect = SpeculativeDecoder(ff, ff, gamma=3,
                                 max_context=cfg.seq_len)
    assert perfect.generate(PROMPTS, max_new_tokens=10) == base
    st = perfect.stats
    assert st.acceptance_rate() == 1.0
    assert st.spec_rounds < st.tokens_generated, \
        "perfect drafter should commit >1 token per round"
    # the EWMA admission model saw the speculative cost + acceptance
    assert eng.admission.spec_acceptance is not None
    assert eng.admission.token_cost_ms > 0


def test_fleet_context_overflow_preempts_not_crashes(gpt2):
    """Regression (review finding): a request beyond the position-table
    bound dispatched through the FLEET must be ledgered (preempted),
    not crash the router with an uncaught ContextOverflowError — other
    in-flight requests complete normally."""
    from flexflow_tpu.serving import ServingFleet

    ff, cfg = gpt2
    fleet = ServingFleet(ff, n_replicas=2, n_slots=2,
                         max_decode_len=1024, exact_decode=True)
    outs = fleet.generate([[1, 2, 3], [4, 5, 6]], max_new_tokens=4)
    assert all(len(o) == 4 for o in outs)
    fleet2 = ServingFleet(ff, n_replicas=2, n_slots=2,
                          max_decode_len=1024, exact_decode=True)
    outs = fleet2.generate([[1, 2, 3]], max_new_tokens=cfg.seq_len + 8)
    assert outs[0] == []  # ledgered, not crashed
    assert sum(fleet2.stats.outcomes.values()) == 1


def test_speculative_context_bounded_by_position_table(gpt2):
    """Regression (review finding): the speculative decoder's scoring
    bound consults the position table — a default max_context above the
    table would silently alias position rows in verification."""
    ff, cfg = gpt2
    spec = SpeculativeDecoder(ff, ff, gamma=2, max_context=1024)
    assert spec.max_context == cfg.seq_len
    # generation truncates at the bound instead of scoring past it
    out = spec.generate([[1, 2, 3]], max_new_tokens=cfg.seq_len + 50)
    assert 0 < len(out[0]) <= cfg.seq_len - 3


def test_speculative_refuses_temperature(gpt2):
    ff, cfg = gpt2
    spec = SpeculativeDecoder(ff, ff, gamma=2, max_context=cfg.seq_len)
    with pytest.raises(NotImplementedError, match="greedy-only"):
        spec.generate([[1, 2]], max_new_tokens=4, temperature=0.7)


def test_speculative_rejects_vocab_mismatch(gpt2):
    ff, cfg = gpt2
    other, _ = _build(vocab=53, seed=9)
    with pytest.raises(ValueError, match="vocab"):
        SpeculativeDecoder(ff, other)


def test_fleet_migration_paged_bitwise(gpt2):
    """Matrix row 5: a mid-decode replica kill migrates PAGED-KV streams
    to the survivor bitwise-unchanged — the re-prefill from committed
    tokens rebuilds block tables on the survivor's own allocator."""
    from flexflow_tpu.resilience import FleetChaosPlan
    from flexflow_tpu.serving import ServingFleet

    ff, cfg = gpt2
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, cfg.vocab_size,
                            size=int(rng.integers(3, 7))).tolist()
               for _ in range(6)]
    base = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len,
                         exact_decode=True).generate(
                             prompts, max_new_tokens=8)
    fleet = ServingFleet(ff, n_replicas=2, n_slots=2,
                         max_decode_len=cfg.seq_len, exact_decode=True)
    outs = fleet.generate(prompts, max_new_tokens=8,
                          chaos=FleetChaosPlan(kill_replica_at={4: 0}))
    assert outs == base, "migrated paged continuations diverged"
    assert fleet.stats.migrations >= 1
    assert fleet.stats.outcomes == {"ok": 6}


# ------------------------------------------------------- allocator + pool
def test_block_allocator_laws():
    a = BlockAllocator(n_blocks=9, block_size=4)
    assert a.n_usable == 8 and a.in_use == 0
    assert a.blocks_needed(1) == 1 and a.blocks_needed(4) == 1
    assert a.blocks_needed(5) == 2 and a.blocks_needed(32) == 8
    got = a.alloc(3)
    assert got == [1, 2, 3] and a.in_use == 3
    assert a.alloc(6) is None, "over-allocation must refuse, not raise"
    assert a.in_use == 3
    a.free([2])
    assert a.alloc(6) == [4, 5, 6, 7, 8, 2]  # FIFO free list
    assert a.in_use == 8 and a.blocks_hwm == 8
    a.reset()
    assert a.in_use == 0 and len(a.free_blocks) == 8
    with pytest.raises(AssertionError):
        BlockAllocator(n_blocks=1, block_size=4)  # garbage block only


def test_small_pool_decouples_occupancy_and_serializes(gpt2):
    """Occupancy accounting: a pool holding exactly ONE max-size request
    still completes a multi-request workload (admission waits on free
    BLOCKS; recycling unblocks it) with streams identical to the
    full-pool run."""
    ff, cfg = gpt2
    mb = -(-cfg.seq_len // 8)
    eng_small = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len,
                              exact_decode=True, kv_block_size=8,
                              kv_pool_blocks=mb + 1)
    eng_full = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len,
                             exact_decode=True, kv_block_size=8)
    out_small = eng_small.generate(PROMPTS, max_new_tokens=6)
    out_full = eng_full.generate(PROMPTS, max_new_tokens=6)
    assert out_small == out_full
    # ISSUE 14: finished prompts' blocks are retained by the prefix trie
    # (that's the cache) — live accounting must equal exactly the trie's
    # holdings, and dropping the trie must leave zero leaked blocks
    for eng in (eng_small, eng_full):
        assert eng.block_allocator.in_use == eng._prefix.n_blocks, \
            "blocks leaked beyond the prefix trie's holdings"
        eng._prefix.clear(free=True)
        assert eng.block_allocator.in_use == 0, "blocks leaked"
    assert eng_small.block_allocator.blocks_hwm <= mb


def test_request_larger_than_pool_refused_at_submit():
    """A request the WHOLE pool cannot hold must refuse at submit (the
    alternative is an admission deadlock). The engine's FF006 check
    already refuses such pools outright; this pins the scheduler-level
    backstop for foreign schedulers."""
    sched = ContinuousBatchScheduler(n_slots=2, max_len=64)
    sched.allocator = BlockAllocator(n_blocks=3, block_size=8)
    req = Request(prompt=np.arange(10, dtype=np.int32),
                  max_new_tokens=16)
    with pytest.raises(ValueError, match="KV blocks"):
        sched.submit(req)


def test_context_overflow_is_serving_rejection(gpt2):
    """ISSUE 12 satellite: position-table overflow rejects at admission
    with a typed ServingRejection naming the max supported context."""
    ff, cfg = gpt2
    eng = ServingEngine(ff, n_slots=2, max_decode_len=1024)
    assert eng.max_context == cfg.seq_len
    # a rejection at the door still lands in the ledger (outcome shed)
    outs = eng.generate([[1, 2, 3]], max_new_tokens=cfg.seq_len + 4)
    assert outs[0] == []
    assert eng.stats.outcomes.get("shed") == 1
    sched = ContinuousBatchScheduler(n_slots=2, max_len=1024)
    req = Request(prompt=np.arange(4, dtype=np.int32),
                  max_new_tokens=cfg.seq_len)
    with pytest.raises(ContextOverflowError,
                       match="max supported context") as ei:
        eng.admit(sched, req)
    assert isinstance(ei.value, ServingRejection)


def test_kv_bytes_accounting_paged_below_ring(gpt2):
    """The decode bytes-read/token column: the paged engine's analytic
    read traffic is strictly below the ring's O(max_len) bill for short
    requests, and both land in the stats summary."""
    ff, cfg = gpt2
    e_p = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len,
                        kv_block_size=8)
    e_r = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len,
                        kv_cache="ring")
    e_p.generate(PROMPTS, max_new_tokens=6)
    e_r.generate(PROMPTS, max_new_tokens=6)
    p, r = (e_p.stats.kv_bytes_per_token(),
            e_r.stats.kv_bytes_per_token())
    assert p is not None and r is not None and p < r
    assert "kv_bytes_per_token" in e_p.stats.summary()


# ------------------------------------------------------ flash-decode kernel
def test_flash_decode_interpret_matches_reference():
    """The Pallas split-K kernel (interpret mode on CPU) matches the
    masked-gather reference for fp and int8 pools, including slots with
    very different true lengths (the clamp-dead-blocks path)."""
    import jax.numpy as jnp

    from flexflow_tpu.kernels.flash_decode import (_reference_decode,
                                                   flash_decode)
    from flexflow_tpu.serving.kvcache import quantize_kv

    rng = np.random.default_rng(0)
    S, H, BS, HD, MB = 3, 4, 8, 64, 4
    NB = 1 + S * MB
    kpool = jnp.asarray(rng.normal(size=(NB, H, BS, HD)) .astype(np.float32))
    vpool = jnp.asarray(rng.normal(size=(NB, H, BS, HD)).astype(np.float32))
    tables = np.zeros((S, MB), np.int32)
    tables[0, :2] = [1, 2]
    tables[1, :4] = [3, 4, 5, 6]
    tables[2, :1] = [7]
    tables = jnp.asarray(tables)
    n_keys = jnp.asarray([13, 30, 5], jnp.int32)
    q = jnp.asarray(rng.normal(size=(S, H, HD)).astype(np.float32))
    out = flash_decode(q, kpool, vpool, tables, n_keys, interpret=True)
    ref = _reference_decode()(q, kpool, vpool, tables, n_keys,
                              1.0 / np.sqrt(HD))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6)
    kq, ks = quantize_kv(kpool)
    vq, vs = quantize_kv(vpool)
    out8 = flash_decode(q, kq, vq, tables, n_keys, kscale=ks,
                        vscale=vs, interpret=True)
    ref8 = _reference_decode()(q, kq, vq, tables, n_keys,
                               1.0 / np.sqrt(HD), kscale=ks, vscale=vs)
    np.testing.assert_allclose(np.asarray(out8), np.asarray(ref8),
                               atol=2e-6)
    # and int8 sits within a loose band of fp (quantization, not bugs)
    assert float(jnp.max(jnp.abs(out8 - ref))) < 0.1


def test_flash_decode_gate_off_tpu():
    from flexflow_tpu.kernels.flash_decode import use_flash_decode

    # CPU process: the gate must refuse regardless of dims
    assert not use_flash_decode(64, 16)
    # and bad dims refuse before the platform probe
    assert not use_flash_decode(60, 16)
    assert not use_flash_decode(64, 3)


# ------------------------------------------------- satellites: warn + FF006
def test_flash_tuning_warns_once_per_generation_and_kernel(monkeypatch):
    """ISSUE 12 satellite: the unmeasured-generation tile warning fires
    once per (generation, KERNEL) — flash_decode gets its own warning
    even after flash_attention already warned."""
    import warnings

    from flexflow_tpu.ops import attention

    monkeypatch.setattr(attention, "_tuning_cache", {})
    monkeypatch.setattr(attention, "_detect_tpu_generation",
                        lambda: (True, "v99"))
    with pytest.warns(UserWarning, match="flash_attention.*no MEASURED"):
        attention._flash_tuning("flash_attention")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        attention._flash_tuning("flash_attention")  # silenced
    with pytest.warns(UserWarning, match="flash_decode.*no MEASURED"):
        attention._flash_tuning("flash_decode")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        attention._flash_tuning("flash_decode")


def test_check_paged_kv_shape_laws(gpt2):
    """FF006 paged extension: misconfigured block tables/pools are
    rejected statically with the rule ID; a clean config passes."""
    from flexflow_tpu.analysis import check_paged_kv

    ff, _cfg = gpt2
    clean = check_paged_kv(ff.pcg, block_size=8, pool_blocks=17,
                           max_blocks_per_slot=4, max_context=32)
    assert clean == []
    short_table = check_paged_kv(ff.pcg, block_size=8, pool_blocks=17,
                                 max_blocks_per_slot=2, max_context=32)
    assert any("block table covers" in d.message for d in short_table)
    assert all(d.rule_id == "FF006" for d in short_table)
    tiny_pool = check_paged_kv(ff.pcg, block_size=8, pool_blocks=3,
                               max_blocks_per_slot=4, max_context=32)
    assert any("deadlock" in d.message for d in tiny_pool)
    bad_shard = check_paged_kv(ff.pcg, block_size=8, pool_blocks=17,
                               max_blocks_per_slot=4, max_context=32,
                               kv_layout="sharded", tp=7)
    assert any("num_heads" in d.message for d in bad_shard)
    # the engine runs the check at construction: a pool too small for
    # one request dies with the rule ID, zero compiles
    from flexflow_tpu.analysis import StaticAnalysisError

    with pytest.raises(StaticAnalysisError, match="FF006"):
        ServingEngine(ff, n_slots=2, max_decode_len=32, kv_block_size=8,
                      kv_pool_blocks=3)


def test_garbage_block_never_poisoned(gpt2):
    """White-box: the chaos poisoner NaNs exactly a LIVE victim's
    occupied blocks — never the shared garbage block (whose finiteness
    the paged/ring bitwise contract depends on), and a free/cleared
    slot is a no-op (its table row points only at garbage)."""
    import jax
    import jax.numpy as jnp

    from flexflow_tpu.resilience.chaos import poison_decode_state

    ff, cfg = gpt2
    eng = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len,
                        kv_block_size=8)
    # make slot 0 LIVE through the real machinery (prefill + admission
    # scatter), slot 1 free
    prompt = np.asarray(PROMPTS[0], np.int32)
    bucket = next(b for b in eng.buckets if b >= len(prompt))
    padded = np.zeros((1, bucket), np.int32)
    padded[0, :len(prompt)] = prompt
    _lg, _last, cache = eng._prefill_fn(bucket)(
        ff.params, [jnp.asarray(padded)],
        jnp.asarray([len(prompt)], np.int32))
    eng._ensure_state(cache)
    blocks = eng.block_allocator.alloc(2)
    row = np.zeros((eng.max_blocks_per_slot,), np.int32)
    row[:2] = blocks
    eng._write_slot(cache, 0, len(prompt), 1, table_row=row)
    state = eng.state
    tables = np.asarray(state.block_tables)
    assert tables[0, 0] == blocks[0]
    poisoned = poison_decode_state(state, 0)
    saw_victim = False
    for entry in poisoned.caches.values():
        for leaf in jax.tree_util.tree_leaves(entry):
            if leaf.ndim >= 3 and jnp.issubdtype(leaf.dtype,
                                                 jnp.floating):
                assert bool(jnp.all(jnp.isfinite(leaf[0]))), \
                    "garbage block was poisoned"
                assert not bool(jnp.all(jnp.isfinite(leaf[blocks[0]])))
                saw_victim = True
    assert saw_victim
    # free slot (all-garbage table row): poisoning it is a pool no-op
    reposoned = poison_decode_state(poisoned, 1)
    for name, entry in reposoned.caches.items():
        for a, b in zip(jax.tree_util.tree_leaves(entry),
                        jax.tree_util.tree_leaves(poisoned.caches[name])):
            if a.ndim >= 3:
                assert np.array_equal(np.asarray(a), np.asarray(b),
                                      equal_nan=True)


def test_freed_slot_clears_table_row_and_cursor(gpt2):
    """Regression (review finding): when a slot is freed, its
    device-side block-table row resets to GARBAGE and its cursor to 0 —
    a stale row would keep scattering the freed slot's discarded tokens
    into blocks the allocator already handed to a NEW request in a
    different slot (silent KV corruption). Plus the churn stress: many
    short/long requests through a minimal pool must match the ring
    stream for stream."""
    ff, cfg = gpt2
    mb = -(-cfg.seq_len // 8)
    eng = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len,
                        exact_decode=True, kv_block_size=8,
                        kv_pool_blocks=mb + 1)
    eng.generate(PROMPTS[:2], max_new_tokens=4)
    tables = np.asarray(eng.state.block_tables)
    lengths = np.asarray(eng.state.lengths)
    assert np.all(tables == 0), "freed slots kept stale table rows"
    assert np.all(lengths == 0), "freed slots kept stale cursors"
    # churn: interleaved short + LONG prompts (a long prompt admitted
    # into freed blocks is exactly the corruption scenario)
    rng = np.random.default_rng(5)
    churn = []
    for i in range(8):
        n = 24 if i % 2 else 3
        churn.append(rng.integers(0, cfg.vocab_size, size=n).tolist())
    ring = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len,
                         exact_decode=True, kv_cache="ring")
    base = ring.generate(churn, max_new_tokens=7)
    eng2 = ServingEngine(ff, n_slots=2, max_decode_len=cfg.seq_len,
                         exact_decode=True, kv_block_size=8,
                         kv_pool_blocks=2 * mb + 1)
    assert eng2.generate(churn, max_new_tokens=7) == base
    # in-use == the prefix trie's retained blocks (ISSUE 14), zero once
    # the trie is dropped
    assert eng2.block_allocator.in_use == eng2._prefix.n_blocks
    eng2._prefix.clear(free=True)
    assert eng2.block_allocator.in_use == 0


def test_serving_search_kv_dtype_axis(gpt2):
    """The serving search sweeps kv_dtype next to the KV layout; int8
    candidates price strictly less KV-stream time, the winner records
    its dtype, and --kv-dtype pins the axis."""
    from flexflow_tpu.search.machine_model import TPUMachineModel
    from flexflow_tpu.serving import serving_search

    ff, _cfg = gpt2
    machine = TPUMachineModel.from_generation("v5e", 8)
    plan = serving_search(ff.pcg, ff.config, 8, machine=machine)
    dtypes = {c.kv_dtype for c in plan.ranked}
    assert dtypes == {"native", "int8"}
    # int8 must beat native at the same (mesh, layout): less KV stream
    by_key = {}
    for c in plan.ranked:
        by_key[(tuple(c.mesh_shape), c.layout, c.kv_dtype)] = c
    for (mesh, layout, dt), c in by_key.items():
        if dt == "int8":
            twin = by_key.get((mesh, layout, "native"))
            if twin is not None:
                assert c.sim_decode_ms <= twin.sim_decode_ms
    assert plan.kv_dtype in ("native", "int8")
    ff.config.kv_dtype = "int8"
    try:
        pinned = serving_search(ff.pcg, ff.config, 8, machine=machine)
        assert {c.kv_dtype for c in pinned.ranked} == {"int8"}
    finally:
        ff.config.kv_dtype = "native"
